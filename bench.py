"""Driver benchmark: prints ONE JSON line.

Headline config: 256^3 C2C sparse 3D FFT, ~15% spherical frequency cutoff
(BASELINE.json config 2 scaled to the driver's 256^3 metric), forward+backward
wall-clock on the attached accelerator, reported as GFLOP/s using the standard
5*N*log2(N) per-3D-transform flop model.

Timing note: on the tunneled TPU platform ``block_until_ready`` does not wait for
execution, so the measurement chains R dependent roundtrips (forward output feeds
the next backward — exact because FULL scaling makes the pair an identity) and
forces completion with a scalar host fetch, dividing by R. The chain runs inside a
single jitted ``lax.scan`` so one dispatch covers all R pairs — per-call dispatch
latency (tens of ms through the development tunnel; irrelevant on directly attached
TPUs) is amortized to noise instead of being billed to every pair.

vs_baseline compares against a dense np.fft (pocketfft) 3D FFT pair on the same grid
measured in the same process — the sparse-accelerator-vs-dense-host-FFT comparison
that motivates SpFFT, since the reference repo publishes no numbers (BASELINE.md).
"""
from __future__ import annotations

import json
import time

import numpy as np

# Chain length: the tunneled dev platform bills a ~110 ms FIXED cost per
# step-call+fetch (measured: an empty scan costs the same 90-130 ms at any
# length) that a directly-attached TPU does not pay; 384 pairs amortize it to
# <0.3 ms/pair so the reported number reflects the transform, not the tunnel.
CHAIN = 384


def _acquire_backend():
    """Initialize the accelerator backend, failing FAST on unavailability.

    Two failure modes cost a round's capture if unhandled (both observed):
    a raised ``Unable to initialize backend`` (rc=1 with a 40-line traceback)
    and a wedged tunnel claim that blocks backend init forever (driver
    timeout). Here: one retry after a short pause for transient flaps, a
    single-line stderr diagnostic, and a watchdog (``SPFFT_TPU_BENCH_INIT_BUDGET_S``,
    default 180 s) that turns a blocked init into a fast exit 2.
    """
    import sys

    import jax
    from spfft_tpu._platform import hang_watchdog

    disarm = hang_watchdog(
        "bench", "SPFFT_TPU_BENCH_INIT_BUDGET_S", 180, exit_code=2
    )
    try:
        for attempt in (1, 2):
            try:
                dev = jax.devices()[0]
                print(f"bench: backend ready: {dev}", file=sys.stderr)
                return
            except RuntimeError as e:
                msg = str(e).split("\n")[0]
                if attempt == 1:
                    print(f"bench: backend init failed ({msg}); retrying in 15s",
                          file=sys.stderr, flush=True)
                    time.sleep(15)
                else:
                    print(f"bench: backend unavailable: {msg}", file=sys.stderr,
                          flush=True)
                    sys.exit(1)
    finally:
        disarm()


def main():
    import jax
    import spfft_tpu as sp
    from spfft_tpu import ProcessingUnit, ScalingType, Transform, TransformType

    _acquire_backend()

    dim = 256
    rng = np.random.default_rng(0)
    triplets = sp.create_spherical_cutoff_triplets(dim, dim, dim, 0.659)  # ~15% nnz
    n = len(triplets)

    t = sp.Transform(
        ProcessingUnit.GPU, TransformType.C2C, dim, dim, dim,
        indices=triplets, dtype=np.float32,
    )
    ex = t._exec

    def roundtrip(re, im):
        # trace_* (un-jitted impls): jit boundaries inside the scan body block
        # cross-stage fusion (measured ~30% slower per pair)
        space_re, space_im = ex.trace_backward(re, im)
        return ex.trace_forward(space_re, space_im, ScalingType.FULL)

    def chain(re, im):
        def body(carry, _):
            return roundtrip(*carry), None
        out, _ = jax.lax.scan(body, (re, im), None, length=CHAIN)
        return out

    step = jax.jit(chain)

    re = ex.put(rng.standard_normal(n).astype(np.float32))
    im = ex.put(rng.standard_normal(n).astype(np.float32))

    # warmup / compile
    wre, wim = step(re, im)
    float(wre[0])

    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        cre, cim = step(re, im)
        float(cre[0])  # forces the whole chain to complete
        best = min(best, (time.perf_counter() - t0) / CHAIN)

    # chain correctness guard: FULL-scaled roundtrip is the identity
    err = float(np.abs(np.asarray(cre[:64]) - np.asarray(re[:64])).max())
    assert err < 1e-2, f"roundtrip chain diverged: {err}"

    ntot = dim**3
    flops = 2 * 5.0 * ntot * np.log2(ntot)  # fwd + bwd
    gflops = flops / best / 1e9

    # dense host FFT pair on the same grid (numpy pocketfft); min of 3 so a
    # transiently busy host does not swing the ratio
    dense = (
        rng.standard_normal((dim, dim, dim)) + 1j * rng.standard_normal((dim, dim, dim))
    ).astype(np.complex64)
    dense_time = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        np.fft.fftn(np.fft.ifftn(dense))
        dense_time = min(dense_time, time.perf_counter() - t0)

    print(
        json.dumps(
            {
                "metric": "c2c_256_sparse15pct_fwd_bwd_gflops",
                "value": round(gflops, 2),
                "unit": "GFLOP/s",
                "vs_baseline": round(dense_time / best, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
