"""Driver benchmark: prints ONE JSON line.

Headline config: 256^3 C2C sparse 3D FFT, ~15% spherical frequency cutoff
(BASELINE.json config 2 scaled to the driver's 256^3 metric), forward+backward
wall-clock on the attached accelerator, reported as GFLOP/s using the standard
5*N*log2(N) per-3D-transform flop model.

Timing note: on the tunneled TPU platform ``block_until_ready`` does not wait for
execution, so the measurement chains R dependent roundtrips (forward output feeds
the next backward — exact because FULL scaling makes the pair an identity) and
forces completion with a scalar host fetch, dividing by R. The chain runs inside a
single jitted ``lax.scan`` so one dispatch covers all R pairs — per-call dispatch
latency (tens of ms through the development tunnel; irrelevant on directly attached
TPUs) is amortized to noise instead of being billed to every pair.

vs_baseline compares against a dense np.fft (pocketfft) 3D FFT pair on the same grid
measured in the same process — the sparse-accelerator-vs-dense-host-FFT comparison
that motivates SpFFT, since the reference repo publishes no numbers (BASELINE.md).
"""
from __future__ import annotations

import json
import time

import numpy as np

# Chain length: the tunneled dev platform bills a ~110 ms FIXED cost per
# step-call+fetch (measured: an empty scan costs the same 90-130 ms at any
# length) that a directly-attached TPU does not pay; 384 pairs amortize it to
# <0.3 ms/pair so the reported number reflects the transform, not the tunnel.
CHAIN = 384


def _acquire_backend():
    """Initialize the accelerator backend without hanging or spewing tracebacks.

    Two failure modes cost a round's capture if unhandled (both observed):
    a raised ``Unable to initialize backend`` (rc=1 with a 40-line traceback)
    and a wedged tunnel claim that blocks backend init forever (driver
    timeout). Here: fast-raise failures are retried every 60 s inside a
    total budget (``SPFFT_TPU_BENCH_RETRY_BUDGET_S``, default 600 s) with
    one-line stderr diagnostics — transient tunnel flaps self-heal within
    minutes — and a hang watchdog (``SPFFT_TPU_BENCH_INIT_BUDGET_S``,
    default 900 s) turns a blocked init into exit 2 instead of a timeout.
    """
    import os
    import sys

    import jax
    from spfft_tpu._platform import hang_watchdog

    disarm = hang_watchdog(
        "bench", "SPFFT_TPU_BENCH_INIT_BUDGET_S", 900, exit_code=2
    )
    budget = float(os.environ.get("SPFFT_TPU_BENCH_RETRY_BUDGET_S", "600"))
    t0 = time.monotonic()
    attempt = 0
    def _reset_backends():
        # jax caches the backend table after first init (including a
        # CPU-only table when an accelerator plugin fail-quietly died), so a
        # retry must clear it or it would be a no-op. jax 0.9 removed the
        # public jax.clear_backends; the maintained implementation lives in
        # jax._src.api (it also clears the get_backend/util caches).
        try:
            from jax._src.api import clear_backends

            clear_backends()
        except Exception:
            try:
                import jax._src.xla_bridge as xb

                xb._clear_backends()
                xb.get_backend.cache_clear()
            except Exception:
                pass

    try:
        while True:
            attempt += 1
            err = None
            try:
                dev = jax.devices()[0]
                if dev.platform == "cpu":
                    # never silently benchmark the host as if it were the
                    # accelerator (fail-quiet plugin death falls back to CPU
                    # when JAX_PLATFORMS is unset)
                    err = f"only CPU devices visible ({dev})"
                else:
                    print(f"bench: backend ready: {dev}", file=sys.stderr)
                    return
            except RuntimeError as e:
                err = str(e).split("\n")[0]
            remaining = budget - (time.monotonic() - t0)
            if remaining <= 60:
                print(f"bench: backend unavailable after {attempt} attempts: "
                      f"{err}", file=sys.stderr, flush=True)
                sys.exit(1)
            print(f"bench: backend init failed ({err}); retrying in 60s "
                  f"({remaining:.0f}s of budget left)",
                  file=sys.stderr, flush=True)
            time.sleep(60)
            _reset_backends()
    finally:
        disarm()


def main():
    import spfft_tpu as sp
    from spfft_tpu import ProcessingUnit, TransformType

    _acquire_backend()

    dim = 256
    rng = np.random.default_rng(0)
    triplets = sp.create_spherical_cutoff_triplets(dim, dim, dim, 0.659)  # ~15% nnz

    t = sp.Transform(
        ProcessingUnit.GPU, TransformType.C2C, dim, dim, dim,
        indices=triplets, dtype=np.float32,
    )

    # The ONE shared timing discipline (spfft_tpu.obs.perf): staged inputs,
    # CHAIN dependent roundtrips in a single jitted lax.scan over the
    # un-jitted trace_* impls (jit boundaries inside the scan body block
    # cross-stage fusion — measured ~30% slower per pair), warmup absorbing
    # compilation, best-of-3 fenced repeats. bench.py used to carry its own
    # copy of this loop; dbench/profile/tuning and this harness now share it,
    # so a fence or warmup fix lands in every trajectory number at once.
    measured = sp.obs.perf.measure_pair_seconds(t, chain=CHAIN, repeats=3)
    best = measured["seconds_per_pair"]

    # chain correctness guard: FULL-scaled roundtrip is the identity
    err = measured["roundtrip_residual"]
    assert err < 1e-2, f"roundtrip chain diverged: {err}"

    ntot = dim**3
    flops = 2 * 5.0 * ntot * np.log2(ntot)  # fwd + bwd
    gflops = flops / best / 1e9

    # dense host FFT pair on the same grid (numpy pocketfft); min of 3 so a
    # transiently busy host does not swing the ratio
    dense = (
        rng.standard_normal((dim, dim, dim)) + 1j * rng.standard_normal((dim, dim, dim))
    ).astype(np.complex64)
    dense_time = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        np.fft.fftn(np.fft.ifftn(dense))
        dense_time = min(dense_time, time.perf_counter() - t0)

    # decision provenance: the plan card rides in every BENCH_*.json so a
    # perf diff across rounds always shows WHAT the plan chose (spfft_tpu.obs),
    # and the wisdom state records HOW it was decided (spfft_tpu.tuning:
    # policy, model-vs-wisdom provenance, store path, hit/miss) so the number
    # is reproducible against the same tuning inputs
    try:
        plan_card = sp.obs.plan_card(t)
    except Exception as e:  # a card bug must never cost a bench capture
        plan_card = {"error": str(e).split("\n")[0]}
    try:
        wisdom = sp.tuning.wisdom_state(t)
    except Exception as e:
        wisdom = {"error": str(e).split("\n")[0]}
    # per-stage perf report (spfft_tpu.obs.perf): the measured pair time
    # attributed to the canonical stage vocabulary — same schema as the
    # distributed dbench rows, so single-chip and multichip captures read
    # with one decoder; device_count stamps the (single-chip) scope
    try:
        perf = sp.obs.perf.perf_report(t, best, repeats=3)
    except Exception as e:  # a perf-model bug must never cost a capture
        perf = {"error": str(e).split("\n")[0]}

    print(
        json.dumps(
            {
                "metric": "c2c_256_sparse15pct_fwd_bwd_gflops",
                "value": round(gflops, 2),
                "unit": "GFLOP/s",
                "vs_baseline": round(dense_time / best, 3),
                "plan": plan_card,
                "wisdom": wisdom,
                "perf": perf,
                "device_count": perf.get("device_count", 1),
                # trace join key (spfft_tpu.obs.trace): the plan's run ID, so
                # a flight-recorder dump or snapshot from this process joins
                # this capture on one key
                "run_id": plan_card.get("run_id"),
                # fusion state (spfft_tpu.ir): fused single-program vs
                # staged per-stage dispatch rows are different scenarios
                # (A/B them with SPFFT_TPU_FUSE / programs/fbench.py)
                "fused": bool(getattr(t, "fused", True)),
                # verification setting (spfft_tpu.verify): perf rows under
                # verification are never comparable to rows without it
                "verify_mode": plan_card.get("verification", {}).get(
                    "mode", "off"
                ),
            }
        )
    )


if __name__ == "__main__":
    main()
