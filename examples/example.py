"""Minimal spfft-tpu usage example — the reference example flow in Python.

Mirrors the behavior of the reference's examples/example.cpp: build the
frequency-domain index triplets of a small grid, create a Grid and a Transform
bound to it, run a backward transform (freq -> space), inspect the space-domain
data, then transform forward with scaling and recover the input values.
"""
import numpy as np

import spfft_tpu as sp
from spfft_tpu import Grid, ProcessingUnit, ScalingType, TransformType


def main():
    dim_x = dim_y = dim_z = 4

    # Frequency-domain triplets: every (x, y, z) of the dense grid (a real
    # application supplies only the indices inside its energy cutoff; see
    # sp.create_spherical_cutoff_triplets).
    indices = np.stack(
        np.meshgrid(
            np.arange(dim_x), np.arange(dim_y), np.arange(dim_z), indexing="ij"
        ),
        axis=-1,
    ).reshape(-1, 3)

    # A Grid pre-allocates for transforms up to the given maxima and can back
    # many transforms; processing unit HOST = CPU engine, GPU = accelerator.
    grid = Grid(
        dim_x,
        dim_y,
        dim_z,
        max_num_local_z_columns=dim_x * dim_y,
        processing_unit=ProcessingUnit.HOST,
    )
    transform = grid.create_transform(
        ProcessingUnit.HOST,
        TransformType.C2C,
        dim_x,
        dim_y,
        dim_z,
        indices=indices,
    )

    rng = np.random.default_rng(0)
    n = len(indices)
    values = rng.standard_normal(n) + 1j * rng.standard_normal(n)
    print(f"input frequency values ({n} elements), first 4: {values[:4]}")

    space = transform.backward(values)  # (dim_z, dim_y, dim_x)
    print(f"space domain shape: {space.shape}, dtype: {space.dtype}")
    print(f"space_domain_data()[0, 0, :4]: {transform.space_domain_data()[0, 0, :4]}")

    roundtrip = transform.forward(scaling=ScalingType.FULL)
    print(f"max roundtrip error: {np.abs(roundtrip - values).max():.2e}")


if __name__ == "__main__":
    main()
