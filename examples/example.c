/*
 * Minimal spfft-tpu C API example — the reference example flow
 * (reference: examples/example.c behavior): triplets -> grid -> transform ->
 * backward -> space pointer -> forward with scaling.
 *
 * Build (after building the native library):
 *   cc examples/example.c -Inative/include -Lnative/build -lspfft_tpu -o example
 *   LD_LIBRARY_PATH=native/build PYTHONPATH=/root/repo ./example
 */
#include <stdio.h>
#include <stdlib.h>

#include <spfft/spfft.h>

int main(void) {
  const int dim = 4;
  const int n = dim * dim * dim;

  int* indices = (int*)malloc((size_t)(3 * n) * sizeof(int));
  int k = 0;
  for (int x = 0; x < dim; ++x)
    for (int y = 0; y < dim; ++y)
      for (int z = 0; z < dim; ++z) {
        indices[k++] = x;
        indices[k++] = y;
        indices[k++] = z;
      }

  SpfftGrid grid = NULL;
  if (spfft_grid_create(&grid, dim, dim, dim, dim * dim, SPFFT_PU_HOST, 1) !=
      SPFFT_SUCCESS)
    return 1;

  SpfftTransform transform = NULL;
  if (spfft_transform_create(&transform, grid, SPFFT_PU_HOST, SPFFT_TRANS_C2C, dim, dim,
                             dim, dim, n, SPFFT_INDEX_TRIPLETS, indices) != SPFFT_SUCCESS)
    return 1;
  /* The grid handle may be destroyed right away: the transform keeps the
   * shared resources alive (reference semantics). */
  spfft_grid_destroy(grid);

  double* freq = (double*)malloc((size_t)(2 * n) * sizeof(double));
  for (int i = 0; i < n; ++i) {
    freq[2 * i] = (double)(i + 1) / n;      /* re */
    freq[2 * i + 1] = -(double)(i + 1) / n; /* im */
  }

  if (spfft_transform_backward(transform, freq, SPFFT_PU_HOST) != SPFFT_SUCCESS) return 1;

  double* space = NULL;
  if (spfft_transform_get_space_domain(transform, SPFFT_PU_HOST, &space) != SPFFT_SUCCESS)
    return 1;
  printf("space domain, first element: %f + %fi\n", space[0], space[1]);

  if (spfft_transform_forward(transform, SPFFT_PU_HOST, freq, SPFFT_FULL_SCALING) !=
      SPFFT_SUCCESS)
    return 1;
  printf("roundtrip, first element: %f + %fi (expected %f + %fi)\n", freq[0], freq[1],
         1.0 / n, -1.0 / n);

  /* `space` points into transform-owned memory; only the handles are freed. */
  spfft_transform_destroy(transform);
  free(freq);
  free(indices);
  return 0;
}
