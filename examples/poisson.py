"""Plane-wave Poisson solver on a sparse frequency sphere — the workload class
SpFFT was built for (SIRIUS-style plane-wave DFT codes; reference: README.md:8).

Solves the periodic Poisson equation  -lap(phi) = rho  on an N^3 box:
the charge density rho lives on the real-space grid; its spectrum is truncated
to a spherical cutoff |G| <= G_max (the plane-wave basis), where the equation
diagonalizes: phi_hat(G) = rho_hat(G) / |G|^2 (phi_hat(0) = 0 fixes the gauge
for a neutral cell). Only the inside-cutoff coefficients are ever stored or
transformed — exactly the sparse-frequency contract of the library.

Run: PYTHONPATH=/root/repo python examples/poisson.py
"""
import numpy as np

import spfft_tpu as sp
from spfft_tpu import ProcessingUnit, ScalingType, Transform, TransformType


def main():
    n = 48
    box = 2 * np.pi  # cubic cell, side length 2*pi -> G vectors are integers

    # Plane-wave basis: all G triplets inside the cutoff sphere (centered
    # indexing: negative frequencies as negative integers).
    g_max = n // 4
    # generator returns centered triplets (negative frequencies as negatives)
    trip = sp.create_spherical_cutoff_triplets(n, n, n, 2 * g_max / n)
    g = trip.astype(np.float64) * (2 * np.pi / box)
    g2 = (g**2).sum(axis=1)

    t = Transform(
        ProcessingUnit.GPU if _have_accel() else ProcessingUnit.HOST,
        TransformType.C2C,
        n,
        n,
        n,
        indices=trip,
    )

    # A neutral charge density: two opposite Gaussian blobs.
    zyx = np.stack(
        np.meshgrid(*([np.arange(n) * (box / n)] * 3), indexing="ij"), axis=-1
    )

    def blob(center, sign, width=0.35):
        d = zyx - np.asarray(center)
        d -= box * np.round(d / box)  # minimum-image (periodic)
        return sign * np.exp(-(d**2).sum(-1) / (2 * width**2))

    rho = blob((2.0, 2.0, 2.0), +1.0) + blob((4.5, 4.0, 3.0), -1.0)
    rho -= rho.mean()  # enforce neutrality exactly

    # forward: real space -> sparse plane-wave coefficients (scaled DFT)
    rho_hat = t.forward(rho.astype(np.complex128), scaling=ScalingType.FULL)

    # solve in the plane-wave basis
    phi_hat = np.where(g2 > 0, rho_hat / np.maximum(g2, 1e-300), 0.0)

    # backward: coefficients -> potential on the grid
    phi = t.backward(phi_hat).real

    # residual of the PDE, evaluated spectrally on the SAME sparse basis
    lap_hat = t.forward(phi.astype(np.complex128), scaling=ScalingType.FULL) * g2
    mask = g2 > 0
    res = np.abs(lap_hat[mask] - rho_hat[mask]).max() / np.abs(rho_hat[mask]).max()

    print(f"plane-wave basis size: {len(trip)} of {n**3} grid points "
          f"({100 * len(trip) / n**3:.1f}%)")
    print(f"potential range: [{phi.min():.4f}, {phi.max():.4f}]")
    print(f"spectral residual |G^2 phi - rho| / |rho|: {res:.2e}")
    # the transform roundtrip is ~1e-9; the spectral residual amplifies it by
    # |G|^2 (up to ~430 here), so a few 1e-6 is the expected floor
    assert res < 1e-5, "Poisson solve failed"
    print("OK")


def _have_accel() -> bool:
    import jax

    return jax.default_backend() != "cpu"


if __name__ == "__main__":
    main()
