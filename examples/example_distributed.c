/*
 * Distributed spfft-tpu C API example: a 4-shard mesh transform from C.
 *
 * Single-controller model: this ONE process drives every shard of the device
 * mesh (the reference's per-rank MPI arrays become shard-major concatenated
 * buffers). On a machine without accelerators, SPFFT_TPU_NUM_CPU_DEVICES=4
 * provides a virtual 4-device CPU mesh.
 *
 * Build (after building the native library):
 *   cc examples/example_distributed.c -Inative/include -Lnative/build \
 *      -lspfft_tpu -o example_distributed
 *   LD_LIBRARY_PATH=native/build PYTHONPATH=/root/repo JAX_PLATFORMS=cpu \
 *      SPFFT_TPU_NUM_CPU_DEVICES=4 ./example_distributed
 */
#include <stdio.h>
#include <stdlib.h>

#include <spfft/spfft.h>

int main(void) {
  const int dim = 8;
  const int shards = 4;
  const int n = dim * dim * dim;

  /* shard r owns the z-sticks with x in {2r, 2r+1} (whole sticks per shard —
   * the hard constraint of the decomposition) */
  int counts[4];
  int* indices = (int*)malloc((size_t)(3 * n) * sizeof(int));
  int k = 0;
  for (int r = 0; r < shards; ++r) {
    counts[r] = 2 * dim * dim;
    for (int x = 2 * r; x < 2 * r + 2; ++x)
      for (int y = 0; y < dim; ++y)
        for (int z = 0; z < dim; ++z) {
          indices[k++] = x;
          indices[k++] = y;
          indices[k++] = z;
        }
  }

  /* Exact-counts exchange (the reference's COMPACT_BUFFERED / Alltoallv). */
  SpfftGrid grid = NULL;
  if (spfft_grid_create_distributed(&grid, dim, dim, dim, dim * dim, dim, shards,
                                    SPFFT_EXCH_COMPACT_BUFFERED, SPFFT_PU_HOST,
                                    1) != SPFFT_SUCCESS) {
    fprintf(stderr, "grid creation failed\n");
    return 1;
  }

  SpfftDistTransform t = NULL;
  if (spfft_dist_transform_create(&t, grid, SPFFT_PU_HOST, SPFFT_TRANS_C2C, dim, dim,
                                  dim, shards, counts, SPFFT_INDEX_TRIPLETS, indices,
                                  1) != SPFFT_SUCCESS) {
    fprintf(stderr, "transform creation failed\n");
    return 1;
  }

  long long wire = 0;
  spfft_dist_transform_exchange_wire_bytes(t, &wire);
  printf("4-shard plan; %lld interconnect bytes per repartition\n", wire);

  /* shard-major concatenated complex values; global (Z, Y, X) space slab */
  double* values = (double*)malloc((size_t)(2 * n) * sizeof(double));
  double* space = (double*)malloc((size_t)(2 * n) * sizeof(double));
  double* back = (double*)malloc((size_t)(2 * n) * sizeof(double));
  for (int i = 0; i < 2 * n; ++i) values[i] = (double)(i % 7) - 3.0;

  if (spfft_dist_transform_backward(t, values, space) != SPFFT_SUCCESS) return 1;
  if (spfft_dist_transform_forward(t, space, back, SPFFT_FULL_SCALING) !=
      SPFFT_SUCCESS)
    return 1;

  double max_err = 0.0;
  for (int i = 0; i < 2 * n; ++i) {
    double d = back[i] - values[i];
    if (d < 0) d = -d;
    if (d > max_err) max_err = d;
  }
  printf("distributed roundtrip max error: %g\n", max_err);

  spfft_dist_transform_destroy(t);
  spfft_grid_destroy(grid);
  free(values);
  free(space);
  free(back);
  free(indices);
  return max_err < 1e-10 ? 0 : 1;
}
