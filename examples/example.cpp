/*
 * Minimal spfft-tpu C++ API example — the reference example flow
 * (reference: examples/example.cpp behavior): triplets -> Grid -> Transform ->
 * backward -> space_domain_data -> forward with scaling.
 *
 * Build (after building the native library):
 *   c++ -std=c++17 examples/example.cpp -Inative/include -Lnative/build \
 *       -lspfft_tpu -o example_cpp
 *   LD_LIBRARY_PATH=native/build PYTHONPATH=/root/repo ./example_cpp
 */
#include <cstdio>
#include <vector>

#include <spfft/spfft.hpp>

int main() {
  const int dim = 4;
  const int n = dim * dim * dim;

  std::vector<int> indices;
  indices.reserve(3 * n);
  for (int x = 0; x < dim; ++x)
    for (int y = 0; y < dim; ++y)
      for (int z = 0; z < dim; ++z) {
        indices.push_back(x);
        indices.push_back(y);
        indices.push_back(z);
      }

  spfft::Grid grid(dim, dim, dim, dim * dim, SPFFT_PU_HOST, 1);
  spfft::Transform transform = grid.create_transform(
      SPFFT_PU_HOST, SPFFT_TRANS_C2C, dim, dim, dim, dim, n, SPFFT_INDEX_TRIPLETS,
      indices.data());

  std::vector<double> freq(2 * n);
  for (int i = 0; i < n; ++i) {
    freq[2 * i] = double(i + 1) / n;
    freq[2 * i + 1] = -double(i + 1) / n;
  }

  transform.backward(freq.data(), SPFFT_PU_HOST);
  const double* space = transform.space_domain_data(SPFFT_PU_HOST);
  std::printf("space domain, first element: %f + %fi\n", space[0], space[1]);

  transform.forward(SPFFT_PU_HOST, freq.data(), SPFFT_FULL_SCALING);
  std::printf("roundtrip, first element: %f + %fi (expected %f + %fi)\n", freq[0],
              freq[1], 1.0 / n, -1.0 / n);
  return 0;
}
