!> Minimal spfft-tpu Fortran example — the reference example flow
!> (reference: examples/example.f90 behavior): triplets -> grid -> transform ->
!> backward -> space pointer -> forward with scaling.
!>
!> Build (after building the native library; needs a Fortran compiler):
!>   gfortran native/include/spfft/spfft.f90 examples/example.f90 \
!>     -Lnative/build -lspfft_tpu -o example_f90
!>   LD_LIBRARY_PATH=native/build PYTHONPATH=/root/repo ./example_f90

program example
  use iso_c_binding
  use spfft
  implicit none

  integer, parameter :: dim = 4
  integer, parameter :: n = dim * dim * dim
  integer(c_int) :: indices(3 * n)
  real(c_double) :: freq(2 * n)
  real(c_double), pointer :: space(:)
  type(c_ptr) :: grid = c_null_ptr
  type(c_ptr) :: transform = c_null_ptr
  type(c_ptr) :: space_ptr = c_null_ptr
  integer :: x, y, z, i, k, st

  k = 1
  do x = 0, dim - 1
    do y = 0, dim - 1
      do z = 0, dim - 1
        indices(k) = x
        indices(k + 1) = y
        indices(k + 2) = z
        k = k + 3
      end do
    end do
  end do

  do i = 1, n
    freq(2 * i - 1) = real(i, c_double) / n
    freq(2 * i) = -real(i, c_double) / n
  end do

  st = spfft_grid_create(grid, dim, dim, dim, dim * dim, SPFFT_PU_HOST, 1)
  if (st /= SPFFT_SUCCESS) error stop "grid_create"

  st = spfft_transform_create(transform, grid, SPFFT_PU_HOST, SPFFT_TRANS_C2C, &
                              dim, dim, dim, dim, n, SPFFT_INDEX_TRIPLETS, indices)
  if (st /= SPFFT_SUCCESS) error stop "transform_create"

  ! the transform keeps the shared resources alive (reference semantics)
  st = spfft_grid_destroy(grid)

  st = spfft_transform_backward(transform, freq, SPFFT_PU_HOST)
  if (st /= SPFFT_SUCCESS) error stop "backward"

  st = spfft_transform_get_space_domain(transform, SPFFT_PU_HOST, space_ptr)
  if (st /= SPFFT_SUCCESS) error stop "get_space_domain"
  call c_f_pointer(space_ptr, space, [2 * n])
  print *, "space domain, first element:", space(1), space(2)

  st = spfft_transform_forward(transform, SPFFT_PU_HOST, freq, SPFFT_FULL_SCALING)
  if (st /= SPFFT_SUCCESS) error stop "forward"
  print *, "roundtrip, first element:", freq(1), freq(2), &
           " (expected", 1.0_c_double / n, -1.0_c_double / n, ")"

  st = spfft_transform_destroy(transform)
end program example
