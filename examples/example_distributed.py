"""Distributed spfft-tpu example: a 4-shard mesh transform from Python.

Single-controller model: this ONE process drives every shard of a device mesh
(the reference's per-rank MPI arrays become per-shard lists). On a machine
without accelerators a virtual 4-device CPU mesh stands in — run with

    JAX_PLATFORMS=cpu python examples/example_distributed.py

(the script requests the virtual devices itself). Demonstrates the plan flow,
the round-trip, and the exchange-discipline accounting
(``exchange_wire_bytes`` / ``exchange_rounds``) that guides the
BUFFERED / COMPACT_BUFFERED / UNBUFFERED choice.
"""
import numpy as np

import spfft_tpu as sp
from spfft_tpu import (
    DistributedTransform,
    ExchangeType,
    ProcessingUnit,
    ScalingType,
    TransformType,
)
from spfft_tpu.parallel.mesh import ensure_virtual_devices
from spfft_tpu.parameters import distribute_triplets


def main():
    dim = 16
    num_shards = 4

    devices = ensure_virtual_devices(num_shards, platform="cpu")
    mesh = sp.make_fft_mesh(devices=devices)

    # Frequency-domain triplets inside a spherical cutoff (plane-wave style),
    # partitioned by whole z-sticks — every (x, y) column lives on one shard.
    triplets = sp.create_spherical_cutoff_triplets(dim, dim, dim, 0.7)
    per_shard = distribute_triplets(triplets, num_shards, dim)

    rng = np.random.default_rng(0)
    values = [
        rng.standard_normal(len(p)) + 1j * rng.standard_normal(len(p))
        for p in per_shard
    ]

    for exchange in (
        ExchangeType.BUFFERED,
        ExchangeType.COMPACT_BUFFERED,
        ExchangeType.UNBUFFERED,
    ):
        t = DistributedTransform(
            ProcessingUnit.HOST,
            TransformType.C2C,
            dim,
            dim,
            dim,
            [p.copy() for p in per_shard],
            mesh=mesh,
            exchange_type=exchange,
        )
        space = t.backward([v.copy() for v in values])  # global (Z, Y, X)
        back = t.forward(scaling=ScalingType.FULL)  # per-shard value lists
        err = max(np.abs(b - v).max() for b, v in zip(back, values))
        print(
            f"{exchange.name:16s} roundtrip {err:.2e}  "
            f"wire {t.exchange_wire_bytes():>8,} B  "
            f"rounds {t.exchange_rounds()}"
        )
        assert err < 1e-4  # f32 default dtype (dtype=np.float64 + x64 for 1e-14)
    print("space domain shape:", space.shape)


if __name__ == "__main__":
    main()
