#!/usr/bin/env bash
# CI entry point — the one command a fresh checkout runs green.
#
# The analogue of the reference's CI pipeline (reference:
# .github/workflows/ci.yml: build + run_local_tests + mpirun -n 2
# run_mpi_tests): Python suite on a virtual 8-device CPU mesh (distributed
# paths included — the conftest forces jax_platforms=cpu), the CPU-forced
# multichip dryrun, and the native C/C++ build + API roundtrip.
#
# Usage:   ./ci.sh            # everything
#          ./ci.sh lint       # ported checkers 1-9 (programs/lint.py shim)
#          ./ci.sh analyze    # full static-analysis gate + doctored-trip proofs
#          ./ci.sh python     # Python suite only
#          ./ci.sh report     # plan-card CLI + JSON schema validation only
#          ./ci.sh tune       # autotuner smoke (trial + wisdom hit, CPU)
#          ./ci.sh trace      # flight recorder: schema + Chrome export + dump
#          ./ci.sh chaos      # fault sites armed one-at-a-time + guard fuzz
#          ./ci.sh verify     # ABFT checks, corrupt-injection recovery, breaker
#          ./ci.sh serve      # serving layer: loadgen smoke + overload chaos
#          ./ci.sh sched      # task-graph scheduler: gbench + gate + chaos
#          ./ci.sh perf       # dbench scaling rows + schema + regression gate
#          ./ci.sh ir         # stage-graph IR: parity suite + fbench fused-vs-staged gate
#          ./ci.sh mhost      # multi-host serving: boot proof + chaos-killed worker
#          ./ci.sh dryrun     # multichip dryrun only
#          ./ci.sh native     # native build + tests only
#
# No network, no accelerator, and no MPI launcher required: every stage runs
# on CPU; a wedged/absent accelerator tunnel must not affect any of it.
set -euo pipefail
cd "$(dirname "$0")"

stage="${1:-all}"

run_lint() {
  echo "== Lint (programs/lint.py: shim over spfft_tpu.analysis checkers 1-9) =="
  python programs/lint.py
}

run_analyze() {
  echo "== Analyze (spfft_tpu.analysis: 19 checkers, baselined gate) =="
  local adir
  adir="$(mktemp -d)"
  # Full gate over the real tree: zero non-baselined findings, and the
  # spfft_tpu.analysis/1 report must validate against its schema floor.
  python programs/analyze.py --json "$adir/analysis.json"
  python - "$adir" <<'EOF'
import json, sys
sys.path.insert(0, "programs")
from analyze import load_analysis

analysis = load_analysis()
doc = json.loads(open(f"{sys.argv[1]}/analysis.json").read())
missing = analysis.validate_report(doc)
assert not missing, f"analysis report schema incomplete: {missing}"
assert len(doc["checkers"]) == 19, [c["code"] for c in doc["checkers"]]
assert doc["counts"]["new"] == 0 and doc["counts"]["stale_baseline"] == 0, doc["counts"]
print(f"analysis report ok ({len(doc['checkers'])} checkers, "
      f"{doc['counts']['total']} finding(s), all baselined)")
EOF
  # The suppression audit: every in-tree `# noqa: SA*` must still fire —
  # an orphaned suppression hides the next real regression on its line.
  python programs/analyze.py --list-noqa -q
  # The gate must TRIP (exit 3, the distinct tripped-gate code) on doctored
  # trees. Copy the scanned surface + anchors, then doctor one defect per
  # proof and assert the typed finding appears.
  mkdir -p "$adir/tree_locks"
  cp -r spfft_tpu programs docs tests analysis_baseline.json "$adir/tree_locks/"
  local t
  for t in donate stale b15 b16 b17 b18 b19; do
    cp -r "$adir/tree_locks" "$adir/tree_$t"
  done
  # (a) lock-order cycle: two module locks acquired in opposite orders.
  cat > "$adir/tree_locks/spfft_tpu/_doctored_locks.py" <<'EOF'
"""Doctored CI fixture: a lock-order cycle the SA011 gate must catch."""
import threading

A = threading.Lock()
B = threading.Lock()


def one():
    with A:
        with B:
            pass


def two():
    with B:
        with A:
            pass
EOF
  local rc=0
  python programs/analyze.py --root "$adir/tree_locks" --only SA011 \
    --json "$adir/locks.json" > /dev/null || rc=$?
  if [ "$rc" -ne 3 ]; then
    echo "analysis gate did not trip on doctored lock-order cycle (rc=$rc)" >&2
    exit 1
  fi
  python - "$adir" <<'EOF'
import json, sys

doc = json.loads(open(f"{sys.argv[1]}/locks.json").read())
hits = [f for f in doc["findings"]
        if f["code"] == "SA011" and "cycle" in f["message"]]
assert hits and not hits[0]["baselined"], doc["findings"]
print(f"doctored lock-order trip ok ({hits[0]['file']})")
EOF
  # (b) use-after-donate: a local backward graph referencing a donated
  # input edge after its consuming node.
  cat >> "$adir/tree_donate/spfft_tpu/ir/lower.py" <<'EOF'


def _lower_local_doctored(e):
    """Doctored CI fixture: use-after-donate the SA012 gate must catch."""

    def backward():
        g = StageGraph("backward")
        g.add_input("values_re")
        g.add_input("values_im")
        g.add(
            "compression", e._st_decompress,
            ("values_re", "values_im"), ("sticks",),
        )
        g.add("z transform", e._st_z_backward, ("sticks", "values_re"), ("z",))
        g.set_outputs(["z"])
        return g

    return {"backward": backward()}
EOF
  rc=0
  python programs/analyze.py --root "$adir/tree_donate" --only SA012 \
    --json "$adir/donate.json" > /dev/null || rc=$?
  if [ "$rc" -ne 3 ]; then
    echo "analysis gate did not trip on doctored use-after-donate (rc=$rc)" >&2
    exit 1
  fi
  python - "$adir" <<'EOF'
import json, sys

doc = json.loads(open(f"{sys.argv[1]}/donate.json").read())
hits = [f for f in doc["findings"]
        if f["code"] == "SA012" and "referenced after its consuming node" in f["message"]]
assert hits and not hits[0]["baselined"], doc["findings"]
print(f"doctored use-after-donate trip ok ({hits[0]['file']}:{hits[0]['line']})")
EOF
  # (c) baseline freshness: an accepted entry whose finding no longer
  # exists must trip too — a fixed finding must leave the baseline.
  python - "$adir" <<'EOF'
import json, sys

p = f"{sys.argv[1]}/tree_stale/analysis_baseline.json"
doc = json.loads(open(p).read())
doc["entries"].append("SA010:spfft_tpu/ghost.py:finding that was fixed")
json.dump(doc, open(p, "w"), indent=2)
EOF
  rc=0
  python programs/analyze.py --root "$adir/tree_stale" > /dev/null || rc=$?
  if [ "$rc" -ne 3 ]; then
    echo "analysis gate did not trip on a stale baseline entry (rc=$rc)" >&2
    exit 1
  fi
  # (d) one doctored trip per concurrency/dataflow checker (SA015-SA019):
  # each tree carries exactly one planted defect; the gate must exit 3
  # with the typed finding.
  cat >> "$adir/tree_b15/spfft_tpu/ir/lower.py" <<'EOF'


def _lower_slab_doctored(e):
    """Doctored CI fixture: batched use-after-consume the SA015 gate must catch."""

    def backward():
        g = StageGraph("backward")
        g.add_input("values_re")
        g.add_input("values_im")
        g.batch_inputs = ("values_re", "values_im")
        g.add(
            "compression", e._st_decompress,
            ("values_re", "values_im"), ("sticks",),
        )
        g.add("z transform", e._st_z, ("sticks", "values_im"), ("z",))
        g.set_outputs(["z"])
        return g

    return {"backward": backward()}
EOF
  cat > "$adir/tree_b16/spfft_tpu/_doctored_metrics.py" <<'EOF'
"""Doctored CI fixture: an undeclared metric the SA016 gate must catch."""
from . import obs


def emit():
    obs.counter("rogue_doctored_total", where="nowhere").inc()
EOF
  cat > "$adir/tree_b17/spfft_tpu/_doctored_threads.py" <<'EOF'
"""Doctored CI fixture: a leaked non-daemon thread the SA017 gate must catch."""
import threading


def go(work):
    t = threading.Thread(target=work)
    t.start()
    return t
EOF
  python - "$adir" <<'EOF'
# SA018: register a new fault site WITHOUT a targeted chaos test
import sys

p = f"{sys.argv[1]}/tree_b18/spfft_tpu/faults/plane.py"
src = open(p).read()
doctored = src.replace('    "sched.run",\n', '    "sched.run",\n    "doctored.site",\n')
assert doctored != src, "SITES anchor moved: update the SA018 doctored trip"
open(p, "w").write(doctored)
EOF
  cat > "$adir/tree_b19/spfft_tpu/_doctored_traced.py" <<'EOF'
"""Doctored CI fixture: a sleep inside a timing span the SA019 gate must catch."""
import time

from . import timing


def f():
    with timing.scoped("dispatch"):
        time.sleep(0.1)
EOF
  local code tree needle
  for spec in \
    "SA015:b15:referenced after its consuming node" \
    "SA016:b16:not declared in the canonical vocabulary" \
    "SA017:b17:neither daemon=True nor joined" \
    "SA018:b18:no targeted chaos test" \
    "SA019:b19:inside timing.scoped"; do
    code="${spec%%:*}"; rest="${spec#*:}"; tree="${rest%%:*}"; needle="${rest#*:}"
    rc=0
    python programs/analyze.py --root "$adir/tree_$tree" --only "$code" \
      --json "$adir/$tree.json" > /dev/null || rc=$?
    if [ "$rc" -ne 3 ]; then
      echo "analysis gate did not trip on doctored $code tree (rc=$rc)" >&2
      exit 1
    fi
    python - "$adir" "$tree" "$code" "$needle" <<'EOF'
import json, sys

doc = json.loads(open(f"{sys.argv[1]}/{sys.argv[2]}.json").read())
hits = [f for f in doc["findings"]
        if f["code"] == sys.argv[3] and sys.argv[4] in f["message"]]
assert hits and not hits[0]["baselined"], doc["findings"]
print(f"doctored {sys.argv[3]} trip ok ({hits[0]['file']}:{hits[0]['line']})")
EOF
  done
  # (e) runtime lockdep: the serve+sched suites run with every package
  # lock wrapped; the observed acquisition graph must validate against
  # SA011's static model with zero unexplained edges, no cycles, and no
  # blocking waits.
  JAX_PLATFORMS=cpu SPFFT_TPU_LOCKDEP=1 \
    SPFFT_TPU_LOCKDEP_REPORT="$adir/lockdep.json" \
    timeout 1500 python -m pytest tests/test_serve.py tests/test_sched.py -q
  rc=0
  python programs/analyze.py --lockdep-check "$adir/lockdep.json" || rc=$?
  if [ "$rc" -ne 0 ]; then
    echo "lockdep cross-check found unexplained runtime lock edges (rc=$rc)" >&2
    exit 1
  fi
  python - "$adir" <<'EOF'
import json, sys

doc = json.loads(open(f"{sys.argv[1]}/lockdep.json").read())
assert doc["schema"] == "spfft_tpu.analysis.lockdep/1", doc["schema"]
assert doc["counts"]["locks"] > 0 and doc["counts"]["edges"] > 0, doc["counts"]
assert doc["cycles"] == [] and doc["blocking"] == [], (doc["cycles"], doc["blocking"])
print(f"lockdep armed run ok ({doc['counts']['locks']} locks, "
      f"{doc['counts']['edges']} edges, 0 cycles, 0 blocking)")
EOF
  echo "analyze gate ok (tree green, noqa audit clean, doctored SA011/SA012/SA015-SA019 + stale baseline each exit 3, lockdep runtime graph matches static)"
  rm -rf "$adir"
}

run_python() {
  echo "== Python test suite (virtual 8-device CPU mesh) =="
  python -m pytest tests/ -q
}

run_report() {
  echo "== Plan-card report (programs/report.py, CPU backend) =="
  # Build a 32^3 plan on CPU, emit the plan card + metrics snapshot, and
  # validate the JSON against the obs schema — missing keys fail (plan-card
  # drift is caught here without TPU hardware).
  JAX_PLATFORMS=cpu timeout 540 python programs/report.py -d 32 32 32 \
    -o /tmp/spfft_ci_report.json > /dev/null
  JAX_PLATFORMS=cpu python - <<'EOF'
import json
from spfft_tpu import obs

doc = json.loads(open("/tmp/spfft_ci_report.json").read())
missing = obs.validate_report(doc)
assert not missing, f"report schema incomplete: {missing}"
print(f"report schema ok ({len(doc['plan'])} plan keys, "
      f"{len(doc['metrics']['counters'])} counters)")
EOF
}

run_tune() {
  echo "== Tune smoke (programs/tune.py: trials then wisdom hit, CPU) =="
  # Tiny grid, 1-repeat trial budget, tmpdir wisdom file, CPU trials allowed
  # (SPFFT_TPU_TUNE_CPU via --allow-cpu-trials). Run twice: the first run
  # must measure, the second must hit wisdom with ZERO trials — the whole
  # tuned-policy loop exercised without accelerator hardware.
  local wdir
  wdir="$(mktemp -d)"
  JAX_PLATFORMS=cpu SPFFT_TPU_WISDOM="$wdir/wisdom.json" timeout 540 \
    python programs/tune.py -d 8 8 8 --shards 2 -s 0.6 --repeats 1 \
    --allow-cpu-trials -o "$wdir/tune1.json" > /dev/null
  JAX_PLATFORMS=cpu SPFFT_TPU_WISDOM="$wdir/wisdom.json" timeout 540 \
    python programs/tune.py -d 8 8 8 --shards 2 -s 0.6 --repeats 1 \
    --allow-cpu-trials -o "$wdir/tune2.json" > /dev/null
  python - "$wdir" <<'EOF'
import json, sys

d = sys.argv[1]
t1 = json.load(open(f"{d}/tune1.json"))["tuning"]
t2 = json.load(open(f"{d}/tune2.json"))["tuning"]
assert t1["hit"] is False and t1["trials"], t1
assert t2["hit"] is True and t2["provenance"] == "wisdom", t2
assert t2["choice"] == t1["choice"], (t1["choice"], t2["choice"])
print(f"tune smoke ok: {t1['choice']} ({len(t1['trials'])} trials, "
      "0 on the second construction)")
EOF
  rm -rf "$wdir"
}

run_trace() {
  echo "== Trace (spfft_tpu.obs.trace: flight recorder, Chrome export, dump-on-error, CPU) =="
  # Traced roundtrip on the CPU backend: the snapshot must validate against
  # its schema and the Chrome export must round-trip through json.load with
  # begin/end pairs for every host phase — trace drift fails here without
  # TPU hardware.
  local tdir
  tdir="$(mktemp -d)"
  JAX_PLATFORMS=cpu SPFFT_TPU_TRACE=1 timeout 540 python programs/trace.py \
    -d 16 16 16 --chrome "$tdir/chrome.json" -o "$tdir/snapshot.json" > /dev/null
  JAX_PLATFORMS=cpu python - "$tdir" <<'EOF'
import json, sys
from spfft_tpu.obs import trace

d = sys.argv[1]
snap = json.load(open(f"{d}/snapshot.json"))
missing = trace.validate_trace(snap)
assert not missing, f"trace schema incomplete: {missing}"
chrome = json.load(open(f"{d}/chrome.json"))
events = chrome["traceEvents"]
for phase in ("backward", "forward", "dispatch", "wait"):
    b = [e for e in events if e["name"] == phase and e["ph"] == "B"]
    e_ = [e for e in events if e["name"] == phase and e["ph"] == "E"]
    assert b and len(b) == len(e_), f"unbalanced chrome track {phase!r}"
print(f"trace schema ok ({len(snap['events'])} events, "
      f"{len(events)} chrome entries)")
EOF
  # Dump-on-error: with a fault site armed to raise, the typed error the
  # ladder converts it to must flush the recorder to SPFFT_TPU_TRACE_DUMP,
  # and the dump's events must carry the failing plan's run ID.
  JAX_PLATFORMS=cpu SPFFT_TPU_TRACE=1 SPFFT_TPU_TRACE_DUMP="$tdir/dumps" \
    SPFFT_TPU_FAULTS="sync.fence=raise" timeout 540 python - "$tdir" <<'EOF'
import glob, json, sys, warnings
import numpy as np
import spfft_tpu as sp
from spfft_tpu import HostExecutionError, ProcessingUnit, Transform, TransformType

d = sys.argv[1]
trip = sp.create_spherical_cutoff_triplets(8, 8, 8, 0.9)
t = Transform(ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8, indices=trip)
rid = t.report()["run_id"]
try:
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        t.backward(np.ones(len(trip), dtype=np.complex128))
except HostExecutionError:
    pass
else:
    raise AssertionError("armed sync.fence fault did not surface typed")
dumps = sorted(glob.glob(f"{d}/dumps/trace-*.json"))
assert dumps, "no dump file written"
doc = json.load(open(dumps[-1]))
runs = {ev["run"] for ev in doc["events"]}
assert rid in runs, (rid, runs)
print(f"dump-on-error ok ({dumps[-1].split('/')[-1]}, run {rid})")
EOF
  rm -rf "$tdir"
}

run_chaos() {
  echo "== Chaos (spfft_tpu.faults: every site armed at rate 1.0, CPU) =="
  # The chaos invariant: with each registered fault site armed one-at-a-time,
  # every transform either raises a typed spfft_tpu.errors exception or
  # returns parity-correct output via a recorded fallback (plan-card
  # degradations + obs metrics) — never a silent wrong answer.
  timeout 540 python -m pytest tests/test_faults.py tests/test_degradation.py -q
  echo "== Guard-mode parity fuzz (SPFFT_TPU_GUARD=1) =="
  # Guard instrumentation must not perturb numerics: the engine-parity fuzzer
  # runs with every pre/post check active and must stay bit-for-bar green.
  SPFFT_TPU_GUARD=1 timeout 540 python -m pytest tests/test_engine_parity_fuzz.py -q
}

run_verify() {
  echo "== Verify (spfft_tpu.verify: ABFT checks + recovery supervisor + breaker, CPU) =="
  timeout 540 python -m pytest tests/test_verify.py -q
  local vdir
  vdir="$(mktemp -d)"
  # Clean verified roundtrip: every check passes, card schema-complete
  # (verification section included), zero recoveries.
  JAX_PLATFORMS=cpu timeout 540 python programs/verify.py -d 16 16 16 \
    -o "$vdir/clean.json" > /dev/null
  # SDC end-to-end: every dispatch corrupted, yet the roundtrip recovers via
  # the jnp.fft reference rung with the recovery recorded — the acceptance
  # invariant (a silently wrong result is impossible) exercised for real.
  JAX_PLATFORMS=cpu timeout 540 python programs/verify.py -d 16 16 16 \
    --inject "engine.execute=corrupt:1.0" -o "$vdir/corrupt.json" > /dev/null
  python - "$vdir" <<'EOF'
import json, sys

d = sys.argv[1]
clean = json.load(open(f"{d}/clean.json"))
corrupt = json.load(open(f"{d}/corrupt.json"))
assert clean["outcome"] == "verified" and not clean["degradations"], clean
assert clean["roundtrip_residual"] < 1e-4, clean["roundtrip_residual"]
assert not clean.get("card_schema_missing"), clean["card_schema_missing"]
for k in ("mode", "checks", "rtol", "retries", "breaker"):
    assert k in clean["verification"], (k, clean["verification"])
assert corrupt["outcome"] == "verified", corrupt
assert corrupt["roundtrip_residual"] < 1e-4, corrupt["roundtrip_residual"]
recoveries = sum(v for k, v in corrupt["metrics"].items()
                 if k.startswith("verify_recoveries_total"))
assert recoveries > 0, corrupt["metrics"]
assert any(e["event"] == "verify_demoted" for e in corrupt["degradations"])
print(f"verify smoke ok (clean residual {clean['roundtrip_residual']:.2e}, "
      f"{recoveries} recoveries under corrupt:1.0)")
EOF
  # Breaker trips at K: with K=2 and every dispatch corrupted, the third
  # transform must find the engine breaker open and skip the primary path.
  JAX_PLATFORMS=cpu SPFFT_TPU_VERIFY=1 SPFFT_TPU_VERIFY_BREAKER_K=2 \
    SPFFT_TPU_FAULTS="engine.execute=corrupt:1.0" timeout 540 python - <<'EOF'
import numpy as np
import spfft_tpu as sp
from spfft_tpu import ProcessingUnit, Transform, TransformType, obs, verify

trip = sp.create_spherical_cutoff_triplets(12, 12, 12, 0.8)
rng = np.random.default_rng(0)
values = rng.standard_normal(len(trip)) + 1j * rng.standard_normal(len(trip))
expect = None
for i in range(3):
    t = Transform(ProcessingUnit.HOST, TransformType.C2C, 12, 12, 12, indices=trip)
    out = t.backward(values)
    expect = out if expect is None else expect
    assert np.allclose(out, expect), f"roundtrip {i} diverged"
state = verify.breaker.describe(t._engine)
assert state["state"] == "open" and state["trips"] == 1, state
assert any(e["event"] == "verify_breaker_open" for e in t.report()["degradations"]), \
    t.report()["degradations"]
gauges = obs.snapshot()["gauges"]
assert any(k.startswith("verify_breaker_state") and v == 1 for k, v in gauges.items()), gauges
print(f"breaker ok: open after K=2 verified failures, third call short-circuited")
EOF
  rm -rf "$vdir"
}

run_serve() {
  echo "== Serve (spfft_tpu.serve: admission, coalescing, shedding, CPU) =="
  # The suite carries the arm-every-serve-site overload chaos sweep.
  timeout 540 python -m pytest tests/test_serve.py -q
  local sdir
  sdir="$(mktemp -d)"
  # Loadgen smoke: sustained open-loop traffic, gate-compatible rows.
  JAX_PLATFORMS=cpu timeout 540 python programs/loadgen.py -d 12 12 12 \
    -s 0.8 --tenants 2 --rate 40 --ramp 1 2 --duration 1.5 \
    -o "$sdir/loadgen.json" > /dev/null
  # Overload run under chaos: tiny queue, offered load far beyond capacity,
  # every serve.* site armed at a fractional rate — the service must keep a
  # bounded queue, shed/reject typed, and resolve every accepted ticket
  # (no deadlock: the run finishing inside its timeout IS the evidence).
  JAX_PLATFORMS=cpu SPFFT_TPU_SERVE_QUEUE_CAP=8 \
    SPFFT_TPU_FAULTS="serve.admit=raise:0.1,serve.batch=raise:0.1,serve.dispatch=raise:0.1" \
    timeout 540 python programs/loadgen.py -d 12 12 12 -s 0.8 --tenants 3 \
    --rate 2000 --ramp 1 --duration 2 --timeout-s 1.0 \
    -o "$sdir/overload.json" > /dev/null
  JAX_PLATFORMS=cpu python - "$sdir" <<'EOF'
import json, sys

d = sys.argv[1]
smoke = json.load(open(f"{d}/loadgen.json"))
assert smoke["schema"] == "spfft_tpu.serve.loadgen/1", smoke["schema"]
for row in smoke["rows"]:
    for k in ("key", "gflops", "seconds_noise", "transforms_per_sec",
              "p50_ms", "p99_ms", "rejected", "shed", "deadline_miss"):
        assert k in row, (k, row)
    assert row["completed"] > 0, row
    assert row["failed"] == 0, row
over = json.load(open(f"{d}/overload.json"))
row = over["rows"][0]
svc = over["service"]["stats"]
assert svc["queue_high_water"] <= svc["queue_capacity"], svc
# offered >= 2x what got through: this WAS overload, and the excess
# became typed rejections/sheds/deadline-misses, not latency or a wedge
refused = row["rejected"] + row["shed"] + row["deadline_miss"]
assert row["offered"] >= 2 * max(1, row["completed"]), row
assert refused > 0, row
assert row["completed"] + refused + row["failed"] == row["offered"], row
print(f"serve smoke ok ({len(smoke['rows'])} rows; overload: "
      f"{row['offered']} offered -> {row['completed']} completed, "
      f"{refused} typed refusals, high water "
      f"{svc['queue_high_water']}/{svc['queue_capacity']})")
EOF
  # Breaker-tripped degradation: with the engine breaker open, the service
  # demotes to the jnp.fft reference rung (results stay correct) instead of
  # queueing into the dead engine.
  JAX_PLATFORMS=cpu timeout 540 python - <<'EOF'
import numpy as np
import spfft_tpu as sp
from spfft_tpu import TransformType, obs, verify
from spfft_tpu.serve import TransformService

trip = sp.create_spherical_cutoff_triplets(12, 12, 12, 0.8)
rng = np.random.default_rng(0)
vals = rng.standard_normal(len(trip)) + 1j * rng.standard_normal(len(trip))
svc = TransformService(start=False, queue_capacity=8)
tk = svc.submit(TransformType.C2C, (12, 12, 12), trip, vals)
svc.pump()
expect = tk.result(timeout=30)
engine = svc.plans.describe()[0]["engine"]
for _ in range(verify.breaker.threshold()):
    verify.breaker.record_failure(engine)
assert verify.breaker.describe(engine)["state"] == "open"
tk = svc.submit(TransformType.C2C, (12, 12, 12), trip, vals)
svc.pump()
out = tk.result(timeout=30)
assert np.allclose(out, expect), "demoted result diverged"
counters = obs.snapshot()["counters"]
demoted = sum(v for k, v in counters.items()
              if k.startswith("serve_demotions_total"))
assert demoted == 1, counters
svc.close()
verify.breaker.reset()
print(f"serve breaker ok: open breaker on {engine!r} -> 1 demotion, "
      "result parity held")
EOF
  rm -rf "$sdir"
}

run_sched() {
  echo "== Sched (spfft_tpu.sched: graph executor, placement, gbench gate, CPU) =="
  # The suite carries graph semantics, tuned-placement reproducibility, and
  # the arm-every-sched-site chaos sweep (typed-or-parity, no graph stall).
  timeout 540 python -m pytest tests/test_sched.py -q
  local gdir
  gdir="$(mktemp -d)"
  # Scheduled-vs-serial on the 8-device CPU mesh: the same mixed-geometry
  # workload one-at-a-time and through the graph executor. The sched row
  # must be strictly above the serial row (the overlap is real, not a
  # measurement artifact), rows are gate-compatible, and placement
  # provenance must ride in the plan cards.
  JAX_PLATFORMS=cpu timeout 540 python programs/gbench.py --devices 8 \
    --dims 12 16 --sparsity 0.8 --tasks 16 --chain 1 --repeats 4 \
    -o "$gdir/gbench.json" > /dev/null
  JAX_PLATFORMS=cpu python - "$gdir" <<'EOF'
import json, sys

d = sys.argv[1]
doc = json.load(open(f"{d}/gbench.json"))
assert doc["schema"] == "spfft_tpu.sched.gbench/1", doc["schema"]
rows = {r["key"].rsplit(":", 1)[-1]: r for r in doc["rows"]}
for row in doc["rows"]:
    for k in ("key", "gflops", "seconds_noise", "transforms_per_sec",
              "p50_ms", "p99_ms", "overlap_vs_serial"):
        assert k in row, (k, row)
assert rows["sched"]["transforms_per_sec"] > rows["serial"]["transforms_per_sec"], (
    "scheduled graph throughput not above one-at-a-time", rows)
for card in doc["plan_cards"]:
    p = card["placement"]
    assert p and p["provenance"] in ("wisdom", "model", "pinned"), card
    assert "hit" in p and "device" in p, card
assert any(k.startswith("sched_tasks_total") for k in doc["metrics"]), doc["metrics"]
print(f"gbench ok: serial {rows['serial']['transforms_per_sec']:.0f} -> "
      f"sched {rows['sched']['transforms_per_sec']:.0f} transforms/s "
      f"(x{rows['sched']['overlap_vs_serial']:.2f}, placement "
      f"{doc['plan_cards'][0]['placement']['provenance']})")
EOF
  # Regression gate over the committed gbench baseline (wide tolerance: an
  # algorithmic slide in the executor, not scheduler jitter) ...
  python programs/perf_gate.py "$gdir/gbench.json" \
    bench_results/gbench_baseline_cpu8.json --tolerance 0.85 \
    --require-matches 2 > /dev/null
  # ... green against itself ...
  python programs/perf_gate.py "$gdir/gbench.json" "$gdir/gbench.json" \
    --require-matches 2 > /dev/null
  # ... and must trip (exit 3) on a doctored baseline claiming 10x.
  python - "$gdir" <<'EOF'
import json, sys

d = sys.argv[1]
doc = json.load(open(f"{d}/gbench.json"))
for r in doc["rows"]:
    r["gflops"] *= 10
    r["seconds_noise"] = 0.0
json.dump(doc, open(f"{d}/doctored.json", "w"))
EOF
  local rc=0
  python programs/perf_gate.py "$gdir/gbench.json" "$gdir/doctored.json" \
    > /dev/null || rc=$?
  if [ "$rc" -ne 3 ]; then
    echo "sched gate did not trip on a doctored baseline (rc=$rc)" >&2
    exit 1
  fi
  # Chaos over the scheduler's own sites at fractional rates: the workload
  # must still finish with every task completed-or-demoted (gbench asserts
  # it) — the no-graph-stall half of the chaos invariant, end to end.
  JAX_PLATFORMS=cpu \
    SPFFT_TPU_FAULTS="sched.place=raise:0.3,sched.run=raise:0.2" \
    timeout 540 python programs/gbench.py --devices 8 --dims 12 \
    --sparsity 0.8 --tasks 6 --chain 1 --repeats 1 \
    -o "$gdir/gbench_chaos.json" > /dev/null
  echo "sched gate ok (baseline green, doctored trips, chaos run clean)"
  rm -rf "$gdir"
}

run_perf() {
  echo "== Perf (spfft_tpu.obs.perf: dbench rows + schema + regression gate, CPU) =="
  # 8-virtual-device distributed bench: slab AND pencil meshes must emit
  # validating spfft_tpu.obs.perf/1 reports (per-stage attribution summing
  # to the measured pair time, geometry-exact exchange bytes, run-ID join)
  # for BOTH exchange disciplines — bulk-synchronous (ov1) and OVERLAPPED
  # (ov4 chunked double-buffered) — and the overlapped rows must show a
  # strictly lower exposed exchange_fraction than their ov1 siblings.
  local pdir
  pdir="$(mktemp -d)"
  JAX_PLATFORMS=cpu timeout 540 python programs/dbench.py --devices 8 \
    --dim 8 --sparsity 0.9 --scaling strong --repeats 2 --chain 2 \
    --engine xla --cpu --overlap 1 4 -o "$pdir/dbench.json" > /dev/null
  JAX_PLATFORMS=cpu python - "$pdir" <<'EOF'
import json, sys
from spfft_tpu.obs import perf

d = sys.argv[1]
doc = json.load(open(f"{d}/dbench.json"))
missing = perf.validate_scaling_doc(doc)
assert not missing, f"scaling doc incomplete: {missing}"
kinds = {r["decomposition"] for r in doc["rows"]}
assert kinds == {"slab", "pencil2"}, kinds
for r in doc["rows"]:
    total = sum(s["seconds"] for s in r["stages"])
    assert abs(total - r["seconds_per_pair"]) < 1e-9, r["key"]
    assert 0.0 < r["exchange_fraction"] < 1.0, r["key"]
    assert r["run_id"], r["key"]
by_ov = {}
for r in doc["rows"]:
    if r["decomposition"] == "local":
        continue
    by_ov.setdefault(r["key"].rsplit(":ov", 1)[0], {})[r["overlap_chunks"]] = r
paired = 0
for base, cells in by_ov.items():
    if len(cells) < 2:
        continue
    paired += 1
    ov1, ovc = cells[1], cells[max(cells)]
    assert ovc["exchange_fraction"] < ov1["exchange_fraction"], (
        base, ov1["exchange_fraction"], ovc["exchange_fraction"])
    assert any("overlapped" in s["stage"] for s in ovc["stages"]), base
assert paired >= 2, f"expected overlapped/bulk row pairs, got {paired}"
print(f"dbench ok ({len(doc['rows'])} rows incl. {paired} overlap pairs)")
EOF
  # Regression gate: the committed baseline is CPU-noise-calibrated (wide
  # tolerance — it exists to catch algorithmic slides, e.g. a collective
  # degrading to serialized scatter, not scheduler jitter) ...
  python programs/perf_gate.py "$pdir/dbench.json" \
    bench_results/perf_baseline_cpu8.json --tolerance 0.85 > /dev/null
  # ... a run gates green against itself ...
  python programs/perf_gate.py "$pdir/dbench.json" "$pdir/dbench.json" > /dev/null
  # ... and must trip (exit 3, the distinct regression code) against a
  # doctored baseline claiming 10x the throughput.
  python - "$pdir" <<'EOF'
import json, sys

d = sys.argv[1]
doc = json.load(open(f"{d}/dbench.json"))
for r in doc["rows"]:
    r["gflops"] *= 10
    r["seconds_noise"] = 0.0
json.dump(doc, open(f"{d}/doctored.json", "w"))
EOF
  local rc=0
  python programs/perf_gate.py "$pdir/dbench.json" "$pdir/doctored.json" \
    > /dev/null || rc=$?
  if [ "$rc" -ne 3 ]; then
    echo "perf gate did not trip on a doctored baseline (rc=$rc)" >&2
    exit 1
  fi
  echo "perf gate ok (committed baseline green, doctored baseline trips)"
  rm -rf "$pdir"
}

run_ir() {
  echo "== IR (spfft_tpu.ir: suite + fused/staged parity smoke + fbench gate, CPU) =="
  # The IR suite: graph validation, fused-vs-staged parity fuzz across
  # {C2C,R2C} x {f32,f64} x {local,slab,pencil} x overlap {1,4}, the
  # single-dispatch proof, card provenance, and the ir.lower/ir.compile
  # degradation rungs — plus the batch-fused suite (batched-vs-looped
  # parity, the one-dispatch-per-batch proof, the ir.batch rung, the
  # tuner-owned batch axis).
  JAX_PLATFORMS=cpu timeout 900 python -m pytest tests/test_ir.py -q
  JAX_PLATFORMS=cpu timeout 900 python -m pytest tests/test_batch.py -q
  local idir
  idir="$(mktemp -d)"
  # Dispatch-path A/B (programs/fbench.py): the fused single program per
  # direction must beat the staged per-stage dispatch reference STRICTLY —
  # the whole point of the fusion pass (at small dims the staged path pays
  # ~10 dispatches + materialized intermediates per direction).
  JAX_PLATFORMS=cpu timeout 540 python programs/fbench.py --dim 24 \
    --radius 0.9 --pairs 8 --repeats 7 -o "$idir/fbench.json"
  JAX_PLATFORMS=cpu python - "$idir" <<'EOF'
import json, sys

d = sys.argv[1]
doc = json.load(open(f"{d}/fbench.json"))
rows = {r["key"].rsplit(":", 1)[-1]: r for r in doc["rows"]}
assert set(rows) == {"fused", "staged", "b1", "b4", "b8"}, sorted(rows)
assert rows["fused"]["ir"]["path"] == "fused", rows["fused"]["ir"]
assert rows["staged"]["ir"]["path"] == "staged", rows["staged"]["ir"]
assert rows["fused"]["ir"]["donation"]["backward"], "fused backward must donate"
for r in doc["rows"]:
    assert r["run_id"] and r["gflops"] > 0, r["key"]
ratio = doc["fused_over_staged"]
assert ratio > 1.0, f"fused not strictly above staged: x{ratio:.3f}"
# the batched row family: one stacked program dispatch per batch must beat
# per-transform dispatch STRICTLY on per-transform throughput (the whole
# point of the batch axis), with the provenance section live on the card
for b in ("b1", "b4", "b8"):
    assert rows[b]["batch_provenance"]["enabled"] is True, rows[b]
    assert not rows[b]["batch_provenance"]["failed"], rows[b]
b_ratio = (
    rows["b1"]["seconds_per_transform"] / rows["b4"]["seconds_per_transform"]
)
assert b_ratio > 1.0, f"batch=4 not strictly above batch=1: x{b_ratio:.3f}"
print(f"fbench ok (fused x{ratio:.2f} over staged, "
      f"batch4 x{b_ratio:.2f} over batch1)")
EOF
  # Regression gate: the committed baseline carries an fbench row family
  # (bench_results/perf_baseline_cpu8.json) — match on the fbench keys ...
  python programs/perf_gate.py "$idir/fbench.json" \
    bench_results/perf_baseline_cpu8.json --tolerance 0.85 \
    --require-matches 3 > /dev/null
  # ... a run gates green against itself ...
  python programs/perf_gate.py "$idir/fbench.json" "$idir/fbench.json" > /dev/null
  # ... and must trip (exit 3) against a doctored baseline claiming 10x.
  python - "$idir" <<'EOF'
import json, sys

d = sys.argv[1]
doc = json.load(open(f"{d}/fbench.json"))
for r in doc["rows"]:
    r["gflops"] *= 10
json.dump(doc, open(f"{d}/doctored.json", "w"))
EOF
  set +e
  python programs/perf_gate.py "$idir/fbench.json" "$idir/doctored.json" \
    > /dev/null 2>&1
  rc=$?
  set -e
  if [ "$rc" -ne 3 ]; then
    echo "ir gate FAILED to trip on doctored baseline (rc=$rc, want 3)" >&2
    exit 1
  fi
  rm -rf "$idir"
  echo "ir gate ok (doctored baseline trips with exit 3)"
}

run_dryrun() {
  echo "== Multichip dryrun (8-device CPU mesh, CPU forced) =="
  timeout 540 python -c "import __graft_entry__ as g; g.dryrun_multichip(8); print('dryrun ok')"
}

run_mhost() {
  echo "== MHost (multi-host serving: boot, RPC front, chaos-killed worker) =="
  # The suites: bootstrap/typed-validation/lockdep-propagation
  # (test_hostmesh) and RPC/heartbeat/host-lost ladder incl. the in-suite
  # SIGKILL scenario (test_cluster).
  timeout 540 python -m pytest tests/test_hostmesh.py tests/test_cluster.py -q
  # Cross-process collective parity (slab engines + overlapped rewrite over
  # real process boundaries); skips cleanly on jax runtimes whose CPU
  # backend lacks multi-process collectives (jax < 0.5).
  timeout 600 python -m pytest tests/test_multihost.py -q
  local mdir
  mdir="$(mktemp -d)"
  # Boot proof: a REAL jax.distributed multi-controller run — 2 worker
  # processes x 4 virtual CPU devices each, every rank must observe the
  # 8-device global mesh (typed up-front validation of the coordinates is
  # part of the same bootstrap).
  JAX_PLATFORMS=cpu timeout 540 python - <<'EOF'
from spfft_tpu import hostmesh

workers = hostmesh.spawn_workers(2, devices_per_host=4, mesh=True)
try:
    for w in workers:
        topo = w.ready["topology"]
        assert topo["process_count"] == 2, topo
        assert topo["global_devices"] == 8, topo
        assert topo["local_devices"] == 4, topo
finally:
    hostmesh.stop_workers(workers)
print("mhost boot ok: 2 processes x 4 devices, 8-device global mesh on every rank")
EOF
  # Chaos: host.heartbeat + rpc.submit armed at fractional rates AND a real
  # SIGKILLed worker mid-ramp. The acceptance invariant: the run completes
  # with zero untyped failures, offered == completed + refused + failed
  # EXACTLY, the lost host lands in hosts_lost_total and on cards, and the
  # surviving host keeps serving (completed_after_kill > 0).
  JAX_PLATFORMS=cpu \
    SPFFT_TPU_FAULTS="host.heartbeat=raise:0.05,rpc.submit=raise:0.05" \
    timeout 540 python programs/loadgen.py -d 12 12 12 -s 0.8 --tenants 2 \
    --rate 50 --ramp 1 --duration 3 --hosts 2 --host-devices 4 \
    --kill-host 0 --kill-at 0.35 -o "$mdir/chaos.json" > /dev/null
  JAX_PLATFORMS=cpu python - "$mdir" <<'EOF'
import json, sys

d = sys.argv[1]
doc = json.load(open(f"{d}/chaos.json"))
assert doc["config"]["hosts"] == 2 and doc["config"]["kill_host"] == 0
row = doc["rows"][0]
refused = row["rejected"] + row["shed"] + row["deadline_miss"]
# exact typed accounting through a SIGKILLed worker: nothing lost, nothing
# double-counted, nothing untyped (an untyped escape would have crashed the
# driver or left a pending ticket — both break this identity)
assert row["completed"] + refused + row["failed"] == row["offered"], row
# per-phase p50/p99 columns (the timeline layer) survive chaos rows too
assert row["phases"] and all(
    "p50_ms" in v and "p99_ms" in v for v in row["phases"].values()
), row["phases"]
assert row["completed_after_kill"] > 0, row
topo = {t["host_id"]: t["alive"] for t in doc["config"]["topology"]}
assert topo[0] is False and topo[1] is True, topo
hosts = {h["name"]: h["lost"] for h in doc["service"]["hosts"]}
assert hosts["host0"] is True and hosts["host1"] is False, hosts
counters = doc["metrics"]["counters"]
assert any(k.startswith("hosts_lost_total") for k in counters), counters
assert any(k.startswith("faults_injected_total") for k in counters), counters
cards = doc["service"]["plan_cards"]
assert any(
    dg["event"] == "host_lost" for c in cards for dg in c["degradations"]
), cards
front_degs = doc["service"]["degradations"]
assert any(dg["event"] == "host_lost" for dg in front_degs), front_degs
print(f"mhost chaos ok: {row['offered']} offered -> {row['completed']} "
      f"completed ({row['completed_after_kill']} after the kill), "
      f"{refused} refused, {row['failed']} typed failures, host0 lost")
EOF
  # Gate rows: a clean 2-host ramp, gate-compatible keys, regression-gated
  # against the committed baseline (wide tolerance — loadgen throughput on
  # a shared CI box is noisy; the gate catches algorithmic slides).
  JAX_PLATFORMS=cpu timeout 540 python programs/loadgen.py -d 12 12 12 \
    -s 0.8 --tenants 2 --rate 50 --ramp 1 2 --duration 2 --hosts 2 \
    --host-devices 4 -o "$mdir/mhost.json" > /dev/null
  python programs/perf_gate.py "$mdir/mhost.json" \
    bench_results/mhost_baseline_cpu.json --tolerance 0.85 \
    --require-matches 2 > /dev/null
  python programs/perf_gate.py "$mdir/mhost.json" "$mdir/mhost.json" \
    --require-matches 2 > /dev/null
  # Lockdep across processes: workers spawned with SPFFT_TPU_LOCKDEP=1
  # (env propagation) write per-host reports on clean shutdown; the front
  # process writes its own; the merged fleet graph must cross-check clean
  # against the SA011 static model. The same session proves the
  # observability plane on REAL process boundaries: tracing is armed on
  # front and workers (env propagation again), every submitted request's
  # run ID must join local and host-tagged spliced events in ONE front-side
  # snapshot, and a live fleetstat scrape must validate.
  JAX_PLATFORMS=cpu SPFFT_TPU_LOCKDEP=1 SPFFT_TPU_TRACE=1 \
    SPFFT_TPU_LOCKDEP_REPORT="$mdir/front.json" \
    timeout 540 python - "$mdir" <<'EOF'
import subprocess
import sys
import numpy as np
import spfft_tpu as sp
from spfft_tpu import TransformType, hostmesh
from spfft_tpu.serve.cluster import ClusterFront

mdir = sys.argv[1]
workers = hostmesh.spawn_workers(2, devices_per_host=1, lockdep_dir=mdir)
# batch_max=1: six same-geometry requests must NOT coalesce into one
# chunk, so dispatches spread over both hosts and the join proof below
# sees spliced spans from both worker processes
front = ClusterFront(
    [w.address for w in workers], heartbeat_s=0.1, batch_max=1
)
trip = sp.create_spherical_cutoff_triplets(8, 8, 8, 0.8)
rng = np.random.default_rng(0)
vals = rng.standard_normal(len(trip)) + 1j * rng.standard_normal(len(trip))
try:
    tks = [front.submit(TransformType.C2C, (8, 8, 8), trip, vals * (1 + i))
           for i in range(6)]
    for tk in tks:
        tk.result(timeout=120)
    # Cross-host trace join: for every request, one front-side snapshot
    # must hold BOTH sides of the dispatch under the request's run ID —
    # the front's own events (no host tag) and the worker's spliced span
    # (host-tagged) — and across the batch both worker processes appear.
    evs = sp.obs.trace.snapshot()["events"]
    spliced_hosts = set()
    for tk in tks:
        mine = [e for e in evs if e["run"] == tk.run]
        assert [e for e in mine if "host" not in e["args"]], tk.run
        remote = {e["args"]["host"] for e in mine if "host" in e["args"]}
        assert remote, (tk.run, mine)
        spliced_hosts |= remote
    assert spliced_hosts == {"host0", "host1"}, spliced_hosts
    # end-to-end timeline: a remote-served ticket reached the wire phases
    tl = [p["phase"] for p in tks[0].timeline()]
    for phase in ("admitted", "dispatched", "wire", "remote_execute",
                  "finalized"):
        assert phase in tl, (phase, tl)
    # fleet scrape while both workers are live: describe() join validates,
    # and the operator CLI writes a document for the shell-side checks
    doc = front.fleet_metrics()
    assert not sp.obs.fleet.validate_fleet(doc), doc["hosts"]
    states = {h: e["state"] for h, e in doc["hosts"].items()}
    assert states == {"host0": "live", "host1": "live"}, states
    cmd = [sys.executable, "programs/fleetstat.py",
           "-o", f"{mdir}/fleet.json"]
    for i, w in enumerate(workers):
        cmd += ["--host", f"host{i}={w.address}"]
    subprocess.run(cmd, check=True, stdout=subprocess.DEVNULL)
finally:
    front.close()
    hostmesh.stop_workers(workers)
print("lockdep-armed mhost session ok (run-ID join across both processes)")
EOF
  python programs/analyze.py --lockdep-check \
    "$mdir/host0.json" "$mdir/host1.json" "$mdir/front.json"
  # Fleet doc discipline: the live scrape re-validates clean, and a
  # doctored document trips the validator with exit 3 (distinct from
  # "tool broken" — the perf_gate.py discipline).
  python programs/fleetstat.py --check "$mdir/fleet.json" 2> /dev/null
  python - "$mdir" <<'EOF'
import json, sys

d = sys.argv[1]
doc = json.load(open(f"{d}/fleet.json"))
doc["schema"] = "spfft_tpu.obs.fleet/999"
del doc["totals"]
json.dump(doc, open(f"{d}/doctored.json", "w"))
EOF
  set +e
  python programs/fleetstat.py --check "$mdir/doctored.json" \
    > /dev/null 2>&1
  rc=$?
  set -e
  if [ "$rc" -ne 3 ]; then
    echo "doctored fleet doc FAILED to trip the validator (rc=$rc, want 3)" >&2
    exit 1
  fi
  echo "fleet doc ok (doctored document trips with exit 3)"
  rm -rf "$mdir"
  echo "mhost stage ok"
}

run_native() {
  echo "== Native build + API tests =="
  # C API parity: zero reference-only names (exits nonzero on any hole).
  python programs/api_surface.py
  cmake -S native -B native/build-ci -DCMAKE_BUILD_TYPE=Release >/dev/null
  cmake --build native/build-ci --parallel >/dev/null
  # HOST-only embedded-interpreter roundtrip: must pass with no accelerator.
  # The embedded CPython resolves spfft_tpu via PYTHONPATH (same contract as
  # tests/test_native_api.py; an installed wheel serves the same role).
  SPFFT_TPU_NUM_CPU_DEVICES=4 JAX_PLATFORMS=cpu \
    PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    timeout 600 ./native/build-ci/run_native_tests
}

case "$stage" in
  lint) run_lint ;;
  analyze) run_analyze ;;
  python) run_python ;;
  report) run_report ;;
  tune) run_tune ;;
  trace) run_trace ;;
  chaos) run_chaos ;;
  verify) run_verify ;;
  serve) run_serve ;;
  sched) run_sched ;;
  perf) run_perf ;;
  ir) run_ir ;;
  mhost) run_mhost ;;
  dryrun) run_dryrun ;;
  native) run_native ;;
  all)
    run_lint
    run_analyze
    run_python
    run_report
    run_tune
    run_trace
    run_chaos
    run_verify
    run_serve
    run_sched
    run_perf
    run_ir
    run_mhost
    run_dryrun
    run_native
    echo "== CI green =="
    ;;
  *)
    echo "unknown stage: $stage (use lint | analyze | python | report | tune | trace | chaos | verify | serve | sched | perf | ir | mhost | dryrun | native | all)" >&2
    exit 2
    ;;
esac
