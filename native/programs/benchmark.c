/*
 * Native benchmark CLI — the C-linkage rebuild of the reference's benchmark
 * program (reference: tests/programs/benchmark.cpp), driving the installed
 * library surface exactly like a SIRIUS-style consumer would.
 *
 * Same flag surface as the reference and as programs/benchmark.py:
 *   -d X Y Z       grid dimensions (required)
 *   -r repeats     timed backward+forward repeats (required)
 *   -o out.json    JSON report path (optional; report always prints to stdout)
 *   -s sparsity    x-slab sparsity in [0, 1] (default 1.0)
 *   -t c2c|r2c     transform type (default c2c)
 *   -e buffered|bufferedFloat|compact|compactFloat|unbuffered
 *                  exchange discipline for --shards > 1 (default compact)
 *   -p cpu|gpu|gpu-gpu  processing unit (default cpu; gpu-gpu = gpu)
 *   -m N           independent transforms run batched per repeat (default 1)
 *   --shards N     distributed mesh size (default 1 = local transform)
 *
 * Stick-generation model (reference: benchmark.cpp:177-205): all (x, y) with
 * x < ceil(dimXFreq * sparsity); for R2C the x == 0 sticks cover only the
 * hermitian non-redundant y half; contiguous even stick split over shards.
 *
 * Timing: wall-clock (CLOCK_MONOTONIC) around the timed loop, after one
 * untimed warm-up pair per transform (compile + constant upload, reference:
 * benchmark.cpp:63-70). With FULL scaling every backward+forward pair is an
 * identity, so each repeat feeds the previous repeat's output back in — the
 * chain is dependent and cannot be elided. NOTE: each C call is one
 * host-facing dispatch; through a tunneled development TPU that carries a
 * fixed ~110 ms/call cost that a directly-attached device does not pay
 * (BASELINE.md "environment floor"). The Python harness's in-program
 * lax.scan chain (programs/benchmark.py) is the sustained-throughput
 * measurement; this program measures the host-facing call path, which is
 * what the reference's benchmark also measures.
 */
#define _POSIX_C_SOURCE 200112L /* clock_gettime, CLOCK_MONOTONIC, setenv */

#include <limits.h>
#include <math.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <time.h>

#include <spfft/spfft.h>

#define MAX_TRANSFORMS 16

#define CHECK(expr)                                                                  \
  do {                                                                               \
    SpfftError e_ = (expr);                                                          \
    if (e_ != SPFFT_SUCCESS) {                                                       \
      fprintf(stderr, "benchmark: %s:%d: %s -> error %d\n", __FILE__, __LINE__,      \
              #expr, (int)e_);                                                       \
      return 1;                                                                      \
    }                                                                                \
  } while (0)

static double now_s(void) {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (double)ts.tv_sec + 1e-9 * (double)ts.tv_nsec;
}

static unsigned int rng_state = 42u;
static double rng_uniform(void) {
  rng_state = rng_state * 1664525u + 1013904223u;
  return (double)(rng_state >> 8) / (double)(1u << 24) - 0.5;
}

typedef struct {
  int dims[3];
  int repeats;
  const char* out_path;
  double sparsity;
  int r2c;
  const char* exchange;
  const char* pu;
  int num_transforms;
  int shards;
} Options;

static int exchange_enum(const char* name, SpfftExchangeType* out) {
  if (strcmp(name, "buffered") == 0) *out = SPFFT_EXCH_BUFFERED;
  else if (strcmp(name, "bufferedFloat") == 0) *out = SPFFT_EXCH_BUFFERED_FLOAT;
  else if (strcmp(name, "compact") == 0) *out = SPFFT_EXCH_COMPACT_BUFFERED;
  else if (strcmp(name, "compactFloat") == 0) *out = SPFFT_EXCH_COMPACT_BUFFERED_FLOAT;
  else if (strcmp(name, "unbuffered") == 0) *out = SPFFT_EXCH_UNBUFFERED;
  else return 0;
  return 1;
}

static int parse_args(int argc, char** argv, Options* o) {
  int i;
  o->repeats = 0;
  o->dims[0] = 0;
  o->out_path = NULL;
  o->sparsity = 1.0;
  o->r2c = 0;
  o->exchange = "compact";
  o->pu = "cpu";
  o->num_transforms = 1;
  o->shards = 1;
  for (i = 1; i < argc; ++i) {
    if (strcmp(argv[i], "-d") == 0 && i + 3 < argc) {
      o->dims[0] = atoi(argv[++i]);
      o->dims[1] = atoi(argv[++i]);
      o->dims[2] = atoi(argv[++i]);
    } else if (strcmp(argv[i], "-r") == 0 && i + 1 < argc) {
      o->repeats = atoi(argv[++i]);
    } else if (strcmp(argv[i], "-o") == 0 && i + 1 < argc) {
      o->out_path = argv[++i];
    } else if (strcmp(argv[i], "-s") == 0 && i + 1 < argc) {
      o->sparsity = atof(argv[++i]);
    } else if (strcmp(argv[i], "-t") == 0 && i + 1 < argc) {
      /* a misspelled value must fail fast, not silently benchmark C2C */
      ++i;
      if (strcmp(argv[i], "r2c") != 0 && strcmp(argv[i], "c2c") != 0) {
        fprintf(stderr, "benchmark: -t must be c2c or r2c (got '%s')\n", argv[i]);
        return 0;
      }
      o->r2c = strcmp(argv[i], "r2c") == 0;
    } else if (strcmp(argv[i], "-e") == 0 && i + 1 < argc) {
      SpfftExchangeType dummy;
      o->exchange = argv[++i];
      if (!exchange_enum(o->exchange, &dummy)) {
        fprintf(stderr, "benchmark: unknown exchange '%s'\n", o->exchange);
        return 0;
      }
    } else if (strcmp(argv[i], "-p") == 0 && i + 1 < argc) {
      ++i;
      /* "gpu-gpu" (reference spelling for device-resident I/O) maps to the
       * accelerator unit — array residency is runtime-managed here */
      if (strcmp(argv[i], "cpu") != 0 && strcmp(argv[i], "gpu") != 0 &&
          strcmp(argv[i], "gpu-gpu") != 0) {
        fprintf(stderr, "benchmark: -p must be cpu, gpu or gpu-gpu (got '%s')\n",
                argv[i]);
        return 0;
      }
      o->pu = argv[i];
    } else if (strcmp(argv[i], "-m") == 0 && i + 1 < argc) {
      o->num_transforms = atoi(argv[++i]);
    } else if (strcmp(argv[i], "--shards") == 0 && i + 1 < argc) {
      o->shards = atoi(argv[++i]);
    } else {
      fprintf(stderr, "benchmark: unknown/incomplete argument '%s'\n", argv[i]);
      return 0;
    }
  }
  if (o->dims[0] <= 0 || o->repeats <= 0) {
    fprintf(stderr,
            "usage: benchmark -d X Y Z -r repeats [-o out.json] [-s sparsity]\n"
            "                 [-t c2c|r2c] [-e exchange] [-p cpu|gpu|gpu-gpu] [-m N]\n"
            "                 [--shards N]\n");
    return 0;
  }
  if (o->num_transforms < 1 || o->num_transforms > MAX_TRANSFORMS) {
    fprintf(stderr, "benchmark: -m must be in [1, %d]\n", MAX_TRANSFORMS);
    return 0;
  }
  if (o->shards > 1 && o->num_transforms != 1) {
    fprintf(stderr, "benchmark: --shards and -m are mutually exclusive\n");
    return 0;
  }
  if (o->shards < 1 || o->shards > 1024) {
    fprintf(stderr, "benchmark: --shards must be in [1, 1024]\n");
    return 0;
  }
  return 1;
}

/* Reference stick model: returns malloc'd triplets + stick count.
 * Counting is done in 64 bits (1024^3-class dense plans exceed INT_MAX/3
 * elements, so int products overflow before any cast); the C API itself
 * takes int value counts, so the caller guards num_values <= INT_MAX. */
static int* make_triplets(const Options* o, int* num_sticks, long long* num_values) {
  const int dim_x_freq = o->r2c ? o->dims[0] / 2 + 1 : o->dims[0];
  const int dim_y_freq = o->r2c ? o->dims[1] / 2 + 1 : o->dims[1];
  int num_x = (int)ceil(dim_x_freq * o->sparsity);
  int x, y, z, sticks = 0;
  size_t k = 0;
  int* trips;
  if (num_x < 1) num_x = 1;
  for (x = 0; x < num_x; ++x) sticks += (o->r2c && x == 0) ? dim_y_freq : o->dims[1];
  trips = (int*)malloc((size_t)3 * (size_t)sticks * (size_t)o->dims[2] * sizeof(int));
  if (!trips) return NULL;
  for (x = 0; x < num_x; ++x) {
    const int ny = (o->r2c && x == 0) ? dim_y_freq : o->dims[1];
    for (y = 0; y < ny; ++y)
      for (z = 0; z < o->dims[2]; ++z) {
        trips[k++] = x;
        trips[k++] = y;
        trips[k++] = z;
      }
  }
  *num_sticks = sticks;
  *num_values = (long long)sticks * o->dims[2];
  return trips;
}

int main(int argc, char** argv) {
  Options o;
  int num_sticks = 0, m, rep;
  long long n = 0, i;
  int* trips;
  SpfftProcessingUnitType pu;
  double* freq[MAX_TRANSFORMS];
  double t_backward = 0.0, t_forward = 0.0, t0, t_total;
  double pair_ms, gflops, flops;
  FILE* out;

  if (!parse_args(argc, argv, &o)) return 2;
  pu = strncmp(o.pu, "gpu", 3) == 0 ? SPFFT_PU_GPU : SPFFT_PU_HOST;
  if (o.shards > 1 && pu == SPFFT_PU_HOST) {
    /* An N-device virtual CPU mesh must exist before the first API call
     * initializes the embedded runtime (no overwrite if the caller set it). */
    char nbuf[16];
    snprintf(nbuf, sizeof(nbuf), "%d", o.shards);
    setenv("SPFFT_TPU_NUM_CPU_DEVICES", nbuf, 0);
  }
  trips = make_triplets(&o, &num_sticks, &n);
  if (!trips) return 1;
  if (n > INT_MAX) {
    fprintf(stderr, "benchmark: %lld values exceed the int-based C API limit\n", n);
    return 1;
  }

  for (m = 0; m < o.num_transforms; ++m) {
    freq[m] = (double*)malloc((size_t)2 * (size_t)n * sizeof(double));
    if (!freq[m]) {
      fprintf(stderr, "benchmark: out of memory (%lld values)\n", n);
      return 1;
    }
    for (i = 0; i < 2 * n; ++i) freq[m][i] = rng_uniform();
  }

  if (o.shards > 1) {
    /* Distributed path: contiguous even stick split (reference:
     * benchmark.cpp:190-205); shard-major triplets are already contiguous. */
    SpfftGrid grid = NULL;
    SpfftDistTransform t = NULL;
    int counts[1024];
    /* the space domain is the FULL dense grid, not the sparse value count */
    const size_t nspace = (size_t)2 * o.dims[0] * o.dims[1] * o.dims[2];
    double* space = (double*)malloc(nspace * sizeof(double));
    long long wire = 0;
    int rounds = 0, r;
    if (!space) {
      fprintf(stderr, "benchmark: out of memory (%zu space doubles)\n", nspace);
      return 1;
    }
    for (r = 0; r < o.shards; ++r) {
      int s = num_sticks / o.shards + (r < num_sticks % o.shards ? 1 : 0);
      counts[r] = s * o.dims[2];
    }
    SpfftExchangeType exch = SPFFT_EXCH_DEFAULT;
    exchange_enum(o.exchange, &exch); /* validated at parse time */
    CHECK(spfft_grid_create_distributed(&grid, o.dims[0], o.dims[1], o.dims[2],
                                        num_sticks, o.dims[2], o.shards, exch, pu,
                                        1));
    CHECK(spfft_dist_transform_create(&t, grid, pu,
                                      o.r2c ? SPFFT_TRANS_R2C : SPFFT_TRANS_C2C,
                                      o.dims[0], o.dims[1], o.dims[2], o.shards,
                                      counts, SPFFT_INDEX_TRIPLETS, trips, 1));
    CHECK(spfft_dist_transform_exchange_wire_bytes(t, &wire));
    CHECK(spfft_dist_transform_exchange_rounds(t, &rounds));

    /* warm-up (compile); the identity chain lets freq double as the output */
    CHECK(spfft_dist_transform_backward(t, freq[0], space));
    CHECK(spfft_dist_transform_forward(t, space, freq[0], SPFFT_FULL_SCALING));

    t0 = now_s();
    for (rep = 0; rep < o.repeats; ++rep) {
      double t1 = now_s();
      CHECK(spfft_dist_transform_backward(t, freq[0], space));
      t_backward += now_s() - t1;
      t1 = now_s();
      CHECK(spfft_dist_transform_forward(t, space, freq[0], SPFFT_FULL_SCALING));
      t_forward += now_s() - t1;
    }
    t_total = now_s() - t0;
    CHECK(spfft_dist_transform_destroy(t));
    CHECK(spfft_grid_destroy(grid));
    free(space);
    printf("exchange %s: wire_bytes=%lld rounds=%d\n", o.exchange, wire, rounds);
  } else {
    SpfftTransform ts[MAX_TRANSFORMS];
    const double* inputs[MAX_TRANSFORMS];
    double* outputs[MAX_TRANSFORMS];
    SpfftProcessingUnitType locs[MAX_TRANSFORMS];
    SpfftScalingType scals[MAX_TRANSFORMS];
    for (m = 0; m < o.num_transforms; ++m) {
      ts[m] = NULL;
      CHECK(spfft_transform_create_independent(
          &ts[m], 1, pu, o.r2c ? SPFFT_TRANS_R2C : SPFFT_TRANS_C2C, o.dims[0],
          o.dims[1], o.dims[2], (int)n, SPFFT_INDEX_TRIPLETS, trips));
      inputs[m] = freq[m];
      outputs[m] = freq[m]; /* identity chain: forward writes next input */
      locs[m] = pu;
      scals[m] = SPFFT_FULL_SCALING;
    }

    /* warm-up (compile) */
    CHECK(spfft_multi_transform_backward(o.num_transforms, ts, inputs, locs));
    CHECK(spfft_multi_transform_forward(o.num_transforms, ts, locs, outputs, scals));

    t0 = now_s();
    for (rep = 0; rep < o.repeats; ++rep) {
      double t1 = now_s();
      CHECK(spfft_multi_transform_backward(o.num_transforms, ts, inputs, locs));
      t_backward += now_s() - t1;
      t1 = now_s();
      CHECK(spfft_multi_transform_forward(o.num_transforms, ts, locs, outputs, scals));
      t_forward += now_s() - t1;
    }
    t_total = now_s() - t0;
    for (m = 0; m < o.num_transforms; ++m) CHECK(spfft_transform_destroy(ts[m]));
  }

  /* identity-chain sanity: repeated FULL-scaled pairs must stay bounded */
  {
    double max_abs = 0.0;
    for (i = 0; i < 2 * n && i < 4096; ++i) {
      double a = fabs(freq[0][i]);
      if (a > max_abs) max_abs = a;
    }
    if (!(max_abs < 10.0)) {
      fprintf(stderr, "benchmark: identity chain diverged (max %g)\n", max_abs);
      return 1;
    }
  }

  pair_ms = 1e3 * t_total / (o.repeats * o.num_transforms);
  flops = 2.0 * 5.0 * (double)o.dims[0] * o.dims[1] * o.dims[2] *
          log2((double)o.dims[0] * o.dims[1] * o.dims[2]);
  gflops = flops / (1e6 * pair_ms);

  out = o.out_path ? fopen(o.out_path, "w") : NULL;
  {
    char buf[1024];
    snprintf(buf, sizeof(buf),
             "{\n"
             "  \"parameters\": {\"dims\": [%d, %d, %d], \"sparsity\": %g,"
             " \"type\": \"%s\", \"processing_unit\": \"%s\","
             " \"num_transforms\": %d, \"shards\": %d, \"exchange\": \"%s\","
             " \"num_sticks\": %d, \"num_values\": %lld, \"repeats\": %d},\n"
             "  \"results\": {\"ms_per_pair\": %.3f, \"gflops\": %.1f,"
             " \"backward_ms\": %.3f, \"forward_ms\": %.3f},\n"
             "  \"harness\": \"native-c\"\n"
             "}\n",
             o.dims[0], o.dims[1], o.dims[2], o.sparsity, o.r2c ? "r2c" : "c2c",
             o.pu, o.num_transforms, o.shards, o.shards > 1 ? o.exchange : "none",
             num_sticks, n, o.repeats, pair_ms, gflops,
             1e3 * t_backward / (o.repeats * o.num_transforms),
             1e3 * t_forward / (o.repeats * o.num_transforms));
    fputs(buf, stdout);
    if (out) {
      fputs(buf, out);
      fclose(out);
    }
  }

  for (m = 0; m < o.num_transforms; ++m) free(freq[m]);
  free(trips);
  return 0;
}
