#include "bridge.hpp"

#include <spfft/exceptions.hpp>

#include <cstdlib>
#include <mutex>
#include <string>

namespace spfft {
namespace bridge {

namespace {

void initialize_interpreter_once() {
  static std::once_flag once;
  std::call_once(once, [] {
    if (Py_IsInitialized()) {
      return; /* loaded into a live Python process — reuse its interpreter */
    }
    /* The double-precision API needs 64-bit element types in the compute
     * core; set the knob before the runtime first loads (no overwrite, so a
     * caller-provided environment wins). */
    setenv("JAX_ENABLE_X64", "1", 0);
    Py_InitializeEx(0);
    /* Drop the GIL acquired by initialization so any thread can take it
     * through PyGILState_Ensure later. */
    PyEval_SaveThread();
  });
}

} // namespace

Gil::Gil() {
  initialize_interpreter_once();
  state_ = PyGILState_Ensure();
}

Gil::~Gil() { PyGILState_Release(state_); }

PyObject* capi() {
  /* Per-process module cache. Import errors surface as HostExecutionError —
   * the runtime environment is unusable. */
  static PyObject* module = nullptr;
  if (module == nullptr) {
    module = PyImport_ImportModule("spfft_tpu.capi");
    if (module == nullptr) {
      PyObject *type = nullptr, *value = nullptr, *trace = nullptr;
      PyErr_Fetch(&type, &value, &trace);
      std::string msg = "spfft_tpu: cannot import runtime bridge";
      if (value != nullptr) {
        PyObject* s = PyObject_Str(value);
        if (s != nullptr) {
          const char* text = PyUnicode_AsUTF8(s);
          if (text != nullptr) {
            msg += ": ";
            msg += text;
          }
          Py_DECREF(s);
        }
      }
      Py_XDECREF(type);
      Py_XDECREF(value);
      Py_XDECREF(trace);
      throw HostExecutionError(msg);
    }
  }
  return module;
}

void throw_pending_error() {
  PyObject *type = nullptr, *value = nullptr, *trace = nullptr;
  PyErr_Fetch(&type, &value, &trace);
  PyErr_NormalizeException(&type, &value, &trace);
  Ref type_ref(type), value_ref(value), trace_ref(trace);

  std::string msg = "spfft_tpu: unknown error";
  long code = SPFFT_UNKNOWN_ERROR;
  if (value_ref) {
    PyObject* s = PyObject_Str(value_ref.get());
    if (s != nullptr) {
      const char* text = PyUnicode_AsUTF8(s);
      if (text != nullptr) msg = text;
      Py_DECREF(s);
    }
    /* Let the Python side classify its own exception. */
    PyObject* code_obj =
        PyObject_CallMethod(capi(), "error_code", "O", value_ref.get());
    if (code_obj != nullptr) {
      code = PyLong_AsLong(code_obj);
      Py_DECREF(code_obj);
    } else {
      PyErr_Clear();
    }
  }

  switch (code) {
  case SPFFT_OVERFLOW_ERROR: throw OverflowError(msg);
  case SPFFT_ALLOCATION_ERROR: throw HostAllocationError(msg);
  case SPFFT_INVALID_PARAMETER_ERROR: throw InvalidParameterError(msg);
  case SPFFT_DUPLICATE_INDICES_ERROR: throw DuplicateIndicesError(msg);
  case SPFFT_INVALID_INDICES_ERROR: throw InvalidIndicesError(msg);
  case SPFFT_MPI_SUPPORT_ERROR: throw MPISupportError(msg);
  case SPFFT_MPI_ERROR: throw MPIError(msg);
  case SPFFT_MPI_PARAMETER_MISMATCH_ERROR: throw MPIParameterMismatchError(msg);
  case SPFFT_HOST_EXECUTION_ERROR: throw HostExecutionError(msg);
  case SPFFT_FFTW_ERROR: throw FFTWError(msg);
  case SPFFT_GPU_ERROR: throw GPUError(msg);
  case SPFFT_GPU_PRECEDING_ERROR: throw GPUPrecedingError(msg);
  case SPFFT_GPU_SUPPORT_ERROR: throw GPUSupportError(msg);
  case SPFFT_GPU_ALLOCATION_ERROR: throw GPUAllocationError(msg);
  case SPFFT_GPU_LAUNCH_ERROR: throw GPULaunchError(msg);
  case SPFFT_GPU_NO_DEVICE_ERROR: throw GPUNoDeviceError(msg);
  case SPFFT_GPU_INVALID_VALUE_ERROR: throw GPUInvalidValueError(msg);
  case SPFFT_GPU_INVALID_DEVICE_PTR_ERROR: throw GPUInvalidDevicePointerError(msg);
  case SPFFT_GPU_COPY_ERROR: throw GPUCopyError(msg);
  case SPFFT_GPU_FFT_ERROR: throw GPUFFTError(msg);
  default: throw GenericError(msg);
  }
}

PyObject* checked(PyObject* obj) {
  if (obj == nullptr) {
    throw_pending_error();
  }
  return obj;
}

Ref view_ro(const void* data, std::size_t bytes) {
  return Ref(checked(PyMemoryView_FromMemory(
      const_cast<char*>(static_cast<const char*>(data)),
      static_cast<Py_ssize_t>(bytes), PyBUF_READ)));
}

Ref view_rw(void* data, std::size_t bytes) {
  return Ref(checked(PyMemoryView_FromMemory(
      static_cast<char*>(data), static_cast<Py_ssize_t>(bytes), PyBUF_WRITE)));
}

Ref call(const char* fn, PyObject* args_tuple) {
  Ref args(checked(args_tuple));
  PyObject* callable = checked(PyObject_GetAttrString(capi(), fn));
  Ref callable_ref(callable);
  return Ref(checked(PyObject_CallObject(callable, args.get())));
}

long long as_longlong(PyObject* obj) {
  long long v = PyLong_AsLongLong(obj);
  if (v == -1 && PyErr_Occurred()) {
    throw_pending_error();
  }
  return v;
}

} // namespace bridge
} // namespace spfft
