/*
 * Embedded-runtime bridge for the spfft_tpu native API.
 *
 * The native library owns the process-side runtime: handle lifetimes, host
 * buffers and error translation live in C++, while the XLA compute core is
 * driven through an embedded CPython interpreter running the spfft_tpu.capi
 * marshalling module. This plays the role the reference's direct FFTW/cuFFT
 * calls play (reference: src/fft/fftw_interface.hpp, src/gpu_util/) — the
 * boundary to the vendor compute runtime, here PJRT-via-JAX.
 *
 * Threading: the interpreter is initialized once on first use; every entry
 * point acquires the GIL through bridge::Gil. When the library is loaded into
 * an existing Python process (e.g. via ctypes for testing) the running
 * interpreter is reused.
 */
#ifndef SPFFT_TPU_BRIDGE_HPP
#define SPFFT_TPU_BRIDGE_HPP

#include <Python.h>

#include <cstddef>

namespace spfft {
namespace bridge {

/* Initialize the interpreter (idempotent) and acquire the GIL for the
 * lifetime of this object. */
class Gil {
public:
  Gil();
  ~Gil();
  Gil(const Gil&) = delete;
  Gil& operator=(const Gil&) = delete;

private:
  PyGILState_STATE state_;
};

/* Owning PyObject reference. Copy/destroy acquire the GIL themselves, so a
 * Ref may live in objects destroyed from arbitrary (non-Python) threads —
 * e.g. a Transform deleted through the C API with no Gil in scope. */
class Ref {
public:
  Ref() = default;
  explicit Ref(PyObject* obj) : obj_(obj) {} /* steals */
  Ref(const Ref& other) : obj_(other.obj_) {
    if (obj_ != nullptr) {
      PyGILState_STATE s = PyGILState_Ensure();
      Py_INCREF(obj_);
      PyGILState_Release(s);
    }
  }
  Ref(Ref&& other) noexcept : obj_(other.obj_) { other.obj_ = nullptr; }
  Ref& operator=(Ref other) noexcept {
    PyObject* tmp = obj_;
    obj_ = other.obj_;
    other.obj_ = tmp;
    return *this;
  }
  ~Ref() {
    if (obj_ != nullptr && Py_IsInitialized()) {
      PyGILState_STATE s = PyGILState_Ensure();
      Py_DECREF(obj_);
      PyGILState_Release(s);
    }
  }

  PyObject* get() const { return obj_; }
  PyObject* release() {
    PyObject* o = obj_;
    obj_ = nullptr;
    return o;
  }
  explicit operator bool() const { return obj_ != nullptr; }

private:
  PyObject* obj_ = nullptr;
};

/* The spfft_tpu.capi module (borrowed reference; GIL must be held). */
PyObject* capi();

/* Translate the pending Python exception into the matching C++ exception
 * from spfft/exceptions.hpp and throw it. */
[[noreturn]] void throw_pending_error();

/* Checked result: throws if `obj` is null (a Python error is pending). */
PyObject* checked(PyObject* obj);

/* Read-only / writable memoryviews over caller memory (no copy). */
Ref view_ro(const void* data, std::size_t bytes);
Ref view_rw(void* data, std::size_t bytes);

/* Call capi.<fn> returning an owned result; throws on Python error. */
Ref call(const char* fn, PyObject* args_tuple /* stolen */);

/* int/long helpers. */
long long as_longlong(PyObject* obj);

} // namespace bridge
} // namespace spfft

#endif // SPFFT_TPU_BRIDGE_HPP
