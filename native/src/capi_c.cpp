/*
 * spfft_tpu native API — extern-C handle functions.
 *
 * Same discipline as the reference C API (reference: src/spfft/transform.cpp:178+,
 * grid.cpp): handles are heap-allocated C++ objects behind void*, every entry
 * point is try/catch translating GenericError -> error_code and anything else
 * -> SPFFT_UNKNOWN_ERROR.
 */
#include <spfft/spfft.h>
#include <spfft/spfft.hpp>

#include <new>
#include <vector>

namespace {

template <typename Fn> SpfftError guarded(Fn&& fn) {
  try {
    fn();
  } catch (const spfft::GenericError& e) {
    return e.error_code();
  } catch (...) {
    return SPFFT_UNKNOWN_ERROR;
  }
  return SPFFT_SUCCESS;
}

spfft::Grid* as_grid(SpfftGrid h) { return static_cast<spfft::Grid*>(h); }
spfft::Transform* as_transform(SpfftTransform h) {
  return static_cast<spfft::Transform*>(h);
}
spfft::TransformFloat* as_float_transform(SpfftFloatTransform h) {
  return static_cast<spfft::TransformFloat*>(h);
}
spfft::DistributedTransform* as_dist_transform(SpfftDistTransform h) {
  return static_cast<spfft::DistributedTransform*>(h);
}

} // namespace

extern "C" {

/* ---- grid ----------------------------------------------------------------- */

SpfftError spfft_grid_create(SpfftGrid* grid, int maxDimX, int maxDimY, int maxDimZ,
                             int maxNumLocalZColumns,
                             SpfftProcessingUnitType processingUnit, int maxNumThreads) {
  if (grid == nullptr) return SPFFT_INVALID_HANDLE_ERROR;
  return guarded([&] {
    *grid = new spfft::Grid(maxDimX, maxDimY, maxDimZ, maxNumLocalZColumns,
                            processingUnit, maxNumThreads);
  });
}

SpfftError spfft_float_grid_create(SpfftFloatGrid* grid, int maxDimX, int maxDimY,
                                   int maxDimZ, int maxNumLocalZColumns,
                                   SpfftProcessingUnitType processingUnit,
                                   int maxNumThreads) {
  return spfft_grid_create(grid, maxDimX, maxDimY, maxDimZ, maxNumLocalZColumns,
                           processingUnit, maxNumThreads);
}

SpfftError spfft_grid_create_distributed(SpfftGrid* grid, int maxDimX, int maxDimY,
                                         int maxDimZ, int maxNumLocalZColumns,
                                         int maxLocalZLength, int numShards,
                                         SpfftExchangeType exchangeType,
                                         SpfftProcessingUnitType processingUnit,
                                         int maxNumThreads) {
  if (grid == nullptr) return SPFFT_INVALID_HANDLE_ERROR;
  return guarded([&] {
    *grid = new spfft::Grid(maxDimX, maxDimY, maxDimZ, maxNumLocalZColumns,
                            maxLocalZLength, numShards, exchangeType, processingUnit,
                            maxNumThreads);
  });
}

SpfftError spfft_grid_create_distributed2(SpfftGrid* grid, int maxDimX, int maxDimY,
                                          int maxDimZ, int maxNumLocalZColumns,
                                          int maxLocalZLength, int p1, int p2,
                                          SpfftExchangeType exchangeType,
                                          SpfftProcessingUnitType processingUnit,
                                          int maxNumThreads) {
  if (grid == nullptr) return SPFFT_INVALID_HANDLE_ERROR;
  return guarded([&] {
    *grid = new spfft::Grid(maxDimX, maxDimY, maxDimZ, maxNumLocalZColumns,
                            maxLocalZLength, p1, p2, exchangeType, processingUnit,
                            maxNumThreads);
  });
}

SpfftError spfft_grid_destroy(SpfftGrid grid) {
  if (grid == nullptr) return SPFFT_INVALID_HANDLE_ERROR;
  return guarded([&] { delete as_grid(grid); });
}

#define SPFFT_TPU_GRID_GETTER(FN, OUT_T, METHOD)                                         \
  SpfftError FN(SpfftGrid grid, OUT_T* out) {                                            \
    if (grid == nullptr || out == nullptr) return SPFFT_INVALID_HANDLE_ERROR;            \
    return guarded([&] { *out = as_grid(grid)->METHOD(); });                             \
  }

SPFFT_TPU_GRID_GETTER(spfft_grid_max_dim_x, int, max_dim_x)
SPFFT_TPU_GRID_GETTER(spfft_grid_max_dim_y, int, max_dim_y)
SPFFT_TPU_GRID_GETTER(spfft_grid_max_dim_z, int, max_dim_z)
SPFFT_TPU_GRID_GETTER(spfft_grid_max_num_local_z_columns, int, max_num_local_z_columns)
SPFFT_TPU_GRID_GETTER(spfft_grid_max_local_z_length, int, max_local_z_length)
SPFFT_TPU_GRID_GETTER(spfft_grid_processing_unit, SpfftProcessingUnitType,
                      processing_unit)
SPFFT_TPU_GRID_GETTER(spfft_grid_device_id, int, device_id)
SPFFT_TPU_GRID_GETTER(spfft_grid_num_threads, int, max_num_threads)
SPFFT_TPU_GRID_GETTER(spfft_grid_num_shards, int, num_shards)

#undef SPFFT_TPU_GRID_GETTER

/* ---- grid (float tier) ----------------------------------------------------
 * GridFloat is the same capacity object (precision lives on the Transform,
 * grid.hpp); the full reference surface (reference: include/spfft/
 * grid_float.h:30-190, instantiated in src/spfft/grid_float.cpp) delegates. */

SpfftError spfft_float_grid_create_distributed(SpfftFloatGrid* grid, int maxDimX,
                                               int maxDimY, int maxDimZ,
                                               int maxNumLocalZColumns,
                                               int maxLocalZLength, int numShards,
                                               SpfftExchangeType exchangeType,
                                               SpfftProcessingUnitType processingUnit,
                                               int maxNumThreads) {
  return spfft_grid_create_distributed(grid, maxDimX, maxDimY, maxDimZ,
                                       maxNumLocalZColumns, maxLocalZLength, numShards,
                                       exchangeType, processingUnit, maxNumThreads);
}

SpfftError spfft_float_grid_destroy(SpfftFloatGrid grid) {
  return spfft_grid_destroy(grid);
}

SpfftError spfft_float_grid_max_dim_x(SpfftFloatGrid grid, int* dimX) {
  return spfft_grid_max_dim_x(grid, dimX);
}
SpfftError spfft_float_grid_max_dim_y(SpfftFloatGrid grid, int* dimY) {
  return spfft_grid_max_dim_y(grid, dimY);
}
SpfftError spfft_float_grid_max_dim_z(SpfftFloatGrid grid, int* dimZ) {
  return spfft_grid_max_dim_z(grid, dimZ);
}
SpfftError spfft_float_grid_max_num_local_z_columns(SpfftFloatGrid grid, int* out) {
  return spfft_grid_max_num_local_z_columns(grid, out);
}
SpfftError spfft_float_grid_max_local_z_length(SpfftFloatGrid grid, int* out) {
  return spfft_grid_max_local_z_length(grid, out);
}
SpfftError spfft_float_grid_processing_unit(SpfftFloatGrid grid,
                                            SpfftProcessingUnitType* out) {
  return spfft_grid_processing_unit(grid, out);
}
SpfftError spfft_float_grid_device_id(SpfftFloatGrid grid, int* deviceId) {
  return spfft_grid_device_id(grid, deviceId);
}
SpfftError spfft_float_grid_num_threads(SpfftFloatGrid grid, int* numThreads) {
  return spfft_grid_num_threads(grid, numThreads);
}

/* ---- MPI-surface parity stubs ---------------------------------------------
 * No MPI exists in this runtime (the device mesh replaces the communicator,
 * docs/api/c_api.md); these keep ported callers LINKING (reference:
 * include/spfft/grid.h:184, transform.h:122,341) and fail with the same code
 * a feature-less reference build reports. The comm argument is declared
 * void* here and never read; callers compiled with an int-typed MPI_Comm
 * (MPICH) pass a technically different by-value type, which is benign on
 * every supported ABI because scalar arguments ride the same registers —
 * see the ABI note at the SpfftMpiComm typedef (types.h). The
 * *_fortran variants take the MPI_Fint the reference's Fortran module binds
 * (reference: src/spfft/grid.cpp *_fortran entries). */

SpfftError spfft_grid_communicator(SpfftGrid, SpfftMpiComm*) {
  return SPFFT_MPI_SUPPORT_ERROR;
}
SpfftError spfft_float_grid_communicator(SpfftFloatGrid, SpfftMpiComm*) {
  return SPFFT_MPI_SUPPORT_ERROR;
}
SpfftError spfft_transform_communicator(SpfftTransform, SpfftMpiComm*) {
  return SPFFT_MPI_SUPPORT_ERROR;
}
SpfftError spfft_float_transform_communicator(SpfftFloatTransform, SpfftMpiComm*) {
  return SPFFT_MPI_SUPPORT_ERROR;
}
SpfftError spfft_grid_communicator_fortran(SpfftGrid, int*) {
  return SPFFT_MPI_SUPPORT_ERROR;
}
SpfftError spfft_float_grid_communicator_fortran(SpfftFloatGrid, int*) {
  return SPFFT_MPI_SUPPORT_ERROR;
}
SpfftError spfft_transform_communicator_fortran(SpfftTransform, int*) {
  return SPFFT_MPI_SUPPORT_ERROR;
}
SpfftError spfft_float_transform_communicator_fortran(SpfftFloatTransform, int*) {
  return SPFFT_MPI_SUPPORT_ERROR;
}

SpfftError spfft_transform_create_independent_distributed(
    SpfftTransform*, int, SpfftMpiComm, SpfftExchangeType, SpfftProcessingUnitType,
    SpfftTransformType, int, int, int, int, int, SpfftIndexFormatType, const int*) {
  return SPFFT_MPI_SUPPORT_ERROR;
}
SpfftError spfft_float_transform_create_independent_distributed(
    SpfftFloatTransform*, int, SpfftMpiComm, SpfftExchangeType, SpfftProcessingUnitType,
    SpfftTransformType, int, int, int, int, int, SpfftIndexFormatType, const int*) {
  return SPFFT_MPI_SUPPORT_ERROR;
}
SpfftError spfft_transform_create_independent_distributed_fortran(
    SpfftTransform*, int, int, SpfftExchangeType, SpfftProcessingUnitType,
    SpfftTransformType, int, int, int, int, int, SpfftIndexFormatType, const int*) {
  return SPFFT_MPI_SUPPORT_ERROR;
}
SpfftError spfft_float_transform_create_independent_distributed_fortran(
    SpfftFloatTransform*, int, int, SpfftExchangeType, SpfftProcessingUnitType,
    SpfftTransformType, int, int, int, int, int, SpfftIndexFormatType, const int*) {
  return SPFFT_MPI_SUPPORT_ERROR;
}

/* ---- transform (double) --------------------------------------------------- */

SpfftError spfft_transform_create_independent(
    SpfftTransform* transform, int /*maxNumThreads*/,
    SpfftProcessingUnitType processingUnit, SpfftTransformType transformType, int dimX,
    int dimY, int dimZ, int numLocalElements, SpfftIndexFormatType indexFormat,
    const int* indices) {
  if (transform == nullptr) return SPFFT_INVALID_HANDLE_ERROR;
  return guarded([&] {
    *transform = new spfft::Transform(processingUnit, transformType, dimX, dimY, dimZ,
                                      numLocalElements, indexFormat, indices);
  });
}

SpfftError spfft_transform_create(SpfftTransform* transform, SpfftGrid grid,
                                  SpfftProcessingUnitType processingUnit,
                                  SpfftTransformType transformType, int dimX, int dimY,
                                  int dimZ, int localZLength, int numLocalElements,
                                  SpfftIndexFormatType indexFormat, const int* indices) {
  if (transform == nullptr || grid == nullptr) return SPFFT_INVALID_HANDLE_ERROR;
  return guarded([&] {
    *transform = new spfft::Transform(as_grid(grid)->create_transform(
        processingUnit, transformType, dimX, dimY, dimZ, localZLength,
        numLocalElements, indexFormat, indices));
  });
}

SpfftError spfft_transform_destroy(SpfftTransform transform) {
  if (transform == nullptr) return SPFFT_INVALID_HANDLE_ERROR;
  return guarded([&] { delete as_transform(transform); });
}

SpfftError spfft_transform_clone(SpfftTransform transform, SpfftTransform* newTransform) {
  if (transform == nullptr || newTransform == nullptr)
    return SPFFT_INVALID_HANDLE_ERROR;
  return guarded(
      [&] { *newTransform = new spfft::Transform(as_transform(transform)->clone()); });
}

SpfftError spfft_transform_backward(SpfftTransform transform, const double* input,
                                    SpfftProcessingUnitType outputLocation) {
  if (transform == nullptr) return SPFFT_INVALID_HANDLE_ERROR;
  return guarded([&] { as_transform(transform)->backward(input, outputLocation); });
}

SpfftError spfft_transform_forward(SpfftTransform transform,
                                   SpfftProcessingUnitType inputLocation, double* output,
                                   SpfftScalingType scaling) {
  if (transform == nullptr) return SPFFT_INVALID_HANDLE_ERROR;
  return guarded(
      [&] { as_transform(transform)->forward(inputLocation, output, scaling); });
}

SpfftError spfft_transform_forward_ptr(SpfftTransform transform, const double* input,
                                       double* output, SpfftScalingType scaling) {
  if (transform == nullptr) return SPFFT_INVALID_HANDLE_ERROR;
  return guarded([&] { as_transform(transform)->forward(input, output, scaling); });
}

SpfftError spfft_transform_backward_ptr(SpfftTransform transform, const double* input,
                                        double* output) {
  if (transform == nullptr || output == nullptr) return SPFFT_INVALID_HANDLE_ERROR;
  return guarded([&] { as_transform(transform)->backward(input, output); });
}

SpfftError spfft_transform_get_space_domain(SpfftTransform transform,
                                            SpfftProcessingUnitType dataLocation,
                                            double** data) {
  if (transform == nullptr || data == nullptr) return SPFFT_INVALID_HANDLE_ERROR;
  return guarded(
      [&] { *data = as_transform(transform)->space_domain_data(dataLocation); });
}

#define SPFFT_TPU_TRANSFORM_GETTER(FN, OUT_T, METHOD)                                    \
  SpfftError FN(SpfftTransform transform, OUT_T* out) {                                  \
    if (transform == nullptr || out == nullptr) return SPFFT_INVALID_HANDLE_ERROR;       \
    return guarded([&] { *out = static_cast<OUT_T>(as_transform(transform)->METHOD()); });\
  }

SPFFT_TPU_TRANSFORM_GETTER(spfft_transform_type, SpfftTransformType, type)
SPFFT_TPU_TRANSFORM_GETTER(spfft_transform_dim_x, int, dim_x)
SPFFT_TPU_TRANSFORM_GETTER(spfft_transform_dim_y, int, dim_y)
SPFFT_TPU_TRANSFORM_GETTER(spfft_transform_dim_z, int, dim_z)
SPFFT_TPU_TRANSFORM_GETTER(spfft_transform_local_z_length, int, local_z_length)
SPFFT_TPU_TRANSFORM_GETTER(spfft_transform_local_z_offset, int, local_z_offset)
SPFFT_TPU_TRANSFORM_GETTER(spfft_transform_local_slice_size, int, local_slice_size)
SPFFT_TPU_TRANSFORM_GETTER(spfft_transform_num_local_elements, int, num_local_elements)
SPFFT_TPU_TRANSFORM_GETTER(spfft_transform_num_global_elements, long long int,
                           num_global_elements)
SPFFT_TPU_TRANSFORM_GETTER(spfft_transform_global_size, long long int, global_size)
SPFFT_TPU_TRANSFORM_GETTER(spfft_transform_processing_unit, SpfftProcessingUnitType,
                           processing_unit)
SPFFT_TPU_TRANSFORM_GETTER(spfft_transform_device_id, int, device_id)
SPFFT_TPU_TRANSFORM_GETTER(spfft_transform_num_threads, int, num_threads)
SPFFT_TPU_TRANSFORM_GETTER(spfft_transform_execution_mode, SpfftExecType, execution_mode)

#undef SPFFT_TPU_TRANSFORM_GETTER

SpfftError spfft_transform_set_execution_mode(SpfftTransform transform,
                                              SpfftExecType mode) {
  if (transform == nullptr) return SPFFT_INVALID_HANDLE_ERROR;
  return guarded([&] { as_transform(transform)->set_execution_mode(mode); });
}

/* ---- transform (float) ---------------------------------------------------- */

SpfftError spfft_float_transform_create_independent(
    SpfftFloatTransform* transform, int /*maxNumThreads*/,
    SpfftProcessingUnitType processingUnit, SpfftTransformType transformType, int dimX,
    int dimY, int dimZ, int numLocalElements, SpfftIndexFormatType indexFormat,
    const int* indices) {
  if (transform == nullptr) return SPFFT_INVALID_HANDLE_ERROR;
  return guarded([&] {
    *transform = new spfft::TransformFloat(processingUnit, transformType, dimX, dimY,
                                           dimZ, numLocalElements, indexFormat, indices);
  });
}

SpfftError spfft_float_transform_create(SpfftFloatTransform* transform,
                                        SpfftFloatGrid grid,
                                        SpfftProcessingUnitType processingUnit,
                                        SpfftTransformType transformType, int dimX,
                                        int dimY, int dimZ, int localZLength,
                                        int numLocalElements,
                                        SpfftIndexFormatType indexFormat,
                                        const int* indices) {
  if (transform == nullptr || grid == nullptr) return SPFFT_INVALID_HANDLE_ERROR;
  return guarded([&] {
    *transform = new spfft::TransformFloat(as_grid(grid)->create_transform_float(
        processingUnit, transformType, dimX, dimY, dimZ, localZLength,
        numLocalElements, indexFormat, indices));
  });
}

SpfftError spfft_float_transform_destroy(SpfftFloatTransform transform) {
  if (transform == nullptr) return SPFFT_INVALID_HANDLE_ERROR;
  return guarded([&] { delete as_float_transform(transform); });
}

SpfftError spfft_float_transform_clone(SpfftFloatTransform transform,
                                       SpfftFloatTransform* newTransform) {
  if (transform == nullptr || newTransform == nullptr)
    return SPFFT_INVALID_HANDLE_ERROR;
  return guarded([&] {
    *newTransform = new spfft::TransformFloat(as_float_transform(transform)->clone());
  });
}

SpfftError spfft_float_transform_backward(SpfftFloatTransform transform,
                                          const float* input,
                                          SpfftProcessingUnitType outputLocation) {
  if (transform == nullptr) return SPFFT_INVALID_HANDLE_ERROR;
  return guarded(
      [&] { as_float_transform(transform)->backward(input, outputLocation); });
}

SpfftError spfft_float_transform_forward(SpfftFloatTransform transform,
                                         SpfftProcessingUnitType inputLocation,
                                         float* output, SpfftScalingType scaling) {
  if (transform == nullptr) return SPFFT_INVALID_HANDLE_ERROR;
  return guarded(
      [&] { as_float_transform(transform)->forward(inputLocation, output, scaling); });
}

SpfftError spfft_float_transform_forward_ptr(SpfftFloatTransform transform,
                                             const float* input, float* output,
                                             SpfftScalingType scaling) {
  if (transform == nullptr) return SPFFT_INVALID_HANDLE_ERROR;
  return guarded(
      [&] { as_float_transform(transform)->forward(input, output, scaling); });
}

SpfftError spfft_float_transform_backward_ptr(SpfftFloatTransform transform,
                                              const float* input, float* output) {
  if (transform == nullptr || output == nullptr) return SPFFT_INVALID_HANDLE_ERROR;
  return guarded([&] { as_float_transform(transform)->backward(input, output); });
}

SpfftError spfft_float_transform_get_space_domain(SpfftFloatTransform transform,
                                                  SpfftProcessingUnitType dataLocation,
                                                  float** data) {
  if (transform == nullptr || data == nullptr) return SPFFT_INVALID_HANDLE_ERROR;
  return guarded(
      [&] { *data = as_float_transform(transform)->space_domain_data(dataLocation); });
}

#define SPFFT_TPU_FLOAT_GETTER(FN, OUT_T, METHOD)                                        \
  SpfftError FN(SpfftFloatTransform transform, OUT_T* out) {                             \
    if (transform == nullptr || out == nullptr) return SPFFT_INVALID_HANDLE_ERROR;       \
    return guarded(                                                                      \
        [&] { *out = static_cast<OUT_T>(as_float_transform(transform)->METHOD()); });    \
  }

SPFFT_TPU_FLOAT_GETTER(spfft_float_transform_type, SpfftTransformType, type)
SPFFT_TPU_FLOAT_GETTER(spfft_float_transform_dim_x, int, dim_x)
SPFFT_TPU_FLOAT_GETTER(spfft_float_transform_dim_y, int, dim_y)
SPFFT_TPU_FLOAT_GETTER(spfft_float_transform_dim_z, int, dim_z)
SPFFT_TPU_FLOAT_GETTER(spfft_float_transform_local_z_length, int, local_z_length)
SPFFT_TPU_FLOAT_GETTER(spfft_float_transform_local_z_offset, int, local_z_offset)
SPFFT_TPU_FLOAT_GETTER(spfft_float_transform_local_slice_size, int, local_slice_size)
SPFFT_TPU_FLOAT_GETTER(spfft_float_transform_num_local_elements, int, num_local_elements)
SPFFT_TPU_FLOAT_GETTER(spfft_float_transform_num_global_elements, long long int,
                       num_global_elements)
SPFFT_TPU_FLOAT_GETTER(spfft_float_transform_global_size, long long int, global_size)
SPFFT_TPU_FLOAT_GETTER(spfft_float_transform_processing_unit, SpfftProcessingUnitType,
                       processing_unit)
SPFFT_TPU_FLOAT_GETTER(spfft_float_transform_device_id, int, device_id)
SPFFT_TPU_FLOAT_GETTER(spfft_float_transform_num_threads, int, num_threads)
SPFFT_TPU_FLOAT_GETTER(spfft_float_transform_execution_mode, SpfftExecType,
                       execution_mode)

#undef SPFFT_TPU_FLOAT_GETTER

SpfftError spfft_float_transform_set_execution_mode(SpfftFloatTransform transform,
                                                    SpfftExecType mode) {
  if (transform == nullptr) return SPFFT_INVALID_HANDLE_ERROR;
  return guarded([&] { as_float_transform(transform)->set_execution_mode(mode); });
}

/* ---- multi-transform ------------------------------------------------------ */

SpfftError spfft_multi_transform_backward(int numTransforms, SpfftTransform* transforms,
                                          const double* const* input,
                                          const SpfftProcessingUnitType* outputLocations) {
  if (transforms == nullptr) return SPFFT_INVALID_HANDLE_ERROR;
  return guarded([&] {
    std::vector<spfft::Transform> objs;
    objs.reserve(numTransforms);
    for (int i = 0; i < numTransforms; ++i) objs.push_back(*as_transform(transforms[i]));
    spfft::multi_transform_backward(numTransforms, objs.data(), input, outputLocations);
  });
}

SpfftError spfft_multi_transform_forward(int numTransforms, SpfftTransform* transforms,
                                         const SpfftProcessingUnitType* inputLocations,
                                         double* const* output,
                                         const SpfftScalingType* scalingTypes) {
  if (transforms == nullptr) return SPFFT_INVALID_HANDLE_ERROR;
  return guarded([&] {
    std::vector<spfft::Transform> objs;
    objs.reserve(numTransforms);
    for (int i = 0; i < numTransforms; ++i) objs.push_back(*as_transform(transforms[i]));
    spfft::multi_transform_forward(numTransforms, objs.data(), inputLocations, output,
                                   scalingTypes);
  });
}

SpfftError spfft_float_multi_transform_backward(
    int numTransforms, SpfftFloatTransform* transforms, const float* const* input,
    const SpfftProcessingUnitType* outputLocations) {
  if (transforms == nullptr) return SPFFT_INVALID_HANDLE_ERROR;
  return guarded([&] {
    std::vector<spfft::TransformFloat> objs;
    objs.reserve(numTransforms);
    for (int i = 0; i < numTransforms; ++i)
      objs.push_back(*as_float_transform(transforms[i]));
    spfft::multi_transform_backward(numTransforms, objs.data(), input, outputLocations);
  });
}

SpfftError spfft_float_multi_transform_forward(
    int numTransforms, SpfftFloatTransform* transforms,
    const SpfftProcessingUnitType* inputLocations, float* const* output,
    const SpfftScalingType* scalingTypes) {
  if (transforms == nullptr) return SPFFT_INVALID_HANDLE_ERROR;
  return guarded([&] {
    std::vector<spfft::TransformFloat> objs;
    objs.reserve(numTransforms);
    for (int i = 0; i < numTransforms; ++i)
      objs.push_back(*as_float_transform(transforms[i]));
    spfft::multi_transform_forward(numTransforms, objs.data(), inputLocations, output,
                                   scalingTypes);
  });
}

/* Pointer-based batch overloads (reference: include/spfft/multi_transform.h:60-95). */

SpfftError spfft_multi_transform_backward_ptr(int numTransforms,
                                              SpfftTransform* transforms,
                                              const double* const* inputPointers,
                                              double* const* outputPointers) {
  if (transforms == nullptr) return SPFFT_INVALID_HANDLE_ERROR;
  return guarded([&] {
    std::vector<spfft::Transform> objs;
    objs.reserve(numTransforms);
    for (int i = 0; i < numTransforms; ++i) objs.push_back(*as_transform(transforms[i]));
    spfft::multi_transform_backward(numTransforms, objs.data(), inputPointers,
                                    outputPointers);
  });
}

SpfftError spfft_multi_transform_forward_ptr(int numTransforms,
                                             SpfftTransform* transforms,
                                             const double* const* inputPointers,
                                             double* const* outputPointers,
                                             const SpfftScalingType* scalingTypes) {
  if (transforms == nullptr) return SPFFT_INVALID_HANDLE_ERROR;
  return guarded([&] {
    std::vector<spfft::Transform> objs;
    objs.reserve(numTransforms);
    for (int i = 0; i < numTransforms; ++i) objs.push_back(*as_transform(transforms[i]));
    spfft::multi_transform_forward(numTransforms, objs.data(), inputPointers,
                                   outputPointers, scalingTypes);
  });
}

SpfftError spfft_float_multi_transform_backward_ptr(int numTransforms,
                                                    SpfftFloatTransform* transforms,
                                                    const float* const* inputPointers,
                                                    float* const* outputPointers) {
  if (transforms == nullptr) return SPFFT_INVALID_HANDLE_ERROR;
  return guarded([&] {
    std::vector<spfft::TransformFloat> objs;
    objs.reserve(numTransforms);
    for (int i = 0; i < numTransforms; ++i)
      objs.push_back(*as_float_transform(transforms[i]));
    spfft::multi_transform_backward(numTransforms, objs.data(), inputPointers,
                                    outputPointers);
  });
}

SpfftError spfft_float_multi_transform_forward_ptr(int numTransforms,
                                                   SpfftFloatTransform* transforms,
                                                   const float* const* inputPointers,
                                                   float* const* outputPointers,
                                                   const SpfftScalingType* scalingTypes) {
  if (transforms == nullptr) return SPFFT_INVALID_HANDLE_ERROR;
  return guarded([&] {
    std::vector<spfft::TransformFloat> objs;
    objs.reserve(numTransforms);
    for (int i = 0; i < numTransforms; ++i)
      objs.push_back(*as_float_transform(transforms[i]));
    spfft::multi_transform_forward(numTransforms, objs.data(), inputPointers,
                                   outputPointers, scalingTypes);
  });
}

/* ---- distributed transform ------------------------------------------------ */

SpfftError spfft_dist_transform_create(SpfftDistTransform* transform, SpfftGrid grid,
                                       SpfftProcessingUnitType processingUnit,
                                       SpfftTransformType transformType, int dimX,
                                       int dimY, int dimZ, int numShards,
                                       const int* shardNumElements,
                                       SpfftIndexFormatType indexFormat,
                                       const int* indices, int doublePrecision) {
  if (transform == nullptr || grid == nullptr) return SPFFT_INVALID_HANDLE_ERROR;
  return guarded([&] {
    *transform = new spfft::DistributedTransform(
        as_grid(grid)->create_transform_distributed(
            processingUnit, transformType, dimX, dimY, dimZ, numShards,
            shardNumElements, indexFormat, indices, doublePrecision != 0));
  });
}

SpfftError spfft_dist_transform_create_independent(
    SpfftDistTransform* transform, int maxNumThreads, int numShards,
    SpfftExchangeType exchangeType, SpfftProcessingUnitType processingUnit,
    SpfftTransformType transformType, int dimX, int dimY, int dimZ,
    const int* shardNumElements, SpfftIndexFormatType indexFormat,
    const int* indices, int doublePrecision) {
  if (transform == nullptr) return SPFFT_INVALID_HANDLE_ERROR;
  /* The internal grid is only a capacity envelope consumed at plan creation
   * (the runtime keeps what it needs), so it is created wide and destroyed
   * immediately after — the reference's grid-less ctor does the same
   * internally (reference: src/spfft/transform.cpp grid-less path). */
  SpfftGrid grid = nullptr;
  SpfftError err = spfft_grid_create_distributed(
      &grid, dimX, dimY, dimZ, dimX * dimY, dimZ, numShards, exchangeType,
      processingUnit, maxNumThreads);
  if (err != SPFFT_SUCCESS) return err;
  err = spfft_dist_transform_create(transform, grid, processingUnit, transformType,
                                    dimX, dimY, dimZ, numShards, shardNumElements,
                                    indexFormat, indices, doublePrecision);
  SpfftError destroy_err = spfft_grid_destroy(grid);
  return err != SPFFT_SUCCESS ? err : destroy_err;
}

SpfftError spfft_dist_transform_destroy(SpfftDistTransform transform) {
  if (transform == nullptr) return SPFFT_INVALID_HANDLE_ERROR;
  return guarded([&] { delete as_dist_transform(transform); });
}

SpfftError spfft_dist_transform_backward(SpfftDistTransform transform,
                                         const double* values, double* space) {
  if (transform == nullptr) return SPFFT_INVALID_HANDLE_ERROR;
  return guarded([&] { as_dist_transform(transform)->backward(values, space); });
}

SpfftError spfft_float_dist_transform_backward(SpfftDistTransform transform,
                                               const float* values, float* space) {
  if (transform == nullptr) return SPFFT_INVALID_HANDLE_ERROR;
  return guarded([&] { as_dist_transform(transform)->backward(values, space); });
}

SpfftError spfft_dist_transform_forward(SpfftDistTransform transform,
                                        const double* space, double* values,
                                        SpfftScalingType scaling) {
  if (transform == nullptr) return SPFFT_INVALID_HANDLE_ERROR;
  return guarded([&] { as_dist_transform(transform)->forward(space, values, scaling); });
}

SpfftError spfft_float_dist_transform_forward(SpfftDistTransform transform,
                                              const float* space, float* values,
                                              SpfftScalingType scaling) {
  if (transform == nullptr) return SPFFT_INVALID_HANDLE_ERROR;
  return guarded([&] { as_dist_transform(transform)->forward(space, values, scaling); });
}

#define SPFFT_TPU_DIST_GETTER(FN, OUT_T, METHOD)                                         \
  SpfftError FN(SpfftDistTransform transform, OUT_T* out) {                              \
    if (transform == nullptr || out == nullptr) return SPFFT_INVALID_HANDLE_ERROR;       \
    return guarded(                                                                      \
        [&] { *out = static_cast<OUT_T>(as_dist_transform(transform)->METHOD()); });     \
  }

SPFFT_TPU_DIST_GETTER(spfft_dist_transform_type, SpfftTransformType, type)
SPFFT_TPU_DIST_GETTER(spfft_dist_transform_dim_x, int, dim_x)
SPFFT_TPU_DIST_GETTER(spfft_dist_transform_dim_y, int, dim_y)
SPFFT_TPU_DIST_GETTER(spfft_dist_transform_dim_z, int, dim_z)
SPFFT_TPU_DIST_GETTER(spfft_dist_transform_num_shards, int, num_shards)
SPFFT_TPU_DIST_GETTER(spfft_dist_transform_num_global_elements, long long int,
                      num_global_elements)
SPFFT_TPU_DIST_GETTER(spfft_dist_transform_global_size, long long int, global_size)
SPFFT_TPU_DIST_GETTER(spfft_dist_transform_exchange_type, SpfftExchangeType,
                      exchange_type)
SPFFT_TPU_DIST_GETTER(spfft_dist_transform_exchange_wire_bytes, long long int,
                      exchange_wire_bytes)
SPFFT_TPU_DIST_GETTER(spfft_dist_transform_exchange_rounds, int, exchange_rounds)

#undef SPFFT_TPU_DIST_GETTER

#define SPFFT_TPU_DIST_SHARD_GETTER(FN, OUT_T, METHOD)                                   \
  SpfftError FN(SpfftDistTransform transform, int shard, OUT_T* out) {                   \
    if (transform == nullptr || out == nullptr) return SPFFT_INVALID_HANDLE_ERROR;       \
    return guarded(                                                                      \
        [&] { *out = static_cast<OUT_T>(as_dist_transform(transform)->METHOD(shard)); });\
  }

SPFFT_TPU_DIST_SHARD_GETTER(spfft_dist_transform_local_z_length, int, local_z_length)
SPFFT_TPU_DIST_SHARD_GETTER(spfft_dist_transform_local_z_offset, int, local_z_offset)
SPFFT_TPU_DIST_SHARD_GETTER(spfft_dist_transform_local_y_length, int, local_y_length)
SPFFT_TPU_DIST_SHARD_GETTER(spfft_dist_transform_local_y_offset, int, local_y_offset)
SPFFT_TPU_DIST_SHARD_GETTER(spfft_dist_transform_num_local_elements, int,
                            num_local_elements)

#undef SPFFT_TPU_DIST_SHARD_GETTER

} /* extern "C" */
