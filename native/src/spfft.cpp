/*
 * spfft_tpu native API — C++ classes and C interface core.
 *
 * Structure mirrors the reference's public layer (reference:
 * src/spfft/transform.cpp, grid.cpp, multi_transform.cpp): thin C++ classes
 * over a shared plan object, and extern-C handle functions (capi_c.cpp) that
 * catch GenericError and return its error code. The plan drives the XLA
 * compute core through the bridge (see bridge.hpp) and owns the host-side
 * space-domain buffer, which gives space_domain_data() the same
 * write-then-forward semantics as the reference (reference:
 * include/spfft/transform.hpp:245, examples/example.cpp usage).
 */
#include "bridge.hpp"

#include <spfft/spfft.hpp>

#include <cstring>
#include <memory>
#include <vector>

namespace spfft {
namespace detail {

namespace br = spfft::bridge;

struct Plan {
  br::Ref py;            /* the compute-core plan object */
  bool dbl = true;       /* double precision? */
  long long num_values = 0;
  long long space_reals = 0; /* reals in the space-domain slab */
  std::vector<unsigned char> space; /* host space-domain buffer */

  /* Immutable layout metadata, fetched once at plan creation so getters never
   * re-enter the embedded runtime. */
  struct Meta {
    int dim_x = 0, dim_y = 0, dim_z = 0;
    int local_z_length = 0, local_z_offset = 0;
    int device_id = 0, num_threads = 1;
    long long local_slice_size = 0, num_global_elements = 0, global_size = 0;
    int transform_type = 0, processing_unit = 0;
  } meta;

  std::size_t elem_bytes() const { return dbl ? sizeof(double) : sizeof(float); }

  long long get(const char* name) const {
    br::Gil gil;
    br::Ref r = br::call("transform_get", Py_BuildValue("(Os)", py.get(), name));
    return br::as_longlong(r.get());
  }

  void backward(const void* input) {
    br::Gil gil;
    br::Ref in =
        br::view_ro(input, static_cast<std::size_t>(2 * num_values) * elem_bytes());
    br::Ref out = br::view_rw(space.data(), space.size());
    br::call("transform_backward",
             Py_BuildValue("(OOO)", py.get(), in.get(), out.get()));
  }

  void forward(const void* space_input, void* output, int scaling) {
    br::Gil gil;
    br::Ref in = br::view_ro(space_input,
                             static_cast<std::size_t>(space_reals) * elem_bytes());
    br::Ref out =
        br::view_rw(output, static_cast<std::size_t>(2 * num_values) * elem_bytes());
    br::call("transform_forward",
             Py_BuildValue("(OOOi)", py.get(), in.get(), out.get(), scaling));
  }

  void set_execution_mode(int mode) {
    br::Gil gil;
    br::call("transform_set_execution_mode", Py_BuildValue("(Oi)", py.get(), mode));
  }
};

struct GridState {
  br::Ref py;
};

/* Mesh-distributed plan: shard-major concatenated host arrays over the
 * single-controller mesh (see spfft_tpu/capi.py dist_* functions). */
struct DistPlan {
  br::Ref py;
  bool dbl = true;
  long long num_global = 0; /* total packed values across shards */
  long long space_reals = 0;

  struct Meta {
    int dim_x = 0, dim_y = 0, dim_z = 0, num_shards = 0;
    int transform_type = 0, processing_unit = 0, exchange_type = 0;
    long long global_size = 0, wire_bytes = 0;
    int exchange_rounds = 0;
  } meta;
  std::vector<long long> shard_elems, shard_zlen, shard_zoff, shard_slice;
  std::vector<long long> shard_ylen, shard_yoff;

  std::size_t elem_bytes() const { return dbl ? sizeof(double) : sizeof(float); }

  long long get(const char* name) const {
    br::Gil gil;
    br::Ref r = br::call("dist_transform_get", Py_BuildValue("(Os)", py.get(), name));
    return br::as_longlong(r.get());
  }

  long long get_shard(const char* name, int shard) const {
    br::Gil gil;
    br::Ref r = br::call("dist_transform_get_shard",
                         Py_BuildValue("(Osi)", py.get(), name, shard));
    return br::as_longlong(r.get());
  }

  void check_shard(int shard) const {
    if (shard < 0 || shard >= meta.num_shards) {
      throw InvalidParameterError("spfft_tpu: shard index out of range");
    }
  }

  void check_precision(bool want_dbl) const {
    if (dbl != want_dbl) {
      throw InvalidParameterError(
          "spfft_tpu: value pointer precision does not match the plan");
    }
  }

  void backward(const void* values, void* space) {
    br::Gil gil;
    br::Ref in = br::view_ro(values,
                             static_cast<std::size_t>(2 * num_global) * elem_bytes());
    br::Ref out =
        br::view_rw(space, static_cast<std::size_t>(space_reals) * elem_bytes());
    br::call("dist_backward", Py_BuildValue("(OOO)", py.get(), in.get(), out.get()));
  }

  void forward(const void* space, void* values, int scaling) {
    br::Gil gil;
    br::Ref out =
        br::view_rw(values, static_cast<std::size_t>(2 * num_global) * elem_bytes());
    if (space == nullptr) {
      br::call("dist_forward",
               Py_BuildValue("(OOOi)", py.get(), Py_None, out.get(), scaling));
      return;
    }
    br::Ref in =
        br::view_ro(space, static_cast<std::size_t>(space_reals) * elem_bytes());
    br::call("dist_forward",
             Py_BuildValue("(OOOi)", py.get(), in.get(), out.get(), scaling));
  }
};

const std::shared_ptr<GridState>& grid_state(const Grid& grid) { return grid.state_; }

Plan* plan_of(Transform& t) { return t.plan_.get(); }
Plan* plan_of(TransformFloat& t) { return t.plan_.get(); }

namespace {

void finish_plan(const std::shared_ptr<Plan>& plan) {
  Plan::Meta& m = plan->meta;
  m.dim_x = static_cast<int>(plan->get("dim_x"));
  m.dim_y = static_cast<int>(plan->get("dim_y"));
  m.dim_z = static_cast<int>(plan->get("dim_z"));
  m.local_z_length = static_cast<int>(plan->get("local_z_length"));
  m.local_z_offset = static_cast<int>(plan->get("local_z_offset"));
  m.device_id = static_cast<int>(plan->get("device_id"));
  m.num_threads = static_cast<int>(plan->get("num_threads"));
  m.local_slice_size = plan->get("local_slice_size");
  m.num_global_elements = plan->get("num_global_elements");
  m.global_size = plan->get("global_size");
  m.transform_type = static_cast<int>(plan->get("transform_type"));
  m.processing_unit = static_cast<int>(plan->get("processing_unit"));
  plan->num_values = plan->get("num_local_elements");
  bool r2c = m.transform_type == SPFFT_TRANS_R2C;
  plan->space_reals = r2c ? m.local_slice_size : 2 * m.local_slice_size;
  plan->space.assign(static_cast<std::size_t>(plan->space_reals) * plan->elem_bytes(),
                     0);
}

} // namespace

std::shared_ptr<Plan> make_plan(const Grid* grid, bool double_precision,
                                SpfftProcessingUnitType pu, SpfftTransformType tt,
                                int dim_x, int dim_y, int dim_z, int local_z_length,
                                int num_local_elements, SpfftIndexFormatType fmt,
                                const int* indices) {
  if (fmt != SPFFT_INDEX_TRIPLETS) {
    throw InvalidParameterError("spfft_tpu: only SPFFT_INDEX_TRIPLETS is supported");
  }
  if (num_local_elements < 0 || (num_local_elements > 0 && indices == nullptr)) {
    throw InvalidParameterError("spfft_tpu: invalid index array");
  }
  auto plan = std::make_shared<Plan>();
  plan->dbl = double_precision;
  {
    br::Gil gil;
    br::Ref idx = br::view_ro(
        indices, static_cast<std::size_t>(3 * num_local_elements) * sizeof(int));
    if (grid != nullptr) {
      plan->py = br::call(
          "transform_create_from_grid",
          Py_BuildValue("(OiiiiiiiOi)", grid_state(*grid)->py.get(),
                        static_cast<int>(pu), static_cast<int>(tt), dim_x, dim_y,
                        dim_z, local_z_length, num_local_elements, idx.get(),
                        double_precision ? 1 : 0));
    } else {
      plan->py = br::call(
          "transform_create",
          Py_BuildValue("(iiiiiiOi)", static_cast<int>(pu), static_cast<int>(tt),
                        dim_x, dim_y, dim_z, num_local_elements, idx.get(),
                        double_precision ? 1 : 0));
    }
  }
  finish_plan(plan);
  return plan;
}

namespace {

std::shared_ptr<Plan> clone_plan(const std::shared_ptr<Plan>& plan) {
  auto out = std::make_shared<Plan>();
  out->dbl = plan->dbl;
  {
    br::Gil gil;
    out->py = br::call("transform_clone", Py_BuildValue("(O)", plan->py.get()));
  }
  finish_plan(out);
  return out;
}

long long grid_attr(const std::shared_ptr<GridState>& state, const char* name) {
  br::Gil gil;
  br::Ref r = br::call("grid_get", Py_BuildValue("(Os)", state->py.get(), name));
  return br::as_longlong(r.get());
}

std::shared_ptr<DistPlan> make_dist_plan(const Grid& grid, bool double_precision,
                                         SpfftProcessingUnitType pu,
                                         SpfftTransformType tt, int dim_x, int dim_y,
                                         int dim_z, int num_shards,
                                         const int* shard_num_elements,
                                         SpfftIndexFormatType fmt, const int* indices) {
  if (fmt != SPFFT_INDEX_TRIPLETS) {
    throw InvalidParameterError("spfft_tpu: only SPFFT_INDEX_TRIPLETS is supported");
  }
  if (num_shards < 1 || shard_num_elements == nullptr) {
    throw InvalidParameterError("spfft_tpu: invalid shard layout");
  }
  long long total = 0;
  for (int r = 0; r < num_shards; ++r) {
    if (shard_num_elements[r] < 0) {
      throw InvalidParameterError("spfft_tpu: negative shard element count");
    }
    total += shard_num_elements[r];
  }
  if (total > 0 && indices == nullptr) {
    throw InvalidParameterError("spfft_tpu: invalid index array");
  }
  auto plan = std::make_shared<DistPlan>();
  plan->dbl = double_precision;
  {
    br::Gil gil;
    br::Ref counts = br::view_ro(shard_num_elements,
                                 static_cast<std::size_t>(num_shards) * sizeof(int));
    br::Ref idx =
        br::view_ro(indices, static_cast<std::size_t>(3 * total) * sizeof(int));
    plan->py = br::call(
        "dist_transform_create",
        Py_BuildValue("(OiiiiiiOOi)", grid_state(grid)->py.get(), static_cast<int>(pu),
                      static_cast<int>(tt), dim_x, dim_y, dim_z, num_shards,
                      counts.get(), idx.get(), double_precision ? 1 : 0));
  }
  DistPlan::Meta& m = plan->meta;
  m.dim_x = static_cast<int>(plan->get("dim_x"));
  m.dim_y = static_cast<int>(plan->get("dim_y"));
  m.dim_z = static_cast<int>(plan->get("dim_z"));
  m.num_shards = static_cast<int>(plan->get("num_shards"));
  m.transform_type = static_cast<int>(plan->get("transform_type"));
  m.processing_unit = static_cast<int>(plan->get("processing_unit"));
  m.exchange_type = static_cast<int>(plan->get("exchange_type"));
  m.global_size = plan->get("global_size");
  m.wire_bytes = plan->get("exchange_wire_bytes");
  m.exchange_rounds = static_cast<int>(plan->get("exchange_rounds"));
  plan->num_global = plan->get("num_global_elements");
  for (int r = 0; r < m.num_shards; ++r) {
    plan->shard_elems.push_back(plan->get_shard("num_local_elements", r));
    plan->shard_zlen.push_back(plan->get_shard("local_z_length", r));
    plan->shard_zoff.push_back(plan->get_shard("local_z_offset", r));
    plan->shard_ylen.push_back(plan->get_shard("local_y_length", r));
    plan->shard_yoff.push_back(plan->get_shard("local_y_offset", r));
    plan->shard_slice.push_back(plan->get_shard("local_slice_size", r));
  }
  bool r2c = m.transform_type == SPFFT_TRANS_R2C;
  plan->space_reals = r2c ? m.global_size : 2 * m.global_size;
  return plan;
}

} // namespace
} // namespace detail

/* ---- Grid ----------------------------------------------------------------- */

Grid::Grid(int max_dim_x, int max_dim_y, int max_dim_z, int max_num_local_z_columns,
           SpfftProcessingUnitType processing_unit, int max_num_threads)
    : state_(std::make_shared<detail::GridState>()) {
  bridge::Gil gil;
  state_->py = bridge::call(
      "grid_create",
      Py_BuildValue("(iiiiii)", max_dim_x, max_dim_y, max_dim_z,
                    max_num_local_z_columns, static_cast<int>(processing_unit),
                    max_num_threads));
}

Grid::Grid(int max_dim_x, int max_dim_y, int max_dim_z, int max_num_local_z_columns,
           int max_local_z_length, int num_shards, SpfftExchangeType exchange_type,
           SpfftProcessingUnitType processing_unit, int max_num_threads)
    : state_(std::make_shared<detail::GridState>()) {
  bridge::Gil gil;
  state_->py = bridge::call(
      "grid_create_distributed",
      Py_BuildValue("(iiiiiiiii)", max_dim_x, max_dim_y, max_dim_z,
                    max_num_local_z_columns, max_local_z_length, num_shards,
                    static_cast<int>(processing_unit),
                    static_cast<int>(exchange_type), max_num_threads));
}

Grid::Grid(int max_dim_x, int max_dim_y, int max_dim_z, int max_num_local_z_columns,
           int max_local_z_length, int p1, int p2, SpfftExchangeType exchange_type,
           SpfftProcessingUnitType processing_unit, int max_num_threads)
    : state_(std::make_shared<detail::GridState>()) {
  bridge::Gil gil;
  state_->py = bridge::call(
      "grid_create_distributed2",
      Py_BuildValue("(iiiiiiiiii)", max_dim_x, max_dim_y, max_dim_z,
                    max_num_local_z_columns, max_local_z_length, p1, p2,
                    static_cast<int>(processing_unit),
                    static_cast<int>(exchange_type), max_num_threads));
}

Grid::Grid(const Grid& other) : state_(std::make_shared<detail::GridState>()) {
  /* Fresh capacity: re-create from the other grid's parameters (the XLA
   * backend holds no shared host buffers, so metadata equality suffices —
   * matches the reference's fresh-buffer copy, grid_internal.cpp:233-262). */
  bridge::Gil gil;
  /* mesh presence, not shard count: a 1-shard distributed grid must copy to a
   * distributed grid (the dist1 pipeline configs in BASELINE.md rely on it) */
  if (detail::grid_attr(detail::grid_state(other), "has_mesh") != 0) {
    const int p1 =
        static_cast<int>(detail::grid_attr(detail::grid_state(other), "mesh_p1"));
    const int exch = static_cast<int>(
        detail::grid_attr(detail::grid_state(other), "exchange_type"));
    if (p1 > 0) {
      state_->py = bridge::call(
          "grid_create_distributed2",
          Py_BuildValue("(iiiiiiiiii)", other.max_dim_x(), other.max_dim_y(),
                        other.max_dim_z(), other.max_num_local_z_columns(),
                        other.max_local_z_length(), p1, other.num_shards() / p1,
                        static_cast<int>(other.processing_unit()), exch,
                        other.max_num_threads()));
      return;
    }
    state_->py = bridge::call(
        "grid_create_distributed",
        Py_BuildValue("(iiiiiiiii)", other.max_dim_x(), other.max_dim_y(),
                      other.max_dim_z(), other.max_num_local_z_columns(),
                      other.max_local_z_length(), other.num_shards(),
                      static_cast<int>(other.processing_unit()), exch,
                      other.max_num_threads()));
    return;
  }
  state_->py = bridge::call(
      "grid_create",
      Py_BuildValue("(iiiiii)", other.max_dim_x(), other.max_dim_y(),
                    other.max_dim_z(), other.max_num_local_z_columns(),
                    static_cast<int>(other.processing_unit()),
                    other.max_num_threads()));
}

Grid::Grid(Grid&&) noexcept = default;
Grid& Grid::operator=(Grid&&) noexcept = default;

/* bridge::Ref acquires the GIL in its own destructor, so default teardown is
 * safe from any thread. */
Grid::~Grid() = default;

Grid& Grid::operator=(const Grid& other) {
  if (this != &other) {
    Grid tmp(other);
    state_ = std::move(tmp.state_);
  }
  return *this;
}

int Grid::max_dim_x() const {
  return static_cast<int>(detail::grid_attr(state_, "max_dim_x"));
}
int Grid::max_dim_y() const {
  return static_cast<int>(detail::grid_attr(state_, "max_dim_y"));
}
int Grid::max_dim_z() const {
  return static_cast<int>(detail::grid_attr(state_, "max_dim_z"));
}
int Grid::max_num_local_z_columns() const {
  return static_cast<int>(detail::grid_attr(state_, "max_num_local_z_columns"));
}
int Grid::max_local_z_length() const {
  return static_cast<int>(detail::grid_attr(state_, "max_local_z_length"));
}
SpfftProcessingUnitType Grid::processing_unit() const {
  return static_cast<SpfftProcessingUnitType>(
      detail::grid_attr(state_, "processing_unit"));
}
int Grid::device_id() const {
  return static_cast<int>(detail::grid_attr(state_, "device_id"));
}
int Grid::max_num_threads() const {
  return static_cast<int>(detail::grid_attr(state_, "max_num_threads"));
}
int Grid::num_shards() const {
  return static_cast<int>(detail::grid_attr(state_, "num_shards"));
}

DistributedTransform Grid::create_transform_distributed(
    SpfftProcessingUnitType processing_unit, SpfftTransformType transform_type,
    int dim_x, int dim_y, int dim_z, int num_shards, const int* shard_num_elements,
    SpfftIndexFormatType index_format, const int* indices,
    bool double_precision) const {
  return DistributedTransform(detail::make_dist_plan(
      *this, double_precision, processing_unit, transform_type, dim_x, dim_y, dim_z,
      num_shards, shard_num_elements, index_format, indices));
}

Transform Grid::create_transform(SpfftProcessingUnitType processing_unit,
                                 SpfftTransformType transform_type, int dim_x, int dim_y,
                                 int dim_z, int local_z_length, int num_local_elements,
                                 SpfftIndexFormatType index_format,
                                 const int* indices) const {
  return Transform(detail::make_plan(this, true, processing_unit, transform_type, dim_x,
                                     dim_y, dim_z, local_z_length, num_local_elements,
                                     index_format, indices));
}

TransformFloat Grid::create_transform_float(SpfftProcessingUnitType processing_unit,
                                            SpfftTransformType transform_type, int dim_x,
                                            int dim_y, int dim_z, int local_z_length,
                                            int num_local_elements,
                                            SpfftIndexFormatType index_format,
                                            const int* indices) const {
  return TransformFloat(detail::make_plan(this, false, processing_unit, transform_type,
                                          dim_x, dim_y, dim_z, local_z_length,
                                          num_local_elements, index_format, indices));
}

/* ---- Transform (double) --------------------------------------------------- */

Transform::Transform(SpfftProcessingUnitType processing_unit,
                     SpfftTransformType transform_type, int dim_x, int dim_y, int dim_z,
                     int num_local_elements, SpfftIndexFormatType index_format,
                     const int* indices)
    : plan_(detail::make_plan(nullptr, true, processing_unit, transform_type, dim_x,
                              dim_y, dim_z, 0, num_local_elements, index_format,
                              indices)) {}

Transform Transform::clone() const { return Transform(detail::clone_plan(plan_)); }

void Transform::backward(const double* input, SpfftProcessingUnitType) {
  plan_->backward(input);
}

void Transform::backward(const double* input, double* output) {
  plan_->backward(input);
  std::memcpy(output, plan_->space.data(), plan_->space.size());
}

void Transform::forward(SpfftProcessingUnitType, double* output,
                        SpfftScalingType scaling) {
  plan_->forward(plan_->space.data(), output, static_cast<int>(scaling));
}

void Transform::forward(const double* input, double* output, SpfftScalingType scaling) {
  plan_->forward(input, output, static_cast<int>(scaling));
}

double* Transform::space_domain_data(SpfftProcessingUnitType) {
  return reinterpret_cast<double*>(plan_->space.data());
}

SpfftTransformType Transform::type() const {
  return static_cast<SpfftTransformType>(plan_->meta.transform_type);
}
int Transform::dim_x() const { return plan_->meta.dim_x; }
int Transform::dim_y() const { return plan_->meta.dim_y; }
int Transform::dim_z() const { return plan_->meta.dim_z; }
int Transform::local_z_length() const { return plan_->meta.local_z_length; }
int Transform::local_z_offset() const { return plan_->meta.local_z_offset; }
long long Transform::local_slice_size() const { return plan_->meta.local_slice_size; }
long long Transform::num_local_elements() const { return plan_->num_values; }
long long Transform::num_global_elements() const {
  return plan_->meta.num_global_elements;
}
long long Transform::global_size() const { return plan_->meta.global_size; }
SpfftProcessingUnitType Transform::processing_unit() const {
  return static_cast<SpfftProcessingUnitType>(plan_->meta.processing_unit);
}
int Transform::device_id() const { return plan_->meta.device_id; }
int Transform::num_threads() const { return plan_->meta.num_threads; }
SpfftExecType Transform::execution_mode() const {
  return static_cast<SpfftExecType>(plan_->get("execution_mode"));
}
void Transform::set_execution_mode(SpfftExecType mode) {
  plan_->set_execution_mode(static_cast<int>(mode));
}

/* ---- TransformFloat ------------------------------------------------------- */

TransformFloat::TransformFloat(SpfftProcessingUnitType processing_unit,
                               SpfftTransformType transform_type, int dim_x, int dim_y,
                               int dim_z, int num_local_elements,
                               SpfftIndexFormatType index_format, const int* indices)
    : plan_(detail::make_plan(nullptr, false, processing_unit, transform_type, dim_x,
                              dim_y, dim_z, 0, num_local_elements, index_format,
                              indices)) {}

TransformFloat TransformFloat::clone() const {
  return TransformFloat(detail::clone_plan(plan_));
}

void TransformFloat::backward(const float* input, float* output) {
  plan_->backward(input);
  std::memcpy(output, plan_->space.data(), plan_->space.size());
}

void TransformFloat::backward(const float* input, SpfftProcessingUnitType) {
  plan_->backward(input);
}

void TransformFloat::forward(SpfftProcessingUnitType, float* output,
                             SpfftScalingType scaling) {
  plan_->forward(plan_->space.data(), output, static_cast<int>(scaling));
}

void TransformFloat::forward(const float* input, float* output,
                             SpfftScalingType scaling) {
  plan_->forward(input, output, static_cast<int>(scaling));
}

float* TransformFloat::space_domain_data(SpfftProcessingUnitType) {
  return reinterpret_cast<float*>(plan_->space.data());
}

SpfftTransformType TransformFloat::type() const {
  return static_cast<SpfftTransformType>(plan_->meta.transform_type);
}
int TransformFloat::dim_x() const { return plan_->meta.dim_x; }
int TransformFloat::dim_y() const { return plan_->meta.dim_y; }
int TransformFloat::dim_z() const { return plan_->meta.dim_z; }
int TransformFloat::local_z_length() const { return plan_->meta.local_z_length; }
int TransformFloat::local_z_offset() const { return plan_->meta.local_z_offset; }
long long TransformFloat::local_slice_size() const {
  return plan_->meta.local_slice_size;
}
long long TransformFloat::num_local_elements() const { return plan_->num_values; }
long long TransformFloat::num_global_elements() const {
  return plan_->meta.num_global_elements;
}
long long TransformFloat::global_size() const { return plan_->meta.global_size; }
SpfftProcessingUnitType TransformFloat::processing_unit() const {
  return static_cast<SpfftProcessingUnitType>(plan_->meta.processing_unit);
}
int TransformFloat::device_id() const { return plan_->meta.device_id; }
int TransformFloat::num_threads() const { return plan_->meta.num_threads; }
SpfftExecType TransformFloat::execution_mode() const {
  return static_cast<SpfftExecType>(plan_->get("execution_mode"));
}
void TransformFloat::set_execution_mode(SpfftExecType mode) {
  plan_->set_execution_mode(static_cast<int>(mode));
}

/* ---- multi-transform ------------------------------------------------------ */

namespace {

/* space_override[i], when non-null, replaces transform i's internal space
 * buffer as the space-domain side (the reference's pointer-based overloads,
 * multi_transform.hpp:64-95); byte count matches the internal buffer. */
template <typename TransformT>
void multi_backward_impl(int n, TransformT* transforms, const void* const* input,
                         void* const* space_override = nullptr) {
  bridge::Gil gil;
  bridge::Ref transform_list(bridge::checked(PyList_New(n)));
  bridge::Ref inputs(bridge::checked(PyList_New(n)));
  bridge::Ref outputs(bridge::checked(PyList_New(n)));
  for (int i = 0; i < n; ++i) {
    detail::Plan* p = detail::plan_of(transforms[i]);
    Py_INCREF(p->py.get());
    PyList_SET_ITEM(transform_list.get(), i, p->py.get());
    bridge::Ref in = bridge::view_ro(
        input[i], static_cast<std::size_t>(2 * p->num_values) * p->elem_bytes());
    PyList_SET_ITEM(inputs.get(), i, in.release());
    void* space = space_override ? space_override[i] : p->space.data();
    bridge::Ref out = bridge::view_rw(space, p->space.size());
    PyList_SET_ITEM(outputs.get(), i, out.release());
  }
  bridge::call("multi_backward", Py_BuildValue("(OOO)", transform_list.get(),
                                               inputs.get(), outputs.get()));
}

template <typename TransformT>
void multi_forward_impl(int n, TransformT* transforms, void* const* output,
                        const SpfftScalingType* scaling_types,
                        const void* const* space_override = nullptr) {
  bridge::Gil gil;
  bridge::Ref transform_list(bridge::checked(PyList_New(n)));
  bridge::Ref spaces(bridge::checked(PyList_New(n)));
  bridge::Ref outputs(bridge::checked(PyList_New(n)));
  bridge::Ref scalings(bridge::checked(PyList_New(n)));
  for (int i = 0; i < n; ++i) {
    detail::Plan* p = detail::plan_of(transforms[i]);
    Py_INCREF(p->py.get());
    PyList_SET_ITEM(transform_list.get(), i, p->py.get());
    const void* space = space_override ? space_override[i] : p->space.data();
    bridge::Ref space_view = bridge::view_ro(space, p->space.size());
    PyList_SET_ITEM(spaces.get(), i, space_view.release());
    bridge::Ref out = bridge::view_rw(
        output[i], static_cast<std::size_t>(2 * p->num_values) * p->elem_bytes());
    PyList_SET_ITEM(outputs.get(), i, out.release());
    PyList_SET_ITEM(scalings.get(), i,
                    bridge::checked(PyLong_FromLong(
                        scaling_types ? static_cast<long>(scaling_types[i]) : 0)));
  }
  bridge::call("multi_forward", Py_BuildValue("(OOOO)", transform_list.get(),
                                              spaces.get(), outputs.get(),
                                              scalings.get()));
}

} // namespace

void multi_transform_backward(int num_transforms, Transform* transforms,
                              const double* const* input,
                              const SpfftProcessingUnitType*) {
  multi_backward_impl(num_transforms, transforms,
                      reinterpret_cast<const void* const*>(input));
}

void multi_transform_forward(int num_transforms, Transform* transforms,
                             const SpfftProcessingUnitType*, double* const* output,
                             const SpfftScalingType* scaling_types) {
  multi_forward_impl(num_transforms, transforms,
                     reinterpret_cast<void* const*>(const_cast<double**>(output)),
                     scaling_types);
}

void multi_transform_backward(int num_transforms, TransformFloat* transforms,
                              const float* const* input,
                              const SpfftProcessingUnitType*) {
  multi_backward_impl(num_transforms, transforms,
                      reinterpret_cast<const void* const*>(input));
}

void multi_transform_forward(int num_transforms, TransformFloat* transforms,
                             const SpfftProcessingUnitType*, float* const* output,
                             const SpfftScalingType* scaling_types) {
  multi_forward_impl(num_transforms, transforms,
                     reinterpret_cast<void* const*>(const_cast<float**>(output)),
                     scaling_types);
}

void multi_transform_backward(int num_transforms, Transform* transforms,
                              const double* const* input, double* const* space_output) {
  multi_backward_impl(num_transforms, transforms,
                      reinterpret_cast<const void* const*>(input),
                      reinterpret_cast<void* const*>(const_cast<double**>(space_output)));
}

void multi_transform_forward(int num_transforms, Transform* transforms,
                             const double* const* space_input, double* const* output,
                             const SpfftScalingType* scaling_types) {
  multi_forward_impl(num_transforms, transforms,
                     reinterpret_cast<void* const*>(const_cast<double**>(output)),
                     scaling_types,
                     reinterpret_cast<const void* const*>(space_input));
}

void multi_transform_backward(int num_transforms, TransformFloat* transforms,
                              const float* const* input, float* const* space_output) {
  multi_backward_impl(num_transforms, transforms,
                      reinterpret_cast<const void* const*>(input),
                      reinterpret_cast<void* const*>(const_cast<float**>(space_output)));
}

void multi_transform_forward(int num_transforms, TransformFloat* transforms,
                             const float* const* space_input, float* const* output,
                             const SpfftScalingType* scaling_types) {
  multi_forward_impl(num_transforms, transforms,
                     reinterpret_cast<void* const*>(const_cast<float**>(output)),
                     scaling_types,
                     reinterpret_cast<const void* const*>(space_input));
}

/* ---- DistributedTransform ------------------------------------------------- */

void DistributedTransform::backward(const double* values, double* space_output) {
  plan_->check_precision(true);
  plan_->backward(values, space_output);
}
void DistributedTransform::backward(const float* values, float* space_output) {
  plan_->check_precision(false);
  plan_->backward(values, space_output);
}
void DistributedTransform::forward(const double* space, double* values_output,
                                   SpfftScalingType scaling) {
  plan_->check_precision(true);
  plan_->forward(space, values_output, static_cast<int>(scaling));
}
void DistributedTransform::forward(const float* space, float* values_output,
                                   SpfftScalingType scaling) {
  plan_->check_precision(false);
  plan_->forward(space, values_output, static_cast<int>(scaling));
}

SpfftTransformType DistributedTransform::type() const {
  return static_cast<SpfftTransformType>(plan_->meta.transform_type);
}
int DistributedTransform::dim_x() const { return plan_->meta.dim_x; }
int DistributedTransform::dim_y() const { return plan_->meta.dim_y; }
int DistributedTransform::dim_z() const { return plan_->meta.dim_z; }
int DistributedTransform::num_shards() const { return plan_->meta.num_shards; }
long long DistributedTransform::num_global_elements() const {
  return plan_->num_global;
}
long long DistributedTransform::global_size() const { return plan_->meta.global_size; }
SpfftProcessingUnitType DistributedTransform::processing_unit() const {
  return static_cast<SpfftProcessingUnitType>(plan_->meta.processing_unit);
}
SpfftExchangeType DistributedTransform::exchange_type() const {
  return static_cast<SpfftExchangeType>(plan_->meta.exchange_type);
}
long long DistributedTransform::exchange_wire_bytes() const {
  return plan_->meta.wire_bytes;
}
int DistributedTransform::exchange_rounds() const {
  return plan_->meta.exchange_rounds;
}
bool DistributedTransform::double_precision() const { return plan_->dbl; }

int DistributedTransform::local_z_length(int shard) const {
  plan_->check_shard(shard);
  return static_cast<int>(plan_->shard_zlen[shard]);
}
int DistributedTransform::local_z_offset(int shard) const {
  plan_->check_shard(shard);
  return static_cast<int>(plan_->shard_zoff[shard]);
}
int DistributedTransform::local_y_length(int shard) const {
  plan_->check_shard(shard);
  return static_cast<int>(plan_->shard_ylen[shard]);
}
int DistributedTransform::local_y_offset(int shard) const {
  plan_->check_shard(shard);
  return static_cast<int>(plan_->shard_yoff[shard]);
}
long long DistributedTransform::local_slice_size(int shard) const {
  plan_->check_shard(shard);
  return plan_->shard_slice[shard];
}
long long DistributedTransform::num_local_elements(int shard) const {
  plan_->check_shard(shard);
  return plan_->shard_elems[shard];
}

} // namespace spfft
