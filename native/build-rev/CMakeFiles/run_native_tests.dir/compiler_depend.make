# Empty compiler generated dependencies file for run_native_tests.
# This may be replaced when dependencies are built.
