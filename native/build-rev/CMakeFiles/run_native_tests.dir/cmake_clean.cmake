file(REMOVE_RECURSE
  "CMakeFiles/run_native_tests.dir/tests/test_api.c.o"
  "CMakeFiles/run_native_tests.dir/tests/test_api.c.o.d"
  "run_native_tests"
  "run_native_tests.pdb"
)

# Per-language clean rules from dependency scanning.
foreach(lang C)
  include(CMakeFiles/run_native_tests.dir/cmake_clean_${lang}.cmake OPTIONAL)
endforeach()
