file(REMOVE_RECURSE
  "CMakeFiles/spfft_tpu_benchmark.dir/programs/benchmark.c.o"
  "CMakeFiles/spfft_tpu_benchmark.dir/programs/benchmark.c.o.d"
  "spfft_tpu_benchmark"
  "spfft_tpu_benchmark.pdb"
)

# Per-language clean rules from dependency scanning.
foreach(lang C)
  include(CMakeFiles/spfft_tpu_benchmark.dir/cmake_clean_${lang}.cmake OPTIONAL)
endforeach()
