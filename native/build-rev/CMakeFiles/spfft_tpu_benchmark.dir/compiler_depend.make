# Empty compiler generated dependencies file for spfft_tpu_benchmark.
# This may be replaced when dependencies are built.
