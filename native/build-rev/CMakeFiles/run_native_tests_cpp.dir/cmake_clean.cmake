file(REMOVE_RECURSE
  "CMakeFiles/run_native_tests_cpp.dir/tests/test_api_cpp.cpp.o"
  "CMakeFiles/run_native_tests_cpp.dir/tests/test_api_cpp.cpp.o.d"
  "run_native_tests_cpp"
  "run_native_tests_cpp.pdb"
)

# Per-language clean rules from dependency scanning.
foreach(lang CXX)
  include(CMakeFiles/run_native_tests_cpp.dir/cmake_clean_${lang}.cmake OPTIONAL)
endforeach()
