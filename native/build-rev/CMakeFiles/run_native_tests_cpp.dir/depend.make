# Empty dependencies file for run_native_tests_cpp.
# This may be replaced when dependencies are built.
