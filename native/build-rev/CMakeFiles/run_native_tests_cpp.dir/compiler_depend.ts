# CMAKE generated file: DO NOT EDIT!
# Timestamp file for compiler generated dependencies management for run_native_tests_cpp.
