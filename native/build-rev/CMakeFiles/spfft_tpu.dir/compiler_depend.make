# Empty compiler generated dependencies file for spfft_tpu.
# This may be replaced when dependencies are built.
