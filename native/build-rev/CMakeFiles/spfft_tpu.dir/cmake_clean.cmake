file(REMOVE_RECURSE
  "CMakeFiles/spfft_tpu.dir/src/bridge.cpp.o"
  "CMakeFiles/spfft_tpu.dir/src/bridge.cpp.o.d"
  "CMakeFiles/spfft_tpu.dir/src/capi_c.cpp.o"
  "CMakeFiles/spfft_tpu.dir/src/capi_c.cpp.o.d"
  "CMakeFiles/spfft_tpu.dir/src/spfft.cpp.o"
  "CMakeFiles/spfft_tpu.dir/src/spfft.cpp.o.d"
  "libspfft_tpu.pdb"
  "libspfft_tpu.so"
  "libspfft_tpu.so.0"
  "libspfft_tpu.so.0.3.0"
)

# Per-language clean rules from dependency scanning.
foreach(lang CXX)
  include(CMakeFiles/spfft_tpu.dir/cmake_clean_${lang}.cmake OPTIONAL)
endforeach()
