#----------------------------------------------------------------
# Generated CMake target import file for configuration "Release".
#----------------------------------------------------------------

# Commands may need to know the format version.
set(CMAKE_IMPORT_FILE_VERSION 1)

# Import target "SpFFTTPU::spfft_tpu" for configuration "Release"
set_property(TARGET SpFFTTPU::spfft_tpu APPEND PROPERTY IMPORTED_CONFIGURATIONS RELEASE)
set_target_properties(SpFFTTPU::spfft_tpu PROPERTIES
  IMPORTED_LINK_DEPENDENT_LIBRARIES_RELEASE "Python3::Python"
  IMPORTED_LOCATION_RELEASE "${_IMPORT_PREFIX}/lib/libspfft_tpu.so.0.3.0"
  IMPORTED_SONAME_RELEASE "libspfft_tpu.so.0"
  )

list(APPEND _cmake_import_check_targets SpFFTTPU::spfft_tpu )
list(APPEND _cmake_import_check_files_for_SpFFTTPU::spfft_tpu "${_IMPORT_PREFIX}/lib/libspfft_tpu.so.0.3.0" )

# Commands beyond this point should not need to know the version.
set(CMAKE_IMPORT_FILE_VERSION)
