# Config for the installed spfft_tpu package: find_package(SpFFTTPU) gives the
# SpFFTTPU::spfft_tpu imported target — the role SpFFTConfig.cmake plays for
# the reference library (reference: cmake/SpFFTConfig.cmake).

####### Expanded from @PACKAGE_INIT@ by configure_package_config_file() #######
####### Any changes to this file will be overwritten by the next CMake run ####
####### The input file was SpFFTTPUConfig.cmake.in                            ########

get_filename_component(PACKAGE_PREFIX_DIR "${CMAKE_CURRENT_LIST_DIR}/../../../" ABSOLUTE)

macro(set_and_check _var _file)
  set(${_var} "${_file}")
  if(NOT EXISTS "${_file}")
    message(FATAL_ERROR "File or directory ${_file} referenced by variable ${_var} does not exist !")
  endif()
endmacro()

macro(check_required_components _NAME)
  foreach(comp ${${_NAME}_FIND_COMPONENTS})
    if(NOT ${_NAME}_${comp}_FOUND)
      if(${_NAME}_FIND_REQUIRED_${comp})
        set(${_NAME}_FOUND FALSE)
      endif()
    endif()
  endforeach()
endmacro()

####################################################################################

include("${CMAKE_CURRENT_LIST_DIR}/SpFFTTPUTargets.cmake")
check_required_components(SpFFTTPU)
