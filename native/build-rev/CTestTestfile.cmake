# CMake generated Testfile for 
# Source directory: /root/repo/native
# Build directory: /root/repo/native/build-rev
# 
# This file includes the relevant testing commands required for 
# testing this directory and lists subdirectories to be tested as well.
add_test(native_api "/root/repo/native/build-rev/run_native_tests")
set_tests_properties(native_api PROPERTIES  _BACKTRACE_TRIPLES "/root/repo/native/CMakeLists.txt;37;add_test;/root/repo/native/CMakeLists.txt;0;")
add_test(native_api_cpp "/root/repo/native/build-rev/run_native_tests_cpp")
set_tests_properties(native_api_cpp PROPERTIES  _BACKTRACE_TRIPLES "/root/repo/native/CMakeLists.txt;40;add_test;/root/repo/native/CMakeLists.txt;0;")
