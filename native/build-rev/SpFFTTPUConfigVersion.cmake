# This is a basic version file for the Config-mode of find_package().
# It is used by write_basic_package_version_file() as input file for configure_file()
# to create a version-file which can be installed along a config.cmake file.
#
# The created file sets PACKAGE_VERSION_EXACT if the current version string and
# the requested version string are exactly the same and it sets
# PACKAGE_VERSION_COMPATIBLE if the current version is >= requested version,
# but only if the requested major version is the same as the current one.
# The variable CVF_VERSION must be set before calling configure_file().


set(PACKAGE_VERSION "0.3.0")

if(PACKAGE_VERSION VERSION_LESS PACKAGE_FIND_VERSION)
  set(PACKAGE_VERSION_COMPATIBLE FALSE)
else()

  if("0.3.0" MATCHES "^([0-9]+)\\.")
    set(CVF_VERSION_MAJOR "${CMAKE_MATCH_1}")
    if(NOT CVF_VERSION_MAJOR VERSION_EQUAL 0)
      string(REGEX REPLACE "^0+" "" CVF_VERSION_MAJOR "${CVF_VERSION_MAJOR}")
    endif()
  else()
    set(CVF_VERSION_MAJOR "0.3.0")
  endif()

  if(PACKAGE_FIND_VERSION_RANGE)
    # both endpoints of the range must have the expected major version
    math (EXPR CVF_VERSION_MAJOR_NEXT "${CVF_VERSION_MAJOR} + 1")
    if (NOT PACKAGE_FIND_VERSION_MIN_MAJOR STREQUAL CVF_VERSION_MAJOR
        OR ((PACKAGE_FIND_VERSION_RANGE_MAX STREQUAL "INCLUDE" AND NOT PACKAGE_FIND_VERSION_MAX_MAJOR STREQUAL CVF_VERSION_MAJOR)
          OR (PACKAGE_FIND_VERSION_RANGE_MAX STREQUAL "EXCLUDE" AND NOT PACKAGE_FIND_VERSION_MAX VERSION_LESS_EQUAL CVF_VERSION_MAJOR_NEXT)))
      set(PACKAGE_VERSION_COMPATIBLE FALSE)
    elseif(PACKAGE_FIND_VERSION_MIN_MAJOR STREQUAL CVF_VERSION_MAJOR
        AND ((PACKAGE_FIND_VERSION_RANGE_MAX STREQUAL "INCLUDE" AND PACKAGE_VERSION VERSION_LESS_EQUAL PACKAGE_FIND_VERSION_MAX)
        OR (PACKAGE_FIND_VERSION_RANGE_MAX STREQUAL "EXCLUDE" AND PACKAGE_VERSION VERSION_LESS PACKAGE_FIND_VERSION_MAX)))
      set(PACKAGE_VERSION_COMPATIBLE TRUE)
    else()
      set(PACKAGE_VERSION_COMPATIBLE FALSE)
    endif()
  else()
    if(PACKAGE_FIND_VERSION_MAJOR STREQUAL CVF_VERSION_MAJOR)
      set(PACKAGE_VERSION_COMPATIBLE TRUE)
    else()
      set(PACKAGE_VERSION_COMPATIBLE FALSE)
    endif()

    if(PACKAGE_FIND_VERSION STREQUAL PACKAGE_VERSION)
      set(PACKAGE_VERSION_EXACT TRUE)
    endif()
  endif()
endif()


# if the installed project requested no architecture check, don't perform the check
if("FALSE")
  return()
endif()

# if the installed or the using project don't have CMAKE_SIZEOF_VOID_P set, ignore it:
if("${CMAKE_SIZEOF_VOID_P}" STREQUAL "" OR "8" STREQUAL "")
  return()
endif()

# check that the installed version has the same 32/64bit-ness as the one which is currently searching:
if(NOT CMAKE_SIZEOF_VOID_P STREQUAL "8")
  math(EXPR installedBits "8 * 8")
  set(PACKAGE_VERSION "${PACKAGE_VERSION} (${installedBits}bit)")
  set(PACKAGE_VERSION_UNSUITABLE TRUE)
endif()
