# Install script for directory: /root/repo/native

# Set the install prefix
if(NOT DEFINED CMAKE_INSTALL_PREFIX)
  set(CMAKE_INSTALL_PREFIX "/usr/local")
endif()
string(REGEX REPLACE "/$" "" CMAKE_INSTALL_PREFIX "${CMAKE_INSTALL_PREFIX}")

# Set the install configuration name.
if(NOT DEFINED CMAKE_INSTALL_CONFIG_NAME)
  if(BUILD_TYPE)
    string(REGEX REPLACE "^[^A-Za-z0-9_]+" ""
           CMAKE_INSTALL_CONFIG_NAME "${BUILD_TYPE}")
  else()
    set(CMAKE_INSTALL_CONFIG_NAME "Release")
  endif()
  message(STATUS "Install configuration: \"${CMAKE_INSTALL_CONFIG_NAME}\"")
endif()

# Set the component getting installed.
if(NOT CMAKE_INSTALL_COMPONENT)
  if(COMPONENT)
    message(STATUS "Install component: \"${COMPONENT}\"")
    set(CMAKE_INSTALL_COMPONENT "${COMPONENT}")
  else()
    set(CMAKE_INSTALL_COMPONENT)
  endif()
endif()

# Install shared libraries without execute permission?
if(NOT DEFINED CMAKE_INSTALL_SO_NO_EXE)
  set(CMAKE_INSTALL_SO_NO_EXE "1")
endif()

# Is this installation the result of a crosscompile?
if(NOT DEFINED CMAKE_CROSSCOMPILING)
  set(CMAKE_CROSSCOMPILING "FALSE")
endif()

# Set default install directory permissions.
if(NOT DEFINED CMAKE_OBJDUMP)
  set(CMAKE_OBJDUMP "/usr/bin/objdump")
endif()

if(CMAKE_INSTALL_COMPONENT STREQUAL "Unspecified" OR NOT CMAKE_INSTALL_COMPONENT)
  foreach(file
      "$ENV{DESTDIR}${CMAKE_INSTALL_PREFIX}/lib/libspfft_tpu.so.0.3.0"
      "$ENV{DESTDIR}${CMAKE_INSTALL_PREFIX}/lib/libspfft_tpu.so.0"
      )
    if(EXISTS "${file}" AND
       NOT IS_SYMLINK "${file}")
      file(RPATH_CHECK
           FILE "${file}"
           RPATH "")
    endif()
  endforeach()
  file(INSTALL DESTINATION "${CMAKE_INSTALL_PREFIX}/lib" TYPE SHARED_LIBRARY FILES
    "/root/repo/native/build-rev/libspfft_tpu.so.0.3.0"
    "/root/repo/native/build-rev/libspfft_tpu.so.0"
    )
  foreach(file
      "$ENV{DESTDIR}${CMAKE_INSTALL_PREFIX}/lib/libspfft_tpu.so.0.3.0"
      "$ENV{DESTDIR}${CMAKE_INSTALL_PREFIX}/lib/libspfft_tpu.so.0"
      )
    if(EXISTS "${file}" AND
       NOT IS_SYMLINK "${file}")
      file(RPATH_CHANGE
           FILE "${file}"
           OLD_RPATH "/usr/local/lib:"
           NEW_RPATH "")
      if(CMAKE_INSTALL_DO_STRIP)
        execute_process(COMMAND "/usr/bin/strip" "${file}")
      endif()
    endif()
  endforeach()
endif()

if(CMAKE_INSTALL_COMPONENT STREQUAL "Unspecified" OR NOT CMAKE_INSTALL_COMPONENT)
  if(EXISTS "$ENV{DESTDIR}${CMAKE_INSTALL_PREFIX}/lib/libspfft_tpu.so" AND
     NOT IS_SYMLINK "$ENV{DESTDIR}${CMAKE_INSTALL_PREFIX}/lib/libspfft_tpu.so")
    file(RPATH_CHECK
         FILE "$ENV{DESTDIR}${CMAKE_INSTALL_PREFIX}/lib/libspfft_tpu.so"
         RPATH "")
  endif()
  file(INSTALL DESTINATION "${CMAKE_INSTALL_PREFIX}/lib" TYPE SHARED_LIBRARY FILES "/root/repo/native/build-rev/libspfft_tpu.so")
  if(EXISTS "$ENV{DESTDIR}${CMAKE_INSTALL_PREFIX}/lib/libspfft_tpu.so" AND
     NOT IS_SYMLINK "$ENV{DESTDIR}${CMAKE_INSTALL_PREFIX}/lib/libspfft_tpu.so")
    file(RPATH_CHANGE
         FILE "$ENV{DESTDIR}${CMAKE_INSTALL_PREFIX}/lib/libspfft_tpu.so"
         OLD_RPATH "/usr/local/lib:"
         NEW_RPATH "")
    if(CMAKE_INSTALL_DO_STRIP)
      execute_process(COMMAND "/usr/bin/strip" "$ENV{DESTDIR}${CMAKE_INSTALL_PREFIX}/lib/libspfft_tpu.so")
    endif()
  endif()
endif()

if(CMAKE_INSTALL_COMPONENT STREQUAL "Unspecified" OR NOT CMAKE_INSTALL_COMPONENT)
  file(INSTALL DESTINATION "${CMAKE_INSTALL_PREFIX}/include" TYPE DIRECTORY FILES "/root/repo/native/include/spfft")
endif()

if(CMAKE_INSTALL_COMPONENT STREQUAL "Unspecified" OR NOT CMAKE_INSTALL_COMPONENT)
  if(EXISTS "$ENV{DESTDIR}${CMAKE_INSTALL_PREFIX}/lib/cmake/SpFFTTPU/SpFFTTPUTargets.cmake")
    file(DIFFERENT _cmake_export_file_changed FILES
         "$ENV{DESTDIR}${CMAKE_INSTALL_PREFIX}/lib/cmake/SpFFTTPU/SpFFTTPUTargets.cmake"
         "/root/repo/native/build-rev/CMakeFiles/Export/be2de7377dd48d357aa543b247146d6b/SpFFTTPUTargets.cmake")
    if(_cmake_export_file_changed)
      file(GLOB _cmake_old_config_files "$ENV{DESTDIR}${CMAKE_INSTALL_PREFIX}/lib/cmake/SpFFTTPU/SpFFTTPUTargets-*.cmake")
      if(_cmake_old_config_files)
        string(REPLACE ";" ", " _cmake_old_config_files_text "${_cmake_old_config_files}")
        message(STATUS "Old export file \"$ENV{DESTDIR}${CMAKE_INSTALL_PREFIX}/lib/cmake/SpFFTTPU/SpFFTTPUTargets.cmake\" will be replaced.  Removing files [${_cmake_old_config_files_text}].")
        unset(_cmake_old_config_files_text)
        file(REMOVE ${_cmake_old_config_files})
      endif()
      unset(_cmake_old_config_files)
    endif()
    unset(_cmake_export_file_changed)
  endif()
  file(INSTALL DESTINATION "${CMAKE_INSTALL_PREFIX}/lib/cmake/SpFFTTPU" TYPE FILE FILES "/root/repo/native/build-rev/CMakeFiles/Export/be2de7377dd48d357aa543b247146d6b/SpFFTTPUTargets.cmake")
  if(CMAKE_INSTALL_CONFIG_NAME MATCHES "^([Rr][Ee][Ll][Ee][Aa][Ss][Ee])$")
    file(INSTALL DESTINATION "${CMAKE_INSTALL_PREFIX}/lib/cmake/SpFFTTPU" TYPE FILE FILES "/root/repo/native/build-rev/CMakeFiles/Export/be2de7377dd48d357aa543b247146d6b/SpFFTTPUTargets-release.cmake")
  endif()
endif()

if(CMAKE_INSTALL_COMPONENT STREQUAL "Unspecified" OR NOT CMAKE_INSTALL_COMPONENT)
  file(INSTALL DESTINATION "${CMAKE_INSTALL_PREFIX}/lib/cmake/SpFFTTPU" TYPE FILE FILES
    "/root/repo/native/build-rev/SpFFTTPUConfig.cmake"
    "/root/repo/native/build-rev/SpFFTTPUConfigVersion.cmake"
    )
endif()

if(CMAKE_INSTALL_COMPONENT STREQUAL "Unspecified" OR NOT CMAKE_INSTALL_COMPONENT)
  file(INSTALL DESTINATION "${CMAKE_INSTALL_PREFIX}/lib/pkgconfig" TYPE FILE FILES "/root/repo/native/build-rev/spfft_tpu.pc")
endif()

if(CMAKE_INSTALL_COMPONENT)
  set(CMAKE_INSTALL_MANIFEST "install_manifest_${CMAKE_INSTALL_COMPONENT}.txt")
else()
  set(CMAKE_INSTALL_MANIFEST "install_manifest.txt")
endif()

string(REPLACE ";" "\n" CMAKE_INSTALL_MANIFEST_CONTENT
       "${CMAKE_INSTALL_MANIFEST_FILES}")
file(WRITE "/root/repo/native/build-rev/${CMAKE_INSTALL_MANIFEST}"
     "${CMAKE_INSTALL_MANIFEST_CONTENT}")
