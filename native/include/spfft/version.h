/*
 * spfft_tpu version macros — the compile-time version surface consumers can
 * feature-test against (the reference exposes its version through CMake's
 * PROJECT_VERSION in SpFFT.pc / SpFFTConfigVersion.cmake; these macros make
 * it available to the preprocessor as well). Keep in sync with the VERSION in
 * native/CMakeLists.txt.
 */
#ifndef SPFFT_TPU_VERSION_H
#define SPFFT_TPU_VERSION_H

#define SPFFT_TPU_VERSION_MAJOR 0
#define SPFFT_TPU_VERSION_MINOR 3
#define SPFFT_TPU_VERSION_PATCH 0
#define SPFFT_TPU_VERSION_STRING "0.3.0"

#endif
