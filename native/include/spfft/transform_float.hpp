/*
 * spfft_tpu native API — single-precision C++ Transform
 * (reference: include/spfft/transform_float.hpp).
 *
 * spfft::TransformFloat is declared alongside spfft::Transform in
 * transform.hpp; this header exists so callers that include
 * <spfft/transform_float.hpp> directly compile unchanged.
 */
#ifndef SPFFT_TPU_TRANSFORM_FLOAT_HPP
#define SPFFT_TPU_TRANSFORM_FLOAT_HPP

#include <spfft/transform.hpp>

#endif /* SPFFT_TPU_TRANSFORM_FLOAT_HPP */
