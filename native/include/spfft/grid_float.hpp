/*
 * spfft_tpu native API — single-precision C++ Grid
 * (reference: include/spfft/grid_float.hpp).
 *
 * spfft::GridFloat is a typedef of spfft::Grid in this build (grid.hpp); this
 * header exists so callers that include <spfft/grid_float.hpp> directly
 * compile unchanged.
 */
#ifndef SPFFT_TPU_GRID_FLOAT_HPP
#define SPFFT_TPU_GRID_FLOAT_HPP

#include <spfft/grid.hpp>

#endif /* SPFFT_TPU_GRID_FLOAT_HPP */
