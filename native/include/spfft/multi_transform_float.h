/*
 * spfft_tpu native API — single-precision C multi-transform interface
 * (reference: include/spfft/multi_transform_float.h).
 *
 * The spfft_float_multi_transform_* surface is declared alongside the double
 * tier in multi_transform.h; this header exists so callers that include
 * <spfft/multi_transform_float.h> directly compile unchanged.
 */
#ifndef SPFFT_TPU_MULTI_TRANSFORM_FLOAT_H
#define SPFFT_TPU_MULTI_TRANSFORM_FLOAT_H

#include <spfft/multi_transform.h>

#endif /* SPFFT_TPU_MULTI_TRANSFORM_FLOAT_H */
