/*
 * spfft_tpu native API — C++ exception hierarchy.
 *
 * One exception class per SpfftError value (reference:
 * include/spfft/exceptions.hpp:40-306 has the same shape). The C API catches
 * GenericError and returns error_code(); unknown exceptions become
 * SPFFT_UNKNOWN_ERROR.
 */
#ifndef SPFFT_TPU_EXCEPTIONS_HPP
#define SPFFT_TPU_EXCEPTIONS_HPP

#include <spfft/errors.h>

#include <stdexcept>
#include <string>

namespace spfft {

class GenericError : public std::exception {
public:
  explicit GenericError(std::string msg = "spfft_tpu: error") : msg_(std::move(msg)) {}

  const char* what() const noexcept override { return msg_.c_str(); }

  virtual SpfftError error_code() const noexcept { return SPFFT_UNKNOWN_ERROR; }

private:
  std::string msg_;
};

#define SPFFT_TPU_DEFINE_ERROR(NAME, CODE, DEFAULT_MSG)                                  \
  class NAME : public GenericError {                                                     \
  public:                                                                                \
    explicit NAME(std::string msg = DEFAULT_MSG) : GenericError(std::move(msg)) {}       \
    SpfftError error_code() const noexcept override { return CODE; }                     \
  };

SPFFT_TPU_DEFINE_ERROR(InvalidHandleError, SPFFT_INVALID_HANDLE_ERROR,
                       "spfft_tpu: invalid handle")
SPFFT_TPU_DEFINE_ERROR(OverflowError, SPFFT_OVERFLOW_ERROR, "spfft_tpu: overflow")
SPFFT_TPU_DEFINE_ERROR(HostAllocationError, SPFFT_ALLOCATION_ERROR,
                       "spfft_tpu: allocation failed")
SPFFT_TPU_DEFINE_ERROR(InvalidParameterError, SPFFT_INVALID_PARAMETER_ERROR,
                       "spfft_tpu: invalid parameter")
SPFFT_TPU_DEFINE_ERROR(DuplicateIndicesError, SPFFT_DUPLICATE_INDICES_ERROR,
                       "spfft_tpu: duplicate indices")
SPFFT_TPU_DEFINE_ERROR(InvalidIndicesError, SPFFT_INVALID_INDICES_ERROR,
                       "spfft_tpu: invalid indices")
SPFFT_TPU_DEFINE_ERROR(MPISupportError, SPFFT_MPI_SUPPORT_ERROR,
                       "spfft_tpu: distributed support unavailable")
SPFFT_TPU_DEFINE_ERROR(MPIError, SPFFT_MPI_ERROR, "spfft_tpu: collective backend error")
SPFFT_TPU_DEFINE_ERROR(MPIParameterMismatchError, SPFFT_MPI_PARAMETER_MISMATCH_ERROR,
                       "spfft_tpu: cross-shard parameter mismatch")
SPFFT_TPU_DEFINE_ERROR(HostExecutionError, SPFFT_HOST_EXECUTION_ERROR,
                       "spfft_tpu: host execution failed")
SPFFT_TPU_DEFINE_ERROR(FFTWError, SPFFT_FFTW_ERROR, "spfft_tpu: host FFT backend error")
SPFFT_TPU_DEFINE_ERROR(GPUError, SPFFT_GPU_ERROR, "spfft_tpu: accelerator error")
SPFFT_TPU_DEFINE_ERROR(GPUPrecedingError, SPFFT_GPU_PRECEDING_ERROR,
                       "spfft_tpu: preceding accelerator error")
SPFFT_TPU_DEFINE_ERROR(GPUSupportError, SPFFT_GPU_SUPPORT_ERROR,
                       "spfft_tpu: accelerator support unavailable")
SPFFT_TPU_DEFINE_ERROR(GPUAllocationError, SPFFT_GPU_ALLOCATION_ERROR,
                       "spfft_tpu: accelerator allocation failed")
SPFFT_TPU_DEFINE_ERROR(GPULaunchError, SPFFT_GPU_LAUNCH_ERROR,
                       "spfft_tpu: accelerator launch failed")
SPFFT_TPU_DEFINE_ERROR(GPUNoDeviceError, SPFFT_GPU_NO_DEVICE_ERROR,
                       "spfft_tpu: no accelerator device")
SPFFT_TPU_DEFINE_ERROR(GPUInvalidValueError, SPFFT_GPU_INVALID_VALUE_ERROR,
                       "spfft_tpu: invalid accelerator value")
SPFFT_TPU_DEFINE_ERROR(GPUInvalidDevicePointerError, SPFFT_GPU_INVALID_DEVICE_PTR_ERROR,
                       "spfft_tpu: invalid device pointer")
SPFFT_TPU_DEFINE_ERROR(GPUCopyError, SPFFT_GPU_COPY_ERROR, "spfft_tpu: device copy failed")
SPFFT_TPU_DEFINE_ERROR(GPUFFTError, SPFFT_GPU_FFT_ERROR,
                       "spfft_tpu: accelerator FFT error")
SPFFT_TPU_DEFINE_ERROR(VerificationError, SPFFT_VERIFICATION_ERROR,
                       "spfft_tpu: self-verification failed, recovery exhausted")
SPFFT_TPU_DEFINE_ERROR(ServiceOverloadError, SPFFT_SERVICE_OVERLOAD_ERROR,
                       "spfft_tpu: service overloaded, admission refused")
SPFFT_TPU_DEFINE_ERROR(DeadlineExceededError, SPFFT_DEADLINE_EXCEEDED_ERROR,
                       "spfft_tpu: request deadline exceeded")
SPFFT_TPU_DEFINE_ERROR(HostLostError, SPFFT_HOST_LOST_ERROR,
                       "spfft_tpu: worker host lost (heartbeat/transport)")

#undef SPFFT_TPU_DEFINE_ERROR

} // namespace spfft

#endif // SPFFT_TPU_EXCEPTIONS_HPP
