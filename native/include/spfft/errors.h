/*
 * spfft_tpu native API — C error codes.
 *
 * Value-compatible with the reference SpfftError enum (reference:
 * include/spfft/errors.h:33-124). Every C API function returns one of these;
 * the C++ API throws the matching exception from spfft/exceptions.hpp.
 */
#ifndef SPFFT_TPU_ERRORS_H
#define SPFFT_TPU_ERRORS_H

enum SpfftError {
  SPFFT_SUCCESS = 0,
  SPFFT_UNKNOWN_ERROR = 1,
  SPFFT_INVALID_HANDLE_ERROR = 2,
  SPFFT_OVERFLOW_ERROR = 3,
  SPFFT_ALLOCATION_ERROR = 4,
  SPFFT_INVALID_PARAMETER_ERROR = 5,
  SPFFT_DUPLICATE_INDICES_ERROR = 6,
  SPFFT_INVALID_INDICES_ERROR = 7,
  SPFFT_MPI_SUPPORT_ERROR = 8, /* distributed support not compiled/available */
  SPFFT_MPI_ERROR = 9,         /* collective backend failure */
  SPFFT_MPI_PARAMETER_MISMATCH_ERROR = 10,
  SPFFT_HOST_EXECUTION_ERROR = 11,
  SPFFT_FFTW_ERROR = 12,
  SPFFT_GPU_ERROR = 13, /* accelerator (TPU) runtime failure */
  SPFFT_GPU_PRECEDING_ERROR = 14,
  SPFFT_GPU_SUPPORT_ERROR = 15,
  SPFFT_GPU_ALLOCATION_ERROR = 16,
  SPFFT_GPU_LAUNCH_ERROR = 17,
  SPFFT_GPU_NO_DEVICE_ERROR = 18,
  SPFFT_GPU_INVALID_VALUE_ERROR = 19,
  SPFFT_GPU_INVALID_DEVICE_PTR_ERROR = 20,
  SPFFT_GPU_COPY_ERROR = 21,
  SPFFT_GPU_FFT_ERROR = 22,
  /* TPU-build extension beyond the reference enum: algorithm-based
   * self-verification (ABFT) failed and recovery was exhausted. */
  SPFFT_VERIFICATION_ERROR = 23,
  /* Serving-layer extensions (spfft_tpu.serve): admission refused under
   * overload (bounded queue full, tenant quota, load shedding) ... */
  SPFFT_SERVICE_OVERLOAD_ERROR = 24,
  /* ... and a request deadline expired at admission or pre-dispatch. */
  SPFFT_DEADLINE_EXCEEDED_ERROR = 25,
  /* Multi-host extension: a worker host died or became unreachable
   * (missed heartbeats / dead RPC transport) with work in flight. */
  SPFFT_HOST_LOST_ERROR = 26
};

#ifndef __cplusplus
typedef enum SpfftError SpfftError;
#endif

#endif /* SPFFT_TPU_ERRORS_H */
