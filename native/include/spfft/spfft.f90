!> spfft_tpu native API — Fortran 2003 ISO-C interface module.
!>
!> bind(C) declarations for the C API in spfft/*.h, so Fortran plane-wave DFT
!> codes call the TPU build the way they call the reference library
!> (reference: include/spfft/spfft.f90 plays the same role for the C API).
!> Handles are type(c_ptr); every function returns an SpfftError integer.
!>
!> Build note: compile this file into the application (the reference ships it
!> the same way); link against libspfft_tpu.

module spfft
  use iso_c_binding
  implicit none

  ! --- SpfftError (spfft/errors.h) ---
  integer(c_int), parameter :: SPFFT_SUCCESS = 0
  integer(c_int), parameter :: SPFFT_UNKNOWN_ERROR = 1
  integer(c_int), parameter :: SPFFT_INVALID_HANDLE_ERROR = 2
  integer(c_int), parameter :: SPFFT_OVERFLOW_ERROR = 3
  integer(c_int), parameter :: SPFFT_ALLOCATION_ERROR = 4
  integer(c_int), parameter :: SPFFT_INVALID_PARAMETER_ERROR = 5
  integer(c_int), parameter :: SPFFT_DUPLICATE_INDICES_ERROR = 6
  integer(c_int), parameter :: SPFFT_INVALID_INDICES_ERROR = 7
  integer(c_int), parameter :: SPFFT_MPI_SUPPORT_ERROR = 8
  integer(c_int), parameter :: SPFFT_MPI_ERROR = 9
  integer(c_int), parameter :: SPFFT_MPI_PARAMETER_MISMATCH_ERROR = 10
  integer(c_int), parameter :: SPFFT_HOST_EXECUTION_ERROR = 11
  integer(c_int), parameter :: SPFFT_FFTW_ERROR = 12
  integer(c_int), parameter :: SPFFT_GPU_ERROR = 13
  integer(c_int), parameter :: SPFFT_GPU_PRECEDING_ERROR = 14
  integer(c_int), parameter :: SPFFT_GPU_SUPPORT_ERROR = 15
  integer(c_int), parameter :: SPFFT_GPU_ALLOCATION_ERROR = 16
  integer(c_int), parameter :: SPFFT_GPU_LAUNCH_ERROR = 17
  integer(c_int), parameter :: SPFFT_GPU_NO_DEVICE_ERROR = 18
  integer(c_int), parameter :: SPFFT_GPU_INVALID_VALUE_ERROR = 19
  integer(c_int), parameter :: SPFFT_GPU_INVALID_DEVICE_PTR_ERROR = 20
  integer(c_int), parameter :: SPFFT_GPU_COPY_ERROR = 21
  integer(c_int), parameter :: SPFFT_GPU_FFT_ERROR = 22
  ! TPU-build extension: self-verification (ABFT) failed, recovery exhausted
  integer(c_int), parameter :: SPFFT_VERIFICATION_ERROR = 23
  ! Serving-layer extensions (spfft_tpu.serve): admission refused under
  ! overload, and a request deadline expired at admission or pre-dispatch
  integer(c_int), parameter :: SPFFT_SERVICE_OVERLOAD_ERROR = 24
  integer(c_int), parameter :: SPFFT_DEADLINE_EXCEEDED_ERROR = 25
  ! Multi-host extension: a worker host died or became unreachable with
  ! work in flight (missed heartbeats / dead RPC transport)
  integer(c_int), parameter :: SPFFT_HOST_LOST_ERROR = 26

  ! --- SpfftExchangeType (spfft/types.h) ---
  integer(c_int), parameter :: SPFFT_EXCH_DEFAULT = 0
  integer(c_int), parameter :: SPFFT_EXCH_BUFFERED = 1
  integer(c_int), parameter :: SPFFT_EXCH_BUFFERED_FLOAT = 2
  integer(c_int), parameter :: SPFFT_EXCH_COMPACT_BUFFERED = 3
  integer(c_int), parameter :: SPFFT_EXCH_COMPACT_BUFFERED_FLOAT = 4
  integer(c_int), parameter :: SPFFT_EXCH_UNBUFFERED = 5
  ! TPU extensions: explicit bfloat16 wire (accuracy ~1e-2, opt-in only)
  integer(c_int), parameter :: SPFFT_EXCH_BUFFERED_BF16 = 6
  integer(c_int), parameter :: SPFFT_EXCH_COMPACT_BUFFERED_BF16 = 7

  ! --- SpfftProcessingUnitType ---
  integer(c_int), parameter :: SPFFT_PU_HOST = 1
  integer(c_int), parameter :: SPFFT_PU_GPU = 2

  ! --- SpfftIndexFormatType ---
  integer(c_int), parameter :: SPFFT_INDEX_TRIPLETS = 0

  ! --- SpfftTransformType ---
  integer(c_int), parameter :: SPFFT_TRANS_C2C = 0
  integer(c_int), parameter :: SPFFT_TRANS_R2C = 1

  ! --- SpfftScalingType ---
  integer(c_int), parameter :: SPFFT_NO_SCALING = 0
  integer(c_int), parameter :: SPFFT_FULL_SCALING = 1

  ! --- SpfftExecType ---
  integer(c_int), parameter :: SPFFT_EXEC_SYNCHRONOUS = 0
  integer(c_int), parameter :: SPFFT_EXEC_ASYNCHRONOUS = 1

  interface

    ! ---- grid --------------------------------------------------------------

    integer(c_int) function spfft_grid_create(grid, maxDimX, maxDimY, maxDimZ, &
        maxNumLocalZColumns, processingUnit, maxNumThreads) bind(C)
      use iso_c_binding
      type(c_ptr), intent(out) :: grid
      integer(c_int), value :: maxDimX, maxDimY, maxDimZ
      integer(c_int), value :: maxNumLocalZColumns, processingUnit, maxNumThreads
    end function

    integer(c_int) function spfft_grid_destroy(grid) bind(C)
      use iso_c_binding
      type(c_ptr), value :: grid
    end function

    integer(c_int) function spfft_grid_max_dim_x(grid, dimX) bind(C)
      use iso_c_binding
      type(c_ptr), value :: grid
      integer(c_int), intent(out) :: dimX
    end function

    integer(c_int) function spfft_grid_max_dim_y(grid, dimY) bind(C)
      use iso_c_binding
      type(c_ptr), value :: grid
      integer(c_int), intent(out) :: dimY
    end function

    integer(c_int) function spfft_grid_max_dim_z(grid, dimZ) bind(C)
      use iso_c_binding
      type(c_ptr), value :: grid
      integer(c_int), intent(out) :: dimZ
    end function

    integer(c_int) function spfft_grid_max_num_local_z_columns(grid, numCols) bind(C)
      use iso_c_binding
      type(c_ptr), value :: grid
      integer(c_int), intent(out) :: numCols
    end function

    integer(c_int) function spfft_grid_processing_unit(grid, processingUnit) bind(C)
      use iso_c_binding
      type(c_ptr), value :: grid
      integer(c_int), intent(out) :: processingUnit
    end function

    integer(c_int) function spfft_grid_max_local_z_length(grid, maxLocalZLength) bind(C)
      use iso_c_binding
      type(c_ptr), value :: grid
      integer(c_int), intent(out) :: maxLocalZLength
    end function

    integer(c_int) function spfft_grid_device_id(grid, deviceId) bind(C)
      use iso_c_binding
      type(c_ptr), value :: grid
      integer(c_int), intent(out) :: deviceId
    end function

    integer(c_int) function spfft_grid_num_threads(grid, numThreads) bind(C)
      use iso_c_binding
      type(c_ptr), value :: grid
      integer(c_int), intent(out) :: numThreads
    end function

    ! ---- distributed grid (single-controller mesh) --------------------------

    integer(c_int) function spfft_grid_create_distributed(grid, maxDimX, maxDimY, &
        maxDimZ, maxNumLocalZColumns, maxLocalZLength, numShards, exchangeType, &
        processingUnit, maxNumThreads) bind(C)
      use iso_c_binding
      type(c_ptr), intent(out) :: grid
      integer(c_int), value :: maxDimX, maxDimY, maxDimZ
      integer(c_int), value :: maxNumLocalZColumns, maxLocalZLength, numShards
      integer(c_int), value :: exchangeType, processingUnit, maxNumThreads
    end function

    integer(c_int) function spfft_grid_num_shards(grid, numShards) bind(C)
      use iso_c_binding
      type(c_ptr), value :: grid
      integer(c_int), intent(out) :: numShards
    end function

    integer(c_int) function spfft_grid_create_distributed2(grid, maxDimX, maxDimY, &
        maxDimZ, maxNumLocalZColumns, maxLocalZLength, p1, p2, exchangeType, &
        processingUnit, maxNumThreads) bind(C)
      use iso_c_binding
      type(c_ptr), intent(out) :: grid
      integer(c_int), value :: maxDimX, maxDimY, maxDimZ
      integer(c_int), value :: maxNumLocalZColumns, maxLocalZLength, p1, p2
      integer(c_int), value :: exchangeType, processingUnit, maxNumThreads
    end function

    ! ---- transform (double) -------------------------------------------------

    integer(c_int) function spfft_transform_create_independent(transform, &
        maxNumThreads, processingUnit, transformType, dimX, dimY, dimZ, &
        numLocalElements, indexFormat, indices) bind(C)
      use iso_c_binding
      type(c_ptr), intent(out) :: transform
      integer(c_int), value :: maxNumThreads, processingUnit, transformType
      integer(c_int), value :: dimX, dimY, dimZ, numLocalElements, indexFormat
      integer(c_int), dimension(*), intent(in) :: indices
    end function

    integer(c_int) function spfft_transform_create(transform, grid, processingUnit, &
        transformType, dimX, dimY, dimZ, localZLength, numLocalElements, &
        indexFormat, indices) bind(C)
      use iso_c_binding
      type(c_ptr), intent(out) :: transform
      type(c_ptr), value :: grid
      integer(c_int), value :: processingUnit, transformType
      integer(c_int), value :: dimX, dimY, dimZ, localZLength
      integer(c_int), value :: numLocalElements, indexFormat
      integer(c_int), dimension(*), intent(in) :: indices
    end function

    integer(c_int) function spfft_transform_destroy(transform) bind(C)
      use iso_c_binding
      type(c_ptr), value :: transform
    end function

    integer(c_int) function spfft_transform_clone(transform, newTransform) bind(C)
      use iso_c_binding
      type(c_ptr), value :: transform
      type(c_ptr), intent(out) :: newTransform
    end function

    integer(c_int) function spfft_transform_backward(transform, input, &
        outputLocation) bind(C)
      use iso_c_binding
      type(c_ptr), value :: transform
      real(c_double), dimension(*), intent(in) :: input
      integer(c_int), value :: outputLocation
    end function

    integer(c_int) function spfft_transform_forward(transform, inputLocation, &
        output, scaling) bind(C)
      use iso_c_binding
      type(c_ptr), value :: transform
      integer(c_int), value :: inputLocation
      real(c_double), dimension(*), intent(out) :: output
      integer(c_int), value :: scaling
    end function

    integer(c_int) function spfft_transform_get_space_domain(transform, &
        dataLocation, dataPtr) bind(C)
      use iso_c_binding
      type(c_ptr), value :: transform
      integer(c_int), value :: dataLocation
      type(c_ptr), intent(out) :: dataPtr
    end function

    integer(c_int) function spfft_transform_dim_x(transform, dimX) bind(C)
      use iso_c_binding
      type(c_ptr), value :: transform
      integer(c_int), intent(out) :: dimX
    end function

    integer(c_int) function spfft_transform_dim_y(transform, dimY) bind(C)
      use iso_c_binding
      type(c_ptr), value :: transform
      integer(c_int), intent(out) :: dimY
    end function

    integer(c_int) function spfft_transform_dim_z(transform, dimZ) bind(C)
      use iso_c_binding
      type(c_ptr), value :: transform
      integer(c_int), intent(out) :: dimZ
    end function

    integer(c_int) function spfft_transform_local_z_length(transform, len) bind(C)
      use iso_c_binding
      type(c_ptr), value :: transform
      integer(c_int), intent(out) :: len
    end function

    integer(c_int) function spfft_transform_local_z_offset(transform, off) bind(C)
      use iso_c_binding
      type(c_ptr), value :: transform
      integer(c_int), intent(out) :: off
    end function

    integer(c_int) function spfft_transform_num_local_elements(transform, n) bind(C)
      use iso_c_binding
      type(c_ptr), value :: transform
      integer(c_int), intent(out) :: n
    end function

    integer(c_int) function spfft_transform_num_global_elements(transform, n) bind(C)
      use iso_c_binding
      type(c_ptr), value :: transform
      integer(c_long_long), intent(out) :: n
    end function

    integer(c_int) function spfft_transform_global_size(transform, n) bind(C)
      use iso_c_binding
      type(c_ptr), value :: transform
      integer(c_long_long), intent(out) :: n
    end function

    integer(c_int) function spfft_transform_set_execution_mode(transform, mode) bind(C)
      use iso_c_binding
      type(c_ptr), value :: transform
      integer(c_int), value :: mode
    end function

    integer(c_int) function spfft_transform_execution_mode(transform, mode) bind(C)
      use iso_c_binding
      type(c_ptr), value :: transform
      integer(c_int), intent(out) :: mode
    end function

    integer(c_int) function spfft_transform_type(transform, transformType) bind(C)
      use iso_c_binding
      type(c_ptr), value :: transform
      integer(c_int), intent(out) :: transformType
    end function

    integer(c_int) function spfft_transform_processing_unit(transform, &
        processingUnit) bind(C)
      use iso_c_binding
      type(c_ptr), value :: transform
      integer(c_int), intent(out) :: processingUnit
    end function

    integer(c_int) function spfft_transform_local_slice_size(transform, size) bind(C)
      use iso_c_binding
      type(c_ptr), value :: transform
      integer(c_int), intent(out) :: size
    end function

    integer(c_int) function spfft_transform_device_id(transform, deviceId) bind(C)
      use iso_c_binding
      type(c_ptr), value :: transform
      integer(c_int), intent(out) :: deviceId
    end function

    integer(c_int) function spfft_transform_num_threads(transform, numThreads) bind(C)
      use iso_c_binding
      type(c_ptr), value :: transform
      integer(c_int), intent(out) :: numThreads
    end function

    ! ---- transform (float) --------------------------------------------------

    integer(c_int) function spfft_float_transform_create_independent(transform, &
        maxNumThreads, processingUnit, transformType, dimX, dimY, dimZ, &
        numLocalElements, indexFormat, indices) bind(C)
      use iso_c_binding
      type(c_ptr), intent(out) :: transform
      integer(c_int), value :: maxNumThreads, processingUnit, transformType
      integer(c_int), value :: dimX, dimY, dimZ, numLocalElements, indexFormat
      integer(c_int), dimension(*), intent(in) :: indices
    end function

    integer(c_int) function spfft_float_transform_destroy(transform) bind(C)
      use iso_c_binding
      type(c_ptr), value :: transform
    end function

    integer(c_int) function spfft_float_transform_backward(transform, input, &
        outputLocation) bind(C)
      use iso_c_binding
      type(c_ptr), value :: transform
      real(c_float), dimension(*), intent(in) :: input
      integer(c_int), value :: outputLocation
    end function

    integer(c_int) function spfft_float_transform_forward(transform, &
        inputLocation, output, scaling) bind(C)
      use iso_c_binding
      type(c_ptr), value :: transform
      integer(c_int), value :: inputLocation
      real(c_float), dimension(*), intent(out) :: output
      integer(c_int), value :: scaling
    end function

    integer(c_int) function spfft_float_transform_get_space_domain(transform, &
        dataLocation, dataPtr) bind(C)
      use iso_c_binding
      type(c_ptr), value :: transform
      integer(c_int), value :: dataLocation
      type(c_ptr), intent(out) :: dataPtr
    end function

    integer(c_int) function spfft_float_grid_create(grid, maxDimX, maxDimY, &
        maxDimZ, maxNumLocalZColumns, processingUnit, maxNumThreads) bind(C)
      use iso_c_binding
      type(c_ptr), intent(out) :: grid
      integer(c_int), value :: maxDimX, maxDimY, maxDimZ
      integer(c_int), value :: maxNumLocalZColumns, processingUnit, maxNumThreads
    end function

    integer(c_int) function spfft_float_transform_create(transform, grid, &
        processingUnit, transformType, dimX, dimY, dimZ, localZLength, &
        numLocalElements, indexFormat, indices) bind(C)
      use iso_c_binding
      type(c_ptr), intent(out) :: transform
      type(c_ptr), value :: grid
      integer(c_int), value :: processingUnit, transformType
      integer(c_int), value :: dimX, dimY, dimZ, localZLength
      integer(c_int), value :: numLocalElements, indexFormat
      integer(c_int), dimension(*), intent(in) :: indices
    end function

    integer(c_int) function spfft_float_transform_clone(transform, newTransform) bind(C)
      use iso_c_binding
      type(c_ptr), value :: transform
      type(c_ptr), intent(out) :: newTransform
    end function

    integer(c_int) function spfft_float_transform_type(transform, transformType) bind(C)
      use iso_c_binding
      type(c_ptr), value :: transform
      integer(c_int), intent(out) :: transformType
    end function

    integer(c_int) function spfft_float_transform_dim_x(transform, dimX) bind(C)
      use iso_c_binding
      type(c_ptr), value :: transform
      integer(c_int), intent(out) :: dimX
    end function

    integer(c_int) function spfft_float_transform_dim_y(transform, dimY) bind(C)
      use iso_c_binding
      type(c_ptr), value :: transform
      integer(c_int), intent(out) :: dimY
    end function

    integer(c_int) function spfft_float_transform_dim_z(transform, dimZ) bind(C)
      use iso_c_binding
      type(c_ptr), value :: transform
      integer(c_int), intent(out) :: dimZ
    end function

    integer(c_int) function spfft_float_transform_local_z_length(transform, len) bind(C)
      use iso_c_binding
      type(c_ptr), value :: transform
      integer(c_int), intent(out) :: len
    end function

    integer(c_int) function spfft_float_transform_local_z_offset(transform, off) bind(C)
      use iso_c_binding
      type(c_ptr), value :: transform
      integer(c_int), intent(out) :: off
    end function

    integer(c_int) function spfft_float_transform_num_local_elements(transform, &
        n) bind(C)
      use iso_c_binding
      type(c_ptr), value :: transform
      integer(c_int), intent(out) :: n
    end function

    integer(c_int) function spfft_float_transform_processing_unit(transform, &
        processingUnit) bind(C)
      use iso_c_binding
      type(c_ptr), value :: transform
      integer(c_int), intent(out) :: processingUnit
    end function

    integer(c_int) function spfft_float_transform_execution_mode(transform, &
        mode) bind(C)
      use iso_c_binding
      type(c_ptr), value :: transform
      integer(c_int), intent(out) :: mode
    end function

    integer(c_int) function spfft_float_transform_set_execution_mode(transform, &
        mode) bind(C)
      use iso_c_binding
      type(c_ptr), value :: transform
      integer(c_int), value :: mode
    end function

    integer(c_int) function spfft_float_transform_local_slice_size(transform, &
        size) bind(C)
      use iso_c_binding
      type(c_ptr), value :: transform
      integer(c_int), intent(out) :: size
    end function

    integer(c_int) function spfft_float_transform_num_global_elements(transform, &
        n) bind(C)
      use iso_c_binding
      type(c_ptr), value :: transform
      integer(c_long_long), intent(out) :: n
    end function

    integer(c_int) function spfft_float_transform_global_size(transform, n) bind(C)
      use iso_c_binding
      type(c_ptr), value :: transform
      integer(c_long_long), intent(out) :: n
    end function

    integer(c_int) function spfft_float_transform_device_id(transform, &
        deviceId) bind(C)
      use iso_c_binding
      type(c_ptr), value :: transform
      integer(c_int), intent(out) :: deviceId
    end function

    integer(c_int) function spfft_float_transform_num_threads(transform, &
        numThreads) bind(C)
      use iso_c_binding
      type(c_ptr), value :: transform
      integer(c_int), intent(out) :: numThreads
    end function

    ! ---- grid (float tier) --------------------------------------------------
    ! Same capacity object as the double grid (precision lives on the
    ! Transform); full reference surface (reference: grid_float.h:30-190).

    integer(c_int) function spfft_float_grid_create_distributed(grid, maxDimX, &
        maxDimY, maxDimZ, maxNumLocalZColumns, maxLocalZLength, numShards, &
        exchangeType, processingUnit, maxNumThreads) bind(C)
      use iso_c_binding
      type(c_ptr), intent(out) :: grid
      integer(c_int), value :: maxDimX, maxDimY, maxDimZ
      integer(c_int), value :: maxNumLocalZColumns, maxLocalZLength, numShards
      integer(c_int), value :: exchangeType, processingUnit, maxNumThreads
    end function

    integer(c_int) function spfft_float_grid_destroy(grid) bind(C)
      use iso_c_binding
      type(c_ptr), value :: grid
    end function

    integer(c_int) function spfft_float_grid_max_dim_x(grid, dimX) bind(C)
      use iso_c_binding
      type(c_ptr), value :: grid
      integer(c_int), intent(out) :: dimX
    end function

    integer(c_int) function spfft_float_grid_max_dim_y(grid, dimY) bind(C)
      use iso_c_binding
      type(c_ptr), value :: grid
      integer(c_int), intent(out) :: dimY
    end function

    integer(c_int) function spfft_float_grid_max_dim_z(grid, dimZ) bind(C)
      use iso_c_binding
      type(c_ptr), value :: grid
      integer(c_int), intent(out) :: dimZ
    end function

    integer(c_int) function spfft_float_grid_max_num_local_z_columns(grid, &
        maxNumLocalZColumns) bind(C)
      use iso_c_binding
      type(c_ptr), value :: grid
      integer(c_int), intent(out) :: maxNumLocalZColumns
    end function

    integer(c_int) function spfft_float_grid_max_local_z_length(grid, &
        maxLocalZLength) bind(C)
      use iso_c_binding
      type(c_ptr), value :: grid
      integer(c_int), intent(out) :: maxLocalZLength
    end function

    integer(c_int) function spfft_float_grid_processing_unit(grid, &
        processingUnit) bind(C)
      use iso_c_binding
      type(c_ptr), value :: grid
      integer(c_int), intent(out) :: processingUnit
    end function

    integer(c_int) function spfft_float_grid_device_id(grid, deviceId) bind(C)
      use iso_c_binding
      type(c_ptr), value :: grid
      integer(c_int), intent(out) :: deviceId
    end function

    integer(c_int) function spfft_float_grid_num_threads(grid, numThreads) bind(C)
      use iso_c_binding
      type(c_ptr), value :: grid
      integer(c_int), intent(out) :: numThreads
    end function

    ! ---- MPI-surface parity stubs -------------------------------------------
    ! No MPI exists in this runtime (the device mesh replaces the
    ! communicator); these link and return SPFFT_MPI_SUPPORT_ERROR. The bind
    ! targets are the *_fortran entry points taking an MPI_Fint-style integer,
    ! exactly like the reference module (reference: spfft.f90:165-169,310-316).

    integer(c_int) function spfft_grid_communicator(grid, comm) &
        bind(C, name="spfft_grid_communicator_fortran")
      use iso_c_binding
      type(c_ptr), value :: grid
      integer(c_int), intent(out) :: comm
    end function

    integer(c_int) function spfft_float_grid_communicator(grid, comm) &
        bind(C, name="spfft_float_grid_communicator_fortran")
      use iso_c_binding
      type(c_ptr), value :: grid
      integer(c_int), intent(out) :: comm
    end function

    integer(c_int) function spfft_transform_communicator(transform, comm) &
        bind(C, name="spfft_transform_communicator_fortran")
      use iso_c_binding
      type(c_ptr), value :: transform
      integer(c_int), intent(out) :: comm
    end function

    integer(c_int) function spfft_float_transform_communicator(transform, comm) &
        bind(C, name="spfft_float_transform_communicator_fortran")
      use iso_c_binding
      type(c_ptr), value :: transform
      integer(c_int), intent(out) :: comm
    end function

    integer(c_int) function spfft_transform_create_independent_distributed( &
        transform, maxNumThreads, comm, exchangeType, processingUnit, &
        transformType, dimX, dimY, dimZ, localZLength, numLocalElements, &
        indexFormat, indices) &
        bind(C, name="spfft_transform_create_independent_distributed_fortran")
      use iso_c_binding
      type(c_ptr), intent(out) :: transform
      integer(c_int), value :: maxNumThreads, comm, exchangeType
      integer(c_int), value :: processingUnit, transformType
      integer(c_int), value :: dimX, dimY, dimZ, localZLength
      integer(c_int), value :: numLocalElements, indexFormat
      integer(c_int), dimension(*), intent(in) :: indices
    end function

    integer(c_int) function spfft_float_transform_create_independent_distributed( &
        transform, maxNumThreads, comm, exchangeType, processingUnit, &
        transformType, dimX, dimY, dimZ, localZLength, numLocalElements, &
        indexFormat, indices) &
        bind(C, name="spfft_float_transform_create_independent_distributed_fortran")
      use iso_c_binding
      type(c_ptr), intent(out) :: transform
      integer(c_int), value :: maxNumThreads, comm, exchangeType
      integer(c_int), value :: processingUnit, transformType
      integer(c_int), value :: dimX, dimY, dimZ, localZLength
      integer(c_int), value :: numLocalElements, indexFormat
      integer(c_int), dimension(*), intent(in) :: indices
    end function

    ! ---- multi-transform ----------------------------------------------------

    integer(c_int) function spfft_multi_transform_backward(numTransforms, &
        transforms, input, outputLocations) bind(C)
      use iso_c_binding
      integer(c_int), value :: numTransforms
      type(c_ptr), dimension(*), intent(in) :: transforms
      type(c_ptr), dimension(*), intent(in) :: input
      integer(c_int), dimension(*), intent(in) :: outputLocations
    end function

    integer(c_int) function spfft_multi_transform_forward(numTransforms, &
        transforms, inputLocations, output, scalingTypes) bind(C)
      use iso_c_binding
      integer(c_int), value :: numTransforms
      type(c_ptr), dimension(*), intent(in) :: transforms
      integer(c_int), dimension(*), intent(in) :: inputLocations
      type(c_ptr), dimension(*), intent(in) :: output
      integer(c_int), dimension(*), intent(in) :: scalingTypes
    end function

    integer(c_int) function spfft_float_multi_transform_backward(numTransforms, &
        transforms, input, outputLocations) bind(C)
      use iso_c_binding
      integer(c_int), value :: numTransforms
      type(c_ptr), dimension(*), intent(in) :: transforms
      type(c_ptr), dimension(*), intent(in) :: input
      integer(c_int), dimension(*), intent(in) :: outputLocations
    end function

    integer(c_int) function spfft_float_multi_transform_forward(numTransforms, &
        transforms, inputLocations, output, scalingTypes) bind(C)
      use iso_c_binding
      integer(c_int), value :: numTransforms
      type(c_ptr), dimension(*), intent(in) :: transforms
      integer(c_int), dimension(*), intent(in) :: inputLocations
      type(c_ptr), dimension(*), intent(in) :: output
      integer(c_int), dimension(*), intent(in) :: scalingTypes
    end function

    ! ---- distributed transform (single-controller mesh) ---------------------

    integer(c_int) function spfft_dist_transform_create(transform, grid, &
        processingUnit, transformType, dimX, dimY, dimZ, numShards, &
        shardNumElements, indexFormat, indices, doublePrecision) bind(C)
      use iso_c_binding
      type(c_ptr), intent(out) :: transform
      type(c_ptr), value :: grid
      integer(c_int), value :: processingUnit, transformType
      integer(c_int), value :: dimX, dimY, dimZ, numShards
      integer(c_int), dimension(*), intent(in) :: shardNumElements
      integer(c_int), value :: indexFormat
      integer(c_int), dimension(*), intent(in) :: indices
      integer(c_int), value :: doublePrecision
    end function

    integer(c_int) function spfft_dist_transform_create_independent(transform, &
        maxNumThreads, numShards, exchangeType, processingUnit, transformType, &
        dimX, dimY, dimZ, shardNumElements, indexFormat, indices, &
        doublePrecision) bind(C)
      use iso_c_binding
      type(c_ptr), intent(out) :: transform
      integer(c_int), value :: maxNumThreads, numShards, exchangeType
      integer(c_int), value :: processingUnit, transformType
      integer(c_int), value :: dimX, dimY, dimZ
      integer(c_int), dimension(*), intent(in) :: shardNumElements
      integer(c_int), value :: indexFormat
      integer(c_int), dimension(*), intent(in) :: indices
      integer(c_int), value :: doublePrecision
    end function

    integer(c_int) function spfft_dist_transform_destroy(transform) bind(C)
      use iso_c_binding
      type(c_ptr), value :: transform
    end function

    integer(c_int) function spfft_dist_transform_backward(transform, values, &
        space) bind(C)
      use iso_c_binding
      type(c_ptr), value :: transform
      real(c_double), dimension(*), intent(in) :: values
      real(c_double), dimension(*), intent(out) :: space
    end function

    integer(c_int) function spfft_float_dist_transform_backward(transform, values, &
        space) bind(C)
      use iso_c_binding
      type(c_ptr), value :: transform
      real(c_float), dimension(*), intent(in) :: values
      real(c_float), dimension(*), intent(out) :: space
    end function

    integer(c_int) function spfft_dist_transform_forward(transform, space, values, &
        scaling) bind(C)
      use iso_c_binding
      type(c_ptr), value :: transform
      real(c_double), dimension(*), intent(in) :: space
      real(c_double), dimension(*), intent(out) :: values
      integer(c_int), value :: scaling
    end function

    integer(c_int) function spfft_float_dist_transform_forward(transform, space, &
        values, scaling) bind(C)
      use iso_c_binding
      type(c_ptr), value :: transform
      real(c_float), dimension(*), intent(in) :: space
      real(c_float), dimension(*), intent(out) :: values
      integer(c_int), value :: scaling
    end function

    integer(c_int) function spfft_dist_transform_type(transform, transformType) bind(C)
      use iso_c_binding
      type(c_ptr), value :: transform
      integer(c_int), intent(out) :: transformType
    end function

    integer(c_int) function spfft_dist_transform_dim_x(transform, dimX) bind(C)
      use iso_c_binding
      type(c_ptr), value :: transform
      integer(c_int), intent(out) :: dimX
    end function

    integer(c_int) function spfft_dist_transform_dim_y(transform, dimY) bind(C)
      use iso_c_binding
      type(c_ptr), value :: transform
      integer(c_int), intent(out) :: dimY
    end function

    integer(c_int) function spfft_dist_transform_dim_z(transform, dimZ) bind(C)
      use iso_c_binding
      type(c_ptr), value :: transform
      integer(c_int), intent(out) :: dimZ
    end function

    integer(c_int) function spfft_dist_transform_num_shards(transform, &
        numShards) bind(C)
      use iso_c_binding
      type(c_ptr), value :: transform
      integer(c_int), intent(out) :: numShards
    end function

    integer(c_int) function spfft_dist_transform_num_global_elements(transform, &
        numGlobalElements) bind(C)
      use iso_c_binding
      type(c_ptr), value :: transform
      integer(c_long_long), intent(out) :: numGlobalElements
    end function

    integer(c_int) function spfft_dist_transform_global_size(transform, &
        globalSize) bind(C)
      use iso_c_binding
      type(c_ptr), value :: transform
      integer(c_long_long), intent(out) :: globalSize
    end function

    integer(c_int) function spfft_dist_transform_exchange_type(transform, &
        exchangeType) bind(C)
      use iso_c_binding
      type(c_ptr), value :: transform
      integer(c_int), intent(out) :: exchangeType
    end function

    integer(c_int) function spfft_dist_transform_exchange_wire_bytes(transform, &
        wireBytes) bind(C)
      use iso_c_binding
      type(c_ptr), value :: transform
      integer(c_long_long), intent(out) :: wireBytes
    end function

    integer(c_int) function spfft_dist_transform_exchange_rounds(transform, &
        rounds) bind(C)
      use iso_c_binding
      type(c_ptr), value :: transform
      integer(c_int), intent(out) :: rounds
    end function

    integer(c_int) function spfft_dist_transform_local_z_length(transform, shard, &
        localZLength) bind(C)
      use iso_c_binding
      type(c_ptr), value :: transform
      integer(c_int), value :: shard
      integer(c_int), intent(out) :: localZLength
    end function

    integer(c_int) function spfft_dist_transform_local_z_offset(transform, shard, &
        offset) bind(C)
      use iso_c_binding
      type(c_ptr), value :: transform
      integer(c_int), value :: shard
      integer(c_int), intent(out) :: offset
    end function

    integer(c_int) function spfft_dist_transform_local_y_length(transform, shard, &
        localYLength) bind(C)
      use iso_c_binding
      type(c_ptr), value :: transform
      integer(c_int), value :: shard
      integer(c_int), intent(out) :: localYLength
    end function

    integer(c_int) function spfft_dist_transform_local_y_offset(transform, shard, &
        offset) bind(C)
      use iso_c_binding
      type(c_ptr), value :: transform
      integer(c_int), value :: shard
      integer(c_int), intent(out) :: offset
    end function

    integer(c_int) function spfft_dist_transform_num_local_elements(transform, &
        shard, numLocalElements) bind(C)
      use iso_c_binding
      type(c_ptr), value :: transform
      integer(c_int), value :: shard
      integer(c_int), intent(out) :: numLocalElements
    end function

  end interface
end module spfft
