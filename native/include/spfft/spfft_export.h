/*
 * spfft_tpu native API — export macros (reference: CMake GenerateExportHeader
 * output installed as spfft/spfft_export.h). All symbols have default
 * visibility here, so every macro expands to nothing — the definitions exist
 * so reference-style prototypes and callers compile unchanged.
 */
#ifndef SPFFT_EXPORT_H
#define SPFFT_EXPORT_H

#define SPFFT_EXPORT
#define SPFFT_NO_EXPORT
#define SPFFT_DEPRECATED
#define SPFFT_DEPRECATED_EXPORT
#define SPFFT_DEPRECATED_NO_EXPORT

#endif
