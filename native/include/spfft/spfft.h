/* spfft_tpu native API — umbrella C header (reference: include/spfft/spfft.h). */
#ifndef SPFFT_TPU_SPFFT_H
#define SPFFT_TPU_SPFFT_H

#include <spfft/errors.h>
#include <spfft/grid.h>
#include <spfft/multi_transform.h>
#include <spfft/transform.h>
#include <spfft/types.h>

#endif /* SPFFT_TPU_SPFFT_H */
