/* spfft_tpu native API — umbrella C header (reference: include/spfft/spfft.h).
 *
 * Scope: local (single-process) transforms, double and single precision — the
 * same surface the reference exposes when built without MPI (SPFFT_MPI=OFF).
 * Mesh-distributed transforms are reached through the Python API
 * (spfft_tpu.DistributedTransform over a jax.sharding.Mesh); a device mesh has
 * no MPI-communicator analogue that can cross the C boundary meaningfully.
 */
#ifndef SPFFT_TPU_SPFFT_H
#define SPFFT_TPU_SPFFT_H

/* Version of the reference API surface this build mirrors (reference:
 * CMakeLists.txt:2 project VERSION 1.0.2). */
#define SPFFT_VERSION_MAJOR 1
#define SPFFT_VERSION_MINOR 0
#define SPFFT_VERSION_PATCH 2
#define SPFFT_VERSION_STRING "1.0.2-tpu"

#include <spfft/errors.h>
#include <spfft/grid.h>
#include <spfft/multi_transform.h>
#include <spfft/transform.h>
#include <spfft/types.h>

#endif /* SPFFT_TPU_SPFFT_H */
