/* spfft_tpu native API — umbrella C header (reference: include/spfft/spfft.h).
 *
 * Scope: the reference's full C surface, double and single precision. MPI-only
 * entry points exist as linkable stubs returning SPFFT_MPI_SUPPORT_ERROR;
 * mesh-distributed transforms run single-controller through the
 * spfft_grid_create_distributed / spfft_dist_transform_* surface (one process
 * drives every shard of a jax.sharding.Mesh).
 */
#ifndef SPFFT_TPU_SPFFT_H
#define SPFFT_TPU_SPFFT_H

/* Version of the reference API surface this build mirrors (reference:
 * CMakeLists.txt:2 project VERSION 1.0.2). */
#define SPFFT_VERSION_MAJOR 1
#define SPFFT_VERSION_MINOR 0
#define SPFFT_VERSION_PATCH 2
#define SPFFT_VERSION_STRING "1.0.2-tpu"

#include <spfft/config.h>
#include <spfft/errors.h>
#include <spfft/grid.h>
#include <spfft/grid_float.h>
#include <spfft/multi_transform.h>
#include <spfft/multi_transform_float.h>
#include <spfft/transform.h>
#include <spfft/transform_float.h>
#include <spfft/types.h>

#endif /* SPFFT_TPU_SPFFT_H */
