/*
 * spfft_tpu native API — single-precision C Grid interface
 * (reference: include/spfft/grid_float.h).
 *
 * GridFloat is the same capacity object as Grid in this build (precision
 * lives on the Transform), so the spfft_float_grid_* surface is declared
 * alongside the double tier in grid.h; this header exists so callers that
 * include <spfft/grid_float.h> directly compile unchanged.
 */
#ifndef SPFFT_TPU_GRID_FLOAT_H
#define SPFFT_TPU_GRID_FLOAT_H

#include <spfft/grid.h>

#endif /* SPFFT_TPU_GRID_FLOAT_H */
