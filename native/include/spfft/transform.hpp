/*
 * spfft_tpu native API — C++ Transform classes.
 *
 * Source-compatible with the reference spfft::Transform /
 * spfft::TransformFloat (reference: include/spfft/transform.hpp:56-318,
 * transform_float.hpp). The plan object is backed by the XLA compute core:
 * construction compiles shape-specialized device programs; backward/forward
 * dispatch them and marshal host buffers across the runtime boundary.
 *
 * Usage mirrors the reference: construct, fill space_domain_data() or pass a
 * frequency-value array to backward(), read results, forward() back.
 */
#ifndef SPFFT_TPU_TRANSFORM_HPP
#define SPFFT_TPU_TRANSFORM_HPP

#include <spfft/errors.h>
#include <spfft/types.h>

#include <memory>

namespace spfft {

class Grid;

class Transform;
class TransformFloat;
class DistributedTransform;

namespace detail {
struct Plan;
struct DistPlan;
std::shared_ptr<Plan> make_plan(const Grid* grid, bool double_precision,
                                SpfftProcessingUnitType pu, SpfftTransformType tt,
                                int dim_x, int dim_y, int dim_z, int local_z_length,
                                int num_local_elements, SpfftIndexFormatType fmt,
                                const int* indices);
Plan* plan_of(Transform& t);
Plan* plan_of(TransformFloat& t);
} // namespace detail

/* Double-precision sparse 3D FFT plan. */
class Transform {
public:
  /* Grid-less constructor (reference v1.0 feature, transform.hpp:76-105). */
  Transform(SpfftProcessingUnitType processing_unit, SpfftTransformType transform_type,
            int dim_x, int dim_y, int dim_z, int num_local_elements,
            SpfftIndexFormatType index_format, const int* indices);

  /* Independent plan with identical layout (reference: transform.hpp:133). */
  Transform clone() const;

  /* Frequency -> space. Result lands in space_domain_data(). */
  void backward(const double* input, SpfftProcessingUnitType output_location);

  /* Pointer-to-pointer overload: the space-domain result is also written to
   * ``output`` (reference: transform.h spfft_transform_backward_ptr). */
  void backward(const double* input, double* output);

  /* Space -> frequency, reading space_domain_data(). */
  void forward(SpfftProcessingUnitType input_location, double* output,
               SpfftScalingType scaling = SPFFT_NO_SCALING);

  /* Pointer-to-pointer overload: space input supplied directly. */
  void forward(const double* input, double* output,
               SpfftScalingType scaling = SPFFT_NO_SCALING);

  /* Writable (dimZ, dimY, dimX) slab; complex-interleaved for C2C, real for
   * R2C. Valid until the next transform call (reference: transform.hpp:245). */
  double* space_domain_data(SpfftProcessingUnitType data_location);

  SpfftTransformType type() const;
  int dim_x() const;
  int dim_y() const;
  int dim_z() const;
  int local_z_length() const;
  int local_z_offset() const;
  long long local_slice_size() const;
  long long num_local_elements() const;
  long long num_global_elements() const;
  long long global_size() const;
  SpfftProcessingUnitType processing_unit() const;
  int device_id() const;
  int num_threads() const;
  SpfftExecType execution_mode() const;
  void set_execution_mode(SpfftExecType mode);

private:
  friend class Grid;
  friend detail::Plan* detail::plan_of(Transform&);
  explicit Transform(std::shared_ptr<detail::Plan> plan) : plan_(std::move(plan)) {}

  std::shared_ptr<detail::Plan> plan_;
};

/* Single-precision plan (reference: include/spfft/transform_float.hpp; on TPU
 * f32 is the native precision, so this is the fast path). */
class TransformFloat {
public:
  TransformFloat(SpfftProcessingUnitType processing_unit,
                 SpfftTransformType transform_type, int dim_x, int dim_y, int dim_z,
                 int num_local_elements, SpfftIndexFormatType index_format,
                 const int* indices);

  TransformFloat clone() const;

  void backward(const float* input, SpfftProcessingUnitType output_location);
  void backward(const float* input, float* output);
  void forward(SpfftProcessingUnitType input_location, float* output,
               SpfftScalingType scaling = SPFFT_NO_SCALING);
  void forward(const float* input, float* output,
               SpfftScalingType scaling = SPFFT_NO_SCALING);
  float* space_domain_data(SpfftProcessingUnitType data_location);

  SpfftTransformType type() const;
  int dim_x() const;
  int dim_y() const;
  int dim_z() const;
  int local_z_length() const;
  int local_z_offset() const;
  long long local_slice_size() const;
  long long num_local_elements() const;
  long long num_global_elements() const;
  long long global_size() const;
  SpfftProcessingUnitType processing_unit() const;
  int device_id() const;
  int num_threads() const;
  SpfftExecType execution_mode() const;
  void set_execution_mode(SpfftExecType mode);

private:
  friend class Grid;
  friend detail::Plan* detail::plan_of(TransformFloat&);
  explicit TransformFloat(std::shared_ptr<detail::Plan> plan) : plan_(std::move(plan)) {}

  std::shared_ptr<detail::Plan> plan_;
};

/* Mesh-distributed sparse 3D FFT plan (single-controller: one process drives
 * every shard; the reference's per-rank MPI contract becomes shard-major
 * concatenated host arrays). Created via Grid::create_transform_distributed.
 * Precision is chosen at creation; the double/float overloads must match it
 * (InvalidParameterError otherwise). */
class DistributedTransform {
public:
  /* values: shard-major concatenated packed frequency data
   * (2 * num_global_elements reals, complex-interleaved); space_output: the
   * assembled global (dimZ, dimY, dimX) slab (complex-interleaved for C2C,
   * real for R2C). */
  void backward(const double* values, double* space_output);
  void backward(const float* values, float* space_output);

  /* space: global (dimZ, dimY, dimX) array, or nullptr to reuse the slabs
   * retained by the last backward; values_output as above. */
  void forward(const double* space, double* values_output,
               SpfftScalingType scaling = SPFFT_NO_SCALING);
  void forward(const float* space, float* values_output,
               SpfftScalingType scaling = SPFFT_NO_SCALING);

  SpfftTransformType type() const;
  int dim_x() const;
  int dim_y() const;
  int dim_z() const;
  int num_shards() const;
  long long num_global_elements() const;
  long long global_size() const;
  SpfftProcessingUnitType processing_unit() const;
  SpfftExchangeType exchange_type() const;
  /* Off-shard interconnect bytes per slab<->pencil repartition. */
  long long exchange_wire_bytes() const;
  /* Sequential collective rounds per repartition under the plan's
   * discipline and active transport. 1-D grids: 1 (padded all_to_all /
   * one-shot ragged), P-1 (chains). 2-D pencil grids report the sum of
   * their two exchanges: 2 (padded/one-shot) or (P-1)+(P1-1) (chains). */
  int exchange_rounds() const;
  bool double_precision() const;

  /* Per-shard layout (the reference's per-rank accessors). On 2-D pencil
   * grids the space block is (local_z_length, local_y_length, dim_x); on 1-D
   * grids local_y_length == dim_y and local_y_offset == 0. */
  int local_z_length(int shard) const;
  int local_z_offset(int shard) const;
  int local_y_length(int shard) const;
  int local_y_offset(int shard) const;
  long long local_slice_size(int shard) const;
  long long num_local_elements(int shard) const;

private:
  friend class Grid;
  explicit DistributedTransform(std::shared_ptr<detail::DistPlan> plan)
      : plan_(std::move(plan)) {}

  std::shared_ptr<detail::DistPlan> plan_;
};

} // namespace spfft

#endif // SPFFT_TPU_TRANSFORM_HPP
