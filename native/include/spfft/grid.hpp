/*
 * spfft_tpu native API — C++ Grid class.
 *
 * Source-compatible with the reference spfft::Grid (reference:
 * include/spfft/grid.hpp:49-205). A Grid declares maximum transform
 * dimensions and hands out Transform plans; on the XLA backend buffer reuse
 * is realized through donated/aliased device buffers rather than shared host
 * arrays, so the Grid is pure capacity metadata plus a shared runtime handle.
 */
#ifndef SPFFT_TPU_GRID_HPP
#define SPFFT_TPU_GRID_HPP

#include <spfft/transform.hpp>
#include <spfft/types.h>

#include <memory>

namespace spfft {

class Grid;

namespace detail {
struct GridState;
const std::shared_ptr<GridState>& grid_state(const Grid& grid);
} // namespace detail

class Grid {
public:
  /* Local grid (reference: grid.hpp:65-66). */
  Grid(int max_dim_x, int max_dim_y, int max_dim_z, int max_num_local_z_columns,
       SpfftProcessingUnitType processing_unit, int max_num_threads);

  /* Distributed grid over a device mesh (the reference's MPI ctor,
   * grid.hpp:89-91, in single-controller form: ONE process drives every shard
   * of the mesh; num_shards replaces the MPI communicator). */
  Grid(int max_dim_x, int max_dim_y, int max_dim_z, int max_num_local_z_columns,
       int max_local_z_length, int num_shards, SpfftExchangeType exchange_type,
       SpfftProcessingUnitType processing_unit, int max_num_threads);

  /* 2-D pencil mesh (p1 x p2): z-slabs x y-slabs in space; lifts the slab
   * decomposition's P <= dimZ cap to dimZ * dimY shards. */
  Grid(int max_dim_x, int max_dim_y, int max_dim_z, int max_num_local_z_columns,
       int max_local_z_length, int p1, int p2, SpfftExchangeType exchange_type,
       SpfftProcessingUnitType processing_unit, int max_num_threads);

  /* Copy creates independent capacity (reference copy ctor allocates fresh
   * buffers, grid.hpp "copy = fresh buffers"). */
  Grid(const Grid&);
  Grid(Grid&&) noexcept;
  Grid& operator=(const Grid&);
  Grid& operator=(Grid&&) noexcept;
  ~Grid();

  /* Create a double-precision transform bound to this grid
   * (reference: grid.hpp:138-141). */
  Transform create_transform(SpfftProcessingUnitType processing_unit,
                             SpfftTransformType transform_type, int dim_x, int dim_y,
                             int dim_z, int local_z_length, int num_local_elements,
                             SpfftIndexFormatType index_format, const int* indices) const;

  /* Single-precision variant (reference: GridFloat::create_transform). */
  TransformFloat create_transform_float(SpfftProcessingUnitType processing_unit,
                                        SpfftTransformType transform_type, int dim_x,
                                        int dim_y, int dim_z, int local_z_length,
                                        int num_local_elements,
                                        SpfftIndexFormatType index_format,
                                        const int* indices) const;

  /* Distributed transform over this grid's mesh (grid must be distributed).
   * shard_num_elements: per-shard value counts; indices: shard-major
   * concatenated triplets (3 * sum(shard_num_elements) ints). */
  DistributedTransform create_transform_distributed(
      SpfftProcessingUnitType processing_unit, SpfftTransformType transform_type,
      int dim_x, int dim_y, int dim_z, int num_shards, const int* shard_num_elements,
      SpfftIndexFormatType index_format, const int* indices,
      bool double_precision = true) const;

  int max_dim_x() const;
  int max_dim_y() const;
  int max_dim_z() const;
  int max_num_local_z_columns() const;
  int max_local_z_length() const;
  SpfftProcessingUnitType processing_unit() const;
  int device_id() const;
  int max_num_threads() const;
  /* 1 for local grids; the mesh size for distributed ones. */
  int num_shards() const;

private:
  friend const std::shared_ptr<detail::GridState>& detail::grid_state(const Grid&);

  std::shared_ptr<detail::GridState> state_;
};

/* Precision lives on the Transform in this build; GridFloat is the same
 * capacity object (reference keeps two classes only because its buffers are
 * typed). */
typedef Grid GridFloat;

} // namespace spfft

#endif // SPFFT_TPU_GRID_HPP
