/*
 * spfft_tpu native API — single-precision C++ multi-transform
 * (reference: include/spfft/multi_transform_float.hpp).
 *
 * The TransformFloat overloads are declared alongside the double tier in
 * multi_transform.hpp; this header exists so callers that include
 * <spfft/multi_transform_float.hpp> directly compile unchanged.
 */
#ifndef SPFFT_TPU_MULTI_TRANSFORM_FLOAT_HPP
#define SPFFT_TPU_MULTI_TRANSFORM_FLOAT_HPP

#include <spfft/multi_transform.hpp>

#endif /* SPFFT_TPU_MULTI_TRANSFORM_FLOAT_HPP */
