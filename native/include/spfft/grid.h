/*
 * spfft_tpu native API — C Grid interface.
 *
 * Opaque-handle mirror of the C++ Grid (reference: include/spfft/grid.h).
 * Every function returns an SpfftError; out-parameters carry results.
 */
#ifndef SPFFT_TPU_GRID_H
#define SPFFT_TPU_GRID_H

#include <spfft/errors.h>
#include <spfft/types.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef void* SpfftGrid;

SpfftError spfft_grid_create(SpfftGrid* grid, int maxDimX, int maxDimY, int maxDimZ,
                             int maxNumLocalZColumns,
                             SpfftProcessingUnitType processingUnit, int maxNumThreads);

/* Distributed grid over a device mesh (the reference's MPI ctor in
 * single-controller form: one process drives all numShards mesh shards; the
 * mesh size replaces the MPI communicator). Set SPFFT_TPU_NUM_CPU_DEVICES=N
 * in the environment before the first API call to get an N-device virtual
 * CPU mesh for SPFFT_PU_HOST testing. */
SpfftError spfft_grid_create_distributed(SpfftGrid* grid, int maxDimX, int maxDimY,
                                         int maxDimZ, int maxNumLocalZColumns,
                                         int maxLocalZLength, int numShards,
                                         SpfftExchangeType exchangeType,
                                         SpfftProcessingUnitType processingUnit,
                                         int maxNumThreads);

/* 2-D pencil mesh (p1 x p2 shards; z-slabs x y-slabs in space — lifts the
 * slab decomposition's P <= dimZ cap). Transforms created from this grid use
 * the same spfft_dist_transform_* surface; per-shard space blocks are
 * (local_z_length, local_y_length, dimX). */
SpfftError spfft_grid_create_distributed2(SpfftGrid* grid, int maxDimX, int maxDimY,
                                          int maxDimZ, int maxNumLocalZColumns,
                                          int maxLocalZLength, int p1, int p2,
                                          SpfftExchangeType exchangeType,
                                          SpfftProcessingUnitType processingUnit,
                                          int maxNumThreads);

SpfftError spfft_grid_destroy(SpfftGrid grid);

SpfftError spfft_grid_max_dim_x(SpfftGrid grid, int* dimX);
SpfftError spfft_grid_max_dim_y(SpfftGrid grid, int* dimY);
SpfftError spfft_grid_max_dim_z(SpfftGrid grid, int* dimZ);
SpfftError spfft_grid_max_num_local_z_columns(SpfftGrid grid, int* maxNumLocalZColumns);
SpfftError spfft_grid_max_local_z_length(SpfftGrid grid, int* maxLocalZLength);
SpfftError spfft_grid_processing_unit(SpfftGrid grid,
                                      SpfftProcessingUnitType* processingUnit);
SpfftError spfft_grid_device_id(SpfftGrid grid, int* deviceId);
SpfftError spfft_grid_num_threads(SpfftGrid grid, int* numThreads);
/* 1 for local grids; the mesh size for distributed ones. */
SpfftError spfft_grid_num_shards(SpfftGrid grid, int* numShards);

/* Single-precision grid — same capacity object (see grid.hpp). The full
 * reference float surface (reference: include/spfft/grid_float.h:30-190) is
 * mirrored so GridFloat callers recompile unchanged; precision itself lives
 * on the Transform in this build. */
typedef void* SpfftFloatGrid;

SpfftError spfft_float_grid_create(SpfftFloatGrid* grid, int maxDimX, int maxDimY,
                                   int maxDimZ, int maxNumLocalZColumns,
                                   SpfftProcessingUnitType processingUnit,
                                   int maxNumThreads);

SpfftError spfft_float_grid_create_distributed(SpfftFloatGrid* grid, int maxDimX,
                                               int maxDimY, int maxDimZ,
                                               int maxNumLocalZColumns,
                                               int maxLocalZLength, int numShards,
                                               SpfftExchangeType exchangeType,
                                               SpfftProcessingUnitType processingUnit,
                                               int maxNumThreads);

SpfftError spfft_float_grid_destroy(SpfftFloatGrid grid);

SpfftError spfft_float_grid_max_dim_x(SpfftFloatGrid grid, int* dimX);
SpfftError spfft_float_grid_max_dim_y(SpfftFloatGrid grid, int* dimY);
SpfftError spfft_float_grid_max_dim_z(SpfftFloatGrid grid, int* dimZ);
SpfftError spfft_float_grid_max_num_local_z_columns(SpfftFloatGrid grid,
                                                    int* maxNumLocalZColumns);
SpfftError spfft_float_grid_max_local_z_length(SpfftFloatGrid grid,
                                               int* maxLocalZLength);
SpfftError spfft_float_grid_processing_unit(SpfftFloatGrid grid,
                                            SpfftProcessingUnitType* processingUnit);
SpfftError spfft_float_grid_device_id(SpfftFloatGrid grid, int* deviceId);
SpfftError spfft_float_grid_num_threads(SpfftFloatGrid grid, int* numThreads);

/* Communicator accessors (reference: include/spfft/grid.h:184,
 * grid_float.h:190). This runtime has no MPI — the device mesh replaces the
 * communicator (docs/api/c_api.md) — so these are linkable stubs returning
 * SPFFT_MPI_SUPPORT_ERROR: a ported MPI caller links and gets a clean error
 * instead of a build failure. SpfftMpiComm (types.h) is MPI_Comm whenever the
 * caller compiles with MPI, so reference call sites compile unchanged. */
SpfftError spfft_grid_communicator(SpfftGrid grid, SpfftMpiComm* comm);
SpfftError spfft_float_grid_communicator(SpfftFloatGrid grid, SpfftMpiComm* comm);

#ifdef __cplusplus
}
#endif

#endif /* SPFFT_TPU_GRID_H */
