/* spfft_tpu native API — umbrella C++ header (reference: include/spfft/spfft.hpp). */
#ifndef SPFFT_TPU_SPFFT_HPP
#define SPFFT_TPU_SPFFT_HPP

#include <spfft/config.h>
#include <spfft/exceptions.hpp>
#include <spfft/grid.hpp>
#include <spfft/grid_float.hpp>
#include <spfft/multi_transform.hpp>
#include <spfft/multi_transform_float.hpp>
#include <spfft/transform.hpp>
#include <spfft/transform_float.hpp>
#include <spfft/types.h>

#endif /* SPFFT_TPU_SPFFT_HPP */
