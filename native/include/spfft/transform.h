/*
 * spfft_tpu native API — C Transform interface.
 *
 * Opaque-handle mirror of the C++ Transform/TransformFloat (reference:
 * include/spfft/transform.h, transform_float.h). Handles are created either
 * grid-less or from an SpfftGrid; all functions return SpfftError.
 */
#ifndef SPFFT_TPU_TRANSFORM_H
#define SPFFT_TPU_TRANSFORM_H

#include <spfft/errors.h>
#include <spfft/grid.h>
#include <spfft/types.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef void* SpfftTransform;

/* Grid-less creation (reference v1.0 feature). */
SpfftError spfft_transform_create_independent(
    SpfftTransform* transform, int maxNumThreads,
    SpfftProcessingUnitType processingUnit, SpfftTransformType transformType, int dimX,
    int dimY, int dimZ, int numLocalElements, SpfftIndexFormatType indexFormat,
    const int* indices);

/* Creation bound to a grid (reference: include/spfft/transform.h
 * spfft_transform_create). */
SpfftError spfft_transform_create(SpfftTransform* transform, SpfftGrid grid,
                                  SpfftProcessingUnitType processingUnit,
                                  SpfftTransformType transformType, int dimX, int dimY,
                                  int dimZ, int localZLength, int numLocalElements,
                                  SpfftIndexFormatType indexFormat, const int* indices);

SpfftError spfft_transform_destroy(SpfftTransform transform);
SpfftError spfft_transform_clone(SpfftTransform transform, SpfftTransform* newTransform);

SpfftError spfft_transform_backward(SpfftTransform transform, const double* input,
                                    SpfftProcessingUnitType outputLocation);
SpfftError spfft_transform_forward(SpfftTransform transform,
                                   SpfftProcessingUnitType inputLocation, double* output,
                                   SpfftScalingType scaling);
SpfftError spfft_transform_forward_ptr(SpfftTransform transform, const double* input,
                                       double* output, SpfftScalingType scaling);
SpfftError spfft_transform_get_space_domain(SpfftTransform transform,
                                            SpfftProcessingUnitType dataLocation,
                                            double** data);

SpfftError spfft_transform_type(SpfftTransform transform, SpfftTransformType* type);
SpfftError spfft_transform_dim_x(SpfftTransform transform, int* dimX);
SpfftError spfft_transform_dim_y(SpfftTransform transform, int* dimY);
SpfftError spfft_transform_dim_z(SpfftTransform transform, int* dimZ);
SpfftError spfft_transform_local_z_length(SpfftTransform transform, int* localZLength);
SpfftError spfft_transform_local_z_offset(SpfftTransform transform, int* offset);
SpfftError spfft_transform_local_slice_size(SpfftTransform transform, int* size);
SpfftError spfft_transform_num_local_elements(SpfftTransform transform, int* numLocalElements);
SpfftError spfft_transform_num_global_elements(SpfftTransform transform,
                                               long long int* numGlobalElements);
SpfftError spfft_transform_global_size(SpfftTransform transform, long long int* globalSize);
SpfftError spfft_transform_processing_unit(SpfftTransform transform,
                                           SpfftProcessingUnitType* processingUnit);
SpfftError spfft_transform_device_id(SpfftTransform transform, int* deviceId);
SpfftError spfft_transform_num_threads(SpfftTransform transform, int* numThreads);
SpfftError spfft_transform_execution_mode(SpfftTransform transform, SpfftExecType* mode);
SpfftError spfft_transform_set_execution_mode(SpfftTransform transform, SpfftExecType mode);

/* ---- single precision ---------------------------------------------------- */

typedef void* SpfftFloatTransform;

SpfftError spfft_float_transform_create_independent(
    SpfftFloatTransform* transform, int maxNumThreads,
    SpfftProcessingUnitType processingUnit, SpfftTransformType transformType, int dimX,
    int dimY, int dimZ, int numLocalElements, SpfftIndexFormatType indexFormat,
    const int* indices);

SpfftError spfft_float_transform_create(SpfftFloatTransform* transform, SpfftFloatGrid grid,
                                        SpfftProcessingUnitType processingUnit,
                                        SpfftTransformType transformType, int dimX,
                                        int dimY, int dimZ, int localZLength,
                                        int numLocalElements,
                                        SpfftIndexFormatType indexFormat,
                                        const int* indices);

SpfftError spfft_float_transform_destroy(SpfftFloatTransform transform);
SpfftError spfft_float_transform_clone(SpfftFloatTransform transform,
                                       SpfftFloatTransform* newTransform);

SpfftError spfft_float_transform_backward(SpfftFloatTransform transform,
                                          const float* input,
                                          SpfftProcessingUnitType outputLocation);
SpfftError spfft_float_transform_forward(SpfftFloatTransform transform,
                                         SpfftProcessingUnitType inputLocation,
                                         float* output, SpfftScalingType scaling);
SpfftError spfft_float_transform_forward_ptr(SpfftFloatTransform transform,
                                             const float* input, float* output,
                                             SpfftScalingType scaling);
SpfftError spfft_float_transform_get_space_domain(SpfftFloatTransform transform,
                                                  SpfftProcessingUnitType dataLocation,
                                                  float** data);

SpfftError spfft_float_transform_type(SpfftFloatTransform transform,
                                      SpfftTransformType* type);
SpfftError spfft_float_transform_dim_x(SpfftFloatTransform transform, int* dimX);
SpfftError spfft_float_transform_dim_y(SpfftFloatTransform transform, int* dimY);
SpfftError spfft_float_transform_dim_z(SpfftFloatTransform transform, int* dimZ);
SpfftError spfft_float_transform_local_z_length(SpfftFloatTransform transform,
                                                int* localZLength);
SpfftError spfft_float_transform_local_z_offset(SpfftFloatTransform transform,
                                                int* offset);
SpfftError spfft_float_transform_num_local_elements(SpfftFloatTransform transform,
                                                    int* numLocalElements);
SpfftError spfft_float_transform_processing_unit(SpfftFloatTransform transform,
                                                 SpfftProcessingUnitType* processingUnit);
SpfftError spfft_float_transform_execution_mode(SpfftFloatTransform transform,
                                                SpfftExecType* mode);
SpfftError spfft_float_transform_set_execution_mode(SpfftFloatTransform transform,
                                                    SpfftExecType mode);

#ifdef __cplusplus
}
#endif

#endif /* SPFFT_TPU_TRANSFORM_H */
