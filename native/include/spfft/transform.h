/*
 * spfft_tpu native API — C Transform interface.
 *
 * Opaque-handle mirror of the C++ Transform/TransformFloat (reference:
 * include/spfft/transform.h, transform_float.h). Handles are created either
 * grid-less or from an SpfftGrid; all functions return SpfftError.
 *
 * Embedding note: the first double-precision plan created through this API
 * enables 64-bit mode (jax_enable_x64) in the embedded Python/JAX runtime.
 * That flag is process-global — if the embedding application also uses JAX in
 * the same process, default array dtypes there widen from that point on. Use
 * the float entry points (spfft_float_*) to avoid it.
 */
#ifndef SPFFT_TPU_TRANSFORM_H
#define SPFFT_TPU_TRANSFORM_H

#include <spfft/errors.h>
#include <spfft/grid.h>
#include <spfft/types.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef void* SpfftTransform;

/* Grid-less creation (reference v1.0 feature). */
SpfftError spfft_transform_create_independent(
    SpfftTransform* transform, int maxNumThreads,
    SpfftProcessingUnitType processingUnit, SpfftTransformType transformType, int dimX,
    int dimY, int dimZ, int numLocalElements, SpfftIndexFormatType indexFormat,
    const int* indices);

/* Creation bound to a grid (reference: include/spfft/transform.h
 * spfft_transform_create). */
SpfftError spfft_transform_create(SpfftTransform* transform, SpfftGrid grid,
                                  SpfftProcessingUnitType processingUnit,
                                  SpfftTransformType transformType, int dimX, int dimY,
                                  int dimZ, int localZLength, int numLocalElements,
                                  SpfftIndexFormatType indexFormat, const int* indices);

SpfftError spfft_transform_destroy(SpfftTransform transform);
SpfftError spfft_transform_clone(SpfftTransform transform, SpfftTransform* newTransform);

SpfftError spfft_transform_backward(SpfftTransform transform, const double* input,
                                    SpfftProcessingUnitType outputLocation);
SpfftError spfft_transform_forward(SpfftTransform transform,
                                   SpfftProcessingUnitType inputLocation, double* output,
                                   SpfftScalingType scaling);
SpfftError spfft_transform_forward_ptr(SpfftTransform transform, const double* input,
                                       double* output, SpfftScalingType scaling);
/* Pointer-output backward: the space-domain slab is also written to
 * ``output`` (reference: transform.h spfft_transform_backward_ptr). */
SpfftError spfft_transform_backward_ptr(SpfftTransform transform, const double* input,
                                        double* output);
SpfftError spfft_transform_get_space_domain(SpfftTransform transform,
                                            SpfftProcessingUnitType dataLocation,
                                            double** data);

SpfftError spfft_transform_type(SpfftTransform transform, SpfftTransformType* type);
SpfftError spfft_transform_dim_x(SpfftTransform transform, int* dimX);
SpfftError spfft_transform_dim_y(SpfftTransform transform, int* dimY);
SpfftError spfft_transform_dim_z(SpfftTransform transform, int* dimZ);
SpfftError spfft_transform_local_z_length(SpfftTransform transform, int* localZLength);
SpfftError spfft_transform_local_z_offset(SpfftTransform transform, int* offset);
SpfftError spfft_transform_local_slice_size(SpfftTransform transform, int* size);
SpfftError spfft_transform_num_local_elements(SpfftTransform transform, int* numLocalElements);
SpfftError spfft_transform_num_global_elements(SpfftTransform transform,
                                               long long int* numGlobalElements);
SpfftError spfft_transform_global_size(SpfftTransform transform, long long int* globalSize);
SpfftError spfft_transform_processing_unit(SpfftTransform transform,
                                           SpfftProcessingUnitType* processingUnit);
SpfftError spfft_transform_device_id(SpfftTransform transform, int* deviceId);
SpfftError spfft_transform_num_threads(SpfftTransform transform, int* numThreads);
SpfftError spfft_transform_execution_mode(SpfftTransform transform, SpfftExecType* mode);
SpfftError spfft_transform_set_execution_mode(SpfftTransform transform, SpfftExecType mode);

/* ---- single precision ---------------------------------------------------- */

typedef void* SpfftFloatTransform;

SpfftError spfft_float_transform_create_independent(
    SpfftFloatTransform* transform, int maxNumThreads,
    SpfftProcessingUnitType processingUnit, SpfftTransformType transformType, int dimX,
    int dimY, int dimZ, int numLocalElements, SpfftIndexFormatType indexFormat,
    const int* indices);

SpfftError spfft_float_transform_create(SpfftFloatTransform* transform, SpfftFloatGrid grid,
                                        SpfftProcessingUnitType processingUnit,
                                        SpfftTransformType transformType, int dimX,
                                        int dimY, int dimZ, int localZLength,
                                        int numLocalElements,
                                        SpfftIndexFormatType indexFormat,
                                        const int* indices);

SpfftError spfft_float_transform_destroy(SpfftFloatTransform transform);
SpfftError spfft_float_transform_clone(SpfftFloatTransform transform,
                                       SpfftFloatTransform* newTransform);

SpfftError spfft_float_transform_backward(SpfftFloatTransform transform,
                                          const float* input,
                                          SpfftProcessingUnitType outputLocation);
SpfftError spfft_float_transform_forward(SpfftFloatTransform transform,
                                         SpfftProcessingUnitType inputLocation,
                                         float* output, SpfftScalingType scaling);
SpfftError spfft_float_transform_forward_ptr(SpfftFloatTransform transform,
                                             const float* input, float* output,
                                             SpfftScalingType scaling);
SpfftError spfft_float_transform_backward_ptr(SpfftFloatTransform transform,
                                              const float* input, float* output);
SpfftError spfft_float_transform_get_space_domain(SpfftFloatTransform transform,
                                                  SpfftProcessingUnitType dataLocation,
                                                  float** data);

SpfftError spfft_float_transform_type(SpfftFloatTransform transform,
                                      SpfftTransformType* type);
SpfftError spfft_float_transform_dim_x(SpfftFloatTransform transform, int* dimX);
SpfftError spfft_float_transform_dim_y(SpfftFloatTransform transform, int* dimY);
SpfftError spfft_float_transform_dim_z(SpfftFloatTransform transform, int* dimZ);
SpfftError spfft_float_transform_local_z_length(SpfftFloatTransform transform,
                                                int* localZLength);
SpfftError spfft_float_transform_local_z_offset(SpfftFloatTransform transform,
                                                int* offset);
SpfftError spfft_float_transform_local_slice_size(SpfftFloatTransform transform,
                                                  int* size);
SpfftError spfft_float_transform_num_local_elements(SpfftFloatTransform transform,
                                                    int* numLocalElements);
SpfftError spfft_float_transform_num_global_elements(SpfftFloatTransform transform,
                                                     long long int* numGlobalElements);
SpfftError spfft_float_transform_global_size(SpfftFloatTransform transform,
                                             long long int* globalSize);
SpfftError spfft_float_transform_processing_unit(SpfftFloatTransform transform,
                                                 SpfftProcessingUnitType* processingUnit);
SpfftError spfft_float_transform_device_id(SpfftFloatTransform transform, int* deviceId);
SpfftError spfft_float_transform_num_threads(SpfftFloatTransform transform,
                                             int* numThreads);
SpfftError spfft_float_transform_execution_mode(SpfftFloatTransform transform,
                                                SpfftExecType* mode);
SpfftError spfft_float_transform_set_execution_mode(SpfftFloatTransform transform,
                                                    SpfftExecType mode);

/* MPI-surface parity stubs (reference: include/spfft/transform.h:122,341 and
 * transform_float.h). No MPI exists in this runtime — the device mesh replaces
 * the communicator (use spfft_grid_create_distributed / the
 * spfft_dist_transform_* surface instead) — so these link and return
 * SPFFT_MPI_SUPPORT_ERROR, exactly what a ported caller can handle.
 * SpfftMpiComm (types.h) is MPI_Comm whenever the caller compiles with MPI. */
SpfftError spfft_transform_create_independent_distributed(
    SpfftTransform* transform, int maxNumThreads, SpfftMpiComm comm,
    SpfftExchangeType exchangeType, SpfftProcessingUnitType processingUnit,
    SpfftTransformType transformType, int dimX, int dimY, int dimZ, int localZLength,
    int numLocalElements, SpfftIndexFormatType indexFormat, const int* indices);
SpfftError spfft_float_transform_create_independent_distributed(
    SpfftFloatTransform* transform, int maxNumThreads, SpfftMpiComm comm,
    SpfftExchangeType exchangeType, SpfftProcessingUnitType processingUnit,
    SpfftTransformType transformType, int dimX, int dimY, int dimZ, int localZLength,
    int numLocalElements, SpfftIndexFormatType indexFormat, const int* indices);
SpfftError spfft_transform_communicator(SpfftTransform transform, SpfftMpiComm* comm);
SpfftError spfft_float_transform_communicator(SpfftFloatTransform transform,
                                              SpfftMpiComm* comm);

/* ---- distributed transforms (single-controller mesh) ----------------------
 * One process drives every shard; per-rank MPI arrays become shard-major
 * concatenated host arrays. Precision is fixed at creation
 * (doublePrecision != 0 -> double entry points, == 0 -> float ones). */

typedef void* SpfftDistTransform;

SpfftError spfft_dist_transform_create(SpfftDistTransform* transform, SpfftGrid grid,
                                       SpfftProcessingUnitType processingUnit,
                                       SpfftTransformType transformType, int dimX,
                                       int dimY, int dimZ, int numShards,
                                       const int* shardNumElements,
                                       SpfftIndexFormatType indexFormat,
                                       const int* indices, int doublePrecision);
/* Grid-less distributed ctor (reference: transform.h
 * spfft_transform_create_independent_distributed, single-controller form:
 * numShards + exchangeType replace the MPI communicator; the capacity
 * envelope a Grid would carry is derived internally). */
SpfftError spfft_dist_transform_create_independent(
    SpfftDistTransform* transform, int maxNumThreads, int numShards,
    SpfftExchangeType exchangeType, SpfftProcessingUnitType processingUnit,
    SpfftTransformType transformType, int dimX, int dimY, int dimZ,
    const int* shardNumElements, SpfftIndexFormatType indexFormat,
    const int* indices, int doublePrecision);
SpfftError spfft_dist_transform_destroy(SpfftDistTransform transform);

/* values: 2 * num_global_elements reals, shard-major complex-interleaved;
 * space: global (dimZ, dimY, dimX) slab (complex for C2C, real for R2C). */
SpfftError spfft_dist_transform_backward(SpfftDistTransform transform,
                                         const double* values, double* space);
SpfftError spfft_float_dist_transform_backward(SpfftDistTransform transform,
                                               const float* values, float* space);
/* space may be NULL to reuse the slabs retained by the last backward. */
SpfftError spfft_dist_transform_forward(SpfftDistTransform transform,
                                        const double* space, double* values,
                                        SpfftScalingType scaling);
SpfftError spfft_float_dist_transform_forward(SpfftDistTransform transform,
                                              const float* space, float* values,
                                              SpfftScalingType scaling);

SpfftError spfft_dist_transform_type(SpfftDistTransform transform,
                                     SpfftTransformType* type);
SpfftError spfft_dist_transform_dim_x(SpfftDistTransform transform, int* dimX);
SpfftError spfft_dist_transform_dim_y(SpfftDistTransform transform, int* dimY);
SpfftError spfft_dist_transform_dim_z(SpfftDistTransform transform, int* dimZ);
SpfftError spfft_dist_transform_num_shards(SpfftDistTransform transform, int* numShards);
SpfftError spfft_dist_transform_num_global_elements(SpfftDistTransform transform,
                                                    long long int* numGlobalElements);
SpfftError spfft_dist_transform_global_size(SpfftDistTransform transform,
                                            long long int* globalSize);
SpfftError spfft_dist_transform_exchange_type(SpfftDistTransform transform,
                                              SpfftExchangeType* exchangeType);
SpfftError spfft_dist_transform_exchange_wire_bytes(SpfftDistTransform transform,
                                                    long long int* wireBytes);
SpfftError spfft_dist_transform_exchange_rounds(SpfftDistTransform transform,
                                                int* rounds);
/* per-shard layout (the reference's per-rank accessors). On 2-D pencil grids
 * the space block is (local_z_length, local_y_length, dimX); on 1-D grids
 * local_y_length == dimY and local_y_offset == 0. */
SpfftError spfft_dist_transform_local_z_length(SpfftDistTransform transform, int shard,
                                               int* localZLength);
SpfftError spfft_dist_transform_local_z_offset(SpfftDistTransform transform, int shard,
                                               int* offset);
SpfftError spfft_dist_transform_local_y_length(SpfftDistTransform transform, int shard,
                                               int* localYLength);
SpfftError spfft_dist_transform_local_y_offset(SpfftDistTransform transform, int shard,
                                               int* offset);
SpfftError spfft_dist_transform_num_local_elements(SpfftDistTransform transform,
                                                   int shard, int* numLocalElements);

#ifdef __cplusplus
}
#endif

#endif /* SPFFT_TPU_TRANSFORM_H */
