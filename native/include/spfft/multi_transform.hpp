/*
 * spfft_tpu native API — batched multi-transform execution (C++).
 *
 * Executes N independent transforms with pipelined dispatch: all device
 * programs are enqueued before any result is awaited, so XLA overlaps the
 * transforms (reference: include/spfft/multi_transform.hpp:48-95, whose
 * pipelining interleaves CPU and GPU stages the same way).
 */
#ifndef SPFFT_TPU_MULTI_TRANSFORM_HPP
#define SPFFT_TPU_MULTI_TRANSFORM_HPP

#include <spfft/transform.hpp>
#include <spfft/types.h>

namespace spfft {

/* Freq -> space for each transform i; results land in each transform's
 * space_domain_data(). */
void multi_transform_backward(int num_transforms, Transform* transforms,
                              const double* const* input,
                              const SpfftProcessingUnitType* output_locations);

/* Space -> freq, reading each transform's space_domain_data(). */
void multi_transform_forward(int num_transforms, Transform* transforms,
                             const SpfftProcessingUnitType* input_locations,
                             double* const* output, const SpfftScalingType* scaling_types);

void multi_transform_backward(int num_transforms, TransformFloat* transforms,
                              const float* const* input,
                              const SpfftProcessingUnitType* output_locations);

void multi_transform_forward(int num_transforms, TransformFloat* transforms,
                             const SpfftProcessingUnitType* input_locations,
                             float* const* output, const SpfftScalingType* scaling_types);

/* Pointer-based overloads (reference: include/spfft/multi_transform.hpp:64-95):
 * the space-domain side reads from / writes to caller pointers instead of each
 * transform's internal space buffer. */
void multi_transform_backward(int num_transforms, Transform* transforms,
                              const double* const* input, double* const* space_output);

void multi_transform_forward(int num_transforms, Transform* transforms,
                             const double* const* space_input, double* const* output,
                             const SpfftScalingType* scaling_types);

void multi_transform_backward(int num_transforms, TransformFloat* transforms,
                              const float* const* input, float* const* space_output);

void multi_transform_forward(int num_transforms, TransformFloat* transforms,
                             const float* const* space_input, float* const* output,
                             const SpfftScalingType* scaling_types);

} // namespace spfft

#endif // SPFFT_TPU_MULTI_TRANSFORM_HPP
