/*
 * spfft_tpu native API — single-precision C Transform interface
 * (reference: include/spfft/transform_float.h).
 *
 * The spfft_float_transform_* surface is declared alongside the double tier
 * in transform.h; this header exists so callers that include
 * <spfft/transform_float.h> directly compile unchanged.
 */
#ifndef SPFFT_TPU_TRANSFORM_FLOAT_H
#define SPFFT_TPU_TRANSFORM_FLOAT_H

#include <spfft/transform.h>

#endif /* SPFFT_TPU_TRANSFORM_FLOAT_H */
