/*
 * spfft_tpu native API — C multi-transform interface.
 *
 * Batched execution of independent transforms with pipelined dispatch
 * (reference: include/spfft/multi_transform.h).
 */
#ifndef SPFFT_TPU_MULTI_TRANSFORM_H
#define SPFFT_TPU_MULTI_TRANSFORM_H

#include <spfft/errors.h>
#include <spfft/transform.h>
#include <spfft/types.h>

#ifdef __cplusplus
extern "C" {
#endif

SpfftError spfft_multi_transform_backward(int numTransforms, SpfftTransform* transforms,
                                          const double* const* input,
                                          const SpfftProcessingUnitType* outputLocations);

SpfftError spfft_multi_transform_forward(int numTransforms, SpfftTransform* transforms,
                                         const SpfftProcessingUnitType* inputLocations,
                                         double* const* output,
                                         const SpfftScalingType* scalingTypes);

SpfftError spfft_float_multi_transform_backward(
    int numTransforms, SpfftFloatTransform* transforms, const float* const* input,
    const SpfftProcessingUnitType* outputLocations);

SpfftError spfft_float_multi_transform_forward(
    int numTransforms, SpfftFloatTransform* transforms,
    const SpfftProcessingUnitType* inputLocations, float* const* output,
    const SpfftScalingType* scalingTypes);

#ifdef __cplusplus
}
#endif

#endif /* SPFFT_TPU_MULTI_TRANSFORM_H */
