/*
 * spfft_tpu native API — C multi-transform interface.
 *
 * Batched execution of independent transforms with pipelined dispatch
 * (reference: include/spfft/multi_transform.h).
 */
#ifndef SPFFT_TPU_MULTI_TRANSFORM_H
#define SPFFT_TPU_MULTI_TRANSFORM_H

#include <spfft/errors.h>
#include <spfft/transform.h>
#include <spfft/types.h>

#ifdef __cplusplus
extern "C" {
#endif

SpfftError spfft_multi_transform_backward(int numTransforms, SpfftTransform* transforms,
                                          const double* const* input,
                                          const SpfftProcessingUnitType* outputLocations);

SpfftError spfft_multi_transform_forward(int numTransforms, SpfftTransform* transforms,
                                         const SpfftProcessingUnitType* inputLocations,
                                         double* const* output,
                                         const SpfftScalingType* scalingTypes);

SpfftError spfft_float_multi_transform_backward(
    int numTransforms, SpfftFloatTransform* transforms, const float* const* input,
    const SpfftProcessingUnitType* outputLocations);

SpfftError spfft_float_multi_transform_forward(
    int numTransforms, SpfftFloatTransform* transforms,
    const SpfftProcessingUnitType* inputLocations, float* const* output,
    const SpfftScalingType* scalingTypes);

/* Pointer-based batch overloads (reference: include/spfft/multi_transform.h:60-95):
 * the space-domain side is a caller-provided pointer per transform instead of
 * each transform's internal space_domain_data() buffer. */

SpfftError spfft_multi_transform_forward_ptr(int numTransforms,
                                             SpfftTransform* transforms,
                                             const double* const* inputPointers,
                                             double* const* outputPointers,
                                             const SpfftScalingType* scalingTypes);

SpfftError spfft_multi_transform_backward_ptr(int numTransforms,
                                              SpfftTransform* transforms,
                                              const double* const* inputPointers,
                                              double* const* outputPointers);

SpfftError spfft_float_multi_transform_forward_ptr(int numTransforms,
                                                   SpfftFloatTransform* transforms,
                                                   const float* const* inputPointers,
                                                   float* const* outputPointers,
                                                   const SpfftScalingType* scalingTypes);

SpfftError spfft_float_multi_transform_backward_ptr(int numTransforms,
                                                    SpfftFloatTransform* transforms,
                                                    const float* const* inputPointers,
                                                    float* const* outputPointers);

#ifdef __cplusplus
}
#endif

#endif /* SPFFT_TPU_MULTI_TRANSFORM_H */
