/*
 * spfft_tpu native API — build configuration (reference: include/spfft/config.h.in,
 * CMake-generated there; static here because this build has exactly one
 * configuration).
 *
 * Feature macros a ported caller may test:
 *  - SPFFT_SINGLE_PRECISION: always on — the float tier (TransformFloat /
 *    GridFloat / spfft_float_*) ships unconditionally (the reference gates it
 *    behind a CMake option).
 *  - SPFFT_CUDA / SPFFT_ROCM / SPFFT_MPI / SPFFT_OMP / SPFFT_GPU_DIRECT:
 *    never defined. The accelerator is a TPU driven through XLA
 *    (SPFFT_PU_GPU maps to it), distribution runs over a device mesh instead
 *    of MPI (docs/api/c_api.md), and threading is owned by the runtime.
 *  - SPFFT_TIMING: always on — the timing tree is runtime-collected
 *    (spfft_tpu.timing) rather than compile-time gated.
 */
#ifndef SPFFT_CONFIG_H
#define SPFFT_CONFIG_H

#define SPFFT_SINGLE_PRECISION
#define SPFFT_TIMING

#include "spfft/spfft_export.h"

#endif
