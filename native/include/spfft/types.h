/*
 * spfft_tpu native API — public enum surface.
 *
 * ABI-compatible with the reference SpFFT C enums (reference:
 * include/spfft/types.h:33-117) so existing callers recompile unchanged.
 * Semantics on the TPU build:
 *  - BUFFERED lowers to one equal-split ICI all-to-all on padded blocks;
 *    COMPACT_BUFFERED/UNBUFFERED send exact per-rank-pair blocks via a
 *    P-1-round permute chain (Alltoallv/Alltoallw semantics).
 *  - SPFFT_PU_GPU selects the accelerator (TPU) backend.
 */
#ifndef SPFFT_TPU_TYPES_H
#define SPFFT_TPU_TYPES_H

/* Communicator type for the MPI-surface parity stubs (reference:
 * include/spfft/grid.h:35-37 includes <mpi.h> under SPFFT_MPI and uses
 * MPI_Comm directly). When the caller builds with MPI this IS MPI_Comm, so
 * reference call sites compile unchanged; otherwise it is an opaque
 * placeholder — the stubs return SPFFT_MPI_SUPPORT_ERROR without reading it
 * (no MPI exists in this runtime; the device mesh replaces the communicator).
 *
 * ABI note: the library TU compiles without <mpi.h>, so a caller built with
 * an int-typed MPI_Comm (MPICH) passes a different by-value parameter type
 * than the TU declares (void*). The stubs never read the argument, and every
 * supported ABI (x86-64 SysV/Win64, AArch64 AAPCS) passes both int and
 * pointer scalars in the same argument register, so the call is benign —
 * but it relies on register passing of scalar arguments; an ABI that
 * class-splits them differently would need the library rebuilt with MPI
 * headers present (which makes the types identical). */
#if defined(SPFFT_MPI) || defined(MPI_VERSION)
#ifndef MPI_VERSION
#include <mpi.h>
#endif
typedef MPI_Comm SpfftMpiComm;
#else
typedef void* SpfftMpiComm;
#endif

enum SpfftExchangeType {
  /* DIVERGENCE from the reference: there DEFAULT == COMPACT_BUFFERED; here it
   * is a measured auto-policy — the runtime picks the discipline per plan
   * from its exact wire volumes, round counts, and backend collective
   * support (spfft_tpu/parallel/policy.py). Pass COMPACT_BUFFERED explicitly
   * for the reference's exact-counts wire behavior. */
  SPFFT_EXCH_DEFAULT = 0,
  /* Equal-sized message blocks; the native ICI all-to-all discipline. */
  SPFFT_EXCH_BUFFERED = 1,
  /* Same, single-precision wire payload (half the ICI bytes). */
  SPFFT_EXCH_BUFFERED_FLOAT = 2,
  /* Exact per-rank-pair block sizes (Alltoallv semantics), via a P-1-round
   * permute chain. */
  SPFFT_EXCH_COMPACT_BUFFERED = 3,
  SPFFT_EXCH_COMPACT_BUFFERED_FLOAT = 4,
  /* Zero-copy datatype exchange in the reference; maps to the same exact-counts
   * chain here. */
  SPFFT_EXCH_UNBUFFERED = 5,
  /* TPU extensions (beyond the reference enum): explicit bfloat16 wire payload
   * — halves ICI bytes vs an f32 wire (quarters vs f64). Accuracy ~1e-2
   * relative, NOT held to the 1e-6 parity bar; opt-in only. */
  SPFFT_EXCH_BUFFERED_BF16 = 6,
  SPFFT_EXCH_COMPACT_BUFFERED_BF16 = 7
};

/* Bitmask: a Grid may hold capacity for both units at once. */
enum SpfftProcessingUnitType {
  SPFFT_PU_HOST = 1,
  SPFFT_PU_GPU = 2 /* the TPU in this build; name kept for source parity */
};

enum SpfftIndexFormatType { SPFFT_INDEX_TRIPLETS = 0 };

enum SpfftTransformType { SPFFT_TRANS_C2C = 0, SPFFT_TRANS_R2C = 1 };

enum SpfftScalingType { SPFFT_NO_SCALING = 0, SPFFT_FULL_SCALING = 1 };

enum SpfftExecType { SPFFT_EXEC_SYNCHRONOUS = 0, SPFFT_EXEC_ASYNCHRONOUS = 1 };

#ifndef __cplusplus
typedef enum SpfftExchangeType SpfftExchangeType;
typedef enum SpfftProcessingUnitType SpfftProcessingUnitType;
typedef enum SpfftIndexFormatType SpfftIndexFormatType;
typedef enum SpfftTransformType SpfftTransformType;
typedef enum SpfftScalingType SpfftScalingType;
typedef enum SpfftExecType SpfftExecType;
#endif

#endif /* SPFFT_TPU_TYPES_H */
