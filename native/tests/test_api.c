/*
 * C-linkage end-to-end test of the spfft_tpu native API.
 *
 * Exercises the same flow as the reference example (reference:
 * examples/example.c): build index triplets, create grid + transform,
 * backward into the space domain, read space_domain_data, forward back with
 * scaling, verify the round trip. Also checks the float API, clone,
 * multi-transform and error-code behavior.
 */
#include <math.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include <spfft/spfft.h>

#define CHECK(expr)                                                                      \
  do {                                                                                   \
    SpfftError e_ = (expr);                                                              \
    if (e_ != SPFFT_SUCCESS) {                                                           \
      fprintf(stderr, "FAIL %s:%d: %s -> %d\n", __FILE__, __LINE__, #expr, (int)e_);     \
      return 1;                                                                          \
    }                                                                                    \
  } while (0)

#define REQUIRE(cond)                                                                    \
  do {                                                                                   \
    if (!(cond)) {                                                                       \
      fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond);                    \
      return 1;                                                                          \
    }                                                                                    \
  } while (0)

static unsigned int rng_state = 42u;
static double rng_uniform(void) {
  rng_state = rng_state * 1664525u + 1013904223u;
  return (double)(rng_state >> 8) / (double)(1u << 24) - 0.5;
}

int main(void) {
  const int dim = 8;
  const int n = dim * dim * dim;
  /* Virtual 4-device CPU mesh for the distributed section; must be set before
   * the first API call initializes the embedded runtime. */
  setenv("SPFFT_TPU_NUM_CPU_DEVICES", "4", 1);
  int* indices = (int*)malloc((size_t)(3 * n) * sizeof(int));
  int x, y, z, i, k = 0;
  for (x = 0; x < dim; ++x)
    for (y = 0; y < dim; ++y)
      for (z = 0; z < dim; ++z) {
        indices[k++] = x;
        indices[k++] = y;
        indices[k++] = z;
      }

  /* ---- double precision, grid-based -------------------------------------- */
  SpfftGrid grid = NULL;
  CHECK(spfft_grid_create(&grid, dim, dim, dim, dim * dim, SPFFT_PU_HOST, 1));

  int got = 0;
  CHECK(spfft_grid_max_dim_x(grid, &got));
  REQUIRE(got == dim);

  SpfftTransform t = NULL;
  CHECK(spfft_transform_create(&t, grid, SPFFT_PU_HOST, SPFFT_TRANS_C2C, dim, dim, dim,
                               dim, n, SPFFT_INDEX_TRIPLETS, indices));

  CHECK(spfft_transform_dim_x(t, &got));
  REQUIRE(got == dim);
  CHECK(spfft_transform_num_local_elements(t, &got));
  REQUIRE(got == n);
  long long gs = 0;
  CHECK(spfft_transform_global_size(t, &gs));
  REQUIRE(gs == (long long)n);

  double* freq = (double*)malloc((size_t)(2 * n) * sizeof(double));
  for (i = 0; i < 2 * n; ++i) freq[i] = rng_uniform();

  CHECK(spfft_transform_backward(t, freq, SPFFT_PU_HOST));

  double* space = NULL;
  CHECK(spfft_transform_get_space_domain(t, SPFFT_PU_HOST, &space));
  REQUIRE(space != NULL);

  /* Round trip with full scaling must reproduce the input. */
  double* back = (double*)malloc((size_t)(2 * n) * sizeof(double));
  CHECK(spfft_transform_forward(t, SPFFT_PU_HOST, back, SPFFT_FULL_SCALING));
  {
    double max_err = 0.0;
    for (i = 0; i < 2 * n; ++i) {
      double d = fabs(back[i] - freq[i]);
      if (d > max_err) max_err = d;
    }
    printf("double roundtrip max err: %g\n", max_err);
    REQUIRE(max_err < 1e-6);
  }

  /* backward_ptr writes the same slab to a caller buffer. */
  {
    double* slab = (double*)malloc((size_t)(2 * n) * sizeof(double));
    CHECK(spfft_transform_backward_ptr(t, freq, slab));
    {
      double max_err = 0.0;
      for (i = 0; i < 2 * n; ++i) {
        double d = fabs(slab[i] - space[i]);
        if (d > max_err) max_err = d;
      }
      REQUIRE(max_err == 0.0); /* identical bytes: same backward, same slab */
    }
    free(slab);
  }

  /* Write-then-forward through the space-domain pointer: scale by 2. */
  for (i = 0; i < 2 * n; ++i) space[i] *= 2.0;
  CHECK(spfft_transform_forward(t, SPFFT_PU_HOST, back, SPFFT_FULL_SCALING));
  {
    double max_err = 0.0;
    for (i = 0; i < 2 * n; ++i) {
      double d = fabs(back[i] - 2.0 * freq[i]);
      if (d > max_err) max_err = d;
    }
    REQUIRE(max_err < 1e-6);
  }

  /* Clone is independent but same layout. */
  SpfftTransform tc = NULL;
  CHECK(spfft_transform_clone(t, &tc));
  CHECK(spfft_transform_dim_x(tc, &got));
  REQUIRE(got == dim);

  /* Multi-transform: run both plans batched. */
  {
    SpfftTransform pair[2];
    const double* inputs[2];
    double* outputs[2];
    SpfftProcessingUnitType locs[2] = {SPFFT_PU_HOST, SPFFT_PU_HOST};
    SpfftScalingType scals[2] = {SPFFT_FULL_SCALING, SPFFT_FULL_SCALING};
    double* back2 = (double*)malloc((size_t)(2 * n) * sizeof(double));
    pair[0] = t;
    pair[1] = tc;
    inputs[0] = freq;
    inputs[1] = freq;
    outputs[0] = back;
    outputs[1] = back2;
    CHECK(spfft_multi_transform_backward(2, pair, inputs, locs));
    CHECK(spfft_multi_transform_forward(2, pair, locs, outputs, scals));
    {
      double max_err = 0.0;
      for (i = 0; i < 2 * n; ++i) {
        double d = fabs(back2[i] - freq[i]);
        if (d > max_err) max_err = d;
      }
      REQUIRE(max_err < 1e-6);
    }
    free(back2);
  }

  /* ---- single precision, grid-less ---------------------------------------- */
  {
    SpfftFloatTransform ft = NULL;
    float* ffreq = (float*)malloc((size_t)(2 * n) * sizeof(float));
    float* fback = (float*)malloc((size_t)(2 * n) * sizeof(float));
    for (i = 0; i < 2 * n; ++i) ffreq[i] = (float)rng_uniform();
    CHECK(spfft_float_transform_create_independent(&ft, 1, SPFFT_PU_HOST,
                                                   SPFFT_TRANS_C2C, dim, dim, dim, n,
                                                   SPFFT_INDEX_TRIPLETS, indices));
    float* fslab = (float*)malloc((size_t)(2 * n) * sizeof(float));
    float* fspace = NULL;
    CHECK(spfft_float_transform_backward(ft, ffreq, SPFFT_PU_HOST));
    CHECK(spfft_float_transform_backward_ptr(ft, ffreq, fslab));
    CHECK(spfft_float_transform_get_space_domain(ft, SPFFT_PU_HOST, &fspace));
    for (i = 0; i < 2 * n; ++i) REQUIRE(fslab[i] == fspace[i]);
    free(fslab);
    CHECK(spfft_float_transform_forward(ft, SPFFT_PU_HOST, fback, SPFFT_FULL_SCALING));
    {
      double max_err = 0.0;
      for (i = 0; i < 2 * n; ++i) {
        double d = fabs((double)fback[i] - (double)ffreq[i]);
        if (d > max_err) max_err = d;
      }
      printf("float roundtrip max err: %g\n", max_err);
      REQUIRE(max_err < 1e-4);
    }
    CHECK(spfft_float_transform_destroy(ft));
    free(ffreq);
    free(fback);
  }

  /* ---- single precision, grid-based (reference: grid_float.h surface) ----- */
  {
    SpfftFloatGrid fgrid = NULL;
    SpfftFloatTransform ft = NULL;
    SpfftProcessingUnitType fpu;
    float* ffreq = (float*)malloc((size_t)(2 * n) * sizeof(float));
    float* fback = (float*)malloc((size_t)(2 * n) * sizeof(float));
    int fgot = 0;
    for (i = 0; i < 2 * n; ++i) ffreq[i] = (float)rng_uniform();
    CHECK(spfft_float_grid_create(&fgrid, dim, dim, dim, dim * dim, SPFFT_PU_HOST, 1));
    CHECK(spfft_float_grid_max_dim_x(fgrid, &fgot));
    REQUIRE(fgot == dim);
    CHECK(spfft_float_grid_max_dim_y(fgrid, &fgot));
    REQUIRE(fgot == dim);
    CHECK(spfft_float_grid_max_dim_z(fgrid, &fgot));
    REQUIRE(fgot == dim);
    CHECK(spfft_float_grid_max_num_local_z_columns(fgrid, &fgot));
    REQUIRE(fgot == dim * dim);
    CHECK(spfft_float_grid_processing_unit(fgrid, &fpu));
    REQUIRE(fpu == SPFFT_PU_HOST);
    CHECK(spfft_float_grid_num_threads(fgrid, &fgot));
    REQUIRE(fgot >= 1);
    CHECK(spfft_float_grid_device_id(fgrid, &fgot));
    CHECK(spfft_float_transform_create(&ft, fgrid, SPFFT_PU_HOST, SPFFT_TRANS_C2C, dim,
                                       dim, dim, dim, n, SPFFT_INDEX_TRIPLETS, indices));
    /* Grid may be destroyed once the transform holds its capacity. */
    CHECK(spfft_float_grid_destroy(fgrid));
    {
      long long fgs = 0;
      CHECK(spfft_float_transform_local_slice_size(ft, &fgot));
      REQUIRE(fgot == n);
      CHECK(spfft_float_transform_num_global_elements(ft, &fgs));
      REQUIRE(fgs == (long long)n);
      CHECK(spfft_float_transform_global_size(ft, &fgs));
      REQUIRE(fgs == (long long)n);
      CHECK(spfft_float_transform_num_threads(ft, &fgot));
      REQUIRE(fgot >= 1);
      CHECK(spfft_float_transform_device_id(ft, &fgot));
    }
    CHECK(spfft_float_transform_backward(ft, ffreq, SPFFT_PU_HOST));
    CHECK(spfft_float_transform_forward(ft, SPFFT_PU_HOST, fback, SPFFT_FULL_SCALING));
    {
      double max_err = 0.0;
      for (i = 0; i < 2 * n; ++i) {
        double d = fabs((double)fback[i] - (double)ffreq[i]);
        if (d > max_err) max_err = d;
      }
      printf("float-grid roundtrip max err: %g\n", max_err);
      REQUIRE(max_err < 1e-4);
    }
    /* Float pointer-based batch (reference: multi_transform_float.h:60-95). */
    {
      SpfftFloatTransform one[1];
      const float* fins[1];
      float* fspaces[1];
      float* fouts[1];
      SpfftScalingType fscals[1] = {SPFFT_FULL_SCALING};
      float* fslab = (float*)malloc((size_t)(2 * n) * sizeof(float));
      float* fout = (float*)malloc((size_t)(2 * n) * sizeof(float));
      one[0] = ft;
      fins[0] = ffreq;
      fspaces[0] = fslab;
      fouts[0] = fout;
      CHECK(spfft_float_multi_transform_backward_ptr(1, one, fins, fspaces));
      CHECK(spfft_float_multi_transform_forward_ptr(1, one, (const float* const*)fspaces,
                                                    fouts, fscals));
      {
        double max_err = 0.0;
        for (i = 0; i < 2 * n; ++i) {
          double d = fabs((double)fout[i] - (double)ffreq[i]);
          if (d > max_err) max_err = d;
        }
        REQUIRE(max_err < 1e-4);
      }
      free(fslab);
      free(fout);
    }
    CHECK(spfft_float_transform_destroy(ft));
    free(ffreq);
    free(fback);
  }

  /* ---- pointer-based double batch (reference: multi_transform.h:60-95) ---- */
  {
    SpfftTransform one[1];
    const double* ins[1];
    double* spaces[1];
    double* outs[1];
    SpfftScalingType scals1[1] = {SPFFT_FULL_SCALING};
    double* slab = (double*)malloc((size_t)(2 * n) * sizeof(double));
    double* out = (double*)malloc((size_t)(2 * n) * sizeof(double));
    one[0] = t;
    ins[0] = freq;
    spaces[0] = slab;
    outs[0] = out;
    CHECK(spfft_multi_transform_backward_ptr(1, one, ins, spaces));
    CHECK(spfft_multi_transform_forward_ptr(1, one, (const double* const*)spaces, outs,
                                            scals1));
    {
      double max_err = 0.0;
      for (i = 0; i < 2 * n; ++i) {
        double d = fabs(out[i] - freq[i]);
        if (d > max_err) max_err = d;
      }
      REQUIRE(max_err < 1e-6);
    }
    free(slab);
    free(out);
  }

  /* ---- MPI-surface parity stubs link and fail cleanly --------------------- */
  {
    void* comm = NULL;
    SpfftTransform dt = NULL;
    REQUIRE(spfft_grid_communicator(grid, &comm) == SPFFT_MPI_SUPPORT_ERROR);
    REQUIRE(spfft_transform_communicator(t, &comm) == SPFFT_MPI_SUPPORT_ERROR);
    REQUIRE(spfft_float_grid_communicator(grid, &comm) == SPFFT_MPI_SUPPORT_ERROR);
    REQUIRE(spfft_transform_create_independent_distributed(
                &dt, 1, NULL, SPFFT_EXCH_DEFAULT, SPFFT_PU_HOST, SPFFT_TRANS_C2C, dim,
                dim, dim, dim, n, SPFFT_INDEX_TRIPLETS, indices) ==
            SPFFT_MPI_SUPPORT_ERROR);
  }

  /* ---- error behavior ----------------------------------------------------- */
  REQUIRE(spfft_transform_backward(NULL, freq, SPFFT_PU_HOST) ==
          SPFFT_INVALID_HANDLE_ERROR);
  {
    /* Out-of-bounds index triplet must be rejected with an indices error. */
    SpfftTransform bad = NULL;
    int bad_idx[3] = {dim + 5, 0, 0};
    SpfftError e = spfft_transform_create_independent(
        &bad, 1, SPFFT_PU_HOST, SPFFT_TRANS_C2C, dim, dim, dim, 1,
        SPFFT_INDEX_TRIPLETS, bad_idx);
    REQUIRE(e == SPFFT_INVALID_INDICES_ERROR || e == SPFFT_INVALID_PARAMETER_ERROR);
    /* Duplicate triplets must be rejected. */
    int dup_idx[6] = {1, 1, 1, 1, 1, 1};
    e = spfft_transform_create_independent(&bad, 1, SPFFT_PU_HOST, SPFFT_TRANS_C2C, dim,
                                           dim, dim, 2, SPFFT_INDEX_TRIPLETS, dup_idx);
    REQUIRE(e == SPFFT_DUPLICATE_INDICES_ERROR);
  }

  /* ---- distributed (single-controller 4-shard mesh) ----------------------- */
  {
    const int shards = 4;
    int counts[4];
    int* didx = (int*)malloc((size_t)(3 * n) * sizeof(int));
    double* dfreq = (double*)malloc((size_t)(2 * n) * sizeof(double));
    double* dback = (double*)malloc((size_t)(2 * n) * sizeof(double));
    double* dspace = (double*)malloc((size_t)(2 * n) * sizeof(double));
    int r, got2 = 0;
    long long ll = 0;
    k = 0;
    /* shard r owns sticks x in {2r, 2r+1}: shard-major concatenated triplets */
    for (r = 0; r < shards; ++r) {
      counts[r] = 2 * dim * dim;
      for (x = 2 * r; x < 2 * r + 2; ++x)
        for (y = 0; y < dim; ++y)
          for (z = 0; z < dim; ++z) {
            didx[k++] = x;
            didx[k++] = y;
            didx[k++] = z;
          }
    }
    for (i = 0; i < 2 * n; ++i) dfreq[i] = rng_uniform();

    SpfftGrid dgrid = NULL;
    CHECK(spfft_grid_create_distributed(&dgrid, dim, dim, dim, dim * dim, dim, shards,
                                        SPFFT_EXCH_COMPACT_BUFFERED, SPFFT_PU_HOST, 1));
    CHECK(spfft_grid_num_shards(dgrid, &got2));
    REQUIRE(got2 == shards);

    SpfftDistTransform dt = NULL;
    CHECK(spfft_dist_transform_create(&dt, dgrid, SPFFT_PU_HOST, SPFFT_TRANS_C2C, dim,
                                      dim, dim, shards, counts, SPFFT_INDEX_TRIPLETS,
                                      didx, 1));
    CHECK(spfft_dist_transform_num_shards(dt, &got2));
    REQUIRE(got2 == shards);
    CHECK(spfft_dist_transform_num_global_elements(dt, &ll));
    REQUIRE(ll == (long long)n);
    CHECK(spfft_dist_transform_local_z_length(dt, 0, &got2));
    REQUIRE(got2 == dim / shards);
    CHECK(spfft_dist_transform_num_local_elements(dt, 1, &got2));
    REQUIRE(got2 == counts[1]);
    CHECK(spfft_dist_transform_exchange_wire_bytes(dt, &ll));
    REQUIRE(ll > 0);
    {
      /* COMPACT_BUFFERED runs the ppermute chain: always shards-1 rounds,
       * backend-independent. */
      int rounds = 0;
      CHECK(spfft_dist_transform_exchange_rounds(dt, &rounds));
      REQUIRE(rounds == shards - 1);
    }

    CHECK(spfft_dist_transform_backward(dt, dfreq, dspace));
    /* explicit-space forward */
    CHECK(spfft_dist_transform_forward(dt, dspace, dback, SPFFT_FULL_SCALING));
    {
      double max_err = 0.0;
      for (i = 0; i < 2 * n; ++i) {
        double d = fabs(dback[i] - dfreq[i]);
        if (d > max_err) max_err = d;
      }
      printf("distributed roundtrip max err: %g\n", max_err);
      REQUIRE(max_err < 1e-6);
    }
    /* retained-space forward (NULL space pointer) */
    memset(dback, 0, (size_t)(2 * n) * sizeof(double));
    CHECK(spfft_dist_transform_forward(dt, NULL, dback, SPFFT_FULL_SCALING));
    {
      double max_err = 0.0;
      for (i = 0; i < 2 * n; ++i) {
        double d = fabs(dback[i] - dfreq[i]);
        if (d > max_err) max_err = d;
      }
      REQUIRE(max_err < 1e-6);
    }
    /* precision mismatch must be rejected, not misread */
    REQUIRE(spfft_float_dist_transform_backward(dt, (const float*)dfreq,
                                                (float*)dspace) ==
            SPFFT_INVALID_PARAMETER_ERROR);
    /* out-of-range shard index */
    REQUIRE(spfft_dist_transform_local_z_length(dt, shards, &got2) ==
            SPFFT_INVALID_PARAMETER_ERROR);

    /* 2-D pencil mesh grid (2x2) over the same 4 devices: same dist API */
    {
      SpfftGrid pgrid = NULL;
      SpfftDistTransform pt = NULL;
      CHECK(spfft_grid_create_distributed2(&pgrid, dim, dim, dim, dim * dim, dim, 2,
                                           2, SPFFT_EXCH_DEFAULT, SPFFT_PU_HOST, 1));
      CHECK(spfft_dist_transform_create(&pt, pgrid, SPFFT_PU_HOST, SPFFT_TRANS_C2C,
                                        dim, dim, dim, shards, counts,
                                        SPFFT_INDEX_TRIPLETS, didx, 1));
      CHECK(spfft_dist_transform_local_y_length(pt, 0, &got2));
      REQUIRE(got2 == dim / 2); /* y split over the first mesh axis */
      CHECK(spfft_dist_transform_local_z_length(pt, 0, &got2));
      REQUIRE(got2 == dim / 2);
      CHECK(spfft_dist_transform_backward(pt, dfreq, dspace));
      CHECK(spfft_dist_transform_forward(pt, dspace, dback, SPFFT_FULL_SCALING));
      {
        double max_err = 0.0;
        for (i = 0; i < 2 * n; ++i) {
          double d = fabs(dback[i] - dfreq[i]);
          if (d > max_err) max_err = d;
        }
        printf("pencil2 roundtrip max err: %g\n", max_err);
        REQUIRE(max_err < 1e-6);
      }
      CHECK(spfft_dist_transform_destroy(pt));
      CHECK(spfft_grid_destroy(pgrid));
    }

    /* grid-less distributed ctor (single-controller form of the reference's
     * spfft_transform_create_independent_distributed) */
    {
      SpfftDistTransform it = NULL;
      CHECK(spfft_dist_transform_create_independent(
          &it, 1, shards, SPFFT_EXCH_DEFAULT, SPFFT_PU_HOST, SPFFT_TRANS_C2C, dim,
          dim, dim, counts, SPFFT_INDEX_TRIPLETS, didx, 1));
      CHECK(spfft_dist_transform_backward(it, dfreq, dspace));
      CHECK(spfft_dist_transform_forward(it, dspace, dback, SPFFT_FULL_SCALING));
      {
        double max_err = 0.0;
        for (i = 0; i < 2 * n; ++i) {
          double d = fabs(dback[i] - dfreq[i]);
          if (d > max_err) max_err = d;
        }
        REQUIRE(max_err < 1e-6);
      }
      CHECK(spfft_dist_transform_destroy(it));
    }

    CHECK(spfft_dist_transform_destroy(dt));
    CHECK(spfft_grid_destroy(dgrid));
    free(didx);
    free(dfreq);
    free(dback);
    free(dspace);
  }

  CHECK(spfft_transform_destroy(tc));
  CHECK(spfft_transform_destroy(t));
  CHECK(spfft_grid_destroy(grid));
  free(freq);
  free(back);
  free(indices);
  printf("ALL NATIVE TESTS PASSED\n");
  return 0;
}
