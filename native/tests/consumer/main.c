/* Minimal consumer: includes the installed headers, checks the version
 * macros, and links a C API symbol. Runtime transform coverage lives in the
 * main native tests; this binary exists to prove the installed package
 * config + headers + library resolve for a downstream build. */
#include <stdio.h>

#include <spfft/spfft.h>
#include <spfft/version.h>

#if SPFFT_TPU_VERSION_MAJOR < 0
#error "version macros missing"
#endif

int main(void) {
  /* destroying a null handle must fail cleanly, exercising a real symbol */
  SpfftError err = spfft_grid_destroy(NULL);
  printf("spfft_tpu %s consumer link OK (err=%d)\n", SPFFT_TPU_VERSION_STRING,
         (int)err);
  return err == SPFFT_SUCCESS ? 1 : 0;
}
