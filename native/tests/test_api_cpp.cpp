/*
 * C++-surface test: Grid copy fidelity across all three grid kinds (local,
 * 1-D distributed, 2-D pencil) — copies must rebuild the same mesh shape
 * (reference contract: copy = fresh buffers, same parameters,
 * grid_internal.cpp:233-262) — plus a transform from a copied pencil grid.
 */
#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <vector>

#include <spfft/spfft.hpp>

#define REQUIRE(cond)                                                                    \
  do {                                                                                   \
    if (!(cond)) {                                                                       \
      std::fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond);               \
      return 1;                                                                          \
    }                                                                                    \
  } while (0)

int main() {
  setenv("SPFFT_TPU_NUM_CPU_DEVICES", "4", 1);
  const int dim = 8;

  /* local grid copy */
  spfft::Grid local(dim, dim, dim, dim * dim, SPFFT_PU_HOST, 1);
  spfft::Grid local_copy(local);
  REQUIRE(local_copy.max_dim_x() == dim);
  REQUIRE(local_copy.num_shards() == 1);

  /* 1-D distributed grid copy keeps the mesh */
  spfft::Grid dist(dim, dim, dim, dim * dim, dim, 4, SPFFT_EXCH_COMPACT_BUFFERED,
                   SPFFT_PU_HOST, 1);
  spfft::Grid dist_copy(dist);
  REQUIRE(dist_copy.num_shards() == 4);

  /* 2-D pencil grid copy keeps the mesh SHAPE (2x2, not a 1-D 4-mesh) */
  spfft::Grid pencil(dim, dim, dim, dim * dim, dim, 2, 2, SPFFT_EXCH_DEFAULT,
                     SPFFT_PU_HOST, 1);
  spfft::Grid pencil_copy(pencil);
  REQUIRE(pencil_copy.num_shards() == 4);

  /* a transform from the COPIED pencil grid must use the 2-D decomposition:
   * per-shard y-split proves the mesh survived the copy */
  const int shards = 4;
  std::vector<int> counts(shards, 2 * dim * dim);
  std::vector<int> idx;
  for (int r = 0; r < shards; ++r)
    for (int x = 2 * r; x < 2 * r + 2; ++x)
      for (int y = 0; y < dim; ++y)
        for (int z = 0; z < dim; ++z) {
          idx.push_back(x);
          idx.push_back(y);
          idx.push_back(z);
        }
  spfft::DistributedTransform t = pencil_copy.create_transform_distributed(
      SPFFT_PU_HOST, SPFFT_TRANS_C2C, dim, dim, dim, shards, counts.data(),
      SPFFT_INDEX_TRIPLETS, idx.data(), true);
  REQUIRE(t.num_shards() == 4);
  REQUIRE(t.local_y_length(0) == dim / 2); /* 2-D split, not full-Y slabs */
  REQUIRE(t.local_z_length(0) == dim / 2);

  const int n = dim * dim * dim;
  std::vector<double> freq(2 * n), space(2 * n), back(2 * n);
  for (int i = 0; i < 2 * n; ++i) freq[i] = (double)(i % 11) - 5.0;
  t.backward(freq.data(), space.data());
  t.forward(space.data(), back.data(), SPFFT_FULL_SCALING);
  double max_err = 0.0;
  for (int i = 0; i < 2 * n; ++i) max_err = std::max(max_err, std::fabs(back[i] - freq[i]));
  std::printf("pencil-copy roundtrip max err: %g\n", max_err);
  REQUIRE(max_err < 1e-10);

  std::printf("ALL NATIVE C++ TESTS PASSED\n");
  return 0;
}
