"""Multi-host bootstrap: spawn, configure and join worker host processes.

The boot half of the multi-host serving layer (ROADMAP item 2;
docs/details.md "Multi-host serving & host loss"). Three concerns, each a
place where multi-process runs classically fail *opaquely*, made typed and
testable:

1. **Joining a mesh** (:func:`boot`): wraps
   :func:`spfft_tpu.parallel.mesh.init_distributed` — which now validates
   the coordinator address and process coordinates up front
   (:func:`~spfft_tpu.parallel.mesh.validate_distributed_args`) — plus the
   virtual-device configuration, and returns the observed topology
   (process count, global/local device counts) so a rank can assert what
   it actually joined instead of discovering a half-formed mesh at first
   collective.
2. **Spawning workers** (:func:`spawn_workers`): launches N
   ``programs/serve_worker.py`` processes with :func:`child_env` —
   every ambient ``SPFFT_TPU_*`` knob propagated verbatim (lockdep arming
   included: a worker spawned under ``SPFFT_TPU_LOCKDEP=1`` records its
   own report), ``JAX_PLATFORMS``/``XLA_FLAGS`` set for the requested
   per-host device count — and waits for each worker's ready file (a
   worker that fails to boot surfaces its log tail in a typed error, never
   a silent hang).
3. **Warm-starting wisdom** (:func:`warm_start`): merges the fleet wisdom
   bundle at ``SPFFT_TPU_HOSTS_WISDOM_BUNDLE`` into the host's own store
   at boot (best-measured-wins, :meth:`WisdomStore.merge`), so a fresh
   host serves pre-tuned from its first request.
"""
from __future__ import annotations

import json
import os
import re
import signal
import socket
import subprocess
import sys
import tempfile
import time
from pathlib import Path

from . import knobs
from .errors import HostExecutionError, InvalidParameterError

WISDOM_BUNDLE_ENV = "SPFFT_TPU_HOSTS_WISDOM_BUNDLE"

_WORKER_SCRIPT = (
    Path(__file__).resolve().parent.parent / "programs" / "serve_worker.py"
)

_DEVICE_COUNT_FLAG = re.compile(
    r"--xla_force_host_platform_device_count=\d+\s*"
)


def free_port() -> int:
    """An OS-assigned free TCP port (the coordinator-allocation helper)."""
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    try:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]
    finally:
        s.close()


def child_env(overrides=None, *, devices: int | None = None) -> dict:
    """Environment for a spawned worker process.

    A minimal base (PATH/HOME/PYTHONPATH, ``JAX_PLATFORMS`` defaulting to
    the parent's value or ``cpu``) plus **every ambient ``SPFFT_TPU_*``
    knob propagated verbatim** — the whole registry surface, so a chaos
    spec, a wisdom path, or lockdep arming configured on the parent governs
    the children too. ``devices`` sets the child's virtual CPU device count
    via ``XLA_FLAGS`` (the pre-backend-init spelling every jax version
    honors); ``overrides`` merge last and win."""
    env = {
        "PATH": os.environ.get("PATH", "/usr/bin:/bin:/usr/local/bin"),
        "HOME": os.environ.get("HOME", "/root"),
        "JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS", "cpu"),
    }
    if "PYTHONPATH" in os.environ:
        env["PYTHONPATH"] = os.environ["PYTHONPATH"]
    for key, value in os.environ.items():
        if key.startswith(knobs.PREFIX):
            env[key] = value
    # two knobs that must NOT propagate verbatim — both name parent-owned
    # output paths. A shared lockdep report path would have every worker
    # and the parent clobber one file at exit (the merge would silently
    # check only the last writer's graph); a shared trace-dump directory
    # interleaves every host's crash dumps into one pid-keyed pile nobody
    # can attribute. Workers get per-host paths via
    # spawn_workers(lockdep_dir=) / its per-host dump subdirectories /
    # explicit overrides.
    env.pop("SPFFT_TPU_LOCKDEP_REPORT", None)
    env.pop("SPFFT_TPU_TRACE_DUMP", None)
    if devices is not None:
        if int(devices) < 1:
            raise InvalidParameterError(
                f"devices must be >= 1, got {devices}"
            )
        flags = _DEVICE_COUNT_FLAG.sub(
            "", os.environ.get("XLA_FLAGS", "")
        ).strip()
        env["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={int(devices)}"
        ).strip()
    if overrides:
        env.update({str(k): str(v) for k, v in dict(overrides).items()})
    return env


def warm_start(bundle_path: str | None = None) -> tuple:
    """Merge a fleet wisdom bundle into this host's active store at boot.

    ``bundle_path`` defaults to ``SPFFT_TPU_HOSTS_WISDOM_BUNDLE``; unset or
    empty is a no-op ``(0, 0)``. Returns ``(added, replaced)`` from
    :meth:`~spfft_tpu.tuning.wisdom.WisdomStore.merge` (best-measured-wins,
    version-checked, corrupt bundles quarantined typed) — a fresh host
    points its store at shared fleet wisdom and serves pre-tuned with zero
    trials."""
    path = (
        bundle_path if bundle_path is not None
        else knobs.get_str(WISDOM_BUNDLE_ENV)
    )
    if not path:
        return (0, 0)
    from .tuning.wisdom import active_store

    return active_store().merge(path)


def boot(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    *,
    devices: int | None = None,
    **kwargs,
) -> dict:
    """Join a multi-controller run and report the observed topology.

    Validates the coordinates typed up front (a malformed value raises
    :class:`~spfft_tpu.errors.InvalidParameterError` here, not a gRPC
    timeout inside a child), optionally configures ``devices`` virtual CPU
    devices (before backend init), calls
    ``jax.distributed.initialize``, and returns ``{"process_count",
    "process_index", "global_devices", "local_devices"}`` so the caller
    asserts the mesh it actually joined."""
    from .parallel import mesh as _mesh

    if devices is not None:
        _mesh.configure_virtual_devices(int(devices), warn=True)
    _mesh.init_distributed(
        coordinator_address, num_processes, process_id, **kwargs
    )
    import jax

    return {
        "process_count": int(jax.process_count()),
        "process_index": int(jax.process_index()),
        "global_devices": len(jax.devices()),
        "local_devices": len(jax.local_devices()),
    }


class WorkerHost:
    """One spawned worker process: its handle, address, and ready record."""

    def __init__(self, host_id: int, proc, ready: dict, log_path: str):
        self.host_id = int(host_id)
        self.proc = proc
        self.ready = dict(ready)
        self.log_path = str(log_path)
        self.address = f"127.0.0.1:{int(ready['port'])}"

    @property
    def pid(self) -> int:
        return self.proc.pid

    def alive(self) -> bool:
        return self.proc.poll() is None

    def kill(self) -> None:
        """SIGKILL — the chaos primitive: no cleanup, no exit hooks, the
        exact shape of an OOM-killed or power-failed host."""
        if self.alive():
            self.proc.send_signal(signal.SIGKILL)

    def join(self, timeout_s: float = 10.0) -> int | None:
        try:
            return self.proc.wait(timeout_s)
        except subprocess.TimeoutExpired:
            return None

    def log_tail(self, limit: int = 2000) -> str:
        try:
            return Path(self.log_path).read_text()[-limit:]
        except OSError:
            return "<no log>"

    def describe(self) -> dict:
        return {
            "host_id": self.host_id,
            "pid": self.pid,
            "address": self.address,
            "alive": self.alive(),
            "ready": self.ready,
        }


def stop_workers(workers, timeout_s: float = 10.0) -> None:
    """Clean-stop a worker fleet: ask each RPC server to shut down (so exit
    hooks — the lockdep report dump — run), then escalate to SIGKILL on the
    stragglers."""
    from .errors import GenericError
    from .serve.rpc import RpcClient

    for w in workers:
        if not w.alive():
            continue
        client = RpcClient(w.address, timeout_s=2.0)
        try:
            client.call({"op": "shutdown"})
        except GenericError:
            pass  # already dead / wedged: the kill below owns it
        finally:
            client.close()
    deadline = time.monotonic() + float(timeout_s)
    for w in workers:
        remaining = max(0.1, deadline - time.monotonic())
        if w.join(remaining) is None:
            w.kill()
            w.join(2.0)


def spawn_workers(
    n: int,
    *,
    devices_per_host: int = 1,
    mesh: bool = False,
    wisdom_bundle: str | None = None,
    lockdep_dir: str | None = None,
    env=None,
    workdir: str | None = None,
    ready_timeout_s: float = 120.0,
    python: str | None = None,
) -> list:
    """Spawn ``n`` RPC serving workers; returns their :class:`WorkerHost`\\ s.

    Each worker runs ``programs/serve_worker.py`` under :func:`child_env`
    (every ambient ``SPFFT_TPU_*`` knob propagated, ``devices_per_host``
    virtual CPU devices). ``mesh=True`` additionally joins the workers into
    ONE ``jax.distributed`` multi-controller run (a coordinator port is
    allocated here; worker 0 hosts the coordination service) — the
    N-process × M-device mesh the CI boot proof stands up. ``wisdom_bundle``
    warm-starts every worker's store; ``lockdep_dir`` arms
    ``SPFFT_TPU_LOCKDEP=1`` in every worker with a per-host report path
    ``<dir>/host<i>.json`` (written on clean shutdown —
    :func:`stop_workers`).

    Boot failures are typed: a worker that dies or fails to write its ready
    file within ``ready_timeout_s`` kills the whole fleet and raises
    :class:`~spfft_tpu.errors.HostExecutionError` carrying its log tail."""
    n = int(n)
    if n < 1:
        raise InvalidParameterError(f"spawn_workers needs n >= 1, got {n}")
    if not _WORKER_SCRIPT.exists():
        raise InvalidParameterError(
            f"worker entry point missing: {_WORKER_SCRIPT}"
        )
    workdir = workdir or tempfile.mkdtemp(prefix="spfft-hostmesh-")
    Path(workdir).mkdir(parents=True, exist_ok=True)
    coordinator = f"127.0.0.1:{free_port()}" if mesh else None
    procs = []
    for i in range(n):
        ready_path = Path(workdir) / f"worker{i}.ready.json"
        log_path = Path(workdir) / f"worker{i}.log"
        cmd = [
            python or sys.executable,
            str(_WORKER_SCRIPT),
            "--host-id", str(i),
            "--port", "0",
            "--ready-file", str(ready_path),
        ]
        if coordinator is not None:
            cmd += [
                "--coordinator", coordinator,
                "--num-processes", str(n),
                "--process-id", str(i),
            ]
        overrides = dict(env or {})
        if wisdom_bundle:
            overrides[WISDOM_BUNDLE_ENV] = str(wisdom_bundle)
        if lockdep_dir:
            overrides["SPFFT_TPU_LOCKDEP"] = "1"
            overrides["SPFFT_TPU_LOCKDEP_REPORT"] = str(
                Path(lockdep_dir) / f"host{i}.json"
            )
        # a parent trace-dump dir fans out per host (child_env pops the
        # verbatim value): each worker flushes its flight recorder into its
        # own subdirectory, so crash dumps stay attributable
        trace_dump = knobs.get_str("SPFFT_TPU_TRACE_DUMP")
        if trace_dump:
            overrides.setdefault(
                "SPFFT_TPU_TRACE_DUMP", str(Path(trace_dump) / f"host{i}")
            )
        cenv = child_env(overrides, devices=devices_per_host)
        with open(log_path, "wb") as log:
            proc = subprocess.Popen(
                cmd, stdout=log, stderr=subprocess.STDOUT, env=cenv,
                cwd=str(_WORKER_SCRIPT.parent.parent),
            )
        procs.append((i, proc, ready_path, log_path))

    workers = []
    deadline = time.monotonic() + float(ready_timeout_s)
    try:
        for i, proc, ready_path, log_path in procs:
            ready = None
            while time.monotonic() < deadline:
                if ready_path.exists():
                    try:
                        ready = json.loads(ready_path.read_text())
                        break
                    except (OSError, json.JSONDecodeError):
                        pass  # mid-write: the atomic rename makes this rare
                if proc.poll() is not None:
                    break
                time.sleep(0.05)
            if ready is None:
                tail = "<no log>"
                try:
                    tail = Path(log_path).read_text()[-2000:]
                except OSError:
                    pass
                raise HostExecutionError(
                    f"worker {i} failed to become ready within "
                    f"{ready_timeout_s}s (exit code {proc.poll()}); log "
                    f"tail:\n{tail}"
                )
            workers.append(WorkerHost(i, proc, ready, str(log_path)))
    except Exception:
        for _, proc, _, _ in procs:
            if proc.poll() is None:
                proc.send_signal(signal.SIGKILL)
        raise
    return workers
