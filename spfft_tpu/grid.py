"""The ``Grid`` public API object.

Parity with the reference ``spfft::Grid`` (reference: include/spfft/grid.hpp:49-205):
a Grid declares maximum transform extents and stick counts up front and hands out
Transforms that must fit inside it. In the reference this exists to pre-allocate and
share scratch buffers (reference: src/spfft/grid_internal.cpp:48-229); under XLA,
buffers are managed by the runtime, so the Grid's remaining jobs are capacity
validation (kept, for API parity) and pinning the processing unit / device (and, for
distributed grids, the mesh) that its transforms execute on.
"""
from __future__ import annotations

import jax

from .errors import InvalidParameterError, OverflowError_
from .types import ExchangeType, ProcessingUnit


def _effective_default_device():
    """The effective ``jax_default_device``, thread-local override included.

    The ``jax.default_device(...)`` context manager installs a THREAD-LOCAL
    override; ``jax.config.jax_default_device`` surfaces it on the pinned JAX
    version but is documented to return only the global value on others
    (advisor r4). Reading the config object's ``.value`` is the
    thread-local-aware accessor; fall back to the public attribute if the
    private module moves."""
    try:
        from jax._src.config import default_device

        return default_device.value
    except (ImportError, AttributeError):
        # the private module moved (ImportError) or dropped the accessor
        # (AttributeError) — the public attribute is the documented fallback
        return jax.config.jax_default_device


def device_for_processing_unit(processing_unit: ProcessingUnit, device=None):
    """Resolve a ProcessingUnit (and optional explicit device) to a JAX device.

    Per-object binding parity with the reference, which pins each Grid /
    Transform to the device current at creation (reference:
    src/spfft/grid_internal.cpp:82, docs/source/details.rst:104-106):

    - ``device`` explicitly given: used as-is (the ``device=`` ctor kwarg).
    - ``jax.default_device`` set to a device of the matching class (CPU for
      HOST, non-CPU for GPU): that device — the JAX analogue of "the device
      current at creation".
    - otherwise HOST maps to a CPU device, resolved WITHOUT initializing
      non-CPU backends (parity with the reference, whose SPFFT_PU_HOST paths
      never touch an accelerator runtime; see spfft_tpu/_platform.py), and GPU
      (the accelerator slot — TPU in this build) maps to the default backend's
      first device, falling back to CPU when no accelerator is attached.
    """
    pu = ProcessingUnit(processing_unit)
    if device is not None:
        return device
    default = _effective_default_device()
    if default is not None and hasattr(default, "platform"):
        if (default.platform == "cpu") == (pu == ProcessingUnit.HOST):
            return default
    if pu == ProcessingUnit.HOST:
        from ._platform import cpu_device

        return cpu_device()
    return jax.devices()[0]


class Grid:
    """Capacity envelope + device binding for transforms.

    Reference ctor: include/spfft/grid.hpp:65-66 (local),
    :89-91 (distributed adds max_local_z_length, comm, exchange_type).
    """

    def __init__(
        self,
        max_dim_x: int,
        max_dim_y: int,
        max_dim_z: int,
        max_num_local_z_columns: int,
        processing_unit: ProcessingUnit = ProcessingUnit.HOST,
        max_num_threads: int = -1,
        *,
        max_local_z_length: int | None = None,
        mesh=None,
        exchange_type: ExchangeType = ExchangeType.DEFAULT,
        device=None,
    ):
        if min(max_dim_x, max_dim_y, max_dim_z) < 1:
            raise InvalidParameterError("grid dimensions must be positive")
        if max_num_local_z_columns < 0:
            raise InvalidParameterError("max_num_local_z_columns must be non-negative")
        if max_dim_x * max_dim_y * max_dim_z >= 2**62:
            raise OverflowError_("grid too large")
        self._max_dim_x = int(max_dim_x)
        self._max_dim_y = int(max_dim_y)
        self._max_dim_z = int(max_dim_z)
        self._max_num_local_z_columns = int(max_num_local_z_columns)
        self._max_local_z_length = int(
            max_dim_z if max_local_z_length is None else max_local_z_length
        )
        self._processing_unit = ProcessingUnit(processing_unit)
        self._max_num_threads = max_num_threads
        self._mesh = mesh
        self._exchange_type = ExchangeType(exchange_type)
        self._device = device_for_processing_unit(self._processing_unit, device)

    # -- accessors, parity with include/spfft/grid.hpp:147-199 --
    @property
    def max_dim_x(self) -> int:
        return self._max_dim_x

    @property
    def max_dim_y(self) -> int:
        return self._max_dim_y

    @property
    def max_dim_z(self) -> int:
        return self._max_dim_z

    @property
    def max_num_local_z_columns(self) -> int:
        return self._max_num_local_z_columns

    @property
    def max_local_z_length(self) -> int:
        return self._max_local_z_length

    @property
    def processing_unit(self) -> ProcessingUnit:
        return self._processing_unit

    @property
    def max_num_threads(self) -> int:
        return self._max_num_threads

    @property
    def device(self):
        return self._device

    @property
    def mesh(self):
        return self._mesh

    @property
    def exchange_type(self) -> ExchangeType:
        return self._exchange_type

    @property
    def num_shards(self) -> int:
        if self._mesh is None:
            return 1
        from .parallel.mesh import fft_mesh_size

        return fft_mesh_size(self._mesh)

    def report(self) -> dict:
        """Grid card: the capacity envelope and bindings transforms created
        from this grid inherit (the grid-level slice of the plan cards
        :meth:`Transform.report` returns — see :mod:`spfft_tpu.obs`)."""
        card = {
            "kind": "grid",
            "max_dims": [self._max_dim_x, self._max_dim_y, self._max_dim_z],
            "max_num_local_z_columns": self._max_num_local_z_columns,
            "max_local_z_length": self._max_local_z_length,
            "processing_unit": self._processing_unit.name,
            "num_shards": self.num_shards,
            "exchange_type": self._exchange_type.name,
        }
        if self._mesh is None:
            card["device"] = str(self._device)
        else:
            card["mesh"] = {
                str(name): int(size)
                for name, size in zip(
                    self._mesh.axis_names, self._mesh.devices.shape
                )
            }
        return card

    def create_transform(
        self,
        processing_unit,
        transform_type,
        dim_x,
        dim_y,
        dim_z,
        num_local_elements=None,
        indices=None,
        *,
        local_z_length=None,
        dtype=None,
        engine: str = "auto",
        precision: str = "highest",
        device=None,
        policy: str | None = None,
        guard: bool | None = None,
        verify=None,
        overlap: int | None = None,
        fuse=None,
    ):
        """Create a transform bound to this grid.

        Reference: include/spfft/grid.hpp:138-141 / transform ctor checks in
        src/spfft/transform_internal.cpp:45-137 (capacity validation against the grid).
        Grids built with a mesh hand out distributed transforms (the reference's MPI
        Grid ctor, include/spfft/grid.hpp:89-91).
        """
        if self._mesh is not None:
            if device is not None:
                raise InvalidParameterError(
                    "device= applies to local transforms only; distributed "
                    "plans are placed by the grid's mesh"
                )
            from .distributed import DistributedTransform

            return DistributedTransform(
                processing_unit,
                transform_type,
                dim_x,
                dim_y,
                dim_z,
                indices,
                mesh=self._mesh,
                local_z_lengths=local_z_length,
                exchange_type=self._exchange_type,
                grid=self,
                dtype=dtype,
                engine=engine,
                precision=precision,
                policy=policy,
                guard=guard,
                verify=verify,
                overlap=overlap,
                fuse=fuse,
            )
        if overlap is not None:
            raise InvalidParameterError(
                "overlap= applies to distributed plans only (local "
                "transforms have no exchange to chunk)"
            )
        from .transform import Transform

        return Transform(
            processing_unit=processing_unit,
            transform_type=transform_type,
            dim_x=dim_x,
            dim_y=dim_y,
            dim_z=dim_z,
            num_local_elements=num_local_elements,
            indices=indices,
            local_z_length=local_z_length,
            grid=self,
            dtype=dtype,
            engine=engine,
            precision=precision,
            device=device,
            policy=policy,
            guard=guard,
            verify=verify,
            fuse=fuse,
        )
