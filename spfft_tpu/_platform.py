"""Guarded CPU device resolution.

JAX initializes EVERY platform named in ``jax_platforms`` on the first device
query (``jax._src.xla_bridge.backends()`` walks the whole list). On hosts whose
default platform is a tunneled/experimental accelerator plugin, that init can
block indefinitely (a wedged device claim never times out), taking down even
code that only wanted a CPU device. The reference never has this failure mode:
its HOST paths (``SPFFT_PU_HOST``, reference: src/spfft/grid.cpp,
src/execution/execution_host.cpp) touch no accelerator runtime at all.

This module restores that property for the TPU build: :func:`cpu_devices`
resolves CPU devices WITHOUT triggering all-platform backend initialization.

Resolution order:

1. Backends already initialized -> the normal ``jax.devices("cpu")`` (cheap).
2. Not initialized, but ``jax_platforms`` is cpu-only -> normal query (it can
   only initialize the CPU backend).
3. Otherwise -> instantiate the CPU backend factory directly and keep a
   private client. The global backend table stays untouched, so a later
   accelerator query still initializes normally.

Arrays placed on private-client devices are committed; jit/dispatch resolve
the backend from the array's client, so compute works without the global
table (verified: jit + Mesh + shard_map all run on a private 8-device client).
"""
from __future__ import annotations

import jax

# (n_virtual_devices_at_creation, client); rebuilt if the requested virtual
# device count changes while backends are still uninitialized.
_private_cpu_client = None


def _cpu_only_configured() -> bool:
    """True when ``jax_platforms`` names only CPU (global init is then safe)."""
    plats = jax.config.jax_platforms
    if not plats:
        return False
    names = {p.strip() for p in str(plats).split(",") if p.strip()}
    return names == {"cpu"}


def global_init_is_safe() -> bool:
    """True when querying default-platform devices cannot block on a
    non-CPU backend init (already initialized, or cpu-only configured)."""
    import jax._src.xla_bridge as xb

    return xb.backends_are_initialized() or _cpu_only_configured()


def cpu_devices(n: int | None = None):
    """Return CPU devices, never initializing non-CPU backends.

    ``n`` truncates the list; honors ``jax_num_cpu_devices`` /
    ``--xla_force_host_platform_device_count`` for virtual multi-device CPU
    setups (they configure the client at creation time on every path below).
    """
    global _private_cpu_client
    import jax._src.xla_bridge as xb

    # jax < 0.4.38 has no jax_num_cpu_devices option; the XLA flag (read by
    # the CPU client factory at creation) is the only knob there.
    num_cfg = int(getattr(jax.config, "jax_num_cpu_devices", None) or 1)
    if _private_cpu_client is None or _private_cpu_client[0] != num_cfg:
        if global_init_is_safe():
            try:
                devs = jax.devices("cpu")
            except RuntimeError:
                devs = None  # initialized without a CPU backend
            if devs:
                return list(devs) if n is None else list(devs[:n])
        try:
            factory = xb._backend_factories["cpu"].factory
        except (AttributeError, KeyError):
            # jax internals moved: fall back to the public query (may
            # initialize all platforms — correct, just unguarded).
            devs = jax.devices("cpu")
            return list(devs) if n is None else list(devs[:n])
        # Rebuild when the requested virtual device count changed (e.g.
        # configure_virtual_devices ran after a 1-device HOST resolution):
        # the factory reads jax_num_cpu_devices at creation time. Arrays on a
        # previous private client stay valid on their own devices.
        _private_cpu_client = (num_cfg, factory())
    devs = list(_private_cpu_client[1].local_devices())
    return devs if n is None else devs[:n]


def cpu_device():
    """The first CPU device (see :func:`cpu_devices` for the guarantees)."""
    return cpu_devices(1)[0]


def hang_watchdog(
    label: str,
    budget_env: str,
    default_s: float,
    exit_code: int,
    budget_s: float | None = None,
):
    """Arm a wall-clock budget against unkillable native hangs (a wedged
    accelerator-plugin init blocks forever and ignores signals delivered to
    the blocked thread). Returns a disarm callable; if not disarmed within the
    budget (env ``budget_env``, default ``default_s`` seconds; an explicit
    ``budget_s`` overrides both), prints a one-line diagnostic plus
    all-thread stacks and ``os._exit``\\ s with ``exit_code`` — a fast,
    capturable failure instead of a driver timeout.

    Armed by the driver entry points (bench.py, __graft_entry__.py) and — as
    the last-resort backstop behind the typed fence deadline — by
    :func:`spfft_tpu.sync.fence` when ``SPFFT_TPU_FENCE_BUDGET_S`` is set;
    ordinary library calls never arm it.
    """
    import faulthandler
    import os
    import sys
    import threading

    from . import knobs

    if budget_s is None:
        if budget_env in knobs.REGISTRY:
            # env set -> registry-typed parse; unset -> the caller's default
            # (driver budgets are registered internal knobs)
            budget_s = (
                knobs.get_float(budget_env) if knobs.raw(budget_env)
                else float(default_s)
            )
        else:
            # foreign budget names (tests arm watchdogs under ad-hoc env
            # names outside the SPFFT_TPU_* surface): raw ambient parse
            budget_s = float(os.environ.get(budget_env) or default_s)  # noqa: SA014
    disarmed = threading.Event()

    def _watch():
        if not disarmed.wait(budget_s):
            print(
                f"{label}: exceeded {budget_s:.0f}s wall-clock budget "
                "(blocked backend init or collective?); dumping stacks and "
                f"exiting {exit_code}",
                file=sys.stderr,
                flush=True,
            )
            faulthandler.dump_traceback(file=sys.stderr)
            sys.stderr.flush()
            os._exit(exit_code)

    threading.Thread(target=_watch, daemon=True).start()
    return disarmed.set
