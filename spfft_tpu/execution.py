"""Execution engines: build jitted transform pipelines from plan metadata.

The analogue of the reference's execution layer
(reference: src/execution/execution_host.cpp:50-352, src/execution/execution_gpu.cpp:47-410),
re-designed for XLA: instead of hand-scheduled stages over pre-allocated buffers, each
direction of a transform is a single pure function traced and compiled once (static
shapes frozen at plan creation, like the reference freezes stick/plane counts), with
XLA fusing compression, symmetry and FFT stages.

Backward (freq -> space), mirroring the reference pipeline order
(reference survey: execution_host.cpp:298-352):
  decompress -> stick symmetry (R2C) -> z-FFT -> stick->plane scatter
  -> plane symmetry (R2C) -> y-FFT -> x-FFT (C2R for R2C)
Forward reverses it and fuses optional 1/(NxNyNz) scaling into the final gather.

The transforms are *unnormalized* DFTs (backward is N * ifft), matching the reference
definition (reference: docs/source/details.rst:4-13,42-44).

Complex data crosses the jit boundary as (real, imag) float pairs: some TPU runtimes
do not implement complex host<->device transfers, and pair form is free on the other
platforms (XLA lays complex out as interleaved pairs anyway). Inside the compiled
function everything is native complex.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from . import obs
from .ops import compression, symmetry
from .parameters import LocalParameters
from .types import ScalingType, TransformType


def _complex_dtype(real_dtype) -> np.dtype:
    return np.dtype(np.complex64) if np.dtype(real_dtype) == np.float32 else np.dtype(np.complex128)


def as_pair(values, real_dtype):
    """Host-side: complex array -> (re, im) contiguous pair."""
    values = np.asarray(values)
    return (
        np.ascontiguousarray(values.real, dtype=real_dtype),
        np.ascontiguousarray(values.imag, dtype=real_dtype),
    )


def from_pair(pair):
    """Host-side: (re, im) -> complex numpy array."""
    re, im = np.asarray(pair[0]), np.asarray(pair[1])
    return re + 1j * im


# One jitted updater reused for every chunked put (jax.jit caches by this
# function's identity + operand shapes; the donated accumulator lets XLA
# update in place instead of holding chunks + result concurrently).
def _chunk_update_fn(buf, chunk, start):
    return jax.lax.dynamic_update_slice_in_dim(buf, chunk, start, 0)


_chunk_update = jax.jit(_chunk_update_fn, donate_argnums=(0,))


class ExecutionBase:
    """Shared boundary state/helpers for the single-device engines (this XLA engine
    and execution_mxu.MxuLocalExecution)."""

    def __init__(self, params: LocalParameters, real_dtype, device=None):
        self.params = params
        self.real_dtype = np.dtype(real_dtype)
        self.complex_dtype = _complex_dtype(real_dtype)
        self.device = device
        # Sorted stick keys => a (0,0) stick, if present, is always row 0.
        self._zero_stick_id = (
            0 if (params.num_sticks > 0 and int(params.stick_xy_indices[0]) == 0) else None
        )

    @property
    def is_r2c(self) -> bool:
        return self.params.transform_type == TransformType.R2C

    def stage_accounting(self) -> list:
        """Analytic per-stage flop/byte rows for one backward+forward pair —
        the :mod:`spfft_tpu.obs.perf` hook for the single-device engines
        (stage names from ``obs.STAGES``; same contract as the distributed
        engines' ``PaddingHelpers.stage_accounting``). The common head/tail
        rows come from the perf layer's shared builders
        (``pipeline_head_rows``/``pipeline_tail_rows``); this hook supplies
        only what the local pipelines add — the dense-y path's
        ``expand``/``pack`` stick<->slab relayout rows (the sparse-y MXU
        variants contract straight from sticks and carry neither)."""
        from .obs.perf import pipeline_head_rows, pipeline_tail_rows

        p = self.params
        Z, Y, X, Xf = p.dim_z, p.dim_y, p.dim_x, p.dim_x_freq
        c_item = 2 * self.real_dtype.itemsize
        S = int(p.num_sticks)
        x_active = int(getattr(self, "_num_x_active", Xf) or Xf)
        grid_elems = Z * Y * x_active
        rows = pipeline_head_rows(
            int(p.num_values), S, Z, c_item,
            # the fill is a no-op without a (0,0) stick (MXU skips the scope
            # entirely) — no stage row for work the pipeline does not do
            stick_symmetry=self.is_r2c and self._zero_stick_id is not None,
        )
        y_scope = getattr(self, "_y_stage_scope", lambda: "y transform")()
        if y_scope == "y transform":
            # dense path: stick -> slab relayout (backward "expand", forward
            # "pack"), each reading the sticks and writing the dense grid
            rows.append(
                {"stage": "expand", "flops": 0, "bytes": (S * Z + grid_elems) * c_item}
            )
            rows.append(
                {"stage": "pack", "flops": 0, "bytes": (S * Z + grid_elems) * c_item}
            )
        return rows + pipeline_tail_rows(
            Z, Y, X, Z * x_active, c_item,
            plane_symmetry=self.is_r2c, y_scope=y_scope,
        )

    @staticmethod
    def _stage_rows(nbytes: int, dim0: int):
        """Leading-axis rows per staging chunk, or None for one-shot transfer.

        Single source of the chunking rule shared by :meth:`put` and
        :meth:`fetch`: ``SPFFT_TPU_STAGE_CHUNK_MB`` (default 256) bounds each
        piece; <= 0 disables chunking."""
        from . import knobs

        limit = knobs.get_int("SPFFT_TPU_STAGE_CHUNK_MB") << 20
        if limit <= 0 or nbytes <= limit or dim0 <= 1:
            return None
        per_row = max(1, nbytes // dim0)
        return max(1, limit // per_row)

    def put(self, array):
        """Host -> device staging, chunked above the size threshold.

        One monolithic transfer of a 512^3-class f64 slab (~1-2 GB per part)
        measured pathologically slow through the tunneled dev TPU (~23 MB/s —
        the ~174 s/pair host-facing row of BASELINE.md's f64 table); chunked
        staging pipelines the same bytes in bounded pieces, assembled by
        donated in-place slice updates so peak HBM stays ~1x the array plus
        one chunk. Device-resident inputs keep the cheap device_put path
        (same-device is a no-op)."""
        if isinstance(array, jax.Array):
            return jax.device_put(array, self.device)
        array = np.asarray(array)
        obs.counter("staged_bytes_total", direction="host_to_device").inc(
            array.nbytes
        )
        rows = self._stage_rows(array.nbytes, array.shape[0] if array.ndim else 1)
        if rows is None:
            return jax.device_put(array, self.device)
        buf = jnp.zeros(array.shape, dtype=array.dtype, device=self.device)
        for i in range(0, array.shape[0], rows):
            chunk = jax.device_put(array[i : i + rows], self.device)
            buf = _chunk_update(buf, chunk, i)
        return buf

    def fetch(self, arr):
        """Device -> host fetch, chunked above the same threshold as put()."""
        obs.counter("staged_bytes_total", direction="device_to_host").inc(
            arr.size * arr.dtype.itemsize
        )
        rows = self._stage_rows(
            arr.size * arr.dtype.itemsize, arr.shape[0] if arr.ndim else 1
        )
        if rows is None:
            return np.asarray(arr)
        out = np.empty(arr.shape, dtype=arr.dtype)
        for i in range(0, arr.shape[0], rows):
            out[i : i + rows] = np.asarray(arr[i : i + rows])
        return out

    def fetch_space_complex(self, pair):
        """(re, im) device pair -> host complex array via chunked fetch —
        the one combine shared by every host-facing C2C space fetch."""
        return self.fetch(pair[0]) + 1j * self.fetch(pair[1])

    def backward_pair_consuming(self, values_re, values_im):
        """``backward_pair`` that DONATES its input buffers to XLA.

        The inputs are invalidated — callers must own them and never touch them
        again (the host-facing flow calls this on freshly staged copies).
        Donation lets XLA alias an input allocation to an output when shapes
        permit — the closest XLA analogue of the reference's Grid scratch
        reuse (reference: src/spfft/grid_internal.cpp:48-229). For this
        pipeline the packed-values and space shapes are disjoint, so the alias
        rarely engages (XLA then treats the arg normally); the expected
        "donated buffers were not usable" warning is suppressed. The actual
        512^3 f64 memory fix is the x-stage chunking (ops/fft.f64_stage_chunks)
        — see BASELINE.md. Routed through the IR runtime: the fused program's
        donating variant when fusion is active, the staged reference (which
        materializes intermediates and cannot donate) otherwise.
        """
        import warnings

        with warnings.catch_warnings():
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable"
            )
            # engines with threaded rotation-table operands append them
            # (never donated; see execution_mxu.phase_operands)
            return self._ir.run_backward_consuming(
                values_re, values_im, *getattr(self, "phase_operands", ())
            )

    # ---- batch-fused entries (SPFFT_TPU_BATCH_FUSE, spfft_tpu.ir) -------------
    # Stacked (B, ...) per-request arrays in, stacked results out — ONE
    # dispatch per direction for the whole batch. Every entry returns None
    # when batch fusion is unavailable or took its rung (batch_fuse_failed
    # on the plan card); callers run their per-request loop then.

    def backward_pair_batch(self, values_re, values_im):
        """Stacked (B, V) freq pairs -> stacked space ((B, ...) native
        layout; pair for C2C), or ``None`` (caller loops)."""
        return self._ir.run_backward_batch(
            values_re, values_im, *getattr(self, "phase_operands", ())
        )

    def backward_pair_batch_consuming(self, values_re, values_im):
        """Batched backward donating the stacked value pair (the host-facing
        consuming flow's donation rule on the batch axis)."""
        import warnings

        with warnings.catch_warnings():
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable"
            )
            return self._ir.run_backward_batch_consuming(
                values_re, values_im, *getattr(self, "phase_operands", ())
            )

    def forward_pair_batch(
        self, space_re, space_im, scaling: ScalingType = ScalingType.NONE
    ):
        """Stacked (B, ...) space -> stacked (B, V) freq pairs, or ``None``.
        ``space_im=None`` (R2C) becomes the stacked zero-width placeholder
        the forward graphs expect."""
        if space_im is None:
            space_im = jnp.zeros(
                (space_re.shape[0], 0), dtype=self.real_dtype
            )
        return self._ir.run_forward_batch(
            ScalingType(scaling), space_re, space_im,
            *getattr(self, "phase_operands", ()),
        )

    def _ir_spec(self) -> dict:
        """The :mod:`spfft_tpu.ir` compile-layer contract of the local
        engines: plain jits, the packed value pair donatable on the consuming
        backward, the engine's monolithic jits as the ``ir_lower_failed``
        legacy rung."""
        return {
            "kind": "local",
            "donate": (0, 1),
            "legacy_backward": self._backward,
            "legacy_backward_consuming": self._backward_consume,
            "legacy_forward": self._forward,
        }


class LocalExecution(ExecutionBase):
    """Single-device execution engine for one transform plan.

    Holds index constants and the two jitted pipelines. Separate compiled variants
    exist per scaling mode (scaling is a static property of the compiled program so
    the multiply fuses into the gather).
    """

    def __init__(
        self, params: LocalParameters, real_dtype=np.float64, device=None,
        fuse=None,
    ):
        super().__init__(params, real_dtype, device)
        p = params
        # Index constants stay as numpy: jit embeds them as program constants,
        # avoiding any host<->device traffic at call time (the analogue of
        # CompressionGPU's one-time index upload, reference: src/compression/compression_gpu.hpp:54-57).
        self._value_indices = np.asarray(p.value_indices, dtype=np.int32)
        self._stick_x = np.asarray(p.stick_x, dtype=np.int32)
        self._stick_y = np.asarray(p.stick_y, dtype=np.int32)

        self._backward = jax.jit(self._backward_impl)
        self._backward_consume = jax.jit(self._backward_impl, donate_argnums=(0, 1))
        self._forward = {
            s: jax.jit(functools.partial(self._forward_impl, scale=self._scale_for(s)))
            for s in (ScalingType.NONE, ScalingType.FULL)
        }
        # Stage-graph IR (spfft_tpu.ir): the pipeline lowered to a validated
        # stage graph, fused into one jitted program per direction (or run
        # per-stage under SPFFT_TPU_FUSE=0); the monolithic jits above remain
        # the ir_lower_failed rung and the unjitted trace composition.
        from .ir.compile import init_engine_ir

        self._ir = init_engine_ir(self, fuse)

    # ---- introspection (spfft_tpu.obs plan cards) -----------------------------

    def describe(self) -> dict:
        """Engine fragment of the plan card (obs.plancard): this engine makes
        no measured decisions — jnp.fft (pocketfft on CPU) plus scatter/gather
        pack/unpack, chosen where that is the fast path."""
        return {"pipeline": "jnp.fft + scatter/gather"}

    def lowered_backward(self):
        """Lower (without compiling) the backward pipeline — the obs layer's
        hook for compiled-program stats (obs.hlo.compiled_stats)."""
        v = jax.ShapeDtypeStruct((self.params.num_values,), self.real_dtype)
        return self._backward.lower(v, v)

    # ---- pipeline stage bodies -------------------------------------------------
    # One implementation per stage, shared by the hand-ordered monolithic
    # impls below (the ir_lower_failed rung + trace composition) and the IR
    # node fns lowered from this engine (spfft_tpu.ir.lower) — the stage
    # math lives exactly once.

    def _st_decompress(self, values_re, values_im):
        p = self.params
        values = jax.lax.complex(
            values_re.astype(self.real_dtype), values_im.astype(self.real_dtype)
        )
        return compression.decompress(
            values, self._value_indices, p.num_sticks, p.dim_z
        )

    def _st_stick_symmetry(self, sticks):
        return symmetry.apply_stick_symmetry(sticks, self._zero_stick_id)

    def _st_z_backward(self, sticks):
        return jnp.fft.ifft(sticks, axis=1)

    def _st_expand(self, sticks):
        # Stick -> plane relayout: scatter each z-stick into its (y, x)
        # column of the dense slab (the local transpose, reference:
        # src/transpose/transpose_host.hpp:50-161).
        p = self.params
        grid = jnp.zeros(
            (p.dim_z, p.dim_y, p.dim_x_freq), dtype=self.complex_dtype
        )
        return grid.at[:, self._stick_y, self._stick_x].set(
            sticks.T, mode="drop", unique_indices=True
        )

    def _st_plane_symmetry(self, grid):
        return symmetry.apply_plane_symmetry(grid)

    def _st_y_backward(self, grid):
        return jnp.fft.ifft(grid, axis=1)

    def _st_x_backward(self, grid):
        # Undo ifft's 1/N normalization: the backward transform is
        # unnormalized (reference: docs/source/details.rst:42-44).
        p = self.params
        total = np.asarray(p.total_size, dtype=self.real_dtype)
        if self.is_r2c:
            out = jnp.fft.irfft(grid, n=p.dim_x, axis=2).astype(self.real_dtype)
            return out * total
        out = jnp.fft.ifft(grid, axis=2) * total
        return out.real, out.imag

    def _st_x_forward(self, space_re, space_im):
        p = self.params
        if self.is_r2c:
            grid = jnp.fft.rfft(space_re.astype(self.real_dtype), n=p.dim_x, axis=2)
            return grid.astype(self.complex_dtype)
        space = jax.lax.complex(
            space_re.astype(self.real_dtype), space_im.astype(self.real_dtype)
        )
        return jnp.fft.fft(space, axis=2)

    def _st_y_forward(self, grid):
        return jnp.fft.fft(grid, axis=1)

    def _st_pack(self, grid):
        # Plane -> stick gather (forward local transpose).
        return grid[:, self._stick_y, self._stick_x].T

    def _st_z_forward(self, sticks):
        return jnp.fft.fft(sticks, axis=1)

    def _st_compress(self, sticks, scale):
        values = compression.compress(sticks, self._value_indices, scale)
        return values.real.astype(self.real_dtype), values.imag.astype(
            self.real_dtype
        )

    # ---- pipelines (traced; complex internal, real pairs at the boundary) -----

    def _backward_impl(self, values_re, values_im):
        # stage scopes: canonical obs.STAGES labels (profiler attribution)
        with jax.named_scope("compression"):
            sticks = self._st_decompress(values_re, values_im)
        if self.is_r2c:
            with jax.named_scope("stick symmetry"):
                sticks = self._st_stick_symmetry(sticks)
        with jax.named_scope("z transform"):
            sticks = self._st_z_backward(sticks)

        with jax.named_scope("expand"):
            grid = self._st_expand(sticks)

        if self.is_r2c:
            with jax.named_scope("plane symmetry"):
                grid = self._st_plane_symmetry(grid)
        with jax.named_scope("y transform"):
            grid = self._st_y_backward(grid)
        with jax.named_scope("x transform"):
            return self._st_x_backward(grid)

    def _forward_impl(self, space_re, space_im, scale):
        with jax.named_scope("x transform"):
            grid = self._st_x_forward(space_re, space_im)
        with jax.named_scope("y transform"):
            grid = self._st_y_forward(grid)

        with jax.named_scope("pack"):
            sticks = self._st_pack(grid)

        with jax.named_scope("z transform"):
            sticks = self._st_z_forward(sticks)
        with jax.named_scope("compression"):
            return self._st_compress(sticks, scale)

    # ---- device-side entry points (pair-form, no host transfers) --------------

    def backward_pair(self, values_re, values_im):
        """freq pair -> space; returns (re, im) pair for C2C, a real array for R2C.
        Routed through the IR runtime (fused single program by default, the
        staged per-node reference under ``SPFFT_TPU_FUSE=0``)."""
        return self._ir.run_backward(values_re, values_im)

    def forward_pair(self, space_re, space_im, scaling: ScalingType = ScalingType.NONE):
        """space -> freq pair. ``space_im`` is ignored (may be None) for R2C."""
        if space_im is None:
            space_im = jnp.zeros((0,), dtype=self.real_dtype)  # placeholder, R2C only
        return self._ir.run_forward(ScalingType(scaling), space_re, space_im)

    # Un-jitted traceables for composition into larger jitted programs (e.g.
    # the benchmark's scan chain): a jit boundary inside a scan body blocks
    # cross-stage fusion (measured ~30% slower per pair at 128^3).

    def trace_backward(self, values_re, values_im, phase=()):
        del phase  # this engine has no rotation operands (MXU-engine contract)
        return self._backward_impl(values_re, values_im)

    def trace_forward(
        self, space_re, space_im, scaling: ScalingType = ScalingType.NONE, phase=()
    ):
        del phase
        if space_im is None:
            space_im = jnp.zeros((0,), dtype=self.real_dtype)
        return self._forward_impl(space_re, space_im, self._scale_for(scaling))

    def _scale_for(self, scaling):
        """The single ScalingType -> scale-factor mapping (jitted + traced paths)."""
        if ScalingType(scaling) == ScalingType.NONE:
            return None
        return 1.0 / self.params.total_size

    # ---- host-facing entry points ---------------------------------------------

    def backward(self, values):
        """freq (num_values,) complex -> space (dim_z, dim_y, dim_x)."""
        re, im = as_pair(values, self.real_dtype)
        return self.backward_pair(self.put(re), self.put(im))

    def forward(self, space, scaling: ScalingType = ScalingType.NONE):
        """space (dim_z, dim_y, dim_x) -> freq (num_values,) as a (re, im) pair."""
        if self.is_r2c:
            space_re = self.put(np.ascontiguousarray(np.asarray(space).real, dtype=self.real_dtype))
            space_im = None
        else:
            re, im = as_pair(space, self.real_dtype)
            space_re, space_im = self.put(re), self.put(im)
        return self.forward_pair(space_re, space_im, scaling)
