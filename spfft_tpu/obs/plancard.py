"""Plan cards: one structured, JSON-stable record of what a plan chose and why.

Every ``Transform`` / ``DistributedTransform`` exposes ``plan.report()`` ->
this card: grid geometry and sparsity, engine and precision, the engine's
measured decisions (active-x compaction, sparse-y variant and its thresholds,
alignment rotations), and — for distributed plans — the exchange discipline's
actual wire bytes / rounds / transport PLUS the cost-model table of every
alternative the ``ExchangeType.DEFAULT`` policy would weigh (chosen and
rejected, from ``parallel/policy.py`` — the same single-sourced accounting the
resolver reads, so card and resolver cannot diverge). With
``include_compiled=True`` the backward pipeline is lowered and compiled and
the card adds compile wall time, ``memory_analysis()`` bytes, StableHLO
op-class counts and the element-granular scatter count
(:mod:`spfft_tpu.obs.hlo`).

Cards are plain ``str``/``int``/``float``/``bool`` containers: they embed
directly into benchmark JSON (``bench.py``, ``programs/benchmark.py``) and the
``programs/report.py`` CLI, and :func:`validate_plan_card` pins the schema so
drift fails CI instead of silently shipping.
"""
from __future__ import annotations

PLAN_CARD_SCHEMA = "spfft_tpu.obs.plan_card/1"

# Schema floor: keys every card must carry / keys distributed cards add.
REQUIRED_KEYS = (
    "schema",
    "kind",
    # construction run ID (spfft_tpu.obs.trace): the join key between this
    # card, the metrics window it ran under, and the flight-recorder events
    "run_id",
    "engine",
    "transform_type",
    "dims",
    "num_elements",
    "num_sticks",
    "nnz_fraction",
    "dtype",
    "precision",
    "policy",
    "platform",
    "execution",
    "degradations",
    "verification",
)
# Every fallback the degradation ladder took for this plan
# (spfft_tpu.faults.ladder): always present ([] on a healthy plan) so a
# degraded plan is diagnosable from its card alone.
DEGRADATION_KEYS = ("event", "reason")
# Self-verification state (spfft_tpu.verify): always present ("mode": "off"
# on unverified plans, with checks/rtol/retries nulled); armed plans add the
# engine circuit breaker's live state so a demoted/broken engine is visible
# from the card alone.
VERIFICATION_KEYS = ("mode", "checks", "rtol", "retries", "breaker")
BREAKER_KEYS = ("engine", "state", "consecutive_failures", "trips", "threshold")
DISTRIBUTED_KEYS = ("num_shards", "mesh", "decomposition", "exchange")
EXCHANGE_KEYS = (
    "discipline",
    "wire_dtype",
    "wire_bytes",
    "rounds",
    "transport",
    # effective OVERLAPPED-discipline chunk count (1 = bulk-synchronous)
    "overlap_chunks",
)
POLICY_KEYS = ("round_cost_bytes", "one_shot_supported", "chosen", "alternatives")
ALTERNATIVE_KEYS = ("discipline", "wire_bytes", "rounds", "cost_bytes", "chosen")
COMPILED_KEYS = (
    "compile_seconds",
    "hlo_op_classes",
    "element_granular_ops",
    "memory_analysis",
)
# TUNED-policy decision provenance (spfft_tpu.tuning._record): wisdom vs
# model, hit/miss, the winning candidate, the per-candidate trial timings.
TUNING_KEYS = (
    "policy",
    "provenance",
    "hit",
    "wisdom_path",
    "key_digest",
    "reason",
    "choice",
    "trials",
)
# a trial row is either measured ("ms") or isolated-failed ("error")
TRIAL_KEYS = ("label",)
TRIAL_RESULT_KEYS = ("ms", "error")
# Stage-graph IR provenance: fusion decision, active path, request source,
# per-direction stage lists, donation map. This module stays import-free,
# so the tuple is a mirror of spfft_tpu/ir/compile.py IR_KEYS — lint
# check 9 pins the two literals equal (the STAGES/SITES/EVENTS contract).
# Always present on fresh cards; pre-IR captures (BENCH_r05 and older)
# omit it and stay valid (same rule as the exchange overlap_chunks key).
IR_SECTION_KEYS = ("fused", "path", "requested", "stages", "donation")
# Batch-fusion provenance (SPFFT_TPU_BATCH_FUSE): whether the batch-fused
# path is live, the knob's source, the distinct batch sizes dispatched, and
# whether the axis took its batch_fuse_failed rung. Mirror of
# spfft_tpu/ir/compile.py BATCH_KEYS (import-free module — the vocabulary
# checker pins the two literals equal, the IR_SECTION_KEYS contract).
# Always present on fresh cards; pre-batch captures omit it and stay valid.
BATCH_SECTION_KEYS = ("enabled", "requested", "sizes", "failed")

# Scheduler-placement provenance (spfft_tpu.sched.placement): present on
# plans the task-graph placement pass built; pins the decision record so a
# placed plan's card alone answers "which device, decided how" — wisdom
# hit/miss included, the same contract as the tuning section.
PLACEMENT_KEYS = (
    "provenance",
    "hit",
    "reason",
    "choice",
    "device",
    "device_index",
)


def base_discipline(exchange_type):
    """Map a wire-format variant (*_FLOAT / *_BF16) onto its base discipline
    — the granularity the DEFAULT cost model reasons at."""
    from ..types import BF16_EXCHANGES, FLOAT_EXCHANGES, ExchangeType

    if exchange_type in (ExchangeType.BUFFERED_FLOAT, ExchangeType.BUFFERED_BF16):
        return ExchangeType.BUFFERED
    if exchange_type in FLOAT_EXCHANGES + BF16_EXCHANGES:
        return ExchangeType.COMPACT_BUFFERED
    return ExchangeType(exchange_type)


def _exchange_policy_1d(transform) -> dict:
    """The ``exchange_policy`` card section for 1-D slab plans: the DEFAULT
    cost model's full table (parallel/policy.py) evaluated for THIS plan's
    geometry and wire width, with the active discipline flagged chosen."""
    from ..parallel.policy import alternative_costs, round_cost_bytes
    from ..parallel.ragged import OneShotExchange, _ragged_a2a_supported
    from ..types import wire_scalar_bytes

    p = transform._params
    ex = transform._exec
    ragged = getattr(ex, "_ragged", None)
    if isinstance(ragged, OneShotExchange):
        one_shot = ragged.transport == "ragged"
    elif p.num_shards > 1:
        # compile-only probe, cached per platform/mesh-size (parallel/ragged.py)
        one_shot = _ragged_a2a_supported(transform.mesh)
    else:
        one_shot = False
    table = alternative_costs(
        p.num_sticks_per_shard,
        p.local_z_lengths,
        one_shot_supported=one_shot,
        wire_scalar_bytes=wire_scalar_bytes(
            transform.exchange_type, transform.dtype
        ),
    )
    chosen = base_discipline(transform.exchange_type)
    ov = int(getattr(transform, "overlap_chunks", 1))
    alternatives = [
        {
            "discipline": d.name,
            "wire_bytes": int(row["wire_bytes"]),
            "rounds": int(row["rounds"]),
            "cost_bytes": int(row["cost_bytes"]),
            "chosen": d == chosen and ov == 1,
        }
        for d, row in table.items()
    ]
    chosen_name = transform.exchange_type.name
    if ov > 1:
        # the OVERLAPPED variant the plan actually runs: same exact wire
        # bytes as its padded base discipline, C chunk-collective rounds —
        # the cost-table provenance row of the overlap knob
        chosen_name = f"{chosen_name}/ov{ov}"
        base_row = table[chosen]
        alternatives.append(
            {
                "discipline": chosen_name,
                "wire_bytes": int(base_row["wire_bytes"]),
                "rounds": ov,
                "cost_bytes": int(base_row["wire_bytes"])
                + ov * round_cost_bytes(),
                "chosen": True,
            }
        )
    return {
        "round_cost_bytes": round_cost_bytes(),
        "one_shot_supported": bool(one_shot),
        "chosen": chosen_name,
        "alternatives": alternatives,
    }


def _exchange_policy_pencil(transform):
    """The ``exchange_policy`` section for 2-D pencil plans: the two cost
    tables the DEFAULT resolver weighed (stashed at plan time,
    pencil2._resolve_pencil2_default), with the backend's one-shot support
    resolved HERE — lazily, like the 1-D path — so plans whose resolver
    short-circuited never pay the probe compile at construction. ``None``
    for explicit disciplines (the cost model never ran)."""
    ex = transform._exec
    tables = getattr(ex, "_policy_tables", None)
    if tables is None:
        return None
    one_shot = ex._policy_probed_one_shot
    if one_shot is None:
        from ..parallel.ragged import _ragged_a2a_supported

        # compile-only probe, cached per platform/mesh-size (parallel/ragged.py)
        one_shot = (
            transform._params.num_shards > 1
            and _ragged_a2a_supported(transform.mesh)
        )
    costs = dict(tables[bool(one_shot)])
    chosen = transform.exchange_type.name
    ov = int(getattr(transform, "overlap_chunks", 1))
    costs["alternatives"] = [
        dict(alt, chosen=alt["discipline"] == chosen and ov == 1)
        for alt in costs["alternatives"]
    ]
    if ov > 1:
        # the OVERLAPPED variant actually running: exact wire bytes of the
        # padded base, 2C chunk-collective rounds (A + B per z-window chunk)
        base = next(
            alt for alt in costs["alternatives"] if alt["discipline"] == chosen
        )
        chosen = f"{chosen}/ov{ov}"
        costs["alternatives"].append(
            {
                "discipline": chosen,
                "wire_bytes": int(base["wire_bytes"]),
                "rounds": 2 * ov,
                "cost_bytes": int(base["wire_bytes"])
                + 2 * ov * int(costs["round_cost_bytes"]),
                "chosen": True,
            }
        )
    costs["chosen"] = chosen
    return costs


def plan_card(transform, *, include_compiled: bool = False) -> dict:
    """Build the card for a local or distributed plan (see module docstring)."""
    from ..types import TransformType, wire_dtype

    ex = transform._exec
    distributed = getattr(transform, "_mesh", None) is not None
    dims = [int(transform.dim_x), int(transform.dim_y), int(transform.dim_z)]
    if distributed:
        p = transform._params
        num_elements = int(transform.num_global_elements)
        num_sticks = int(sum(int(n) for n in p.num_sticks_per_shard))
    else:
        num_elements = int(transform.num_local_elements)
        num_sticks = int(transform._params.num_sticks)
    card = {
        "schema": PLAN_CARD_SCHEMA,
        "kind": "distributed" if distributed else "local",
        # the construction run ID (obs.trace) — flight-recorder events of
        # this plan's construction and executions carry the same ID
        "run_id": getattr(transform, "_run_id", None),
        "engine": transform._engine,
        "transform_type": TransformType(transform.transform_type).name,
        "dims": dims,
        "num_elements": num_elements,
        "num_sticks": num_sticks,
        "nnz_fraction": num_elements / float(transform.global_size),
        "dtype": str(transform.dtype),
        "precision": str(transform._precision),
        # plan-decision policy + TUNED provenance (spfft_tpu.tuning): whether
        # decisions came from the analytic model or measured wisdom, with the
        # trial table — the empirical counterpart of exchange_policy below
        "policy": getattr(transform, "_policy", "default"),
        "platform": _platform_of(transform),
        "execution": ex.describe(),
        # fallbacks taken while building this plan (spfft_tpu.faults.ladder)
        "degradations": [
            dict(d) for d in getattr(transform, "_degradations", ())
        ],
        # self-verification state (spfft_tpu.verify): mode, armed checks,
        # tolerances, and the engine circuit breaker — schema-pinned
        "verification": _verification_section(transform),
        # stage-graph IR provenance (spfft_tpu.ir): per-direction stage
        # lists, the fusion decision (fused single program vs staged
        # per-node dispatch vs the ir_lower_failed legacy rung), and the
        # donation map of the fused consuming backward — schema-pinned
        # (IR_KEYS below)
        "ir": ex._ir.describe(),
        # batch-fusion provenance (spfft_tpu.ir batch axis) — schema-pinned
        # (BATCH_SECTION_KEYS)
        "batch": ex._ir.describe_batch(),
    }
    tuning_record = getattr(transform, "_tuning", None)
    if tuning_record is not None:
        card["tuning"] = tuning_record
    placement = getattr(transform, "_placement", None)
    if placement is not None:
        # scheduler-placement provenance (spfft_tpu.sched): which device the
        # placement pass bound this plan to and how the width was decided
        card["placement"] = placement
    if distributed:
        p = transform._params
        mesh = transform.mesh
        card["num_shards"] = int(p.num_shards)
        card["mesh"] = {
            str(name): int(size)
            for name, size in zip(mesh.axis_names, mesh.devices.shape)
        }
        pencil = transform._engine.startswith("pencil2")
        card["decomposition"] = "pencil2" if pencil else "slab"
        card["num_sticks_per_shard"] = [int(n) for n in p.num_sticks_per_shard]
        card["local_z_lengths"] = [int(n) for n in p.local_z_lengths]
        card["exchange"] = {
            "discipline": transform.exchange_type.name,
            "wire_dtype": str(wire_dtype(transform.exchange_type, transform.dtype)),
            "wire_bytes": int(transform.exchange_wire_bytes()),
            "rounds": int(transform.exchange_rounds()),
            "transport": ex.exchange_transport(),
            "overlap_chunks": int(getattr(transform, "overlap_chunks", 1)),
        }
        if pencil:
            costs = _exchange_policy_pencil(transform)
            if costs is not None:
                card["exchange_policy"] = costs
        else:
            card["exchange_policy"] = _exchange_policy_1d(transform)
    if include_compiled:
        from ..faults import InjectedFault, record_degradation, summarize
        from .hlo import compiled_stats

        # Compiled introspection is optional (ladder rung 5): a lowering/
        # compile/stats failure (fault site hlo.stats) degrades to a card
        # without the "compiled" section, recorded — never a failed report().
        try:
            card["compiled"] = compiled_stats(ex.lowered_backward())
        except (InjectedFault, RuntimeError, OSError) as e:
            card["degradations"].append(
                record_degradation("hlo_stats_unavailable", summarize(e))
            )
    return card


def _verification_section(transform) -> dict:
    """The card's ``verification`` section: the supervisor's own description
    when armed, an explicit "off" record (still schema-complete, breaker
    state included — a broken engine matters even to unverified plans)
    otherwise."""
    verifier = getattr(transform, "_verifier", None)
    if verifier is not None:
        return verifier.describe()
    from ..verify import breaker

    return {
        "mode": getattr(transform, "_verify_mode", "off"),
        "checks": [],
        "rtol": None,
        "retries": 0,
        "breaker": breaker.describe(getattr(transform, "_engine", "unknown")),
    }


def _platform_of(transform) -> str:
    mesh = getattr(transform, "_mesh", None)
    if mesh is not None:
        return str(mesh.devices.flat[0].platform)
    return str(transform.device.platform)


def validate_plan_card(card: dict) -> list:
    """Missing/malformed key paths of a plan card ([] when valid)."""
    missing = [k for k in REQUIRED_KEYS if k not in card]
    if card.get("schema") not in (None, PLAN_CARD_SCHEMA):
        missing.append(f"schema (unknown: {card['schema']!r})")
    for i, entry in enumerate(card.get("degradations", ())):
        missing.extend(
            f"degradations[{i}].{k}" for k in DEGRADATION_KEYS if k not in entry
        )
    ver = card.get("verification")
    if isinstance(ver, dict):
        missing.extend(
            f"verification.{k}" for k in VERIFICATION_KEYS if k not in ver
        )
        missing.extend(
            f"verification.breaker.{k}"
            for k in BREAKER_KEYS
            if k not in (ver.get("breaker") or {})
        )
    if card.get("kind") == "distributed":
        missing.extend(k for k in DISTRIBUTED_KEYS if k not in card)
        missing.extend(
            f"exchange.{k}"
            for k in EXCHANGE_KEYS
            if k not in card.get("exchange", {})
        )
        policy = card.get("exchange_policy")
        if policy is not None:
            missing.extend(
                f"exchange_policy.{k}" for k in POLICY_KEYS if k not in policy
            )
            for i, alt in enumerate(policy.get("alternatives", ())):
                missing.extend(
                    f"exchange_policy.alternatives[{i}].{k}"
                    for k in ALTERNATIVE_KEYS
                    if k not in alt
                )
        elif card.get("decomposition") == "slab":
            missing.append("exchange_policy")
    if "compiled" in card:
        missing.extend(
            f"compiled.{k}" for k in COMPILED_KEYS if k not in card["compiled"]
        )
    if "ir" in card:
        rec = card["ir"]
        missing.extend(f"ir.{k}" for k in IR_SECTION_KEYS if k not in rec)
        if rec.get("path") not in ("fused", "staged", "legacy"):
            missing.append(f"ir.path (unknown: {rec.get('path')!r})")
        don = rec.get("donation")
        if not isinstance(don, dict) or not {"backward", "forward"} <= set(
            don or {}
        ):
            missing.append("ir.donation.backward|forward")
    if "batch" in card:
        rec = card["batch"]
        missing.extend(
            f"batch.{k}" for k in BATCH_SECTION_KEYS if k not in rec
        )
        if rec.get("requested") not in ("env", "default"):
            missing.append(
                f"batch.requested (unknown: {rec.get('requested')!r})"
            )
    if "placement" in card:
        rec = card["placement"]
        missing.extend(f"placement.{k}" for k in PLACEMENT_KEYS if k not in rec)
        if rec.get("provenance") not in ("wisdom", "model", "pinned"):
            missing.append(
                f"placement.provenance (unknown: {rec.get('provenance')!r})"
            )
    if "tuning" in card:
        rec = card["tuning"]
        missing.extend(f"tuning.{k}" for k in TUNING_KEYS if k not in rec)
        if rec.get("provenance") not in ("wisdom", "model"):
            missing.append(
                f"tuning.provenance (unknown: {rec.get('provenance')!r})"
            )
        for i, trial in enumerate(rec.get("trials", ())):
            missing.extend(
                f"tuning.trials[{i}].{k}" for k in TRIAL_KEYS if k not in trial
            )
            if not any(k in trial for k in TRIAL_RESULT_KEYS):
                missing.append(f"tuning.trials[{i}].ms|error")
    return missing
