"""Process-global run-metrics registry: counters, gauges, histograms.

The run-time counterpart of the plan-time cards (:mod:`spfft_tpu.obs.plancard`)
— what the host-facing transform paths actually did: transforms executed per
direction/engine, bytes staged host<->device, dispatch/wait latency
distributions, exchange wire bytes shipped. The registry is deliberately
host-side only: nothing here ever runs inside a compiled program, so recording
costs a dict lookup and an add — and with metrics disabled the instrument
factories return shared no-op singletons (the same zero-allocation pattern as
``timing.scoped``'s shared no-op scope), so the hot path records nothing.

Gate: the ``SPFFT_TPU_METRICS`` env knob (``0`` disables at import) plus
runtime :func:`enable`/:func:`disable`, mirroring ``timing.enable/disable``.

Export: :func:`snapshot` (JSON-stable dict, schema-tagged and validated by
:func:`validate_snapshot`) and :func:`prometheus_text` (Prometheus exposition
format, ``spfft_tpu_``-prefixed).
"""
from __future__ import annotations

import threading
import time

from .. import knobs

METRICS_ENV = "SPFFT_TPU_METRICS"
SNAPSHOT_SCHEMA = "spfft_tpu.obs.snapshot/1"

# Latency-oriented cumulative bucket bounds (seconds); +Inf is implicit.
HISTOGRAM_BUCKETS = (1e-5, 1e-4, 1e-3, 1e-2, 1e-1, 1.0, 10.0)


def _escape_label(value) -> str:
    """Prometheus label-value escaping (backslash, double-quote, newline) —
    applied when keys are built, so snapshot keys and the exposition format
    agree on one quoting rule."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _label_key(labels: tuple) -> str:
    if not labels:
        return ""
    return "{" + ",".join(f'{k}="{_escape_label(v)}"' for k, v in labels) + "}"


class Counter:
    """Monotonic counter."""

    __slots__ = ("name", "labels", "value")

    def __init__(self, name: str, labels: tuple):
        self.name = name
        self.labels = labels
        self.value = 0

    def inc(self, n: int = 1) -> None:
        # registry lock: instruments are process-global and += is a
        # read-modify-write, so concurrent dispatch threads must not interleave
        with _lock:
            self.value += n


class Gauge:
    """Last-value gauge."""

    __slots__ = ("name", "labels", "value")

    def __init__(self, name: str, labels: tuple):
        self.name = name
        self.labels = labels
        self.value = 0.0

    def set(self, v: float) -> None:
        with _lock:
            self.value = float(v)


class Histogram:
    """Fixed-bucket cumulative histogram (count/sum/min/max + bucket counts)."""

    __slots__ = ("name", "labels", "count", "sum", "min", "max", "bucket_counts")

    def __init__(self, name: str, labels: tuple):
        self.name = name
        self.labels = labels
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = float("-inf")
        self.bucket_counts = [0] * (len(HISTOGRAM_BUCKETS) + 1)

    def observe(self, v: float) -> None:
        v = float(v)
        # under the registry lock so count/sum/buckets stay mutually
        # consistent (the cumulative-bucket contract prometheus_text emits)
        with _lock:
            self.count += 1
            self.sum += v
            if v < self.min:
                self.min = v
            if v > self.max:
                self.max = v
            for i, bound in enumerate(HISTOGRAM_BUCKETS):
                if v <= bound:
                    self.bucket_counts[i] += 1
                    return
            self.bucket_counts[-1] += 1

    def to_dict(self) -> dict:
        buckets = {}
        cum = 0
        for bound, n in zip(HISTOGRAM_BUCKETS, self.bucket_counts):
            cum += n
            buckets[repr(bound)] = cum
        buckets["+Inf"] = self.count
        return {
            "count": self.count,
            "sum": self.sum,
            "min": self.min if self.count else 0.0,
            "max": self.max if self.count else 0.0,
            "buckets": buckets,
        }


class _NoopInstrument:
    """Shared do-nothing counter/gauge/histogram handed out while disabled —
    no registry entry, no per-call allocation on the hot path."""

    __slots__ = ()

    def inc(self, n: int = 1) -> None:
        pass

    def set(self, v: float) -> None:
        pass

    def observe(self, v: float) -> None:
        pass


_NOOP_INSTRUMENT = _NoopInstrument()


class _NoopScope:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP_SCOPE = _NoopScope()


class _PhaseTimer:
    """Context manager feeding one wall-clock duration into a histogram."""

    __slots__ = ("_hist", "_t0")

    def __init__(self, hist: Histogram):
        self._hist = hist

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._hist.observe(time.perf_counter() - self._t0)
        return False


_lock = threading.Lock()
_counters: dict = {}
_gauges: dict = {}
_histograms: dict = {}
_enabled = knobs.get_bool(METRICS_ENV)


def enable() -> None:
    """Turn metrics recording on (overrides ``SPFFT_TPU_METRICS=0``)."""
    global _enabled
    _enabled = True


def disable() -> None:
    """Turn metrics recording off: instrument factories return shared no-ops."""
    global _enabled
    _enabled = False


def is_enabled() -> bool:
    return _enabled


def clear() -> None:
    """Drop every recorded instrument (tests / fresh measurement windows)."""
    with _lock:
        _counters.clear()
        _gauges.clear()
        _histograms.clear()


def _instrument(table: dict, cls, name: str, labels: dict):
    key = (name, tuple(sorted(labels.items())))
    inst = table.get(key)
    if inst is None:
        with _lock:
            inst = table.setdefault(key, cls(name, key[1]))
    return inst


def counter(name: str, **labels) -> Counter:
    if not _enabled:
        return _NOOP_INSTRUMENT
    return _instrument(_counters, Counter, name, labels)


def gauge(name: str, **labels) -> Gauge:
    if not _enabled:
        return _NOOP_INSTRUMENT
    return _instrument(_gauges, Gauge, name, labels)


def histogram(name: str, **labels) -> Histogram:
    if not _enabled:
        return _NOOP_INSTRUMENT
    return _instrument(_histograms, Histogram, name, labels)


def phase_timer(name: str, **labels):
    """Scoped wall-clock observation into ``histogram(name, **labels)``;
    the shared no-op scope when disabled (zero allocation)."""
    if not _enabled:
        return _NOOP_SCOPE
    return _PhaseTimer(_instrument(_histograms, Histogram, name, labels))


def snapshot() -> dict:
    """JSON-stable view of everything recorded so far.

    Schema (``SNAPSHOT_SCHEMA``): ``schema``/``enabled`` headers plus one map
    per instrument kind, keyed ``name{label="value",...}``. Round-trips
    through ``json.dumps``/``loads`` unchanged (plain str/int/float only).
    """
    with _lock:
        return {
            "schema": SNAPSHOT_SCHEMA,
            "enabled": _enabled,
            "counters": {
                c.name + _label_key(c.labels): c.value for c in _counters.values()
            },
            "gauges": {
                g.name + _label_key(g.labels): g.value for g in _gauges.values()
            },
            "histograms": {
                h.name + _label_key(h.labels): h.to_dict()
                for h in _histograms.values()
            },
        }


_SNAPSHOT_KEYS = ("schema", "enabled", "counters", "gauges", "histograms")
_HISTOGRAM_KEYS = ("count", "sum", "min", "max", "buckets")


def validate_snapshot(snap: dict) -> list:
    """Missing/malformed key paths of a snapshot dict ([] when valid)."""
    missing = [k for k in _SNAPSHOT_KEYS if k not in snap]
    if snap.get("schema") not in (None, SNAPSHOT_SCHEMA):
        missing.append(f"schema (unknown: {snap['schema']!r})")
    for key, h in snap.get("histograms", {}).items():
        missing.extend(
            f"histograms[{key}].{k}" for k in _HISTOGRAM_KEYS if k not in h
        )
    return missing


def prometheus_text(snap: dict | None = None) -> str:
    """Prometheus exposition rendering of a snapshot (``spfft_tpu_`` prefix).

    Gauges and counters render directly; histograms render the standard
    ``_bucket``/``_sum``/``_count`` series with cumulative ``le`` buckets.
    """
    snap = snapshot() if snap is None else snap
    lines: list = []
    typed: set = set()  # one "# TYPE" line per metric name

    def split(key: str):
        name, _, labels = key.partition("{")
        return "spfft_tpu_" + name, ("{" + labels if labels else "")

    def type_line(name: str, kind: str):
        if name not in typed:
            typed.add(name)
            lines.append(f"# TYPE {name} {kind}")

    for kind, table in (("counter", "counters"), ("gauge", "gauges")):
        for key, value in sorted(snap.get(table, {}).items()):
            name, labels = split(key)
            type_line(name, kind)
            lines.append(f"{name}{labels} {value}")
    for key, h in sorted(snap.get("histograms", {}).items()):
        name, labels = split(key)
        base = labels[1:-1] if labels else ""
        type_line(name, "histogram")
        for bound, cum in h["buckets"].items():
            sep = "," if base else ""
            lines.append(f'{name}_bucket{{{base}{sep}le="{bound}"}} {cum}')
        lines.append(f"{name}_sum{labels} {h['sum']}")
        lines.append(f"{name}_count{labels} {h['count']}")
    return "\n".join(lines) + "\n"
