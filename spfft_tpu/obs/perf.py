"""Performance reports: fenced device time attributed to pipeline stages.

The FIFTH observability layer (docs/details.md "Observability"): the timing
tree measures what the host paid, plan cards record what the plan decided, the
metrics registry counts what ran, the flight recorder logs what happened —
none of them says how *fast* the device pipeline was, or where the time went.
This module does: a **performance report** (schema :data:`PERF_SCHEMA`,
:func:`validate_perf_report`) joins the existing run-ID key and attributes one
measured, *fenced* seconds-per-pair figure to the canonical
:data:`spfft_tpu.obs.STAGES` vocabulary.

**Measurement** (:func:`measure_pair_seconds`): the one timing discipline
every harness in this repo shares — warmup dispatches absorb compilation
(``tuning/runner.py``), then best-of-R timed backward+forward roundtrips,
chained inside a single jitted ``lax.scan`` so per-call dispatch latency is
amortized instead of billed to every pair (``bench.py``'s chained-roundtrip
trick), fenced with the platform-correct completion fence
(:mod:`spfft_tpu.sync`).

**Attribution**: under XLA the whole pipeline is one compiled program, so
per-stage wall time is not separately measurable from the host. The report
therefore distributes the measured total over the stages by an **analytic
cost model** — the standard ``5 * N * log2(N)`` flops per 1-D FFT pass
(sparse-aware: the z pass runs only on active sticks) and exact byte counts
for the data-movement stages, with exchange bytes taken from the same
stick/slab geometry accounting the plan card embeds
(``exchange_wire_bytes``). Flops and bytes combine through one machine
balance — :data:`DEFAULT_FLOP_PER_BYTE` flops per byte, override with
``SPFFT_TPU_PERF_FLOP_PER_BYTE`` — and the report records the method and the
balance used (``attribution``), so consumers know these per-stage seconds are
*model-apportioned measurements*, not independent timings. Stage seconds sum
to the measured wall time by construction.

**The scoreboard numbers**: ``gflops`` (the dense ``5 N log2 N`` model over
measured seconds — directly comparable to ``bench.py``'s headline and the
BENCH_r0x trajectory), per-stage GFLOP/s and GB/s, and ``exchange_fraction``
— the share of a pair attributed to the exchange stages
(:data:`EXCHANGE_STAGES`). For bulk-synchronous plans that fraction bounds
what communication/compute overlap can win; under the OVERLAPPED discipline
(``overlap_chunks`` > 1) the chunked exchange rows are scored on their
**exposed** (non-hidden) time — :func:`_exposed_weight` subtracts the
``(C-1)/C · min(exchange, hiding compute)`` the double-buffer pipelines
away, while the rows' modeled ``bytes`` remain the exact geometry wire
volume — so the scoreboard shows what communication actually costs, not
what rides the wire (docs/details.md "Hiding the exchange").

Every report also lands in the run registry (``perf_pair_seconds``,
``perf_stage_seconds`` histograms, ``perf_gflops`` / ``perf_exchange_fraction``
gauges) and emits a ``perf`` trace instant under the plan's run ID, so perf
rows join cards, metrics and traces on one key.

Surfaces: ``programs/dbench.py`` (multichip strong/weak scaling rows),
``programs/perf_gate.py`` (+ ``./ci.sh perf``) regression gate,
``programs/profile.py``, ``bench.py`` (embeds a report per capture).
"""
from __future__ import annotations

import math

from .. import knobs
from . import trace
from .registry import gauge, histogram
from .stages import STAGES

PERF_SCHEMA = "spfft_tpu.obs.perf/1"
SCALING_SCHEMA = "spfft_tpu.obs.perf.scaling/1"
FLOP_PER_BYTE_ENV = "SPFFT_TPU_PERF_FLOP_PER_BYTE"

# Machine balance used to mix flop-weighted compute stages and byte-weighted
# movement stages into one attribution scale: flops that cost the same time
# as moving one byte. The default comes from the same ICI-class numbers as
# parallel/policy.round_cost_bytes (hundreds of GFLOP/s against ~100 GB/s).
DEFAULT_FLOP_PER_BYTE = knobs.default(FLOP_PER_BYTE_ENV)

# The pipeline-stage vocabulary the perf model covers: exactly the engine
# stages of obs.STAGES (the autotuner's "tune warmup"/"tune trial" phases are
# trial harness stages, not pipeline stages, and carry no flop/byte model).
# Pure literal tuple — programs/lint.py enforces it both ways against STAGES
# (every modeled stage canonical, every engine stage modeled).
MODELED_STAGES = (
    "compression",
    "stick symmetry",
    "plane symmetry",
    "z transform",
    "y transform",
    "y transform sparse",
    "y transform blocked",
    "x transform",
    "expand",
    "pack",
    "exchange",
    "unpack",
    "pack A",
    "exchange A",
    "unpack A",
    "pack B",
    "exchange B",
    "unpack B",
    "exchange overlapped",
    "exchange A overlapped",
    "exchange B overlapped",
)

# The stages whose attributed seconds make up ``exchange_fraction`` — the
# interconnect collectives, not their local pack/unpack bookends. The
# overlapped variants contribute their EXPOSED (non-hidden) seconds, so the
# fraction is the share of wall time communication actually costs.
EXCHANGE_STAGES = (
    "exchange",
    "exchange A",
    "exchange B",
    "exchange overlapped",
    "exchange A overlapped",
    "exchange B overlapped",
)

REQUIRED_KEYS = (
    "schema",
    # the plan's construction run ID (spfft_tpu.obs.trace): perf rows join
    # plan cards, metrics windows and flight-recorder events on this key
    "run_id",
    "kind",
    "engine",
    "decomposition",
    "transform_type",
    "dims",
    "num_elements",
    "nnz_fraction",
    "dtype",
    "device_count",
    "mesh",
    "exchange_discipline",
    "seconds_per_pair",
    "repeats",
    "gflops",
    "model_gflops",
    "dense_flops_per_pair",
    "model_flops_per_pair",
    "wire_bytes_per_pair",
    "exchange_seconds",
    "exchange_fraction",
    "exchange_gbps",
    "attribution",
    "stages",
)
STAGE_KEYS = ("stage", "flops", "bytes", "seconds", "fraction", "gflops", "gbps")
ATTRIBUTION_KEYS = ("method", "flop_per_byte")


def flop_per_byte() -> float:
    """The active flops-per-byte machine balance (env-overridable)."""
    return knobs.get_float(FLOP_PER_BYTE_ENV)


def fft_pass_flops(lines: int, length: int) -> int:
    """Analytic flops of one 1-D FFT pass: ``5 * n * log2(n)`` per length-n
    line (the standard FFT cost model every benchmark in this repo uses),
    times the number of lines transformed. Zero for degenerate lengths."""
    if length <= 1 or lines <= 0:
        return 0
    return int(round(5.0 * lines * length * math.log2(length)))


def pipeline_head_rows(
    total_values: int,
    total_sticks: int,
    dim_z: int,
    c_item: int,
    *,
    stick_symmetry: bool,
) -> list:
    """Shared head of every engine's stage model — ``compression`` (packed
    values <-> sticks), the optional (0,0)-stick hermitian fill, and the
    sparse-aware z pass. One builder for all six engines so the common rows
    cannot drift; each hook passes its own pipeline's guard for the
    symmetry stage (the engines gate it differently)."""
    rows = [
        {
            "stage": "compression",
            "flops": 0,
            "bytes": 2 * (total_values + total_sticks * dim_z) * c_item,
        }
    ]
    if stick_symmetry:
        rows.append(
            {"stage": "stick symmetry", "flops": 0, "bytes": 2 * dim_z * c_item}
        )
    rows.append(
        {
            "stage": "z transform",
            "flops": 2 * fft_pass_flops(total_sticks, dim_z),
            "bytes": 0,
        }
    )
    return rows


def pipeline_tail_rows(
    dim_z: int,
    dim_y: int,
    dim_x: int,
    y_lines: int,
    c_item: int,
    *,
    plane_symmetry: bool,
    y_scope: str = "y transform",
) -> list:
    """Shared tail of every engine's stage model — the optional x=0 plane
    hermitian fill, the y pass (label and line count supplied by the engine:
    the sparse-y MXU variants carry their disambiguated scope and count only
    active x columns), and the x pass. Counterpart of
    :func:`pipeline_head_rows`."""
    rows = []
    if plane_symmetry:
        rows.append(
            {
                "stage": "plane symmetry",
                "flops": 0,
                "bytes": 2 * dim_z * dim_y * c_item,
            }
        )
    rows.append(
        {"stage": y_scope, "flops": 2 * fft_pass_flops(y_lines, dim_y), "bytes": 0}
    )
    rows.append(
        {
            "stage": "x transform",
            "flops": 2 * fft_pass_flops(dim_z * dim_y, dim_x),
            "bytes": 0,
        }
    )
    return rows


def dense_pair_flops(dims) -> int:
    """The dense-model flops of one backward+forward pair over the full
    grid: ``2 * 5 * N * log2(N)`` — the same figure ``bench.py`` divides by
    wall time, so report GFLOP/s and the BENCH trajectory are comparable."""
    n = 1
    for d in dims:
        n *= int(d)
    if n <= 1:
        return 0
    return int(round(2 * 5.0 * n * math.log2(n)))


def _exposed_weight(row: dict, base: dict, balance: float) -> float:
    """Attribution weight of one stage row, overlap-aware.

    Plain rows weigh ``flops + bytes * balance``. An OVERLAPPED exchange row
    (carrying an ``overlap`` record from the engine's ``stage_accounting``)
    weighs only its **exposed** wire time: with C chunks double-buffered
    against the compute stage it hides behind, at most ``(C-1)/C`` of
    ``min(exchange, compute)`` overlaps — the classic software-pipeline
    bound (arxiv.org/pdf/1804.09536) — so

        exposed = full - min(full, hidden_stage_weight) * (C - 1) / C.

    The row's modeled ``bytes`` stay the exact geometry wire volume either
    way; only the time attribution changes. The hiding compute stage keeps
    its full weight (it IS the pipeline's critical path)."""
    w = row["flops"] + row["bytes"] * balance
    ov = row.get("overlap")
    if not ov:
        return w
    chunks = max(1, int(ov.get("chunks", 1)))
    if chunks == 1:
        return w
    hide_w = base.get(ov.get("hides"), 0.0)
    return max(w - min(w, hide_w) * (chunks - 1) / chunks, 0.0)


def _attribute(rows: list, seconds: float, balance: float) -> list:
    """Distribute ``seconds`` over the stage rows by model weight
    (``flops + bytes * balance``; overlapped exchange rows by their exposed
    share — :func:`_exposed_weight`); equal split when the model is
    all-zero. The attributed stage seconds sum to ``seconds`` by
    construction."""
    base = {r["stage"]: r["flops"] + r["bytes"] * balance for r in rows}
    weights = [_exposed_weight(r, base, balance) for r in rows]
    total_w = sum(weights)
    out = []
    for r, w in zip(rows, weights):
        frac = (w / total_w) if total_w > 0 else (1.0 / len(rows) if rows else 0.0)
        sec = seconds * frac
        row = {
            "stage": r["stage"],
            "flops": int(r["flops"]),
            "bytes": int(r["bytes"]),
            "seconds": sec,
            "fraction": frac,
            "gflops": (r["flops"] / sec / 1e9) if sec > 0 else 0.0,
            "gbps": (r["bytes"] / sec / 1e9) if sec > 0 else 0.0,
        }
        if r.get("overlap"):
            row["overlap"] = dict(r["overlap"])
        out.append(row)
    return out


def _merge_rows(rows: list) -> list:
    """Aggregate duplicate stage names (an engine hook may emit a stage once
    per direction) into one row each, preserving first-seen order and any
    ``overlap`` record (first occurrence wins — the engines emit one
    consistent record per overlapped exchange)."""
    order, table = [], {}
    for r in rows:
        name = r["stage"]
        if name not in table:
            table[name] = {"stage": name, "flops": 0, "bytes": 0}
            order.append(name)
        table[name]["flops"] += int(r.get("flops", 0))
        table[name]["bytes"] += int(r.get("bytes", 0))
        if r.get("overlap") and "overlap" not in table[name]:
            table[name]["overlap"] = dict(r["overlap"])
    return [table[n] for n in order]


def stage_model(transform) -> list:
    """The analytic per-stage flop/byte model of one backward+forward pair
    for ``transform``'s actual pipeline — the engine's ``stage_accounting()``
    hook (every engine implements it; exchange bytes come from the same
    geometry accounting the plan card embeds), duplicate stages merged and
    names checked against :data:`MODELED_STAGES`."""
    rows = _merge_rows(transform._exec.stage_accounting())
    for r in rows:
        if r["stage"] not in MODELED_STAGES:
            from ..errors import InvalidParameterError

            # typed-error discipline (analysis SA010): a stage outside the
            # modeled vocabulary is a broken engine contract, surfaced typed
            raise InvalidParameterError(
                f"engine stage_accounting emitted unmodeled stage {r['stage']!r}"
            )
    return rows


def perf_report(
    transform,
    seconds: float,
    *,
    repeats: int | None = None,
    batch: int | None = None,
) -> dict:
    """Build the performance report for one measured ``transform`` pair.

    ``seconds`` is the measured, fenced wall time of one backward+forward
    pair (see :func:`measure_pair_seconds`); ``repeats`` records how many
    timed repetitions the best-of came from. ``batch`` (default 1) says the
    measured pair carried B stacked transforms through one dispatch (the
    batch-fused path): the flop/byte models — stage rows, dense flops, wire
    bytes — scale by B so per-stage GFLOP/s and the headline ``gflops``
    read as aggregate throughput of the batched dispatch, and the extent is
    stamped into ``attribution["batch"]`` (validation-optional, the
    ``overlap_chunks`` precedent: consumers read a missing value as 1).
    The report validates against :func:`validate_perf_report`, feeds the
    run registry, and emits a ``perf`` trace instant under the plan's run
    ID."""
    seconds = float(seconds)
    b = 1 if batch is None else int(batch)
    if b < 1:
        from ..errors import InvalidParameterError

        raise InvalidParameterError(f"batch must be >= 1, got {batch}")
    model_rows = stage_model(transform)
    if b > 1:
        model_rows = [
            dict(r, flops=r["flops"] * b, bytes=r["bytes"] * b)
            for r in model_rows
        ]
    rows = _attribute(model_rows, seconds, flop_per_byte())
    dims = [int(transform.dim_x), int(transform.dim_y), int(transform.dim_z)]
    distributed = getattr(transform, "_mesh", None) is not None
    if distributed:
        mesh = transform.mesh
        mesh_card = {
            str(name): int(size)
            for name, size in zip(mesh.axis_names, mesh.devices.shape)
        }
        device_count = int(transform.num_shards)
        decomposition = (
            "pencil2" if transform._engine.startswith("pencil2") else "slab"
        )
        discipline = transform.exchange_type.name
        overlap_chunks = int(getattr(transform, "overlap_chunks", 1))
        wire_bytes = 2 * int(transform.exchange_wire_bytes())  # fwd + bwd
        num_elements = int(transform.num_global_elements)
    else:
        mesh_card = None
        device_count = 1
        decomposition = "local"
        discipline = None
        overlap_chunks = 1
        wire_bytes = 0
        num_elements = int(transform.num_local_elements)
    if b > 1:
        wire_bytes *= b  # the batched dispatch ships every member's slabs
    model_flops = sum(r["flops"] for r in rows)
    dense_flops = dense_pair_flops(dims) * b
    exchange_seconds = sum(
        r["seconds"] for r in rows if r["stage"] in EXCHANGE_STAGES
    )
    report = {
        "schema": PERF_SCHEMA,
        "run_id": getattr(transform, "_run_id", None),
        "kind": "distributed" if distributed else "local",
        "engine": transform._engine,
        "decomposition": decomposition,
        "transform_type": transform.transform_type.name,
        "dims": dims,
        "num_elements": num_elements,
        "nnz_fraction": num_elements / float(transform.global_size),
        "dtype": str(transform.dtype),
        "device_count": device_count,
        "mesh": mesh_card,
        "exchange_discipline": discipline,
        # effective OVERLAPPED-discipline chunk count (1 = bulk-synchronous);
        # part of the scenario identity, so dbench keys and the perf gate
        # hold overlapped and unoverlapped rows side by side. Deliberately
        # NOT in REQUIRED_KEYS: schema-/1 documents captured before the
        # overlap work (MULTICHIP_r06 and older baselines) stay valid —
        # consumers read a missing value as 1
        "overlap_chunks": overlap_chunks,
        # fusion state (spfft_tpu.ir): fused-single-program vs staged rows
        # are different scenarios — part of the row identity like
        # overlap_chunks, and like it validation-optional (pre-IR captures
        # read as fused: the monolithic jits WERE one program per direction)
        "fused": bool(
            getattr(getattr(transform._exec, "_ir", None), "fused", True)
        ),
        "seconds_per_pair": seconds,
        "repeats": repeats,
        "gflops": (dense_flops / seconds / 1e9) if seconds > 0 else 0.0,
        "model_gflops": (model_flops / seconds / 1e9) if seconds > 0 else 0.0,
        "dense_flops_per_pair": dense_flops,
        "model_flops_per_pair": int(model_flops),
        "wire_bytes_per_pair": wire_bytes,
        "exchange_seconds": exchange_seconds,
        "exchange_fraction": (exchange_seconds / seconds) if seconds > 0 else 0.0,
        "exchange_gbps": (
            wire_bytes / exchange_seconds / 1e9 if exchange_seconds > 0 else 0.0
        ),
        "attribution": {
            "method": "analytic",
            "flop_per_byte": flop_per_byte(),
            "batch": b,
        },
        "stages": rows,
    }
    _record(report)
    return report


def _record(report: dict) -> None:
    """Feed the run registry + flight recorder from a finished report."""
    labels = {
        "engine": report["engine"],
        "decomposition": report["decomposition"],
    }
    histogram("perf_pair_seconds", **labels).observe(report["seconds_per_pair"])
    gauge("perf_gflops", **labels).set(report["gflops"])
    gauge("perf_exchange_fraction", **labels).set(report["exchange_fraction"])
    for row in report["stages"]:
        histogram("perf_stage_seconds", stage=row["stage"]).observe(
            row["seconds"]
        )
    with trace.with_run(report["run_id"]):
        trace.event(
            "perf",
            gflops=round(report["gflops"], 3),
            exchange_fraction=round(report["exchange_fraction"], 4),
            devices=report["device_count"],
            decomposition=report["decomposition"],
        )


def measure_pair_seconds(
    transform, *, chain: int = 4, repeats: int = 3, warmup: int = 1
) -> dict:
    """Measure one fenced backward+forward pair on ``transform``.

    The shared timing discipline (module docstring): random frequency inputs
    of the plan's exact shape staged on device (host staging is not billed —
    ``tuning/runner.py``'s rule), ``chain`` dependent roundtrips inside one
    jitted ``lax.scan`` (FULL scaling makes each C2C pair the identity, so
    the chain is exact; dispatch latency is amortized over the chain —
    ``bench.py``'s trick), ``warmup`` untimed chain calls absorbing
    compilation, then best-of-``repeats`` timed calls, each fenced with the
    platform-correct completion fence before the clock stops.

    Returns ``{"seconds_per_pair", "rep_seconds", "chain", "repeats",
    "roundtrip_residual"}`` — ``rep_seconds`` is the full per-repeat list
    (per pair), so consumers can derive a noise estimate
    (``programs/perf_gate.py``'s noise-aware threshold); the residual is the
    C2C chain-identity check (None for R2C, whose roundtrip projects onto
    hermitian-consistent spectra rather than reproducing arbitrary input).
    """
    import time

    import jax
    import numpy as np

    from ..sync import fence
    from ..tuning.runner import _stage_inputs
    from ..types import ScalingType, TransformType

    chain = max(1, int(chain))
    repeats = max(1, int(repeats))
    ex = transform._exec
    staged = _stage_inputs(transform)
    phase = getattr(ex, "phase_operands", ())
    is_r2c = transform.transform_type == TransformType.R2C

    def roundtrip(re, im, ph):
        space = ex.trace_backward(re, im, phase=ph)
        sre, sim = (space, None) if is_r2c else space
        return ex.trace_forward(sre, sim, ScalingType.FULL, phase=ph)

    def chain_fn(re, im, ph):
        def body(carry, _):
            return roundtrip(*carry, ph), None

        out, _ = jax.lax.scan(body, (re, im), None, length=chain)
        return out

    step = jax.jit(chain_fn)
    for _ in range(max(0, int(warmup))):
        fence(step(*staged, phase))
    rep_seconds = []
    out = None
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = step(*staged, phase)
        fence(out)
        rep_seconds.append((time.perf_counter() - t0) / chain)
    residual = None
    if not is_r2c:
        # FULL-scaled C2C roundtrips are the identity; a diverged chain means
        # the measurement ran a broken pipeline and must not become a row
        got = np.asarray(out[0]).reshape(-1)[:64]
        want = np.asarray(staged[0]).reshape(-1)[:64]
        residual = float(np.abs(got - want).max())
    return {
        "seconds_per_pair": min(rep_seconds),
        "rep_seconds": rep_seconds,
        "chain": chain,
        "repeats": repeats,
        "roundtrip_residual": residual,
    }


def validate_perf_report(report: dict) -> list:
    """Missing/malformed key paths of a perf report ([] when valid) — the
    schema pin, same contract as ``obs.validate_plan_card`` /
    ``trace.validate_trace``. Stage names must come from the canonical
    ``obs.STAGES`` vocabulary."""
    missing = [k for k in REQUIRED_KEYS if k not in report]
    if report.get("schema") not in (None, PERF_SCHEMA):
        missing.append(f"schema (unknown: {report['schema']!r})")
    att = report.get("attribution")
    if isinstance(att, dict):
        missing.extend(
            f"attribution.{k}" for k in ATTRIBUTION_KEYS if k not in att
        )
    for i, row in enumerate(report.get("stages", ())):
        missing.extend(f"stages[{i}].{k}" for k in STAGE_KEYS if k not in row)
        name = row.get("stage")
        if name not in STAGES:
            missing.append(f"stages[{i}].stage (unknown: {name!r})")
    return missing


def validate_scaling_doc(doc: dict) -> list:
    """Missing-key paths of a ``programs/dbench.py`` scaling document
    (schema :data:`SCALING_SCHEMA`): header keys plus every row's perf-report
    schema. [] when valid."""
    missing = [k for k in ("schema", "config", "rows") if k not in doc]
    if doc.get("schema") not in (None, SCALING_SCHEMA):
        missing.append(f"schema (unknown: {doc['schema']!r})")
    for i, row in enumerate(doc.get("rows", ())):
        for k in ("key", "scaling", "seconds_noise"):
            if k not in row:
                missing.append(f"rows[{i}].{k}")
        missing.extend(f"rows[{i}].{m}" for m in validate_perf_report(row))
    return missing
