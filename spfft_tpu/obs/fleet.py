"""Fleet metrics aggregation: one merged view over every serving host.

The sixth observability layer (docs/details.md "Observability") and the
first one that spans the fleet: every other layer — cards, metrics, traces,
perf reports, timelines — is process-local, so a :class:`~spfft_tpu.serve.
cluster.ClusterFront` serving through N worker hosts had N+1 metric islands
reachable only one at a time. This module merges them:

* :func:`fleet_snapshot` scrapes each live host's ``obs.snapshot()`` over
  the ``metrics`` RPC op (one bounded ``SPFFT_TPU_FLEET_SCRAPE_S`` deadline
  per host — a dead or blackholed host is stamped, never a hung scrape;
  hosts already declared lost are skipped typed without touching the wire),
* :func:`merge_snapshots` folds the per-host documents into one
  :data:`FLEET_SCHEMA` document: every series re-keyed with a ``host``
  label, counters additionally summed fleet-wide and histogram buckets
  summed bound-by-bound under ``totals`` (gauges stay per-host — a
  last-value has no meaningful fleet sum),
* :func:`validate_fleet` pins the schema (the ``validate_snapshot`` /
  ``validate_plan_card`` discipline) and :func:`fleet_prometheus_text`
  renders the host-labeled series in the exposition format, so one scrape
  endpoint can expose the whole fleet.

``ClusterFront.describe()`` joins a fleet document in, and
``programs/fleetstat.py`` is the operator CLI (``./ci.sh mhost`` validates
its output and proves a doctored document trips the validator).
"""
from __future__ import annotations

import time

from .. import knobs
from ..errors import GenericError, InvalidParameterError
from . import registry, trace

FLEET_SCHEMA = "spfft_tpu.obs.fleet/1"
FLEET_SCRAPE_ENV = "SPFFT_TPU_FLEET_SCRAPE_S"

# Host scrape states: "live" (snapshot merged), "lost" (already declared
# lost — skipped typed, no wire touched), "unreachable" (scrape failed or
# timed out inside the per-host deadline), "malformed" (answered, but the
# snapshot failed its own schema pin — excluded from the merge).
HOST_STATES = ("live", "lost", "unreachable", "malformed")

_FLEET_KEYS = (
    "schema", "scraped_unix", "hosts", "counters", "gauges", "histograms",
    "totals",
)
_HOST_KEYS = ("state", "error")
_TOTALS_KEYS = ("counters", "histograms")


def resolve_scrape_s(value=None) -> float:
    """The per-host fleet scrape deadline (``SPFFT_TPU_FLEET_SCRAPE_S``)."""
    return knobs.get_float(FLEET_SCRAPE_ENV, value)


# ---- series keys ------------------------------------------------------------


def parse_series_key(key: str) -> tuple:
    """``name{k="v",...}`` -> ``(name, ((k, v), ...))`` — the inverse of the
    registry's key builder, honoring its escaping (backslash, quote,
    newline). Malformed label blocks raise typed
    :class:`~spfft_tpu.errors.InvalidParameterError` (callers treat the
    snapshot as malformed)."""
    name, brace, rest = key.partition("{")
    if not brace:
        return name, ()
    if not rest.endswith("}"):
        raise InvalidParameterError(
            f"unterminated label block in series key {key!r}"
        )
    body = rest[:-1]
    labels = []
    i = 0
    while i < len(body):
        eq = body.find("=", i)
        if eq < 0:
            raise InvalidParameterError(
                f"label without '=' in series key {key!r}"
            )
        k = body[i:eq]
        if not body[eq + 1 : eq + 2] == '"':
            raise InvalidParameterError(
                f"unquoted label value in series key {key!r}"
            )
        j = eq + 2
        out = []
        while True:
            if j >= len(body):
                raise InvalidParameterError(
                    f"unterminated label value in {key!r}"
                )
            c = body[j]
            if c == "\\":
                nxt = body[j + 1 : j + 2]
                out.append({"n": "\n"}.get(nxt, nxt))
                j += 2
                continue
            if c == '"':
                break
            out.append(c)
            j += 1
        labels.append((k, "".join(out)))
        i = j + 1
        if i < len(body) and body[i] == ",":
            i += 1
    return name, tuple(labels)


def host_series_key(key: str, host: str) -> str:
    """Re-key one series with a ``host`` label merged in (sorted with the
    existing labels, the registry's ordering rule)."""
    name, labels = parse_series_key(key)
    merged = tuple(
        sorted({**dict(labels), "host": str(host)}.items())
    )
    return name + registry._label_key(merged)


# ---- merge ------------------------------------------------------------------


def _merge_histogram(total: dict, h: dict) -> None:
    total["count"] += h.get("count", 0)
    total["sum"] += h.get("sum", 0.0)
    if h.get("count", 0):
        total["min"] = min(total["min"], h.get("min", 0.0))
        total["max"] = max(total["max"], h.get("max", 0.0))
    for bound, cum in h.get("buckets", {}).items():
        total["buckets"][bound] = total["buckets"].get(bound, 0) + cum


def merge_snapshots(host_snaps: dict, hosts: dict | None = None) -> dict:
    """Fold per-host registry snapshots into one :data:`FLEET_SCHEMA` doc.

    ``host_snaps`` maps host name -> its ``obs.snapshot()``; ``hosts``
    (optional) maps host name -> a scrape-status entry (``state``/
    ``error``) for hosts that did NOT answer, so the document records who
    is missing and why (a fleet view that silently dropped a host would
    read as a healthy fleet). Counters and histograms re-key with a
    ``host`` label; ``totals`` carries the fleet-wide sums (counters
    summed, histogram buckets summed bound-by-bound)."""
    doc = {
        "schema": FLEET_SCHEMA,
        "scraped_unix": time.time(),
        "hosts": {},
        "counters": {},
        "gauges": {},
        "histograms": {},
        "totals": {"counters": {}, "histograms": {}},
    }
    for host, entry in (hosts or {}).items():
        doc["hosts"][str(host)] = dict(entry)
    for host, snap in host_snaps.items():
        host = str(host)
        doc["hosts"].setdefault(host, {"state": "live", "error": None})
        for key, value in snap.get("counters", {}).items():
            doc["counters"][host_series_key(key, host)] = value
            totals = doc["totals"]["counters"]
            totals[key] = totals.get(key, 0) + value
        for key, value in snap.get("gauges", {}).items():
            doc["gauges"][host_series_key(key, host)] = value
        for key, h in snap.get("histograms", {}).items():
            doc["histograms"][host_series_key(key, host)] = dict(
                h, buckets=dict(h.get("buckets", {}))
            )
            total = doc["totals"]["histograms"].setdefault(
                key,
                {
                    "count": 0, "sum": 0.0, "min": float("inf"),
                    "max": float("-inf"), "buckets": {},
                },
            )
            _merge_histogram(total, h)
    for total in doc["totals"]["histograms"].values():
        if not total["count"]:
            total["min"] = 0.0
            total["max"] = 0.0
    return doc


# ---- scrape -----------------------------------------------------------------


def fleet_snapshot(hosts, timeout_s: float | None = None) -> dict:
    """Scrape every host and merge: the fleet's ``obs.snapshot()``.

    ``hosts`` is an iterable of host handles (duck-typed: ``name``,
    ``lost``, and a ``client`` whose ``call`` speaks the ``metrics`` RPC
    op — exactly the cluster front's ``HostHandle``). Each live host gets
    ONE bounded scrape (``timeout_s``, default
    ``SPFFT_TPU_FLEET_SCRAPE_S``); a host that cannot answer inside it is
    stamped ``unreachable`` and the aggregation moves on — a scrape must
    never hang behind one dead host. Hosts already declared lost are
    skipped typed (``state="lost"``, ``error="host_lost"``) WITHOUT
    touching the wire: the loss ladder already closed their clients."""
    budget = resolve_scrape_s(timeout_s)
    snaps: dict = {}
    status: dict = {}
    for handle in hosts:
        name = str(getattr(handle, "name", handle))
        if getattr(handle, "lost", False):
            status[name] = {
                "state": "lost", "error": "host_lost",
                "skipped_unix": time.time(),
            }
            registry.counter(
                "fleet_scrapes_total", host=name, outcome="lost"
            ).inc()
            trace.event("host", what="scrape_skipped", host=name)
            continue
        try:
            reply = handle.client.call({"op": "metrics"}, timeout_s=budget)
            snap = reply.get("metrics") if isinstance(reply, dict) else None
        except GenericError as e:
            # a scrape failure is a per-host verdict, never an aggregation
            # failure: the client raises typed (HostLostError on transport
            # death) and the host is stamped unreachable with the class name
            status[name] = {"state": "unreachable", "error": type(e).__name__}
            registry.counter(
                "fleet_scrapes_total", host=name, outcome="unreachable"
            ).inc()
            trace.event(
                "host", what="scrape_failed", host=name,
                error=type(e).__name__,
            )
            continue
        if not isinstance(snap, dict) or registry.validate_snapshot(snap):
            status[name] = {"state": "malformed", "error": "invalid_snapshot"}
            registry.counter(
                "fleet_scrapes_total", host=name, outcome="malformed"
            ).inc()
            continue
        snaps[name] = snap
        registry.counter("fleet_scrapes_total", host=name, outcome="ok").inc()
    return merge_snapshots(snaps, status)


# ---- schema pin / export ----------------------------------------------------


def validate_fleet(doc: dict) -> list:
    """Missing/malformed key paths of a fleet document ([] when valid) —
    the schema pin, same style as ``obs.validate_snapshot``."""
    if not isinstance(doc, dict):
        return ["fleet (not a dict)"]
    missing = [k for k in _FLEET_KEYS if k not in doc]
    if doc.get("schema") != FLEET_SCHEMA:
        missing.append(f"schema (unknown: {doc.get('schema')!r})")
    for host, entry in doc.get("hosts", {}).items():
        if not isinstance(entry, dict):
            missing.append(f"hosts[{host}] (not a dict)")
            continue
        missing.extend(
            f"hosts[{host}].{k}" for k in _HOST_KEYS if k not in entry
        )
        if entry.get("state") not in HOST_STATES:
            missing.append(
                f"hosts[{host}].state (unknown: {entry.get('state')!r})"
            )
    with trace.suppressed_dumps():
        # probing keys for malformedness constructs typed errors by design:
        # a validator run must not flood the dump directory
        for key in doc.get("counters", {}):
            try:
                _, labels = parse_series_key(key)
            except InvalidParameterError:
                missing.append(f"counters[{key}] (malformed series key)")
                continue
            if "host" not in dict(labels):
                missing.append(f"counters[{key}] (missing host label)")
    for key, h in doc.get("histograms", {}).items():
        if not isinstance(h, dict) or "buckets" not in h:
            missing.append(f"histograms[{key}].buckets")
    totals = doc.get("totals")
    if isinstance(totals, dict):
        missing.extend(
            f"totals.{k}" for k in _TOTALS_KEYS if k not in totals
        )
    return missing


def fleet_prometheus_text(doc: dict) -> str:
    """Prometheus exposition rendering of a fleet document: the host-labeled
    series through the registry's own renderer (one scrape endpoint for the
    whole fleet; ``totals`` are derivable by the scraper and deliberately
    not re-exported — double-counting a summed series is the classic
    aggregation bug)."""
    return registry.prometheus_text(
        {
            "counters": doc.get("counters", {}),
            "gauges": doc.get("gauges", {}),
            "histograms": doc.get("histograms", {}),
        }
    )
