"""Canonical pipeline stage names — the single source for ``jax.named_scope``
labels across every engine.

Every engine wraps its pipeline stages in ``jax.named_scope`` so
``jax.profiler`` traces read like the reference's rt_graph timing tree
(reference: src/execution/execution_host.cpp:249-293). The labels live here so
that (1) profiler traces attribute stages unambiguously — e.g. the sparse,
blocked and dense y-DFT variants carry distinct names instead of three
colliding "y transform" scopes, and the 2-D pencil engine's two exchanges are
tagged A/B — and (2) ``programs/lint.py`` can enforce consistency both ways:
every engine scope label must come from this list, and every listed stage must
appear in at least one engine.

``STAGES`` is a pure literal tuple (lint reads it with ``ast.literal_eval``
so the check stays import-free).
"""
from __future__ import annotations

STAGES = (
    # sparse value pack/unpack (reference: compression_host.hpp)
    "compression",
    # R2C hermitian completions (reference: symmetry_host.hpp)
    "stick symmetry",
    "plane symmetry",
    # DFT stages
    "z transform",
    "y transform",          # dense y-DFT
    "y transform sparse",   # per-slot sparse-y contraction (ops/fft.plan_sparse_y)
    "y transform blocked",  # blocked sparse-y buckets (ops/fft.plan_sparse_y_blocked)
    "x transform",
    # local stick -> plane relayout (MXU local engine)
    "expand",
    # 1-D slab exchange phases (reference: transpose_mpi_*_host.cpp)
    "pack",
    "exchange",
    "unpack",
    # 2-D pencil engine: exchange A (sticks -> y-pencils, over both mesh axes)
    # and exchange B (y-pencils -> 2-D slabs, over "fft" only) are distinct
    # pipeline points and carry distinct labels
    "pack A",
    "exchange A",
    "unpack A",
    "pack B",
    "exchange B",
    "unpack B",
    # OVERLAPPED exchange discipline (overlap chunks > 1): the chunked,
    # double-buffered collectives carry distinct labels so traces and perf
    # attribution can tell pipelined wire time from bulk-synchronous wire
    # time — the perf layer scores these on EXPOSED (non-hidden) time
    "exchange overlapped",
    "exchange A overlapped",
    "exchange B overlapped",
    # autotuner trial phases (spfft_tpu/tuning/runner.py): warmup dispatches
    # absorbing compilation, then the timed roundtrips wisdom records
    "tune warmup",
    "tune trial",
)
