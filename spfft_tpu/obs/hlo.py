"""Compiled-program introspection: StableHLO op-class counts, the
element-granular gather/scatter detector, and compiled-executable stats.

The detector is the library home of the guard first written in
``tests/test_pencil2_rowgranular.py`` (the round-4/5 on-chip finding: element
scatters cost ~20 ns/element through XLA:TPU's serialized scatter, turning a
1x1-mesh pencil plan ~230x slower than the local engine while every CPU oracle
test stayed green). Promoted here so plan cards carry the same signal the
regression tests assert on — a plan whose card reports
``element_granular_ops > 0`` has reintroduced the pathology.
"""
from __future__ import annotations

import re
import time

# Metadata lookups (branch tables, shard geometry) legitimately gather single
# elements out of tiny operands; data arrays are far larger.
METADATA_ELEMS = 4096


def operand_elems(shape_str: str) -> int:
    """Element count of a StableHLO tensor type like ``'16385xf32'``."""
    dims = re.findall(r"(\d+)x", shape_str)
    n = 1
    for d in dims:
        n *= int(d)
    return n


def element_granular_ops(hlo: str, metadata_elems: int = METADATA_ELEMS):
    """``(op, operand, detail)`` rows for every gather/scatter in ``hlo``
    (StableHLO text) that moves single elements out of/into an operand larger
    than ``metadata_elems`` elements."""
    bad = []
    # gathers: slice_sizes all-1 means one element per index row
    for m in re.finditer(
        r'"stablehlo\.gather"[^\n]*?slice_sizes\s*=\s*array<i64([^>]*)>'
        r"[^\n]*?:\s*\(tensor<([^>]+)>",
        hlo,
    ):
        sizes = [int(x) for x in re.findall(r"-?\d+", m.group(1))]
        if sizes and all(s == 1 for s in sizes):
            if operand_elems(m.group(2)) > metadata_elems:
                bad.append(("gather", m.group(2), sizes))
    # scatters: no update_window_dims (StableHLO omits the attribute when
    # empty) means element updates
    for m in re.finditer(
        r'"stablehlo\.scatter"\(.*?\}\)\s*:\s*\(tensor<([^>]+)>', hlo, re.DOTALL
    ):
        mw = re.search(r"update_window_dims = \[([^\]]*)\]", m.group(0))
        window = re.findall(r"\d+", mw.group(1)) if mw else []
        if not window and operand_elems(m.group(1)) > metadata_elems:
            bad.append(("scatter", m.group(1), []))
    return bad


_OP_RE = re.compile(r"\bstablehlo\.([a-z_0-9]+)")


def hlo_op_class_counts(hlo: str) -> dict:
    """``{op_class: count}`` over a StableHLO module text — the coarse
    "what does this program spend its ops on" summary plan cards embed
    (dot_general vs gather vs collective counts is the shape of most TPU
    perf diffs here)."""
    counts: dict = {}
    for m in _OP_RE.finditer(hlo):
        op = m.group(1)
        counts[op] = counts.get(op, 0) + 1
    return dict(sorted(counts.items(), key=lambda kv: (-kv[1], kv[0])))


def compiled_stats(lowered) -> dict:
    """Compile a ``jax.stages.Lowered`` and report program statistics.

    Returns ``compile_seconds`` (wall clock of ``.compile()``),
    ``hlo_op_classes`` and ``element_granular_ops`` from the lowered StableHLO
    text, and whatever ``compiled.memory_analysis()`` exposes on this backend
    (peak/argument/output/temp/code bytes; every field is best-effort — some
    runtimes return nothing).

    Fault site ``hlo.stats`` fires before lowering text is read: compiled
    introspection is an *optional* plan-card layer, so a failure here must
    degrade ``plan.report(include_compiled=True)`` (card omits ``compiled``,
    degradation recorded) rather than fail it — obs.plancard owns that catch.
    """
    from .. import faults

    faults.site("hlo.stats")
    hlo = lowered.as_text()
    t0 = time.perf_counter()
    compiled = lowered.compile()
    stats = {
        "compile_seconds": time.perf_counter() - t0,
        "hlo_op_classes": hlo_op_class_counts(hlo),
        "element_granular_ops": len(element_granular_ops(hlo)),
    }
    mem = {}
    try:
        analysis = compiled.memory_analysis()
    except (AttributeError, NotImplementedError, RuntimeError):
        # backends without the memory-analysis API (AttributeError /
        # NotImplementedError) or whose runtime refuses it (XlaRuntimeError
        # is a RuntimeError) — the stats block just omits the mem section
        analysis = None
    for field in (
        "temp_size_in_bytes",
        "argument_size_in_bytes",
        "output_size_in_bytes",
        "alias_size_in_bytes",
        "generated_code_size_in_bytes",
        "peak_memory_in_bytes",
    ):
        value = getattr(analysis, field, None)
        if value is not None:
            mem[field] = int(value)
    stats["memory_analysis"] = mem
    return stats
