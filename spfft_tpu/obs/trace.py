"""Execution tracing: run IDs, a flight recorder, and Chrome-trace export.

The fourth observability layer (docs/details.md "Observability"): the timing
tree reports *aggregate* host cost, plan cards record *decisions*, the metrics
registry *counts* — none of them can answer "what else happened in that same
execution?". This module can: every host-facing operation (plan construction,
``forward``/``backward`` execution, a tuning trial) runs under a **run ID**,
and typed events — operation/phase begin/end spans, degradation rungs, guard
verdicts, fault injections, engine/exchange decisions, wisdom I/O — land in a
bounded ring-buffer **flight recorder** stamped with the active run ID. Plan
cards embed their construction run ID (``plan.report()["run_id"]``) and
``bench.py`` JSON carries it too, so card ↔ metrics ↔ trace join on one key.

**Arming**: the ``SPFFT_TPU_TRACE`` env knob (``1`` arms at import; capacity
via ``SPFFT_TPU_TRACE_CAP``, default :data:`DEFAULT_CAPACITY` events) or
:func:`enable`/:func:`disable` at runtime. Disarmed — the default — the
module-level recorder is a shared falsy no-op and every emit path is a single
falsy check; :func:`span`/:func:`operation` hand out one shared no-op scope
(the same zero-allocation discipline as the metrics registry's no-op
instruments and ``timing.scoped``).

**Event vocabulary** (:data:`EVENTS`): every event name emitted by the
package is declared here and every declared name is emitted somewhere —
``programs/lint.py`` enforces the list both ways, the same contract as
``obs.STAGES`` and ``faults.SITES``.

**Export**: :func:`snapshot` (JSON-stable, schema-pinned by
:func:`validate_trace` like plan cards) and :func:`chrome_trace` — Chrome
trace-event format loadable in Perfetto / ``chrome://tracing``, one track per
host phase (the ``timing.py`` phase vocabulary) with operation spans and
instant events on their own tracks.

**Dump-on-error**: when ``SPFFT_TPU_TRACE_DUMP`` names a directory, every
typed :mod:`spfft_tpu.errors` exception (guard failures included — they raise
typed errors) flushes the flight recorder there via :func:`dump`
(warn-once), so the events leading up to a crash survive it.
"""
from __future__ import annotations

import collections
import contextlib
import itertools
import json
import os
import threading
import time
import warnings

from .. import knobs

TRACE_ENV = "SPFFT_TPU_TRACE"
TRACE_CAP_ENV = "SPFFT_TPU_TRACE_CAP"
TRACE_DUMP_ENV = "SPFFT_TPU_TRACE_DUMP"
TRACE_SCHEMA = "spfft_tpu.obs.trace/1"

DEFAULT_CAPACITY = knobs.default(TRACE_CAP_ENV)

# Canonical trace event-name vocabulary. Every ``trace.event/span/operation``
# call in the package names one of these; programs/lint.py enforces the list
# both ways (every emitted name declared, every declared name emitted), the
# same contract as obs.STAGES and faults.SITES. Pure literal tuple (lint
# reads it with ast.literal_eval, import-free).
EVENTS = (
    # operation spans (each pushes/propagates the active run ID)
    "plan",            # Transform / DistributedTransform construction
    "execute",         # one host-facing backward/forward call
    "tune.trial",      # one autotuner candidate trial (child run of its plan)
    # nested host-phase spans (labels = the timing-tree phase vocabulary)
    "phase",
    # completion-fence span (sync.fence)
    "fence",
    # instants
    "decision",        # engine / exchange discipline resolution
    "degradation",     # ladder rung fired (faults.record_degradation)
    "guard",           # guard verdict, pass or fail (faults.guard)
    "fault.injected",  # armed fault site fired (faults.plane)
    "wisdom.load",     # wisdom store consulted (tuning.wisdom)
    "wisdom.save",     # wisdom store write attempt (tuning.wisdom)
    "verify",          # ABFT check verdict / retry / demotion / breaker
    #                    transition (spfft_tpu.verify)
    "serve",           # serving-layer transition (spfft_tpu.serve): admit /
    #                    reject / shed / coalesce / dispatch / complete
    "sched",           # task-graph scheduler transition (spfft_tpu.sched):
    #                    graph / place / dispatch / finalize / demote / fail
    #                    / rehost (host-loss requeue)
    "host",            # multi-host liveness transition (serve.cluster):
    #                    heartbeat verdicts, a worker host declared lost
    "rpc",             # cross-host RPC transition (serve.rpc): request
    #                    served / failed, transport death

    "perf",            # performance report built (spfft_tpu.obs.perf):
    #                    measured GFLOP/s + exchange_fraction, run-ID-joined
    "error",           # typed spfft_tpu.errors exception constructed
)

# Chrome phase codes used in recorded events: B/E duration pairs, i instants.
_PHASES = ("B", "E", "i")

_lock = threading.Lock()
_run_counter = itertools.count(1)
_dump_counter = itertools.count(1)
_tls = threading.local()


def _jsonable(value):
    """Coerce an event arg to a JSON-plain scalar (events must round-trip
    through ``json.dumps`` unchanged, like metrics snapshots)."""
    if isinstance(value, (str, int, float, bool)) or value is None:
        return value
    return str(value)


class TraceRecorder:
    """Bounded ring-buffer of typed events — the flight recorder.

    Capacity-bounded (:data:`SPFFT_TPU_TRACE_CAP`): a long-running process
    keeps the *last* N events, evicting the oldest (``dropped`` counts the
    evictions so a snapshot is honest about truncation). Thread-safe; ``seq``
    is a process-wide total order over emissions."""

    __slots__ = ("capacity", "_events", "_seq", "_dropped", "_epoch", "epoch_unix")

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.capacity = max(1, int(capacity))
        self._events: collections.deque = collections.deque(maxlen=self.capacity)
        self._seq = 0
        self._dropped = 0
        self._epoch = time.perf_counter()
        self.epoch_unix = time.time()

    def emit(self, name: str, ph: str, run: str | None, args: dict) -> None:
        with _lock:
            # timestamp under the lock so ts agrees with the seq total order
            # (concurrent emitters must not interleave read and append)
            ts = time.perf_counter() - self._epoch
            self._seq += 1
            if len(self._events) == self.capacity:
                self._dropped += 1
            self._events.append(
                {
                    "seq": self._seq,
                    "ts": ts,
                    "run": run,
                    "name": name,
                    "ph": ph,
                    "args": {k: _jsonable(v) for k, v in args.items()},
                }
            )

    def events(self) -> list:
        with _lock:
            return [dict(e, args=dict(e["args"])) for e in self._events]

    @property
    def dropped(self) -> int:
        return self._dropped

    def clear(self) -> None:
        with _lock:
            self._events.clear()
            self._dropped = 0


class _NoopRecorder:
    """Shared falsy stand-in while tracing is disarmed: the emit paths gate
    on ``if not _recorder`` — one falsy check, no allocation (the
    ``faults.site`` / no-op-instrument discipline)."""

    __slots__ = ()
    capacity = 0
    dropped = 0
    epoch_unix = 0.0

    def __bool__(self) -> bool:
        return False

    def emit(self, name, ph, run, args) -> None:
        pass

    def events(self) -> list:
        return []

    def clear(self) -> None:
        pass


_NOOP_RECORDER = _NoopRecorder()


class _NoopSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP_SPAN = _NoopSpan()


def _default_capacity() -> int:
    return knobs.get_int(TRACE_CAP_ENV)


_recorder = (
    TraceRecorder(_default_capacity())
    if knobs.get_bool(TRACE_ENV)
    else _NOOP_RECORDER
)


def enable(capacity: int | None = None) -> None:
    """Arm the flight recorder (overriding ``SPFFT_TPU_TRACE``). A fresh
    recorder is installed when tracing was off or ``capacity`` is given;
    an armed recorder with no capacity change is kept (events retained)."""
    global _recorder
    if not _recorder or capacity is not None:
        _recorder = TraceRecorder(
            _default_capacity() if capacity is None else capacity
        )


def disable() -> None:
    """Disarm: swap in the shared no-op recorder (recorded events are
    dropped; emit paths return to the single falsy check)."""
    global _recorder
    _recorder = _NOOP_RECORDER


def enabled() -> bool:
    return bool(_recorder)


def clear() -> None:
    """Drop recorded events (tests / fresh measurement windows)."""
    _recorder.clear()


def new_run_id() -> str:
    """Fresh process-unique run ID (``r``-prefixed, monotonic). Minted even
    while tracing is disarmed — plan cards always carry one, so arming the
    recorder later still joins against cards built before."""
    return f"r{next(_run_counter):06d}"


def current_run_id() -> str | None:
    """The innermost active run ID (None outside any operation scope)."""
    stack = getattr(_tls, "runs", None)
    return stack[-1] if stack else None


def event(name: str, **args) -> None:
    """Record one instant event stamped with the active run ID; a falsy
    check when disarmed. ``name`` must come from :data:`EVENTS`
    (``programs/lint.py`` enforces it on package call sites)."""
    if not _recorder:
        return
    _recorder.emit(name, "i", current_run_id(), args)


class _Span:
    """Begin/end duration event pair stamped with the active run ID."""

    __slots__ = ("_name", "_args")

    def __init__(self, name: str, args: dict):
        self._name = name
        self._args = args

    def __enter__(self):
        _recorder.emit(self._name, "B", current_run_id(), self._args)
        return self

    def __exit__(self, exc_type, exc, tb):
        args = self._args
        if exc_type is not None:
            args = dict(args, error=exc_type.__name__)
        _recorder.emit(self._name, "E", current_run_id(), args)
        return False


class _Operation:
    """A :class:`_Span` that also pushes a run ID for its scope, so every
    nested event — phases, degradations, injections, guard verdicts — is
    stamped with it. A nested operation (a tuning trial inside a plan
    construction) gets its own run ID and records its parent's."""

    __slots__ = ("_span", "_run")

    def __init__(self, name: str, run_id: str | None, args: dict):
        parent = current_run_id()
        if parent is not None:
            args = dict(args, parent=parent)
        self._run = run_id or new_run_id()
        self._span = _Span(name, args)

    def __enter__(self):
        stack = getattr(_tls, "runs", None)
        if stack is None:
            stack = _tls.runs = []
        stack.append(self._run)
        self._span.__enter__()
        return self

    def __exit__(self, exc_type, exc, tb):
        try:
            return self._span.__exit__(exc_type, exc, tb)
        finally:
            _tls.runs.pop()


@contextlib.contextmanager
def with_run(run_id: str | None):
    """Make ``run_id`` the active run for the scope WITHOUT emitting events —
    the run-ID stack is thread-local, so code that hands work to a helper
    thread (``sync.fence``'s budgeted wait) captures :func:`current_run_id`
    in the caller and re-enters it in the worker with this scope, keeping
    the card <-> metrics <-> trace join intact across threads. ``None`` is a
    no-op scope."""
    if run_id is None:
        yield
        return
    stack = getattr(_tls, "runs", None)
    if stack is None:
        stack = _tls.runs = []
    stack.append(run_id)
    try:
        yield
    finally:
        stack.pop()


def span(name: str, **args):
    """Scoped duration event (begin/end pair); the shared no-op scope when
    disarmed (zero allocation)."""
    if not _recorder:
        return _NOOP_SPAN
    return _Span(name, args)


def operation(name: str, run_id: str | None = None, **args):
    """Scoped host-facing operation: a duration span that also makes
    ``run_id`` (fresh when None) the active run for everything nested under
    it. The no-op scope when disarmed."""
    if not _recorder:
        return _NOOP_SPAN
    return _Operation(name, run_id, args)


# ---- export -------------------------------------------------------------------

_SNAPSHOT_KEYS = ("schema", "enabled", "capacity", "dropped", "epoch_unix", "events")
_EVENT_KEYS = ("seq", "ts", "run", "name", "ph", "args")


def snapshot() -> dict:
    """JSON-stable view of the flight recorder (schema
    :data:`TRACE_SCHEMA`); round-trips through ``json.dumps``/``loads``
    unchanged. ``dropped`` counts ring evictions, so consumers know when the
    window truncated."""
    return {
        "schema": TRACE_SCHEMA,
        "enabled": enabled(),
        "capacity": _recorder.capacity,
        "dropped": _recorder.dropped,
        "epoch_unix": _recorder.epoch_unix,
        "events": _recorder.events(),
    }


def validate_trace(snap: dict) -> list:
    """Missing/malformed key paths of a trace snapshot ([] when valid) —
    the schema pin, same style as ``obs.validate_snapshot`` /
    ``obs.validate_plan_card``."""
    missing = [k for k in _SNAPSHOT_KEYS if k not in snap]
    if snap.get("schema") not in (None, TRACE_SCHEMA):
        missing.append(f"schema (unknown: {snap['schema']!r})")
    for i, ev in enumerate(snap.get("events", ())):
        missing.extend(f"events[{i}].{k}" for k in _EVENT_KEYS if k not in ev)
        if ev.get("ph") not in _PHASES:
            missing.append(f"events[{i}].ph (unknown: {ev.get('ph')!r})")
        if ev.get("name") not in EVENTS:
            missing.append(f"events[{i}].name (unknown: {ev.get('name')!r})")
    return missing


# ---- cross-host segments --------------------------------------------------

# Wire format of the cross-host trace join (docs/details.md "Observability",
# fleet layer): a worker host answers an RPC whose frame carried the caller's
# run ID with the slice of its OWN flight recorder stamped with that run, and
# the cluster front splices the slice into the local recorder tagged
# ``host=`` — one front-side snapshot()/chrome_trace() then shows the whole
# cross-host request under one run ID.
SEGMENT_SCHEMA = "spfft_tpu.obs.trace.segment/1"
_SEGMENT_KEYS = ("schema", "run", "events")
_SEGMENT_EVENT_KEYS = ("ts", "name", "ph", "args")


def segment(run_id: str, limit: int | None = None) -> dict:
    """Compact, schema-pinned slice of the flight recorder: every recorded
    event stamped with ``run_id``, stripped to the wire keys
    (``ts``/``name``/``ph``/``args`` — ``seq`` is recorder-local and the run
    is hoisted to the envelope). ``limit`` keeps the NEWEST events (reply
    frames stay bounded; the ring already bounds the worst case). Empty
    while disarmed — a disarmed worker still answers, with no events."""
    events = [
        {"ts": e["ts"], "name": e["name"], "ph": e["ph"], "args": e["args"]}
        for e in _recorder.events()
        if e["run"] == run_id
    ]
    if limit is not None and len(events) > int(limit):
        events = events[-int(limit):]
    return {"schema": SEGMENT_SCHEMA, "run": run_id, "events": events}


def validate_segment(seg: dict) -> list:
    """Missing/malformed key paths of a remote-span segment ([] when
    valid) — the schema pin of the cross-host wire format."""
    if not isinstance(seg, dict):
        return ["segment (not a dict)"]
    missing = [k for k in _SEGMENT_KEYS if k not in seg]
    if seg.get("schema") != SEGMENT_SCHEMA:
        missing.append(f"schema (unknown: {seg.get('schema')!r})")
    for i, ev in enumerate(seg.get("events", ())):
        if not isinstance(ev, dict):
            missing.append(f"events[{i}] (not a dict)")
            continue
        missing.extend(
            f"events[{i}].{k}" for k in _SEGMENT_EVENT_KEYS if k not in ev
        )
        if ev.get("ph") not in _PHASES:
            missing.append(f"events[{i}].ph (unknown: {ev.get('ph')!r})")
        if ev.get("name") not in EVENTS:
            missing.append(f"events[{i}].name (unknown: {ev.get('name')!r})")
    return missing


def splice(seg: dict, host: str | None = None) -> int:
    """Re-emit a remote segment's events into the local flight recorder
    under the segment's run ID, each tagged ``host=`` and carrying the
    remote recorder's timestamp as ``remote_ts`` (local ``ts``/``seq`` are
    assigned at splice time — two hosts' clocks never interleave). Events
    that fail the segment schema are SKIPPED, never spliced — remote spans
    are advisory and must not invalidate the local snapshot — and the
    count of spliced events is returned (0 while disarmed or on a
    malformed envelope)."""
    if not _recorder or not isinstance(seg, dict):
        return 0
    if seg.get("schema") != SEGMENT_SCHEMA:
        return 0
    run = seg.get("run")
    spliced = 0
    for ev in seg.get("events", ()):
        if not isinstance(ev, dict):
            continue
        if any(k not in ev for k in _SEGMENT_EVENT_KEYS):
            continue
        if ev["ph"] not in _PHASES or ev["name"] not in EVENTS:
            continue
        args = dict(ev["args"] if isinstance(ev["args"], dict) else {})
        if host is not None:
            args["host"] = str(host)
        args["remote_ts"] = ev["ts"]
        _recorder.emit(ev["name"], ev["ph"], run, args)
        spliced += 1
    return spliced


def _track_of(ev: dict) -> str:
    """Chrome track key: host phases get one track per phase label (the
    issue contract — the timing vocabulary becomes rows), every other event
    name is its own track."""
    if ev["name"] == "phase":
        return str(ev["args"].get("label", "phase"))
    return ev["name"]


def chrome_trace(snap: dict | None = None) -> dict:
    """Chrome trace-event rendering of a snapshot — loadable in Perfetto /
    ``chrome://tracing``. One process ("spfft_tpu host"), one named track
    per host phase / event name; B/E spans render as slices, instants as
    thread-scoped ``i`` events; every event's args carry its run ID.

    Ring eviction can orphan a ``B`` or ``E`` at the window edge; viewers
    tolerate the unmatched end, and ``dropped`` in the source snapshot says
    whether the window truncated.
    """
    snap = snapshot() if snap is None else snap
    pid = 1
    out = [
        {
            "name": "process_name",
            "ph": "M",
            "pid": pid,
            "tid": 0,
            "args": {"name": "spfft_tpu host"},
        }
    ]
    tids: dict = {}
    for ev in snap.get("events", ()):
        track = _track_of(ev)
        tid = tids.get(track)
        if tid is None:
            tid = tids[track] = len(tids) + 1
            out.append(
                {
                    "name": "thread_name",
                    "ph": "M",
                    "pid": pid,
                    "tid": tid,
                    "args": {"name": track},
                }
            )
        entry = {
            "name": track,
            "cat": ev["name"],
            "ph": ev["ph"],
            "ts": round(ev["ts"] * 1e6, 3),  # Chrome wants microseconds
            "pid": pid,
            "tid": tid,
            "args": {**ev["args"], "run": ev["run"], "seq": ev["seq"]},
        }
        if ev["ph"] == "i":
            entry["s"] = "t"  # thread-scoped instant
        out.append(entry)
    return {"traceEvents": out, "displayTimeUnit": "ms"}


# ---- dump-on-error ------------------------------------------------------------

# Dump files rotate like the event ring: at most DUMP_KEEP files per process,
# the oldest overwritten — a long-running service with recovered typed errors
# keeps bounded disk AND the dump that matters (the final crash) is always
# among the newest files, never dropped for a cap.
DUMP_KEEP = 64

_dump_warned = False


@contextlib.contextmanager
def suppressed_dumps():
    """Scope in which :func:`dump` is a no-op (events still record).

    For code that *expects and recovers from* typed errors — tuning-trial
    isolation, probe paths — so a debugging session with
    ``SPFFT_TPU_TRACE_DUMP`` armed is not flooded with dumps of errors the
    ladder handled."""
    prev = getattr(_tls, "no_dump", 0)
    _tls.no_dump = prev + 1
    try:
        yield
    finally:
        _tls.no_dump = prev


def dump(reason: str = "error") -> str | None:
    """Flush the flight recorder to a JSON file in the
    ``SPFFT_TPU_TRACE_DUMP`` directory; returns the path (None when the knob
    is unset, tracing is disarmed, a :func:`suppressed_dumps` scope is
    active, or the write failed — a dump must never add a second failure to
    the one being dumped). At most :data:`DUMP_KEEP` files per process, the
    oldest rotated over. Warns once per process on the first dump so crash
    logs point at the artifact.

    Called automatically when a typed :mod:`spfft_tpu.errors` exception is
    constructed (guard failures raise those), and callable directly from
    debugging sessions."""
    global _dump_warned
    directory = knobs.get_str(TRACE_DUMP_ENV)
    if not directory or not _recorder or getattr(_tls, "no_dump", 0):
        return None
    doc = dict(snapshot(), reason=str(reason))
    path = os.path.join(
        directory,
        f"trace-{os.getpid()}-{next(_dump_counter) % DUMP_KEEP:04d}.json",
    )
    try:
        os.makedirs(directory, exist_ok=True)
        with open(path, "w") as f:
            json.dump(doc, f, indent=1)
    except OSError:
        return None
    with _lock:
        first = not _dump_warned
        _dump_warned = True
    if first:
        warnings.warn(
            f"spfft_tpu flight recorder dumped to {path!r} ({reason})",
            RuntimeWarning,
            stacklevel=3,
        )
    return path
