"""spfft_tpu.obs — unified metrics, plan introspection, and execution tracing.

Six observability layers, coarse to fine (docs/details.md "Observability"):

1. **Host timing tree** (:mod:`spfft_tpu.timing`): rt_graph-parity nested wall
   -clock statistics of the host-visible phases (init, staging, dispatch,
   wait).
2. **This module**: *plan cards* — ``plan.report()`` /
   :func:`plan_card`, the machine-readable record of every plan-time decision
   (exchange discipline chosen AND the cost-model table of rejected
   alternatives, sparse-y engagement, compiled-program stats) — and *run
   metrics* — a process-global counter/gauge/histogram registry
   (:func:`counter`/:func:`gauge`/:func:`histogram`) recording what the
   host-facing paths did, exported via :func:`snapshot` (JSON) and
   :func:`prometheus_text`. ``SPFFT_TPU_METRICS=0`` turns the registry into
   shared no-ops.
3. **Execution trace** (:mod:`spfft_tpu.obs.trace`): per-execution typed
   events — run-ID-correlated operation/phase spans, degradations, guard
   verdicts, fault injections, decisions — in a bounded flight recorder
   (``SPFFT_TPU_TRACE``), exported as schema-pinned JSON
   (``trace.snapshot()``) and Chrome trace-event format
   (``trace.chrome_trace()``), flushed to ``SPFFT_TPU_TRACE_DUMP`` when a
   typed error fires. Plan cards embed their construction run ID, so card,
   metrics and trace join on one key.
4. **Device traces** (``jax.profiler`` via ``programs/profile.py``): per-stage
   attribution inside the compiled programs, tagged with the canonical
   :data:`STAGES` scope names every engine uses (``programs/lint.py`` enforces
   the list both ways).
5. **Performance reports** (:mod:`spfft_tpu.obs.perf`): measured, fenced
   seconds-per-pair attributed to the same :data:`STAGES` vocabulary via
   analytic flop/byte models — per-stage GFLOP/s, GB/s and the
   ``exchange_fraction`` scoreboard, schema-pinned
   (:func:`perf.validate_perf_report`) and run-ID-joined like everything
   above. Surfaces: ``programs/dbench.py`` (multichip scaling rows),
   ``programs/perf_gate.py`` + ``./ci.sh perf`` (regression gate),
   ``bench.py`` (embedded report).
6. **Fleet aggregation** (:mod:`spfft_tpu.obs.fleet`): the first layer that
   spans processes — each worker host's registry snapshot scraped over the
   ``metrics`` RPC op (bounded per-host deadline, lost hosts skipped typed)
   and merged into one host-labeled ``spfft_tpu.obs.fleet/1`` document
   (counters summed, histogram buckets summed, gauges per-host), with
   :func:`fleet.validate_fleet` and :func:`fleet.fleet_prometheus_text`;
   cross-host *trace propagation* rides the same RPC plane (run IDs in
   request frames, remote-span segments spliced back ``host=``-tagged), so
   the run-ID join holds across the fleet.
"""
from . import fleet, perf, trace  # noqa: F401
from .registry import (  # noqa: F401
    HISTOGRAM_BUCKETS,
    METRICS_ENV,
    SNAPSHOT_SCHEMA,
    clear,
    counter,
    disable,
    enable,
    gauge,
    histogram,
    is_enabled,
    phase_timer,
    prometheus_text,
    snapshot,
    validate_snapshot,
)
from .stages import STAGES  # noqa: F401

# Heavier pieces (plan cards pull in engine/parallel modules, hlo pulls
# compile machinery) resolve lazily so importing the package — which the
# engines themselves do for the registry — cannot cycle.


def plan_card(transform, *, include_compiled: bool = False) -> dict:
    """Structured record of a plan's decisions (see obs.plancard)."""
    from .plancard import plan_card as _plan_card

    return _plan_card(transform, include_compiled=include_compiled)


def validate_plan_card(card: dict) -> list:
    """Missing-key paths of a plan card ([] when schema-complete)."""
    from .plancard import validate_plan_card as _validate

    return _validate(card)


def validate_report(report: dict) -> list:
    """Validate a ``programs/report.py`` JSON document: a ``plan`` card plus
    a ``metrics`` snapshot. Returns the combined missing-key paths."""
    missing = []
    if "plan" not in report:
        missing.append("plan")
    else:
        missing.extend(f"plan.{m}" for m in validate_plan_card(report["plan"]))
    if "metrics" not in report:
        missing.append("metrics")
    else:
        missing.extend(
            f"metrics.{m}" for m in validate_snapshot(report["metrics"])
        )
    return missing
