"""Canonical run-metrics vocabulary: every instrument, declared once.

The registry (:mod:`.registry`) hands out counters/gauges/histograms by
name — which means a typo'd name or a divergent label set creates a NEW
time series silently, and dashboards join against nothing. This module is
the single declaration of the package's metric surface, the same contract
shape as ``obs.STAGES`` / ``faults.SITES`` / ``trace.EVENTS``:

* every ``obs.counter/gauge/histogram/phase_timer`` call in the package
  names a row here, with exactly the declared label keys,
* every row is emitted by at least one package call site (no dead
  declarations),
* the docs table in ``docs/details.md`` regenerates from this tuple
  (``programs/gen_api_docs.py``, the knob-table pattern).

The ``metrics-vocab`` checker (SA016, ``spfft_tpu.analysis``) enforces all
three directions; the import-free analysis layer reads this surface via
``ast``, so ``METRICS`` must stay a pure literal.

Rows are ``(name, kind, label_keys, doc)``. Label VALUES are free-form
(tenants, engines, stage names); only the key set is pinned.
"""
from __future__ import annotations

METRICS = (
    # ---- transform execution ------------------------------------------------
    ("transforms_total", "counter", ("direction", "engine"),
     "host-facing transforms executed, per direction and engine"),
    ("staged_bytes_total", "counter", ("direction",),
     "bytes staged across the host boundary (host_to_device / "
     "device_to_host)"),
    ("exchange_wire_bytes_total", "counter", ("engine",),
     "exact geometry wire bytes shipped through mesh exchanges"),
    ("dispatch_seconds", "histogram", ("direction",),
     "host time to enqueue one compiled program (async dispatch)"),
    ("wait_seconds", "histogram", ("direction",),
     "host time blocked on completion (fence / block_until_ready)"),
    ("execution_failures_total", "counter", ("op",),
     "dispatch/fence failures converted to typed execution errors"),
    ("engine_fallbacks_total", "counter", ("from", "to"),
     "degradation-ladder engine substitutions (e.g. MXU compile failure "
     "-> jnp.fft)"),
    ("degradations_total", "counter", ("event",),
     "degradation-ladder rungs taken, by recorded event name"),
    ("ir_dispatches_total", "counter", ("mode", "direction"),
     "stage-graph IR program dispatches (fused=1/direction, staged=1/node, "
     "batched=1/batch)"),
    # ---- guard / faults -----------------------------------------------------
    ("guard_checks_total", "counter", ("check",),
     "guard-mode validations performed (NaN/Inf scans, contracts)"),
    ("guard_failures_total", "counter", ("check",),
     "guard-mode validations that raised typed"),
    ("faults_injected_total", "counter", ("site", "kind"),
     "chaos injections that actually fired, per site and kind"),
    ("sync_probe_failures_total", "counter", ("error",),
     "advisory-fence platform probes that failed (by exception type)"),
    # ---- tuning / wisdom ----------------------------------------------------
    ("tuning_trials_total", "counter", ("candidate",),
     "autotuner trial candidates measured"),
    ("tuning_trial_failures_total", "counter", ("candidate",),
     "trial candidates that errored into an error row"),
    ("tuning_trial_seconds", "histogram", (),
     "wall time of one trial measurement (warmup + repeats)"),
    ("wisdom_quarantined_total", "counter", (),
     "corrupt wisdom stores/bundles moved aside to *.corrupt"),
    ("wisdom_retries_total", "counter", (),
     "wisdom write retries (transient filesystem failures)"),
    ("wisdom_save_failures_total", "counter", (),
     "wisdom writes abandoned after the retry budget (recorded loss)"),
    # ---- verification / breaker ---------------------------------------------
    ("verify_checks_total", "counter", ("check", "verdict"),
     "ABFT check evaluations, per check and pass/fail verdict"),
    ("verify_retries_total", "counter", ("direction",),
     "supervisor re-executions after a failed check or typed error"),
    ("verify_recoveries_total", "counter", ("direction",),
     "supervised transforms that recovered (retry or demote rung)"),
    ("verify_failures_total", "counter", ("direction",),
     "supervised attempts that failed a check or raised typed"),
    ("verify_breaker_state", "gauge", ("engine",),
     "per-engine circuit-breaker state (0 closed / 1 half-open / 2 open)"),
    ("verify_breaker_trips_total", "counter", ("engine",),
     "circuit-breaker open transitions"),
    # ---- serving ------------------------------------------------------------
    ("serve_requests_total", "counter", ("tenant", "outcome"),
     "serviced requests, per tenant and resolution outcome"),
    ("serve_sheds_total", "counter", ("reason",),
     "requests refused/shed (queue_full, tenant_quota, fair_share, "
     "deadline, breaker_open, plan_evicted, closing)"),
    ("serve_deadline_misses_total", "counter", ("tenant",),
     "requests that expired before or during dispatch"),
    ("serve_batches_total", "counter", (),
     "coalesced batches executed"),
    ("serve_retries_total", "counter", (),
     "batch re-dispatches after transient typed failures"),
    ("serve_demotions_total", "counter", ("engine",),
     "batches rerouted through the jnp.fft reference rung on an open "
     "breaker"),
    ("serve_plan_cache_total", "counter", ("event",),
     "plan-cache traffic (hit / miss / evict)"),
    ("serve_queue_depth", "gauge", (),
     "admission-queue depth high-water tracking"),
    ("serve_batch_occupancy", "histogram", (),
     "requests coalesced per executed batch"),
    ("serve_latency_seconds", "histogram", ("tenant",),
     "admission-to-resolution latency per request"),
    ("serve_phase_seconds", "histogram", ("phase",),
     "per-request seconds spent reaching each ticket phase stamp from the "
     "previous one (admitted -> coalesced -> dispatched -> wire -> "
     "remote_execute -> finalized); labeled by the phase REACHED, so "
     "phase=\"coalesced\" is queue wait and phase=\"remote_execute\" is "
     "the cross-host round trip"),
    # ---- multi-host serving -------------------------------------------------
    ("hosts_lost_total", "counter", ("host",),
     "worker hosts declared lost (missed heartbeat budget or dead RPC "
     "transport)"),
    ("host_heartbeats_total", "counter", ("verdict",),
     "liveness probes sent to worker hosts, per ok/missed verdict"),
    ("host_requeues_total", "counter", (),
     "in-flight tasks requeued onto a surviving host after host loss"),
    ("rpc_requests_total", "counter", ("op", "outcome"),
     "length-prefixed-JSON RPC requests served by a worker host, per op "
     "and ok/error outcome"),
    ("fleet_scrapes_total", "counter", ("host", "outcome"),
     "per-host metric scrapes by the fleet aggregator (obs.fleet), per "
     "ok / lost (skipped typed) / unreachable outcome"),
    ("remote_spans_spliced_total", "counter", ("host",),
     "remote trace-segment events spliced into the local flight recorder "
     "by the cluster front (cross-host run-ID join)"),
    # ---- scheduler ----------------------------------------------------------
    ("sched_tasks_total", "counter", ("outcome",),
     "task-graph tasks resolved, per outcome"),
    ("sched_place_total", "counter", ("provenance",),
     "placement decisions, per provenance (model / wisdom / pinned)"),
    ("sched_retries_total", "counter", (),
     "task re-dispatches inside the executor ladder"),
    ("sched_inflight", "gauge", (),
     "transform executions currently dispatched and unfinalized"),
    ("sched_graph_depth", "gauge", (),
     "critical-path depth of the last scheduled graph"),
    # ---- performance observatory --------------------------------------------
    ("perf_pair_seconds", "histogram", ("engine", "decomposition"),
     "fenced seconds per backward+forward pair (perf reports)"),
    ("perf_stage_seconds", "histogram", ("stage",),
     "modeled per-stage seconds from the perf attribution"),
    ("perf_gflops", "gauge", ("engine", "decomposition"),
     "dense-equivalent GFLOP/s of the last perf report"),
    ("perf_exchange_fraction", "gauge", ("engine", "decomposition"),
     "exposed exchange fraction of the last perf report (the overlap "
     "scoreboard)"),
)

KINDS = ("counter", "gauge", "histogram")


def names() -> tuple:
    """Declared metric names, registration order."""
    return tuple(row[0] for row in METRICS)


def describe() -> list:
    """JSON-plain dump of the vocabulary (docs generation / tests)."""
    return [
        {"name": n, "kind": k, "labels": list(labels), "doc": d}
        for n, k, labels, d in METRICS
    ]
