"""Distributed (mesh) execution: slab<->pencil repartition over ICI/DCN.

The analogue of the reference's MPI transpose + parameter machinery
(reference: src/transpose/*, src/parameters/parameters.cpp:43-140), rebuilt on
``jax.sharding.Mesh`` + ``shard_map`` with ``lax.all_to_all`` collectives.
"""
from .mesh import init_distributed, make_fft_mesh, make_fft_mesh2  # noqa: F401
from .execution import DistributedExecution  # noqa: F401
