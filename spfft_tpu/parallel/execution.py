"""Mesh-distributed execution engine.

The analogue of the reference's distributed pipeline — ExecutionHost/GPU wired to an
MPI transpose (reference: src/execution/execution_host.cpp:125-243,
src/transpose/transpose_mpi_buffered_gpu.cpp) — rebuilt TPU-first:

* one ``shard_map``-ped program over a 1-D ``"fft"`` mesh axis; XLA compiles the whole
  backward/forward pipeline (FFTs + repack + collective) into a single executable,
* the slab<->pencil repartition is an equal-split ``lax.all_to_all`` over ICI — the
  reference's BUFFERED exchange discipline (uniform max_sticks x max_planes blocks,
  reference: src/transpose/transpose_mpi_buffered_host.cpp:53-270); COMPACT/UNBUFFERED
  instead run the exact-counts ppermute chain (parallel/ragged.py),
* the pack/unpack kernels of the reference (buffered_kernels.cu) become static
  gather/scatter index maps XLA fuses into the surrounding stages,
* ``*_FLOAT`` exchange variants cast the wire payload to complex64 around the
  collective, halving ICI bytes for f64 transforms
  (reference: src/gpu_util/complex_conversion.cuh:37-56),
* the OVERLAPPED discipline (``overlap`` chunks > 1, padded wire formats
  only) splits the stick batch into C chunks, each with its own
  z-FFT -> pack -> all_to_all chain and no cross-chunk dependence, so chunk
  k's collective can hide behind chunk k+1's FFTs — the pipelined all-to-all
  of "Fast parallel multidimensional FFT using advanced MPI"
  (arxiv.org/pdf/1804.09536); the autotuner owns the chunk count
  (tuning/candidates.py).

Frequency-domain per-shard data is padded to uniform (V_max values, S_max sticks);
space-domain slabs to L_max planes. Padded slots carry out-of-bounds sentinels and are
dropped/zero-filled by the gather/scatter ops, so they never contaminate results.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..execution import _complex_dtype
from ..ops import symmetry
from ..parameters import DistributedParameters
from ..types import (
    RAGGED_EXCHANGES as _RAGGED_EXCHANGES,
    ExchangeType,
    ScalingType,
    TransformType,
)
from .mesh import FFT_AXIS, fft_axis_size
from .ragged import OneShotExchange, RaggedExchange


def chunk_ranges(n: int, chunks: int) -> list:
    """``chunks`` contiguous, near-equal ``(start, stop)`` ranges covering
    ``[0, n)`` — the chunk split of the OVERLAPPED exchange discipline (first
    ``n % chunks`` ranges get one extra element). Callers clamp ``chunks`` to
    ``[1, n]`` first, so no range is ever empty."""
    chunks = max(1, min(int(chunks), int(n)))
    base, extra = divmod(int(n), chunks)
    out, start = [], 0
    for i in range(chunks):
        stop = start + base + (1 if i < extra else 0)
        out.append((start, stop))
        start = stop
    return out


def mesh_process_span(mesh) -> int:
    """Number of OS processes the mesh's devices live on.

    Computed from the device objects themselves — NOT ``jax.process_count()``,
    which queries the default backend and can therefore initialize (and block
    on) an unrelated wedged accelerator plugin even when every mesh device is
    a CPU device. The mesh-span semantic is also the correct one: per-process
    block assembly is needed exactly when THIS mesh spans processes."""
    return len({d.process_index for d in mesh.devices.flat})


def exchange_build_checkpoint() -> None:
    """Fault checkpoint every distributed engine passes while constructing
    its exchange machinery (site ``exchange.build`` — an injected failure
    models the collective/transport layer refusing to build). Plan
    construction converts a failure that survives the engine-fallback rung
    into a typed :class:`~spfft_tpu.errors.MPIError` (distributed.py)."""
    from .. import faults

    faults.site("exchange.build")


def _check_multihost_mesh(mesh) -> None:
    """Fail fast at plan creation: multi-process padding requires a dedicated
    1-D fft mesh (multi-axis meshes are single-controller only) — catching it
    here avoids compiling pipelines that die at first data staging."""
    span = mesh_process_span(mesh)
    if span > 1 and mesh.devices.ndim != 1:
        from ..errors import InvalidParameterError

        raise InvalidParameterError(
            f"multi-process runs require a dedicated 1-D fft mesh, but this "
            f"{'x'.join(str(s) for s in mesh.devices.shape)} mesh (axes "
            f"{tuple(mesh.axis_names)}) spans {span} processes: per-process "
            "block assembly (pad_values/unpad_space) is only defined along "
            "one slab axis. Multi-axis pencil meshes run single-controller; "
            'see docs/details.md "Multi-host serving & host loss".'
        )


class PaddingHelpers:
    """Host-side padding between caller per-shard arrays and the padded-uniform
    sharded device layout, plus exchange-volume accounting. Shared by both mesh
    engines (DistributedExecution and MxuDistributedExecution); requires
    ``params``, ``mesh``, ``real_dtype``, ``complex_dtype``, ``is_r2c``, ``_S``,
    ``_V``, ``_L``, ``_ragged`` (None for padded disciplines), a
    ``_wire_scalar_bytes()`` method, ``value_sharding`` and ``space_sharding``
    on the inheriting class.

    Multi-host: when the mesh spans processes (after
    :func:`spfft_tpu.init_distributed`), each process supplies/receives only the
    shards on its own devices — the reference's per-rank data contract
    (reference: docs/source/details.rst:50-53). Remote entries of
    ``values_per_shard`` may be ``None``; ``unpad_*`` return ``None`` for
    remote shards.
    """

    # Mesh axes the engine's per-shard IR graphs are mapped over
    # (spfft_tpu.ir.compile derives partition specs from these; the 2-D
    # pencil engines override with their (AX1, AX2) pair).
    _IR_AXES = (FFT_AXIS,)

    def _ir_spec(self) -> dict:
        """The :mod:`spfft_tpu.ir` compile-layer contract of the mesh
        engines: per-shard graphs compiled under ``shard_map`` over
        :data:`_IR_AXES`, the engine's monolithic jits as the
        ``ir_lower_failed`` legacy rung."""
        from .mesh import shard_mapper

        return {
            "kind": "mesh",
            "axes": self._IR_AXES,
            "sm": shard_mapper(self.mesh),
            "legacy_backward": self._backward,
            "legacy_forward": self._forward,
        }

    # ---- batch-fused entries (SPFFT_TPU_BATCH_FUSE, spfft_tpu.ir) -------------
    # Sharded stacked arrays (P, B, *per_shard): mesh axis on the block dim,
    # every shard holding its slice of all B requests. One shard_map program
    # per direction per batch; None = batch fusion unavailable (caller loops).

    def backward_pair_batch(self, values_re, values_im):
        """Stacked (P, B, V_max) freq pairs -> stacked space slabs
        ((P, B, L, Y, X); pair for C2C), or ``None`` (caller loops)."""
        return self._ir.run_backward_batch(
            values_re, values_im, self._value_indices
        )

    def forward_pair_batch(
        self, space_re, space_im, scaling: ScalingType = ScalingType.NONE
    ):
        """Stacked (P, B, L, Y, X) space slabs -> stacked (P, B, V_max)
        freq pairs, or ``None``."""
        s = ScalingType(scaling)
        if self.is_r2c:
            return self._ir.run_forward_batch(
                s, space_re, self._value_indices
            )
        return self._ir.run_forward_batch(
            s, space_re, space_im, self._value_indices
        )

    def _batched_sharding(self, sharding):
        """``sharding`` with a replicated batch axis spliced in after the
        mesh block dim — the layout of every stacked batched array."""
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        spec = sharding.spec
        return NamedSharding(self.mesh, P(spec[0], None, *spec[1:]))

    def stack_staged(self, staged, sharding):
        """Stack per-request staged device arrays along the batch axis
        (axis 1, after the mesh block dim) and commit the stack to the
        batched sharding — the staging half every mesh batch entry rides."""
        return jax.device_put(
            jnp.stack(staged, axis=1), self._batched_sharding(sharding)
        )

    def _local_shard_ids(self):
        # flat device index == shard id only on a dedicated 1-D fft mesh; the
        # per-process block-assembly path below relies on that
        if self.mesh.devices.ndim != 1:
            from ..errors import InvalidParameterError

            raise InvalidParameterError(
                f"multi-process padding requires a dedicated 1-D fft mesh; "
                f"this one is "
                f"{'x'.join(str(s) for s in self.mesh.devices.shape)} (axes "
                f"{tuple(self.mesh.axis_names)}) — multi-axis meshes are "
                "supported in single-controller mode only (see "
                'docs/details.md "Multi-host serving & host loss")'
            )
        me = jax.process_index()
        return [
            i for i, d in enumerate(self.mesh.devices.flat) if d.process_index == me
        ]

    def _check_count(self, r, v):
        if v.size != int(self.params.num_values_per_shard[r]):
            from ..errors import InvalidParameterError

            raise InvalidParameterError(
                f"shard {r}: expected {int(self.params.num_values_per_shard[r])} "
                f"values, got {v.size}"
            )

    def _dispatch_forward(self, table, space_re, space_im, scaling):
        """Select the scaling-specialized forward and pass the r2c-dependent
        argument tuple (engines with their own contract override this)."""
        fn = table[ScalingType(scaling)]
        if self.is_r2c:
            return fn(space_re, self._value_indices)
        return fn(space_re, space_im, self._value_indices)

    def _wire_scalar_bytes(self) -> int:
        from ..types import wire_scalar_bytes

        return wire_scalar_bytes(self.exchange_type, self.real_dtype)

    def _ragged_wire_format(self):
        """The ragged chain's wire tag, derived from the same single-sourced
        rule (types.wire_dtype) the padded exchanges use."""
        from ..types import wire_dtype

        wd = wire_dtype(self.exchange_type, self.real_dtype)
        if wd == jnp.bfloat16:
            return "bf16"
        if wd != self.real_dtype:
            return "f32"
        return None

    def _exchange_axis_span(self, axes) -> int:
        """Static shard count an exchange over ``axes`` spans."""
        names = (axes,) if isinstance(axes, str) else tuple(axes)
        return int(np.prod([int(self.mesh.shape[n]) for n in names]))

    def _complex_wire_exchange(self, buffer, axes):
        """all_to_all on a complex buffer in the plan's wire format — derived
        from types.wire_dtype, the same rule the byte accounting uses, so the
        cast and the accounting cannot diverge.

        A single-shard exchange is the identity: no collective is emitted, so a
        P=1 distributed plan compiles to the same compute-only program shape as
        a local one (the reference's 1-rank MPI transform likewise takes the
        plain compute path, reference: src/spfft/transform_internal.cpp:45-137),
        and the surrounding pack/unpack reshapes collapse to metadata."""
        if self._exchange_axis_span(axes) == 1:
            return buffer
        from ..types import wire_dtype

        wd = wire_dtype(self.exchange_type, self.real_dtype)
        if wd == jnp.bfloat16:
            # no complex-bf16 dtype: ride as a (re, im)-stacked real pair —
            # still one collective, half the f32 wire bytes
            wire = jnp.stack(
                [buffer.real.astype(wd), buffer.imag.astype(wd)], axis=1
            )
            recv = jax.lax.all_to_all(
                wire, axes, split_axis=0, concat_axis=0, tiled=True
            )
            recv = recv.astype(self.real_dtype)
            return jax.lax.complex(recv[:, 0], recv[:, 1]).astype(self.complex_dtype)
        if wd != self.real_dtype:  # f32 wire for an f64 plan
            recv = jax.lax.all_to_all(
                buffer.astype(np.complex64), axes, split_axis=0, concat_axis=0,
                tiled=True,
            )
            return recv.astype(self.complex_dtype)
        return jax.lax.all_to_all(buffer, axes, split_axis=0, concat_axis=0, tiled=True)

    def stage_accounting(self) -> list:
        """Analytic per-stage flop/byte rows for one backward+forward pair —
        the :mod:`spfft_tpu.obs.perf` hook (stage names from ``obs.STAGES``).

        Flops follow the ``5 n log2 n`` 1-D-pass model (the z pass is
        sparse-aware: only the plan's active sticks transform); bytes count
        the complex elements each data-movement stage touches (read+write)
        and, for the ``exchange`` stage, the same off-shard wire volume the
        plan card embeds (:meth:`exchange_wire_bytes`) — so perf attribution
        and the card's exchange accounting cannot diverge. The common
        head/tail rows come from the perf layer's shared builders; this hook
        supplies the slab exchange middle, discipline-aware: the padded path
        carries ``pack``/``unpack`` rows, the ragged chains (whose
        pack/unpack ride inside the collective steps) only the backward slab
        ``unpack``.

        Under the OVERLAPPED discipline (``_overlap`` chunks > 1) the
        exchange row carries the ``exchange overlapped`` label and an
        ``overlap`` record naming the compute stage its chunks hide behind
        (the z pass) — the perf layer attributes only the *exposed*
        (non-hidden) share of its wire time to it, while the row's ``bytes``
        stay the exact geometry wire volume (obs/perf.py ``_attribute``)."""
        from ..obs.perf import pipeline_head_rows, pipeline_tail_rows

        p = self.params
        P = int(p.num_shards)
        Z, Y, X, Xf = p.dim_z, p.dim_y, p.dim_x, p.dim_x_freq
        c_item = 2 * self.real_dtype.itemsize
        total_sticks = int(np.asarray(p.num_sticks_per_shard).sum())
        grid_elems = Z * Y * Xf  # global slab (padding excluded: sum L == Z)
        rows = pipeline_head_rows(
            int(np.asarray(p.num_values_per_shard).sum()), total_sticks, Z,
            c_item,
            stick_symmetry=self.is_r2c and p.zero_stick_shard >= 0,
        )
        if P > 1:
            if self._ragged is None:
                buf = P * P * self._L * self._S  # padded buffers, all shards
                ends = P * (self._S * Z + self._L * Y * Xf)  # stage endpoints
                rows.append(
                    {"stage": "pack", "flops": 0, "bytes": (2 * buf + ends) * c_item}
                )
                rows.append(
                    {"stage": "unpack", "flops": 0, "bytes": (2 * buf + ends) * c_item}
                )
            else:
                rows.append(
                    {"stage": "unpack", "flops": 0, "bytes": grid_elems * c_item}
                )
            ov = getattr(self, "_overlap", 1)
            row = {
                "stage": "exchange" if ov == 1 else "exchange overlapped",
                "flops": 0,
                # per pair (fwd + bwd volumes are equal) — exact geometry
                # wire bytes under BOTH labels; overlap changes exposure,
                # never the modeled volume
                "bytes": 2 * self.exchange_wire_bytes(),
            }
            if ov > 1:
                row["overlap"] = {"chunks": int(ov), "hides": "z transform"}
            rows.append(row)
        y_lines = Z * int(getattr(self, "_num_x_active", Xf) or Xf)
        return rows + pipeline_tail_rows(
            Z, Y, X, y_lines, c_item,
            plane_symmetry=self.is_r2c,
            y_scope=getattr(self, "_y_stage_scope", lambda: "y transform")(),
        )

    def exchange_wire_bytes(self) -> int:
        """Off-shard bytes one slab<->pencil repartition puts on the
        interconnect (self-blocks excluded for all disciplines; per direction
        — forward and backward volumes are equal).

        Padded (BUFFERED): every shard sends P-1 uniform S_max x L_max blocks.
        COMPACT: the ppermute chain's per-step buffers, sized
        max_i sticks_i * planes_{(i+k) mod P}. UNBUFFERED: the exact Alltoallw
        volume sum_{i != j} sticks_i * planes_j. Lets callers pick the
        discipline from plan geometry instead of folklore.

        Bytes only — pair with :meth:`exchange_rounds` for the latency side
        (see parallel/ragged.py's LATENCY note)."""
        p = self.params
        if self._ragged is not None:
            elems = self._ragged.offwire_elems()
        else:
            elems = p.num_shards * (p.num_shards - 1) * self._S * self._L
        # elems counts complex values; x2 real scalars each
        return elems * 2 * self._wire_scalar_bytes()

    def exchange_rounds(self) -> int:
        """Sequential collective rounds one repartition takes under the plan's
        discipline: 1 for the padded all_to_all and the one-shot UNBUFFERED
        exchange (C chunk collectives under the OVERLAPPED discipline — each
        chunk is its own wire round, pipelined against the neighbor chunks'
        FFTs), P-1 for the COMPACT ppermute chain (and for UNBUFFERED's
        chain-transport fallback on backends without ragged-all-to-all)."""
        if self._ragged is not None:
            return self._ragged.rounds()
        return int(getattr(self, "_overlap", 1))

    def exchange_transport(self) -> str:
        """Short name of the collective form that actually carries the
        exchange — the discipline says what rides the wire, this says how
        (plan-card vocabulary, obs.plancard): ``all_to_all`` (padded),
        ``ragged_all_to_all`` (one-shot exact rows), ``one-shot chain``
        (UNBUFFERED's ppermute fallback off-TPU), ``ppermute chain``
        (COMPACT)."""
        from .ragged import OneShotExchange

        if self._ragged is None:
            if getattr(self, "_overlap", 1) > 1:
                return "chunked all_to_all"
            return "all_to_all"
        if isinstance(self._ragged, OneShotExchange):
            if self._ragged.transport == "ragged":
                return "ragged_all_to_all"
            return "one-shot chain"
        return "ppermute chain"

    def _num_staged_shards(self) -> int:
        """Shards THIS process stages host<->device (all of them on a
        single-process mesh) — the staged_bytes_total accounting unit, so
        per-process snapshots aggregate across processes without double
        counting."""
        if mesh_process_span(self.mesh) == 1:
            return int(self.params.num_shards)
        return len(self._local_shard_ids())

    def pad_values(self, values_per_shard):
        """List of per-shard complex arrays -> sharded (P, V_max) (re, im) pair."""
        from .. import obs

        p = self.params
        obs.counter("staged_bytes_total", direction="host_to_device").inc(
            2 * self._num_staged_shards() * self._V * self.real_dtype.itemsize
        )
        if mesh_process_span(self.mesh) == 1:
            re = np.zeros((p.num_shards, self._V), dtype=self.real_dtype)
            im = np.zeros((p.num_shards, self._V), dtype=self.real_dtype)
            for r, v in enumerate(values_per_shard):
                v = np.asarray(v).reshape(-1)
                self._check_count(r, v)
                re[r, : v.size] = v.real
                im[r, : v.size] = v.imag
            return (
                jax.device_put(re, self.value_sharding),
                jax.device_put(im, self.value_sharding),
            )
        # multi-host: assemble the global array from process-local shard blocks
        if len(values_per_shard) != p.num_shards:
            from ..errors import InvalidParameterError

            raise InvalidParameterError(
                f"values_per_shard must have one entry per shard "
                f"({p.num_shards}; None for shards owned by other processes), "
                f"got {len(values_per_shard)}"
            )
        flat = list(self.mesh.devices.flat)
        blocks_re, blocks_im = [], []
        for r in self._local_shard_ids():
            v = np.asarray(values_per_shard[r]).reshape(-1)
            self._check_count(r, v)
            re = np.zeros((1, self._V), dtype=self.real_dtype)
            im = np.zeros((1, self._V), dtype=self.real_dtype)
            re[0, : v.size] = v.real
            im[0, : v.size] = v.imag
            blocks_re.append(jax.device_put(re, flat[r]))
            blocks_im.append(jax.device_put(im, flat[r]))
        shape = (p.num_shards, self._V)
        return (
            jax.make_array_from_single_device_arrays(
                shape, self.value_sharding, blocks_re
            ),
            jax.make_array_from_single_device_arrays(
                shape, self.value_sharding, blocks_im
            ),
        )

    def unpad_values(self, pair):
        """Sharded (P, V_max) pair -> list of per-shard complex numpy arrays
        (``None`` for shards owned by other processes)."""
        from .. import obs

        obs.counter("staged_bytes_total", direction="device_to_host").inc(
            2 * self._num_staged_shards() * self._V * self.real_dtype.itemsize
        )
        counts = [int(x) for x in self.params.num_values_per_shard]
        if mesh_process_span(self.mesh) == 1:
            re, im = np.asarray(pair[0]), np.asarray(pair[1])
            return [re[r, :n] + 1j * im[r, :n] for r, n in enumerate(counts)]
        out = [None] * self.params.num_shards
        ims = {s.index[0].start: np.asarray(s.data) for s in pair[1].addressable_shards}
        for s in pair[0].addressable_shards:
            r = s.index[0].start
            n = counts[r]
            out[r] = np.asarray(s.data)[0, :n] + 1j * ims[r][0, :n]
        return out

    def pad_space(self, space):
        """Global (Z, Y, X) array -> sharded (P, L, Y, X) real (re, im or re-only)
        arrays. On a multi-process mesh each process stages only its own shards
        (the global input array must still be supplied on every process)."""
        from .. import obs

        p = self.params
        obs.counter("staged_bytes_total", direction="host_to_device").inc(
            (1 if self.is_r2c else 2)
            * self._num_staged_shards() * self._L * p.dim_y * p.dim_x
            * self.real_dtype.itemsize
        )
        arrs = []
        parts = [np.asarray(space).real, None if self.is_r2c else np.asarray(space).imag]
        multihost = mesh_process_span(self.mesh) > 1
        flat = list(self.mesh.devices.flat)
        for part in parts:
            if part is None:
                arrs.append(None)
                continue
            if not multihost:
                out = np.zeros(
                    (p.num_shards, self._L, p.dim_y, p.dim_x), dtype=self.real_dtype
                )
                for r in range(p.num_shards):
                    l, o = int(p.local_z_lengths[r]), int(p.z_offsets[r])
                    out[r, :l] = part[o : o + l]
                arrs.append(jax.device_put(out, self.space_sharding))
                continue
            blocks = []
            for r in self._local_shard_ids():
                l, o = int(p.local_z_lengths[r]), int(p.z_offsets[r])
                blk = np.zeros((1, self._L, p.dim_y, p.dim_x), dtype=self.real_dtype)
                blk[0, :l] = part[o : o + l]
                blocks.append(jax.device_put(blk, flat[r]))
            arrs.append(
                jax.make_array_from_single_device_arrays(
                    (p.num_shards, self._L, p.dim_y, p.dim_x),
                    self.space_sharding,
                    blocks,
                )
            )
        return arrs[0], arrs[1]

    def unpad_space(self, out):
        """Sharded (P, L, Y, X) result -> global (Z, Y, X) numpy array.

        On a multi-process mesh, returns a per-shard list instead (local slab
        arrays of shape (local_z_length, Y, X); ``None`` for remote shards) —
        the reference's per-rank space-domain contract."""
        from .. import obs

        p = self.params
        obs.counter("staged_bytes_total", direction="device_to_host").inc(
            (1 if self.is_r2c else 2)
            * self._num_staged_shards() * self._L * p.dim_y * p.dim_x
            * self.real_dtype.itemsize
        )
        if mesh_process_span(self.mesh) == 1:
            if self.is_r2c:
                full = np.asarray(out)
                dst = np.zeros((p.dim_z, p.dim_y, p.dim_x), dtype=self.real_dtype)
            else:
                re, im = np.asarray(out[0]), np.asarray(out[1])
                full = re + 1j * im
                dst = np.zeros((p.dim_z, p.dim_y, p.dim_x), dtype=self.complex_dtype)
            for r in range(p.num_shards):
                l, o = int(p.local_z_lengths[r]), int(p.z_offsets[r])
                dst[o : o + l] = full[r, :l]
            return dst
        slabs = [None] * p.num_shards
        if self.is_r2c:
            for s in out.addressable_shards:
                r = s.index[0].start
                l = int(p.local_z_lengths[r])
                slabs[r] = np.asarray(s.data)[0, :l]
            return slabs
        ims = {s.index[0].start: np.asarray(s.data) for s in out[1].addressable_shards}
        for s in out[0].addressable_shards:
            r = s.index[0].start
            l = int(p.local_z_lengths[r])
            slabs[r] = np.asarray(s.data)[0, :l] + 1j * ims[r][0, :l]
        return slabs


class DistributedExecution(PaddingHelpers):
    """Compiled distributed pipelines for one transform plan over one mesh."""

    def __init__(
        self,
        params: DistributedParameters,
        real_dtype,
        mesh,
        exchange_type: ExchangeType = ExchangeType.DEFAULT,
        overlap: int = 1,
        fuse=None,
    ):
        self.params = params
        self.mesh = mesh
        self.real_dtype = np.dtype(real_dtype)
        self.complex_dtype = _complex_dtype(real_dtype)
        self.exchange_type = ExchangeType(exchange_type)
        p = params
        if fft_axis_size(mesh) != p.num_shards:
            from ..errors import MPIParameterMismatchError

            raise MPIParameterMismatchError(
                f"plan has {p.num_shards} shards but the mesh {FFT_AXIS!r} axis "
                f"has {fft_axis_size(mesh)} devices"
            )
        _check_multihost_mesh(mesh)
        exchange_build_checkpoint()

        # ---- static exchange geometry (host-side, baked into the program) ----
        self._S = p.max_num_sticks
        self._L = max(1, p.max_local_z_length)
        self._V = p.max_num_values
        xf = p.dim_x_freq
        # Flattened (y, x) slot per stick across all shards; padding slots get the
        # out-of-bounds sentinel (drop on scatter, zero-fill on gather). Built from
        # the padded stick tables whose padding already carries x == dim_x_freq.
        sx = p.stick_x_all.reshape(-1).astype(np.int64)
        sy = p.stick_y_all.reshape(-1).astype(np.int64)
        yx = sy * xf + sx
        yx[sx >= xf] = p.dim_y * xf  # sentinel: one past the slab plane
        self._yx_flat = yx.astype(np.int32)
        self._pack_z = p.pack_z_map()
        self._unpack_z = p.unpack_z_map()

        # Exact-counts exchanges: COMPACT_* runs the ppermute chain (true
        # Alltoallv blocks, P-1 rounds); UNBUFFERED runs the one-shot
        # ragged-all-to-all discipline (true Alltoallw: exact counts in ONE
        # collective round where the backend supports the HLO; same-layout
        # chain transport elsewhere). See parallel/ragged.py.
        self._ragged = None
        if self.exchange_type in _RAGGED_EXCHANGES and p.num_shards > 1:
            cls = (
                OneShotExchange
                if self.exchange_type == ExchangeType.UNBUFFERED
                else RaggedExchange
            )
            kw = {"mesh": mesh} if cls is OneShotExchange else {}
            self._ragged = cls(
                p.num_sticks_per_shard, p.local_z_lengths, p.z_offsets,
                self._S, self._L, p.dim_z, p.dim_y * xf, self._yx_flat, **kw,
            )
        self._ragged_wire = self._ragged_wire_format()

        # OVERLAPPED discipline: the padded single-collective exchange is
        # split into C chunk collectives along the stick axis, each chunk's
        # wire time pipelined against its neighbor chunks' z-FFTs. Feasible
        # only for the padded disciplines (the ragged chains already round-
        # pipeline) and clamped to the stick extent; P=1 plans have no wire.
        if self._ragged is not None or p.num_shards <= 1:
            self._overlap = 1
        else:
            self._overlap = max(1, min(int(overlap), self._S))
        self._chunks = chunk_ranges(self._S, self._overlap)

        # ---- sharded per-shard constants ----
        vi_sharding = NamedSharding(mesh, P(FFT_AXIS, None))
        self._value_indices = jax.device_put(
            np.asarray(p.value_indices, dtype=np.int32), vi_sharding
        )
        self.value_sharding = vi_sharding
        self.space_sharding = NamedSharding(mesh, P(FFT_AXIS, None, None, None))

        # ---- compiled pipelines ----
        specs_v = P(FFT_AXIS, None)  # global (P, V_max), per-shard blocks (1, V_max)
        specs_s = P(FFT_AXIS, None, None, None)  # global (P, L, Y, X) space slabs
        from .mesh import shard_mapper

        sm = shard_mapper(mesh)

        self._backward_sm = sm(
            self._backward_impl,
            in_specs=(specs_v, specs_v, specs_v),
            out_specs=(specs_s, specs_s) if not self.is_r2c else specs_s,
        )
        self._backward = jax.jit(self._backward_sm)
        self._forward_sm = {}
        self._forward = {}
        for scaling, scale in (
            (ScalingType.NONE, None),
            (ScalingType.FULL, 1.0 / p.total_size),
        ):
            self._forward_sm[scaling] = sm(
                functools.partial(self._forward_impl, scale=scale),
                in_specs=(specs_s, specs_s, specs_v)
                if not self.is_r2c
                else (specs_s, specs_v),
                out_specs=(specs_v, specs_v),
            )
            self._forward[scaling] = jax.jit(self._forward_sm[scaling])

        # Stage-graph IR (spfft_tpu.ir): the per-shard pipeline lowered to a
        # validated stage graph (overlap chunking applied as a graph
        # rewrite), fused into one jitted shard_map program per direction —
        # or run node-per-dispatch under SPFFT_TPU_FUSE=0. The monolithic
        # jits above remain the ir_lower_failed rung and the trace path.
        from ..ir.compile import init_engine_ir

        self._ir = init_engine_ir(self, fuse)

    @property
    def is_r2c(self) -> bool:
        return self.params.transform_type == TransformType.R2C

    # ---- introspection (spfft_tpu.obs plan cards) -----------------------------

    def describe(self) -> dict:
        """Engine fragment of the plan card (obs.plancard)."""
        return {
            "pipeline": "jnp.fft + scatter/gather (shard_map)",
            "overlap_chunks": int(self._overlap),
            "padded_geometry": {
                "s_max": int(self._S),
                "l_max": int(self._L),
                "v_max": int(self._V),
            },
        }

    def lowered_backward(self):
        """Lower (without compiling) the backward pipeline — the obs layer's
        hook for compiled-program stats (obs.hlo.compiled_stats)."""
        p = self.params
        v = jax.ShapeDtypeStruct(
            (p.num_shards, self._V), self.real_dtype, sharding=self.value_sharding
        )
        return self._backward.lower(v, v, self._value_indices)

    # ---- wire-format casts (float exchange) -----------------------------------

    def _exchange(self, buffer):
        """One ``all_to_all`` over the mesh axis in the configured wire format."""
        return self._complex_wire_exchange(buffer, FFT_AXIS)

    # ---- pipeline stage bodies -------------------------------------------------
    # One per-shard implementation per stage, shared by the monolithic impls
    # below (bulk AND overlapped paths — the chunk loop calls the same
    # bodies on sub-windows) and the IR node fns lowered from this engine
    # (spfft_tpu.ir.lower).

    def _st_decompress(self, values_re, values_im, value_indices):
        # decompress: scatter local packed values into padded local sticks. No
        # unique_indices hint: padding slots share the same out-of-range sentinel.
        p = self.params
        S, Z = self._S, p.dim_z
        values = jax.lax.complex(
            values_re.astype(self.real_dtype), values_im.astype(self.real_dtype)
        )
        flat = jnp.zeros(S * Z + 1, dtype=self.complex_dtype)
        flat = flat.at[value_indices].set(values, mode="drop")
        return flat[: S * Z].reshape(S, Z)

    def _st_stick_symmetry(self, sticks):
        p = self.params
        row = sticks[p.zero_stick_row]
        filled = symmetry.hermitian_fill_1d(row, axis=0)
        is_owner = jax.lax.axis_index(FFT_AXIS) == p.zero_stick_shard
        return sticks.at[p.zero_stick_row].set(jnp.where(is_owner, filled, row))

    def _st_z_backward(self, sticks):
        return jnp.fft.ifft(sticks, axis=1)

    def _st_pack(self, z_sticks):
        """(W, Z) z-transformed stick rows -> (P, L, W) exchange blocks,
        padding planes zero-filled — any stick window W <= S (the bulk path
        is the W == S case; the OVERLAPPED chunks pass their windows)."""
        p = self.params
        buf = jnp.take(
            z_sticks.T, jnp.asarray(self._pack_z), axis=0, mode="fill",
            fill_value=0,
        )
        return buf.reshape(p.num_shards, self._L, z_sticks.shape[0])

    def _st_exchange(self, buf):
        return self._exchange(buf)

    def _st_unpack(self, *recvs):
        """(P, L, W) received block(s) -> (L, Y, Xf) slab; multiple chunk
        receives reassemble the padded (P, L, S) layout first."""
        recv = recvs[0] if len(recvs) == 1 else jnp.concatenate(recvs, axis=2)
        return self._unpack_slab(recv)

    def _st_ragged_exchange_backward(self, z_sticks):
        return self._ragged.backward(
            (z_sticks,), wire=self._ragged_wire, real_dtype=self.real_dtype
        )[0]

    def _st_ragged_unpack(self, planes):
        p = self.params
        return planes.T.reshape(self._L, p.dim_y, p.dim_x_freq)

    def _st_plane_symmetry(self, slab):
        return symmetry.apply_plane_symmetry(slab)

    def _st_y_backward(self, slab):
        return jnp.fft.ifft(slab, axis=1)

    def _st_x_backward(self, slab):
        p = self.params
        total = np.asarray(p.total_size, dtype=self.real_dtype)
        if self.is_r2c:
            return (
                jnp.fft.irfft(slab, n=p.dim_x, axis=2).astype(self.real_dtype)
                * total
            )
        out = jnp.fft.ifft(slab, axis=2) * total
        return out.real, out.imag

    def _st_x_forward(self, space_re, space_im=None):
        p = self.params
        if self.is_r2c:
            slab = space_re.astype(self.real_dtype)
            return jnp.fft.rfft(slab, n=p.dim_x, axis=2).astype(self.complex_dtype)
        slab = jax.lax.complex(
            space_re.astype(self.real_dtype), space_im.astype(self.real_dtype)
        )
        return jnp.fft.fft(slab, axis=2)

    def _st_y_forward(self, grid):
        return jnp.fft.fft(grid, axis=1)

    def _st_pack_fwd(self, grid, c0=0, c1=None):
        """Forward pack: gather every shard's stick columns (window
        ``[c0, c1)`` of the padded stick order) from my planes ->
        (P, L, W) blocks — bulk path and OVERLAPPED chunks share it."""
        p = self.params
        S, L = self._S, self._L
        c1 = S if c1 is None else c1
        flat_grid = grid.reshape(L, p.dim_y * p.dim_x_freq)
        cols = self._yx_flat.reshape(p.num_shards, S)[:, c0:c1].reshape(-1)
        planes = jnp.take(
            flat_grid, jnp.asarray(cols), axis=1, mode="fill", fill_value=0
        )
        return planes.reshape(L, p.num_shards, c1 - c0).transpose(1, 0, 2)

    def _st_unpack_fwd(self, rc):
        """(P, L, W) received blocks -> (W, Z) stick z-rows via the
        global-z map — any window width."""
        p = self.params
        W = rc.shape[2]
        sz = rc.transpose(2, 0, 1).reshape(W, p.num_shards * self._L)
        return jnp.take(sz, jnp.asarray(self._unpack_z), axis=1)

    def _st_z_forward(self, sz):
        return jnp.fft.fft(sz, axis=1)

    def _st_concat_sticks(self, *parts):
        return parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=0)

    def _st_ragged_exchange_forward(self, grid):
        return self._ragged.forward(
            (grid.reshape(self._L, -1).T,),  # -> (Y*Xf, L) slot-major rows
            wire=self._ragged_wire, real_dtype=self.real_dtype,
        )[0]

    def _st_compress(self, sticks, value_indices, scale):
        values = jnp.take(
            sticks.reshape(-1), value_indices, mode="fill", fill_value=0
        )
        if scale is not None:
            values = values * np.asarray(scale, dtype=self.real_dtype)
        return (
            values.real.astype(self.real_dtype),
            values.imag.astype(self.real_dtype),
        )

    # ---- pipelines (traced once; run per-shard under shard_map) ---------------

    def _unpack_slab(self, recv):
        """(P, L, S) received blocks -> (L, Y, Xf) slab: scatter every stick
        into the local planes through the flat (y, x) slot table. Shared by
        the bulk-synchronous padded path and the OVERLAPPED chunk path (whose
        concatenated chunk receives reassemble the same (P, L, S) layout)."""
        p = self.params
        planes = recv.transpose(1, 0, 2).reshape(self._L, p.num_shards * self._S)
        slab = jnp.zeros(
            (self._L, p.dim_y * p.dim_x_freq + 1), dtype=self.complex_dtype
        )
        slab = slab.at[:, jnp.asarray(self._yx_flat)].set(planes, mode="drop")
        return slab[:, : p.dim_y * p.dim_x_freq].reshape(
            self._L, p.dim_y, p.dim_x_freq
        )

    def _backward_impl(self, values_re, values_im, value_indices):
        p = self.params
        # stage scopes: canonical obs.STAGES labels (profiler attribution)
        with jax.named_scope("compression"):
            sticks = self._st_decompress(
                values_re[0], values_im[0], value_indices[0]
            )

        if self.is_r2c and p.zero_stick_shard >= 0:
            with jax.named_scope("stick symmetry"):
                sticks = self._st_stick_symmetry(sticks)

        if self._overlap > 1:
            # OVERLAPPED discipline: each stick chunk runs its own
            # z-FFT -> pack -> all_to_all chain with no cross-chunk data
            # dependence, so chunk k's collective can fly while chunk k+1's
            # z-FFTs compute (the pipelined all-to-all of
            # arxiv.org/pdf/1804.09536; XLA's latency-hiding scheduler does
            # the interleaving — the dataflow here only has to permit it)
            recvs = []
            for c0, c1 in self._chunks:
                with jax.named_scope("z transform"):
                    zc = self._st_z_backward(sticks[c0:c1])
                with jax.named_scope("pack"):
                    buf = self._st_pack(zc)
                with jax.named_scope("exchange overlapped"):
                    recvs.append(self._exchange(buf))
            with jax.named_scope("unpack"):
                slab = self._st_unpack(*recvs)
        else:
            with jax.named_scope("z transform"):
                sticks = self._st_z_backward(sticks)

            if self._ragged is not None:
                # exact-counts exchange: ppermute chain, blocks sized
                # sticks_i x planes_j (the reference's Alltoallv discipline,
                # see parallel/ragged.py)
                with jax.named_scope("exchange"):
                    planes = self._st_ragged_exchange_backward(sticks)
                with jax.named_scope("unpack"):
                    slab = self._st_ragged_unpack(planes)
            else:
                # pack: (Z, S) -> (P, L, S) blocks, padding planes zero-filled
                with jax.named_scope("pack"):
                    buffer = self._st_pack(sticks)

                # exchange: shard r receives every shard's sticks on r's planes
                #   (the MPI_Alltoall of the reference's BUFFERED transpose,
                #    reference: src/transpose/transpose_mpi_buffered_host.cpp:162-173)
                with jax.named_scope("exchange"):
                    recv = self._exchange(buffer)

                # unpack: scatter all sticks into the local slab planes
                with jax.named_scope("unpack"):
                    slab = self._st_unpack(recv)

        if self.is_r2c:
            with jax.named_scope("plane symmetry"):
                slab = self._st_plane_symmetry(slab)
        with jax.named_scope("y transform"):
            slab = self._st_y_backward(slab)
        with jax.named_scope("x transform"):
            out = self._st_x_backward(slab)
            if self.is_r2c:
                return out[None]
            return out[0][None], out[1][None]

    def _forward_impl(self, space_re, *rest, scale):
        with jax.named_scope("x transform"):
            if self.is_r2c:
                (value_indices,) = rest
                grid = self._st_x_forward(space_re[0])
            else:
                space_im, value_indices = rest
                grid = self._st_x_forward(space_re[0], space_im[0])
        with jax.named_scope("y transform"):
            grid = self._st_y_forward(grid)

        if self._overlap > 1:
            # OVERLAPPED discipline (forward direction): chunk k's received
            # sticks run their z-FFTs while chunk k+1's collective is in
            # flight — the mirror of the backward chunk pipeline
            parts = []
            for c0, c1 in self._chunks:
                with jax.named_scope("pack"):
                    buf = self._st_pack_fwd(grid, c0, c1)
                with jax.named_scope("exchange overlapped"):
                    rc = self._exchange(buf)
                with jax.named_scope("unpack"):
                    sz = self._st_unpack_fwd(rc)
                with jax.named_scope("z transform"):
                    parts.append(self._st_z_forward(sz))
            sticks = self._st_concat_sticks(*parts)
        else:
            if self._ragged is not None:
                with jax.named_scope("exchange"):
                    sticks = self._st_ragged_exchange_forward(grid)
            else:
                # pack: gather every shard's stick columns from my planes
                # -> (P, L, S)
                with jax.named_scope("pack"):
                    buffer = self._st_pack_fwd(grid)

                # exchange: shard r receives its own sticks' values on every
                # shard's planes
                with jax.named_scope("exchange"):
                    recv = self._exchange(buffer)

                # unpack: (P, L, S) -> (S, Z) via the global-z map
                with jax.named_scope("unpack"):
                    sticks = self._st_unpack_fwd(recv)

            with jax.named_scope("z transform"):
                sticks = self._st_z_forward(sticks)

        # compress: gather local packed values (+ optional scaling)
        with jax.named_scope("compression"):
            vre, vim = self._st_compress(sticks, value_indices[0], scale)
            return vre[None], vim[None]

    # ---- device-side entry points ---------------------------------------------

    def backward_pair(self, values_re, values_im):
        """(P, V_max) freq pairs -> space slabs (P, L, Y, X) (pair for C2C).
        Routed through the IR runtime (fused single shard_map program by
        default, the staged per-node reference under ``SPFFT_TPU_FUSE=0``)."""
        return self._ir.run_backward(values_re, values_im, self._value_indices)

    def forward_pair(self, space_re, space_im, scaling: ScalingType = ScalingType.NONE):
        """(P, L, Y, X) space slabs -> (P, V_max) freq pairs."""
        s = ScalingType(scaling)
        if self.is_r2c:
            return self._ir.run_forward(s, space_re, self._value_indices)
        return self._ir.run_forward(s, space_re, space_im, self._value_indices)

    # Un-jitted traceables (see LocalExecution.trace_backward for rationale).

    def trace_backward(self, values_re, values_im, phase=()):
        del phase  # mesh engines keep per-shard reps internal (no operands)
        return self._backward_sm(values_re, values_im, self._value_indices)

    def trace_forward(
        self, space_re, space_im, scaling: ScalingType = ScalingType.NONE, phase=()
    ):
        del phase
        return self._dispatch_forward(self._forward_sm, space_re, space_im, scaling)
