"""Mesh helpers.

The distributed transform runs over a 1-D mesh axis named ``"fft"`` — the analogue of
the reference's MPI communicator (reference: src/mpi_util/mpi_communicator_handle.hpp).
On a TPU pod slice the axis should ride ICI; on multi-host CPU it rides DCN. Callers
with a larger model mesh can carve an ``"fft"`` sub-axis out of it and pass that.
"""
from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

FFT_AXIS = "fft"
FFT_AXIS2 = "fft2"


def is_pencil2_mesh(mesh) -> bool:
    """True for 2-D pencil meshes (both ``"fft"`` and ``"fft2"`` axes present)."""
    return FFT_AXIS in mesh.axis_names and FFT_AXIS2 in mesh.axis_names


def fft_mesh_size(mesh) -> int:
    """Total FFT shards: the ``"fft"`` axis size, times ``"fft2"`` if present."""
    n = fft_axis_size(mesh)
    if FFT_AXIS2 in mesh.axis_names:
        n *= int(mesh.shape[FFT_AXIS2])
    return n


def fft_axis_size(mesh) -> int:
    """Number of FFT shards in a mesh: the size of the ``"fft"`` axis.

    Accepts both a dedicated 1-D FFT mesh and a larger multi-axis model mesh
    that carries an ``"fft"`` sub-axis (transforms shard over it and are
    replicated over the remaining axes). Raises if the axis is absent.
    """
    if FFT_AXIS not in mesh.axis_names:
        from ..errors import InvalidParameterError

        raise InvalidParameterError(
            f'mesh has no "{FFT_AXIS}" axis (axes: {mesh.axis_names}); '
            f"build one with make_fft_mesh or name an axis {FFT_AXIS!r}"
        )
    return int(mesh.shape[FFT_AXIS])


def shard_mapper(mesh):
    """``jax.shard_map`` bound to ``mesh`` with replication checking off,
    across jax versions: the top-level ``jax.shard_map`` (``check_vma=``) where
    it exists, the ``jax.experimental.shard_map`` form (``check_rep=``) on
    older runtimes. The single shard_map entry point for every engine, so a
    jax API move is one edit here."""
    import functools

    if hasattr(jax, "shard_map"):
        return functools.partial(jax.shard_map, mesh=mesh, check_vma=False)
    from jax.experimental.shard_map import shard_map as _shard_map

    return functools.partial(_shard_map, mesh=mesh, check_rep=False)


def configure_virtual_devices(n_devices: int, *, warn: bool = False) -> None:
    """Request an ``n_devices``-wide virtual CPU backend, without touching devices.

    Safe at import time (no backend initialization). Must run before JAX
    initializes its backends to take effect; if too late, ``warn=True`` prints
    a stderr diagnostic and the caller's later device-count check decides
    whether that matters.
    """
    try:
        jax.config.update("jax_num_cpu_devices", max(int(n_devices), 1))
    except RuntimeError as e:  # backend already initialized elsewhere
        if warn:
            import sys

            print(f"spfft_tpu: jax_num_cpu_devices ignored ({e})", file=sys.stderr)
    except AttributeError:
        # jax < 0.4.38: same knob spelled as an XLA flag, honored at CPU
        # client creation (both the global backend and the private client of
        # _platform.cpu_devices read it)
        import os

        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count="
                f"{max(int(n_devices), 1)}"
            ).strip()


def ensure_virtual_devices(n_devices: int, *, warn: bool = False, platform=None):
    """Return ``n_devices`` JAX devices, standing up a virtual CPU backend if needed.

    The single bootstrap for every single-controller caller that must validate
    n-way sharding on a host with fewer than n chips (the analogue of the
    reference exercising MPI paths under ``mpirun -n 2`` on one CI VM,
    reference: tests/run_mpi_tests.cpp:14-21): pre-configures the CPU backend
    with ``n_devices`` virtual devices (honored until first backend use) and
    falls back to CPU devices when the default platform has too few devices.
    When the default platform is already initialized and exposes enough (a
    real pod slice), those are returned so collectives ride the actual
    interconnect.

    ``platform="cpu"`` skips the default platform entirely. With
    ``platform=None`` the default platform is consulted ONLY when doing so
    cannot block: backend init walks every platform in ``jax_platforms``, and
    on a wedged tunneled accelerator that init hangs indefinitely (round-2
    MULTICHIP rc=124). When backends are uninitialized and a non-CPU platform
    is configured, the virtual CPU path — which the ``n_devices`` config above
    can always satisfy — is used instead of risking the hang.

    ``warn=True`` prints a stderr note when the config arrives after backend
    initialization (the embedded-interpreter caller wants the diagnostic;
    raising would break an otherwise-valid single-device run).
    """
    import sys

    from .. import knobs
    from .._platform import cpu_devices, global_init_is_safe

    n_devices = max(int(n_devices), 1)
    configure_virtual_devices(n_devices, warn=warn)
    if platform == "cpu":
        devices = cpu_devices()
    elif global_init_is_safe() or knobs.get_str(
        "SPFFT_TPU_ENSURE_PLATFORM"
    ) == "default":
        devices = jax.devices(platform)
    else:
        # Uninitialized backends + a non-CPU platform configured: initializing
        # the default platform here can block indefinitely on a wedged
        # tunneled accelerator, so resolve the (always-satisfiable) virtual
        # CPU path and say so. Callers on a healthy pod slice who want the
        # real chips: initialize the backend first (any jax.devices() call),
        # pass devices= explicitly, or set SPFFT_TPU_ENSURE_PLATFORM=default.
        print(
            "spfft_tpu: ensure_virtual_devices resolving virtual CPU devices "
            "without initializing the configured default platform "
            f"({jax.config.jax_platforms or 'autodetect'}); initialize it "
            "first or set SPFFT_TPU_ENSURE_PLATFORM=default for real devices",
            file=sys.stderr,
        )
        devices = cpu_devices()
    if len(devices) < n_devices:
        try:
            devices = cpu_devices()
        except RuntimeError:
            devices = []
    if len(devices) < n_devices:
        from ..errors import InvalidParameterError

        # typed-error discipline (analysis SA010): a process configured with
        # too few devices is a configuration failure, surfaced as taxonomy
        raise InvalidParameterError(
            f"need {n_devices} devices but only {len(devices)} are visible; "
            f"start the process with JAX_NUM_CPU_DEVICES={n_devices} (or "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={n_devices}) so "
            "the config is applied before JAX backend initialization."
        )
    return list(devices[:n_devices])


def make_fft_mesh(num_devices: int | None = None, devices=None) -> Mesh:
    """Build a 1-D mesh over ``num_devices`` devices (default: all local devices).

    After :func:`init_distributed`, ``jax.devices()`` spans every process, so the
    same call builds a multi-host mesh (collectives ride ICI within a slice and
    DCN across hosts).
    """
    if devices is None:
        devices = jax.devices()
        if num_devices is not None:
            devices = devices[:num_devices]
    return Mesh(np.asarray(devices), (FFT_AXIS,))


def make_fft_mesh2(p1: int, p2: int, devices=None) -> Mesh:
    """Build a 2-D ``(p1, p2)`` pencil mesh (axes ``"fft"`` x ``"fft2"``).

    Transforms over it use the 2-D pencil decomposition
    (:mod:`spfft_tpu.parallel.pencil2`): space is split into z-slabs over
    ``"fft2"`` AND y-slabs over ``"fft"``, lifting the 1-D slab engine's
    ``P <= dim_z`` useful-parallelism cap to ``p1 * p2 <= dim_z * dim_y``.
    """
    from ..errors import InvalidParameterError

    if p1 < 1 or p2 < 1:
        raise InvalidParameterError("mesh factors must be positive")
    if devices is None:
        devices = jax.devices()[: p1 * p2]
    devices = np.asarray(devices)
    if devices.size < p1 * p2:
        raise InvalidParameterError(
            f"make_fft_mesh2({p1}, {p2}) needs {p1 * p2} devices, "
            f"have {devices.size}"
        )
    return Mesh(devices.reshape(p1, p2), (FFT_AXIS, FFT_AXIS2))


def validate_distributed_args(
    coordinator_address, num_processes, process_id
) -> None:
    """Typed up-front validation of the ``init_distributed`` arguments.

    ``jax.distributed.initialize`` fails opaquely *inside the child process*
    on malformed values (a bad coordinator string surfaces as a gRPC
    connect timeout minutes later; a process_id out of range wedges the
    whole barrier), so the bootstrap validates here, before anything is
    spawned or joined: a malformed value raises
    :class:`~spfft_tpu.errors.InvalidParameterError` naming it. All three
    may be None together (TPU pods infer them from the environment); given
    explicitly, the coordinator must be ``host:port`` with a port in
    [1, 65535], ``num_processes >= 1`` and ``0 <= process_id <
    num_processes``."""
    from ..errors import InvalidParameterError

    if coordinator_address is not None:
        addr = str(coordinator_address)
        host, sep, port_s = addr.rpartition(":")
        if not sep or not host:
            raise InvalidParameterError(
                f"malformed coordinator_address {addr!r}: expected "
                "'host:port' (e.g. 'localhost:8476')"
            )
        try:
            port = int(port_s)
        except ValueError:
            raise InvalidParameterError(
                f"malformed coordinator_address {addr!r}: port {port_s!r} "
                "is not an integer"
            ) from None
        if not 1 <= port <= 65535:
            raise InvalidParameterError(
                f"coordinator_address {addr!r}: port {port} out of range "
                "[1, 65535]"
            )
    if num_processes is not None:
        try:
            n = int(num_processes)
        except (TypeError, ValueError):
            raise InvalidParameterError(
                f"invalid num_processes {num_processes!r}: expected an "
                "integer >= 1"
            ) from None
        if n < 1:
            raise InvalidParameterError(
                f"invalid num_processes {num_processes}: expected >= 1"
            )
    if process_id is not None:
        try:
            pid = int(process_id)
        except (TypeError, ValueError):
            raise InvalidParameterError(
                f"invalid process_id {process_id!r}: expected an integer"
            ) from None
        if pid < 0:
            raise InvalidParameterError(
                f"invalid process_id {pid}: expected >= 0"
            )
        if num_processes is not None and pid >= int(num_processes):
            raise InvalidParameterError(
                f"process_id {pid} out of range for num_processes "
                f"{int(num_processes)} (expected 0 <= process_id < "
                "num_processes)"
            )
        if num_processes is None:
            raise InvalidParameterError(
                "process_id given without num_processes: a rank cannot "
                "join a run of unknown size"
            )


def init_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    **kwargs,
) -> None:
    """Join a multi-host run: every host calls this once before building meshes.

    Thin wrapper over ``jax.distributed.initialize`` — the analogue of the
    reference's ``MPI_Init`` requirement for its multi-node transforms
    (reference: src/mpi_util/mpi_init_handle.hpp:43-48). On TPU pods the
    arguments are inferred from the environment; on CPU/GPU clusters pass the
    coordinator address and process coordinates explicitly. Malformed values
    raise typed :class:`~spfft_tpu.errors.InvalidParameterError` here, up
    front (:func:`validate_distributed_args`), instead of letting
    ``jax.distributed.initialize`` fail opaquely inside a child process.
    """
    validate_distributed_args(coordinator_address, num_processes, process_id)
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        **kwargs,
    )
