"""Mesh helpers.

The distributed transform runs over a 1-D mesh axis named ``"fft"`` — the analogue of
the reference's MPI communicator (reference: src/mpi_util/mpi_communicator_handle.hpp).
On a TPU pod slice the axis should ride ICI; on multi-host CPU it rides DCN. Callers
with a larger model mesh can carve an ``"fft"`` sub-axis out of it and pass that.
"""
from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

FFT_AXIS = "fft"


def make_fft_mesh(num_devices: int | None = None, devices=None) -> Mesh:
    """Build a 1-D mesh over ``num_devices`` devices (default: all local devices)."""
    if devices is None:
        devices = jax.devices()
        if num_devices is not None:
            devices = devices[:num_devices]
    return Mesh(np.asarray(devices), (FFT_AXIS,))
