"""Mesh helpers.

The distributed transform runs over a 1-D mesh axis named ``"fft"`` — the analogue of
the reference's MPI communicator (reference: src/mpi_util/mpi_communicator_handle.hpp).
On a TPU pod slice the axis should ride ICI; on multi-host CPU it rides DCN. Callers
with a larger model mesh can carve an ``"fft"`` sub-axis out of it and pass that.
"""
from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

FFT_AXIS = "fft"


def fft_axis_size(mesh) -> int:
    """Number of FFT shards in a mesh: the size of the ``"fft"`` axis.

    Accepts both a dedicated 1-D FFT mesh and a larger multi-axis model mesh
    that carries an ``"fft"`` sub-axis (transforms shard over it and are
    replicated over the remaining axes). Raises if the axis is absent.
    """
    if FFT_AXIS not in mesh.axis_names:
        from ..errors import InvalidParameterError

        raise InvalidParameterError(
            f'mesh has no "{FFT_AXIS}" axis (axes: {mesh.axis_names}); '
            f"build one with make_fft_mesh or name an axis {FFT_AXIS!r}"
        )
    return int(mesh.shape[FFT_AXIS])


def make_fft_mesh(num_devices: int | None = None, devices=None) -> Mesh:
    """Build a 1-D mesh over ``num_devices`` devices (default: all local devices).

    After :func:`init_distributed`, ``jax.devices()`` spans every process, so the
    same call builds a multi-host mesh (collectives ride ICI within a slice and
    DCN across hosts).
    """
    if devices is None:
        devices = jax.devices()
        if num_devices is not None:
            devices = devices[:num_devices]
    return Mesh(np.asarray(devices), (FFT_AXIS,))


def init_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    **kwargs,
) -> None:
    """Join a multi-host run: every host calls this once before building meshes.

    Thin wrapper over ``jax.distributed.initialize`` — the analogue of the
    reference's ``MPI_Init`` requirement for its multi-node transforms
    (reference: src/mpi_util/mpi_init_handle.hpp:43-48). On TPU pods the
    arguments are inferred from the environment; on CPU/GPU clusters pass the
    coordinator address and process coordinates explicitly.
    """
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        **kwargs,
    )
