"""MXU compute path for the 2-D pencil decomposition.

Same geometry, exchanges and boundary contract as
:class:`spfft_tpu.parallel.pencil2.Pencil2Execution` (which this subclasses),
with the compute stages engineered like the 1-D MXU engines for TPU hardware:

* every DFT stage is a batched matmul (ops/fft.py) on (re, im) real pairs —
  3 real matmuls per complex stage (Gauss form, ops/fft.complex_matmul),
  2 for the R2C/C2R x-stage,
* the x-stage folds the pencil slot layout INTO the DFT matrix: the
  ``(group, slot) -> x`` map (with sentinel padding slots as zero rows) rides
  ``ops/fft.x_stage_matrices``, so the post-exchange-B column scatter and the
  pre-exchange-B column gather of the XLA engine disappear into the matmul
  (permutation folding, the designed fusion hook of ops/fft.c2c_matrix),
* sparse decompress/compress run as per-shard lane-copy plans selected by a
  deduped ``lax.switch`` (MxuValuePlans — shared with the 1-D MXU engine),
* both exchanges ride ONE stacked (re, im) all_to_all each, in the plan's
  wire dtype.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..ops import fft as offt
from ..ops import lanecopy, symmetry
from ..types import ExchangeType, ScalingType
from .execution_mxu import MxuValuePlans
from .pencil2 import AX1, AX2, Pencil2Execution


class MxuPencil2Execution(Pencil2Execution, MxuValuePlans):
    """2-D pencil pipelines with matmul DFT stages and lane-copy value plans."""

    def __init__(
        self, params, real_dtype, mesh, exchange_type=ExchangeType.DEFAULT,
        precision="highest", overlap: int = 1, fuse=None,
    ):
        self._precision = offt.resolve_precision(precision)
        super().__init__(params, real_dtype, mesh, exchange_type, overlap=overlap)
        p = params
        rt = self.real_dtype
        self._wz_b, self._wy_b, self._wy_f, self._wz_f = offt.zy_stage_matrices(
            p.dim_z, p.dim_y, p.total_size, rt
        )
        # x-stage over the (P1 * Ax) slot columns; sentinel slots -> zero rows
        slot_to_x = self._xcol.astype(np.int64).copy()
        slot_to_x[slot_to_x >= p.dim_x_freq] = -1
        self._wx_b, self._wx_f = offt.x_stage_matrices(
            p.dim_x, slot_to_x, slot_to_x.size, self.is_r2c, rt
        )
        self._build_value_branches()
        # pencil programs consume the base's size-aware rep directly (the
        # shared MxuValuePlans._phase_tables resolution): tables below the
        # budget are embedded as constants, bigger plans generate in-trace

        # Stage-graph IR (spfft_tpu.ir), deferred past the matrix builds
        # above (see Pencil2Execution.__init__).
        from ..ir.compile import init_engine_ir

        self._ir = init_engine_ir(self, fuse)

    def describe(self) -> dict:
        """Engine fragment of the plan card (obs.plancard): the pencil
        geometry from the base class plus the MXU compute-stage decisions."""
        card = super().describe()
        card["pipeline"] = "matmul DFT stages + lane-copy value plans (pencil)"
        card["matmul_precision"] = str(self._precision).rsplit(".", 1)[-1]
        card["alignment_rotations"] = self._align_rep is not None
        card["value_plan_branches"] = len(self._decompress_branches)
        return card

    def _exchange_pair(self, bre, bim, axes, reverse=False):
        """(re, im) blocks through the configured discipline: the padded
        stacked-pair all_to_all (MxuValuePlans), or the exact-counts block
        chain when the plan uses a COMPACT/UNBUFFERED exchange. ``reverse``
        marks the forward-transform direction (transposed valid rectangles;
        the padded path is symmetric and ignores it)."""
        if self._ragged2 is not None:
            out = self._ragged_block_exchange([bre, bim], axes, reverse)
            return out[0], out[1]
        return super()._exchange_pair(bre, bim, axes)

    # ---- pipeline stage bodies -------------------------------------------------
    # One per-shard implementation per stage, shared by the monolithic impls
    # below and the IR node fns lowered from this engine
    # (spfft_tpu.ir.lower). The pair-array mirror of the base class's stage
    # bodies; the A/B pack/unpack ride the base's shared helpers.

    def _st_decompress(self, values_re, values_im):
        rt = self.real_dtype
        _, _, s_me = self._shard_me()
        return jax.lax.switch(
            jnp.asarray(self._branch_of_shard)[s_me],
            self._decompress_branches,
            values_re.astype(rt),
            values_im.astype(rt),
        )

    def _st_stick_symmetry(self, sre, sim):
        p = self.params
        _, _, s_me = self._shard_me()
        i = p.zero_stick_row
        fre, fim = symmetry.hermitian_fill_1d_pair(sre[i], sim[i], axis=0)
        own = s_me == p.zero_stick_shard
        return (
            sre.at[i].set(jnp.where(own, fre, sre[i])),
            sim.at[i].set(jnp.where(own, fim, sim[i])),
        )

    def _st_z_backward(self, sre, sim):
        _, _, s_me = self._shard_me()
        sre, sim = offt.complex_matmul(
            sre, sim, *self._wz_b, "sz,zk->sk", self._precision
        )
        # undo the alignment rotations; the shared MxuValuePlans resolution
        # reads the embedded/in-trace rep (pencil engines stage no operands)
        cos_t, sin_t = self._phase_tables(s_me, self.real_dtype)
        if cos_t is not None:
            sre, sim = lanecopy.apply_alignment_phase(sre, sim, cos_t, sin_t, -1)
        return sre, sim

    def _st_pack_a_pair(self, sre, sim, zwin):
        # pack A: my sticks split by destination (x-group, z-slab) —
        # whole-row gathers + static window slices (base-class helpers)
        _, _, s_me = self._shard_me()
        return (
            self._pack_a(sre, s_me, zwin=zwin),
            self._pack_a(sim, s_me, zwin=zwin),
        )

    def _st_exchange_a_pair(self, bre, bim, reverse=False):
        return self._exchange_pair(bre, bim, (AX1, AX2), reverse=reverse)

    def _st_unpack_a_pair(self, rre, rim):
        a_me, _, _ = self._shard_me()
        return self._unpack_a(rre, a_me), self._unpack_a(rim, a_me)

    def _st_plane_symmetry(self, gre, gim):
        a_me, _, _ = self._shard_me()
        g0, s0 = self._x0_group, self._x0_slot
        pre, pim = symmetry.hermitian_fill_1d_pair(
            gre[:, s0, :], gim[:, s0, :], axis=0
        )
        return (
            gre.at[:, s0, :].set(jnp.where(a_me == g0, pre, gre[:, s0, :])),
            gim.at[:, s0, :].set(jnp.where(a_me == g0, pim, gim[:, s0, :])),
        )

    def _st_y_backward(self, gre, gim):
        return offt.complex_matmul(
            gre, gim, *self._wy_b, "yal,yk->kal", self._precision
        )

    def _st_pack_b_pair(self, gre, gim):
        return self._pack_b(gre), self._pack_b(gim)

    def _st_exchange_b_pair(self, bre, bim, reverse=False):
        return self._exchange_pair(bre, bim, (AX1,), reverse=reverse)

    def _st_x_backward(self, rbre, rbim):
        # x transform: the slot->x map is folded into the matrix (zero rows
        # on sentinel slots), so assembly is a reshape + matmul
        prec = self._precision
        Ly, P1, Ax = self._Ly, self.P1, self._Ax
        W = rbre.shape[-1]
        hre = rbre.transpose(1, 0, 2, 3).reshape(Ly, P1 * Ax, W)
        him = rbim.transpose(1, 0, 2, 3).reshape(Ly, P1 * Ax, W)
        if self.is_r2c:
            return offt.real_out_matmul(hre, him, *self._wx_b, "ycl,cx->lyx", prec)
        return offt.complex_matmul(hre, him, *self._wx_b, "ycl,cx->lyx", prec)

    def _st_space_out(self, *parts):
        # matmul DFT engines never apply ifft's 1/N, so no un-normalization
        if self.is_r2c:
            return parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=0)
        k = len(parts) // 2
        if k == 1:
            return parts[0], parts[1]
        return (
            jnp.concatenate(parts[:k], axis=0),
            jnp.concatenate(parts[k:], axis=0),
        )

    def _st_x_forward(self, space_re, space_im=None, zwin=None):
        prec, rt = self._precision, self.real_dtype
        c0, c1 = (0, self._Lz) if zwin is None else zwin
        if self.is_r2c:
            return offt.real_in_matmul(
                space_re[c0:c1].astype(rt), *self._wx_f, "lyx,xc->ycl", prec
            )
        return offt.complex_matmul(
            space_re[c0:c1].astype(rt), space_im[c0:c1].astype(rt),
            *self._wx_f, "lyx,xc->ycl", prec,
        )

    def _st_pack_b_rev_pair(self, hre, him):
        # exchange B reverse: send each x-group home (within my z-window);
        # the x matrices land in slot order, so the split is the shared
        # _split_b reshape alone
        W = hre.shape[-1]
        return self._split_b(hre, W), self._split_b(him, W)

    def _st_unpack_b_rev_pair(self, rbre, rbim):
        return self._unpack_b_rev(rbre), self._unpack_b_rev(rbim)

    def _st_y_forward(self, gre, gim):
        return offt.complex_matmul(
            gre, gim, *self._wy_f, "yal,yj->jal", self._precision
        )

    def _st_pack_a_rev_pair(self, gre, gim, z0):
        a_me, b_me, _ = self._shard_me()
        return (
            self._pack_a_rev(gre, a_me, b_me, z0=z0),
            self._pack_a_rev(gim, a_me, b_me, z0=z0),
        )

    def _st_unpack_a_rev_pair(self, *recvs):
        k = len(recvs) // 2
        rre = recvs[0] if k == 1 else jnp.concatenate(recvs[:k], axis=-1)
        rim = recvs[k] if k == 1 else jnp.concatenate(recvs[k:], axis=-1)
        _, _, s_me = self._shard_me()
        return self._unpack_a_rev(rre, s_me), self._unpack_a_rev(rim, s_me)

    def _st_z_forward(self, sre, sim, scaling):
        _, _, s_me = self._shard_me()
        cos_t, sin_t = self._phase_tables(s_me, self.real_dtype)
        if cos_t is not None:
            # enter the rotated layout on the space side
            sre, sim = lanecopy.apply_alignment_phase(sre, sim, cos_t, sin_t, +1)
        return offt.complex_matmul(
            sre, sim, *self._wz_f[ScalingType(scaling)], "sz,zk->sk",
            self._precision,
        )

    def _st_compress(self, sre, sim):
        _, _, s_me = self._shard_me()
        return jax.lax.switch(
            jnp.asarray(self._branch_of_shard)[s_me], self._compress_branches,
            sre, sim,
        )

    # ---- pipelines (traced lazily by the base's jit/shard_map wrappers) -------

    def _backward_impl(self, values_re, values_im, value_indices):
        del value_indices  # lane-copy branches close over their plans
        p = self.params

        with jax.named_scope("compression"):
            sre, sim = self._st_decompress(values_re[0], values_im[0])

        if self.is_r2c and p.zero_stick_shard >= 0:
            with jax.named_scope("stick symmetry"):
                sre, sim = self._st_stick_symmetry(sre, sim)

        with jax.named_scope("z transform"):
            sre, sim = self._st_z_backward(sre, sim)

        # Post-z chunk loop (see Pencil2Execution._backward_impl): one
        # full-window chunk bulk-synchronously, C z-window chunks under the
        # OVERLAPPED discipline so the A/B collectives pipeline against the
        # neighbor chunks' matmuls.
        ov = self._overlap > 1
        parts_re, parts_im = [], []
        for c0, c1 in self._chunks:
            with jax.named_scope("pack A"):
                bre, bim = self._st_pack_a_pair(sre, sim, (c0, c1))

            with jax.named_scope("exchange A overlapped" if ov else "exchange A"):
                rre, rim = self._st_exchange_a_pair(bre, bim)

            # unpack A -> (Y, Ax, W) y-pencil grid (one row gather per part)
            with jax.named_scope("unpack A"):
                gre, gim = self._st_unpack_a_pair(rre, rim)

            if self.is_r2c and self._have_x0:
                with jax.named_scope("plane symmetry"):
                    gre, gim = self._st_plane_symmetry(gre, gim)

            with jax.named_scope("y transform"):
                gre, gim = self._st_y_backward(gre, gim)

            # pack B: each destination's y-rows (within my z-window)
            with jax.named_scope("pack B"):
                bre, bim = self._st_pack_b_pair(gre, gim)

            with jax.named_scope("exchange B overlapped" if ov else "exchange B"):
                rbre, rbim = self._st_exchange_b_pair(bre, bim)

            with jax.named_scope("x transform"):
                out = self._st_x_backward(rbre, rbim)
                if self.is_r2c:
                    parts_re.append(out)
                else:
                    parts_re.append(out[0])
                    parts_im.append(out[1])
        out = self._st_space_out(*parts_re, *parts_im)
        if self.is_r2c:
            return out[None]
        return out[0][None], out[1][None]

    def _forward_impl(self, space_re, *rest, scale):
        scaling = ScalingType.NONE if scale is None else ScalingType.FULL

        if self.is_r2c:
            (_,) = rest  # value_indices unused (lane-copy branches)
            space_im = None
        else:
            space_im, _ = rest

        # Forward mirror of the backward chunk loop (see
        # Pencil2Execution._forward_impl).
        ov = self._overlap > 1
        recvs_re, recvs_im = [], []
        for c0, c1 in self._chunks:
            with jax.named_scope("x transform"):
                hre, him = self._st_x_forward(
                    space_re[0],
                    None if space_im is None else space_im[0],
                    zwin=(c0, c1),
                )

            with jax.named_scope("pack B"):
                bre, bim = self._st_pack_b_rev_pair(hre, him)
            with jax.named_scope("exchange B overlapped" if ov else "exchange B"):
                rbre, rbim = self._st_exchange_b_pair(bre, bim, reverse=True)

            # reassemble the full y extent of my x-group (one row gather each)
            with jax.named_scope("unpack B"):
                gre, gim = self._st_unpack_b_rev_pair(rbre, rbim)

            with jax.named_scope("y transform"):
                gre, gim = self._st_y_forward(gre, gim)

            # exchange A reverse: each stick's z-chunk back to its owner
            with jax.named_scope("pack A"):
                bre, bim = self._st_pack_a_rev_pair(gre, gim, c0)
            with jax.named_scope("exchange A overlapped" if ov else "exchange A"):
                rre, rim = self._st_exchange_a_pair(bre, bim, reverse=True)
            recvs_re.append(rre)
            recvs_im.append(rim)

        with jax.named_scope("unpack A"):
            sre, sim = self._st_unpack_a_rev_pair(*recvs_re, *recvs_im)

        with jax.named_scope("z transform"):
            sre, sim = self._st_z_forward(sre, sim, scaling)

        with jax.named_scope("compression"):
            vre, vim = self._st_compress(sre, sim)
        return vre[None], vim[None]
