"""MXU compute path for the 2-D pencil decomposition.

Same geometry, exchanges and boundary contract as
:class:`spfft_tpu.parallel.pencil2.Pencil2Execution` (which this subclasses),
with the compute stages engineered like the 1-D MXU engines for TPU hardware:

* every DFT stage is a batched matmul (ops/fft.py) on (re, im) real pairs —
  3 real matmuls per complex stage (Gauss form, ops/fft.complex_matmul),
  2 for the R2C/C2R x-stage,
* the x-stage folds the pencil slot layout INTO the DFT matrix: the
  ``(group, slot) -> x`` map (with sentinel padding slots as zero rows) rides
  ``ops/fft.x_stage_matrices``, so the post-exchange-B column scatter and the
  pre-exchange-B column gather of the XLA engine disappear into the matmul
  (permutation folding, the designed fusion hook of ops/fft.c2c_matrix),
* sparse decompress/compress run as per-shard lane-copy plans selected by a
  deduped ``lax.switch`` (MxuValuePlans — shared with the 1-D MXU engine),
* both exchanges ride ONE stacked (re, im) all_to_all each, in the plan's
  wire dtype.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..ops import fft as offt
from ..ops import lanecopy, symmetry
from ..types import ExchangeType, ScalingType
from .execution_mxu import MxuValuePlans
from .pencil2 import AX1, AX2, Pencil2Execution


class MxuPencil2Execution(Pencil2Execution, MxuValuePlans):
    """2-D pencil pipelines with matmul DFT stages and lane-copy value plans."""

    def __init__(
        self, params, real_dtype, mesh, exchange_type=ExchangeType.DEFAULT,
        precision="highest", overlap: int = 1,
    ):
        self._precision = offt.resolve_precision(precision)
        super().__init__(params, real_dtype, mesh, exchange_type, overlap=overlap)
        p = params
        rt = self.real_dtype
        self._wz_b, self._wy_b, self._wy_f, self._wz_f = offt.zy_stage_matrices(
            p.dim_z, p.dim_y, p.total_size, rt
        )
        # x-stage over the (P1 * Ax) slot columns; sentinel slots -> zero rows
        slot_to_x = self._xcol.astype(np.int64).copy()
        slot_to_x[slot_to_x >= p.dim_x_freq] = -1
        self._wx_b, self._wx_f = offt.x_stage_matrices(
            p.dim_x, slot_to_x, slot_to_x.size, self.is_r2c, rt
        )
        self._build_value_branches()
        # pencil programs consume the base's size-aware rep directly
        # (lanecopy.phase_rep_tables_at): tables below the budget are embedded
        # as constants, bigger plans generate in-trace

    def describe(self) -> dict:
        """Engine fragment of the plan card (obs.plancard): the pencil
        geometry from the base class plus the MXU compute-stage decisions."""
        card = super().describe()
        card["pipeline"] = "matmul DFT stages + lane-copy value plans (pencil)"
        card["matmul_precision"] = str(self._precision).rsplit(".", 1)[-1]
        card["alignment_rotations"] = self._align_rep is not None
        card["value_plan_branches"] = len(self._decompress_branches)
        return card

    def _exchange_pair(self, bre, bim, axes, reverse=False):
        """(re, im) blocks through the configured discipline: the padded
        stacked-pair all_to_all (MxuValuePlans), or the exact-counts block
        chain when the plan uses a COMPACT/UNBUFFERED exchange. ``reverse``
        marks the forward-transform direction (transposed valid rectangles;
        the padded path is symmetric and ignores it)."""
        if self._ragged2 is not None:
            out = self._ragged_block_exchange([bre, bim], axes, reverse)
            return out[0], out[1]
        return super()._exchange_pair(bre, bim, axes)

    # ---- pipelines (traced lazily by the base's jit/shard_map wrappers) -------

    def _backward_impl(self, values_re, values_im, value_indices):
        del value_indices  # lane-copy branches close over their plans
        p = self.params
        prec = self._precision
        rt = self.real_dtype
        S, Z, Y = self._S, p.dim_z, p.dim_y
        P1, P2, Ax, Lz, Ly = self.P1, self.P2, self._Ax, self._Lz, self._Ly
        a_me = jax.lax.axis_index(AX1)
        b_me = jax.lax.axis_index(AX2)
        s_me = a_me * P2 + b_me

        with jax.named_scope("compression"):
            sre, sim = jax.lax.switch(
                jnp.asarray(self._branch_of_shard)[s_me],
                self._decompress_branches,
                values_re[0].astype(rt),
                values_im[0].astype(rt),
            )

        if self.is_r2c and p.zero_stick_shard >= 0:
            with jax.named_scope("stick symmetry"):
                i = p.zero_stick_row
                fre, fim = symmetry.hermitian_fill_1d_pair(sre[i], sim[i], axis=0)
                own = s_me == p.zero_stick_shard
                sre = sre.at[i].set(jnp.where(own, fre, sre[i]))
                sim = sim.at[i].set(jnp.where(own, fim, sim[i]))

        with jax.named_scope("z transform"):
            sre, sim = offt.complex_matmul(sre, sim, *self._wz_b, "sz,zk->sk", prec)
            if self._align_rep is not None:
                # undo the alignment rotations; phase rides as embedded tables
                # below the size budget, or is generated in-trace above it
                cos_t, sin_t = lanecopy.phase_rep_tables_at(self._align_rep, s_me, rt)
                sre, sim = lanecopy.apply_alignment_phase(sre, sim, cos_t, sin_t, -1)

        # Post-z chunk loop (see Pencil2Execution._backward_impl): one
        # full-window chunk bulk-synchronously, C z-window chunks under the
        # OVERLAPPED discipline so the A/B collectives pipeline against the
        # neighbor chunks' matmuls.
        ov = self._overlap > 1
        parts_re, parts_im = [], []
        for c0, c1 in self._chunks:
            # pack A: my sticks split by destination (x-group, z-slab) —
            # whole-row gathers + static window slices (base-class helpers)
            with jax.named_scope("pack A"):
                bre = self._pack_a(sre, s_me, zwin=(c0, c1))
                bim = self._pack_a(sim, s_me, zwin=(c0, c1))

            with jax.named_scope("exchange A overlapped" if ov else "exchange A"):
                rre, rim = self._exchange_pair(bre, bim, (AX1, AX2))

            # unpack A -> (Y, Ax, W) y-pencil grid (one row gather per part)
            with jax.named_scope("unpack A"):
                gre = self._unpack_a(rre, a_me)
                gim = self._unpack_a(rim, a_me)

            if self.is_r2c and self._have_x0:
                with jax.named_scope("plane symmetry"):
                    g0, s0 = self._x0_group, self._x0_slot
                    pre, pim = symmetry.hermitian_fill_1d_pair(
                        gre[:, s0, :], gim[:, s0, :], axis=0
                    )
                    gre = gre.at[:, s0, :].set(
                        jnp.where(a_me == g0, pre, gre[:, s0, :])
                    )
                    gim = gim.at[:, s0, :].set(
                        jnp.where(a_me == g0, pim, gim[:, s0, :])
                    )

            with jax.named_scope("y transform"):
                gre, gim = offt.complex_matmul(
                    gre, gim, *self._wy_b, "yal,yk->kal", prec
                )

            # pack B: each destination's y-rows (within my z-window)
            with jax.named_scope("pack B"):
                bre = self._pack_b(gre)
                bim = self._pack_b(gim)

            with jax.named_scope("exchange B overlapped" if ov else "exchange B"):
                rbre, rbim = self._exchange_pair(bre, bim, (AX1,))

            # x transform: the slot->x map is folded into the matrix (zero
            # rows on sentinel slots), so assembly is a reshape + matmul
            with jax.named_scope("x transform"):
                hre = rbre.transpose(1, 0, 2, 3).reshape(Ly, P1 * Ax, c1 - c0)
                him = rbim.transpose(1, 0, 2, 3).reshape(Ly, P1 * Ax, c1 - c0)
                if self.is_r2c:
                    parts_re.append(
                        offt.real_out_matmul(
                            hre, him, *self._wx_b, "ycl,cx->lyx", prec
                        )
                    )
                else:
                    ore, oim = offt.complex_matmul(
                        hre, him, *self._wx_b, "ycl,cx->lyx", prec
                    )
                    parts_re.append(ore)
                    parts_im.append(oim)
        if self.is_r2c:
            out = (
                parts_re[0] if len(parts_re) == 1
                else jnp.concatenate(parts_re, axis=0)
            )
            return out[None]
        ore = parts_re[0] if len(parts_re) == 1 else jnp.concatenate(parts_re, axis=0)
        oim = parts_im[0] if len(parts_im) == 1 else jnp.concatenate(parts_im, axis=0)
        return ore[None], oim[None]

    def _forward_impl(self, space_re, *rest, scale):
        p = self.params
        prec = self._precision
        rt = self.real_dtype
        S, Z, Y = self._S, p.dim_z, p.dim_y
        P1, P2, Ax, Lz, Ly = self.P1, self.P2, self._Ax, self._Lz, self._Ly
        a_me = jax.lax.axis_index(AX1)
        b_me = jax.lax.axis_index(AX2)
        s_me = a_me * P2 + b_me
        scaling = ScalingType.NONE if scale is None else ScalingType.FULL

        if self.is_r2c:
            (_,) = rest  # value_indices unused (lane-copy branches)
            space_im = None
        else:
            space_im, _ = rest

        # Forward mirror of the backward chunk loop (see
        # Pencil2Execution._forward_impl).
        ov = self._overlap > 1
        recvs_re, recvs_im = [], []
        for c0, c1 in self._chunks:
            with jax.named_scope("x transform"):
                if self.is_r2c:
                    hre, him = offt.real_in_matmul(
                        space_re[0][c0:c1].astype(rt), *self._wx_f,
                        "lyx,xc->ycl", prec,
                    )
                else:
                    hre, him = offt.complex_matmul(
                        space_re[0][c0:c1].astype(rt),
                        space_im[0][c0:c1].astype(rt),
                        *self._wx_f, "lyx,xc->ycl", prec,
                    )

            # exchange B reverse: send each x-group home (within my z-window)
            with jax.named_scope("pack B"):
                bre = hre.reshape(Ly, P1, Ax, c1 - c0).transpose(1, 0, 2, 3)
                bim = him.reshape(Ly, P1, Ax, c1 - c0).transpose(1, 0, 2, 3)
            with jax.named_scope("exchange B overlapped" if ov else "exchange B"):
                rbre, rbim = self._exchange_pair(bre, bim, (AX1,), reverse=True)

            # reassemble the full y extent of my x-group (one row gather each)
            with jax.named_scope("unpack B"):
                gre = self._unpack_b_rev(rbre)
                gim = self._unpack_b_rev(rbim)

            with jax.named_scope("y transform"):
                gre, gim = offt.complex_matmul(
                    gre, gim, *self._wy_f, "yal,yj->jal", prec
                )

            # exchange A reverse: each stick's z-chunk back to its owner
            with jax.named_scope("pack A"):
                bre = self._pack_a_rev(gre, a_me, b_me, z0=c0)
                bim = self._pack_a_rev(gim, a_me, b_me, z0=c0)
            with jax.named_scope("exchange A overlapped" if ov else "exchange A"):
                rre, rim = self._exchange_pair(bre, bim, (AX1, AX2), reverse=True)
            recvs_re.append(rre)
            recvs_im.append(rim)
        rre = (
            recvs_re[0] if len(recvs_re) == 1
            else jnp.concatenate(recvs_re, axis=-1)
        )
        rim = (
            recvs_im[0] if len(recvs_im) == 1
            else jnp.concatenate(recvs_im, axis=-1)
        )

        with jax.named_scope("unpack A"):
            sre = self._unpack_a_rev(rre, s_me)
            sim = self._unpack_a_rev(rim, s_me)

        with jax.named_scope("z transform"):
            if self._align_rep is not None:
                # enter the rotated layout on the space side
                cos_t, sin_t = lanecopy.phase_rep_tables_at(self._align_rep, s_me, rt)
                sre, sim = lanecopy.apply_alignment_phase(sre, sim, cos_t, sin_t, +1)
            sre, sim = offt.complex_matmul(
                sre, sim, *self._wz_f[scaling], "sz,zk->sk", prec
            )

        with jax.named_scope("compression"):
            vre, vim = jax.lax.switch(
                jnp.asarray(self._branch_of_shard)[s_me], self._compress_branches,
                sre, sim,
            )
        return vre[None], vim[None]
