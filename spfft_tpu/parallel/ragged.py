"""Exact-counts slab<->pencil exchange: the true COMPACT_BUFFERED discipline.

The reference's COMPACT_BUFFERED transpose is an MPI_Alltoallv sending exactly
``sticks_i x planes_j`` elements per rank pair (reference:
src/transpose/transpose_mpi_compact_buffered_host.cpp:52-106, Alltoallv at
:183-200, :269-285). The padded ``lax.all_to_all`` the mesh engines default to
(ExchangeType.BUFFERED) pads every block to ``S_max x L_max``, wasting wire
bytes by the imbalance factor ``max_sticks / sticks_i``.

This module realizes exact counts on TPU as a chain of P-1 ``lax.ppermute``
rotations (XLA's ragged-all-to-all HLO is not available on all backends; a
ring of shifted permutes is the portable ICI-friendly form — each step is a
uniform nearest-neighbor-style rotation). Step k moves the (i -> (i+k) mod P)
blocks for every shard i at once; each step's buffer is padded only to
``max_i sticks_i * planes_{(i+k) mod P}`` — the per-step maximum of *exact
products*, not the global ``S_max * L_max``. Total wire volume is therefore
``P * sum_k max_i(n_i * L_{(i+k) mod P})``: between the exact Alltoallv volume
and the padded ``P (P-1) S_max L_max``, and strictly below the padded volume
whenever the step maxima vary (imbalance in both sticks and planes; with
uniform planes and one heavy stick shard the two volumes tie). The self-block
(k = 0) never touches the wire.

Block layout on the wire is stick-major ``(stick, plane)``, matching the
reference's pack order (reference:
transpose_mpi_compact_buffered_host.cpp:109-175). All gather/scatter indices
are computed in-trace from iota plus per-step traced scalars (the peer's
stick/plane counts), so no O(data)-sized index tables are materialized.

Used by both mesh engines for ExchangeType.COMPACT_BUFFERED{,_FLOAT,_BF16} and
UNBUFFERED (the reference's other exact-counts discipline); BUFFERED/DEFAULT
keep the single fused all_to_all, which wins when shards are balanced.

LATENCY: the chain is P-1 *sequential* collective rounds, so per-exchange step
latency grows linearly with shard count, vs one fused collective for BUFFERED.
``exchange_wire_bytes()`` captures only bytes, not rounds — at large P the
exact-counts discipline can lose on latency even with lower wire volume. Pick
the discipline from both: bytes (``exchange_wire_bytes``) and round count
(P-1 vs 1).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .mesh import FFT_AXIS


def _wire_cast_out(chunk, wire):
    """Apply the wire format to an outgoing chunk (complex or real)."""
    if wire is None:
        return chunk
    if wire == "f32":
        if jnp.iscomplexobj(chunk):
            return chunk.astype(np.complex64)
        return chunk.astype(np.float32)
    if wire == "bf16":
        if jnp.iscomplexobj(chunk):
            # no complex-bf16 dtype: ride as a stacked (2, B) real pair
            return jnp.stack(
                [chunk.real.astype(jnp.bfloat16), chunk.imag.astype(jnp.bfloat16)]
            )
        return chunk.astype(jnp.bfloat16)
    raise ValueError(f"unknown wire format {wire!r}")


def _wire_cast_in(chunk, wire, dtype, real_dtype):
    if wire == "bf16" and np.dtype(dtype).kind == "c":
        re = chunk[0].astype(real_dtype)
        im = chunk[1].astype(real_dtype)
        return jax.lax.complex(re, im).astype(dtype)
    return chunk.astype(dtype)


def _wire_step(chunks, k, num_shards, axis_names, wire, dtype, real_dtype):
    """One rotation step's wire protocol, shared by both chain forms: stack
    multi-part chunks, cast to the wire format, ppermute by +k over the
    (possibly joint) axis, cast back, unstack."""
    perm = [(i, (i + k) % num_shards) for i in range(num_shards)]
    stacked = len(chunks) > 1
    wirebuf = jnp.stack(chunks) if stacked else chunks[0]
    wirebuf = _wire_cast_out(wirebuf, wire)
    wirebuf = jax.lax.ppermute(wirebuf, axis_names, perm)
    wirebuf = _wire_cast_in(wirebuf, wire, dtype, real_dtype)
    return [wirebuf[i] for i in range(len(chunks))] if stacked else [wirebuf]


class RaggedExchange:
    """Static geometry + traced pipelines for one plan's exact-counts exchange.

    Parameters (all host-side static):
      num_sticks:      (P,) exact per-shard z-stick counts
      local_z_lengths: (P,) exact per-shard xy-plane counts
      z_offsets:       (P,) global z offset of each shard's slab
      s_max:           padded stick rows per shard (stick tables' row pitch)
      l_max:           padded plane rows per shard (slab buffers' row pitch)
      dim_z:           global z extent
      num_slots:       plane slot count (dim_y * dim_x_freq for the XLA engine,
                       dim_y * active_x for the MXU engine's compact planes)
      yx_flat:         (P * s_max,) destination plane slot per padded global
                       stick row, values >= num_slots meaning padding
    """

    def __init__(
        self, num_sticks, local_z_lengths, z_offsets, s_max, l_max, dim_z,
        num_slots, yx_flat,
    ):
        n = np.asarray(num_sticks, dtype=np.int64)
        L = np.asarray(local_z_lengths, dtype=np.int64)
        zo = np.asarray(z_offsets, dtype=np.int64)
        self.P = int(n.size)
        self.S, self.Lm, self.Z = int(s_max), int(l_max), int(dim_z)
        self.nslots = int(num_slots)
        self._n, self._L, self._zo = n, L, zo
        self._yx = np.asarray(yx_flat, dtype=np.int32)
        P = self.P
        # Per-step exact-product buffer sizes (>= 1 so iota shapes stay valid).
        # One static size per step serves both sides: at step k, max over
        # senders of the send size equals max over receivers of the recv size.
        self._b_bwd = [
            max(1, int((n * L[(np.arange(P) + k) % P]).max())) for k in range(P)
        ]
        self._b_fwd = [
            max(1, int((n[(np.arange(P) + k) % P] * L).max())) for k in range(P)
        ]

    @property
    def step_buffer_sizes(self):
        """Static per-rotation buffer sizes (elements per shard per part) for
        steps 1..P-1 — what actually rides the wire; the k=0 self-block stays
        local. Backward and forward totals are equal (b_fwd[k] = b_bwd[P-k])."""
        return tuple(self._b_bwd[1:])

    # ---- traced helpers ----

    def _tables(self):
        return (
            jnp.asarray(self._n.astype(np.int32)),
            jnp.asarray(self._L.astype(np.int32)),
            jnp.asarray(self._zo.astype(np.int32)),
            jnp.asarray(self._yx),
        )

    def _stick_chunk(self, flats, b, n_me, L_peer, zo_peer):
        """Gather (n_me sticks x L_peer planes of `peer`) from padded (S*Z + 1)
        stick flats, stick-major, zero-padded to static size b."""
        idx = jnp.arange(b, dtype=jnp.int32)
        Ls = jnp.maximum(L_peer, 1)
        s, l = idx // Ls, idx % Ls
        src = jnp.where(idx < n_me * L_peer, s * self.Z + zo_peer + l, self.S * self.Z)
        return [f[src] for f in flats]

    def _plane_chunk(self, flats, peer, b, n_peer, L_me, yx):
        """Gather (n_peer sticks of `peer` x L_me planes) from padded
        (Lm*nslots + 1) plane flats, stick-major, zero-padded to size b."""
        idx = jnp.arange(b, dtype=jnp.int32)
        Ls = jnp.maximum(L_me, 1)
        s, l = idx // Ls, idx % Ls
        valid = idx < n_peer * L_me
        slot = yx[peer * self.S + jnp.where(valid, s, 0)]
        src = jnp.where(
            valid & (slot < self.nslots), l * self.nslots + slot, self.Lm * self.nslots
        )
        return [f[src] for f in flats]

    def _scatter_planes(self, outs, chunks, src_shard, n_src, L_me, yx):
        """Scatter a received (n_src sticks x L_me planes) chunk into the
        (Lm*nslots + 1) plane flats."""
        b = chunks[0].shape[-1]
        idx = jnp.arange(b, dtype=jnp.int32)
        Ls = jnp.maximum(L_me, 1)
        s, l = idx // Ls, idx % Ls
        valid = idx < n_src * L_me
        slot = yx[src_shard * self.S + jnp.where(valid, s, 0)]
        dest = jnp.where(
            valid & (slot < self.nslots), l * self.nslots + slot, self.Lm * self.nslots
        )
        return [o.at[dest].set(c) for o, c in zip(outs, chunks)]

    def _scatter_sticks(self, outs, chunks, n_me, L_src, zo_src):
        """Scatter a received (n_me sticks x L_src planes) chunk into the
        (S*Z + 1) stick flats."""
        b = chunks[0].shape[-1]
        idx = jnp.arange(b, dtype=jnp.int32)
        Ls = jnp.maximum(L_src, 1)
        s, l = idx // Ls, idx % Ls
        dest = jnp.where(idx < n_me * L_src, s * self.Z + zo_src + l, self.S * self.Z)
        return [o.at[dest].set(c) for o, c in zip(outs, chunks)]

    def _chain(self, flats, outs, make_chunk, scatter, sizes, wire, rt):
        """The ppermute chain: self-block locally, then P-1 rotations."""
        P = self.P
        me = jax.lax.axis_index(FFT_AXIS)
        dtype = flats[0].dtype
        for k in range(P):
            dst = (me + k) % P
            src = (me - k) % P
            chunks = make_chunk(flats, dst, sizes[k])
            if k:
                chunks = _wire_step(chunks, k, P, FFT_AXIS, wire, dtype, rt)
            outs = scatter(outs, chunks, src)
        return outs

    # ---- public pipelines (called inside shard_map) ----

    def backward(self, parts, wire=None, real_dtype=None):
        """(S, Z) stick parts -> (Lm * nslots + 1,) plane flats (padding slot last).

        parts: tuple of (S, Z) arrays (one complex array, or a (re, im) pair).
        """
        n_t, L_t, zo_t, yx = self._tables()
        me = jax.lax.axis_index(FFT_AXIS)
        n_me, L_me = n_t[me], L_t[me]
        flats = [
            jnp.concatenate([p.reshape(-1), jnp.zeros(1, p.dtype)]) for p in parts
        ]
        outs = [
            jnp.zeros(self.Lm * self.nslots + 1, dtype=p.dtype) for p in parts
        ]

        def make_chunk(flats, dst, b):
            return self._stick_chunk(flats, b, n_me, L_t[dst], zo_t[dst])

        def scatter(outs, chunks, src):
            return self._scatter_planes(outs, chunks, src, n_t[src], L_me, yx)

        return self._chain(
            flats, outs, make_chunk, scatter, self._b_bwd, wire, real_dtype
        )

    def forward(self, parts, wire=None, real_dtype=None):
        """(Lm * nslots,) plane flats -> (S, Z) stick parts (padding rows zero)."""
        n_t, L_t, zo_t, yx = self._tables()
        me = jax.lax.axis_index(FFT_AXIS)
        n_me, L_me = n_t[me], L_t[me]
        flats = [
            jnp.concatenate([p.reshape(-1), jnp.zeros(1, p.dtype)]) for p in parts
        ]
        outs = [jnp.zeros(self.S * self.Z + 1, dtype=p.dtype) for p in parts]

        def make_chunk(flats, dst, b):
            return self._plane_chunk(flats, dst, b, n_t[dst], L_me, yx)

        def scatter(outs, chunks, src):
            return self._scatter_sticks(outs, chunks, n_me, L_t[src], zo_t[src])

        sticks = self._chain(
            flats, outs, make_chunk, scatter, self._b_fwd, wire, real_dtype
        )
        return [s[: self.S * self.Z].reshape(self.S, self.Z) for s in sticks]


class RaggedBlockExchange:
    """Exact-counts exchange over rectangular-valid padded block buffers.

    Generic COMPACT-discipline form for exchanges whose pack stage already
    produces per-destination blocks: a (P, R, C) buffer where the valid data of
    the block for destination ``d`` on shard ``s`` is the top-left
    ``(rows[s, d], cols[s, d])`` rectangle (row-major within (R, C)), the rest
    zero padding. Each of the P-1 rotation steps ships only the exact
    rectangles, padded to the per-step maximum product — the same discipline as
    :class:`RaggedExchange`, without assuming the 1-D stick/plane geometry.
    Used by the 2-D pencil engines for their exchanges A (joint-axis rotation
    over ``("fft", "fft2")``) and B (rotation over ``"fft"`` within fixed
    z-slab rows); reference discipline being matched: MPI_Alltoallv
    (reference: src/transpose/transpose_mpi_compact_buffered_host.cpp:183-200).
    The LATENCY note at the top of this module applies: P-1 sequential rounds.

    ``axis_names``/``axis_sizes``: the mesh axes the flattened shard index runs
    over, row-major (``ppermute`` accepts the tuple directly).
    """

    def __init__(self, axis_names, axis_sizes, rows, cols, R, C):
        self.axis_names = tuple(axis_names)
        self.axis_sizes = tuple(int(n) for n in axis_sizes)
        rows = np.asarray(rows, dtype=np.int64)
        cols = np.asarray(cols, dtype=np.int64)
        self.P = int(np.prod(self.axis_sizes))
        if rows.shape != (self.P, self.P) or cols.shape != (self.P, self.P):
            raise ValueError("rows/cols must be (P, P) tables")
        self.R, self.C = int(R), int(C)
        if (rows > self.R).any() or (cols > self.C).any():
            raise ValueError("rows/cols entries must fit the (R, C) block")
        self._rows, self._cols = rows, cols
        P = self.P
        s = np.arange(P)
        # reverse direction (the exchange's inverse repartition) swaps
        # sender/receiver roles: its tables are the transposes, and its
        # per-step sizes are the forward sizes reversed (size_rev[k] ==
        # size_fwd[P-k], so wire totals are direction-independent)
        self._sizes = {
            False: [
                max(1, int((rows[s, (s + k) % P] * cols[s, (s + k) % P]).max()))
                for k in range(P)
            ],
            True: [
                max(1, int((rows[(s + k) % P, s] * cols[(s + k) % P, s]).max()))
                for k in range(P)
            ],
        }

    @property
    def step_buffer_sizes(self):
        """Static per-rotation buffer sizes (elements per shard per part) for
        steps 1..P-1 — what rides the wire; the k = 0 self-block stays local.
        Direction-independent totals (see __init__)."""
        return tuple(self._sizes[False][1:])

    def _me(self):
        me = 0
        for name, size in zip(self.axis_names, self.axis_sizes):
            me = me * size + jax.lax.axis_index(name)
        return me

    def exchange(self, parts, wire=None, real_dtype=None, reverse=False):
        """parts: list of (P, R, C) arrays. Returns the received blocks as a
        list of (P, R, C) arrays where out[src] is the block src sent here
        (exact rectangle; padding zero). ``reverse=True`` runs the inverse
        repartition (the forward transform direction), whose valid rectangles
        are the transposed tables."""
        P, R, C = self.P, self.R, self.C
        rows = self._rows.T if reverse else self._rows
        cols = self._cols.T if reverse else self._cols
        rows_t = jnp.asarray(rows.astype(np.int32))
        cols_t = jnp.asarray(cols.astype(np.int32))
        me = self._me()
        dtype = parts[0].dtype
        flats = [
            jnp.concatenate([p.reshape(-1), jnp.zeros(1, p.dtype)]) for p in parts
        ]
        outs = [jnp.zeros(P * R * C + 1, dtype=p.dtype) for p in parts]
        for k in range(P):
            dst = (me + k) % P
            src = (me - k) % P
            b = self._sizes[reverse][k]
            idx = jnp.arange(b, dtype=jnp.int32)
            # gather the exact rectangle for dst (sender-side shape)
            c_s = jnp.maximum(cols_t[me, dst], 1)
            r_i, c_i = idx // c_s, idx % c_s
            valid_s = idx < rows_t[me, dst] * cols_t[me, dst]
            gsrc = jnp.where(valid_s, dst * (R * C) + r_i * C + c_i, P * R * C)
            chunks = [f[gsrc] for f in flats]
            if k:
                chunks = _wire_step(
                    chunks, k, P, self.axis_names, wire, dtype, real_dtype
                )
            # scatter with the receiver-side shape of src's rectangle
            c_r = jnp.maximum(cols_t[src, me], 1)
            r_o, c_o = idx // c_r, idx % c_r
            valid_r = idx < rows_t[src, me] * cols_t[src, me]
            gdst = jnp.where(valid_r, src * (R * C) + r_o * C + c_o, P * R * C)
            outs = [o.at[gdst].set(c) for o, c in zip(outs, chunks)]
        return [o[: P * R * C].reshape(P, R, C) for o in outs]
