"""Exact-counts slab<->pencil exchange: the true COMPACT_BUFFERED discipline.

The reference's COMPACT_BUFFERED transpose is an MPI_Alltoallv sending exactly
``sticks_i x planes_j`` elements per rank pair (reference:
src/transpose/transpose_mpi_compact_buffered_host.cpp:52-106, Alltoallv at
:183-200, :269-285). The padded ``lax.all_to_all`` the mesh engines default to
(ExchangeType.BUFFERED) pads every block to ``S_max x L_max``, wasting wire
bytes by the imbalance factor ``max_sticks / sticks_i``.

This module realizes exact counts on TPU as a chain of P-1 ``lax.ppermute``
rotations (XLA's ragged-all-to-all HLO is not available on all backends; a
ring of shifted permutes is the portable ICI-friendly form — each step is a
uniform nearest-neighbor-style rotation). Step k moves the (i -> (i+k) mod P)
blocks for every shard i at once. The self-block (k = 0) never touches the
wire.

ROW-GRANULAR transport (round 5): every buffer moves whole rows — constant
(maxn, Lm) 2-D windows on the chain, L_max-wide row units on the
one-shot ragged-all-to-all — via dynamic slices and static-map row gathers,
never per-element index math (XLA:TPU serializes element gathers/scatters at
~20 ns/element; bench_results/round5_pencil_bisect2.json measured 640 ms of
a 980 ms pencil pair in exactly this pathology). Consequence for the CHAIN's
wire volume: each step's window spans the maxima over ALL its shard pairs,
which for P >= 2 ties the padded BUFFERED volume — the chain's value is now
latency-shape portability (the exact-rows transport where ragged-all-to-all
does not compile), while the byte savings of exact counts live in the
one-shot UNBUFFERED form (exact rows x L_max; 1/P of the padded volume under
maximal stick skew). ``_chain_step_sizes`` is the single source for what the
chain ships, shared with the DEFAULT policy's cost model.

Block layout on the wire is stick-major ``(stick, plane)``, matching the
reference's pack order (reference:
transpose_mpi_compact_buffered_host.cpp:109-175).

Used by both mesh engines for ExchangeType.COMPACT_BUFFERED{,_FLOAT,_BF16};
UNBUFFERED instead uses :class:`OneShotExchange` below (exact counts in ONE
ragged-all-to-all collective — the reference's Alltoallw analogue), and
BUFFERED/DEFAULT keep the single fused padded all_to_all, which wins when
shards are balanced.

LATENCY: the chain is P-1 *sequential* collective rounds, so per-exchange step
latency grows linearly with shard count, vs one fused collective for BUFFERED.
``exchange_wire_bytes()`` captures only bytes, not rounds — at large P the
exact-counts discipline can lose on latency even with lower wire volume. Pick
the discipline from both: bytes (``exchange_wire_bytes``) and round count
(P-1 vs 1).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..errors import InvalidParameterError
from .mesh import FFT_AXIS


def _wire_cast_out(chunk, wire):
    """Apply the wire format to an outgoing chunk (complex or real)."""
    if wire is None:
        return chunk
    if wire == "f32":
        if jnp.iscomplexobj(chunk):
            return chunk.astype(np.complex64)
        return chunk.astype(np.float32)
    if wire == "bf16":
        if jnp.iscomplexobj(chunk):
            # no complex-bf16 dtype: ride as a stacked (2, B) real pair
            return jnp.stack(
                [chunk.real.astype(jnp.bfloat16), chunk.imag.astype(jnp.bfloat16)]
            )
        return chunk.astype(jnp.bfloat16)
    raise InvalidParameterError(f"unknown wire format {wire!r}")


def _wire_cast_in(chunk, wire, dtype, real_dtype):
    if wire == "bf16" and np.dtype(dtype).kind == "c":
        re = chunk[0].astype(real_dtype)
        im = chunk[1].astype(real_dtype)
        return jax.lax.complex(re, im).astype(dtype)
    return chunk.astype(dtype)


def _wire_np_dtype(wire):
    """Real scalar dtype a wire tag casts to (None: no cast). Callers split
    complex parts into (re, im) real pairs BEFORE applying this — see
    _split_complex."""
    if wire is None:
        return None
    if wire == "f32":
        return np.float32
    if wire == "bf16":
        return jnp.bfloat16
    raise InvalidParameterError(f"unknown wire format {wire!r}")


def _fold_axis_index(axis_names, axis_sizes):
    """Traced row-major flat shard index over the given mesh axes."""
    me = 0
    for name, size in zip(axis_names, axis_sizes):
        me = me * size + jax.lax.axis_index(name)
    return me


def _split_complex(parts):
    """Complex parts ride as (re, im) real pairs: collective operands stay
    real (complex HLO support varies across backends), and the wire casts
    become plain dtype swaps."""
    if not jnp.iscomplexobj(parts[0]):
        return list(parts), None
    real_parts = []
    for p in parts:
        real_parts += [p.real, p.imag]
    return real_parts, parts[0].dtype


def _join_complex(outs, cdtype):
    if cdtype is None:
        return outs
    return [
        jax.lax.complex(outs[2 * i], outs[2 * i + 1]).astype(cdtype)
        for i in range(len(outs) // 2)
    ]


def value_order_map(plan_triplets, request_triplets):
    """Static permutation aligning a caller's packed value order with a
    plan's storage order — the coalescing map of the serving layer
    (:mod:`spfft_tpu.serve`).

    Two requests "share a stick layout" when their sparse index TRIPLET sets
    are equal; their packed value vectors may still be permutations of each
    other (each caller packs in its own submission order, the plan packs in
    storage order). This computes the whole-row static map ``src`` with

        ``plan_packed[i] == request_values[src[i]]``

    so a request's values scatter into a cached plan's order (backward
    input: ``request_values[src]``) and a plan's packed result scatters back
    (forward output: ``out[src] = plan_result``) — the same
    static-map-over-whole-rows discipline as every exchange in this module,
    applied to the request axis instead of the shard axis. Returns ``None``
    when the triplet sets differ (the geometries do not coalesce). Both
    inputs are ``(V, 3)`` (or flat ``3V``) integer arrays; duplicate rows
    cannot occur on either side (plans reject duplicate indices)."""
    a = np.asarray(plan_triplets, dtype=np.int64).reshape(-1, 3)
    b = np.asarray(request_triplets, dtype=np.int64).reshape(-1, 3)
    if a.shape != b.shape:
        return None
    oa = np.lexsort((a[:, 2], a[:, 1], a[:, 0]))
    ob = np.lexsort((b[:, 2], b[:, 1], b[:, 0]))
    if not np.array_equal(a[oa], b[ob]):
        return None
    src = np.empty(a.shape[0], dtype=np.int64)
    src[oa] = ob
    return src


def _chain_step_sizes(n, L):
    """Per-rotation static buffer sizes for an exact-counts chain over
    per-shard stick counts ``n`` and plane counts ``L``.

    Since round 5 the chain ships 2-D ROW windows, never flat element
    buffers (whole-row dynamic slices are the TPU-fast form — element-unit
    packing measured ~20 ns/element, bench_results/round5_pencil_bisect2.json).
    Every step's window must fit every shard pair of the step, and each
    step's pairs range over ALL shards, so the window is the CONSTANT
    (max_i n_i, max_i L_i) rectangle — the chain's wire volume therefore
    ties the padded BUFFERED discipline's; its remaining value is
    portability (the exact-rows transport where ragged-all-to-all does not
    compile). Returns (backward, forward) per-step size lists (uniform;
    kept list-shaped for the accounting sums). Shared by the COMPACT chain
    and the one-shot exchange's chain transport — and by the DEFAULT
    policy's cost model, which must stay single-sourced with this rule."""
    P = int(n.size)
    window = max(1, int(n.max())) * max(1, int(L.max()))
    return [window] * P, [window] * P


def _wire_step(chunks, k, num_shards, axis_names, wire, dtype, real_dtype):
    """One rotation step's wire protocol, shared by both chain forms: stack
    multi-part chunks, cast to the wire format, ppermute by +k over the
    (possibly joint) axis, cast back, unstack."""
    perm = [(i, (i + k) % num_shards) for i in range(num_shards)]
    stacked = len(chunks) > 1
    wirebuf = jnp.stack(chunks) if stacked else chunks[0]
    wirebuf = _wire_cast_out(wirebuf, wire)
    wirebuf = jax.lax.ppermute(wirebuf, axis_names, perm)
    wirebuf = _wire_cast_in(wirebuf, wire, dtype, real_dtype)
    return [wirebuf[i] for i in range(len(chunks))] if stacked else [wirebuf]


class RaggedExchange:
    """Static geometry + traced pipelines for one plan's exact-counts exchange.

    Parameters (all host-side static):
      num_sticks:      (P,) exact per-shard z-stick counts
      local_z_lengths: (P,) exact per-shard xy-plane counts
      z_offsets:       (P,) global z offset of each shard's slab
      s_max:           padded stick rows per shard (stick tables' row pitch)
      l_max:           padded plane rows per shard (slab buffers' row pitch)
      dim_z:           global z extent
      num_slots:       plane slot count (dim_y * dim_x_freq for the XLA engine,
                       dim_y * active_x for the MXU engine's compact planes)
      yx_flat:         (P * s_max,) destination plane slot per padded global
                       stick row, values >= num_slots meaning padding
    """

    def __init__(
        self, num_sticks, local_z_lengths, z_offsets, s_max, l_max, dim_z,
        num_slots, yx_flat,
    ):
        n = np.asarray(num_sticks, dtype=np.int64)
        L = np.asarray(local_z_lengths, dtype=np.int64)
        zo = np.asarray(z_offsets, dtype=np.int64)
        self.P = int(n.size)
        self.S, self.Lm, self.Z = int(s_max), int(l_max), int(dim_z)
        self.nslots = int(num_slots)
        self._n, self._L, self._zo = n, L, zo
        self._yx = np.asarray(yx_flat, dtype=np.int32)
        P = self.P
        # Row-granular transport geometry (see _chain_step_sizes): the
        # constant (maxn, Lm) window, its size for the wire accounting, and
        # the static maps the end-of-chain compactions gather through.
        self._b_bwd, _ = _chain_step_sizes(n, L)
        self._maxn = max(1, int(n.max()))
        # plane slot -> row in the received (P, maxn) stick-row stack
        # (sentinel P*maxn -> zero row)
        slot_src = np.full(self.nslots, P * self._maxn, dtype=np.int32)
        for r in range(P):
            for j in range(int(n[r])):
                slot = int(self._yx[r * self.S + j])
                if slot < self.nslots:
                    slot_src[slot] = r * self._maxn + j
        self._slot_src = slot_src
        # padded global stick row -> plane slot, sentinel -> the zero row
        # appended after the (nslots, Lm) planes
        self._yx_rows = np.minimum(
            self._yx.astype(np.int64), self.nslots
        ).astype(np.int32)

    @property
    def step_buffer_sizes(self):
        """Static per-rotation buffer sizes (elements per shard per part) for
        steps 1..P-1 — what actually rides the wire; the k=0 self-block stays
        local. Backward and forward totals are equal (b_fwd[k] = b_bwd[P-k])."""
        return tuple(self._b_bwd[1:])

    def offwire_elems(self) -> int:
        """Off-shard complex elements one exchange direction ships, summed over
        the mesh: P shards each send every step's (per-step-max) buffer."""
        return self.P * sum(self.step_buffer_sizes)

    def rounds(self) -> int:
        """Sequential collective rounds per exchange (see the LATENCY note)."""
        return self.P - 1

    # ---- traced helpers ----

    def _tables(self):
        return (
            jnp.asarray(self._n.astype(np.int32)),
            jnp.asarray(self._L.astype(np.int32)),
            jnp.asarray(self._zo.astype(np.int32)),
            jnp.asarray(self._yx),
        )

    # ---- public pipelines (called inside shard_map) ----
    #
    # ROW-GRANULAR transport (round 5): every chain step moves a 2-D window
    # of whole rows via dynamic_slice / dynamic_update_slice — never element
    # index math (measured ~20 ns/element through XLA:TPU's serialized
    # gather/scatter, bench_results/round5_pencil_bisect2.json). Receives
    # accumulate into per-source (P, maxn, Lm) row stacks; the slab/stick
    # reassembly happens ONCE at the end through static maps (the same
    # z-minor restructuring the pencil engines got in this round).

    def backward(self, parts, wire=None, real_dtype=None):
        """(S, Z) stick parts -> (nslots, Lm) slot-major plane-row parts.

        parts: tuple of (S, Z) arrays (one complex array, or a (re, im) pair).
        Each output row is one plane slot's z-extent (valid prefix = the
        local plane count); consumers reorient with plain reshapes/transposes.
        """
        P, S, Lm = self.P, self.S, self.Lm
        n_t, L_t, zo_t, _ = self._tables()
        me = jax.lax.axis_index(FFT_AXIS)
        dtype = parts[0].dtype
        maxn = self._maxn
        zero = jnp.zeros((), jnp.int32)
        # z-padding keeps the (maxn, Lm) window slice clamp-free at every zo
        padded = [jnp.pad(p, ((0, 0), (0, Lm))) for p in parts]
        stacks = [jnp.zeros((P, maxn, Lm), dtype) for _ in parts]
        for k in range(P):
            dst = (me + k) % P
            src = (me - k) % P
            chunks = [
                jax.lax.dynamic_slice(pz, (zero, zo_t[dst]), (maxn, Lm))
                for pz in padded
            ]
            # ship zeros beyond the destination's plane count (rows beyond
            # the local stick count are zero already: stick-table padding)
            cmask = jnp.arange(Lm, dtype=jnp.int32)[None, :] < L_t[dst]
            chunks = [jnp.where(cmask, c, 0) for c in chunks]
            if k:
                chunks = _wire_step(chunks, k, P, FFT_AXIS, wire, dtype, real_dtype)
            stacks = [
                jax.lax.dynamic_update_slice(o, c[None], (src, zero, zero))
                for o, c in zip(stacks, chunks)
            ]
        # one static whole-row gather: plane slot -> (source shard, stick row)
        inv = jnp.asarray(self._slot_src)
        outs = []
        for st in stacks:
            rows = jnp.concatenate(
                [st.reshape(P * maxn, Lm), jnp.zeros((1, Lm), dtype)]
            )
            outs.append(jnp.take(rows, inv, axis=0))
        return outs

    def forward(self, parts, wire=None, real_dtype=None):
        """(nslots, Lm) slot-major plane-row parts -> (S, Z) stick parts
        (padding rows zero)."""
        P, S, Z, Lm = self.P, self.S, self.Z, self.Lm
        n_t, L_t, zo_t, _ = self._tables()
        me = jax.lax.axis_index(FFT_AXIS)
        L_me = L_t[me]
        dtype = parts[0].dtype
        maxn = self._maxn
        zero = jnp.zeros((), jnp.int32)
        # one static whole-row gather: every shard's stick rows from my planes
        yx_rows = jnp.asarray(self._yx_rows)
        rows = [
            jnp.take(
                jnp.concatenate([p, jnp.zeros((1, Lm), dtype)]), yx_rows, axis=0
            ).reshape(P, S, Lm)
            for p in parts
        ]
        cmask_me = jnp.arange(Lm, dtype=jnp.int32)[None, :] < L_me
        stacks = [jnp.zeros((P, maxn, Lm), dtype) for _ in parts]
        for k in range(P):
            dst = (me + k) % P
            src = (me - k) % P
            chunks = [
                jax.lax.dynamic_slice(rw, (dst, zero, zero), (1, maxn, Lm))[0]
                for rw in rows
            ]
            # ship zeros beyond my plane count (sentinel rows are zero already)
            chunks = [jnp.where(cmask_me, c, 0) for c in chunks]
            if k:
                chunks = _wire_step(chunks, k, P, FFT_AXIS, wire, dtype, real_dtype)
            stacks = [
                jax.lax.dynamic_update_slice(o, c[None], (src, zero, zero))
                for o, c in zip(stacks, chunks)
            ]
        # static compaction: stick s's z-line = its per-source z-windows in
        # slab order (the z-slabs tile [0, Z))
        outs = []
        for st in stacks:
            pieces = [st[p_, :, : int(self._L[p_])] for p_ in range(P)]
            full = jnp.concatenate(pieces, axis=-1)  # (maxn, Z)
            outs.append(jnp.pad(full, ((0, S - maxn), (0, 0))))
        return outs


def _ragged_a2a_supported(mesh) -> bool:
    """True when the mesh's backend compiles the ``ragged-all-to-all`` HLO.

    Probed by compiling (not running) a tiny shard_map program once per
    backend — XLA:CPU's thunk emitter rejects the op at compile time, real
    TPU runtimes accept it. ``SPFFT_TPU_ONESHOT_TRANSPORT=ragged|chain``
    overrides the probe in both directions.
    """
    from .. import knobs

    override = knobs.get_str("SPFFT_TPU_ONESHOT_TRANSPORT")
    if override == "ragged":
        return True
    if override == "chain":
        return False
    devs = mesh.devices.flat
    key = (next(iter(devs)).platform, mesh.devices.size)
    if key not in _RAGGED_A2A_PROBE_CACHE:
        import numpy as np
        from jax.sharding import PartitionSpec

        P = int(mesh.devices.size)
        names = tuple(mesh.axis_names)

        def probe(x):
            z = jnp.zeros(2 * P, x.dtype)
            off = jnp.arange(P, dtype=jnp.int32)
            one = jnp.ones(P, dtype=jnp.int32)
            return jax.lax.ragged_all_to_all(
                x, z, off, one, off, one, axis_name=names
            )

        from .mesh import shard_mapper

        spec = PartitionSpec(names)
        try:
            jax.jit(
                shard_mapper(mesh)(probe, in_specs=spec, out_specs=spec)
            ).lower(jax.ShapeDtypeStruct((P * P,), np.float32)).compile()
            _RAGGED_A2A_PROBE_CACHE[key] = True
        except Exception:  # noqa: SA010 — capability probe: ANY compile
            # failure (XlaRuntimeError, NotImplementedError, lowering
            # TypeError...) means "this backend lacks ragged a2a"; the
            # result is the cached False, not a swallowed error
            _RAGGED_A2A_PROBE_CACHE[key] = False
    return _RAGGED_A2A_PROBE_CACHE[key]


_RAGGED_A2A_PROBE_CACHE: dict = {}


class OneShotExchange:
    """Exact-counts slab<->pencil exchange in ONE collective: the UNBUFFERED
    discipline.

    The reference's UNBUFFERED transpose is an ``MPI_Alltoallw`` with derived
    datatypes — one call, exact per-pair counts, no intermediate padded copies
    (reference: src/transpose/transpose_mpi_unbuffered_host.cpp:51-176). The
    TPU-native analogue is XLA's ragged-all-to-all HLO
    (:func:`jax.lax.ragged_all_to_all`): one collective whose per-peer segment
    offsets/sizes are the exact ``sticks_i x planes_j`` products, so wire
    volume is the true Alltoallv volume AND the latency is one round — beating
    both the padded BUFFERED single collective (volume) and the COMPACT
    ppermute chain (P-1 rounds, see the LATENCY note above).

    Buffer layout (identical for both transports):

    * backward send (per shard ``i``, size ``S * Z``): peer ``j``'s segment at
      offset ``n_i * zo_j``, length ``n_i * L_j``, stick-major — i.e. the
      (sticks x z) table re-packed so each destination slab's columns are
      contiguous.
    * backward recv (size ``N_total * L_max``): the contiguous segment from
      peer ``i`` (its ``n_i`` stick rows x my ``L_me`` planes, row stride
      ``L_me``) lands at ``cumn_i * L_max``; one gather re-spreads the rows
      and one scatter places them into the slab planes (compact rows: no
      padded inter-shard rows, unlike the BUFFERED unpack).
    * forward reverses both layouts (send/recv swap roles).

    Where the backend cannot compile ragged-all-to-all (XLA:CPU), the same
    one-shot buffers ride a ppermute rotation chain (``transport="chain"``) —
    bytes stay exact, rounds degrade to P-1; numerics and layout are identical,
    so CPU-mesh tests validate the entire discipline minus the HLO itself.

    Geometry parameters match :class:`RaggedExchange`.
    """

    def __init__(
        self, num_sticks, local_z_lengths, z_offsets, s_max, l_max, dim_z,
        num_slots, yx_flat, *, mesh=None, transport="auto",
    ):
        n = np.asarray(num_sticks, dtype=np.int64)
        L = np.asarray(local_z_lengths, dtype=np.int64)
        zo = np.asarray(z_offsets, dtype=np.int64)
        self.P = int(n.size)
        self.S, self.Lm, self.Z = int(s_max), int(l_max), int(dim_z)
        self.nslots = int(num_slots)
        self._n, self._L, self._zo = n, L, zo
        self.N = int(n.sum())
        self._cumn = np.concatenate([[0], np.cumsum(n)])[:-1]
        if transport == "auto":
            transport = (
                "ragged" if mesh is not None and _ragged_a2a_supported(mesh)
                else "chain"
            )
        if transport not in ("ragged", "chain"):
            raise InvalidParameterError(f"unknown transport {transport!r}")
        self.transport = transport

        # compact global stick row -> plane slot (strip the padded rows of the
        # (P, S) stick tables; sentinel slots cannot occur on real sticks)
        yx = np.asarray(yx_flat, dtype=np.int64)
        rows = []
        for r in range(self.P):
            rows.append(yx[r * self.S : r * self.S + int(n[r])])
        self._yx_compact = (
            np.concatenate(rows) if rows else np.zeros(0, np.int64)
        ).astype(np.int32)
        # compact row -> (owner shard, owner-local row) for the forward send
        self._row_cumn = np.repeat(self._cumn, n).astype(np.int64)
        self._row_owner = np.repeat(np.arange(self.P), n).astype(np.int64)
        # Row-granular transport geometry (round 5; see _chain_step_sizes):
        # the ragged unit is one Lm-wide row, chain steps ship the constant
        # (maxn, Lm) window.
        self._maxn = max(1, int(n.max()))
        # plane slot -> compact stick row (sentinel N -> zero row)
        inv_compact = np.full(self.nslots, max(1, self.N), dtype=np.int32)
        if self.N:
            inv_compact[self._yx_compact] = np.arange(self.N, dtype=np.int32)
        self._inv_compact = inv_compact
        # compact row -> row in the chain transport's (P, maxn) receive stack
        self._compact_stack_row = (
            self._row_owner * self._maxn
            + (np.arange(max(1, self.N))[: self.N] - self._row_cumn)
        ).astype(np.int32)

    def offwire_elems(self) -> int:
        """Off-shard element count per exchange direction, summed over the
        mesh: exact rows x the full Lm row width (the round-5 row-granular
        wire form — rows ship whole, their invalid-cols tail zero; the chain
        transport ships per-step (max rows x max cols) windows instead,
        accounted by step_buffer_sizes... this reports the ragged one-shot
        volume the discipline targets)."""
        n = self._n
        return int(n.sum()) * (self.P - 1) * self.Lm

    def rounds(self) -> int:
        """Sequential collective rounds per exchange under the active transport."""
        return 1 if self.transport == "ragged" else self.P - 1

    # ---- traced helpers ----

    def _tables(self):
        i32 = np.int32
        return (
            jnp.asarray(self._n.astype(i32)),
            jnp.asarray(self._L.astype(i32)),
            jnp.asarray(self._zo.astype(i32)),
            jnp.asarray(self._cumn.astype(i32)),
        )

    # complex parts ride as (re, im) real pairs (module helpers)
    _split_complex = staticmethod(_split_complex)
    _join_complex = staticmethod(_join_complex)

    # ---- public pipelines (called inside shard_map) ----
    #
    # ROW-GRANULAR buffers (round 5): the ragged-all-to-all unit is one
    # Lm-wide row; the chain transport ships 2-D windows. Pack/unpack are
    # whole-row gathers through STATIC maps plus static window slices --
    # never element index math (measured ~20 ns/element through XLA:TPU's
    # serialized gather/scatter, bench_results/round5_pencil_bisect2.json).

    def backward(self, parts, wire=None, real_dtype=None):
        """(S, Z) stick parts -> (nslots, Lm) slot-major plane-row parts.
        Same contract as :meth:`RaggedExchange.backward`."""
        parts, cdt = self._split_complex(parts)
        P, S, Lm, N = self.P, self.S, self.Lm, max(1, self.N)
        n_t, L_t, zo_t, cumn_t = self._tables()
        me = jax.lax.axis_index(FFT_AXIS)
        n_me = n_t[me]
        dtype = parts[0].dtype
        rt = real_dtype
        maxn = self._maxn
        zero = jnp.zeros((), jnp.int32)

        # pack: per-destination z-windows of my sticks, all offsets STATIC
        # ((P, S, Lm) stack; window d holds cols [0, L_d) of slab d)
        def window_stack(part):
            wins = []
            for d in range(P):
                Ld, zod = int(self._L[d]), int(self._zo[d])
                w = jax.lax.slice(part, (0, zod), (S, zod + Ld))
                if Ld < Lm:
                    w = jnp.pad(w, ((0, 0), (0, Lm - Ld)))
                wins.append(w)
            return jnp.stack(wins)  # (P, S, Lm)

        stacks = [window_stack(part) for part in parts]
        wd = _wire_np_dtype(wire)

        if self.transport == "ragged":
            operand = jnp.stack(
                [st.reshape(P * S, Lm) for st in stacks], axis=-1
            )  # (P*S, Lm, parts): segment d at row offset d*S, n_me valid rows
            buf = operand if wd is None else operand.astype(wd)
            out = jnp.zeros((N, Lm, len(parts)), dtype=buf.dtype)
            res = jax.lax.ragged_all_to_all(
                buf, out,
                (jnp.arange(P, dtype=jnp.int32) * S),
                jnp.broadcast_to(n_me, (P,)).astype(jnp.int32),
                jnp.broadcast_to(cumn_t[me], (P,)).astype(jnp.int32),
                n_t.astype(jnp.int32),
                axis_name=FFT_AXIS,
            )
            if wd is not None:
                res = res.astype(dtype)
            rows = [res[..., j] for j in range(len(parts))]  # (N, Lm) compact
        else:
            recv = [jnp.zeros((P, maxn, Lm), dtype) for _ in parts]
            for k in range(P):
                dst = (me + k) % P
                src = (me - k) % P
                chunks = [
                    jax.lax.dynamic_slice(st, (dst, zero, zero), (1, maxn, Lm))[0]
                    for st in stacks
                ]
                if k:
                    chunks = _wire_step(
                        chunks, k, P, FFT_AXIS, wire, dtype, rt
                    )
                recv = [
                    jax.lax.dynamic_update_slice(o, c[None], (src, zero, zero))
                    for o, c in zip(recv, chunks)
                ]
            remap = jnp.asarray(self._compact_stack_row)  # (N,) static
            rows = [
                jnp.take(r.reshape(P * maxn, Lm), remap, axis=0) for r in recv
            ]

        # unpack: one static whole-row gather, plane slot -> compact row
        inv = jnp.asarray(self._inv_compact)
        outs = []
        for r in rows:
            rg = jnp.concatenate([r, jnp.zeros((1, Lm), dtype)])
            outs.append(jnp.take(rg, inv, axis=0))
        return self._join_complex(outs, cdt)

    def forward(self, parts, wire=None, real_dtype=None):
        """(nslots, Lm) slot-major plane-row parts -> (S, Z) stick parts
        (padding rows zero). Same contract as :meth:`RaggedExchange.forward`."""
        parts, cdt = self._split_complex(parts)
        P, S, Z, Lm, N = self.P, self.S, self.Z, self.Lm, max(1, self.N)
        n_t, L_t, zo_t, cumn_t = self._tables()
        me = jax.lax.axis_index(FFT_AXIS)
        n_me, L_me = n_t[me], L_t[me]
        dtype = parts[0].dtype
        rt = real_dtype
        maxn = self._maxn
        zero = jnp.zeros((), jnp.int32)

        # pack: compact (N, Lm) stick rows out of my planes (static map),
        # zeros beyond my plane count
        yx_c = jnp.asarray(
            self._yx_compact if self.N else np.zeros(1, np.int32)
        )
        cmask_me = jnp.arange(Lm, dtype=jnp.int32)[None, :] < L_me
        rows = [
            jnp.where(cmask_me, jnp.take(part, yx_c, axis=0), 0)
            for part in parts
        ]  # (N, Lm): row i = my planes' values for compact stick i
        wd = _wire_np_dtype(wire)

        if self.transport == "ragged":
            operand = jnp.stack(rows, axis=-1)
            buf = operand if wd is None else operand.astype(wd)
            out = jnp.zeros((P * S, Lm, len(parts)), dtype=buf.dtype)
            res = jax.lax.ragged_all_to_all(
                buf, out,
                cumn_t.astype(jnp.int32),
                n_t.astype(jnp.int32),
                jnp.broadcast_to(me * S, (P,)).astype(jnp.int32),
                jnp.broadcast_to(n_me, (P,)).astype(jnp.int32),
                axis_name=FFT_AXIS,
            )
            if wd is not None:
                res = res.astype(dtype)
            stacks = [res[..., j].reshape(P, S, Lm) for j in range(len(parts))]
            pitch = S
        else:
            stacks = [jnp.zeros((P, maxn, Lm), dtype) for _ in parts]
            # trailing zero rows keep the window slice clamp-free when
            # cumn[dst] + bR overruns N (a clamped start silently shifts
            # the window)
            rows_pad = [jnp.pad(r, ((0, maxn), (0, 0))) for r in rows]
            for k in range(P):
                dst = (me + k) % P
                src = (me - k) % P
                rmask = jnp.arange(maxn, dtype=jnp.int32)[:, None] < n_t[dst]
                chunks = [
                    jnp.where(
                        rmask,
                        jax.lax.dynamic_slice(r, (cumn_t[dst], zero), (maxn, Lm)),
                        0,
                    )
                    for r in rows_pad
                ]
                if k:
                    chunks = _wire_step(
                        chunks, k, P, FFT_AXIS, wire, dtype, rt
                    )
                stacks = [
                    jax.lax.dynamic_update_slice(o, c[None], (src, zero, zero))
                    for o, c in zip(stacks, chunks)
                ]
            pitch = maxn

        # unpack: static per-source z-window compaction -> (S, Z)
        outs = []
        for st in stacks:
            pieces = [st[p_, :, : int(self._L[p_])] for p_ in range(P)]
            full = jnp.concatenate(pieces, axis=-1)  # (pitch, Z)
            if pitch < S:
                full = jnp.pad(full, ((0, S - pitch), (0, 0)))
            outs.append(full)
        return self._join_complex(outs, cdt)


class OneShotBlockExchange:
    """One-collective exact-counts variant of :class:`RaggedBlockExchange`.

    Same block geometry and ``exchange`` contract (a (P, R, C) buffer per part
    whose block for destination ``d`` on shard ``s`` is the top-left
    ``(rows[s, d], cols[s, d])`` rectangle), but the exact rectangles ride ONE
    :func:`jax.lax.ragged_all_to_all` instead of P-1 rotation rounds — the
    UNBUFFERED (Alltoallw) discipline for the 2-D pencil engines' exchanges.
    Requires a backend that compiles the ragged-all-to-all HLO
    (:func:`_ragged_a2a_supported`); callers fall back to the chain class
    elsewhere.

    Send layout: destination-contiguous blocks of whole C-wide ROWS at static
    per-shard row offsets (exclusive prefix sums of ``rows`` over
    destinations); recv layout: source-contiguous row segments at the
    receiver's prefix sums. The ragged unit is one (C,) row — never an
    element — so pack/unpack compile to whole-row gathers (the round-5
    on-chip finding: element-unit packing cost ~20 ns/element through
    XLA:TPU's serialized scatter, bench_results/round5_pencil_bisect2.json).
    Rows ship their full C width; the valid-cols tail is zero by the pack
    contract and carries no information (wire accounting reflects this).
    All offset tables are static (P, P) numpy arrays — only the ``me`` row
    lookup is traced.
    """

    def __init__(self, axis_names, axis_sizes, rows, cols, R, C):
        self.axis_names = tuple(axis_names)
        self.axis_sizes = tuple(int(n) for n in axis_sizes)
        rows = np.asarray(rows, dtype=np.int64)
        cols = np.asarray(cols, dtype=np.int64)
        self.P = int(np.prod(self.axis_sizes))
        if rows.shape != (self.P, self.P) or cols.shape != (self.P, self.P):
            raise InvalidParameterError("rows/cols must be (P, P) tables")
        self.R, self.C = int(R), int(C)
        if (rows > self.R).any() or (cols > self.C).any():
            raise InvalidParameterError("rows/cols entries must fit the (R, C) block")
        self._rows, self._cols = rows, cols
        self._geom = {}
        for reverse in (False, True):
            r = rows.T if reverse else rows
            off_in = np.cumsum(r, axis=1) - r  # exclusive row offsets, sender
            off_recv = np.cumsum(r, axis=0) - r  # exclusive, per receiver
            self._geom[reverse] = (
                r.astype(np.int32),
                off_in.astype(np.int32),
                off_recv.astype(np.int32),
                max(1, int(r.sum(axis=1).max())),  # send rows, padded max
                max(1, int(r.sum(axis=0).max())),  # recv rows, padded max
            )

    def offwire_elems(self) -> int:
        """Off-shard elements per exchange: exact rows x the full C row width
        (the row-granular wire form) — direction-independent."""
        off = int(self._rows.sum() - np.diag(self._rows).sum())
        return off * self.C

    def rounds(self) -> int:
        return 1

    def _me(self):
        return _fold_axis_index(self.axis_names, self.axis_sizes)

    def exchange(self, parts, wire=None, real_dtype=None, reverse=False):
        """Same contract as :meth:`RaggedBlockExchange.exchange`. Complex
        parts are split into (re, im) real pairs around the collective (the
        ragged-all-to-all operand stays real; see _split_complex)."""
        parts, cdt = _split_complex(parts)
        P, R, C = self.P, self.R, self.C
        rows, off_in, off_recv, send_rows, recv_rows = self._geom[bool(reverse)]
        rows_t = jnp.asarray(rows)
        off_in_t = jnp.asarray(off_in)
        off_recv_t = jnp.asarray(off_recv)
        me = self._me()
        dtype = parts[0].dtype
        nparts = len(parts)

        # pack: (P, R, C) blocks -> destination-contiguous ROW buffer via one
        # whole-row gather: send row t belongs to destination d(t) (found by
        # binary search over my row-offset prefix) at block row t - off[d]
        t_idx = jnp.arange(send_rows, dtype=jnp.int32)
        cum_me = off_in_t[me] + rows_t[me]  # inclusive prefix, (P,)
        d_of = jnp.searchsorted(cum_me, t_idx, side="right").astype(jnp.int32)
        d_safe = jnp.minimum(d_of, P - 1)
        r_in = t_idx - off_in_t[me][d_safe]
        total_me = cum_me[P - 1]
        srow = jnp.where(t_idx < total_me, d_safe * R + r_in, P * R)
        send = jnp.stack(
            [
                jnp.take(
                    jnp.concatenate([p.reshape(P * R, C), jnp.zeros((1, C), dtype)]),
                    srow, axis=0,
                )
                for p in parts
            ],
            axis=-1,
        )  # (send_rows, C, nparts)

        wd = _wire_np_dtype(wire)
        buf = send if wd is None else send.astype(wd)
        out = jnp.zeros((recv_rows, C, nparts), dtype=buf.dtype)
        res = jax.lax.ragged_all_to_all(
            buf, out,
            off_in_t[me],
            rows_t[me],
            off_recv_t[me],  # where my row segment lands on each receiver
            rows_t[:, me],
            axis_name=self.axis_names,
        )
        if wd is not None:
            res = res.astype(dtype)

        # unpack: source-contiguous row segments -> (P, R, C) blocks, one
        # whole-row gather per part (sentinel -> zero row)
        r_i = jnp.arange(R, dtype=jnp.int32)[None, :]
        grow = off_recv_t[:, me][:, None] + r_i  # (P, R)
        grow = jnp.where(r_i < rows_t[:, me][:, None], grow, recv_rows)
        grow = grow.reshape(-1)
        res_g = jnp.concatenate([res, jnp.zeros((1, C, nparts), dtype)])
        outs = [
            jnp.take(res_g[..., j], grow, axis=0).reshape(P, R, C)
            for j in range(nparts)
        ]
        return _join_complex(outs, cdt)


class RaggedBlockExchange:
    """Exact-counts exchange over rectangular-valid padded block buffers.

    Generic COMPACT-discipline form for exchanges whose pack stage already
    produces per-destination blocks: a (P, R, C) buffer where the valid data of
    the block for destination ``d`` on shard ``s`` is the top-left
    ``(rows[s, d], cols[s, d])`` rectangle (row-major within (R, C)), the rest
    zero padding. Each of the P-1 rotation steps ships a 2-D window sized to
    the step's (max rows x max cols) over its shard pairs — row-granular
    dynamic slices, never element index math (the round-5 on-chip finding;
    see __init__), slightly above the exact-product padding for skewed
    geometries but the same exact-counts discipline class as
    :class:`RaggedExchange`, without assuming the 1-D stick/plane geometry.
    Used by the 2-D pencil engines for their exchanges A (joint-axis rotation
    over ``("fft", "fft2")``) and B (rotation over ``"fft"`` within fixed
    z-slab rows); reference discipline being matched: MPI_Alltoallv
    (reference: src/transpose/transpose_mpi_compact_buffered_host.cpp:183-200).
    The LATENCY note at the top of this module applies: P-1 sequential rounds.

    ``axis_names``/``axis_sizes``: the mesh axes the flattened shard index runs
    over, row-major (``ppermute`` accepts the tuple directly).
    """

    def __init__(self, axis_names, axis_sizes, rows, cols, R, C):
        self.axis_names = tuple(axis_names)
        self.axis_sizes = tuple(int(n) for n in axis_sizes)
        rows = np.asarray(rows, dtype=np.int64)
        cols = np.asarray(cols, dtype=np.int64)
        self.P = int(np.prod(self.axis_sizes))
        if rows.shape != (self.P, self.P) or cols.shape != (self.P, self.P):
            raise InvalidParameterError("rows/cols must be (P, P) tables")
        self.R, self.C = int(R), int(C)
        if (rows > self.R).any() or (cols > self.C).any():
            raise InvalidParameterError("rows/cols entries must fit the (R, C) block")
        self._rows, self._cols = rows, cols
        P = self.P
        s = np.arange(P)
        # Per-step 2-D buffer dims: step k ships the (max rows, max cols)
        # rectangle over its (s, (s+k)%P) pairs. Blocks are zero outside
        # their valid rects (the pack contract), so slicing and writing the
        # padded rectangle moves only zeros beyond the exact data — and the
        # transport stays ROW-granular (dynamic_slice / dynamic_update_slice,
        # no element index math; the round-5 on-chip finding: the earlier
        # flat exact-product buffers cost ~20 ns/element through XLA:TPU's
        # serialized element gather/scatter — 640 ms of a 980 ms pencil pair
        # at 256^3, bench_results/round5_pencil_bisect2.json).
        # The reverse direction (the exchange's inverse repartition) swaps
        # sender/receiver roles: its tables are the transposes.
        def step_dims(r, c):
            return [
                (
                    max(1, int(r[s, (s + k) % P].max())),
                    max(1, int(c[s, (s + k) % P].max())),
                )
                for k in range(P)
            ]

        self._dims = {False: step_dims(rows, cols), True: step_dims(rows.T, cols.T)}
        # wire accounting follows the 2-D padded rectangles
        self._sizes = {
            d: [r * c for r, c in dims] for d, dims in self._dims.items()
        }

    @property
    def step_buffer_sizes(self):
        """Static per-rotation buffer sizes (elements per shard per part) for
        steps 1..P-1 — what rides the wire; the k = 0 self-block stays local.
        Direction-independent totals: reverse step k covers the transposed
        pairs of forward step P-k (rows.T[s, s+k] enumerates the same (s, d)
        set as rows[s, s+(P-k)]), so its per-step maxima — and with them the
        size list — are the forward ones reversed."""
        return tuple(self._sizes[False][1:])

    def offwire_elems(self) -> int:
        """Off-shard elements per exchange, summed over the subgroup's P
        shards (each ships every step's per-step-max buffer)."""
        return self.P * sum(self.step_buffer_sizes)

    def rounds(self) -> int:
        return self.P - 1

    def _me(self):
        return _fold_axis_index(self.axis_names, self.axis_sizes)

    def exchange(self, parts, wire=None, real_dtype=None, reverse=False):
        """parts: list of (P, R, C) arrays. Returns the received blocks as a
        list of (P, R, C) arrays where out[src] is the block src sent here
        (exact rectangle; padding zero). ``reverse=True`` runs the inverse
        repartition (the forward transform direction), whose valid rectangles
        are the transposed tables."""
        P = self.P
        me = self._me()
        dtype = parts[0].dtype
        outs = [jnp.zeros(p.shape, dtype=p.dtype) for p in parts]
        for k in range(P):
            dst = (me + k) % P
            src = (me - k) % P
            bR, bC = self._dims[bool(reverse)][k]
            # slice dst's padded rectangle (whole rows; zeros beyond the
            # valid rect ride along, carrying no information)
            zero = jnp.zeros((), dst.dtype)
            chunks = [
                jax.lax.dynamic_slice(p, (dst, zero, zero), (1, bR, bC))[0]
                for p in parts
            ]
            if k:
                chunks = _wire_step(
                    chunks, k, P, self.axis_names, wire, dtype, real_dtype
                )
            # write src's rectangle; the padded window beyond src's valid
            # rect holds zeros over the zero-initialized output
            outs = [
                jax.lax.dynamic_update_slice(o, c[None], (src, zero, zero))
                for o, c in zip(outs, chunks)
            ]
        return outs
