"""2-D pencil decomposition: scaling past the reference's slab limit.

The reference (and this build's 1-D engines) splits the space domain into z
slabs, capping useful parallelism at ``dim_z`` ranks (zero-length slabs beyond
that — reference: docs/source/details.rst:50-52). This engine distributes over
a 2-D ``("fft", "fft2")`` mesh instead:

* frequency domain: z-sticks sharded over ALL P1*P2 shards (whole-stick
  constraint unchanged),
* intermediate domain: y-pencils — shard (a, b) owns x-group a (a subset of
  the active-x list chosen per plan: round-robin for the padded discipline,
  ownership-aligned for the exact-counts ones — see _x_group_assignment) and
  z-planes b, with the full y extent,
* space domain: 2-D slabs — shard (a, b) owns z-planes b and y-rows a, full x.

Backward pipeline: z-FFT (stick-local) -> exchange A (ONE all_to_all over both
mesh axes jointly: stick z-chunks -> y-pencils) -> y-FFT -> exchange B (one
all_to_all over the "fft" axis only, inside fixed z-planes: y-pencils -> 2-D
slabs) -> x-FFT. Forward reverses. Useful parallelism now scales to
``dim_z * dim_y`` shards — the same two-transpose structure as dense pencil
FFT frameworks (AccFFT / mpi4py-fft lineage), adapted to sparse z-stick input
(which removes one of their three transposes: sticks are already z-local).

The intermediate y-pencil grid is laid out (Y, Ax, Lz) with z MINOR, so every
pack/unpack around both exchanges moves whole contiguous z-rows — compiled as
row gathers and static slices, never element scatters (the TPU-fast form; see
the "exchange-A pack/unpack" section). The space-domain boundary stays the
(Lz, Ly, X) slab contract.

Wire discipline is padded-uniform (BUFFERED) on both exchanges; ``*_FLOAT`` /
``*_BF16`` wire casts apply around each collective. R2C works because both
hermitian completions stay shard-local: the (0,0) stick fill happens on its
owner before exchange A (as in 1-D), and the x=0 plane fill happens after
exchange A on whichever (group, slot) holds x=0 under the plan's assignment,
where that shard holds the FULL y extent
(reference: src/symmetry/symmetry_host.hpp:40-97). XLA/jnp.fft compute path.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..errors import InvalidParameterError
from ..execution import _complex_dtype
from ..ops import symmetry
from ..types import (
    RAGGED_EXCHANGES as _RAGGED,
    ExchangeType,
    ScalingType,
    TransformType,
)
from .execution import PaddingHelpers, chunk_ranges, exchange_build_checkpoint

AX1 = "fft"   # x-group / y-slab axis (size P1)
AX2 = "fft2"  # z-slab axis (size P2)


def _ceil_split(n: int, parts: int) -> np.ndarray:
    """Balanced contiguous split sizes (first ``n % parts`` get one extra)."""
    base, extra = divmod(n, parts)
    return np.asarray([base + (1 if i < extra else 0) for i in range(parts)])


def _x_group_assignment(ux, sx_all, valid, P1, P2, aligned):
    """Assign active-x values to the P1 x-groups; returns
    ``(group_of_ux, slot_of_ux, Ax)`` over the sorted active list ``ux``.

    Two strategies (the x-group map is arbitrary — the slot->x reassembly
    table handles any assignment):

    - balanced (``aligned=False``): round-robin over the active-x list.
      Equalizes per-(shard, group) stick counts even when stick ownership is
      x-contiguous (distribute_triplets), which is what the PADDED exchange-A
      blocks (uniform SG x Lz) need — measured at 256^3/15% x-slab, round-robin
      halves SG vs the earlier contiguous equal-width split.
    - ownership-aligned (``aligned=True``): each x goes to the group of the
      shard-COLUMN (a = s // P2) owning most of its sticks. When stick
      ownership is x-contiguous this makes exchange A near-column-diagonal:
      only the z-chunk redistribution inside each column crosses the wire
      ((P2-1)/P2 of stick data instead of (P-1)/P), and the EXACT-counts
      disciplines (whose off-column blocks then ship ~0 bytes) collect the
      saving. The padded discipline cannot (its blocks stay SG x Lz uniform),
      so callers pick the strategy via the discipline (see __init__).
    """
    ux = np.asarray(ux, dtype=np.int64)
    if not aligned:
        group = np.arange(ux.size) % P1
        slot = np.arange(ux.size) // P1
        return group, slot, max(1, -(-ux.size // P1))
    col_weight = np.zeros((ux.size, P1), dtype=np.int64)
    col_of_shard = np.arange(sx_all.shape[0]) // P2
    colmat = np.broadcast_to(col_of_shard[:, None], sx_all.shape)
    xi = np.searchsorted(ux, sx_all[valid])
    np.add.at(col_weight, (xi, colmat[valid]), 1)
    group = np.argmax(col_weight, axis=1)
    slot = np.zeros(ux.size, dtype=np.int64)
    fill = np.zeros(P1, dtype=np.int64)
    for i in range(ux.size):
        slot[i] = fill[group[i]]
        fill[group[i]] += 1
    return group, slot, max(1, int(fill.max()))


def _resolve_pencil2_default(assign, lz, ly, Lz, Ly, P1, P2, mesh,
                             wire_scalar_bytes):
    """ExchangeType.DEFAULT resolution for 2-D pencil plans.

    Same cost model as the 1-D engines (parallel/policy.py:
    ``cost = bytes + rounds * round_cost``), evaluated over this engine's two
    exchanges with each discipline's own x-group strategy: the padded
    discipline with the balanced assignment, the exact-counts disciplines
    with the ownership-aligned one (see _x_group_assignment). The backend's
    one-shot ragged-a2a support is probed only when the answer depends on it.

    Returns ``(choice, policy_tables)``: the resolved discipline plus the
    full per-alternative accounting (one table per one-shot-support flag, in
    the plan-card ``exchange_policy`` shape minus the ``chosen`` marks —
    obs.plancard) so the engine can stash what the resolver actually weighed.
    """
    from .policy import round_cost_bytes
    from ..types import ExchangeType as ET

    Pn = P1 * P2
    d = np.arange(Pn)
    a_of, b_of = d // P2, d % P2
    per_round = round_cost_bytes()
    s_idx = np.arange(Pn)
    q_idx = np.arange(P1)

    def volumes(aligned):
        """Per-discipline wire volumes matching the transports' actual row-
        granular buffer forms (parallel/ragged.py): the one-shot ships exact
        rows x the full C row width; the chain ships per-step
        (max rows x max cols) 2-D rectangles."""
        _, _, ax, counts = assign[aligned]
        rows_a = counts[:, a_of]  # (P, P): rows of block s -> d
        cols_a = lz[b_of]  # (P,) per-destination valid cols
        a_pad = Pn * (Pn - 1) * max(1, int(counts.max())) * Lz
        a_exact = Lz * int(rows_a.sum() - np.diag(rows_a).sum())
        a_chain = Pn * sum(
            max(1, int(rows_a[s_idx, (s_idx + k) % Pn].max()))
            * max(1, int(cols_a[(s_idx + k) % Pn].max()))
            for k in range(1, Pn)
        )
        rows_b = np.broadcast_to(ly, (P1, P1))  # (q, q'): rows q -> q'
        b_pad = Pn * (P1 - 1) * Lz * Ly * ax
        b_exact = P2 * int(
            (rows_b.sum() - np.diag(rows_b).sum()) * ax * Lz
        )
        b_chain = P2 * P1 * sum(
            max(1, int(rows_b[q_idx, (q_idx + k) % P1].max())) * ax * Lz
            for k in range(1, P1)
        )
        return (a_pad, a_exact, a_chain), (b_pad, b_exact, b_chain)

    (a_pad, _, _), (b_pad, _, _) = volumes(False)
    (_, a_exact, a_chain), (_, b_exact, b_chain) = volumes(True)

    def cost(vol, rounds):
        return vol * 2 * wire_scalar_bytes + rounds * per_round

    c_buffered = cost(a_pad + b_pad, 2)
    c_oneshot = cost(a_exact + b_exact, 2)
    c_chain = cost(a_chain + b_chain, (Pn - 1) + (P1 - 1))

    def pick(one_shot_supported):
        cands = [(c_buffered, 0, ET.BUFFERED)]
        if one_shot_supported:
            cands.append((c_oneshot, 1, ET.UNBUFFERED))
        cands.append((c_chain, 2, ET.COMPACT_BUFFERED))
        return min(cands)[2]

    def policy_table(one_shot_supported):
        chain_rounds = (Pn - 1) + (P1 - 1)
        rows = {
            ET.BUFFERED: (a_pad + b_pad, 2, c_buffered),
            ET.UNBUFFERED: (
                (a_exact + b_exact, 2, c_oneshot)
                if one_shot_supported
                # without the HLO the one-shot buffers ride the block chain —
                # cost what actually rides the wire (same rule as policy.py)
                else (a_chain + b_chain, chain_rounds, c_chain)
            ),
            ET.COMPACT_BUFFERED: (a_chain + b_chain, chain_rounds, c_chain),
        }
        return {
            "round_cost_bytes": per_round,
            "one_shot_supported": bool(one_shot_supported),
            "alternatives": [
                {
                    "discipline": d.name,
                    "wire_bytes": int(vol * 2 * wire_scalar_bytes),
                    "rounds": int(rounds),
                    "cost_bytes": int(c),
                }
                for d, (vol, rounds, c) in rows.items()
            ],
        }

    tables = {flag: policy_table(flag) for flag in (False, True)}
    if pick(False) == pick(True) or Pn <= 1:
        return pick(False), tables
    from .ragged import _ragged_a2a_supported

    return pick(_ragged_a2a_supported(mesh)), tables


class Pencil2Execution(PaddingHelpers):
    """Compiled 2-D-pencil distributed pipelines for one plan (C2C or R2C)."""

    # 2-D pencil graphs map over both mesh axes (spfft_tpu.ir.compile)
    _IR_AXES = (AX1, AX2)

    def __init__(self, params, real_dtype, mesh, exchange_type=ExchangeType.DEFAULT,
                 overlap: int = 1, fuse=None):
        self.params = params
        self.mesh = mesh
        self.real_dtype = np.dtype(real_dtype)
        self.complex_dtype = _complex_dtype(real_dtype)
        self.exchange_type = ExchangeType(exchange_type)
        self._ragged = None  # the 1-D chain is unused by the pencil engines
        self._ragged2 = None  # exact-counts block chains, built after geometry
        p = params
        ax = dict(zip(mesh.axis_names, mesh.devices.shape))
        P1, P2 = int(ax[AX1]), int(ax[AX2])
        if P1 * P2 != p.num_shards:
            raise InvalidParameterError(
                f"plan has {p.num_shards} shards but the mesh is {P1}x{P2}"
            )
        self.P1, self.P2 = P1, P2
        exchange_build_checkpoint()
        S, Z, Y, Xf = p.max_num_sticks, p.dim_z, p.dim_y, p.dim_x_freq
        self._S, self._V = S, p.max_num_values

        # ---- static 2-D geometry ------------------------------------------------
        sx_all = p.stick_x_all.astype(np.int64)  # (P, S), sentinel Xf
        sy_all = p.stick_y_all.astype(np.int64)
        valid = sx_all < Xf
        ux = np.unique(sx_all[valid])
        if ux.size == 0:
            ux = np.zeros(1, dtype=np.int64)
        # z-slabs over AX2, y-slabs over AX1
        lz = _ceil_split(Z, P2)
        ly = _ceil_split(Y, P1)
        zo = np.concatenate([[0], np.cumsum(lz)[:-1]])
        yo = np.concatenate([[0], np.cumsum(ly)[:-1]])
        Lz, Ly = max(1, int(lz.max())), max(1, int(ly.max()))
        self._Lz, self._Ly = Lz, Ly
        self._lz, self._zo, self._ly, self._yo = lz, zo, ly, yo

        # ---- x-group assignment + DEFAULT resolution ---------------------------
        # The padded discipline needs balanced per-(shard, group) stick counts;
        # the exact-counts disciplines profit from ownership-aligned groups
        # (near-column-diagonal exchange A) — see _x_group_assignment. DEFAULT
        # picks the discipline (and with it the strategy) by the same cost
        # model as the 1-D engines (parallel/policy.py).
        Pn = p.num_shards

        def group_counts(group_of_ux):
            g_of_x = np.full(Xf, P1, dtype=np.int64)
            g_of_x[ux] = group_of_ux
            counts = np.zeros((Pn, P1), dtype=np.int64)
            for s in range(Pn):
                gs = g_of_x[sx_all[s, valid[s]]]
                np.add.at(counts, (s, gs), 1)
            return counts

        assign = {}

        def get_assign(aligned):
            if aligned not in assign:
                g, slot, ax = _x_group_assignment(
                    ux, sx_all, valid, P1, P2, aligned
                )
                assign[aligned] = (g, slot, ax, group_counts(g))
            return assign[aligned]

        def ragged_volume(aligned, one_shot):
            """A+B element volume under an assignment, computed for the
            transport that will actually run (parallel/ragged.py): the
            one-shot ships exact rows x the full C row width; the chain
            ships per-step (max rows x max cols) 2-D windows."""
            _, _, ax, counts = get_assign(aligned)
            d = np.arange(Pn)
            rows_a = counts[:, d // P2]
            if one_shot:
                a_vol = Lz * int(rows_a.sum() - np.diag(rows_a).sum())
                b_vol = P2 * (P1 - 1) * int(ly.sum() * ax * Lz)
                return a_vol + b_vol
            cols_a = lz[d % P2]
            si = np.arange(Pn)
            a_vol = Pn * sum(
                max(1, int(rows_a[si, (si + k) % Pn].max()))
                * max(1, int(cols_a[(si + k) % Pn].max()))
                for k in range(1, Pn)
            )
            qi = np.arange(P1)
            b_vol = P2 * P1 * sum(
                max(1, int(ly[(qi + k) % P1].max())) * int(ax) * Lz
                for k in range(1, P1)
            )
            return a_vol + b_vol

        if self.exchange_type == ExchangeType.DEFAULT:
            get_assign(False), get_assign(True)
            self.exchange_type, policy_tables = _resolve_pencil2_default(
                assign, lz, ly, Lz, Ly, P1, P2, mesh,
                wire_scalar_bytes=self.real_dtype.itemsize,
            )
        else:
            policy_tables = None

        from .ragged import _ragged_a2a_supported

        if self.exchange_type in _RAGGED:
            # resolved here once: drives both the assignment pick below and
            # the transport class choice (one-shot where the backend compiles
            # ragged-all-to-all, the rotation chain elsewhere / for COMPACT_*)
            one_shot = (
                self.exchange_type == ExchangeType.UNBUFFERED
                and _ragged_a2a_supported(mesh)
            )
            # The aligned strategy only helps when stick placement is
            # column-local (distribute_triplets layout=...); user-supplied or
            # greedy placements can make it strictly worse (bigger Ax, no
            # diagonal A) — pick whichever assignment ships fewer bytes UNDER
            # THE TRANSPORT THAT WILL RUN (the chain's per-step maxima can
            # rank assignments differently than the one-shot's exact rows).
            aligned = ragged_volume(True, one_shot) < ragged_volume(False, one_shot)
        else:
            one_shot = False
            aligned = False
        # Plan-card provenance (obs.plancard): when the DEFAULT cost model
        # ran, stash BOTH tables it weighed; report() resolves the backend's
        # actual one-shot support lazily (obs/plancard._exchange_policy_pencil)
        # so plan construction never pays a probe compile the resolver
        # deliberately skipped. For UNBUFFERED the transport choice above
        # already IS the probe result — record it so the card never re-probes.
        self._policy_tables = policy_tables
        self._policy_probed_one_shot = (
            bool(one_shot)
            if self.exchange_type == ExchangeType.UNBUFFERED
            else None
        )
        self._aligned_x_groups = bool(aligned)
        group_of_ux, slot_of_ux, Ax, counts = get_assign(aligned)
        group_of_x = np.full(Xf, P1, dtype=np.int64)  # sentinel P1
        slot_of_x = np.zeros(Xf, dtype=np.int64)
        group_of_x[ux] = group_of_ux
        slot_of_x[ux] = slot_of_ux
        self._Ax = int(Ax)
        SG = max(1, int(counts.max()))
        self._SG = SG
        rows = np.full((Pn, P1, SG), S, dtype=np.int32)        # local stick row
        cols = np.full((Pn, P1, SG), Y * Ax, dtype=np.int32)   # (y, xslot) plane col
        fill = np.zeros((Pn, P1), dtype=np.int64)
        for s in range(Pn):
            for r in np.flatnonzero(valid[s]):
                a = group_of_x[sx_all[s, r]]
                j = fill[s, a]
                rows[s, a, j] = r
                cols[s, a, j] = sy_all[s, r] * Ax + slot_of_x[sx_all[s, r]]
                fill[s, a] = j + 1
        self._rows, self._cols = rows, cols
        # Inverse tables for the ROW-GRANULAR exchange-A pack/unpack (see the
        # "exchange-A pack/unpack" section below): destination grid row
        # (y*Ax + slot) -> owning source row d*SG + j in the received block
        # stack (per x-group a; sentinel Pn*SG -> zero row), and stick row
        # r -> its gathered-stack row a*SG + j (per shard; sentinel P1*SG).
        inv_rows = np.full((P1, Y * Ax), Pn * SG, dtype=np.int32)
        stick_src = np.full((Pn, S), P1 * SG, dtype=np.int32)
        for s in range(Pn):
            for a in range(P1):
                for j in range(SG):
                    r = rows[s, a, j]
                    if r >= S:
                        continue
                    inv_rows[a, cols[s, a, j]] = s * SG + j
                    stick_src[s, r] = a * SG + j
        self._inv_rows = inv_rows
        self._stick_src = stick_src
        # x reassembly: global Xf column of (group q, slot g); sentinel Xf
        xcol = np.full(P1 * Ax, Xf, dtype=np.int64)
        xcol[group_of_x[ux] * Ax + slot_of_x[ux]] = ux
        self._xcol = xcol.astype(np.int32)
        # R2C symmetry site: the x == 0 plane's (group, slot) under the active
        # assignment (any strategy may place it anywhere)
        self._have_x0 = bool((ux == 0).any())
        self._x0_group = int(group_of_x[0]) if self._have_x0 else 0
        self._x0_slot = int(slot_of_x[0]) if self._have_x0 else 0
        # y chunk maps: global y of (group q, row l) with sentinel Y, and inverse
        ymap = np.full((P1, Ly), Y, dtype=np.int64)
        for a in range(P1):
            ymap[a, : ly[a]] = yo[a] + np.arange(ly[a])
        self._ymap = ymap.reshape(-1).astype(np.int32)
        yinv = np.zeros(Y, dtype=np.int64)  # y -> q*Ly + l
        for a in range(P1):
            yinv[yo[a] : yo[a] + ly[a]] = a * Ly + np.arange(ly[a])
        self._yinv = yinv.astype(np.int32)

        # ---- exact-counts exchange chains (COMPACT/UNBUFFERED disciplines) ----
        # Exchange A blocks are (P, SG, Lz) with valid rectangle
        # (counts[s, a(d)], lz[b(d)]) — stick-count imbalance across x-groups
        # and z-slab raggedness both shrink the wire. Exchange B blocks are
        # (P1, Ly, Ax*Lz) with valid rows ly[q] (z-minor row layout); its
        # rotation spans only the balanced y split, so its savings are usually
        # small — A carries the discipline's value. Reference: MPI_Alltoallv
        # (transpose_mpi_compact_buffered_host.cpp:183-200).
        if self.exchange_type in _RAGGED:
            from .ragged import OneShotBlockExchange, RaggedBlockExchange

            # UNBUFFERED: one ragged-all-to-all collective per exchange where
            # the backend compiles the HLO (TPU); block chains elsewhere and
            # for COMPACT_* (``one_shot`` resolved with the assignment pick
            # above, see parallel/ragged.py).
            cls = OneShotBlockExchange if one_shot else RaggedBlockExchange
            d = np.arange(Pn)
            rows_a = counts[:, d // P2]  # (P, P): rows_a[s, d] = counts[s, a(d)]
            cols_a = np.broadcast_to(lz[d % P2], (Pn, Pn))
            rows_b = np.broadcast_to(ly, (P1, P1))  # valid rows = dest y-length
            cols_b = np.full((P1, P1), int(Ax) * Lz, dtype=np.int64)
            self._ragged2 = {
                (AX1, AX2): cls((AX1, AX2), (P1, P2), rows_a, cols_a, SG, Lz),
                (AX1,): cls((AX1,), (P1,), rows_b, cols_b, Ly, int(Ax) * Lz),
            }

        # OVERLAPPED discipline: the whole post-z pipeline (exchange A ->
        # y-FFT -> exchange B -> x-FFT and its forward mirror) chunks along
        # the local-z axis — each Lz sub-window runs its own A and B
        # collectives, so chunk k's exchange A can fly while chunk k-1's
        # y-FFTs compute and chunk k-1's exchange B while chunk k unpacks:
        # the two collectives on disjoint mesh axes stop serializing.
        # Padded wire formats only (the block chains already round-pipeline);
        # clamped to the z-window extent.
        if self._ragged2 is not None or p.num_shards <= 1:
            self._overlap = 1
        else:
            self._overlap = max(1, min(int(overlap), Lz))
        self._chunks = chunk_ranges(Lz, self._overlap)

        # ---- sharded constants + compiled pipelines ----
        both = (AX1, AX2)
        self.value_sharding = NamedSharding(mesh, P(both, None))
        self.space_sharding = NamedSharding(mesh, P(both, None, None, None))
        self._value_indices = jax.device_put(
            np.asarray(p.value_indices, dtype=np.int32), self.value_sharding
        )
        specs_v = P(both, None)
        specs_s = P(both, None, None, None)
        r2c = self.is_r2c
        from .mesh import shard_mapper

        sm = shard_mapper(mesh)
        self._backward_sm = sm(
            self._backward_impl,
            in_specs=(specs_v, specs_v, specs_v),
            out_specs=specs_s if r2c else (specs_s, specs_s),
        )
        self._backward = jax.jit(self._backward_sm)
        self._forward_sm = {}
        self._forward = {}
        for scaling, scale in (
            (ScalingType.NONE, None),
            (ScalingType.FULL, 1.0 / p.total_size),
        ):
            self._forward_sm[scaling] = sm(
                functools.partial(self._forward_impl, scale=scale),
                in_specs=(specs_s, specs_v) if r2c else (specs_s, specs_s, specs_v),
                out_specs=(specs_v, specs_v),
            )
            self._forward[scaling] = jax.jit(self._forward_sm[scaling])

        # Stage-graph IR (spfft_tpu.ir): see DistributedExecution.__init__.
        # The MXU subclass builds its DFT matrices AFTER this constructor, so
        # it defers its own IR init to the end of its __init__.
        if type(self) is Pencil2Execution:
            from ..ir.compile import init_engine_ir

            self._ir = init_engine_ir(self, fuse)

    # ---- shared bits ----------------------------------------------------------

    @property
    def is_r2c(self) -> bool:
        return self.params.transform_type == TransformType.R2C

    def _exchange_elems(self) -> tuple:
        """(exchange A, exchange B) off-shard complex-element volumes per
        repartition — the single-sourced split behind
        :meth:`exchange_wire_bytes` and the per-stage perf accounting
        (:meth:`stage_accounting`), so the two can never disagree."""
        p = self.params
        if self._ragged2 is not None:
            # exchange A spans the whole mesh (its offwire_elems covers every
            # shard); exchange B runs per "fft2" subgroup, P2 of them
            a_elems = self._ragged2[(AX1, AX2)].offwire_elems()
            b_elems = self.P2 * self._ragged2[(AX1,)].offwire_elems()
        else:
            a_elems = p.num_shards * (p.num_shards - 1) * self._SG * self._Lz
            b_elems = p.num_shards * (self.P1 - 1) * self._Lz * self._Ly * self._Ax
        return int(a_elems), int(b_elems)

    def exchange_wire_bytes(self) -> int:
        """Off-shard bytes per repartition pair (exchange A + exchange B).
        Bytes only — the exact-counts chains add P-1 (A) and P1-1 (B)
        sequential rounds (see parallel/ragged.py's LATENCY note)."""
        a_elems, b_elems = self._exchange_elems()
        return (a_elems + b_elems) * 2 * self._wire_scalar_bytes()

    def stage_accounting(self) -> list:
        """Analytic per-stage flop/byte rows for one backward+forward pair —
        the :mod:`spfft_tpu.obs.perf` hook for the 2-D pencil engines (same
        contract as ``PaddingHelpers.stage_accounting``). The two exchanges
        carry distinct A/B rows whose byte volumes come from
        :meth:`_exchange_elems` — the same single-sourced split as
        :meth:`exchange_wire_bytes` — so the PR-7 overlap work can score the
        stick->y-pencil and y-pencil->slab hops separately. The common
        head/tail rows come from the perf layer's shared builders; this hook
        supplies the A/B exchange middle."""
        from ..obs.perf import pipeline_head_rows, pipeline_tail_rows

        p = self.params
        P = int(p.num_shards)
        Z, Y, X, Xf = p.dim_z, p.dim_y, p.dim_x, p.dim_x_freq
        c_item = 2 * self.real_dtype.itemsize
        total_sticks = int(np.asarray(p.num_sticks_per_shard).sum())
        a_elems, b_elems = self._exchange_elems()
        wire_scalar = self._wire_scalar_bytes()
        buf_a = P * P * self._SG * self._Lz  # A-block buffers, all shards
        buf_b = P * self.P1 * self._Lz * self._Ly * self._Ax  # B buffers
        rows = pipeline_head_rows(
            int(np.asarray(p.num_values_per_shard).sum()), total_sticks, Z,
            c_item,
            stick_symmetry=self.is_r2c and p.zero_stick_shard >= 0,
        )
        ov = getattr(self, "_overlap", 1)
        for tag, buf, elems, hides in (
            # backward: A chunks fly while neighbor chunks y-transform, B
            # chunks while neighbor chunks x-transform (forward mirrors) —
            # the compute stage each overlapped exchange hides behind, for
            # the perf layer's exposed-time attribution (obs/perf.py)
            ("A", buf_a, a_elems, "y transform"),
            ("B", buf_b, b_elems, "x transform"),
        ):
            rows.append(
                {"stage": f"pack {tag}", "flops": 0, "bytes": 2 * 2 * buf * c_item}
            )
            xrow = {
                "stage": (
                    f"exchange {tag}" if ov == 1 else f"exchange {tag} overlapped"
                ),
                "flops": 0,
                # pair; 2 scalars/elem — exact geometry wire bytes under
                # BOTH labels (overlap changes exposure, never the volume)
                "bytes": 2 * elems * 2 * wire_scalar,
            }
            if ov > 1:
                xrow["overlap"] = {"chunks": int(ov), "hides": hides}
            rows.append(xrow)
            rows.append(
                {"stage": f"unpack {tag}", "flops": 0, "bytes": 2 * 2 * buf * c_item}
            )
        return rows + pipeline_tail_rows(
            Z, Y, X, Z * min(Xf, self._Ax * self.P1), c_item,
            plane_symmetry=self.is_r2c,
        )

    def exchange_rounds(self) -> int:
        """Sequential collective rounds per repartition pair (exchange A +
        exchange B): 2 padded all_to_alls (2C chunk collectives under the
        OVERLAPPED discipline — each z-window chunk runs its own A and B),
        the block chains' (P-1) + (P1-1) rotations, or 2 one-shot ragged
        collectives for UNBUFFERED on backends with the HLO."""
        if self._ragged2 is not None:
            return (
                self._ragged2[(AX1, AX2)].rounds() + self._ragged2[(AX1,)].rounds()
            )
        return 2 * int(getattr(self, "_overlap", 1))

    def exchange_transport(self) -> str:
        """Plan-card transport vocabulary for the pencil exchanges (A + B) —
        see PaddingHelpers.exchange_transport."""
        if self._ragged2 is None:
            if getattr(self, "_overlap", 1) > 1:
                return "chunked all_to_all"
            return "all_to_all"
        from .ragged import OneShotBlockExchange

        if isinstance(self._ragged2[(AX1, AX2)], OneShotBlockExchange):
            return "ragged_all_to_all blocks"
        return "block chain"

    def describe(self) -> dict:
        """Engine fragment of the plan card (obs.plancard): the 2-D pencil
        geometry and the x-group strategy the discipline selected."""
        return {
            "pipeline": "jnp.fft + scatter/gather (pencil shard_map)",
            "overlap_chunks": int(self._overlap),
            "pencil_geometry": {
                "p1": int(self.P1),
                "p2": int(self.P2),
                "lz_max": int(self._Lz),
                "ly_max": int(self._Ly),
                "ax": int(self._Ax),
                "sg_max": int(self._SG),
            },
            "x_group_strategy": (
                "ownership-aligned" if self._aligned_x_groups else "balanced"
            ),
        }

    def lowered_backward(self):
        """Lower (without compiling) the backward pipeline — the obs layer's
        hook for compiled-program stats (obs.hlo.compiled_stats)."""
        p = self.params
        v = jax.ShapeDtypeStruct(
            (p.num_shards, self._V), self.real_dtype, sharding=self.value_sharding
        )
        return self._backward.lower(v, v, self._value_indices)

    def _exchange(self, buf, axes, reverse=False):
        """Padded all_to_all (BUFFERED) or exact-counts block chain
        (COMPACT/UNBUFFERED) with the configured wire format (single-sourced
        rule: PaddingHelpers._complex_wire_exchange / types.wire_dtype).
        ``reverse`` marks the forward-transform direction, whose exact valid
        rectangles are transposed (padded path: symmetric, ignores it)."""
        if self._ragged2 is not None:
            (out,) = self._ragged_block_exchange([buf], axes, reverse)
            return out
        return self._complex_wire_exchange(buf, axes)

    def _ragged_block_exchange(self, parts, axes, reverse):
        """Run the exact-counts block chain for ``axes`` on a list of
        same-shaped block buffers (one complex array, or a (re, im) pair);
        single dispatch point shared by both compute paths."""
        rex = self._ragged2[tuple(axes)]
        shape = parts[0].shape
        blocks = [p.reshape(rex.P, rex.R, rex.C) for p in parts]
        out = rex.exchange(
            blocks,
            wire=self._ragged_wire_format(),
            real_dtype=self.real_dtype,
            reverse=reverse,
        )
        return [o.reshape(shape) for o in out]

    # ---- host boundary (2-D slabs) --------------------------------------------

    def pad_space(self, space):
        """Global (Z, Y, X) array -> sharded (P, Lz, Ly, X) real arrays
        ((re, im) pair for C2C; (re, None) for R2C)."""
        from .. import obs

        p = self.params
        obs.counter("staged_bytes_total", direction="host_to_device").inc(
            (1 if self.is_r2c else 2)
            * self._num_staged_shards() * self._Lz * self._Ly * p.dim_x
            * self.real_dtype.itemsize
        )
        space = np.asarray(space)
        out = []
        for part in (space.real, None if self.is_r2c else space.imag):
            if part is None:
                out.append(None)
                continue
            buf = np.zeros(
                (p.num_shards, self._Lz, self._Ly, p.dim_x), dtype=self.real_dtype
            )
            for a in range(self.P1):
                for b in range(self.P2):
                    s = a * self.P2 + b
                    lz, zo = int(self._lz[b]), int(self._zo[b])
                    lyn, yof = int(self._ly[a]), int(self._yo[a])
                    buf[s, :lz, :lyn] = part[zo : zo + lz, yof : yof + lyn]
            out.append(jax.device_put(buf, self.space_sharding))
        return out[0], out[1]

    def unpad_space(self, out):
        """Sharded (P, Lz, Ly, X) result -> global (Z, Y, X) numpy array."""
        from .. import obs

        p = self.params
        obs.counter("staged_bytes_total", direction="device_to_host").inc(
            (1 if self.is_r2c else 2)
            * self._num_staged_shards() * self._Lz * self._Ly * p.dim_x
            * self.real_dtype.itemsize
        )
        if self.is_r2c:
            full = np.asarray(out)
            dst = np.zeros((p.dim_z, p.dim_y, p.dim_x), dtype=self.real_dtype)
        else:
            full = np.asarray(out[0]) + 1j * np.asarray(out[1])
            dst = np.zeros((p.dim_z, p.dim_y, p.dim_x), dtype=self.complex_dtype)
        for a in range(self.P1):
            for b in range(self.P2):
                s = a * self.P2 + b
                lz, zo = int(self._lz[b]), int(self._zo[b])
                lyn, yof = int(self._ly[a]), int(self._yo[a])
                dst[zo : zo + lz, yof : yof + lyn] = full[s, :lz, :lyn]
        return dst

    # ---- per-shard 2-D slab layout (consulted by DistributedTransform) --------

    def local_z_length(self, shard: int) -> int:
        return int(self._lz[shard % self.P2])

    def local_z_offset(self, shard: int) -> int:
        return int(self._zo[shard % self.P2])

    def local_y_length(self, shard: int) -> int:
        return int(self._ly[shard // self.P2])

    def local_y_offset(self, shard: int) -> int:
        return int(self._yo[shard // self.P2])

    def local_slice_size(self, shard: int) -> int:
        return self.local_z_length(shard) * self.local_y_length(shard) * self.params.dim_x

    # ---- exchange-A pack/unpack: row-granular, z-minor layout -----------------
    #
    # Every transfer moves whole z-rows: the intermediate y-pencil grid is laid
    # out (Y, Ax, Lz) with z MINOR, so each (stick, z-window) is one contiguous
    # row and pack/unpack compile to whole-row gathers plus static slices — the
    # TPU-fast form (ops/lanecopy.py's measured ~0.01 ns/element row-gather
    # path). The earlier (Lz, Y, Ax) layout forced (P, SG, Lz) ELEMENT
    # scatters/gathers here (~20 ns/element), which made on-chip pencil runs
    # ~230x slower than the local engine (round-4 root cause, ROADMAP 8b).
    # Reference pack/unpack being matched:
    # src/transpose/transpose_mpi_compact_buffered_host.cpp:109-175.

    def _pack_a(self, sticks, s_me, zwin=None):
        """(S, Z) stick table -> (P, SG, W) exchange-A blocks: one whole-row
        gather of my sticks (sentinel rows -> zeros), then one static z-window
        slice per destination z-slab (zero-padded to the window width).
        ``zwin``: the ``(c0, c1)`` sub-window of the padded Lz extent this
        chunk ships (the OVERLAPPED discipline's unit; default the full
        window)."""
        S, Z = self._S, self.params.dim_z
        P1, P2, SG, Lz = self.P1, self.P2, self._SG, self._Lz
        c0, c1 = (0, Lz) if zwin is None else zwin
        W = c1 - c0
        rows = jnp.asarray(self._rows)[s_me].reshape(-1)  # (P1*SG,), sentinel S
        padded = jnp.concatenate([sticks, jnp.zeros((1, Z), sticks.dtype)])
        g = jnp.take(padded, rows, axis=0)  # (P1*SG, Z)
        wins = []
        for b in range(P2):
            lz, zo = int(self._lz[b]), int(self._zo[b])
            lo, hi = min(c0, lz), min(c1, lz)
            w = jax.lax.slice(g, (0, zo + lo), (P1 * SG, zo + hi))
            if hi - lo < W:
                w = jnp.pad(w, ((0, 0), (0, W - (hi - lo))))
            wins.append(w)
        buf = jnp.stack(wins, axis=1)  # (P1*SG, P2, W)
        return buf.reshape(P1, SG, P2, W).transpose(0, 2, 1, 3).reshape(
            P1 * P2, SG, W
        )

    def _unpack_a(self, recv, a_me):
        """(P, SG, W) received blocks -> (Y, Ax, W) y-pencil grid: one
        whole-row gather through the per-group inverse row table (any
        z-window width W <= Lz)."""
        Y, Ax = self.params.dim_y, self._Ax
        W = recv.shape[-1]
        flat = recv.reshape(self.P1 * self.P2 * self._SG, W)
        flat = jnp.concatenate([flat, jnp.zeros((1, W), recv.dtype)])
        inv = jnp.asarray(self._inv_rows)[a_me]  # (Y*Ax,), sentinel -> zero row
        return jnp.take(flat, inv, axis=0).reshape(Y, Ax, W)

    def _pack_a_rev(self, grid, a_me, b_me, z0=0):
        """(Y, Ax, W) grid -> (P, SG, W) blocks (forward direction): one
        whole-row gather of each destination's stick rows. ``z0``: the
        window's offset inside the padded Lz extent (chunked forward packs
        mask validity against absolute z positions)."""
        Y, Ax = self.params.dim_y, self._Ax
        Pn, SG = self.P1 * self.P2, self._SG
        W = grid.shape[-1]
        g2 = grid.reshape(Y * Ax, W)
        g2 = jnp.concatenate([g2, jnp.zeros((1, W), grid.dtype)])
        cols = jnp.asarray(self._cols)[:, a_me, :].reshape(-1)  # (P*SG,)
        buf = jnp.take(g2, cols, axis=0).reshape(Pn, SG, W)
        # ship zeros beyond my z-length (padded windows must stay clean)
        lz_me = jnp.asarray(self._lz.astype(np.int32))[b_me]
        return jnp.where(
            (z0 + jnp.arange(W))[None, None, :] < lz_me, buf, 0
        )

    def _unpack_a_rev(self, recv, s_me):
        """(P, SG, Lz) received z-windows -> (S, Z) stick table (forward
        direction): static window compaction, then one whole-row gather."""
        S, Z = self._S, self.params.dim_z
        P1, P2, SG, Lz = self.P1, self.P2, self._SG, self._Lz
        big = recv.reshape(P1, P2, SG, Lz).transpose(0, 2, 1, 3)  # (P1, SG, P2, Lz)
        if int(self._lz.min()) == Lz:
            rows = big.reshape(P1 * SG, Z)
        else:
            parts = [
                jax.lax.slice(big, (0, 0, b, 0), (P1, SG, b + 1, int(self._lz[b])))
                for b in range(P2)
            ]
            rows = jnp.concatenate(
                [pc.reshape(P1, SG, -1) for pc in parts], axis=-1
            ).reshape(P1 * SG, Z)
        rows = jnp.concatenate([rows, jnp.zeros((1, Z), recv.dtype)])
        src = jnp.asarray(self._stick_src)[s_me]  # (S,), sentinel -> zero row
        return jnp.take(rows, src, axis=0)

    def _pack_b(self, grid):
        """(Y, Ax, W) grid -> (P1, Ly, Ax, W) exchange-B blocks: one
        whole-row gather of each destination's y-rows (any z-window width)."""
        Ly, P1 = self._Ly, self.P1
        Ax, W = grid.shape[1], grid.shape[2]
        gp = jnp.concatenate(
            [grid, jnp.zeros((1, Ax, W), grid.dtype)], axis=0
        )
        return jnp.take(gp, jnp.asarray(self._ymap), axis=0).reshape(
            P1, Ly, Ax, W
        )

    def _unpack_b_rev(self, recvb):
        """(P1, Ly, Ax, W) received blocks -> (Y, Ax, W) grid (forward
        direction): one whole-row gather through the y inverse map."""
        Ly, P1 = self._Ly, self.P1
        Ax, W = recvb.shape[2], recvb.shape[3]
        rows = recvb.reshape(P1 * Ly, Ax, W)
        return jnp.take(rows, jnp.asarray(self._yinv), axis=0)

    # ---- pipeline stage bodies -------------------------------------------------
    # One per-shard implementation per stage, shared by the monolithic impls
    # below (the bulk path IS the one-full-window chunk) and the IR node fns
    # lowered from this engine (spfft_tpu.ir.lower).

    def _shard_me(self):
        a_me = jax.lax.axis_index(AX1)
        b_me = jax.lax.axis_index(AX2)
        return a_me, b_me, a_me * self.P2 + b_me

    def _split_b(self, h, W):
        """(Ly, P1*Ax, W) plane columns -> (P1, Ly, Ax, W) exchange-B blocks
        — the one reshape both pencil engines' forward packs share (the XLA
        engine gathers through the slot map first; the MXU engine's x
        matrices land directly in slot order)."""
        return h.reshape(self._Ly, self.P1, self._Ax, W).transpose(1, 0, 2, 3)

    def _st_decompress(self, values_re, values_im, value_indices):
        S, Z = self._S, self.params.dim_z
        values = jax.lax.complex(
            values_re.astype(self.real_dtype), values_im.astype(self.real_dtype)
        )
        flat = jnp.zeros(S * Z + 1, dtype=self.complex_dtype)
        flat = flat.at[value_indices].set(values, mode="drop")
        return flat[: S * Z].reshape(S, Z)

    def _st_stick_symmetry(self, sticks):
        # (0,0)-stick hermitian fill on its owner, before the z transform
        p = self.params
        _, _, s_me = self._shard_me()
        row = sticks[p.zero_stick_row]
        filled = symmetry.hermitian_fill_1d(row, axis=0)
        return sticks.at[p.zero_stick_row].set(
            jnp.where(s_me == p.zero_stick_shard, filled, row)
        )

    def _st_z_backward(self, sticks):
        return jnp.fft.ifft(sticks, axis=1)

    def _st_pack_a(self, sticks, zwin):
        _, _, s_me = self._shard_me()
        return self._pack_a(sticks, s_me, zwin=zwin)

    def _st_exchange_a(self, buf, reverse=False):
        return self._exchange(buf, (AX1, AX2), reverse=reverse)

    def _st_unpack_a(self, recv):
        a_me, _, _ = self._shard_me()
        return self._unpack_a(recv, a_me)

    def _st_plane_symmetry(self, grid):
        # x == 0 plane hermitian fill along y on its (group, slot) owner,
        # which has the FULL y extent here (z is space-domain)
        a_me, _, _ = self._shard_me()
        g0, s0 = self._x0_group, self._x0_slot
        col = symmetry.hermitian_fill_1d(grid[:, s0, :], axis=0)
        return grid.at[:, s0, :].set(
            jnp.where(a_me == g0, col, grid[:, s0, :])
        )

    def _st_y_backward(self, grid):
        return jnp.fft.ifft(grid, axis=0)

    def _st_pack_b(self, grid):
        return self._pack_b(grid)

    def _st_exchange_b(self, bufb, reverse=False):
        return self._exchange(bufb, (AX1,), reverse=reverse)

    def _st_unpack_b(self, recvb):
        # assemble the full frequency-x extent
        Xf = self.params.dim_x_freq
        Ly, P1, Ax = self._Ly, self.P1, self._Ax
        W = recvb.shape[-1]
        h = recvb.transpose(1, 0, 2, 3).reshape(Ly, P1 * Ax, W)
        slab = jnp.zeros((Ly, Xf + 1, W), dtype=self.complex_dtype)
        slab = slab.at[:, jnp.asarray(self._xcol), :].set(h, mode="drop")
        return slab[:, :Xf, :]

    def _st_x_backward(self, slab):
        p = self.params
        if self.is_r2c:
            out = jnp.fft.irfft(slab, n=p.dim_x, axis=1).astype(self.real_dtype)
        else:
            out = jnp.fft.ifft(slab, axis=1)
        # (W, Ly, X) slice of the space slab contract
        return out.transpose(2, 0, 1)

    def _st_space_out(self, *parts):
        # z-window slices -> the (Lz, Ly, X) slab; the backward transform is
        # unnormalized, so undo ifft's 1/N here
        total = np.asarray(self.params.total_size, self.real_dtype)
        out = parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=0)
        out = out * total
        if self.is_r2c:
            return out
        return out.real, out.imag

    def _st_x_forward(self, space_re, space_im=None, zwin=None):
        c0, c1 = (0, self._Lz) if zwin is None else zwin
        if self.is_r2c:
            slab = space_re[c0:c1].astype(self.real_dtype)
            return jnp.fft.rfft(slab, axis=2).astype(self.complex_dtype)
        slab = jax.lax.complex(
            space_re[c0:c1].astype(self.real_dtype),
            space_im[c0:c1].astype(self.real_dtype),
        )
        return jnp.fft.fft(slab, axis=2)  # (W, Ly, Xf)

    def _st_pack_b_rev(self, freq):
        # split into x-group columns, send each group home (exchange B rev)
        Ly = self._Ly
        W = freq.shape[0]
        fq = freq.transpose(1, 2, 0)  # (Ly, Xf, W) z-minor
        hpad = jnp.concatenate(
            [fq, jnp.zeros((Ly, 1, W), self.complex_dtype)], axis=1
        )
        h = jnp.take(hpad, jnp.asarray(self._xcol), axis=1)
        return self._split_b(h, W)

    def _st_unpack_b_rev(self, recvb):
        return self._unpack_b_rev(recvb)  # (Y, Ax, W)

    def _st_y_forward(self, grid):
        return jnp.fft.fft(grid, axis=0)

    def _st_pack_a_rev(self, grid, z0):
        a_me, b_me, _ = self._shard_me()
        return self._pack_a_rev(grid, a_me, b_me, z0=z0)

    def _st_unpack_a_rev(self, *recvs):
        # reassemble my (S, Z) stick table from the chunk receives
        recv = recvs[0] if len(recvs) == 1 else jnp.concatenate(recvs, axis=-1)
        _, _, s_me = self._shard_me()
        return self._unpack_a_rev(recv, s_me)

    def _st_z_forward(self, sticks):
        return jnp.fft.fft(sticks, axis=1)

    def _st_compress(self, sticks, value_indices, scale):
        values = jnp.take(
            sticks.reshape(-1), value_indices, mode="fill", fill_value=0
        )
        if scale is not None:
            values = values * np.asarray(scale, dtype=self.real_dtype)
        return (
            values.real.astype(self.real_dtype),
            values.imag.astype(self.real_dtype),
        )

    # ---- pipelines (traced once; run per-shard under shard_map) ---------------

    def _backward_impl(self, values_re, values_im, value_indices):
        p = self.params

        # stage scopes: canonical obs.STAGES labels (profiler attribution;
        # the two exchanges are tagged A/B so traces attribute them apart)
        with jax.named_scope("compression"):
            sticks = self._st_decompress(
                values_re[0], values_im[0], value_indices[0]
            )

        if self.is_r2c and p.zero_stick_shard >= 0:
            with jax.named_scope("stick symmetry"):
                sticks = self._st_stick_symmetry(sticks)

        with jax.named_scope("z transform"):
            sticks = self._st_z_backward(sticks)

        # The post-z pipeline runs once per z-window chunk (one full-window
        # chunk bulk-synchronously; C chunks under the OVERLAPPED discipline,
        # where chunk k's exchange A can fly while chunk k-1 y-transforms and
        # chunk k-1's exchange B while chunk k unpacks — the two collectives
        # on disjoint mesh axes stop serializing).
        ov = self._overlap > 1
        parts = []
        for c0, c1 in self._chunks:
            # pack A: my sticks split by destination (x-group a', z-slab b')
            with jax.named_scope("pack A"):
                buf = self._st_pack_a(sticks, (c0, c1))

            # exchange A: one collective over BOTH mesh axes (row-major (a, b))
            with jax.named_scope("exchange A overlapped" if ov else "exchange A"):
                recv = self._st_exchange_a(buf)  # (P, SG, W): s's sticks

            # unpack A -> y-pencil grid (Y, Ax, W): my x-group's sticks, my z
            with jax.named_scope("unpack A"):
                grid = self._st_unpack_a(recv)

            if self.is_r2c and self._have_x0:
                with jax.named_scope("plane symmetry"):
                    grid = self._st_plane_symmetry(grid)

            with jax.named_scope("y transform"):
                grid = self._st_y_backward(grid)

            # pack B: gather each destination's y-rows (within my z-window)
            with jax.named_scope("pack B"):
                bufb = self._st_pack_b(grid)

            # exchange B: within the row (fixed z-slab), over the x-group axis
            with jax.named_scope("exchange B overlapped" if ov else "exchange B"):
                recvb = self._st_exchange_b(bufb)  # (P1, Ly, Ax, W)

            with jax.named_scope("unpack B"):
                slab = self._st_unpack_b(recvb)
            with jax.named_scope("x transform"):
                parts.append(self._st_x_backward(slab))
        out = self._st_space_out(*parts)
        if self.is_r2c:
            return out[None]
        return out[0][None], out[1][None]

    def _forward_impl(self, space_re, *rest, scale):
        if self.is_r2c:
            (value_indices,) = rest
            space_im = None
        else:
            space_im, value_indices = rest

        # Forward mirror of the backward chunk loop: each z-window chunk
        # x-transforms, ships its exchange B, y-transforms, and ships its
        # exchange A — under the OVERLAPPED discipline chunk k's collectives
        # fly while the neighbor chunks' FFTs compute.
        ov = self._overlap > 1
        recvs = []
        for c0, c1 in self._chunks:
            with jax.named_scope("x transform"):
                freq = self._st_x_forward(
                    space_re[0],
                    None if space_im is None else space_im[0],
                    zwin=(c0, c1),
                )

            with jax.named_scope("pack B"):
                bufb = self._st_pack_b_rev(freq)
            # (P1, Ly, Ax, W): my x-group, q's y
            with jax.named_scope("exchange B overlapped" if ov else "exchange B"):
                recvb = self._st_exchange_b(bufb, reverse=True)

            # reassemble the full y extent of my x-group
            with jax.named_scope("unpack B"):
                grid = self._st_unpack_b_rev(recvb)
            with jax.named_scope("y transform"):
                grid = self._st_y_forward(grid)

            # exchange A reverse: each stick's z-chunk back to its owner
            with jax.named_scope("pack A"):
                buf = self._st_pack_a_rev(grid, c0)  # (P, SG, W)
            # (P, SG, W): my sticks, p's z
            with jax.named_scope("exchange A overlapped" if ov else "exchange A"):
                recvs.append(self._st_exchange_a(buf, reverse=True))

        with jax.named_scope("unpack A"):
            sticks = self._st_unpack_a_rev(*recvs)
        with jax.named_scope("z transform"):
            sticks = self._st_z_forward(sticks)

        with jax.named_scope("compression"):
            vre, vim = self._st_compress(sticks, value_indices[0], scale)
            return vre[None], vim[None]

    # ---- device-side entry points ---------------------------------------------

    def backward_pair(self, values_re, values_im):
        """Routed through the IR runtime (see DistributedExecution)."""
        return self._ir.run_backward(values_re, values_im, self._value_indices)

    def forward_pair(self, space_re, space_im, scaling: ScalingType = ScalingType.NONE):
        s = ScalingType(scaling)
        if self.is_r2c:
            return self._ir.run_forward(s, space_re, self._value_indices)
        return self._ir.run_forward(s, space_re, space_im, self._value_indices)

    def trace_backward(self, values_re, values_im, phase=()):
        del phase  # mesh engines keep per-shard reps internal (no operands)
        return self._backward_sm(values_re, values_im, self._value_indices)

    def trace_forward(
        self, space_re, space_im, scaling: ScalingType = ScalingType.NONE, phase=()
    ):
        del phase
        return self._dispatch_forward(self._forward_sm, space_re, space_im, scaling)
