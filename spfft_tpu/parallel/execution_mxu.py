"""Distributed MXU execution: the TPU-fast mesh pipeline.

Same plan geometry and boundary contract as
:class:`spfft_tpu.parallel.execution.DistributedExecution` (the XLA/pocketfft
engine, the fast path on CPU meshes), but engineered like the local MXU engine
(execution_mxu.py) for what profiles fast on TPU hardware:

* every DFT stage is a batched matmul on the MXU (ops/fft.py) instead of
  ``jnp.fft`` — the z stage runs stick-compact (padded-uniform S rows, Z lanes),
  the xy stages run per local plane with x on the lanes,
* sparse value pack/unpack (decompress/compress) run as per-shard lane-copy
  plans (ops/lanecopy.py) selected by ``lax.switch`` on the mesh axis index —
  the SPMD program embeds every shard's plan and each shard executes its own;
  shards whose caller value order is too fragmented for copy planning fall back
  to element scatter/gather in their branch only,
* the slab<->pencil repartition is ONE ``lax.all_to_all`` over the mesh axis on
  a (re, im)-stacked buffer — the uniform-block BUFFERED discipline of the
  reference (reference: src/transpose/transpose_mpi_buffered_host.cpp:162-173)
  which is the collective shape ICI likes; COMPACT_*/UNBUFFERED run the
  exact-counts ppermute chain instead (parallel/ragged.py); ``*_FLOAT``
  exchange variants halve the f64 wire to f32 around the collective, the
  analogue of the reference's float exchanges (reference:
  include/spfft/types.h:41-47, src/gpu_util/complex_conversion.cuh:37-56),
* complex data is carried as (re, im) real pairs end to end (axon TPU cannot
  transfer complex across the host boundary, and real pairs let the 4-matmul
  complex product run on the MXU).

Space-domain layout is the public (L, Y, X) slab per shard; the backward
pipeline's only transposes are one (Y*A, L) -> (L, Y*A) dense transpose per
direction (A = global active-x extent, the mesh-wide "uniqueXIndices"
compaction), placed so every xy matmul keeps x on the 128-lane minor dimension.

Compile-size note: the ``lax.switch`` embeds P copy-plan branches in the one
SPMD program. That is cheap for pod-slice shard counts (P <= 64); beyond that,
group shards with identical stick layouts or fall back to the XLA engine.
"""
from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..ops import fft as offt
from ..ops import lanecopy, symmetry
from ..types import (
    RAGGED_EXCHANGES as _RAGGED_EXCHANGES,
    ExchangeType,
    ScalingType,
    TransformType,
)
from .execution import PaddingHelpers
from .mesh import FFT_AXIS, fft_axis_size
from .ragged import OneShotExchange, RaggedExchange


def _complex_dtype(real_dtype):
    return (
        np.dtype(np.complex64)
        if np.dtype(real_dtype) == np.float32
        else np.dtype(np.complex128)
    )


class MxuValuePlans:
    """Shared MXU-engine machinery: per-shard value copy-plan branches (deduped
    lax.switch), wire-format selection, and the stacked-pair exchange. Used by
    the 1-D MXU mesh engine and the 2-D pencil MXU engine. Requires ``params``,
    ``real_dtype``, ``exchange_type``, ``_S`` and ``_V`` on the inheriting
    class."""

    def _build_value_branches(self):
        """Hash each shard's local value layout; shards with identical layouts
        share one switch branch (compile size = layout diversity, not P).

        Each shard's layout first goes through the lane-alignment stick
        rotations (ops/lanecopy.plan_alignment_rotations — same optimization as
        the local MXU engine, measured 1.19x end-to-end at the 256^3 headline):
        the branch plans are built on the rotated value->slot map, and the
        per-shard phase that undoes the rotation on the space side of the
        z matmuls lands in ``self._align_rep`` (a size-aware
        ``lanecopy.alignment_phase_rep`` value, or None when no shard
        rotates); each engine decides how to materialize it — the 1-D engine
        stages table-form reps as sharded runtime operands, the pencil
        engines embed them, and the compact ("delta") form generates each
        shard's tables in-trace everywhere.
        """
        p = self.params
        S, Z = self._S, p.dim_z
        rt = self.real_dtype
        unique_plans = {}
        branch_of_shard = np.zeros(max(1, p.num_shards), dtype=np.int32)
        self._decompress_branches = []
        self._compress_branches = []
        deltas = np.zeros((max(1, p.num_shards), S), dtype=np.int64)
        for r in range(p.num_shards):
            n = int(p.num_values_per_shard[r])
            vi = np.asarray(p.value_indices[r, :n], dtype=np.int64)
            holds_zero_stick = (
                self.is_r2c and r == p.zero_stick_shard and p.zero_stick_shard >= 0
            )
            rot = lanecopy.plan_alignment_rotations(
                vi, S, Z,
                keep_zero=(p.zero_stick_row,) if holds_zero_stick else (),
            )
            if rot is not None:
                deltas[r, : rot[0].size] = rot[0]
                vi = rot[1]
            key = (n, vi.tobytes())
            if key not in unique_plans:
                unique_plans[key] = len(self._decompress_branches)
                self._decompress_branches.append(self._make_decompress(vi, n))
                self._compress_branches.append(self._make_compress(vi, n))
            branch_of_shard[r] = unique_plans[key]
        self._branch_of_shard = branch_of_shard
        # Size-aware phase representation (lanecopy.alignment_phase_rep):
        # ("table", cos, sin) below the budget — the 1-D engine stages those
        # tables as sharded runtime operands, the pencil engines embed them —
        # or ("delta", (P, S) i32, Z) above it, where each shard's tables are
        # generated in-trace (the stacked tables at 512^3-class plans are
        # hundreds of MB). None when no shard rotates.
        self._align_rep = (
            lanecopy.alignment_phase_rep(deltas, Z, rt) if deltas.any() else None
        )

    def _make_decompress(self, vi: np.ndarray, n: int):
        """Branch: (V_max,) pair -> (S, Z) pair sticks for one shard."""
        S, Z = self._S, self.params.dim_z
        plan = lanecopy.build_decompress_plan(vi, S * Z, n) if n else None

        if plan is not None:
            def branch(vre, vim, plan=plan, n=n):
                pre, pim = plan.apply_pair(vre[:n], vim[:n])
                return (
                    pre.reshape(-1)[: S * Z].reshape(S, Z),
                    pim.reshape(-1)[: S * Z].reshape(S, Z),
                )

            return branch

        idx = jnp.asarray(np.asarray(vi, dtype=np.int32))

        def branch_scatter(vre, vim, idx=idx, n=n):
            out = []
            for v in (vre, vim):
                flat = jnp.zeros(S * Z, dtype=v.dtype).at[idx].set(
                    v[:n], mode="drop", unique_indices=True
                )
                out.append(flat.reshape(S, Z))
            return tuple(out)

        return branch_scatter

    def _make_compress(self, vi: np.ndarray, n: int):
        """Branch: (S, Z) pair sticks -> (V_max,) pair packed values."""
        S, Z, V = self._S, self.params.dim_z, self._V
        plan = lanecopy.build_compress_plan(vi, S * Z) if n else None

        if n == 0:
            def branch_empty(sre, sim):
                z = jnp.zeros(V, dtype=sre.dtype)
                return z, z

            return branch_empty

        if plan is not None:
            def branch(sre, sim, plan=plan, n=n):
                pre, pim = plan.apply_pair(sre.reshape(-1), sim.reshape(-1))
                pad = (0, V - n)
                return (
                    jnp.pad(pre.reshape(-1)[:n], pad),
                    jnp.pad(pim.reshape(-1)[:n], pad),
                )

            return branch

        idx = jnp.asarray(np.asarray(vi, dtype=np.int32))

        def branch_gather(sre, sim, idx=idx, n=n):
            pad = (0, V - n)
            return (
                jnp.pad(sre.reshape(-1)[idx], pad),
                jnp.pad(sim.reshape(-1)[idx], pad),
            )

        return branch_gather

    def _phase_tables(self, shard, rt, phase_re=None, phase_im=None):
        """Resolve this shard's per-shard (cos, sin) alignment-phase tables —
        the ONE resolution rule for every distributed MXU engine (PR-7 left a
        copy in the 1-D engine and inline ``phase_rep_tables_at`` calls in the
        pencil engine; this is the deduplicated form):

        * ``phase_re``/``phase_im`` given (the 1-D engine's staged sharded
          runtime operands, already stripped to per-shard form) — use them;
        * compact ("delta") rep — generate this shard's tables in-trace;
        * embedded table rep without staged operands (the pencil engines) —
          read them off the rep;
        * no rotations anywhere — ``(None, None)``.

        The 1-D engine's table-form rep always arrives via operands; absent
        operands it resolves to ``(None, None)`` (the historical no-operand
        contract of its trace paths)."""
        if phase_re is not None:
            return phase_re, phase_im
        rep = getattr(self, "_align_rep", None)
        if rep is None:
            return None, None
        if rep[0] != "delta" and getattr(self, "_align_phase", None) is not None:
            return None, None  # staged-operand form: caller threads them
        return lanecopy.phase_rep_tables_at(rep, shard, rt)

    def _wire_dtype(self):
        # the single-sourced wire rule (types.wire_dtype): *_FLOAT halves the
        # f64 wire like the reference's float exchange, *_BF16 is the explicit
        # bf16 opt-in; the (re, im)-stacked exchange buffer is already real,
        # so it is a pure wire-dtype swap here.
        from ..types import wire_dtype

        return wire_dtype(self.exchange_type, self.real_dtype)

    def _exchange_pair(self, bre, bim, axes):
        """(re, im) blocks -> all_to_all over ``axes``, one collective on a
        (P, 2, ...) stacked buffer in the wire dtype.

        Single-shard exchanges are the identity (no collective emitted; the
        surrounding pack/unpack reshapes then collapse to metadata), so a P=1
        distributed plan matches the local compute path — the reference's
        1-rank MPI transform does the same (reference:
        src/spfft/transform_internal.cpp:45-137)."""
        if self._exchange_axis_span(axes) == 1:
            return bre, bim
        wd = self._wire_dtype()
        buf = jnp.stack([bre.astype(wd), bim.astype(wd)], axis=1)
        recv = jax.lax.all_to_all(buf, axes, split_axis=0, concat_axis=0, tiled=True)
        recv = recv.astype(self.real_dtype)
        return recv[:, 0], recv[:, 1]


class MxuDistributedExecution(PaddingHelpers, MxuValuePlans):
    """Compiled distributed MXU pipelines for one transform plan over one mesh.

    Boundary-compatible with DistributedExecution: ``pad_values`` /
    ``backward_pair`` / ``forward_pair`` / ``unpad_*`` carry the same shapes and
    shardings, so DistributedTransform switches engines transparently.
    """

    def __init__(
        self,
        params,
        real_dtype,
        mesh,
        exchange_type: ExchangeType = ExchangeType.DEFAULT,
        precision="highest",
        overlap: int = 1,
        fuse=None,
    ):
        self.params = params
        self.mesh = mesh
        self.real_dtype = np.dtype(real_dtype)
        self.complex_dtype = _complex_dtype(real_dtype)
        self.exchange_type = ExchangeType(exchange_type)
        self._precision = offt.resolve_precision(precision)
        p = params
        if fft_axis_size(mesh) != p.num_shards:
            from ..errors import MPIParameterMismatchError

            raise MPIParameterMismatchError(
                f"plan has {p.num_shards} shards but the mesh {FFT_AXIS!r} axis "
                f"has {fft_axis_size(mesh)} devices"
            )
        from .execution import _check_multihost_mesh, exchange_build_checkpoint

        _check_multihost_mesh(mesh)
        exchange_build_checkpoint()
        rt = self.real_dtype
        r2c = self.is_r2c
        S = p.max_num_sticks
        L = max(1, p.max_local_z_length)
        V = p.max_num_values
        Z, Y, Xf = p.dim_z, p.dim_y, p.dim_x_freq
        self._S, self._L, self._V = S, L, V

        # ---- global active-x compaction ----------------------------------------
        # The xy stages only touch x-rows that carry at least one stick anywhere
        # in the mesh — the reference's "uniqueXIndices" optimization
        # (reference: src/execution/execution_host.cpp:138-144) as rectangular
        # DFT matrices, like the local MXU engine. Extent padding / full-extent
        # fallback policy: ops/fft.compact_x_extent.
        sx_all = p.stick_x_all.reshape(-1).astype(np.int64)
        ux = np.unique(sx_all[sx_all < Xf])
        if ux.size == 0:
            ux = np.zeros(1, dtype=np.int64)
        A = offt.compact_x_extent(ux.size, Xf)
        if A == Xf:
            ux_full = np.arange(Xf, dtype=np.int64)
            xslot_of = np.arange(Xf, dtype=np.int64)
        else:
            ux_full = ux
            xslot_of = np.zeros(Xf, dtype=np.int64)
            xslot_of[ux] = np.arange(ux.size)
        self._num_x_active = A

        # ---- DFT matrices (static constants; scale folded into forward z) ----
        self._wz_b, self._wy_b, self._wy_f, self._wz_f = offt.zy_stage_matrices(
            Z, Y, p.total_size, rt
        )

        # ---- exchange geometry (global constants, identical on every shard) ----
        # z-split: uniform slabs make pack/unpack pure reshapes; ragged slabs go
        # through one lane-gather per direction.
        lz, zo = np.asarray(p.local_z_lengths), np.asarray(p.z_offsets)
        self._uniform_z = bool((lz == L).all() and (zo == np.arange(p.num_shards) * L).all())
        self._pack_z = p.pack_z_map()  # (P*L,) global z per packed slot, sentinel dim_z
        self._unpack_z = p.unpack_z_map()  # (Z,) packed slot per global z
        # global stick slot tables over the padded (P, S) stick order, in the
        # COMPACT (Y, A) plane space: slot = y * A + xslot(x)
        sy = p.stick_y_all.reshape(-1).astype(np.int64)
        valid = sx_all < Xf
        yx = np.full(sx_all.size, Y * A, dtype=np.int64)  # padding sentinel
        yx[valid] = sy[valid] * A + xslot_of[sx_all[valid]]
        self._stick_yx = yx.astype(np.int32)  # (P*S,) compact plane slot per stick
        # inverse: compact plane slot -> global stick row (sentinel P*S -> zero row)
        inv = np.full(Y * A, p.num_shards * S, dtype=np.int32)
        inv[yx[valid]] = np.flatnonzero(valid).astype(np.int32)
        self._yx_stick = inv
        # R2C backward plane symmetry acts on x == 0, which is slot 0 iff an
        # x == 0 stick exists (otherwise that compact column is absent or zero;
        # ux is sorted, so any valid x == 0 lands in slot 0)
        self._have_x0 = bool((sx_all[valid] == 0).any())

        # Sparse-y stage (C2C only): global per-slot y contraction; the
        # plane-side slot space then shrinks from Y * A to A * Sy, which also
        # shrinks every exchange unpack/pack and the ragged exchanges' plane
        # flats. Engagement policy + matrix build shared with the local engine
        # (ops/fft.plan_sparse_y); built from the GLOBAL stick arrays, so
        # every shard's SPMD program agrees.
        self._sparse_y = False
        self._sparse_y_blocked = None
        self._sy_x0_bucket = None
        self._sy_x0_flat = 0
        if valid.any():
            xslot_valid = xslot_of[sx_all[valid]]
            sy_plan = (
                offt.plan_sparse_y(xslot_valid, sy[valid], A, Y, rt)
                if not r2c
                else None  # per-slot variant stays C2C-only
            )
            if sy_plan is not None:
                self._sparse_y = True
                self._sy, row_valid, self._wy_b_sp, self._wy_f_sp = sy_plan
                Sy = self._sy
                row_of = np.full(sx_all.size, A * Sy, dtype=np.int64)  # sentinel
                row_of[np.flatnonzero(valid)] = row_valid
                self._stick_row = row_of.astype(np.int32)  # (P*S,) table row
                inv_row = np.full(A * Sy, p.num_shards * S, dtype=np.int32)
                inv_row[row_valid] = np.flatnonzero(valid).astype(np.int32)
                self._row_stick = inv_row  # table row -> global stick row
            elif A < Xf:
                # Blocked sparse-y ABOVE the per-slot crossover, like the local
                # engine (ops/fft.plan_sparse_y_blocked): exact global stick
                # set, per-bucket padded tables whose flats also become the
                # plane slot space the exchanges ship (A < Xf gate: at the
                # full extent the slot domain is all of x and the permutation
                # bookkeeping buys nothing).
                nvalid = int(valid.sum())
                # R2C rides the blocked variant too: the x == 0 plane (the
                # hermitian-fill site) becomes a dense trailing bucket whose
                # flat rows [off, off+Y) every shard holds post-exchange
                dense_slots = (0,) if r2c and self._have_x0 else ()
                blk = offt.plan_sparse_y_blocked(
                    xslot_valid, sy[valid], Y, rt, nvalid, A * Y,
                    matrix_budget_mb=offt.sparse_y_matrix_budget_bytes() >> 20,
                    dense_slots=dense_slots,
                )
                if blk is not None:
                    vrows = np.flatnonzero(valid)
                    buckets = []
                    for row_idx, wyb, wyf in blk["buckets"]:
                        g = np.full(row_idx.shape, p.num_shards * S, np.int64)
                        ok = row_idx < nvalid
                        g[ok] = vrows[row_idx[ok]]
                        buckets.append((g.astype(np.int32), wyb, wyf))
                    self._sparse_y_blocked = buckets
                    rb = sum(ri.size for ri, _, _ in buckets)
                    self._rb = rb
                    row_of = np.full(sx_all.size, rb, dtype=np.int64)
                    row_of[vrows] = blk["row_of_stick"]
                    self._stick_row_b = row_of.astype(np.int32)
                    if dense_slots:
                        # the x0 plane is the LAST bucket (trailing dense)
                        self._sy_x0_bucket = len(buckets) - 1
                        self._sy_x0_flat = int(blk["dense_flat"][0])
                    # bucket-major slot order folds into the x matrices
                    ux_full = ux_full[blk["slot_perm"]]

        self._wx_b, self._wx_f = offt.x_stage_matrices(p.dim_x, ux_full, A, r2c, rt)

        # Exact-counts exchanges over the compact plane slots (Y * A, the
        # sparse-y (A, Sy) table rows, or the blocked bucket flats): COMPACT_*
        # runs the ppermute chain, UNBUFFERED the one-shot ragged-all-to-all
        # discipline; the exchange machinery is generic over
        # (num_slots, per-stick slot map).
        if self._sparse_y:
            plane_slots, slot_of_stick = A * self._sy, self._stick_row
        elif self._sparse_y_blocked is not None:
            plane_slots, slot_of_stick = self._rb, self._stick_row_b
        else:
            plane_slots, slot_of_stick = Y * A, self._stick_yx
        self._plane_slots = plane_slots
        self._ragged = None
        if self.exchange_type in _RAGGED_EXCHANGES and p.num_shards > 1:
            cls = (
                OneShotExchange
                if self.exchange_type == ExchangeType.UNBUFFERED
                else RaggedExchange
            )
            kw = {"mesh": mesh} if cls is OneShotExchange else {}
            self._ragged = cls(
                p.num_sticks_per_shard, p.local_z_lengths, p.z_offsets,
                S, L, Z, plane_slots, slot_of_stick, **kw,
            )
        self._ragged_wire = self._ragged_wire_format()

        # OVERLAPPED discipline (see DistributedExecution): C stick-chunk
        # collectives pipelined against the neighbor chunks' z matmuls —
        # padded wire formats only, clamped to the stick extent.
        from .execution import chunk_ranges

        if self._ragged is not None or p.num_shards <= 1:
            self._overlap = 1
        else:
            self._overlap = max(1, min(int(overlap), S))
        self._chunks = chunk_ranges(S, self._overlap)

        # ---- per-shard value copy plans (deduped lax.switch branches) ----
        self._build_value_branches()

        # ---- sharded constants + compiled pipelines ----
        self.value_sharding = NamedSharding(mesh, P(FFT_AXIS, None))
        self.space_sharding = NamedSharding(mesh, P(FFT_AXIS, None, None, None))
        # per-shard alignment-rotation phase tables (see _build_value_branches),
        # sharded so each device holds only its own (S, Z) slab; the compact
        # ("delta") rep needs no operands — tables generate in-trace
        if self._align_rep is not None and self._align_rep[0] == "table":
            phase_sharding = NamedSharding(mesh, P(FFT_AXIS, None, None))
            self._align_phase = tuple(
                jax.device_put(t, phase_sharding) for t in self._align_rep[1:]
            )
        else:
            self._align_phase = None
        specs_v = P(FFT_AXIS, None)
        specs_s = P(FFT_AXIS, None, None, None)
        from .mesh import shard_mapper

        sm = shard_mapper(mesh)

        specs_p = P(FFT_AXIS, None, None)
        phase_specs = () if self._align_phase is None else (specs_p, specs_p)

        self._backward_sm = sm(
            self._backward_impl,
            in_specs=(specs_v, specs_v, *phase_specs),
            out_specs=(specs_s, specs_s) if not r2c else specs_s,
        )
        self._backward = jax.jit(self._backward_sm)
        self._forward_sm = {
            s: sm(
                functools.partial(self._forward_impl, scaling=s),
                in_specs=(
                    (specs_s, specs_s, *phase_specs)
                    if not r2c
                    else (specs_s, *phase_specs)
                ),
                out_specs=(specs_v, specs_v),
            )
            for s in (ScalingType.NONE, ScalingType.FULL)
        }
        self._forward = {s: jax.jit(f) for s, f in self._forward_sm.items()}

        # Stage-graph IR (spfft_tpu.ir): see DistributedExecution.__init__.
        from ..ir.compile import init_engine_ir

        self._ir = init_engine_ir(self, fuse)

    @property
    def is_r2c(self) -> bool:
        return self.params.transform_type == TransformType.R2C

    # ---- introspection (spfft_tpu.obs plan cards) -----------------------------

    def _y_stage_scope(self) -> str:
        """The canonical named-scope label of the engaged y-DFT variant
        (obs.STAGES): sparse, blocked and dense pipelines carry distinct
        labels so profiler traces attribute them unambiguously."""
        if self._sparse_y:
            return "y transform sparse"
        if self._sparse_y_blocked is not None:
            return "y transform blocked"
        return "y transform"

    def describe(self) -> dict:
        """Engine fragment of the plan card (obs.plancard): the distributed
        MXU engine's measured decisions."""
        from ..ops.fft import describe_sparse_y

        sparse_y = describe_sparse_y(
            self._sparse_y,
            self._sparse_y_blocked,
            self._sy if self._sparse_y else 0,
        )
        return {
            "pipeline": "matmul DFT stages + lane-copy value plans (shard_map)",
            "overlap_chunks": int(self._overlap),
            "matmul_precision": str(self._precision).rsplit(".", 1)[-1],
            "num_x_active": int(self._num_x_active),
            "dim_x_freq": int(self.params.dim_x_freq),
            "sparse_y": sparse_y,
            "plane_slots": int(self._plane_slots),
            "alignment_rotations": self._align_rep is not None,
            "value_plan_branches": len(self._decompress_branches),
            "padded_geometry": {
                "s_max": int(self._S),
                "l_max": int(self._L),
                "v_max": int(self._V),
            },
            "uniform_z": bool(self._uniform_z),
        }

    def lowered_backward(self):
        """Lower (without compiling) the backward pipeline — the obs layer's
        hook for compiled-program stats (obs.hlo.compiled_stats)."""
        p = self.params
        v = jax.ShapeDtypeStruct(
            (p.num_shards, self._V), self.real_dtype, sharding=self.value_sharding
        )
        return self._backward.lower(v, v, *self._phase_args())

    # ---- wire + exchange (shared machinery in MxuValuePlans) ------------------

    def _exchange(self, bre, bim):
        """(P, S, L) pair -> all_to_all over the mesh axis, one collective."""
        return self._exchange_pair(bre, bim, FFT_AXIS)

    def _unpack_freq(self, rre, rim):
        """(P, S, L) received stick blocks -> the compact frequency planes
        ((L, Y, A), the sparse-y (A, Sy, L) table, or the blocked (rb, L)
        bucket flats) through the global stick slot tables — the padded
        unpack shared by the bulk-synchronous and OVERLAPPED chunk paths."""
        L, Y, A = self._L, self.params.dim_y, self._num_x_active
        rt = self.real_dtype
        rows_re = jnp.concatenate([rre.reshape(-1, L), jnp.zeros((1, L), rt)])
        rows_im = jnp.concatenate([rim.reshape(-1, L), jnp.zeros((1, L), rt)])
        if self._sparse_y:
            m = jnp.asarray(self._row_stick)
            gre = jnp.take(rows_re, m, axis=0).reshape(A, self._sy, L)
            gim = jnp.take(rows_im, m, axis=0).reshape(A, self._sy, L)
        elif self._sparse_y_blocked is not None:
            gre, gim = rows_re, rows_im  # bucket gathers follow per bucket
        else:
            m = jnp.asarray(self._yx_stick)
            gre = jnp.take(rows_re, m, axis=0).T.reshape(L, Y, A)
            gim = jnp.take(rows_im, m, axis=0).T.reshape(L, Y, A)
        return gre, gim

    def _forward_slot_map(self):
        """The static per-stick plane-slot map the forward pack gathers
        through (variant-dependent: sparse-y table rows, blocked bucket
        flats, or the compact (y, x) slots)."""
        if self._sparse_y:
            return self._stick_row
        if self._sparse_y_blocked is not None:
            return self._stick_row_b
        return self._stick_yx

    def _forward_flats(self, gre, gim):
        """Flattened plane rows (+ the zero sentinel row) the forward pack
        gathers through — shared by the bulk pack and the OVERLAPPED
        per-chunk packs (the per-stick slot map is resolved separately via
        :meth:`_forward_slot_map`)."""
        L, Y, A = self._L, self.params.dim_y, self._num_x_active
        rt = self.real_dtype
        if self._sparse_y:
            flat_re = jnp.concatenate(
                [gre.reshape(A * self._sy, L), jnp.zeros((1, L), rt)]
            )
            flat_im = jnp.concatenate(
                [gim.reshape(A * self._sy, L), jnp.zeros((1, L), rt)]
            )
        elif self._sparse_y_blocked is not None:
            flat_re = jnp.concatenate([gre, jnp.zeros((1, L), rt)])
            flat_im = jnp.concatenate([gim, jnp.zeros((1, L), rt)])
        else:
            flat_re = jnp.concatenate(
                [gre.reshape(L, Y * A).T, jnp.zeros((1, L), rt)]
            )
            flat_im = jnp.concatenate(
                [gim.reshape(L, Y * A).T, jnp.zeros((1, L), rt)]
            )
        return flat_re, flat_im

    # ---- pipeline stage bodies -------------------------------------------------
    # One per-shard implementation per stage, shared by the monolithic impls
    # below (bulk AND overlapped paths) and the IR node fns lowered from
    # this engine (spfft_tpu.ir.lower).

    def _st_decompress(self, values_re, values_im):
        rt = self.real_dtype
        shard = jax.lax.axis_index(FFT_AXIS)
        return jax.lax.switch(
            jnp.asarray(self._branch_of_shard)[shard],
            self._decompress_branches,
            values_re.astype(rt),
            values_im.astype(rt),
        )

    def _st_stick_symmetry(self, sre, sim):
        p = self.params
        i = p.zero_stick_row
        fre, fim = symmetry.hermitian_fill_1d_pair(sre[i], sim[i], axis=0)
        own = jax.lax.axis_index(FFT_AXIS) == p.zero_stick_shard
        return (
            sre.at[i].set(jnp.where(own, fre, sre[i])),
            sim.at[i].set(jnp.where(own, fim, sim[i])),
        )

    def _st_phase_hoist(self):
        """Per-direction alignment-phase tables for the OVERLAPPED chunk
        paths: the delta rep's in-trace (S, Z) table generation is hoisted
        out of the chunk loop — once per direction, chunks slice — exactly
        the PR-7 discipline (table-form reps already arrive hoisted as
        staged operands; everything else resolves to ``(None, None)``)."""
        return self._phase_tables(jax.lax.axis_index(FFT_AXIS), self.real_dtype)

    def _st_z_backward(self, sre, sim, phase_re=None, phase_im=None, zwin=None):
        """z matmul (+ alignment-phase undo, fused multiply) over stick
        window ``zwin`` (bulk path: the full extent)."""
        prec, rt = self._precision, self.real_dtype
        c0, c1 = (0, self._S) if zwin is None else zwin
        shard = jax.lax.axis_index(FFT_AXIS)
        cre, cim = offt.complex_matmul(
            sre[c0:c1], sim[c0:c1], *self._wz_b, "sz,zk->sk", prec
        )
        cos_t, sin_t = self._phase_tables(shard, rt, phase_re, phase_im)
        if cos_t is not None:
            cre, cim = lanecopy.apply_alignment_phase(
                cre, cim, cos_t[c0:c1], sin_t[c0:c1], -1
            )
        return cre, cim

    def _st_pack(self, cre, cim):
        """(W, Z) z-matmul'd stick pair -> (P, W, L) exchange blocks — any
        stick window (bulk W == S; OVERLAPPED chunks pass their windows)."""
        p = self.params
        L = self._L
        W = cre.shape[0]
        if not self._uniform_z:
            zmap = jnp.asarray(self._pack_z)
            cre = jnp.take(cre, zmap, axis=1, mode="fill", fill_value=0)
            cim = jnp.take(cim, zmap, axis=1, mode="fill", fill_value=0)
        return (
            cre.reshape(W, p.num_shards, L).transpose(1, 0, 2),
            cim.reshape(W, p.num_shards, L).transpose(1, 0, 2),
        )

    def _st_unpack(self, *recvs):
        """Received block pair(s) -> compact frequency planes; chunk
        receives (first half re, second half im) reassemble the padded
        stick stack first."""
        k = len(recvs) // 2
        rre = recvs[0] if k == 1 else jnp.concatenate(recvs[:k], axis=1)
        rim = recvs[k] if k == 1 else jnp.concatenate(recvs[k:], axis=1)
        return self._unpack_freq(rre, rim)

    def _st_ragged_exchange_backward(self, sre, sim):
        # (nslots, L) slot-major plane rows (round-5 row-granular contract)
        # — same orientation family as the padded unpack
        p = self.params
        rt = self.real_dtype
        A, Y, L = self._num_x_active, p.dim_y, self._L
        fre, fim = self._ragged.backward(
            (sre, sim), wire=self._ragged_wire, real_dtype=rt
        )
        if self._sparse_y:
            return fre.reshape(A, self._sy, L), fim.reshape(A, self._sy, L)
        if self._sparse_y_blocked is not None:
            return fre, fim  # (rb, L) bucket flats
        return (
            fre.reshape(Y, A, L).transpose(2, 0, 1),
            fim.reshape(Y, A, L).transpose(2, 0, 1),
        )

    def _st_plane_symmetry(self, gre, gim):
        """The standalone R2C x==0 hermitian fills (ragged blocked flats or
        the dense slot-0 plane); the padded blocked path's fill rides inside
        the y stage instead (:meth:`_st_y_backward`)."""
        Y = self.params.dim_y
        if self._sparse_y_blocked is not None:
            # blocked flats (rb, L): the dense x0 bucket occupies rows
            # [off, off+Y) in natural y order
            o = self._sy_x0_flat
            pre, pim = symmetry.hermitian_fill_1d_pair(
                gre[o : o + Y], gim[o : o + Y], axis=0
            )
            return gre.at[o : o + Y].set(pre), gim.at[o : o + Y].set(pim)
        pre, pim = symmetry.hermitian_fill_1d_pair(
            gre[:, :, 0], gim[:, :, 0], axis=1
        )
        return gre.at[:, :, 0].set(pre), gim.at[:, :, 0].set(pim)

    def _st_y_backward(self, gre, gim):
        """The engaged y-variant contraction (per-slot sparse, per-bucket
        blocked — padded blocked includes the x0 fill — or dense)."""
        prec = self._precision
        L, A = self._L, self._num_x_active
        if self._sparse_y:
            # per-slot y contraction straight off the stick table (both
            # exchange paths deliver the same (A, Sy, L) orientation)
            return offt.complex_matmul(
                gre, gim, *self._wy_b_sp, "ajl,ajk->lka", prec
            )
        if self._sparse_y_blocked is not None:
            # per-bucket contractions; bucket-major slot concatenation
            # (the x matrices fold the slot permutation)
            outs_re, outs_im = [], []
            off = 0
            for b, (row_idx, wyb, _) in enumerate(self._sparse_y_blocked):
                Ag, Syg = row_idx.shape
                if self._ragged is not None:
                    bre = gre[off : off + Ag * Syg].reshape(Ag, Syg, L)
                    bim = gim[off : off + Ag * Syg].reshape(Ag, Syg, L)
                else:
                    idx = jnp.asarray(row_idx)
                    bre, bim = gre[idx], gim[idx]  # (Ag, Syg, L)
                    if b == self._sy_x0_bucket:
                        # R2C: hermitian-complete the dense x0 plane
                        # along y before its y-DFT (see plane symmetry)
                        fre, fim = symmetry.hermitian_fill_1d_pair(
                            bre[0], bim[0], axis=0
                        )
                        bre, bim = fre[None], fim[None]
                ore, oim = offt.complex_matmul(
                    bre, bim, *wyb, "ajl,ajk->lka", prec
                )
                outs_re.append(ore)
                outs_im.append(oim)
                off += Ag * Syg
            gre = jnp.concatenate(outs_re, axis=2)
            gim = jnp.concatenate(outs_im, axis=2)
            if gre.shape[2] < A:  # compact_x_extent padding slots
                padw = A - gre.shape[2]
                gre = jnp.pad(gre, ((0, 0), (0, 0), (0, padw)))
                gim = jnp.pad(gim, ((0, 0), (0, 0), (0, padw)))
            return gre, gim
        return offt.complex_matmul(gre, gim, *self._wy_b, "lyx,yk->lkx", prec)

    def _st_x_backward(self, gre, gim):
        prec = self._precision
        if self.is_r2c:
            return offt.real_out_matmul(gre, gim, *self._wx_b, "lkx,xj->lkj", prec)
        return offt.complex_matmul(gre, gim, *self._wx_b, "lkx,xj->lkj", prec)

    def _plane_symmetry_standalone(self) -> bool:
        """Whether the R2C x==0 fill runs as its own stage (vs inside the
        padded blocked y loop) — the gate the monolithic tail and the IR
        lowering share."""
        return self.is_r2c and self._have_x0 and not (
            self._sparse_y_blocked is not None and self._ragged is None
        )

    def _st_x_forward(self, space_re, space_im=None):
        prec, rt = self._precision, self.real_dtype
        if self.is_r2c:
            return offt.real_in_matmul(
                space_re.astype(rt), *self._wx_f, "lyx,xk->lyk", prec
            )
        return offt.complex_matmul(
            space_re.astype(rt), space_im.astype(rt),
            *self._wx_f, "lyx,xk->lyk", prec,
        )

    def _st_y_forward(self, gre, gim):
        prec = self._precision
        L = self._L
        if self._sparse_y:
            # per-slot y contraction straight into the stick table (both
            # exchange paths consume the same (A, Sy, L) orientation)
            return offt.complex_matmul(
                gre, gim, *self._wy_f_sp, "lyk,kjy->kjl", prec
            )
        if self._sparse_y_blocked is not None:
            # per-bucket contractions into (rb, L) bucket flats (the
            # orientation both exchange paths consume)
            flats_re, flats_im = [], []
            col = 0
            for row_idx, _, wyf in self._sparse_y_blocked:
                Ag, Syg = row_idx.shape
                fre_b, fim_b = offt.complex_matmul(
                    gre[:, :, col : col + Ag], gim[:, :, col : col + Ag],
                    *wyf, "lyk,kjy->kjl", prec,
                )
                flats_re.append(fre_b.reshape(Ag * Syg, L))
                flats_im.append(fim_b.reshape(Ag * Syg, L))
                col += Ag
            return (
                jnp.concatenate(flats_re, axis=0),
                jnp.concatenate(flats_im, axis=0),
            )
        return offt.complex_matmul(gre, gim, *self._wy_f, "lyk,yj->ljk", prec)

    def _st_forward_flats(self, gre, gim):
        return self._forward_flats(gre, gim)

    def _st_pack_fwd(self, flat_re, flat_im, c0=0, c1=None):
        """Forward pack window ``[c0, c1)`` off the hoisted plane flats ->
        (P, W, L) block pair — bulk path and OVERLAPPED chunks share it."""
        p = self.params
        S, L = self._S, self._L
        c1 = S if c1 is None else c1
        m = self._forward_slot_map()
        mc = jnp.asarray(m.reshape(p.num_shards, S)[:, c0:c1].reshape(-1))
        return (
            jnp.take(flat_re, mc, axis=0).reshape(p.num_shards, c1 - c0, L),
            jnp.take(flat_im, mc, axis=0).reshape(p.num_shards, c1 - c0, L),
        )

    def _st_unpack_fwd(self, rre, rim):
        """(P, W, L) received blocks -> (W, Z) stick z-rows — any window."""
        p = self.params
        L = self._L
        W = rre.shape[1]
        cre = rre.transpose(1, 0, 2).reshape(W, p.num_shards * L)
        cim = rim.transpose(1, 0, 2).reshape(W, p.num_shards * L)
        if not self._uniform_z:
            zmap = jnp.asarray(self._unpack_z)
            cre = jnp.take(cre, zmap, axis=1)
            cim = jnp.take(cim, zmap, axis=1)
        return cre, cim

    def _st_z_forward(
        self, cre, cim, scaling, phase_re=None, phase_im=None, zwin=None
    ):
        prec, rt = self._precision, self.real_dtype
        c0, c1 = (0, self._S) if zwin is None else zwin
        shard = jax.lax.axis_index(FFT_AXIS)
        cos_t, sin_t = self._phase_tables(shard, rt, phase_re, phase_im)
        if cos_t is not None:
            # enter the rotated layout on the space side (fused multiply)
            cre, cim = lanecopy.apply_alignment_phase(
                cre, cim, cos_t[c0:c1], sin_t[c0:c1], +1
            )
        return offt.complex_matmul(
            cre, cim, *self._wz_f[ScalingType(scaling)], "sz,zk->sk", prec
        )

    def _st_concat_pair(self, *parts):
        k = len(parts) // 2
        if k == 1:
            return parts[0], parts[1]
        return (
            jnp.concatenate(parts[:k], axis=0),
            jnp.concatenate(parts[k:], axis=0),
        )

    def _st_ragged_exchange_forward(self, gre, gim):
        p = self.params
        rt = self.real_dtype
        A, Y, L = self._num_x_active, p.dim_y, self._L
        if self._sparse_y:
            fre = gre.reshape(A * self._sy, L)
            fim = gim.reshape(A * self._sy, L)
        elif self._sparse_y_blocked is not None:
            fre, fim = gre, gim  # (rb, L) already
        else:
            fre = gre.reshape(L, Y * A).T
            fim = gim.reshape(L, Y * A).T
        return self._ragged.forward(
            (fre, fim), wire=self._ragged_wire, real_dtype=rt
        )

    def _st_compress(self, sre, sim):
        shard = jax.lax.axis_index(FFT_AXIS)
        return jax.lax.switch(
            jnp.asarray(self._branch_of_shard)[shard],
            self._compress_branches, sre, sim,
        )

    # ---- pipelines (traced once; run per-shard under shard_map) ---------------

    def _backward_impl(self, values_re, values_im, phase_re=None, phase_im=None):
        p = self.params
        pre = None if phase_re is None else phase_re[0]
        pim = None if phase_im is None else phase_im[0]

        with jax.named_scope("compression"):
            sre, sim = self._st_decompress(values_re[0], values_im[0])

        if self.is_r2c and p.zero_stick_shard >= 0:
            with jax.named_scope("stick symmetry"):
                sre, sim = self._st_stick_symmetry(sre, sim)

        if self._overlap > 1:
            # OVERLAPPED discipline: per-chunk z matmul -> pack -> collective
            # with no cross-chunk dependence, so chunk k's wire time can hide
            # behind chunk k+1's matmuls (see DistributedExecution)
            if pre is None:
                pre, pim = self._st_phase_hoist()  # delta-rep hoist
            recvs_re, recvs_im = [], []
            for c0, c1 in self._chunks:
                with jax.named_scope("z transform"):
                    cre, cim = self._st_z_backward(
                        sre, sim, pre, pim, zwin=(c0, c1)
                    )
                with jax.named_scope("pack"):
                    bre, bim = self._st_pack(cre, cim)
                with jax.named_scope("exchange overlapped"):
                    rc_re, rc_im = self._exchange(bre, bim)
                recvs_re.append(rc_re)
                recvs_im.append(rc_im)
            with jax.named_scope("unpack"):
                gre, gim = self._st_unpack(*recvs_re, *recvs_im)
            return self._backward_tail(gre, gim)

        with jax.named_scope("z transform"):
            sre, sim = self._st_z_backward(sre, sim, pre, pim)

        if self._ragged is not None:
            # exact-counts exchange straight into the compact planes (or the
            # sparse-y (A, Sy) stick table — the slot space the exchange was
            # built over)
            with jax.named_scope("exchange"):
                gre, gim = self._st_ragged_exchange_backward(sre, sim)
        else:
            # pack: (S, Z) -> (P, S, L) exchange blocks
            with jax.named_scope("pack"):
                bre, bim = self._st_pack(sre, sim)

            with jax.named_scope("exchange"):
                rre, rim = self._exchange(bre, bim)

            # expand: (P*S, L) global stick rows -> compact freq planes
            # ((L, Y, A), or the (A, Sy, L) table when sparse-y is engaged)
            with jax.named_scope("unpack"):
                gre, gim = self._st_unpack(rre, rim)

        return self._backward_tail(gre, gim)

    def _backward_tail(self, gre, gim):
        """Plane symmetry + y/x DFT stages of the backward pipeline over the
        compact frequency planes — shared by the bulk-synchronous paths and
        the OVERLAPPED chunk path (all of which deliver the same plane
        orientation; the ragged/padded distinction only matters for the
        blocked sparse-y layout, where the OVERLAPPED path follows the
        padded convention by construction)."""
        if self._plane_symmetry_standalone():
            with jax.named_scope("plane symmetry"):
                gre, gim = self._st_plane_symmetry(gre, gim)

        with jax.named_scope(self._y_stage_scope()):
            gre, gim = self._st_y_backward(gre, gim)
        with jax.named_scope("x transform"):
            out = self._st_x_backward(gre, gim)
        if self.is_r2c:
            return out[None]
        return out[0][None], out[1][None]

    def _forward_impl(self, space_re, *rest, scaling):
        if self.is_r2c:
            space_im = None
            phase = rest  # () or (phase_re, phase_im)
        else:
            space_im, phase = rest[0], rest[1:]
        phase_re, phase_im = phase if phase else (None, None)
        pre = None if phase_re is None else phase_re[0]
        pim = None if phase_im is None else phase_im[0]

        with jax.named_scope("x transform"):
            if self.is_r2c:
                gre, gim = self._st_x_forward(space_re[0])
            else:
                gre, gim = self._st_x_forward(space_re[0], space_im[0])
        with jax.named_scope(self._y_stage_scope()):
            gre, gim = self._st_y_forward(gre, gim)

        if self._overlap > 1:
            # OVERLAPPED discipline (forward direction): chunk k's received
            # stick z-chunks run their z matmuls while chunk k+1's collective
            # is in flight — the mirror of the backward chunk pipeline
            if pre is None:
                pre, pim = self._st_phase_hoist()  # delta-rep hoist
            flat_re, flat_im = self._st_forward_flats(gre, gim)
            parts_re, parts_im = [], []
            for c0, c1 in self._chunks:
                with jax.named_scope("pack"):
                    bre, bim = self._st_pack_fwd(flat_re, flat_im, c0, c1)
                with jax.named_scope("exchange overlapped"):
                    rre, rim = self._exchange(bre, bim)
                with jax.named_scope("unpack"):
                    cre, cim = self._st_unpack_fwd(rre, rim)
                with jax.named_scope("z transform"):
                    cre, cim = self._st_z_forward(
                        cre, cim, scaling, pre, pim, zwin=(c0, c1)
                    )
                parts_re.append(cre)
                parts_im.append(cim)
            sre, sim = self._st_concat_pair(*parts_re, *parts_im)
        elif self._ragged is not None:
            with jax.named_scope("exchange"):
                sre, sim = self._st_ragged_exchange_forward(gre, gim)
        else:
            # pack: gather every global stick's compact plane slot (or sparse-y
            # table row) from my planes
            with jax.named_scope("pack"):
                flat_re, flat_im = self._st_forward_flats(gre, gim)
                bre, bim = self._st_pack_fwd(flat_re, flat_im)

            with jax.named_scope("exchange"):
                rre, rim = self._exchange(bre, bim)

            # unpack: (P, S, L) my sticks' z chunks -> (S, Z)
            with jax.named_scope("unpack"):
                sre, sim = self._st_unpack_fwd(rre, rim)

        if self._overlap == 1:
            with jax.named_scope("z transform"):
                sre, sim = self._st_z_forward(sre, sim, scaling, pre, pim)

        with jax.named_scope("compression"):
            vre, vim = self._st_compress(sre, sim)
        return vre[None], vim[None]

    # ---- device-side entry points ---------------------------------------------

    def _phase_args(self):
        return () if self._align_phase is None else self._align_phase

    def backward_pair(self, values_re, values_im):
        """(P, V_max) freq pairs -> space slabs (P, L, Y, X) (pair for C2C).
        Routed through the IR runtime (see DistributedExecution)."""
        return self._ir.run_backward(values_re, values_im, *self._phase_args())

    def backward_pair_batch(self, values_re, values_im):
        """Batched variant (see PaddingHelpers): this engine threads its
        alignment-phase operands instead of a value-index table."""
        return self._ir.run_backward_batch(
            values_re, values_im, *self._phase_args()
        )

    def forward_pair_batch(
        self, space_re, space_im, scaling: ScalingType = ScalingType.NONE
    ):
        s = ScalingType(scaling)
        if self.is_r2c:
            return self._ir.run_forward_batch(s, space_re, *self._phase_args())
        return self._ir.run_forward_batch(
            s, space_re, space_im, *self._phase_args()
        )

    def _dispatch_forward(self, table, space_re, space_im, scaling):
        fn = table[ScalingType(scaling)]
        if self.is_r2c:
            return fn(space_re, *self._phase_args())
        return fn(space_re, space_im, *self._phase_args())

    def forward_pair(self, space_re, space_im, scaling: ScalingType = ScalingType.NONE):
        """(P, L, Y, X) space slabs -> (P, V_max) freq pairs."""
        s = ScalingType(scaling)
        if self.is_r2c:
            return self._ir.run_forward(s, space_re, *self._phase_args())
        return self._ir.run_forward(s, space_re, space_im, *self._phase_args())

    # Un-jitted traceables (see LocalExecution.trace_backward for rationale).

    def trace_backward(self, values_re, values_im, phase=()):
        del phase  # mesh engines keep per-shard reps internal (no operands)
        return self._backward_sm(values_re, values_im, *self._phase_args())

    def trace_forward(
        self, space_re, space_im, scaling: ScalingType = ScalingType.NONE, phase=()
    ):
        del phase
        return self._dispatch_forward(self._forward_sm, space_re, space_im, scaling)

