"""Measured auto-policy for ``ExchangeType.DEFAULT``.

The reference hardwires DEFAULT to COMPACT_BUFFERED (reference:
src/spfft/grid_internal.cpp:176-179) — a folklore pick. This build already
computes the exact wire volume and round count of every discipline from plan
geometry (``exchange_wire_bytes`` / ``exchange_rounds``), so DEFAULT resolves
them through a cost model instead:

    cost(d) = wire_bytes(d) + rounds(d) * round_cost_bytes

``round_cost_bytes`` is the latency of one sequential collective round
expressed in byte-equivalents (latency x bandwidth). The default, 128 KiB,
comes from ICI-class numbers (~1-2 us/round at ~100 GB/s); override with
``SPFFT_TPU_EXCH_ROUND_COST_KB``. Grounding against the measured CPU-mesh
tables (BASELINE.md "Exchange-discipline comparison"): the model picks
BUFFERED for every balanced row and UNBUFFERED for the stick-imbalanced
rows on backends with the one-shot ragged-all-to-all (exact rows, 1 round —
the TPU transport). Since the round-5 row-granular transport, the COMPACT
chain's constant (maxn, Lm) windows tie BUFFERED's volume while costing P-1
rounds, so DEFAULT never resolves to COMPACT — the enum remains for API
parity and as the portable exact-rows transport where ragged-all-to-all
does not compile. Decision-grade ICI wall-clock needs pod hardware (VERDICT r3 item 5);
until then the constant is the documented, overridable part of the policy.

Explicit disciplines are never overridden — the policy runs only for DEFAULT.
"""
from __future__ import annotations

import numpy as np

from .. import knobs
from ..types import ExchangeType

ROUND_COST_ENV = "SPFFT_TPU_EXCH_ROUND_COST_KB"

# ---- communication/compute overlap (the OVERLAPPED exchange discipline) -----
#
# Chunk count of the chunked, double-buffered exchange pipelines: the padded
# single-collective disciplines (BUFFERED and its *_FLOAT/*_BF16 wire
# variants) split each repartition into C independent chunk collectives so
# chunk k's wire time can hide behind chunk k+1's FFTs (the pipelined
# all-to-all designs of arxiv.org/pdf/1804.09536 / arxiv.org/pdf/2306.16589).
# 1 = the classic bulk-synchronous exchange. Resolved per plan: explicit
# ``overlap=`` argument, else SPFFT_TPU_OVERLAP_CHUNKS, else 1 — and under
# ``policy="tuned"`` the autotuner owns the knob (tuning/candidates.py
# enumerates overlap variants and wisdom remembers the measured winner).
OVERLAP_ENV = "SPFFT_TPU_OVERLAP_CHUNKS"


def resolve_overlap_chunks(overlap=None) -> int:
    """The requested exchange-overlap chunk count: explicit argument, else
    the ``SPFFT_TPU_OVERLAP_CHUNKS`` env knob, else 1 (no chunking). Engines
    clamp the request to what their geometry supports (chunkable extent,
    padded discipline, P > 1) — this resolves intent, not feasibility."""
    from ..errors import InvalidParameterError

    if overlap is None:
        overlap = knobs.get_int(OVERLAP_ENV)
    overlap = int(overlap)
    if overlap < 1:
        raise InvalidParameterError(
            f"overlap chunk count must be >= 1, got {overlap}"
        )
    return overlap

# ---- plan-decision policies -------------------------------------------------
#
# "default": this module's analytic cost model resolves ExchangeType.DEFAULT
#            and the engines' static auto rules pick everything else.
# "tuned":   the spfft_tpu.tuning subsystem measures the alternatives on the
#            caller's real geometry/mesh/dtype and remembers winners in the
#            persistent wisdom store (SPFFT_TPU_WISDOM) — falling back to
#            "default" where trials cannot run (see tuning module docstring).
#
# Selected per plan via the Transform/DistributedTransform ``policy=``
# argument, or process-wide via SPFFT_TPU_POLICY.
POLICY_ENV = "SPFFT_TPU_POLICY"
POLICIES = ("default", "tuned")


def resolve_policy(policy=None) -> str:
    """The active plan-decision policy: explicit argument, else the
    ``SPFFT_TPU_POLICY`` env knob, else ``"default"``."""
    if policy is None:
        policy = knobs.get_str(POLICY_ENV)
    policy = str(policy)
    if policy not in POLICIES:
        from ..errors import InvalidParameterError

        raise InvalidParameterError(
            f"unknown policy {policy!r}: expected one of {POLICIES}"
        )
    return policy


def resolve_default_for_plan(params, mesh, real_dtype) -> ExchangeType:
    """Full model resolution of ``ExchangeType.DEFAULT`` for a 1-D slab plan:
    :func:`resolve_default_exchange` evaluated under both one-shot-support
    answers, probing the backend (compile-only, cached — parallel/ragged.py)
    only when the two disagree. The single home shared by plan construction
    (distributed.py) and the TUNED policy's model fallback (spfft_tpu.tuning).
    """
    picks = {
        supported: resolve_default_exchange(
            params.num_sticks_per_shard,
            params.local_z_lengths,
            one_shot_supported=supported,
            wire_scalar_bytes=np.dtype(real_dtype).itemsize,
        )
        for supported in (False, True)
    }
    if picks[False] == picks[True] or params.num_shards <= 1:
        return picks[False]
    from .ragged import _ragged_a2a_supported

    return picks[_ragged_a2a_supported(mesh)]


def discipline_volumes(num_sticks_per_shard, local_z_lengths):
    """Exchange-A complex-element volumes per repartition, self-blocks excluded.

    Returns ``{BUFFERED, COMPACT_BUFFERED, UNBUFFERED: off-wire elems}`` from
    plan geometry alone (matches the engines' accounting:
    PaddingHelpers.exchange_wire_bytes, parallel/ragged.py offwire_elems) —
    all three reflecting the round-5 ROW-GRANULAR transports:

    - BUFFERED: P(P-1) uniform S_max x L_max padded blocks.
    - COMPACT: the ppermute chain's constant (S_max x L_max) 2-D windows
      (the engines' _chain_step_sizes rule — single source so the cost
      model cannot diverge from what actually rides the wire; ties
      BUFFERED's volume, see the ragged module docstring).
    - UNBUFFERED: exact rows x the full L_max row width,
      ``sum_{i != j} sticks_i * L_max`` (the ragged-all-to-all unit is an
      L_max-wide row).
    """
    from .ragged import _chain_step_sizes

    s = np.asarray(num_sticks_per_shard, dtype=np.int64)
    l = np.asarray(local_z_lengths, dtype=np.int64)
    P = int(s.size)
    if P <= 1:
        return {
            ExchangeType.BUFFERED: 0,
            ExchangeType.COMPACT_BUFFERED: 0,
            ExchangeType.UNBUFFERED: 0,
        }
    Lm = int(max(1, l.max()))
    buffered = P * (P - 1) * int(s.max()) * Lm
    oneshot = (P - 1) * int(s.sum()) * Lm
    b_bwd, _ = _chain_step_sizes(s, l)
    compact = P * sum(b_bwd[1:])
    return {
        ExchangeType.BUFFERED: buffered,
        ExchangeType.COMPACT_BUFFERED: compact,
        ExchangeType.UNBUFFERED: oneshot,
    }


def round_cost_bytes() -> int:
    """Per-round latency in byte-equivalents (see module docstring)."""
    return knobs.get_int(ROUND_COST_ENV) << 10


def alternative_costs(
    num_sticks_per_shard,
    local_z_lengths,
    *,
    one_shot_supported: bool,
    wire_scalar_bytes: int = 4,
) -> dict:
    """The full accounting table behind :func:`resolve_default_exchange`:
    ``{discipline: {"wire_bytes", "rounds", "cost_bytes"}}`` for the three
    base disciplines under this plan geometry and wire width. This is what
    plan cards embed as the chosen-vs-rejected exchange record (the
    ``exchange_policy`` section, spfft_tpu/obs/plancard.py), so the card and
    the resolver can never disagree — both read this one table.
    """
    s = np.asarray(num_sticks_per_shard)
    P = int(s.size)
    vols = discipline_volumes(num_sticks_per_shard, local_z_lengths)
    per_round = round_cost_bytes()
    rounds = {
        ExchangeType.BUFFERED: 1,
        ExchangeType.COMPACT_BUFFERED: max(1, P - 1),
        ExchangeType.UNBUFFERED: 1 if one_shot_supported else max(1, P - 1),
    }
    if not one_shot_supported:
        # The chain transport ships per-step-maxima buffers, not the exact
        # Alltoallw volume — cost what actually rides the wire (ragged.py
        # OneShotExchange falls back to the same _chain_step_sizes rule).
        vols[ExchangeType.UNBUFFERED] = vols[ExchangeType.COMPACT_BUFFERED]
    return {
        d: {
            "wire_bytes": vols[d] * 2 * wire_scalar_bytes,
            "rounds": rounds[d],
            "cost_bytes": vols[d] * 2 * wire_scalar_bytes
            + rounds[d] * per_round,
        }
        for d in vols
    }


def resolve_default_exchange(
    num_sticks_per_shard,
    local_z_lengths,
    *,
    one_shot_supported: bool,
    wire_scalar_bytes: int = 4,
) -> ExchangeType:
    """Pick the discipline for ``ExchangeType.DEFAULT`` from plan geometry.

    ``one_shot_supported``: whether the backend compiles the one-shot
    ragged-all-to-all (parallel/ragged.py:_ragged_a2a_supported); without it
    UNBUFFERED's transport degrades to the P-1-round chain and is costed as
    such. ``wire_scalar_bytes``: bytes per real scalar on the wire (4 for f32,
    8 for f64, 2 for the *_FLOAT half-wire variants' bf16).
    """
    s = np.asarray(num_sticks_per_shard)
    P = int(s.size)
    if P <= 1:
        return ExchangeType.BUFFERED
    costs = {
        d: row["cost_bytes"]
        for d, row in alternative_costs(
            num_sticks_per_shard,
            local_z_lengths,
            one_shot_supported=one_shot_supported,
            wire_scalar_bytes=wire_scalar_bytes,
        ).items()
    }
    # Deterministic tie-break: the fused single collective is the ICI-native
    # shape; then the one-shot exact exchange — unless its transport would be
    # the chain anyway, where COMPACT is the honest name for the same wire
    # behavior.
    if one_shot_supported:
        order = (
            ExchangeType.BUFFERED,
            ExchangeType.UNBUFFERED,
            ExchangeType.COMPACT_BUFFERED,
        )
    else:
        order = (
            ExchangeType.BUFFERED,
            ExchangeType.COMPACT_BUFFERED,
            ExchangeType.UNBUFFERED,
        )
    return min(order, key=lambda d: costs[d])
