"""Multi-host serving that survives host loss: heartbeats, requeue, typing.

The cross-host front of the serving layer (ROADMAP item 2, the DaggerFFT
distributed task-scheduling shape, arxiv 2601.12209): a
:class:`ClusterFront` owns one bounded admission queue — so admission,
per-tenant quotas, deadlines and fair-share shedding span the whole fleet —
and dispatches coalesced same-geometry chunks to worker hosts (each one a
:class:`~spfft_tpu.serve.service.TransformService` behind a
:class:`~spfft_tpu.serve.rpc.RpcServer`) through the task-graph scheduler.
Three pieces make host death a *typed, recoverable* event instead of an
untyped hang:

1. **Liveness** (:class:`HeartbeatMonitor`): one daemon thread pings every
   live host each ``SPFFT_TPU_HOSTS_HEARTBEAT_S`` interval (inter-sweep
   sleeps jittered ×[0.5, 1.5) so fleet heartbeats never synchronize);
   ``SPFFT_TPU_HOSTS_HEARTBEAT_MISSES`` consecutive failures declare the
   host lost (``hosts_lost_total{host}``). A dead transport on a live
   dispatch declares it immediately — the monitor is the *slow-path*
   detector for hosts that die while idle.
2. **Requeue** (:class:`RemotePlan` + the scheduler's ``host_lost`` rung):
   dispatches cross the wire as scheduler tasks whose plan is a
   :class:`RemotePlan`; a transport death surfaces as typed
   :class:`~spfft_tpu.errors.HostLostError`, and
   :mod:`spfft_tpu.sched.executor` requeues the in-flight task onto a
   surviving host (``rehost()``, bounded by ``SPFFT_TPU_HOSTS_RETRIES``
   with jittered ``SPFFT_TPU_HOSTS_BACKOFF_S`` backoff) before resolving
   it typed with the ``host_lost`` outcome — dependents cascade
   ``upstream_failed`` exactly like any other failed dependency.
3. **Accounting**: every admitted request's ticket resolves on every path
   (the serving layer's no-deadlock contract, now spanning processes);
   ``offered == completed + refused + failed`` holds exactly through a
   SIGKILLed worker (``./ci.sh mhost`` proves it), every ``host_lost``
   rung lands on the geometry entry's card and in the degradation
   counters.

The ``rpc.submit`` fault site fires in the dispatch path and
``host.heartbeat`` in the monitor's probe path, so worker-kill chaos is a
first-class armed scenario (docs/details.md "Multi-host serving & host
loss").

**Cross-host observability** (docs/details.md "Observability", layer 6):
the front mints one trace run ID per admitted request and ships it on the
wire (``runs`` in the ``submit_batch`` frame); the worker records its spans
under that key and the reply carries back a compact trace *segment* per
request, which :meth:`RemotePlan._finalize` splices into the front's own
flight recorder tagged ``host=`` — one ``trace.snapshot()`` on the front
shows both sides of every dispatch under the submitting request's run ID.
Tickets carry monotonic phase stamps (``admitted -> coalesced ->
dispatched -> wire -> remote_execute -> finalized``) feeding the
``serve_phase_seconds{phase}`` histograms, and :meth:`ClusterFront.describe`
joins a fleet metrics document (:func:`spfft_tpu.obs.fleet.fleet_snapshot`
over the ``metrics`` RPC op, lost hosts skipped typed).
"""
from __future__ import annotations

import collections
import hashlib
import random
import threading
import time

import numpy as np

from .. import faults, knobs, obs, sched
from ..errors import (
    GenericError,
    HostLostError,
    InvalidParameterError,
)
from ..types import ScalingType, TransformType
from .errors import DeadlineExceededError, ServiceOverloadError, as_typed
from .queue import AdmissionQueue, Request
from .rpc import RpcClient
from .service import (
    SERVE_BACKOFF_ENV,
    SERVE_BATCH_MAX_ENV,
    SERVE_QUEUE_CAP_ENV,
    SERVE_RETRIES_ENV,
    SERVE_TENANT_QUOTA_ENV,
    SERVE_TIMEOUT_ENV,
    _batch_chunks,
)

HEARTBEAT_ENV = "SPFFT_TPU_HOSTS_HEARTBEAT_S"
HEARTBEAT_MISSES_ENV = "SPFFT_TPU_HOSTS_HEARTBEAT_MISSES"
HOST_RETRIES_ENV = "SPFFT_TPU_HOSTS_RETRIES"
HOST_BACKOFF_ENV = "SPFFT_TPU_HOSTS_BACKOFF_S"


class HostHandle:
    """One worker host: its RPC client plus liveness state."""

    def __init__(self, name: str, address: str, *, timeout_s=None):
        self.name = str(name)
        self.address = str(address)
        self.client = RpcClient(address, timeout_s=timeout_s)
        self._lock = threading.Lock()
        self.lost = False
        self.lost_reason = None
        self.misses = 0

    def beat_ok(self) -> None:
        with self._lock:
            self.misses = 0

    def beat_missed(self) -> int:
        with self._lock:
            self.misses += 1
            return self.misses

    def mark_lost(self, reason: str) -> bool:
        """Idempotent; True when THIS call transitioned the host to lost."""
        with self._lock:
            if self.lost:
                return False
            self.lost = True
            self.lost_reason = str(reason)
            return True

    def describe(self) -> dict:
        with self._lock:
            return {
                "name": self.name,
                "address": self.address,
                "lost": self.lost,
                "lost_reason": self.lost_reason,
                "heartbeat_misses": self.misses,
            }


class HeartbeatMonitor:
    """Jittered liveness sweeps over a :class:`ClusterFront`'s hosts.

    One daemon thread; each sweep pings every not-yet-lost host with the
    sweep interval as the probe's wall deadline (bounded waits everywhere),
    counts ``host_heartbeats_total{verdict}``, and declares a host lost
    after the configured consecutive misses. The ``host.heartbeat`` fault
    site fires before each probe, so chaos runs exercise the miss ladder
    without a real dead host."""

    def __init__(self, front, *, interval_s=None, misses=None):
        self.front = front
        self.interval_s = knobs.get_float(HEARTBEAT_ENV, interval_s)
        self.misses = knobs.get_int(HEARTBEAT_MISSES_ENV, misses)
        self._stop = threading.Event()
        self._rng = random.Random()
        self._started = False
        self._thread = threading.Thread(
            target=self._loop, name="spfft-host-heartbeat", daemon=True
        )

    def start(self) -> None:
        self._started = True
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._started:
            self._thread.join(2.0)

    def _loop(self) -> None:
        while not self._stop.is_set():
            for handle in self.front.hosts:
                if handle.lost or self._stop.is_set():
                    continue
                try:
                    faults.site("host.heartbeat")
                    handle.client.call(
                        {"op": "ping"}, timeout_s=self.interval_s
                    )
                except (GenericError, faults.InjectedFault) as e:
                    n = handle.beat_missed()
                    obs.counter(
                        "host_heartbeats_total", verdict="missed"
                    ).inc()
                    obs.trace.event(
                        "host", what="missed", host=handle.name, misses=n
                    )
                    if n >= self.misses:
                        self.front._mark_lost(
                            handle,
                            f"missed {n} consecutive heartbeats: "
                            f"{faults.summarize(e)}",
                        )
                else:
                    handle.beat_ok()
                    obs.counter("host_heartbeats_total", verdict="ok").inc()
            # jittered inter-sweep sleep: a fleet of fronts never herds its
            # probes (the backoff_s jitter rule, applied to liveness)
            self._stop.wait(self.interval_s * (0.5 + self._rng.random()))


class _RpcPending:
    """In-flight RPC dispatch: the scheduler's pending handle.

    Runs the blocking client call on its own daemon thread so the
    executor's dispatch returns immediately; ``is_ready()`` feeds the
    completion-order finalize poll, ``result()`` re-raises transport
    failures as :class:`HostLostError` and application failures as their
    own taxonomy members."""

    def __init__(self, client: RpcClient, msg: dict, timeout_s: float):
        self._client = client
        self._msg = msg
        self._timeout_s = float(timeout_s)
        self._event = threading.Event()
        self._reply = None
        self._error = None
        self.expected = 0  # payload count; _finalize validates the reply
        self._thread = threading.Thread(
            target=self._run, name="spfft-rpc-call", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        try:
            self._reply = self._client.call(self._msg)
        except GenericError as e:
            self._error = e
        except Exception as e:  # noqa: BLE001 — count + convert: the
            # pending handle must NEVER swallow a failure (an unresolved
            # handle would wedge finalize), so anything unexpected becomes
            # the typed execution surface
            obs.counter("execution_failures_total", op="rpc pending").inc()
            self._error = as_typed(e, "cpu")
        finally:
            self._event.set()

    def is_ready(self) -> bool:
        return self._event.is_set()

    def result(self) -> dict:
        if not self._event.wait(self._timeout_s + 1.0):
            raise HostLostError(
                f"host {self._client.address} RPC call outlived its "
                f"{self._timeout_s:.1f}s deadline"
            )
        if self._error is not None:
            raise self._error
        return self._reply


class RemotePlan:
    """Scheduler-compatible plan adapter executing on a remote host.

    Implements exactly the split-phase surface
    :mod:`spfft_tpu.sched.executor` drives (``_dispatch_* / _finalize_*``,
    batch and single forms) by shipping the geometry entry's requests as
    one ``submit_batch`` RPC per dispatch, plus the ``rehost()`` hook the
    executor's ``host_lost`` rung calls to requeue in-flight work onto a
    surviving host. Unsupervised and unguarded by construction — the worker
    host's own service applies its ladder remotely."""

    _verifier = None
    _guard = False
    device = None

    def __init__(self, front, entry, handle: HostHandle, requests=None):
        self.front = front
        self.entry = entry
        self.handle = handle
        # the chunk's admitted requests, payload-aligned: their run IDs ride
        # the wire frame and their tickets take the wire/remote_execute
        # phase stamps (None for ad-hoc plans built without requests)
        self.requests = list(requests) if requests is not None else []

    # ---- host-loss requeue hook ---------------------------------------------

    def rehost(self, error) -> None:
        """Move this plan to a surviving host (the scheduler's requeue
        rung): marks the current host lost, picks a live one (typed
        :class:`HostLostError` when none remain), and records the
        ``host_lost`` rung on the geometry entry's card."""
        lost = self.handle
        self.front._mark_lost(lost, faults.summarize(error))
        self.handle = self.front._pick_host()
        self.entry.record_degradation(
            "host_lost",
            faults.summarize(error),
            host=lost.name,
            rehomed_to=self.handle.name,
        )

    # ---- dispatch/finalize surface ------------------------------------------

    def _msg(self, direction: str, payloads: list, scaling) -> dict:
        e = self.entry
        msg = {
            "op": "submit_batch",
            "transform_type": int(e.transform_type.value),
            "dims": list(e.dims),
            "indices": e.indices,
            "direction": direction,
            "scaling": int(ScalingType(scaling).value),
            "tenant": "cluster",
            "timeout_s": None,
            "payloads": [np.asarray(p) for p in payloads],
        }
        if len(self.requests) == len(payloads):
            # trace propagation: the worker records its spans under the
            # caller's run IDs and the reply carries them back as segments
            msg["runs"] = [r.run for r in self.requests]
        return msg

    def _dispatch(self, direction: str, payloads: list, scaling):
        # the RPC transport's fault checkpoint: an injected failure here
        # models the submit machinery dying and must degrade through the
        # scheduler's typed ladder (retry -> requeue -> host_lost)
        faults.site("rpc.submit")
        for req in self.requests:
            req.ticket.stamp("wire")  # first-wins: a rehosted re-dispatch
            # keeps the ORIGINAL time the request hit the wire
        pending = _RpcPending(
            self.handle.client,
            self._msg(direction, payloads, scaling),
            self.handle.client.timeout_s,
        )
        pending.expected = len(payloads)
        return pending

    def _finalize(self, pending: _RpcPending) -> list:
        """The worker's per-entry reply, request-aligned: each member is a
        result array OR the member's own taxonomy error (held as a value —
        the front resolves tickets per member, so one refused request never
        discards or re-executes its completed peers). A malformed or
        short reply is a TRANSPORT failure (typed :class:`HostLostError`,
        feeding the requeue ladder): a results list shorter than the
        payloads sent would otherwise leave tail tickets unresolved
        forever."""
        from .rpc import raise_error_payload

        reply = pending.result()
        for req in self.requests:
            req.ticket.stamp("remote_execute")
        self._splice_spans(reply.get("spans"))
        results = reply.get("results")
        if not isinstance(results, list) or len(results) != pending.expected:
            got = len(results) if isinstance(results, list) else "no"
            raise HostLostError(
                f"host {pending._client.address} returned a malformed "
                f"submit_batch reply ({got} results for "
                f"{pending.expected} payloads)"
            )
        out = []
        for row in results:
            err = row.get("error")
            if err is not None:
                try:
                    raise_error_payload(err)
                except GenericError as e:
                    out.append(e)
                continue
            out.append(np.asarray(row["result"]))
        return out

    def _splice_spans(self, spans) -> None:
        """Splice the reply's remote trace segments into the front's flight
        recorder, tagged with the worker's host name (the cross-host run-ID
        join). Segments are advisory: a missing or malformed one never
        fails the request — splice() skips invalid events itself."""
        if not isinstance(spans, list):
            return
        n = 0
        for seg in spans:
            if seg:
                n += obs.trace.splice(seg, host=self.handle.name)
        if n:
            obs.counter(
                "remote_spans_spliced_total", host=self.handle.name
            ).inc(n)

    def _dispatch_backward_batch(self, payloads):
        return self._dispatch("backward", payloads, ScalingType.NONE)

    def _dispatch_forward_batch(self, payloads, scaling):
        return self._dispatch("forward", payloads, scaling)

    def _finalize_backward_batch(self, pending):
        return self._finalize(pending)

    def _finalize_forward_batch(self, pending):
        return self._finalize(pending)

    def _dispatch_backward(self, payload):
        return self._dispatch("backward", [payload], ScalingType.NONE)

    def _dispatch_forward(self, payload, scaling):
        return self._dispatch("forward", [payload], scaling)

    def _single(self, pending):
        value = self._finalize(pending)[0]
        if isinstance(value, GenericError):
            raise value
        return value

    def _finalize_backward(self, pending):
        return self._single(pending)

    def _finalize_forward(self, pending):
        return self._single(pending)


class _GeomEntry:
    """One coalescing geometry of the front: identity + card."""

    def __init__(self, digest, transform_type, dims, indices):
        self.digest = digest
        self.transform_type = transform_type
        self.dims = tuple(int(d) for d in dims)
        self.indices = np.ascontiguousarray(indices, dtype=np.int32)
        self._lock = threading.Lock()
        self.card = {
            "digest": digest,
            "transform_type": transform_type.name,
            "dims": list(self.dims),
            "num_values": int(len(self.indices)),
            "degradations": [],
        }

    def record_degradation(self, event: str, reason: str, **extra) -> None:
        entry = faults.record_degradation(event, reason, **extra)
        with self._lock:
            self.card["degradations"].append(entry)

    def append_degradation(self, entry: dict) -> None:
        """Attach an already-recorded (counted/traced) degradation entry —
        a fleet-level event like a host loss lands on every geometry card
        without double-counting ``degradations_total``."""
        with self._lock:
            self.card["degradations"].append(dict(entry))

    def describe(self) -> dict:
        with self._lock:
            return {
                **{k: v for k, v in self.card.items() if k != "degradations"},
                "degradations": list(self.card["degradations"]),
            }


class ClusterFront:
    """Fleet-spanning admission + dispatch over RPC worker hosts.

    One bounded :class:`AdmissionQueue` (quotas, deadlines, fair-share
    shedding — the single backpressure surface of the whole fleet), one
    dispatcher (daemon thread, or caller-driven :meth:`pump`), one
    :class:`HeartbeatMonitor`. Coalesced same-geometry chunks execute as
    scheduler batch tasks on :class:`RemotePlan`\\ s spread round-robin over
    the live hosts; the scheduler owns per-task retries and the host-loss
    requeue ladder. Every ticket resolves typed on every path — a SIGKILLed
    worker mid-flight degrades through ``host_lost``, never an untyped
    hang."""

    def __init__(
        self,
        addresses,
        *,
        queue_capacity: int | None = None,
        tenant_quota: float | None = None,
        default_timeout_s: float | None = None,
        batch_max: int | None = None,
        retries: int | None = None,
        backoff_s: float | None = None,
        host_retries: int | None = None,
        host_backoff_s: float | None = None,
        heartbeat_s: float | None = None,
        heartbeat_misses: int | None = None,
        rpc_timeout_s: float | None = None,
        start: bool = True,
    ):
        addresses = list(addresses)
        if not addresses:
            raise InvalidParameterError(
                "ClusterFront needs at least one worker host address"
            )
        self.hosts = [
            HostHandle(f"host{i}", addr, timeout_s=rpc_timeout_s)
            for i, addr in enumerate(addresses)
        ]
        self.queue_capacity = knobs.get_int(SERVE_QUEUE_CAP_ENV, queue_capacity)
        quota = knobs.get_float(SERVE_TENANT_QUOTA_ENV, tenant_quota)
        self.default_timeout_s = knobs.get_float(
            SERVE_TIMEOUT_ENV, default_timeout_s
        )
        self.batch_max = knobs.get_int(SERVE_BATCH_MAX_ENV, batch_max)
        self.retries = knobs.get_int(SERVE_RETRIES_ENV, retries)
        self.backoff_s = knobs.get_float(SERVE_BACKOFF_ENV, backoff_s)
        self.host_retries = knobs.get_int(HOST_RETRIES_ENV, host_retries)
        self.host_backoff_s = knobs.get_float(HOST_BACKOFF_ENV, host_backoff_s)
        self.queue = AdmissionQueue(self.queue_capacity, quota)
        self.queue.on_shed = lambda tenant: self._count("shed", tenant)
        self._entries: dict = {}
        self._entries_lock = threading.Lock()
        self._rr = 0
        self._rr_lock = threading.Lock()
        self._counts: collections.Counter = collections.Counter()
        self._counts_lock = threading.Lock()
        self.degradations: list = []
        self._deg_lock = threading.Lock()
        self._retry_rng = random.Random()
        self._closing = False
        self.monitor = HeartbeatMonitor(
            self, interval_s=heartbeat_s, misses=heartbeat_misses
        )
        self._worker = None
        if start:
            self.monitor.start()
            self._worker = threading.Thread(
                target=self._dispatch_loop,
                name="spfft-cluster-dispatch",
                daemon=True,
            )
            self._worker.start()

    # ---- host liveness -------------------------------------------------------

    def live_hosts(self) -> list:
        return [h for h in self.hosts if not h.lost]

    def _pick_host(self) -> HostHandle:
        """Round-robin over the live hosts; typed when none remain."""
        live = self.live_hosts()
        if not live:
            raise HostLostError(
                f"no live worker hosts remain (all {len(self.hosts)} lost)"
            )
        with self._rr_lock:
            handle = live[self._rr % len(live)]
            self._rr += 1
        return handle

    def _mark_lost(self, handle: HostHandle, reason: str) -> None:
        """Declare one host lost (idempotent): counted once, traced, a
        ``host_lost`` degradation recorded on the front."""
        if not handle.mark_lost(reason):
            return
        obs.counter("hosts_lost_total", host=handle.name).inc()
        obs.trace.event(
            "host", what="lost", host=handle.name, reason=str(reason)[:200]
        )
        entry = faults.record_degradation(
            "host_lost", str(reason), host=handle.name
        )
        with self._deg_lock:
            self.degradations.append(entry)
        # the rung lands on every geometry card: a host loss degrades the
        # whole fleet's capacity, and a capture's cards must show it even
        # when no in-flight chunk happened to be requeued
        with self._entries_lock:
            entries = list(self._entries.values())
        for geom in entries:
            geom.append_degradation(entry)
        handle.client.close()

    # ---- submission ----------------------------------------------------------

    def submit(
        self,
        transform_type,
        dims,
        indices,
        payload,
        *,
        direction: str = "backward",
        tenant: str = "default",
        timeout_s: float | None = None,
        scaling: ScalingType = ScalingType.NONE,
    ):
        """Admit one request into the fleet; returns its ticket without
        waiting (the same contract as
        :meth:`~spfft_tpu.serve.service.TransformService.submit`, minus
        plan building — workers own plans). Each request gets its own trace
        run ID: the worker host records its spans under the same key (the
        ``runs`` wire field) and the reply splices them back, so the
        request's whole cross-host life joins on one run."""
        tenant = str(tenant)
        run = obs.trace.new_run_id()
        try:
            if self._closing:
                obs.counter("serve_sheds_total", reason="closing").inc()
                raise ServiceOverloadError("cluster front is closing")
            if direction not in ("backward", "forward"):
                raise InvalidParameterError(
                    f"unknown direction {direction!r}: expected "
                    "backward/forward"
                )
            deadline = self._resolve_deadline(timeout_s)
            if deadline is not None and deadline <= time.monotonic():
                raise DeadlineExceededError(
                    "request deadline expired before admission"
                )
            ttype = TransformType(transform_type)
            dims = tuple(int(d) for d in dims)
            if len(dims) != 3:
                raise InvalidParameterError(
                    "dims must be (dim_x, dim_y, dim_z)"
                )
            entry = self._ensure_entry(ttype, dims, indices)
            payload = self._stage_payload(entry, direction, payload)
            request = Request(
                tenant=tenant, direction=direction,
                scaling=ScalingType(scaling), plan_key=entry.digest,
                payload=payload, order_map=None, deadline=deadline,
                run=run,
            )
            self.queue.admit(request)
        except Exception:
            self._count("rejected", tenant)
            with obs.trace.with_run(run):
                obs.trace.event("serve", what="reject", tenant=tenant)
            raise
        with obs.trace.with_run(run):
            obs.trace.event(
                "serve", what="admit", tenant=tenant, direction=direction
            )
        self._count("admitted", tenant)
        return request.ticket

    def _resolve_deadline(self, timeout_s):
        if timeout_s is None:
            timeout_s = self.default_timeout_s
        timeout_s = float(timeout_s)
        if timeout_s <= 0:
            return None
        return time.monotonic() + timeout_s

    def _ensure_entry(self, ttype, dims, indices) -> _GeomEntry:
        trip = np.ascontiguousarray(indices, dtype=np.int32)
        if trip.ndim != 2 or trip.shape[1] != 3:
            raise InvalidParameterError(
                f"indices must be (V, 3) int triplets, got shape "
                f"{trip.shape}"
            )
        h = hashlib.sha1()
        h.update(ttype.name.encode())
        h.update(np.asarray(dims, dtype=np.int64).tobytes())
        h.update(trip.tobytes())
        digest = h.hexdigest()
        with self._entries_lock:
            entry = self._entries.get(digest)
            if entry is None:
                entry = _GeomEntry(digest, ttype, dims, trip)
                self._entries[digest] = entry
        return entry

    def _stage_payload(self, entry: _GeomEntry, direction: str, payload):
        if direction == "backward":
            values = np.asarray(payload).reshape(-1)
            if values.size != len(entry.indices):
                raise InvalidParameterError(
                    f"expected {len(entry.indices)} frequency values, got "
                    f"{values.size}"
                )
            return values
        space = np.asarray(payload)
        expect = int(np.prod(entry.dims))
        if space.size != expect:
            raise InvalidParameterError(
                f"expected a {entry.dims[2]}x{entry.dims[1]}x"
                f"{entry.dims[0]} space slab ({expect} elements), got "
                f"{space.size}"
            )
        return space.reshape(entry.dims[2], entry.dims[1], entry.dims[0])

    # ---- dispatch ------------------------------------------------------------

    def pump(self, max_batches: int | None = None) -> int:
        """Drain coalesced batches synchronously (``start=False`` fronts)."""
        if self._worker is not None and self._worker.is_alive():
            raise InvalidParameterError(
                "pump() on a threaded cluster front: the dispatcher owns "
                "the queue"
            )
        processed = 0
        while max_batches is None or processed < max_batches:
            limit = 2 * max(1, len(self.live_hosts()))
            if max_batches is not None:
                limit = min(limit, max_batches - processed)
            batches = self._pop_batches(limit, timeout=0.0)
            if not batches:
                break
            self._process(batches)
            processed += len(batches)
        return processed

    def _dispatch_loop(self) -> None:
        while True:
            batches = self._pop_batches(
                2 * max(1, len(self.live_hosts())), timeout=0.05
            )
            if not batches:
                if self._closing:
                    return
                continue
            self._process(batches)

    def _pop_batches(self, limit: int, timeout: float) -> list:
        batch = self.queue.pop_batch(self.batch_max, timeout=timeout)
        if not batch:
            return []
        batches = [batch]
        while len(batches) < max(1, int(limit)):
            more = self.queue.pop_batch(self.batch_max, timeout=0.0)
            if not more:
                break
            batches.append(more)
        return batches

    def _process(self, batches: list) -> None:
        """One dispatch cycle, resolving every ticket (the catch-all
        no-deadlock contract of :meth:`TransformService._process_batch`,
        spanning hosts)."""
        try:
            self._process_inner(batches)
        except Exception as e:  # noqa: BLE001 — see _process_batch docstring
            err = as_typed(e, "cpu")
            for batch in batches:
                for req in batch:
                    if req.ticket.fail(err):
                        self._count("failed", req.tenant)

    def _process_inner(self, batches: list) -> None:
        graph = sched.TaskGraph()
        jobs = []
        for batch in batches:
            obs.counter("serve_batches_total").inc()
            survivors = self._shed_expired(batch)
            if not survivors:
                continue
            with self._entries_lock:
                entry = self._entries[batch[0].plan_key]
            for chunk in _batch_chunks(survivors, self.batch_max):
                try:
                    # one RemotePlan per chunk: no shared-object edges, so
                    # chunks spread across hosts and run concurrently
                    plan = RemotePlan(
                        self, entry, self._pick_host(), requests=chunk
                    )
                except HostLostError as e:
                    for req in chunk:
                        if req.ticket.fail(e):
                            self._count("failed", req.tenant)
                            self._count_only("host_lost")
                            # no survivors left: each request's trace still
                            # closes TYPED under its own run ID
                            with obs.trace.with_run(req.run):
                                obs.trace.event(
                                    "error", what="host_lost",
                                    tenant=req.tenant,
                                )
                    continue
                deadlines = [r.deadline for r in chunk]
                obs.histogram("serve_batch_occupancy").observe(len(chunk))
                tid = graph.add(
                    chunk[0].direction,
                    payload=[r.payload for r in chunk],
                    scaling=chunk[0].scaling,
                    transform=plan,
                    deadline=None
                    if any(d is None for d in deadlines)
                    else max(deadlines),
                    batch=True,
                )
                jobs.append((tid, chunk))
        if not jobs:
            return
        obs.trace.event(
            "serve", what="dispatch", engine="cluster", occupancy=len(jobs),
            attempt=0,
        )
        for _tid, chunk in jobs:
            for req in chunk:
                req.ticket.stamp("dispatched")
        report = sched.run_graph(
            graph, retries=self.retries, demote=False, on_error="resolve",
            backoff_s=self.backoff_s, backoff_rng=self._retry_rng,
            host_retries=self.host_retries,
            host_backoff_s=self.host_backoff_s,
        )
        for tid, chunk in jobs:
            outcome = report.outcomes[tid]
            err = report.errors.get(tid)
            if outcome == "completed":
                results = report.results[tid]
                now = time.monotonic()
                for req, res in zip(chunk, results):
                    if isinstance(res, GenericError):
                        # the member's OWN typed failure from the worker
                        # (refusal, deadline, execution error), held as a
                        # value so its completed peers resolve normally
                        if isinstance(res, DeadlineExceededError):
                            self._shed_one(req, res)
                        elif req.ticket.fail(res):
                            self._count("failed", req.tenant)
                        continue
                    if req.expired(now):
                        # the chunk ran under its LATEST member's deadline;
                        # an individually-expired member still lands as a
                        # deadline miss (the per-request contract)
                        self._shed_one(req)
                        continue
                    if req.ticket.resolve(res):
                        self._observe_completion(req)
            elif isinstance(err, DeadlineExceededError):
                for req in chunk:
                    self._shed_one(req, err)
            else:
                if outcome == "host_lost":
                    self._count_only("host_lost")
                    for req in chunk:
                        # the request's trace closes TYPED under its own
                        # run: a SIGKILLed worker reads as host_lost in the
                        # per-request timeline, never a silent gap
                        with obs.trace.with_run(req.run):
                            obs.trace.event(
                                "error", what="host_lost", tenant=req.tenant
                            )
                err = (
                    as_typed(err, "cpu") if err is not None
                    else ServiceOverloadError("cluster task unresolved")
                )
                for req in chunk:
                    if req.ticket.fail(err):
                        self._count("failed", req.tenant)

    def _shed_one(self, req, err=None) -> None:
        obs.counter("serve_deadline_misses_total", tenant=req.tenant).inc()
        obs.counter("serve_sheds_total", reason="deadline").inc()
        obs.trace.event(
            "serve", what="shed", reason="deadline", tenant=req.tenant
        )
        if req.ticket.fail(
            err
            if err is not None
            else DeadlineExceededError(
                "request deadline expired inside a cluster dispatch"
            ),
            outcome="deadline_miss",
        ):
            self._count("deadline_miss", req.tenant)

    def _shed_expired(self, batch: list) -> list:
        now = time.monotonic()
        survivors = []
        for req in batch:
            if req.expired(now):
                self._shed_one(req)
            else:
                survivors.append(req)
        return survivors

    def _observe_completion(self, req) -> None:
        self._count("completed", req.tenant)
        obs.counter(
            "serve_requests_total", tenant=req.tenant, outcome="completed"
        ).inc()
        latency = req.ticket.latency_s()
        if latency is not None:
            obs.histogram(
                "serve_latency_seconds", tenant=req.tenant
            ).observe(latency)
        # the dispatcher thread's completion event joins the caller's trace
        with obs.trace.with_run(req.run):
            obs.trace.event("serve", what="complete", tenant=req.tenant)

    # ---- bookkeeping ---------------------------------------------------------

    def _count(self, outcome: str, tenant: str) -> None:
        with self._counts_lock:
            self._counts[outcome] += 1
        if outcome != "admitted":
            obs.counter(
                "serve_requests_total", tenant=tenant, outcome=outcome
            ).inc()

    def _count_only(self, key: str) -> None:
        with self._counts_lock:
            self._counts[key] += 1

    def stats(self) -> dict:
        with self._counts_lock:
            counts = dict(self._counts)
        return {
            "counts": counts,
            "queue_depth": self.queue.depth(),
            "queue_high_water": self.queue.high_water,
            "queue_capacity": self.queue.capacity,
            "tenant_quota_slots": self.queue.quota,
            "batch_max": self.batch_max,
            "hosts": len(self.hosts),
            "hosts_live": len(self.live_hosts()),
            "hosts_lost": len(self.hosts) - len(self.live_hosts()),
        }

    def fleet_metrics(self, timeout_s: float | None = None) -> dict:
        """The fleet's merged metrics document: every live worker host's
        ``obs.snapshot()`` scraped over the ``metrics`` RPC op and folded
        into one host-labeled ``spfft_tpu.obs.fleet/1`` document (lost
        hosts stamped and skipped — see :mod:`spfft_tpu.obs.fleet`)."""
        return obs.fleet.fleet_snapshot(self.hosts, timeout_s=timeout_s)

    def describe(self) -> dict:
        """Front configuration + host topology + per-geometry cards (each
        carrying its ``host_lost`` degradations) + the front-level
        degradation list + the merged fleet metrics document — the
        loadgen/CI provenance surface."""
        with self._entries_lock:
            entries = list(self._entries.values())
        with self._deg_lock:
            degradations = list(self.degradations)
        return {
            "config": {
                "queue_capacity": self.queue_capacity,
                "batch_max": self.batch_max,
                "tenant_quota_slots": self.queue.quota,
                "default_timeout_s": self.default_timeout_s,
                "retries": self.retries,
                "backoff_s": self.backoff_s,
                "host_retries": self.host_retries,
                "host_backoff_s": self.host_backoff_s,
                "heartbeat_s": self.monitor.interval_s,
                "heartbeat_misses": self.monitor.misses,
                "threaded": self._worker is not None,
            },
            "hosts": [h.describe() for h in self.hosts],
            "plan_cards": [e.describe() for e in entries],
            "degradations": degradations,
            "stats": self.stats(),
            "fleet": self.fleet_metrics(),
        }

    # ---- lifecycle -----------------------------------------------------------

    def close(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop the front; pending tickets drain or fail typed, never leak
        (the service close contract)."""
        self._closing = True
        self.queue.shut()
        if not drain:
            self._shed_closing()
        if self._worker is not None:
            self.queue.wake()
            self._worker.join(timeout)
            self._worker = None
        elif drain:
            self.pump()
        self._shed_closing()
        self.monitor.stop()
        for h in self.hosts:
            h.client.close()

    def _shed_closing(self) -> None:
        for req in self.queue.drain():
            obs.counter("serve_sheds_total", reason="closing").inc()
            if req.ticket.fail(
                ServiceOverloadError("cluster front closed before dispatch"),
                outcome="shed",
            ):
                self._count("shed", req.tenant)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
