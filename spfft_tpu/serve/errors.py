"""Typed serving-layer failure surface: vocabularies and conversion helpers.

The serving layer never fails untyped and never fails silently: every
admission refusal, shed, deadline miss, and execution failure resolves as a
member of the :mod:`spfft_tpu.errors` taxonomy (C-translatable through
``capi.error_code`` like the rest of the package), tagged with a reason from
the canonical vocabularies below, counted in the run-metrics registry, and
stamped into the flight recorder. The acceptance invariant of the whole
layer — *every accepted request either completes or fails typed* — rests on
these being the only ways out of the service.
"""
from __future__ import annotations

from ..errors import (  # noqa: F401  (the serving layer's error surface)
    DeadlineExceededError,
    GenericError,
    ServiceOverloadError,
)
from ..faults import execution_error, summarize

# Terminal outcomes a submitted request can reach (the ``outcome`` label of
# ``serve_requests_total{tenant,outcome}``). ``rejected`` happens at admission
# (the caller's submit raises, nothing was queued); the rest happen to
# admitted requests and resolve their tickets.
OUTCOMES = ("completed", "rejected", "shed", "deadline_miss", "failed")

# Why a request was refused or shed (the ``reason`` label of
# ``serve_sheds_total{reason}``):
#   queue_full    — bounded admission queue at capacity, no sheddable peer
#   tenant_quota  — the submitting tenant is over its per-tenant queue quota
#   fair_share    — a queued request of an over-share tenant was evicted to
#                   admit an under-share tenant (noisy-neighbor protection)
#   deadline      — the request expired while queued (shed pre-dispatch)
#   breaker_open  — the engine circuit breaker is open and the service is
#                   configured to shed instead of demote
#   plan_evicted  — the request's plan-cache entry was LRU-evicted while it
#                   sat queued (cache thrash under many cold geometries)
#   closing       — the service is shutting down
SHED_REASONS = (
    "queue_full",
    "tenant_quota",
    "fair_share",
    "deadline",
    "breaker_open",
    "plan_evicted",
    "closing",
)


def as_typed(exc: BaseException, platform: str) -> GenericError:
    """Convert any execution failure into the typed error surface: typed
    :mod:`spfft_tpu.errors` exceptions pass through, anything else becomes
    the platform's execution error (``HostExecutionError`` on CPU plans,
    ``GPUFFTError`` on accelerators) with the original as ``__cause__`` —
    the same conversion rule as :func:`spfft_tpu.faults.typed_execution`,
    usable where the failure is held as a value (ticket resolution) rather
    than raised through a scope."""
    if isinstance(exc, GenericError):
        return exc
    err = execution_error(platform)(f"serving execution failed: {summarize(exc)}")
    err.__cause__ = exc
    return err
