"""Plan cache + same-geometry coalescing into batched executions.

The throughput half of the serving layer (AccFFT's framing: amortize fixed
per-dispatch cost across batched executions; arxiv 1506.07933): requests
whose sparse index sets share a stick layout resolve to ONE cached plan —
keyed like the tuning wisdom store (dims / transform type / dtype /
precision / platform / sparsity-signature digest,
:func:`spfft_tpu.tuning.wisdom.key_digest`) — and a coalesced batch of them
executes through the task-graph scheduler (:func:`spfft_tpu.sched.run_tasks`
over the same split-phase halves ``multi_transform`` pipelines: all
dispatches enqueued back-to-back, then finalized in completion order), so B
small transforms pay ~one dispatch latency instead of B.

Raggedness is handled at the *value-order* level: two callers with the same
index-triplet set pack their values in their own submission orders, so each
request carries a static whole-row permutation onto the plan's storage order
(:func:`spfft_tpu.parallel.ragged.value_order_map` — the same
static-map-over-rows discipline as the exchange transports, applied to the
request axis). Backward inputs gather through it; forward outputs scatter
back through it.

Plans are built once per geometry key and **leased** per batch: each cached
entry holds the canonical plan plus up to ``batch_max - 1`` clones (a plan
object's retained space buffer is per-object state, so a batch needs one
object per in-flight request — the same rule that makes
``multi_transform_*`` reject duplicate transform objects). The cache is LRU
over whole entries (``SPFFT_TPU_SERVE_PLANS``).

The ``serve.batch`` fault site fires at batch assembly, so chaos runs prove
a blown-up coalesce/dispatch surfaces as typed ticket failures.
"""
from __future__ import annotations

import collections
import contextlib
import threading

import numpy as np

from .. import faults, obs, sched
from ..tuning.wisdom import key_digest, sparsity_signature

# Bound on remembered per-caller value orderings per plan entry (each is one
# (V,) int array): callers with stable submission orders hit this cache on
# every request; an adversarial stream of novel orderings evicts FIFO
# instead of growing without bound.
ORDER_MAP_CACHE = 64


def wrap_triplets(indices, dims) -> np.ndarray:
    """(V, 3) triplets in storage form: centered (negative-frequency)
    coordinates wrapped modulo the dims — the representation the plans'
    storage-order triplets use, so order maps compare like with like.
    Wrapping never changes which frequency a value belongs to.

    Bounds are validated BEFORE wrapping against the union of the storage
    interval ``[0, dim)`` and the centered interval ``[dim//2 + 1 - dim,
    dim//2]`` (the package accepts both conventions per element): a typo'd
    out-of-range index must raise typed :class:`InvalidIndicesError` like
    the direct Transform path does, never silently alias onto the wrong
    frequency — the canonical plan is built from the wrapped form, which
    would otherwise bypass plan-construction validation entirely."""
    t = np.asarray(indices, dtype=np.int64).reshape(-1, 3)
    d = np.asarray([int(dims[0]), int(dims[1]), int(dims[2])], dtype=np.int64)
    lo = d // 2 + 1 - d  # centered minimum; storage minimum is 0
    hi = d - 1           # storage maximum; centered maximum is d // 2
    if t.size and bool(((t < lo[None, :]) | (t > hi[None, :])).any()):
        from ..errors import InvalidIndicesError

        bad = t[((t < lo[None, :]) | (t > hi[None, :])).any(axis=1)][0]
        raise InvalidIndicesError(
            f"frequency index triplet {tuple(int(v) for v in bad)} out of "
            f"bounds for dims {tuple(int(v) for v in d)}"
        )
    return np.mod(t, d[None, :])


def sort_triplets(wrapped: np.ndarray) -> np.ndarray:
    """Lexicographic sort of already-wrapped (V, 3) triplets — the sort half
    of :func:`canonical_triplets`, for callers (the submit hot path) that
    wrapped once and must not pay the bounds check twice."""
    return wrapped[np.lexsort((wrapped[:, 2], wrapped[:, 1], wrapped[:, 0]))]


def canonical_triplets(indices, dims) -> np.ndarray:
    """Wrapped, lexicographically sorted (V, 3) triplets — the geometry in
    layout- and sign-convention-independent form. Requests whose frequency
    SETS are equal share a canonical form, hence a plan-cache key, hence a
    coalesced batch."""
    return sort_triplets(wrap_triplets(indices, dims))


class PlanEntry:
    """One cached geometry: the canonical plan, its clone pool, and the
    per-caller value-order maps.

    The clone pool exists for the split-phase loop only (B in-flight
    split-phase requests need B plan objects — retained-buffer state is
    per-object); leasing is LAZY, so batch-fused entries — whose whole batch
    runs through ONE stacked program on the canonical plan — never build the
    ``batch_max - 1`` clones they would never use."""

    __slots__ = (
        "plan", "clones", "order_maps", "storage_triplets",
        # tuner-owned fused batch size (spfft_tpu.tuning.tuned_batch):
        # resolved lazily once per entry; _UNSET until then, then None
        # (uncapped) or the wisdom/trial-measured cap
        "batch_cap", "batch_record",
    )

    def __init__(self, plan):
        self.plan = plan
        self.clones: list = []
        self.order_maps: collections.OrderedDict = collections.OrderedDict()
        self.storage_triplets = plan._verify_triplets()
        self.batch_cap = _UNSET
        self.batch_record = None

    def lease(self, n: int, build_clone) -> list:
        """``n`` distinct plan objects for one batch (clone on demand)."""
        while 1 + len(self.clones) < n:
            self.clones.append(build_clone(self.plan))
        return [self.plan] + self.clones[: max(0, n - 1)]


_UNSET = object()  # PlanEntry.batch_cap sentinel (None is a valid cap)


class PlanCache:
    """LRU plan cache keyed like the wisdom store; thread-safe."""

    def __init__(self, build_plan, capacity: int):
        self._build = build_plan  # (canonical_triplets, key_dict) -> Transform
        self.capacity = max(1, int(capacity))
        self._lock = threading.Lock()
        self._entries: collections.OrderedDict = collections.OrderedDict()
        self._building: dict = {}  # digest -> per-build lock (see ensure)

    def key(self, transform_type, dims, canonical, *, dtype, precision,
            engine, platform) -> tuple:
        """(digest, key dict) of one request geometry — the same shape of
        key the wisdom store uses, so a serving fleet's plan population and
        its tuning wisdom line up one-to-one."""
        from ..types import TransformType

        key = {
            "kind": "serve.plan",
            "type": TransformType(transform_type).name,
            "dims": [int(d) for d in dims],
            "dtype": str(np.dtype(dtype)),
            "precision": str(precision),
            "engine": str(engine),
            "platform": str(platform),
            "sticks": sparsity_signature(canonical),
        }
        return key_digest(key), key

    def ensure(self, digest: str, key: dict, canonical, request_triplets):
        """Resolve ``digest`` to a (entry, order_map) pair, building the
        canonical plan on a miss and the caller's value-order map on first
        sight of its packing order.

        Plan construction — a JAX trace/compile, potentially seconds — runs
        OUTSIDE the global cache lock under a per-digest build latch: one
        build per key, while cache hits for other geometries (and the
        dispatcher's lookups) proceed unblocked. Admission stays O(1)
        backpressure for every tenant not waiting on exactly this cold
        geometry."""
        entry = self._lookup(digest)
        if entry is None:
            with self._build_latch(digest):
                entry = self._lookup(digest)  # a racer may have built it
                if entry is None:
                    obs.counter("serve_plan_cache_total", event="miss").inc()
                    plan = self._build(canonical, key)  # no cache lock held
                    entry = PlanEntry(plan)
                    with self._lock:
                        entry = self._entries.setdefault(digest, entry)
                        self._entries.move_to_end(digest)
                        while len(self._entries) > self.capacity:
                            self._entries.popitem(last=False)
                            obs.counter(
                                "serve_plan_cache_total", event="evict"
                            ).inc()
        return entry, self._order_map(entry, request_triplets)

    def _lookup(self, digest: str):
        """LRU-touching cache probe (counts a hit when found)."""
        with self._lock:
            entry = self._entries.get(digest)
            if entry is not None:
                obs.counter("serve_plan_cache_total", event="hit").inc()
                self._entries.move_to_end(digest)
            return entry

    @contextlib.contextmanager
    def _build_latch(self, digest: str):
        """Per-digest mutex for the build path; dropped from the table once
        no builder holds it (the table stays bounded by in-flight builds)."""
        with self._lock:
            latch = self._building.setdefault(digest, threading.Lock())
        with latch:
            try:
                yield
            finally:
                with self._lock:
                    self._building.pop(digest, None)

    def _order_map(self, entry, request_triplets):
        order_sig = sparsity_signature(request_triplets)
        # the map computation is O(V log V) numpy — done outside any lock,
        # with a double-checked insert (racers compute identical maps)
        with self._lock:
            src = entry.order_maps.get(order_sig)
            if src is not None:
                entry.order_maps.move_to_end(order_sig)
                return src
        from ..parallel.ragged import value_order_map

        src = value_order_map(entry.storage_triplets, request_triplets)
        if src is None:
            # cannot happen for equal-set triplets (the digest pinned the
            # canonical set) — guard against hash collisions
            from ..errors import InvalidParameterError

            raise InvalidParameterError(
                "plan-cache digest collision: triplet sets differ"
            )
        with self._lock:
            entry.order_maps[order_sig] = src
            entry.order_maps.move_to_end(order_sig)
            while len(entry.order_maps) > ORDER_MAP_CACHE:
                entry.order_maps.popitem(last=False)
        return src

    def get(self, digest: str):
        with self._lock:
            return self._entries.get(digest)

    def describe(self) -> list:
        """JSON-plain cache inventory: one row per entry with its wisdom-
        style key, pool width, and the plan's card run ID (the join key into
        metrics and traces)."""
        with self._lock:
            rows = []
            for digest, entry in self._entries.items():
                rows.append(
                    {
                        "digest": digest,
                        "plans": 1 + len(entry.clones),
                        "order_maps": len(entry.order_maps),
                        "run_id": entry.plan._run_id,
                        "engine": entry.plan._engine,
                        # tuner-owned fused batch cap: None = uncapped,
                        # "unresolved" = no batch dispatched yet
                        "batch_cap": (
                            "unresolved"
                            if entry.batch_cap is _UNSET
                            else entry.batch_cap
                        ),
                        # the cap's decision provenance (tuned entries only)
                        "batch_tuning": (
                            None
                            if entry.batch_record is None
                            else {
                                "provenance": entry.batch_record["provenance"],
                                "hit": entry.batch_record["hit"],
                                "choice": entry.batch_record["choice"],
                            }
                        ),
                    }
                )
            return rows

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


def run_batch(entry, requests: list, build_clone, *, batch_cap=None) -> list:
    """Execute one coalesced batch; returns per-request results in request
    value order. Verified plans (``verify=`` armed) execute supervised
    per-request — the ABFT checks are host-side anyway, and the recovery
    ladder (retry -> jnp.fft reference -> typed ``VerificationError``) must
    own each request's attempt. Unverified batches take the **batch-fused**
    path when it is live (``SPFFT_TPU_BATCH_FUSE``, :mod:`spfft_tpu.ir`):
    the whole batch — every request already bridged into plan storage order
    — stacks into ONE jitted program dispatch per direction on the canonical
    plan, in ``batch_cap``-sized chunks when the tuner capped the axis, with
    no plan clones leased at all. The rung below it is today's split-phase
    loop through the task-graph scheduler (:func:`spfft_tpu.sched.run_tasks`
    on lazily-leased clones, completion-order finalize): a failed batched
    build records ``batch_fuse_failed`` on the plan card and the loop
    answers — never a failed batch. Failure semantics are unchanged: the
    scheduler runs without its own retry/demote rungs here
    (``on_error="raise"``) because the service's retry loop and breaker
    ladder own batch recovery."""
    faults.site("serve.batch")
    direction = requests[0].direction
    obs.histogram("serve_batch_occupancy").observe(len(requests))
    obs.trace.event(
        "serve", what="coalesce", direction=direction, occupancy=len(requests)
    )
    plan = entry.plan
    supervised = plan._verifier is not None
    if not supervised:
        outs = _run_batch_fused(plan, requests, direction, batch_cap)
        if outs is not None:
            return outs
    plans = entry.lease(len(requests), build_clone)
    if direction == "backward":
        if supervised:
            outs = [p.backward(r.payload) for p, r in zip(plans, requests)]
        else:
            # window = whole batch: every dispatch enqueues back-to-back
            # before any finalize (the one-dispatch-latency contract), even
            # when batch_max exceeds the scheduler's default window
            outs = sched.run_tasks(
                plans, "backward", [r.payload for r in requests],
                max_inflight=len(requests),
            )
        return outs
    if supervised:
        outs = [p.forward(r.payload, r.scaling) for p, r in zip(plans, requests)]
    else:
        outs = sched.run_tasks(
            plans, "forward", [r.payload for r in requests],
            [r.scaling for r in requests], max_inflight=len(requests),
        )
    return [_to_request_order(r, out) for r, out in zip(requests, outs)]


def _run_batch_fused(plan, requests: list, direction: str, cap) -> list | None:
    """The batch-fused arm of :func:`run_batch`: one stacked program
    dispatch per ``cap``-sized chunk (forward additionally groups by
    scaling — the program is scaling-specialized). Returns per-request
    results, or ``None`` when the path is unavailable or took its
    ``batch_fuse_failed`` rung mid-flight (the caller's split-phase loop
    then answers; partial chunk results are discarded — correctness over
    thrift on the degraded path)."""
    if not plan._exec._ir.batch_available():
        return None
    cap = len(requests) if not cap else max(1, int(cap))
    obs.trace.event(
        "serve", what="batch_fused", direction=direction,
        occupancy=len(requests), cap=cap,
    )
    if direction == "backward":
        outs = []
        for i in range(0, len(requests), cap):
            chunk = requests[i : i + cap]
            payloads, n = _bucket_pad([r.payload for r in chunk])
            res = plan.backward_batch(payloads, fallback=False, count=n)
            if res is None:
                return None
            outs.extend(res)
        return outs
    outs: list = [None] * len(requests)
    groups: dict = {}
    for idx, r in enumerate(requests):
        groups.setdefault(r.scaling, []).append(idx)
    for scaling, idxs in groups.items():
        for j in range(0, len(idxs), cap):
            sub = idxs[j : j + cap]
            payloads, n = _bucket_pad([requests[k].payload for k in sub])
            res = plan.forward_batch(payloads, scaling, fallback=False, count=n)
            if res is None:
                return None
            for k, out in zip(sub, res):
                outs[k] = _to_request_order(requests[k], out)
    return outs


def _bucket_pad(payloads: list) -> tuple:
    """Pad a chunk's payload list to the next power of two by repeating the
    last payload; returns ``(padded, real_count)``. The batched program is
    jit-specialized per batch extent, so without bucketing a serving stream
    with fluctuating occupancy pays one XLA compile per distinct size —
    bucketing bounds the specializations to the powers of two up to
    batch_max at the cost of a few duplicate rows' compute. The real count
    rides as ``count=`` into the batch calls, so metrics/guard checks and
    returned results cover exactly the real requests."""
    n = len(payloads)
    bucket = 1
    while bucket < n:
        bucket *= 2
    return payloads + [payloads[-1]] * (bucket - n), n


def run_reference(plan, request):
    """Execute one request through the plan's ``jnp.fft`` reference rung
    (the breaker-open demotion path): a code path disjoint from the primary
    engine's dispatch, mirroring the verify supervisor's demote rung."""
    if request.direction == "backward":
        return plan._reference_backward(request.payload)
    out = plan._reference_forward(request.payload, request.scaling)
    return _to_request_order(request, out)


def _to_request_order(request, packed):
    """Scatter a plan-order packed forward result back into the caller's
    value order (``out[src] = plan_result``; see value_order_map)."""
    if request.order_map is None:
        return packed
    out = np.empty_like(np.asarray(packed))
    out[request.order_map] = packed
    return out
