"""`TransformService`: a multi-tenant, overload-safe transform service.

The serving layer's owner object (ROADMAP item 2): callers submit sparse
transforms (triplets + payload) from any thread and get back a
:class:`~spfft_tpu.serve.queue.Ticket`; a single dispatcher (a background
thread, or the caller via :meth:`TransformService.pump`) pops same-geometry
coalesced batches from the bounded admission queue and executes them through
the plan cache. Robustness is the headline — the service's behavior *under
overload* is its contract:

- **Backpressure, not latency**: the bounded queue refuses admission with
  typed :class:`ServiceOverloadError` (queue full / tenant quota) — offered
  load beyond capacity is rejected in O(1), never absorbed as unbounded
  queueing delay.
- **Deadlines, twice**: an expired deadline is refused at admission and shed
  pre-dispatch — including between retry attempts — so device time is never
  burned on an answer nobody is waiting for
  (:class:`DeadlineExceededError`, ``deadline_miss``).
- **Fair-share shedding**: one noisy tenant cannot starve the rest (see
  :mod:`spfft_tpu.serve.queue`).
- **Retry with jittered backoff**: transient typed execution failures
  (``RETRYABLE_ERRORS``) re-dispatch up to ``SPFFT_TPU_SERVE_RETRIES`` times
  with :func:`spfft_tpu.faults.backoff_s` jitter — concurrent batches
  retrying one flaky engine spread out instead of herding.
- **Breaker ladder**: a tripped verify circuit breaker
  (:mod:`spfft_tpu.verify.breaker`) on the batch's engine flips the service
  to shed-or-demote (``SPFFT_TPU_SERVE_ON_BREAKER``): ``demote`` reroutes
  requests through the plan's ``jnp.fft`` reference rung, ``shed`` fails
  them typed — never queue-and-die behind a dead engine.
- **No silent exits**: every admitted request's ticket resolves — completed,
  or failed with a typed :mod:`spfft_tpu.errors` member — on every path,
  chaos included (``./ci.sh serve``, ``tests/test_serve.py`` arm every
  ``serve.*`` fault site at 2x offered overload and assert it).

Observability rides the existing registries: per-tenant counters and latency
histograms, queue-depth gauges, batch-occupancy histograms
(``serve_*`` metrics in ``obs.snapshot()``), and ``serve`` flight-recorder
events for admit/shed/dispatch/complete transitions.
"""
from __future__ import annotations

import collections
import random
import threading
import time

import numpy as np

from .. import faults, knobs, obs, sched
from ..errors import (
    FFTWError,
    GPUFFTError,
    HostExecutionError,
    InvalidParameterError,
    MPIError,
)
from ..types import ProcessingUnit, ScalingType, TransformType
from ..ir.compile import resolve_batch_fuse
from ..verify import breaker
from .batcher import (
    PlanCache,
    _to_request_order,
    run_batch,
    run_reference,
    sort_triplets,
    wrap_triplets,
)
from .errors import DeadlineExceededError, ServiceOverloadError, as_typed
from .queue import AdmissionQueue, Request

SERVE_QUEUE_CAP_ENV = "SPFFT_TPU_SERVE_QUEUE_CAP"
SERVE_BATCH_MAX_ENV = "SPFFT_TPU_SERVE_BATCH_MAX"
SERVE_TENANT_QUOTA_ENV = "SPFFT_TPU_SERVE_TENANT_QUOTA"
SERVE_TIMEOUT_ENV = "SPFFT_TPU_SERVE_TIMEOUT_S"
SERVE_RETRIES_ENV = "SPFFT_TPU_SERVE_RETRIES"
SERVE_BACKOFF_ENV = "SPFFT_TPU_SERVE_BACKOFF_S"
SERVE_ON_BREAKER_ENV = "SPFFT_TPU_SERVE_ON_BREAKER"
SERVE_PLANS_ENV = "SPFFT_TPU_SERVE_PLANS"
SERVE_SCHED_ENV = "SPFFT_TPU_SERVE_SCHED"
SERVE_SCHED_BATCHES_ENV = "SPFFT_TPU_SERVE_SCHED_BATCHES"

# defaults live in the spfft_tpu.knobs registry (the single holder); these
# aliases keep the module's public surface stable
DEFAULT_QUEUE_CAP = knobs.default(SERVE_QUEUE_CAP_ENV)
DEFAULT_BATCH_MAX = knobs.default(SERVE_BATCH_MAX_ENV)
DEFAULT_TENANT_QUOTA = knobs.default(SERVE_TENANT_QUOTA_ENV)
DEFAULT_RETRIES = knobs.default(SERVE_RETRIES_ENV)
DEFAULT_BACKOFF_S = knobs.default(SERVE_BACKOFF_ENV)
DEFAULT_PLANS = knobs.default(SERVE_PLANS_ENV)
DEFAULT_SCHED_BATCHES = knobs.default(SERVE_SCHED_BATCHES_ENV)

# Typed execution failures one re-dispatch may heal (the verify supervisor's
# retry rule): the dual error surface's dispatch/fence conversions plus the
# collective layer. Parameter/index errors and overload/deadline refusals
# are NOT retryable — they would fail identically.
RETRYABLE_ERRORS = (HostExecutionError, GPUFFTError, MPIError, FFTWError)


def resolve_on_breaker(value: str | None = None) -> str:
    """``demote`` (reroute through the jnp.fft reference rung) or ``shed``
    (typed refusal) — what the service does with a batch whose engine's
    circuit breaker is open (``SPFFT_TPU_SERVE_ON_BREAKER``)."""
    mode = value if value is not None else knobs.get_str(SERVE_ON_BREAKER_ENV)
    if mode not in ("demote", "shed"):
        raise InvalidParameterError(
            f"invalid breaker response {mode!r}: expected 'demote' or 'shed'"
        )
    return mode


class TransformService:
    """Multi-tenant transform service over a bounded admission queue.

    One service instance owns one plan cache, one admission queue and one
    dispatcher. ``start=True`` (default) runs the dispatcher as a daemon
    thread; ``start=False`` leaves dispatch to explicit :meth:`pump` calls
    (deterministic tests, caller-owned event loops). Close with
    :meth:`close` or a ``with`` block — pending tickets are drained or
    failed typed, never leaked.

    Plan-construction keyword arguments (``engine``, ``precision``,
    ``policy``, ``guard``, ``verify``, ``dtype``, ``device``) pass through
    to every cached :class:`~spfft_tpu.transform.Transform`, so a verified
    service (``verify="on"``) runs every request under the ABFT recovery
    supervisor and a tuned one (``policy="tuned"``) resolves engines through
    wisdom."""

    def __init__(
        self,
        processing_unit=ProcessingUnit.HOST,
        *,
        dtype=None,
        engine: str = "auto",
        precision: str = "highest",
        policy: str | None = None,
        guard: bool | None = None,
        verify=None,
        device=None,
        queue_capacity: int | None = None,
        batch_max: int | None = None,
        tenant_quota: float | None = None,
        default_timeout_s: float | None = None,
        retries: int | None = None,
        backoff_s: float | None = None,
        on_breaker: str | None = None,
        plan_cache_size: int | None = None,
        sched: bool | None = None,
        sched_batches: int | None = None,
        start: bool = True,
    ):
        self._pu = ProcessingUnit(processing_unit)
        self._plan_kwargs = dict(
            dtype=dtype, engine=engine, precision=precision, policy=policy,
            guard=guard, verify=verify, device=device,
        )
        self.queue_capacity = (
            int(queue_capacity) if queue_capacity is not None
            else knobs.get_int(SERVE_QUEUE_CAP_ENV)
        )
        self.batch_max = (
            max(1, int(batch_max)) if batch_max is not None
            else knobs.get_int(SERVE_BATCH_MAX_ENV)
        )
        quota = (
            float(tenant_quota) if tenant_quota is not None
            else knobs.get_float(SERVE_TENANT_QUOTA_ENV)
        )
        self.default_timeout_s = (
            float(default_timeout_s) if default_timeout_s is not None
            else knobs.get_float(SERVE_TIMEOUT_ENV)
        )
        self.retries = (
            max(0, int(retries)) if retries is not None
            else knobs.get_int(SERVE_RETRIES_ENV)
        )
        self.backoff_s = (
            max(0.0, float(backoff_s)) if backoff_s is not None
            else knobs.get_float(SERVE_BACKOFF_ENV)
        )
        self.on_breaker = resolve_on_breaker(on_breaker)
        # graph-scheduled dispatch (spfft_tpu.sched): one dispatch cycle pops
        # up to sched_batches coalesced batches — mixed geometries included —
        # and runs them as ONE task graph, so a flood across many plan-cache
        # entries stops serializing per entry (SPFFT_TPU_SERVE_SCHED;
        # programs/loadgen.py --sched A/Bs it)
        self.sched = (
            bool(sched) if sched is not None
            else knobs.get_bool(SERVE_SCHED_ENV)
        )
        self.sched_batches = (
            max(1, int(sched_batches)) if sched_batches is not None
            else knobs.get_int(SERVE_SCHED_BATCHES_ENV)
        )
        cache_cap = (
            int(plan_cache_size) if plan_cache_size is not None
            else knobs.get_int(SERVE_PLANS_ENV)
        )
        self.queue = AdmissionQueue(self.queue_capacity, quota)
        self.queue.on_shed = lambda tenant: self._count("shed", tenant)
        self.plans = PlanCache(self._build_plan, cache_cap)
        self._retry_rng = random.Random()
        self._counts: collections.Counter = collections.Counter()
        self._counts_lock = threading.Lock()
        self._closing = False
        self._worker = None
        if start:
            self._worker = threading.Thread(
                target=self._dispatch_loop, name="spfft-serve-dispatch",
                daemon=True,
            )
            self._worker.start()

    # ---- plan construction ---------------------------------------------------

    def _build_plan(self, canonical, key):
        """Build the canonical plan of one cache entry (runs under the
        cache lock — one build per geometry key, ever)."""
        from ..transform import Transform

        return Transform(
            self._pu,
            TransformType[key["type"]],
            key["dims"][0], key["dims"][1], key["dims"][2],
            indices=canonical,
            **self._plan_kwargs,
        )

    def _clone_plan(self, plan):
        return plan.clone()

    def _platform(self) -> str:
        return "gpu" if self._pu == ProcessingUnit.GPU else "cpu"

    # ---- submission ----------------------------------------------------------

    def submit(
        self,
        transform_type,
        dims,
        indices,
        payload,
        *,
        direction: str = "backward",
        tenant: str = "default",
        timeout_s: float | None = None,
        scaling: ScalingType = ScalingType.NONE,
        run_id: str | None = None,
    ):
        """Admit one request; returns its ticket without waiting.

        ``indices`` are the caller's (V, 3) index triplets in the caller's
        packing order; ``payload`` is the packed frequency values
        (``direction="backward"``) or the ``(Z, Y, X)`` space slab
        (``direction="forward"``). Raises typed
        :class:`ServiceOverloadError` / :class:`DeadlineExceededError` on
        refusal — admission is the backpressure surface.

        ``run_id`` is the request's trace run ID (the card <-> metrics <->
        trace join key): a fresh one is minted when None, and an RPC front
        passes its CALLER's through so everything this service records joins
        under the caller's key (docs/details.md "Observability", fleet
        layer). The ID rides the request's ticket (``Ticket.run``)."""
        tenant = str(tenant)
        run = run_id if run_id is not None else obs.trace.new_run_id()
        try:
            if self._closing:
                obs.counter("serve_sheds_total", reason="closing").inc()
                raise ServiceOverloadError("service is closing")
            if direction not in ("backward", "forward"):
                raise InvalidParameterError(
                    f"unknown direction {direction!r}: expected backward/forward"
                )
            # cheap refusals BEFORE plan resolution: a request destined for
            # a typed rejection must not pay a plan build (seconds of JAX
            # trace/compile) or thrash the LRU cache on its way out — the
            # O(1)-backpressure half of the admission contract. The queue
            # re-checks both authoritatively under its own lock.
            deadline = self._resolve_deadline(timeout_s)
            if deadline is not None and deadline <= time.monotonic():
                raise DeadlineExceededError(
                    "request deadline expired before admission"
                )
            if self.queue.tenant_depth(tenant) >= self.queue.quota:
                obs.counter("serve_sheds_total", reason="tenant_quota").inc()
                raise ServiceOverloadError(
                    f"tenant {tenant!r} is over its queue quota "
                    f"({self.queue.quota} of {self.queue.capacity} slots)"
                )
            ttype = TransformType(transform_type)
            dims = tuple(int(d) for d in dims)
            if len(dims) != 3:
                raise InvalidParameterError("dims must be (dim_x, dim_y, dim_z)")
            request_triplets = wrap_triplets(indices, dims)
            canonical = sort_triplets(request_triplets)
            plan = self._plan_kwargs
            digest, key = self.plans.key(
                ttype, dims, canonical,
                dtype=plan["dtype"] if plan["dtype"] is not None else _default_dtype(),
                precision=plan["precision"], engine=plan["engine"],
                platform=self._platform(),
            )
            entry, src = self.plans.ensure(digest, key, canonical, request_triplets)
            payload = self._stage_payload(
                entry.plan, direction, payload, src, len(request_triplets)
            )
            request = Request(
                tenant=tenant, direction=direction,
                scaling=ScalingType(scaling), plan_key=digest,
                payload=payload,
                order_map=src if direction == "forward" else None,
                deadline=deadline, run=run,
            )
            try:
                self.queue.admit(request)
            except faults.InjectedFault as e:
                # the serve.admit chaos site: admission machinery death is
                # an overload-class refusal, typed like every other one
                raise ServiceOverloadError(
                    f"admission machinery failed: {faults.summarize(e)}"
                ) from e
        except Exception:
            self._count("rejected", tenant)
            with obs.trace.with_run(run):
                obs.trace.event("serve", what="reject", tenant=tenant)
            raise
        with obs.trace.with_run(run):
            obs.trace.event(
                "serve", what="admit", tenant=tenant, direction=direction
            )
        self._count("admitted", tenant)
        return request.ticket

    def backward(self, transform_type, dims, indices, values, **kw):
        """Submit one backward request and wait for its result."""
        return self.submit(
            transform_type, dims, indices, values, direction="backward", **kw
        ).result()

    def forward(self, transform_type, dims, indices, space,
                scaling: ScalingType = ScalingType.NONE, **kw):
        """Submit one forward request and wait for its packed result (in the
        caller's index order)."""
        return self.submit(
            transform_type, dims, indices, space, direction="forward",
            scaling=scaling, **kw
        ).result()

    def _resolve_deadline(self, timeout_s):
        if timeout_s is None:
            timeout_s = self.default_timeout_s
        timeout_s = float(timeout_s)
        if timeout_s <= 0:
            return None
        return time.monotonic() + timeout_s

    def _stage_payload(self, plan, direction, payload, src, num_values):
        """Validate + reorder the caller's payload into plan order (backward
        values gather through the value-order map; forward slabs pass
        through shape-checked)."""
        if direction == "backward":
            values = np.asarray(payload).reshape(-1)
            if values.size != num_values:
                raise InvalidParameterError(
                    f"expected {num_values} frequency values, got {values.size}"
                )
            return values[src]
        space = np.asarray(payload)
        expect = plan.dim_z * plan.dim_y * plan.dim_x
        if space.size != expect:
            raise InvalidParameterError(
                f"expected a {plan.dim_z}x{plan.dim_y}x{plan.dim_x} space "
                f"slab ({expect} elements), got {space.size}"
            )
        return space.reshape(plan.dim_z, plan.dim_y, plan.dim_x)

    # ---- dispatch ------------------------------------------------------------

    def pump(self, max_batches: int | None = None) -> int:
        """Drain coalesced batches synchronously (``start=False`` services);
        returns the number of batches processed. Single consumer only — a
        service with a live dispatcher thread refuses."""
        if self._worker is not None and self._worker.is_alive():
            raise InvalidParameterError(
                "pump() on a threaded service: the dispatcher owns the queue"
            )
        processed = 0
        while max_batches is None or processed < max_batches:
            if self.sched:
                limit = self.sched_batches
                if max_batches is not None:
                    limit = min(limit, max_batches - processed)
                batches = self._pop_batches(limit, timeout=0.0)
                if not batches:
                    break
                self._process_graph(batches)
                processed += len(batches)
                continue
            batch = self.queue.pop_batch(self.batch_max, timeout=0.0)
            if not batch:
                break
            self._process_batch(batch)
            processed += 1
        return processed

    def _dispatch_loop(self) -> None:
        while True:
            if self.sched:
                batches = self._pop_batches(self.sched_batches, timeout=0.05)
                if not batches:
                    if self._closing:
                        return
                    continue
                self._process_graph(batches)
                continue
            batch = self.queue.pop_batch(self.batch_max, timeout=0.05)
            if not batch:
                if self._closing:
                    return
                continue
            self._process_batch(batch)

    def _pop_batches(self, limit: int, timeout: float) -> list:
        """Up to ``limit`` coalesced batches for one graph-scheduled dispatch
        cycle: block up to ``timeout`` for the first, then drain whatever
        other groups are immediately available (mixed geometries included —
        that is the point: they stop serializing per plan-cache entry)."""
        batch = self.queue.pop_batch(self.batch_max, timeout=timeout)
        if not batch:
            return []
        batches = [batch]
        while len(batches) < max(1, int(limit)):
            more = self.queue.pop_batch(self.batch_max, timeout=0.0)
            if not more:
                break
            batches.append(more)
        return batches

    def _process_batch(self, batch: list) -> None:
        """Execute one coalesced batch end-to-end, resolving every ticket.

        The catch-all is deliberate and narrow in effect: a dispatcher that
        dies mid-batch would leave tickets pending forever (the queue-and-
        die failure mode this layer exists to remove), so ANY failure here
        resolves the whole batch's tickets with the typed conversion of the
        cause and the loop survives — the no-deadlock half of the chaos
        invariant."""
        try:
            self._process_batch_inner(batch)
        except Exception as e:  # noqa: BLE001 — see docstring
            err = as_typed(e, self._platform())
            for req in batch:
                # count only tickets THIS failure resolved: requests the
                # inner path already shed/resolved keep their first outcome
                if req.ticket.fail(err):
                    self._count("failed", req.tenant)

    def _process_batch_inner(self, batch: list) -> None:
        obs.counter("serve_batches_total").inc()
        platform = self._platform()
        entry = self.plans.get(batch[0].plan_key)
        survivors = self._shed_expired(batch)
        if not survivors:
            return
        if entry is None:  # evicted between admit and dispatch: rebuild-free shed
            err = ServiceOverloadError("plan cache entry evicted while queued")
            for req in survivors:
                obs.counter("serve_sheds_total", reason="plan_evicted").inc()
                if req.ticket.fail(err, outcome="shed"):
                    self._count("shed", req.tenant)
            return
        engine = entry.plan._engine
        supervised = entry.plan._verifier is not None
        # breaker ladder: an open breaker on this batch's engine means the
        # primary path is known-bad — shed or demote instead of queueing
        # into a dead engine. Supervised plans skip this: their recovery
        # supervisor owns the whole ladder, half-open probes included.
        # Unsupervised batches consult allow() — which performs the
        # open→half-open cooldown transition and grants THIS dispatcher the
        # probe slot — and report the execution verdict back below, so serve
        # traffic alone can heal (or re-open) a tripped breaker instead of
        # demoting forever.
        if not supervised and not breaker.allow(engine):
            self._breaker_response(survivors, engine, entry)
            return
        # From here an unsupervised dispatcher MAY hold the breaker's single
        # half-open probe slot (allow() just granted it). Every exit path
        # must settle it: success/exhaustion report verdicts inline; the
        # finally releases a verdict-carrying or verdict-less probe on the
        # remaining exits (batch fully deadline-shed mid-retry, a
        # non-retryable escape to the catch-all) so the breaker can never
        # wedge in half-open behind a lost probe.
        settled = supervised
        observed_failure = False
        try:
            attempt = 0
            while True:
                survivors = self._shed_expired(survivors)
                if not survivors:
                    return
                obs.trace.event(
                    "serve", what="dispatch", engine=engine,
                    occupancy=len(survivors), attempt=attempt,
                )
                for req in survivors:
                    req.ticket.stamp("dispatched")
                try:
                    with faults.typed_execution(platform, "serve dispatch"):
                        faults.site("serve.dispatch")
                        results = run_batch(
                            entry, survivors, self._clone_plan,
                            batch_cap=self._batch_cap(entry),
                        )
                except RETRYABLE_ERRORS as e:
                    observed_failure = True
                    attempt += 1
                    if attempt > self.retries:
                        if not supervised:
                            # an exhausted-retries episode is an engine-
                            # health signal: feed the breaker's consecutive-
                            # failure count (and settle a held probe)
                            breaker.record_failure(engine)
                            settled = True
                        err = as_typed(e, platform)
                        for req in survivors:
                            if req.ticket.fail(err):
                                self._count("failed", req.tenant)
                        return
                    obs.counter("serve_retries_total").inc()
                    self._count_only("retries")
                    # jittered exponential backoff (faults.backoff_s):
                    # concurrent batches retrying one flaky engine spread
                    # out, not herd
                    time.sleep(
                        faults.backoff_s(self.backoff_s, attempt, self._retry_rng)
                    )
                    continue
                if not supervised:
                    # execution succeeded: settle a half-open probe / reset
                    # the consecutive-failure count (supervised plans'
                    # supervisors already reported their verified verdicts)
                    breaker.record_success(engine)
                    settled = True
                for req, result in zip(survivors, results):
                    if req.ticket.resolve(result):
                        self._observe_completion(req)
                return
        finally:
            if not settled:
                if observed_failure:
                    breaker.record_failure(engine)
                else:
                    breaker.release_probe(engine)

    def _process_graph(self, batches: list) -> None:
        """Execute one graph-scheduled dispatch cycle end-to-end, resolving
        every ticket of every batch (the same catch-all no-deadlock contract
        as :meth:`_process_batch`, over the whole cycle)."""
        try:
            self._process_graph_inner(batches)
        except Exception as e:  # noqa: BLE001 — see _process_batch docstring
            err = as_typed(e, self._platform())
            for batch in batches:
                for req in batch:
                    if req.ticket.fail(err):
                        self._count("failed", req.tenant)

    def _process_graph_inner(self, batches: list) -> None:
        """Admit each batch through the same gates as the per-batch path
        (deadline shed, evicted-entry shed, breaker ladder), then run every
        surviving request of every geometry as ONE task graph
        (:func:`spfft_tpu.sched.run_graph`): mixed-geometry dispatches
        overlap instead of serializing per plan-cache entry, finalize runs
        in completion order, and a failed task demotes through the
        scheduler's reference rung without stalling the rest of the cycle.
        The scheduler owns per-task retries here (``retries=self.retries``);
        engine breakers settle from the cycle's per-engine verdicts."""
        platform = self._platform()
        graph = sched.TaskGraph()
        jobs = []  # (task_id, request, engine, supervised)
        engines: dict = {}  # engine -> {"supervised", "failed"}
        settled = False
        # From the first allow() below this cycle MAY hold an engine
        # breaker's single half-open probe slot. Every exit — the normal
        # verdict loop included — must settle each engine's probe, so the
        # finally releases verdict-less probes on the exceptional exits (a
        # serve.batch fault on a later batch, a graph-build error): the
        # breaker must never wedge in half-open behind a lost probe (the
        # same contract as _process_batch_inner's finally).
        try:
            for batch in batches:
                obs.counter("serve_batches_total").inc()
                entry = self.plans.get(batch[0].plan_key)
                survivors = self._shed_expired(batch)
                if not survivors:
                    continue
                if entry is None:  # evicted between admit and dispatch
                    err = ServiceOverloadError(
                        "plan cache entry evicted while queued"
                    )
                    for req in survivors:
                        obs.counter(
                            "serve_sheds_total", reason="plan_evicted"
                        ).inc()
                        if req.ticket.fail(err, outcome="shed"):
                            self._count("shed", req.tenant)
                    continue
                engine = entry.plan._engine
                supervised = entry.plan._verifier is not None
                if not supervised and not breaker.allow(engine):
                    self._breaker_response(survivors, engine, entry)
                    continue
                state = engines.setdefault(
                    engine, {"supervised": supervised, "failed": False}
                )
                state["supervised"] = state["supervised"] and supervised
                faults.site("serve.batch")
                obs.histogram("serve_batch_occupancy").observe(len(survivors))
                obs.trace.event(
                    "serve", what="coalesce",
                    direction=survivors[0].direction,
                    occupancy=len(survivors),
                )
                if not supervised and entry.plan._exec._ir.batch_available():
                    # batch-fused entry: the scheduler sees the whole batch
                    # as ONE task (one stacked dispatch, one finalize, one
                    # ladder) — no plan clones leased. Forward groups by
                    # scaling (the batched program is scaling-specialized);
                    # the tuner-owned cap chunks oversized batches.
                    for chunk in _batch_chunks(
                        survivors, self._batch_cap(entry)
                    ):
                        deadlines = [r.deadline for r in chunk]
                        # no bucket padding here (unlike run_batch's fused
                        # arm): the scheduler's demote rung and split-phase
                        # fallback iterate the payload per request, so pad
                        # rows would be recomputed on the already-degraded
                        # path — sched mode accepts per-size specialization
                        tid = graph.add(
                            chunk[0].direction,
                            payload=[r.payload for r in chunk],
                            scaling=chunk[0].scaling, transform=entry.plan,
                            # the TASK deadline is the latest in the chunk (a
                            # batch must not shed early for its most urgent
                            # member); each member's OWN deadline is
                            # re-checked at resolution below, so coalescing
                            # never weakens the per-request contract
                            deadline=None
                            if any(d is None for d in deadlines)
                            else max(deadlines),
                            batch=True,
                        )
                        jobs.append((tid, chunk, engine, supervised, True))
                    continue
                plans = entry.lease(len(survivors), self._clone_plan)
                for plan, req in zip(plans, survivors):
                    tid = graph.add(
                        req.direction, payload=req.payload,
                        scaling=req.scaling, transform=plan,
                        deadline=req.deadline,
                    )
                    jobs.append((tid, [req], engine, supervised, False))
            if not jobs:
                return  # the finally releases any held probes verdict-less
            obs.trace.event(
                "serve", what="dispatch", engine="sched",
                occupancy=len(jobs), attempt=0,
            )
            for _tid, reqs, _engine, _supervised, _is_batch in jobs:
                for req in reqs:
                    req.ticket.stamp("dispatched")
            with faults.typed_execution(platform, "serve dispatch"):
                faults.site("serve.dispatch")
                report = sched.run_graph(
                    graph, retries=self.retries, demote=True,
                    on_error="resolve", backoff_s=self.backoff_s,
                    backoff_rng=self._retry_rng,
                )
            for tid, reqs, engine, supervised, is_batch in jobs:
                outcome = report.outcomes[tid]
                err = report.errors.get(tid)
                if outcome in ("completed", "demoted"):
                    result = report.results[tid]
                    # batch tasks resolve a request-aligned result list;
                    # per-request tasks wrap their single result
                    results = result if is_batch else [result]
                    if outcome == "demoted":
                        # the scheduler's reference rung answered: correct
                        # data over a failed primary — an engine-health signal
                        if not supervised:
                            engines[engine]["failed"] = True
                    now = time.monotonic()
                    for req, res in zip(reqs, results):
                        if is_batch and req.expired(now):
                            # the batch task ran under its LATEST member's
                            # deadline; a member whose own deadline expired
                            # meanwhile keeps the per-request contract —
                            # deadline_miss, exactly as if it had been shed
                            # pre-dispatch (per-request tasks enforce this
                            # inside the executor instead)
                            obs.counter(
                                "serve_deadline_misses_total",
                                tenant=req.tenant,
                            ).inc()
                            obs.counter(
                                "serve_sheds_total", reason="deadline"
                            ).inc()
                            obs.trace.event(
                                "serve", what="shed", reason="deadline",
                                tenant=req.tenant,
                            )
                            if req.ticket.fail(
                                DeadlineExceededError(
                                    "request deadline expired inside a "
                                    "batched dispatch"
                                ),
                                outcome="deadline_miss",
                            ):
                                self._count("deadline_miss", req.tenant)
                            continue
                        if req.direction == "forward":
                            res = _to_request_order(req, res)
                        if outcome == "demoted":
                            self._count_only("demoted")
                            obs.counter(
                                "serve_demotions_total", engine=engine
                            ).inc()
                            obs.trace.event(
                                "serve", what="demote", engine=engine,
                                tenant=req.tenant,
                            )
                        if req.ticket.resolve(res):
                            self._observe_completion(req)
                elif isinstance(err, DeadlineExceededError):
                    # expired between retry attempts inside the executor:
                    # the same accounting as a pre-dispatch shed — and NOT
                    # an engine-health failure
                    for req in reqs:
                        obs.counter(
                            "serve_deadline_misses_total", tenant=req.tenant
                        ).inc()
                        obs.counter(
                            "serve_sheds_total", reason="deadline"
                        ).inc()
                        obs.trace.event(
                            "serve", what="shed", reason="deadline",
                            tenant=req.tenant,
                        )
                        if req.ticket.fail(err, outcome="deadline_miss"):
                            self._count("deadline_miss", req.tenant)
                else:
                    if not supervised:
                        engines[engine]["failed"] = True
                    err = (
                        as_typed(err, platform) if err is not None
                        else ServiceOverloadError("scheduled task unresolved")
                    )
                    for req in reqs:
                        if req.ticket.fail(err):
                            self._count("failed", req.tenant)
            # settle the breakers with this cycle's verdicts (supervised
            # plans' supervisors already reported theirs)
            settled = True
            for engine, state in engines.items():
                if state["supervised"]:
                    continue
                if state["failed"]:
                    breaker.record_failure(engine)
                else:
                    breaker.record_success(engine)
        finally:
            if not settled:
                for engine, state in engines.items():
                    if not state["supervised"]:
                        breaker.release_probe(engine)

    def _batch_cap(self, entry):
        """The tuner-owned fused batch size of one cache entry (``None`` =
        uncapped), resolved lazily on the entry's first dispatch through the
        ``fused/bN`` wisdom axis (:func:`spfft_tpu.tuning.tuned_batch`) —
        zero trials on a warm store, model fallback (uncapped) where trials
        are skipped. Entries outside the tuned policy, or without a live
        batch-fused path, stay uncapped for free."""
        from .batcher import _UNSET

        if entry.batch_cap is not _UNSET:
            return entry.batch_cap
        plan = entry.plan
        cap, record = None, None
        if (
            getattr(plan, "_policy", "default") == "tuned"
            and plan._verifier is None
            and plan._exec._ir.batch_available()
        ):
            from .. import tuning

            choice, record = tuning.tuned_batch(
                plan, batch_max=self.batch_max
            )
            cap = choice.get("batch")
        entry.batch_cap = cap
        entry.batch_record = record
        return cap

    def _shed_expired(self, batch: list) -> list:
        now = time.monotonic()
        survivors = []
        for req in batch:
            if req.expired(now):
                obs.counter(
                    "serve_deadline_misses_total", tenant=req.tenant
                ).inc()
                obs.counter("serve_sheds_total", reason="deadline").inc()
                obs.trace.event("serve", what="shed", reason="deadline",
                                tenant=req.tenant)
                if req.ticket.fail(
                    DeadlineExceededError(
                        "request expired while queued; shed pre-dispatch"
                    ),
                    outcome="deadline_miss",
                ):
                    self._count("deadline_miss", req.tenant)
            else:
                survivors.append(req)
        return survivors

    def _breaker_response(self, batch: list, engine: str, entry) -> None:
        if self.on_breaker == "shed":
            obs.counter("serve_sheds_total", reason="breaker_open").inc()
            err = ServiceOverloadError(
                f"engine {engine!r} circuit breaker open; shedding"
            )
            for req in batch:
                obs.trace.event("serve", what="shed", reason="breaker_open",
                                tenant=req.tenant)
                if req.ticket.fail(err, outcome="shed"):
                    self._count("shed", req.tenant)
            return
        # demote: the jnp.fft reference rung, per request (correctness over
        # batching on the degraded path), mirroring the verify supervisor
        platform = self._platform()
        for req in batch:
            obs.trace.event("serve", what="demote", engine=engine,
                            tenant=req.tenant)
            self._count_only("demoted")
            obs.counter("serve_demotions_total", engine=engine).inc()
            req.ticket.stamp("dispatched")
            try:
                with faults.typed_execution(platform, "serve demote"):
                    result = run_reference(entry.plan, req)
            except Exception as e:  # noqa: BLE001 — ticket must resolve
                if req.ticket.fail(as_typed(e, platform)):
                    self._count("failed", req.tenant)
                continue
            if req.ticket.resolve(result):
                self._observe_completion(req)

    def _observe_completion(self, req) -> None:
        self._count("completed", req.tenant)
        obs.counter(
            "serve_requests_total", tenant=req.tenant, outcome="completed"
        ).inc()
        latency = req.ticket.latency_s()
        if latency is not None:
            obs.histogram("serve_latency_seconds", tenant=req.tenant).observe(
                latency
            )
        # under the request's run ID: the dispatcher thread's completion
        # event joins the caller's trace (and rides the RPC reply segment
        # when the caller sits on another host)
        with obs.trace.with_run(req.run):
            obs.trace.event("serve", what="complete", tenant=req.tenant)

    # ---- bookkeeping ---------------------------------------------------------

    def _count(self, outcome: str, tenant: str) -> None:
        with self._counts_lock:
            self._counts[outcome] += 1
        if outcome != "admitted":
            obs.counter(
                "serve_requests_total", tenant=tenant, outcome=outcome
            ).inc()

    def _count_only(self, key: str) -> None:
        with self._counts_lock:
            self._counts[key] += 1

    def stats(self) -> dict:
        """JSON-plain service counters + queue state (the loadgen/CI
        surface; the obs registry carries the per-tenant breakdown)."""
        with self._counts_lock:
            counts = dict(self._counts)
        return {
            "counts": counts,
            "queue_depth": self.queue.depth(),
            "queue_high_water": self.queue.high_water,
            "queue_capacity": self.queue.capacity,
            "tenant_quota_slots": self.queue.quota,
            "batch_max": self.batch_max,
            "plan_cache_entries": len(self.plans),
            "on_breaker": self.on_breaker,
            "sched": self.sched,
            "sched_batches": self.sched_batches,
        }

    def describe(self) -> dict:
        """Service configuration + plan-cache inventory (each entry carries
        its plan's card run ID — the join key into metrics and traces) +
        the breaker state of every cached engine."""
        cache = self.plans.describe()
        engines = sorted({row["engine"] for row in cache})
        return {
            "config": {
                "queue_capacity": self.queue_capacity,
                "batch_max": self.batch_max,
                "tenant_quota_slots": self.queue.quota,
                "default_timeout_s": self.default_timeout_s,
                "retries": self.retries,
                "backoff_s": self.backoff_s,
                "on_breaker": self.on_breaker,
                "verify": str(self._plan_kwargs.get("verify")),
                "threaded": self._worker is not None,
                "sched": self.sched,
                "sched_batches": self.sched_batches,
                # the serving batch-fuse A/B flag (read at call time, so it
                # reflects the knob the NEXT dispatch cycle will honor)
                "batch_fuse": resolve_batch_fuse()[0],
            },
            "plan_cache": cache,
            "breakers": {e: breaker.describe(e) for e in engines},
            "stats": self.stats(),
        }

    # ---- lifecycle -----------------------------------------------------------

    def close(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop the service. ``drain=True`` lets the dispatcher finish the
        queue first; ``drain=False`` fails every pending ticket typed
        (``ServiceOverloadError``, reason ``closing``). Idempotent; pending
        tickets are never leaked either way."""
        self._closing = True
        # refuse further admissions under the queue's own lock FIRST: a
        # submit racing this close either enqueued before the flag (drained
        # below or finished by the worker) or fails typed — no ticket leaks
        self.queue.shut()
        if not drain:
            self._shed_closing()
        if self._worker is not None:
            self.queue.wake()
            self._worker.join(timeout)
            self._worker = None
        elif drain:
            self.pump()
        # whatever survived a non-draining close or a wedged worker fails
        # typed — the no-leaked-ticket contract
        self._shed_closing()

    def _shed_closing(self) -> None:
        for req in self.queue.drain():
            obs.counter("serve_sheds_total", reason="closing").inc()
            if req.ticket.fail(
                ServiceOverloadError("service closed before dispatch"),
                outcome="shed",
            ):
                self._count("shed", req.tenant)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def _batch_chunks(requests: list, cap) -> list:
    """Split one coalesced batch into batch-task chunks: grouped by scaling
    (the batched forward program is scaling-specialized; backward groups
    are trivially uniform), then cut to the tuner-owned cap."""
    groups: dict = {}
    for r in requests:
        groups.setdefault((r.direction, r.scaling), []).append(r)
    chunks = []
    for reqs in groups.values():
        step = len(reqs) if not cap else max(1, int(cap))
        for i in range(0, len(reqs), step):
            chunks.append(reqs[i : i + step])
    return chunks


def _default_dtype():
    import jax

    return np.dtype(
        np.float64 if jax.config.read("jax_enable_x64") else np.float32
    )
