"""spfft_tpu.serve — overload-safe multi-tenant transform serving.

The serving layer (ROADMAP item 2): millions of users means floods of
concurrent small/medium transforms, not one giant one — and a library that
falls over the moment two callers contend is not a production system. This
package turns the plan/execute machinery into a *service* whose defining
property is graceful behavior under overload:

1. **Admission queue** (:mod:`.queue`): bounded, per-tenant accounted.
   Overload becomes immediate typed :class:`ServiceOverloadError`
   backpressure (queue full, tenant quota) or fair-share shedding — never
   unbounded latency. Deadlines are enforced at admission AND pre-dispatch.
2. **Coalesced batching** (:mod:`.batcher`): requests whose sparse index
   sets share a stick layout resolve to one cached plan (keyed like the
   tuning wisdom store) and execute **batch-fused**
   (``SPFFT_TPU_BATCH_FUSE``): the whole same-geometry batch stacks into
   ONE jitted program dispatch per direction on the canonical plan
   (:mod:`spfft_tpu.ir` batch axis — no plan clones, chunk sizes owned by
   the autotuner, occupancy bucket-padded to bound jit specializations),
   with per-caller value orders bridged by static maps
   (:func:`spfft_tpu.parallel.ragged.value_order_map`) — the AccFFT
   amortize-the-dispatch discipline (arxiv 1506.07933) taken from
   amortized host staging to amortized *programs*. The rung below it
   (``batch_fuse_failed``) is the split-phase loop through the task-graph
   scheduler (:func:`spfft_tpu.sched.run_tasks` over the
   ``multi_transform`` halves on lazily-leased plan clones — dispatches
   enqueued back-to-back, finalized in completion order).
3. **Service** (:mod:`.service`): the dispatcher — retry with jittered
   backoff for transient typed failures, the verify circuit breaker wired
   into a shed-or-demote ladder, per-tenant metrics/histograms on the obs
   registry, ``serve`` flight-recorder events, and fault sites
   ``serve.admit`` / ``serve.batch`` / ``serve.dispatch`` making the whole
   admission→coalesce→execute→respond path chaos-testable. With
   ``sched=True`` (``SPFFT_TPU_SERVE_SCHED``) one dispatch cycle pops up to
   ``SPFFT_TPU_SERVE_SCHED_BATCHES`` coalesced batches — mixed geometries
   included — and runs them as ONE task graph
   (:func:`spfft_tpu.sched.run_graph`), so a flood across many plan-cache
   entries stops serializing per entry.

Guarantee (``tests/test_serve.py``, ``./ci.sh serve``): at offered load
beyond capacity, with faults armed on every ``serve.*`` site, the queue
stays bounded, refusals are typed, the dispatcher never deadlocks, and
every accepted request's ticket resolves — completed (verified, when
``verify=`` is armed) or failed with a typed :mod:`spfft_tpu.errors`
member. ``programs/loadgen.py`` drives sustained open-loop traffic against
it and emits the gate-compatible throughput/latency report
(``SERVE_r08.json``).
"""
from .errors import (  # noqa: F401
    OUTCOMES,
    SHED_REASONS,
    DeadlineExceededError,
    ServiceOverloadError,
    as_typed,
)
from .queue import AdmissionQueue, Request, Ticket  # noqa: F401
from .batcher import PlanCache, canonical_triplets, wrap_triplets  # noqa: F401
from .rpc import RpcClient, RpcServer  # noqa: F401
from .cluster import (  # noqa: F401
    ClusterFront,
    HeartbeatMonitor,
    HostHandle,
    RemotePlan,
)
from .service import (  # noqa: F401
    DEFAULT_BACKOFF_S,
    DEFAULT_BATCH_MAX,
    DEFAULT_PLANS,
    DEFAULT_QUEUE_CAP,
    DEFAULT_RETRIES,
    DEFAULT_SCHED_BATCHES,
    DEFAULT_TENANT_QUOTA,
    RETRYABLE_ERRORS,
    SERVE_BACKOFF_ENV,
    SERVE_BATCH_MAX_ENV,
    SERVE_ON_BREAKER_ENV,
    SERVE_PLANS_ENV,
    SERVE_QUEUE_CAP_ENV,
    SERVE_RETRIES_ENV,
    SERVE_SCHED_BATCHES_ENV,
    SERVE_SCHED_ENV,
    SERVE_TENANT_QUOTA_ENV,
    SERVE_TIMEOUT_ENV,
    TransformService,
    resolve_on_breaker,
)
