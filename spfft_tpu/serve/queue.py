"""Bounded admission queue: backpressure, deadlines, quotas, fair-share shed.

The front door of the serving layer (:mod:`spfft_tpu.serve`). Its defining
property is that it is *bounded*: under overload the queue converts excess
offered load into immediate typed :class:`ServiceOverloadError` rejections —
explicit backpressure the caller can act on — instead of unbounded queueing
latency (the queue-and-die failure mode the DaggerFFT/AccFFT serving framing
warns about). Four admission rules, in order:

1. **Deadline** — a request whose deadline already passed is refused with
   :class:`DeadlineExceededError` (it would only be shed later anyway).
2. **Tenant quota** — one tenant may hold at most ``quota`` queued slots
   (``SPFFT_TPU_SERVE_TENANT_QUOTA`` x capacity): a single runaway caller
   cannot fill the queue however fast it submits.
3. **Fair-share shed** — when the queue is full but the submitting tenant
   holds less than its fair share (capacity / active tenants), the *newest*
   queued request of the most-loaded tenant is shed (its ticket fails typed
   with reason ``fair_share``) to make room: a noisy tenant cannot starve a
   quiet one. Newest-first eviction preserves the victim tenant's oldest
   (closest-to-dispatch) work.
4. **Capacity** — otherwise a full queue refuses with reason ``queue_full``.

The ``serve.admit`` fault site fires inside :meth:`AdmissionQueue.admit`
(payload: the request's mapped values), so chaos runs prove an admission
machinery failure surfaces as a typed rejection, never a hang or a silently
dropped request.
"""
from __future__ import annotations

import collections
import threading
import time

from .. import faults, obs
from ..errors import InvalidParameterError
from .errors import DeadlineExceededError, ServiceOverloadError

# End-to-end request phases, in stamp order. Each ticket records the
# monotonic time it REACHED a phase (first stamp wins); the deltas between
# adjacent present stamps feed ``serve_phase_seconds{phase}`` at resolution
# so overload p99 attributes to WHERE latency lives — queue wait
# (``coalesced``), batch formation (``dispatched``), the cross-host round
# trip (``wire``/``remote_execute``), or resolution (``finalized``). The
# in-process path simply never stamps the wire phases; the histogram family
# and :meth:`Ticket.timeline` skip absent stamps.
PHASES = (
    "admitted", "coalesced", "dispatched", "wire", "remote_execute",
    "finalized",
)


class Ticket:
    """Completion handle of one admitted request.

    Resolved exactly once — with a value (:meth:`resolve`) or a typed error
    (:meth:`fail`); :meth:`result` blocks until then. The serving layer's
    no-deadlock contract is that every admitted request's ticket is resolved
    on every path (completion, shed, deadline, execution failure, service
    close).

    Carries the request's trace run ID (``run``) and monotonic phase stamps
    (:data:`PHASES`): :meth:`stamp` is called by the admission queue, the
    coalescer, the dispatcher and the RPC plane as the request moves, and
    resolution observes the per-phase deltas into
    ``serve_phase_seconds{phase}`` and freezes :meth:`timeline`."""

    __slots__ = (
        "tenant", "submitted_at", "finished_at", "outcome", "run", "stamps",
        "_event", "_value", "_error", "_lock",
    )

    def __init__(self, tenant: str, run: str | None = None):
        self.tenant = tenant
        self.submitted_at = time.monotonic()
        self.finished_at = None
        self.outcome = None  # one of serve.errors.OUTCOMES once resolved
        self.run = run  # trace run ID (card <-> metrics <-> trace join key)
        self.stamps = {}  # phase name -> monotonic ts (PHASES subset)
        self._event = threading.Event()
        self._value = None
        self._error = None
        self._lock = threading.Lock()

    def stamp(self, phase: str) -> None:
        """Record the monotonic time this ticket reached ``phase``. First
        stamp per phase wins (a retry re-crossing the wire keeps the
        original transition time — stamps stay monotonic in PHASES order);
        unknown phases are refused typed so the vocabulary stays closed."""
        if phase not in PHASES:
            raise InvalidParameterError(
                f"unknown ticket phase {phase!r} (one of {PHASES})"
            )
        self.stamps.setdefault(phase, time.monotonic())

    def timeline(self) -> list:
        """The request's phase timeline: ``[{"phase", "t"}]`` rows in
        :data:`PHASES` order, ``t`` = seconds since submission. Absent
        phases (e.g. the wire stamps of an in-process request) are
        omitted; complete once the ticket resolved."""
        return [
            {"phase": phase, "t": self.stamps[phase] - self.submitted_at}
            for phase in PHASES
            if phase in self.stamps
        ]

    def phase_seconds(self) -> dict:
        """Seconds between adjacent present stamps, keyed by the phase
        REACHED (the ``serve_phase_seconds`` labeling: ``coalesced`` is
        queue wait, ``remote_execute`` is the cross-host round trip)."""
        out = {}
        prev = None
        for phase in PHASES:
            ts = self.stamps.get(phase)
            if ts is None:
                continue
            if prev is not None:
                out[phase] = max(0.0, ts - prev)
            prev = ts
        return out

    def resolve(self, value) -> bool:
        """First-resolution-wins; returns whether THIS call resolved the
        ticket (resolution can race between the dispatcher and queue-side
        shedding, and outcome accounting must count each request once)."""
        return self._finish("completed", value=value)

    def fail(self, error: BaseException, outcome: str = "failed") -> bool:
        """Typed-failure counterpart of :meth:`resolve` (same contract)."""
        return self._finish(outcome, error=error)

    def _finish(self, outcome: str, value=None, error=None) -> bool:
        with self._lock:
            if self._event.is_set():
                return False
            self._value = value
            self._error = error
            self.finished_at = time.monotonic()
            self.stamps.setdefault("finalized", self.finished_at)
            self.outcome = outcome
            self._event.set()
        # phase observation OUTSIDE the ticket lock (registry locks must
        # never nest under resolution — same rule as waiter callbacks)
        for phase, seconds in self.phase_seconds().items():
            obs.histogram("serve_phase_seconds", phase=phase).observe(seconds)
        return True

    def done(self) -> bool:
        return self._event.is_set()

    def latency_s(self) -> float | None:
        """Submit-to-resolution wall seconds (None while pending)."""
        if self.finished_at is None:
            return None
        return self.finished_at - self.submitted_at

    def result(self, timeout: float | None = None):
        """Block until resolved; returns the value or raises the typed
        error. ``timeout`` raises builtin ``TimeoutError`` (the ticket stays
        pending — the request is still owned by the service)."""
        if not self._event.wait(timeout):
            # documented builtin contract: callers polling a ticket catch
            # concurrent.futures-style TimeoutError, and the request stays
            # owned by the service (not a failure of it)
            raise TimeoutError("serving request still pending")  # noqa: SA010
        if self._error is not None:
            raise self._error
        return self._value


class Request:
    """One admitted unit of work, carrying everything the batcher needs."""

    __slots__ = (
        "tenant", "direction", "scaling", "plan_key", "payload", "order_map",
        "deadline", "run", "ticket",
    )

    def __init__(
        self, *, tenant, direction, scaling, plan_key, payload, order_map,
        deadline, run=None,
    ):
        self.tenant = str(tenant)
        self.direction = direction          # "backward" | "forward"
        self.scaling = scaling              # ScalingType (forward only)
        self.plan_key = plan_key            # plan-cache digest (coalesce key)
        self.payload = payload              # mapped values / space slab
        self.order_map = order_map          # plan order -> request order, or None
        self.deadline = deadline            # absolute monotonic, or None
        self.run = run                      # trace run ID (join key), or None
        self.ticket = Ticket(self.tenant, run=run)

    def expired(self, now: float | None = None) -> bool:
        if self.deadline is None:
            return False
        return (time.monotonic() if now is None else now) >= self.deadline

    def group(self) -> tuple:
        """Coalescing identity: requests in one batched execution share a
        plan-cache entry and a direction (scaling rides per-request)."""
        return (self.plan_key, self.direction)


class AdmissionQueue:
    """Bounded FIFO with per-tenant accounting and same-geometry batch pop."""

    def __init__(self, capacity: int, tenant_quota: float):
        if capacity < 1:
            raise InvalidParameterError("admission queue capacity must be >= 1")
        if not 0.0 < tenant_quota <= 1.0:
            raise InvalidParameterError(
                f"tenant quota must be in (0, 1], got {tenant_quota}"
            )
        self.capacity = int(capacity)
        self.quota = max(1, int(round(self.capacity * float(tenant_quota))))
        self._cond = threading.Condition()
        self._pending: collections.deque = collections.deque()
        self._per_tenant: collections.Counter = collections.Counter()
        self.high_water = 0  # max depth ever observed (boundedness evidence)
        self.on_shed = None  # optional (tenant) callback for queue-side sheds
        self.closed = False  # set under the lock by shut(); admit() refuses

    # ---- depth accounting ---------------------------------------------------

    def depth(self) -> int:
        with self._cond:
            return len(self._pending)

    def tenant_depth(self, tenant: str) -> int:
        with self._cond:
            return self._per_tenant.get(str(tenant), 0)

    def _gauge(self) -> None:
        depth = len(self._pending)
        if depth > self.high_water:
            self.high_water = depth
        obs.gauge("serve_queue_depth").set(depth)

    # ---- admission ----------------------------------------------------------

    def admit(self, request: Request) -> None:
        """Apply the admission rules (module docstring); raises typed on
        refusal, otherwise enqueues and wakes the dispatcher. A fair-share
        eviction resolves the victim's ticket *outside* the queue lock."""
        # the admission machinery's own fault site, OUTSIDE the queue lock
        # (a delay-kind injection must stall only this submitter, never the
        # dispatcher or other tenants): an injected failure surfaces as a
        # typed rejection (the service converts InjectedFault), and nan/
        # corrupt kinds poison the payload so guard/verify layers downstream
        # prove they catch a poisoned admission
        request.payload = faults.site("serve.admit", payload=request.payload)
        shed_victim = None
        try:
            with self._cond:
                if self.closed:
                    # checked under the SAME lock shut() takes: a submit
                    # racing close() either lands before the flag (and is
                    # drained/dispatched by close) or is refused typed here
                    # — an admitted-but-never-resolved ticket is impossible
                    obs.counter("serve_sheds_total", reason="closing").inc()
                    raise ServiceOverloadError("service is closing")
                now = time.monotonic()
                if request.expired(now):
                    raise DeadlineExceededError(
                        "request deadline expired before admission"
                    )
                tenant = request.tenant
                if self._per_tenant[tenant] >= self.quota:
                    obs.counter("serve_sheds_total", reason="tenant_quota").inc()
                    raise ServiceOverloadError(
                        f"tenant {tenant!r} is over its queue quota "
                        f"({self.quota} of {self.capacity} slots)"
                    )
                if len(self._pending) >= self.capacity:
                    shed_victim = self._fair_share_victim(tenant)
                    if shed_victim is None:
                        obs.counter("serve_sheds_total", reason="queue_full").inc()
                        raise ServiceOverloadError(
                            f"admission queue full ({self.capacity} requests)"
                        )
                    self._pending.remove(shed_victim)
                    self._per_tenant[shed_victim.tenant] -= 1
                    obs.counter("serve_sheds_total", reason="fair_share").inc()
                self._pending.append(request)
                self._per_tenant[tenant] += 1
                request.ticket.stamp("admitted")
                self._gauge()
                self._cond.notify_all()
        finally:
            if shed_victim is not None:
                # ticket resolution can run arbitrary waiter code: never
                # under the queue lock
                obs.trace.event(
                    "serve", what="shed", reason="fair_share",
                    tenant=shed_victim.tenant,
                )
                if shed_victim.ticket.fail(
                    ServiceOverloadError(
                        f"shed under overload: tenant {shed_victim.tenant!r} "
                        "over fair share"
                    ),
                    outcome="shed",
                ) and self.on_shed is not None:
                    self.on_shed(shed_victim.tenant)

    def _fair_share_victim(self, newcomer_tenant: str):
        """The newest queued request of the most-loaded tenant, IF that
        tenant is over the current fair share and the newcomer is under it;
        None when the newcomer has no shedding claim (it is the hog, or load
        is balanced)."""
        counts = {t: c for t, c in self._per_tenant.items() if c > 0}
        if not counts:
            return None
        # the newcomer is an active claimant even while holding zero slots —
        # that is exactly the starvation case fair-share shedding exists for
        active = len(counts) + (0 if counts.get(newcomer_tenant) else 1)
        fair = max(1, self.capacity // max(active, 1))
        hog, hog_count = max(counts.items(), key=lambda kv: kv[1])
        if hog == newcomer_tenant or hog_count <= fair:
            return None
        if self._per_tenant[newcomer_tenant] >= fair:
            return None
        for req in reversed(self._pending):
            if req.tenant == hog:
                return req
        return None

    # ---- dispatch side ------------------------------------------------------

    def pop_batch(self, batch_max: int, timeout: float | None = None) -> list:
        """Pop the oldest request plus up to ``batch_max - 1`` younger
        requests sharing its coalescing group (same plan-cache key and
        direction), preserving FIFO order within the group. Blocks up to
        ``timeout`` for work; returns [] on timeout/empty wake."""
        with self._cond:
            if not self._pending:
                self._cond.wait(timeout)
            if not self._pending:
                return []
            head = self._pending[0]
            group = head.group()
            batch = []
            for req in list(self._pending):
                if len(batch) >= max(1, int(batch_max)):
                    break
                if req.group() == group:
                    batch.append(req)
            for req in batch:
                self._pending.remove(req)
                self._per_tenant[req.tenant] -= 1
                req.ticket.stamp("coalesced")
            self._gauge()
            return batch

    def drain(self) -> list:
        """Remove and return every pending request (service shutdown)."""
        with self._cond:
            batch = list(self._pending)
            self._pending.clear()
            self._per_tenant.clear()
            self._gauge()
            return batch

    def shut(self) -> None:
        """Refuse all further admissions (typed) and wake the dispatcher —
        the first step of service close, taken under the queue lock so no
        submit can slip in after the final drain."""
        with self._cond:
            self.closed = True
            self._cond.notify_all()

    def wake(self) -> None:
        """Wake any dispatcher blocked in :meth:`pop_batch` (shutdown)."""
        with self._cond:
            self._cond.notify_all()
