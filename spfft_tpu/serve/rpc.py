"""Length-prefixed JSON RPC: the cross-host transport of the serving layer.

The thin wire protocol that puts a :class:`~spfft_tpu.serve.service.
TransformService` on the network (ROADMAP item 2's "thin RPC front"):
stdlib ``socket`` only — no new dependencies — with every message a 4-byte
big-endian length prefix followed by a UTF-8 JSON object. Arrays cross the
wire as ``{"__nd__": {dtype, shape, b64}}`` envelopes (raw little-endian
bytes, base64), so the protocol stays pure JSON while payloads round-trip
bit-exactly.

Failure surface is typed on both sides, which is the whole point:

* an **application** failure on the worker (overload refusal, deadline
  miss, execution failure) crosses back as ``{"error": {code, type,
  message}}`` and the client re-raises the *same*
  :mod:`spfft_tpu.errors` taxonomy member — a refused admission on a remote
  host looks exactly like a refused admission on a local service;
* a **transport** failure (connect refused, reset, timeout — what a
  SIGKILLed worker produces) raises
  :class:`~spfft_tpu.errors.HostLostError` naming the host, which is the
  signal the cluster layer's requeue ladder and the scheduler's
  ``host_lost`` rung key on (docs/details.md "Multi-host serving & host
  loss").

The ``rpc.submit`` fault site fires in the client's dispatch path
(:meth:`RpcClient.call` via the cluster layer), so chaos runs prove an RPC
machinery failure degrades through the typed ladder, never an untyped hang.
Server-side, every request counts ``rpc_requests_total{op,outcome}`` and
lands a ``rpc`` flight-recorder event.

**Cross-host trace propagation** (docs/details.md "Observability", fleet
layer): submit frames may carry the caller's trace run ID (``run`` on
``submit``, a ``runs`` list aligned with ``payloads`` on ``submit_batch``).
The server enters ``trace.with_run(...)`` for the whole handling scope, so
everything the worker records — admission verdicts, dispatch spans,
degradations, guard verdicts — lands under the CALLER's key, and the reply
carries back a compact, schema-pinned remote-span segment
(``trace.SEGMENT_SCHEMA``, capped at :data:`SEGMENT_LIMIT` events) that the
cluster front splices into its own flight recorder tagged ``host=``. One
front-side ``trace.snapshot()`` then shows the whole cross-host request.
"""
from __future__ import annotations

import base64
import json
import socket
import struct
import threading
import time

import numpy as np

from .. import knobs, obs
from ..errors import (
    GenericError,
    HostLostError,
    InvalidParameterError,
)
from ..types import ScalingType, TransformType
from .errors import as_typed

RPC_TIMEOUT_ENV = "SPFFT_TPU_RPC_TIMEOUT_S"

# One frame's length prefix: 4-byte big-endian unsigned. The size cap
# refuses absurd frames before allocating (a corrupted prefix must not
# become a 4 GB allocation).
_LEN = struct.Struct(">I")
MAX_FRAME_BYTES = 256 * 1024 * 1024

# Ops a worker's RpcServer answers. "submit"/"submit_batch" execute through
# the wrapped TransformService; "ping" is the heartbeat probe; "describe"/
# "stats" export the service surfaces; "metrics" returns the host's
# ``obs.snapshot()`` (the fleet-aggregation scrape, ``spfft_tpu.obs.fleet``);
# "shutdown" asks the worker process to exit cleanly (so its lockdep report /
# exit hooks run — a SIGKILL deliberately does not).
OPS = (
    "ping", "submit", "submit_batch", "describe", "stats", "metrics",
    "shutdown",
)

# Cap on the events one remote-span reply segment carries back per request
# (newest win): replies stay small next to their array payloads while a
# pathological event storm on the worker cannot bloat a frame to the cap.
SEGMENT_LIMIT = 256


def resolve_timeout_s(value=None) -> float:
    """The per-call RPC wall deadline (``SPFFT_TPU_RPC_TIMEOUT_S``)."""
    return knobs.get_float(RPC_TIMEOUT_ENV, value)


# ---- wire encoding ----------------------------------------------------------


def encode_array(a) -> dict:
    """numpy array -> JSON-plain ``__nd__`` envelope (C-order raw bytes)."""
    a = np.ascontiguousarray(a)
    return {
        "__nd__": {
            "dtype": a.dtype.str,
            "shape": list(a.shape),
            "b64": base64.b64encode(a.tobytes()).decode("ascii"),
        }
    }


def decode_value(obj):
    """Recursively decode ``__nd__`` envelopes inside a parsed message."""
    if isinstance(obj, dict):
        nd = obj.get("__nd__")
        if nd is not None and set(obj) == {"__nd__"}:
            a = np.frombuffer(
                base64.b64decode(nd["b64"]), dtype=np.dtype(nd["dtype"])
            )
            return a.reshape(nd["shape"]).copy()
        return {k: decode_value(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [decode_value(v) for v in obj]
    return obj


def encode_value(obj):
    """Recursively encode numpy arrays into ``__nd__`` envelopes."""
    if isinstance(obj, np.ndarray):
        return encode_array(obj)
    if isinstance(obj, dict):
        return {k: encode_value(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [encode_value(v) for v in obj]
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    return obj


def error_payload(exc: GenericError) -> dict:
    """Typed error -> wire form (code + class name + first message line)."""
    return {
        "error": {
            "code": int(exc.error_code),
            "type": type(exc).__name__,
            "message": str(exc),
        }
    }


def _code_classes() -> dict:
    from .. import errors as _errors

    table = {}
    for name in dir(_errors):
        cls = getattr(_errors, name)
        if (
            isinstance(cls, type)
            and issubclass(cls, GenericError)
            and cls is not GenericError
        ):
            table[int(cls.error_code)] = cls
    return table


_CODE_CLASSES = _code_classes()


def raise_error_payload(err: dict):
    """Re-raise a wire-form error as its taxonomy member (the class with the
    matching C enum code; unknown codes fall back to ``GenericError``)."""
    cls = _CODE_CLASSES.get(int(err.get("code", -1)), GenericError)
    # cls is resolved from the taxonomy table above — every raise here IS a
    # GenericError subclass, just not spellable statically
    raise cls(str(err.get("message", "remote error")))  # noqa: SA010


# ---- framing ----------------------------------------------------------------


def send_msg(sock: socket.socket, msg: dict) -> None:
    """Send one length-prefixed JSON frame."""
    body = json.dumps(encode_value(msg)).encode("utf-8")
    if len(body) > MAX_FRAME_BYTES:
        raise InvalidParameterError(
            f"RPC frame of {len(body)} bytes exceeds the "
            f"{MAX_FRAME_BYTES}-byte cap"
        )
    sock.sendall(_LEN.pack(len(body)) + body)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            # deliberate builtin contract: a short read is a TRANSPORT
            # failure, caught by the client (-> typed HostLostError naming
            # the host) and the server's per-connection loop (-> drop)
            raise ConnectionError("RPC peer closed the connection")  # noqa: SA010
        buf.extend(chunk)
    return bytes(buf)


def recv_msg(sock: socket.socket) -> dict:
    """Receive one length-prefixed JSON frame (arrays decoded)."""
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if n > MAX_FRAME_BYTES:
        raise InvalidParameterError(
            f"RPC frame of {n} bytes exceeds the {MAX_FRAME_BYTES}-byte cap"
        )
    return decode_value(json.loads(_recv_exact(sock, n).decode("utf-8")))


# ---- server -----------------------------------------------------------------


class RpcServer:
    """Serve one :class:`TransformService` over length-prefixed JSON.

    One daemon accept thread plus one daemon handler thread per live
    connection; every socket operation runs under the configured timeout, so
    no thread can block unboundedly (the SA017 discipline). ``close()`` is
    idempotent and joins the accept thread with a bounded wait. The optional
    ``on_shutdown`` callback runs when a peer sends the ``shutdown`` op —
    the worker entry point uses it to exit cleanly."""

    def __init__(
        self,
        service,
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        timeout_s: float | None = None,
        on_shutdown=None,
    ):
        self.service = service
        self.timeout_s = resolve_timeout_s(timeout_s)
        self.on_shutdown = on_shutdown
        self._closing = False
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, int(port)))
        self._sock.listen(64)
        # short accept timeout: the loop polls the closing flag (bounded
        # waits everywhere — a close() can never hang behind accept())
        self._sock.settimeout(0.2)
        self.host, self.port = self._sock.getsockname()
        self._accept = threading.Thread(
            target=self._accept_loop, name="spfft-rpc-accept", daemon=True
        )
        self._accept.start()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def _accept_loop(self) -> None:
        while not self._closing:
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return  # listener closed under us: close() owns shutdown
            conn.settimeout(self.timeout_s)
            threading.Thread(
                target=self._serve_conn,
                args=(conn,),
                name="spfft-rpc-conn",
                daemon=True,
            ).start()

    def _serve_conn(self, conn) -> None:
        import select

        try:
            while not self._closing:
                # idle-wait OUTSIDE the frame reader: an IDLE connection is
                # not a dead one (the client pool keeps sockets across
                # bursts; dropping them would make the next pooled call
                # read as host death, ejecting a healthy host) — but a
                # timeout MID-frame below is a genuine stall and does drop
                # the connection (resuming mid-stream would desync framing)
                readable, _, _ = select.select([conn], [], [], 0.2)
                if not readable:
                    continue
                try:
                    msg = recv_msg(conn)
                except (OSError, ConnectionError, ValueError, GenericError):
                    # peer went away, garbage frame, mid-frame stall, or an
                    # over-cap length prefix (typed refusal): drop the conn
                    return
                reply = self._handle(msg)
                try:
                    send_msg(conn, reply)
                except GenericError as e:
                    # the REPLY breached the frame cap: answer with the
                    # typed error instead of dying — a silent connection
                    # drop reads as host loss and would requeue the same
                    # doomed oversized batch onto every host in turn
                    send_msg(conn, error_payload(e))
        except OSError:
            return  # reply write failed: peer is gone
        finally:
            conn.close()

    def _handle(self, msg: dict) -> dict:
        op = str(msg.get("op", ""))
        try:
            if op not in OPS:
                raise InvalidParameterError(
                    f"unknown RPC op {op!r}: expected one of {OPS}"
                )
            out = getattr(self, f"_op_{op}")(msg)
        except Exception as e:  # noqa: BLE001 — count + convert (typed wire
            # contract: EVERY failure crosses back as a taxonomy member, so
            # the remote caller's ladder sees exactly what a local one would)
            err = as_typed(e, "cpu")
            obs.counter("rpc_requests_total", op=op, outcome="error").inc()
            obs.trace.event("rpc", what="error", op=op, error=type(err).__name__)
            return error_payload(err)
        obs.counter("rpc_requests_total", op=op, outcome="ok").inc()
        obs.trace.event("rpc", what="serve", op=op)
        return out

    # ---- ops ----------------------------------------------------------------

    def _op_ping(self, msg: dict) -> dict:
        return {"ok": 1, "queue_depth": self.service.queue.depth()}

    def _op_stats(self, msg: dict) -> dict:
        return {"stats": self.service.stats()}

    def _op_describe(self, msg: dict) -> dict:
        return {"describe": self.service.describe()}

    def _op_shutdown(self, msg: dict) -> dict:
        if self.on_shutdown is not None:
            self.on_shutdown()
        return {"ok": 1}

    def _op_metrics(self, msg: dict) -> dict:
        """This host's metrics-registry snapshot — the fleet-aggregation
        scrape (``spfft_tpu.obs.fleet`` merges one of these per live
        host)."""
        return {"metrics": obs.snapshot()}

    def _submit_one(self, msg: dict):
        run = msg.get("run")
        return self.service.submit(
            TransformType(int(msg["transform_type"])),
            tuple(int(d) for d in msg["dims"]),
            np.asarray(msg["indices"], dtype=np.int32),
            msg["payload"],
            direction=str(msg.get("direction", "backward")),
            tenant=str(msg.get("tenant", "default")),
            timeout_s=msg.get("timeout_s"),
            scaling=ScalingType(int(msg.get("scaling", 0))),
            run_id=None if run is None else str(run),
        )

    def _reply_budget_s(self) -> float:
        """The wall budget for producing one reply: strictly inside the
        client's per-call socket timeout (minus a wire margin), so a slow
        worker answers with per-entry typed timeout errors instead of
        letting the CLIENT's recv expire — a recv timeout reads as host
        loss and would eject a live-but-backlogged host from the fleet."""
        return max(0.5, self.timeout_s - 2.0)

    def _op_submit(self, msg: dict) -> dict:
        run = msg.get("run")
        run = None if run is None else str(run)
        with obs.trace.with_run(run):
            with obs.trace.span("rpc", what="remote", op="submit"):
                ticket = self._submit_one(msg)
                result = np.asarray(
                    ticket.result(timeout=self._reply_budget_s())
                )
        reply = {"result": result}
        if run is not None:
            reply["spans"] = obs.trace.segment(run, limit=SEGMENT_LIMIT)
        return reply

    def _op_submit_batch(self, msg: dict) -> dict:
        """Admit every payload of one same-geometry chunk, then wait for all
        tickets: per-entry results so one member's typed failure never hides
        its peers' completions. The whole wait runs under ONE reply budget
        (:meth:`_reply_budget_s`), not a per-ticket one — N tickets must
        never stack N socket timeouts. A ``runs`` list aligned with
        ``payloads`` propagates each caller's trace run ID; the reply's
        ``spans`` list carries one remote-span segment per entry."""
        payloads = msg["payloads"]
        if not isinstance(payloads, list) or not payloads:
            raise InvalidParameterError(
                "submit_batch needs a non-empty 'payloads' list"
            )
        runs = msg.get("runs")
        if not isinstance(runs, list) or len(runs) != len(payloads):
            runs = [None] * len(payloads)
        runs = [None if r is None else str(r) for r in runs]
        tickets = []
        for payload, run in zip(payloads, runs):
            one = dict(msg)
            one["payload"] = payload
            one["run"] = run
            with obs.trace.with_run(run):
                with obs.trace.span("rpc", what="remote", op="submit_batch"):
                    try:
                        tickets.append(self._submit_one(one))
                    except GenericError as e:
                        tickets.append(e)
        deadline = time.monotonic() + self._reply_budget_s()
        results = []
        for t in tickets:
            if isinstance(t, GenericError):
                results.append(error_payload(t))
                continue
            try:
                remaining = max(0.05, deadline - time.monotonic())
                results.append(
                    {"result": np.asarray(t.result(timeout=remaining))}
                )
            except GenericError as e:
                results.append(error_payload(e))
            except TimeoutError as e:
                results.append(error_payload(as_typed(e, "cpu")))
        reply = {"results": results}
        if any(r is not None for r in runs):
            # segments are cut AFTER the waits, so dispatcher-side events
            # recorded under each caller's run during execution ride along
            reply["spans"] = [
                None if r is None else obs.trace.segment(r, limit=SEGMENT_LIMIT)
                for r in runs
            ]
        return reply

    # ---- lifecycle ----------------------------------------------------------

    def close(self) -> None:
        self._closing = True
        try:
            self._sock.close()
        except OSError:
            pass
        self._accept.join(2.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


# ---- client -----------------------------------------------------------------


class RpcClient:
    """Pooled client for one worker host's :class:`RpcServer`.

    ``call`` checks a connection out of the idle pool (dialing a new one
    when empty), runs one request/response exchange under the configured
    timeout, and returns the connection to the pool. Any transport failure
    — connect refused, reset, short read, timeout — closes the connection
    and raises typed :class:`~spfft_tpu.errors.HostLostError` naming the
    host: the cluster layer keys its requeue ladder on exactly that class.
    Application errors from the worker re-raise as their own taxonomy
    members and do NOT mark the transport dead."""

    def __init__(self, address: str, *, timeout_s: float | None = None):
        host, sep, port_s = str(address).rpartition(":")
        if not sep or not host:
            raise InvalidParameterError(
                f"malformed RPC address {address!r}: expected 'host:port'"
            )
        try:
            self.port = int(port_s)
        except ValueError:
            raise InvalidParameterError(
                f"malformed RPC address {address!r}: port {port_s!r} is not "
                "an integer"
            ) from None
        self.host = host
        self.address = f"{host}:{self.port}"
        self.timeout_s = resolve_timeout_s(timeout_s)
        self._idle: list = []
        self._lock = threading.Lock()
        self._closed = False

    def _checkout(self, timeout_s: float | None = None):
        with self._lock:
            if self._closed:
                raise HostLostError(
                    f"RPC client for {self.address} is closed"
                )
            if self._idle:
                return self._idle.pop()
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        # the caller's deadline governs the DIAL too: a blackholed host
        # (dropped SYNs, no RST) must not hold a short-deadline probe —
        # the heartbeat's interval-bounded ping — for the default timeout
        sock.settimeout(self.timeout_s if timeout_s is None else float(timeout_s))
        sock.connect((self.host, self.port))
        return sock

    def _checkin(self, sock) -> None:
        with self._lock:
            if not self._closed:
                self._idle.append(sock)
                return
        sock.close()

    def call(self, msg: dict, *, timeout_s: float | None = None) -> dict:
        """One request/response exchange; returns the decoded reply body.

        Raises the reply's taxonomy member when the worker answered with a
        typed error, and :class:`HostLostError` when the transport itself
        failed."""
        try:
            sock = self._checkout(timeout_s)
        except (OSError, ConnectionError) as e:
            raise HostLostError(
                f"host {self.address} unreachable: {type(e).__name__}: {e}"
            ) from e
        try:
            if timeout_s is not None:
                sock.settimeout(float(timeout_s))
            send_msg(sock, msg)
            reply = recv_msg(sock)
        except (OSError, ConnectionError, ValueError) as e:
            sock.close()
            raise HostLostError(
                f"host {self.address} died mid-call "
                f"(op {msg.get('op')!r}): {type(e).__name__}: {e}"
            ) from e
        except BaseException:
            # non-transport failure (an over-cap request frame's typed
            # refusal, a serialization bug): the socket's state is unknown —
            # close it rather than leak it or pool it half-written
            sock.close()
            raise
        if timeout_s is not None:
            sock.settimeout(self.timeout_s)
        self._checkin(sock)
        err = reply.get("error") if isinstance(reply, dict) else None
        if err is not None:
            raise_error_payload(err)
        return reply

    def close(self) -> None:
        with self._lock:
            self._closed = True
            idle, self._idle = self._idle, []
        for sock in idle:
            sock.close()
