"""The ``Transform`` public API object.

Parity with the reference ``spfft::Transform`` (reference: include/spfft/transform.hpp:56-318):
a shape-specialized FFT plan created either from a Grid or standalone, exposing
``forward`` / ``backward`` and the full accessor surface. The reference's
double/float split (``Transform`` vs ``TransformFloat``) becomes a ``dtype``
argument; ``TransformFloat`` is provided as a thin alias for API parity.
"""
from __future__ import annotations

import numpy as np

import jax

from . import faults, obs, timing
from .tuning import env_overrides
from .errors import FFTWError, InvalidParameterError
from .execution import LocalExecution, _complex_dtype, as_pair, from_pair
from .sync import fence
from .grid import Grid, device_for_processing_unit
from .parameters import make_local_parameters
from .types import ExecType, IndexFormat, ProcessingUnit, ScalingType, TransformType


class Transform:
    """A sparse 3D FFT plan.

    Create standalone (reference grid-less ctor, include/spfft/transform.hpp:76-105)
    or via :meth:`Grid.create_transform`.

    ``backward(values)`` maps packed sparse frequency values to the dense space-domain
    slab (shape ``(dim_z, dim_y, dim_x)``, addressing parity with
    reference docs/source/details.rst:21-27); ``forward(space, scaling)`` maps back,
    optionally scaling by 1/(NxNyNz) (reference: docs/source/details.rst:42-44).
    """

    def __init__(
        self,
        processing_unit,
        transform_type,
        dim_x,
        dim_y,
        dim_z,
        num_local_elements=None,
        indices=None,
        *,
        local_z_length=None,
        index_format: IndexFormat = IndexFormat.TRIPLETS,
        grid: Grid | None = None,
        dtype=None,
        engine: str = "auto",
        precision: str = "highest",
        device=None,
        policy: str | None = None,
        guard: bool | None = None,
        verify=None,
        fuse=None,
    ):
        if IndexFormat(index_format) != IndexFormat.TRIPLETS:
            raise InvalidParameterError("only SPFFT_INDEX_TRIPLETS is supported")
        if indices is None:
            raise InvalidParameterError("index triplets are required")
        indices = np.asarray(indices)
        if num_local_elements is not None:
            flat = indices.reshape(-1)
            if flat.size < 3 * num_local_elements:
                raise InvalidParameterError("fewer indices than num_local_elements")
            indices = flat[: 3 * int(num_local_elements)]

        self._processing_unit = ProcessingUnit(processing_unit)
        self._grid = grid
        self._exec_mode = ExecType.SYNCHRONOUS
        self._params = make_local_parameters(
            TransformType(transform_type), dim_x, dim_y, dim_z, indices
        )

        # Envelope validation for an explicit local_z_length (reference:
        # src/spfft/transform.cpp:51-55 rejects negatives; grid capacity checks
        # in src/spfft/transform_internal.cpp:45-137). A local plan owns the
        # full z-extent, so any other positive value is a porting error —
        # reject loudly instead of silently accepting it. 0 is treated as
        # "unspecified", like None: the reference's serial path ignores the
        # parameter entirely, and ported callers legally pass 0 there
        # (divergence documented in docs/MIGRATION.md).
        if local_z_length is not None:
            local_z_length = int(local_z_length)
            if local_z_length < 0:
                raise InvalidParameterError("local_z_length must be non-negative")
            if local_z_length == 0:
                local_z_length = None
        if local_z_length is not None:
            if local_z_length != int(dim_z):
                raise InvalidParameterError(
                    f"a local transform spans the full z-extent: local_z_length "
                    f"must be dim_z ({int(dim_z)}), got {local_z_length}; use the "
                    "distributed transform for partial z-slabs"
                )
            if grid is not None and local_z_length > grid.max_local_z_length:
                raise InvalidParameterError("local z length exceeds grid maximum")

        if grid is not None:
            # Capacity validation, parity with src/spfft/transform_internal.cpp:45-137.
            p = self._params
            if (
                p.dim_x > grid.max_dim_x
                or p.dim_y > grid.max_dim_y
                or p.dim_z > grid.max_dim_z
            ):
                raise InvalidParameterError("transform dimensions exceed grid maxima")
            if p.num_sticks > grid.max_num_local_z_columns:
                raise InvalidParameterError("more z-columns than grid maximum")
            if not (ProcessingUnit(processing_unit) & grid.processing_unit):
                raise InvalidParameterError(
                    "transform processing unit not covered by grid"
                )

        if dtype is None:
            dtype = np.float64 if jax.config.read("jax_enable_x64") else np.float32
        self._real_dtype = np.dtype(dtype)
        if self._real_dtype not in (np.dtype(np.float32), np.dtype(np.float64)):
            raise InvalidParameterError("dtype must be float32 or float64")

        from .ops.fft import resolve_precision

        resolve_precision(precision)  # validate up front on every engine path

        # Per-object device binding (reference: each Grid/Transform pins the
        # device current at creation, grid_internal.cpp:82): explicit device=
        # wins, then the grid's bound device, then jax.default_device / the
        # PU's default. put() commits inputs there, so the jitted pipelines
        # compile for and execute on that device.
        if device is None and grid is not None:
            gdev = grid.device
            if (gdev.platform == "cpu") == (
                self._processing_unit == ProcessingUnit.HOST
            ):
                device = gdev
        device = device_for_processing_unit(self._processing_unit, device)
        self._device = device

        from .parallel.policy import resolve_policy

        self._policy = resolve_policy(policy)
        # Guard mode (spfft_tpu.faults.guard): explicit kwarg wins, else the
        # SPFFT_TPU_GUARD env knob. Every fallback the construction or the
        # degradation ladder takes lands on _degradations (surfaced
        # schema-pinned in the plan card's "degradations" section).
        self._guard = faults.guard_enabled(guard)
        self._degradations: list = []
        self._tuning = None
        # Fusion request (spfft_tpu.ir): the raw kwarg — engines resolve
        # kwarg-else-SPFFT_TPU_FUSE at construction, so a tuned candidate's
        # env override can own the knob when the caller leaves it unset.
        self._fuse = fuse
        # Run ID (spfft_tpu.obs.trace): the correlation key joining this
        # plan's card, metrics and flight-recorder events. The "plan"
        # operation span keeps it active for the whole construction, so
        # tuning trials, ladder rungs, fault injections and guard verdicts
        # below stamp it.
        self._run_id = obs.trace.new_run_id()
        with obs.trace.operation("plan", run_id=self._run_id, kind="local"):
            engine_env = {}
            if engine == "auto" and self._policy == "tuned":
                # TUNED policy (spfft_tpu.tuning): resolve the engine axis (MXU
                # matmul DFTs vs jnp.fft, incl. the sparse-y knob variants)
                # empirically — wisdom hit, else on-device trials on THIS plan's
                # stick layout, else the static auto rule (CPU-only hosts /
                # corrupt store). Trial plans use explicit engines and the model
                # policy, so tuning cannot recurse.
                from . import tuning

                p = self._params
                triplets = _storage_triplets(p)

                def build(cand):
                    with tuning.env_overrides(cand.get("env") or {}):
                        return Transform(
                            self._processing_unit,
                            p.transform_type,
                            p.dim_x,
                            p.dim_y,
                            p.dim_z,
                            indices=triplets,
                            dtype=self._real_dtype,
                            engine=cand["engine"],
                            precision=precision,
                            device=device,
                            policy="default",
                            # An explicit fuse= pins the fusion axis: trials
                            # run at the pinned state (the kwarg beats any
                            # candidate env in ir.resolve_fuse) and tuned_local
                            # keys wisdom on the pin, so the measured variant
                            # is always the variant the chosen plan runs.
                            fuse=fuse,
                        )

                with faults.collecting(self._degradations):
                    choice, self._tuning = tuning.tuned_local(
                        p, device, self._real_dtype, precision, build,
                        fuse=fuse,
                    )
                engine = choice["engine"]
                engine_env = dict(choice.get("env") or {})
            # Engine selection: the MXU engine (matmul DFTs + lane-copy pack/unpack,
            # execution_mxu.py) wins on accelerators; the XLA engine (jnp.fft + scatter,
            # execution.py) wins on CPU where pocketfft is the fast path.
            if engine == "auto":
                engine = "xla" if device.platform == "cpu" else "mxu"
            if engine not in ("mxu", "xla"):
                raise InvalidParameterError(f"unknown engine {engine!r}")
            # Plan-creation timing scope, parity with the reference's "Execution init"
            # (reference: src/execution/execution_host.cpp:56). Degradation ladder
            # rung 1: an MXU engine that fails to lower/compile (fault site
            # engine.compile) falls back to the jnp.fft engine instead of failing
            # plan construction; the fallback is recorded on the plan card and in
            # engine_fallbacks_total. A jnp.fft engine failure has no rung below
            # it and raises typed FFTWError.
            with timing.scoped("Execution init"), faults.collecting(self._degradations):
                if engine == "mxu":
                    from .execution_mxu import MxuLocalExecution

                    try:
                        faults.site("engine.compile")
                        # engine_env: a tuned candidate's knob overrides (empty ->
                        # os.environ untouched; see tuning.env_overrides)
                        with env_overrides(engine_env):
                            self._exec = MxuLocalExecution(
                                self._params, self._real_dtype, device=device,
                                precision=precision, fuse=fuse,
                            )
                        self._native_transposed = True
                    except faults.ENGINE_BUILD_ERRORS as e:
                        faults.engine_fallback("mxu", "xla", faults.summarize(e))
                        engine = "xla"
                if engine == "xla":
                    try:
                        self._exec = LocalExecution(
                            self._params, self._real_dtype, device=device,
                            fuse=fuse,
                        )
                    except faults.ENGINE_BUILD_ERRORS as e:
                        raise FFTWError(
                            f"local engine construction failed: {e}"
                        ) from e
                    self._native_transposed = False
            obs.trace.event(
                "decision", what="engine", choice=engine, policy=self._policy
            )
        self._engine = engine
        self._precision = precision
        self._space_data = None
        # Self-verification (spfft_tpu.verify): explicit verify= wins, else
        # SPFFT_TPU_VERIFY. Armed, every host-facing backward/forward runs
        # under the recovery supervisor (check -> retry -> jnp.fft reference
        # -> typed VerificationError); disarmed, the hot path pays exactly
        # one falsy attribute check.
        from .verify import resolve_mode

        self._verify_mode = resolve_mode(verify)
        self._verifier = None
        self._reference_exec = None
        if self._verify_mode != "off":
            from .verify import Supervisor

            self._verifier = Supervisor(self, self._verify_mode)

    # ---- transforms -----------------------------------------------------------

    def backward(self, values, output_location: ProcessingUnit | None = None):
        """Frequency -> space. Returns the (dim_z, dim_y, dim_x) space-domain array
        (complex for C2C, real for R2C).

        Reference: include/spfft/transform.hpp:286-298. The result is also retained
        (device-resident) for :meth:`space_domain_data` / input-less :meth:`forward`,
        mirroring the reference's internal space-domain buffer.
        """

        if output_location is not None:
            _validate_data_location(output_location)
        # Timing scopes mirror the reference's top-level "backward" plus the
        # host-visible phases (reference: src/spfft/transform_internal.cpp:255;
        # stage-level attribution lives in profiler traces — see timing module doc).
        obs.counter("transforms_total", direction="backward", engine=self._engine).inc()
        plat = self._device.platform
        # "execute" operation span (spfft_tpu.obs.trace): runs under the
        # plan's run ID, so the trace of this call joins the plan card.
        with obs.trace.operation(
            "execute", run_id=self._run_id, direction="backward"
        ), timing.scoped("backward"):
            if self._guard:
                faults.check_array(
                    np.asarray(values), check="backward input", platform=plat
                )
            if self._verifier is not None:
                # supervised path (spfft_tpu.verify): check -> retry ->
                # jnp.fft reference -> typed VerificationError
                return self._verifier.backward(values)
            return self._backward_attempt(values)

    def _backward_attempt(self, values):
        """One full backward execution (dispatch, fence, finalize, guard
        post-checks) — the unit the verify supervisor re-executes on a
        failed check; identical to the whole unsupervised path."""
        plat = self._device.platform
        out = self._dispatch_backward(values)
        if self._exec_mode == ExecType.SYNCHRONOUS:
            with timing.scoped("wait"), obs.phase_timer(
                "wait_seconds", direction="backward"
            ), faults.typed_execution(plat, "backward wait"):
                fence(out)
        with timing.scoped("output staging"):
            result = self._finalize_backward(out)
        if self._guard:
            faults.check_device(
                out, self._device, check="backward output", platform=plat
            )
            faults.check_array(
                result,
                check="backward output",
                platform=plat,
                shape=(self.dim_z, self.dim_y, self.dim_x),
                dtype=self._real_dtype
                if self._is_r2c
                else _complex_dtype(self._real_dtype),
            )
        return result

    def _dispatch_backward(self, values):
        """Stage inputs and enqueue the backward pipeline; returns the
        device-resident result without waiting. The host-level analogue of the
        reference's split-phase backward_z/exchange/xy dispatch used by
        multi-transform pipelining (reference: src/spfft/transform_internal.hpp,
        multi_transform_internal.hpp:113-176)."""

        values = np.asarray(values)
        if values.size != self._params.num_values:
            raise InvalidParameterError(
                f"expected {self._params.num_values} frequency values, got {values.size}"
            )
        values = values.reshape(self._params.num_values)
        with timing.scoped("input staging"):
            re, im = as_pair(values, self._real_dtype)
            re, im = self._exec.put(re), self._exec.put(im)
        with timing.scoped("dispatch"), obs.phase_timer(
            "dispatch_seconds", direction="backward"
        ), faults.typed_execution(self._device.platform, "backward dispatch"):
            # staged copies are dead after the call: donate them so XLA reuses
            # the allocations for pipeline temporaries
            out = self._exec.backward_pair_consuming(re, im)
            out = faults.site("engine.execute", payload=out)
        self._space_data = out  # engine-native layout; pair for C2C, real for R2C
        return out

    def backward_pair(self, values_re, values_im):
        """Device-side backward: (re, im) freq pair in, device-resident space out
        ((re, im) pair for C2C, real array for R2C). No host transfers.

        The space array uses the *engine-native* axis order given by
        :attr:`space_domain_layout` — ``(Z, Y, X)`` for the XLA engine, ``(Y, X, Z)``
        for the MXU engine. This mirrors the reference, whose GPU backend likewise
        keeps device-resident space data in a transposed layout while host-facing
        calls translate (reference: docs/source/details.rst:55-59). Host-facing
        :meth:`backward` / :meth:`space_domain_data` always return ``(Z, Y, X)``.
        """
        out = self._exec.backward_pair(values_re, values_im)
        self._space_data = out
        return out

    # ---- batch-fused execution (SPFFT_TPU_BATCH_FUSE, spfft_tpu.ir) -----------

    def backward_batch(self, values_batch, *, fallback: bool = True,
                       count: int | None = None):
        """Execute B same-plan backward transforms as ONE batched fused
        program per direction (``SPFFT_TPU_BATCH_FUSE``): the packed value
        arrays stack along a leading batch axis, the whole batch pays one
        dispatch, and the stacked staging buffers are donated. Returns the
        per-request space arrays in batch order.

        Degradation: a batched build/compile failure records
        ``batch_fuse_failed`` on the plan card and — with ``fallback=True``
        — the batch re-runs as today's per-request split-phase loop, never a
        failed batch. ``fallback=False`` returns ``None`` instead, for
        callers (the serving batcher) that own a richer fallback path.
        ``count`` marks the first N entries as the REAL requests of a
        bucket-padded batch (the serving batcher's jit-specialization
        bound): only those are counted, guard-checked and returned — the
        padding tail is dispatch ballast. Verified plans always run
        per-request under their supervisor (the ABFT ladder owns each
        request's attempt). The retained space buffer
        (:meth:`space_domain_data`) is left untouched by the batched path."""
        values_batch = list(values_batch)
        count = _resolve_batch_count(count, len(values_batch))
        if not values_batch:
            return []
        if self._verifier is not None:
            return [self.backward(v) for v in values_batch[:count]]
        plat = self._device.platform
        obs.counter(
            "transforms_total", direction="backward", engine=self._engine
        ).inc(count)
        with obs.trace.operation(
            "execute", run_id=self._run_id, direction="backward",
        ), timing.scoped("backward"):
            if self._guard:
                for v in values_batch[:count]:
                    faults.check_array(
                        np.asarray(v), check="backward input", platform=plat
                    )
            pending = self._dispatch_backward_batch(
                values_batch, fallback=fallback, count=count
            )
            if pending is None:
                return None
            with timing.scoped("wait"), obs.phase_timer(
                "wait_seconds", direction="backward"
            ), faults.typed_execution(plat, "backward wait"):
                fence(pending)
            with timing.scoped("output staging"):
                results = self._finalize_backward_batch(pending)[:count]
            if self._guard:
                if "batched" in pending:
                    faults.check_device(
                        pending["batched"], self._device,
                        check="backward output", platform=plat,
                    )
                for result in results:
                    faults.check_array(
                        result,
                        check="backward output",
                        platform=plat,
                        shape=(self.dim_z, self.dim_y, self.dim_x),
                        dtype=self._real_dtype
                        if self._is_r2c
                        else _complex_dtype(self._real_dtype),
                    )
            return results

    def forward_batch(
        self,
        spaces,
        scaling: ScalingType = ScalingType.NONE,
        *,
        fallback: bool = True,
        count: int | None = None,
    ):
        """Batched counterpart of :meth:`forward` over explicit space
        arrays: B ``(Z, Y, X)`` slabs -> B packed complex value arrays
        through one batched fused program (same contract, knob, degradation
        rung and ``count`` padding semantics as :meth:`backward_batch`; one
        ``scaling`` for the whole batch — the serving batcher groups by
        scaling)."""
        spaces = list(spaces)
        count = _resolve_batch_count(count, len(spaces))
        if not spaces:
            return []
        if self._verifier is not None:
            return [self.forward(s, scaling) for s in spaces[:count]]
        plat = self._device.platform
        obs.counter(
            "transforms_total", direction="forward", engine=self._engine
        ).inc(count)
        with obs.trace.operation(
            "execute", run_id=self._run_id, direction="forward",
        ), timing.scoped("forward"):
            if self._guard:
                for s in spaces[:count]:
                    faults.check_array(
                        np.asarray(s), check="forward input", platform=plat
                    )
            pending = self._dispatch_forward_batch(
                spaces, scaling, fallback=fallback, count=count
            )
            if pending is None:
                return None
            with timing.scoped("wait"), obs.phase_timer(
                "wait_seconds", direction="forward"
            ), faults.typed_execution(plat, "forward wait"):
                fence(pending)
            with timing.scoped("output staging"):
                results = self._finalize_forward_batch(pending)[:count]
            if self._guard:
                for result in results:
                    faults.check_array(
                        result,
                        check="forward output",
                        platform=plat,
                        shape=(self.num_local_elements,),
                        dtype=_complex_dtype(self._real_dtype),
                    )
            return results

    def _dispatch_backward_batch(self, values_batch, *, fallback: bool = True,
                                 count: int | None = None):
        """Stage + enqueue one batch without waiting. Returns the pending
        handle :meth:`_finalize_backward_batch` completes: ``{"batched":
        stacked}`` after ONE batched dispatch, or ``{"loop": [...]}`` of
        per-request split-phase pendings (the rung / knob-off path;
        ``fallback=False`` returns ``None`` there instead; the loop skips a
        bucket-padded tail — only the batched program needs it)."""
        count = _resolve_batch_count(count, len(values_batch))
        n = self._params.num_values
        rows = []
        for values in values_batch:
            values = np.asarray(values)
            if values.size != n:
                raise InvalidParameterError(
                    f"expected {n} frequency values, got {values.size}"
                )
            rows.append(values.reshape(n))
        out = None
        if self._exec._ir.batch_available():
            with timing.scoped("input staging"):
                re, im = as_pair(np.stack(rows), self._real_dtype)
                re, im = self._exec.put(re), self._exec.put(im)
            with timing.scoped("dispatch"), obs.phase_timer(
                "dispatch_seconds", direction="backward"
            ), faults.typed_execution(
                self._device.platform, "backward dispatch"
            ):
                out = self._exec.backward_pair_batch_consuming(re, im)
                if out is not None:
                    out = faults.site("engine.execute", payload=out)
        if out is not None:
            return {"batched": out}
        if not fallback:
            return None
        # the split-phase rung: every dispatch enqueued back-to-back on this
        # plan before any finalize (retained state is not read mid-batch)
        return {"loop": [self._dispatch_backward(v) for v in rows[:count]]}

    def _finalize_backward_batch(self, pending):
        if "loop" in pending:
            return [self._finalize_backward(p) for p in pending["loop"]]
        out = pending["batched"]
        if self._is_r2c:
            arr = self._exec.fetch(out)
        else:
            arr = self._exec.fetch_space_complex(out)
        if self._native_transposed:
            arr = arr.transpose(0, 3, 1, 2)  # (B, Y, X, Z) -> (B, Z, Y, X)
        return [arr[b] for b in range(arr.shape[0])]

    def _dispatch_forward_batch(
        self, spaces, scaling, *, fallback: bool = True,
        count: int | None = None,
    ):
        """Split-phase forward half of the batched flow (see
        :meth:`_dispatch_backward_batch`)."""
        count = _resolve_batch_count(count, len(spaces))
        p = self._params
        slabs = [
            np.asarray(s).reshape(p.dim_z, p.dim_y, p.dim_x) for s in spaces
        ]
        out = None
        if self._exec._ir.batch_available():
            with timing.scoped("input staging"):
                stack = np.stack(slabs)
                if self._native_transposed:
                    stack = stack.transpose(0, 2, 3, 1)  # (B,Z,Y,X)->(B,Y,X,Z)
                if self._is_r2c:
                    re = self._exec.put(
                        np.ascontiguousarray(stack.real, dtype=self._real_dtype)
                    )
                    im = None
                else:
                    re, im = as_pair(stack, self._real_dtype)
                    re, im = self._exec.put(re), self._exec.put(im)
            with timing.scoped("dispatch"), obs.phase_timer(
                "dispatch_seconds", direction="forward"
            ), faults.typed_execution(
                self._device.platform, "forward dispatch"
            ):
                out = self._exec.forward_pair_batch(
                    re, im, ScalingType(scaling)
                )
                if out is not None:
                    out = faults.site("engine.execute", payload=out)
        if out is not None:
            return {"batched": out}
        if not fallback:
            return None
        return {
            "loop": [self._dispatch_forward(s, scaling) for s in slabs[:count]]
        }

    def _finalize_forward_batch(self, pending):
        if "loop" in pending:
            return [self._finalize_forward(p) for p in pending["loop"]]
        re, im = pending["batched"]
        arr = from_pair((re, im))
        return [arr[b] for b in range(arr.shape[0])]

    def forward(
        self,
        space=None,
        scaling: ScalingType = ScalingType.NONE,
        input_location: ProcessingUnit | None = None,
    ):
        """Space -> frequency. Returns the packed (num_local_elements,) complex values.

        Reference: include/spfft/transform.hpp:259-283. ``space=None`` reads the
        retained space-domain buffer (the reference's pointer-free overload reading
        ``space_domain_data``).
        """

        if input_location is not None:
            _validate_data_location(input_location)
        obs.counter("transforms_total", direction="forward", engine=self._engine).inc()
        plat = self._device.platform
        with obs.trace.operation(
            "execute", run_id=self._run_id, direction="forward"
        ), timing.scoped("forward"):
            if self._guard and space is not None:
                faults.check_array(
                    np.asarray(space), check="forward input", platform=plat
                )
            if self._verifier is not None:
                return self._verifier.forward(space, scaling)
            return self._forward_attempt(space, scaling)

    def _forward_attempt(self, space, scaling):
        """One full forward execution (dispatch, fence, finalize, guard
        post-checks) — the re-executable unit of the verify supervisor."""
        plat = self._device.platform
        pair = self._dispatch_forward(space, scaling)
        if self._exec_mode == ExecType.SYNCHRONOUS:
            with timing.scoped("wait"), obs.phase_timer(
                "wait_seconds", direction="forward"
            ), faults.typed_execution(plat, "forward wait"):
                fence(pair)
        with timing.scoped("output staging"):
            result = self._finalize_forward(pair)
        if self._guard:
            faults.check_device(
                pair, self._device, check="forward output", platform=plat
            )
            faults.check_array(
                result,
                check="forward output",
                platform=plat,
                shape=(self.num_local_elements,),
                dtype=_complex_dtype(self._real_dtype),
            )
        return result

    def _dispatch_forward(self, space, scaling):
        """Stage the space-domain input (or reuse the retained buffer) and enqueue
        the forward pipeline; returns the device-resident (re, im) pair without
        waiting (split-phase counterpart of :meth:`_dispatch_backward`)."""

        if space is None:
            if self._space_data is None:
                raise InvalidParameterError(
                    "no space domain data: run backward first or pass an array"
                )
        else:
            with timing.scoped("input staging"):
                self._retain_space(space)
        if self._is_r2c:
            re, im = self._space_data, None
        else:
            re, im = self._space_data
        with timing.scoped("dispatch"), obs.phase_timer(
            "dispatch_seconds", direction="forward"
        ), faults.typed_execution(self._device.platform, "forward dispatch"):
            pair = self._exec.forward_pair(re, im, ScalingType(scaling))
            return faults.site("engine.execute", payload=pair)

    def _retain_space(self, space) -> None:
        """Stage a host ``(Z, Y, X)`` space array as the retained
        device-resident buffer (engine-native layout) — the staging half of
        :meth:`_dispatch_forward`, also used by the verify supervisor to
        replace a failed primary result with the verified recovery."""
        p = self._params
        space = np.asarray(space).reshape(p.dim_z, p.dim_y, p.dim_x)
        if self._native_transposed:
            space = space.transpose(1, 2, 0)  # public (Z,Y,X) -> native (Y,X,Z)
        if self._is_r2c:
            self._space_data = self._exec.put(
                np.ascontiguousarray(space.real, dtype=self._real_dtype)
            )
        else:
            re, im = as_pair(space, self._real_dtype)
            self._space_data = (self._exec.put(re), self._exec.put(im))

    def forward_pair(self, scaling: ScalingType = ScalingType.NONE):
        """Device-side forward over the retained space buffer; returns the (re, im)
        freq pair without host transfers."""
        if self._space_data is None:
            raise InvalidParameterError("no space domain data: run backward first")
        if self._is_r2c:
            return self._exec.forward_pair(self._space_data, None, ScalingType(scaling))
        re, im = self._space_data
        return self._exec.forward_pair(re, im, ScalingType(scaling))

    def _finalize_backward(self, out):
        """Host-side completion of a dispatched backward (fetch + relayout)."""
        return self._combine_space(out)

    def _finalize_forward(self, pair):
        """Host-side completion of a dispatched forward (fetch + recombine)."""

        return from_pair(pair)

    # ---- verification hooks (spfft_tpu.verify) --------------------------------

    def _verify_triplets(self) -> np.ndarray:
        """Storage-order index rows aligned with the packed value order — the
        geometry the ABFT checks recompute invariants from."""
        return _storage_triplets(self._params)

    def _reference_engine(self):
        """Lazily built ``jnp.fft`` reference pipeline (the verify
        supervisor's demotion rung): a fresh :class:`LocalExecution` on the
        plan's device and geometry — a code path disjoint from the primary
        engine's dispatch (no ``engine.execute`` fault site, no shared
        compiled programs), so a poisoned primary cannot poison it."""
        if self._reference_exec is None:
            self._reference_exec = LocalExecution(
                self._params, self._real_dtype, device=self._device
            )
        return self._reference_exec

    def _reference_backward(self, values):
        """Reference backward: freq values -> host ``(Z, Y, X)`` slab via
        the jnp.fft engine (hermitian completion included for R2C)."""
        ref = self._reference_engine()
        values = np.asarray(values).reshape(self._params.num_values)
        out = ref.backward(values)
        fence(out)
        return ref.fetch(out) if self._is_r2c else ref.fetch_space_complex(out)

    def _reference_forward(self, space, scaling):
        """Reference forward: host space slab -> packed freq values via the
        jnp.fft engine."""
        ref = self._reference_engine()
        pair = ref.forward(
            np.asarray(space).reshape(self.dim_z, self.dim_y, self.dim_x),
            ScalingType(scaling),
        )
        fence(pair)
        return from_pair(pair)

    @property
    def space_domain_layout(self) -> str:
        """Axis order of *device-side* space-domain arrays (backward_pair output /
        forward_pair retained input): ``"zyx"`` or ``"yxz"`` (MXU engine).
        Host-facing methods always use ``(dim_z, dim_y, dim_x)``."""
        return "yxz" if self._native_transposed else "zyx"

    @property
    def _is_r2c(self) -> bool:
        return self._params.transform_type == TransformType.R2C

    def _combine_space(self, out):
        # chunked fetch above the staging threshold (execution.ExecutionBase.fetch)
        if self._is_r2c:
            arr = self._exec.fetch(out)
        else:
            arr = self._exec.fetch_space_complex(out)
        if self._native_transposed:
            arr = arr.transpose(2, 0, 1)  # native (Y,X,Z) -> public (Z,Y,X)
        return arr

    def space_domain_data(self, processing_unit: ProcessingUnit | None = None):
        """The most recent space-domain result (reference: transform.hpp:245).

        ``ProcessingUnit.HOST`` (default) returns a numpy ``(Z, Y, X)`` array;
        ``ProcessingUnit.GPU`` returns the device-resident buffer without a
        host transfer, in the engine-native layout (see
        :attr:`space_domain_layout`) — the analogue of the reference handing
        out a device pointer for ``SPFFT_PU_GPU``.
        """
        if self._space_data is None:
            raise InvalidParameterError("no space domain data available yet")
        if processing_unit is not None:
            pu = _validate_data_location(processing_unit)
            if pu == ProcessingUnit.GPU:
                return self._space_data
        return self._combine_space(self._space_data)

    def clone(self) -> "Transform":
        """Create an independent transform with identical layout.

        Reference: include/spfft/transform.hpp:133 (clone deep-copies the grid so the
        clone never shares buffers; here plans are already independent).
        """
        p = self._params
        triplets = _storage_triplets(p)
        return Transform(
            self._processing_unit,
            p.transform_type,
            p.dim_x,
            p.dim_y,
            p.dim_z,
            indices=triplets,
            grid=self._grid,
            dtype=self._real_dtype,
            engine=self._engine,
            precision=self._precision,
            device=self._device,
            guard=self._guard,
            verify=self._verify_mode,
            fuse=self._fuse,
        )

    @property
    def fused(self) -> bool:
        """Whether this plan executes through the IR-fused single program
        per direction (False: the staged per-node reference path or the
        ``ir_lower_failed`` legacy rung — see the plan card's ``ir``
        section)."""
        return bool(self._exec._ir.fused)

    # ---- introspection --------------------------------------------------------

    def report(self, *, include_compiled: bool = False) -> dict:
        """Plan card: the machine-readable record of this plan's decisions
        (grid geometry, sparsity, engine, the engine's measured choices).
        ``include_compiled=True`` additionally lowers and compiles the backward
        pipeline and adds compile wall time, memory analysis and HLO op-class
        counts. See :mod:`spfft_tpu.obs`."""
        return obs.plan_card(self, include_compiled=include_compiled)

    # ---- accessors, parity with include/spfft/transform.hpp:147-245 -----------

    @property
    def transform_type(self) -> TransformType:
        return self._params.transform_type

    @property
    def dim_x(self) -> int:
        return self._params.dim_x

    @property
    def dim_y(self) -> int:
        return self._params.dim_y

    @property
    def dim_z(self) -> int:
        return self._params.dim_z

    @property
    def local_z_length(self) -> int:
        return self._params.dim_z

    @property
    def local_z_offset(self) -> int:
        return 0

    @property
    def local_slice_size(self) -> int:
        return self.dim_x * self.dim_y * self.local_z_length

    @property
    def num_local_elements(self) -> int:
        return self._params.num_values

    @property
    def num_global_elements(self) -> int:
        return self._params.num_values

    @property
    def global_size(self) -> int:
        return self._params.total_size

    @property
    def processing_unit(self) -> ProcessingUnit:
        return self._processing_unit

    @property
    def device(self):
        """The JAX device this plan is bound to.

        Reference parity: the CUDA device current at creation pins the object
        (grid_internal.cpp:82, details.rst:104-106)."""
        return self._device

    @property
    def device_id(self) -> int:
        return getattr(self._exec.device, "id", 0)

    @property
    def num_threads(self) -> int:
        return 1

    @property
    def dtype(self) -> np.dtype:
        return self._real_dtype

    @property
    def grid(self) -> Grid | None:
        return self._grid

    def execution_mode(self) -> ExecType:
        return self._exec_mode

    def set_execution_mode(self, mode: ExecType) -> None:
        """Reference: include/spfft/transform.hpp:225 — ASYNCHRONOUS skips the
        blocking wait after dispatch (JAX dispatch is naturally async)."""
        self._exec_mode = ExecType(mode)

    def synchronize(self) -> None:
        # typed conversion mirrors the in-transform waits: ASYNCHRONOUS-mode
        # plans fence only here, and a fence failure must surface typed
        if self._space_data is not None:
            with faults.typed_execution(self._device.platform, "synchronize"):
                fence(self._space_data)


def _resolve_batch_count(count, size: int) -> int:
    """The REAL-request count of a (possibly bucket-padded) batch: default
    = the whole batch; an explicit count must address a non-empty prefix."""
    if count is None:
        return size
    count = int(count)
    if not 0 < count <= size:
        raise InvalidParameterError(
            f"batch count= must be in [1, {size}], got {count}"
        )
    return count


def _validate_pu(pu) -> None:
    try:
        ProcessingUnit(pu)
    except ValueError as e:
        raise InvalidParameterError(f"invalid processing unit: {pu!r}") from e


def _validate_data_location(pu) -> ProcessingUnit:
    """A data location must be exactly HOST or GPU — the combined HOST|GPU flag
    is valid as a grid/transform capability but not as a location (reference
    treats a combined data-location as invalid)."""
    _validate_pu(pu)
    pu = ProcessingUnit(pu)
    if pu not in (ProcessingUnit.HOST, ProcessingUnit.GPU):
        raise InvalidParameterError(f"invalid data location: {pu!r}")
    return pu


def storage_triplets_from(value_indices, stick_x, stick_y, dim_z) -> np.ndarray:
    """Decode a value->slot map (``stick_id * dim_z + z``) back to storage-order
    index triplets — THE inverse of the value-index wire rule
    (indices.convert_index_triplets). Single decoder shared by both clone()
    implementations; an encoding change is one edit here."""
    vi = np.asarray(value_indices, dtype=np.int64)
    stick_of_value = vi // dim_z
    z = vi % dim_z
    x = np.asarray(stick_x, dtype=np.int64)[stick_of_value]
    y = np.asarray(stick_y, dtype=np.int64)[stick_of_value]
    return np.stack([x, y, z], axis=1).astype(np.int32)


def _storage_triplets(p) -> np.ndarray:
    """Reconstruct storage-order index triplets from plan metadata (for clone)."""
    return storage_triplets_from(p.value_indices, p.stick_x, p.stick_y, p.dim_z)


class TransformFloat(Transform):
    """Single-precision transform, parity alias.

    Reference: include/spfft/transform_float.hpp (separate class gated behind
    SPFFT_SINGLE_PRECISION; here just ``dtype=float32``).
    """

    def __init__(self, *args, **kwargs):
        kwargs.setdefault("dtype", np.float32)
        super().__init__(*args, **kwargs)
