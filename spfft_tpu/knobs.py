"""Typed registry of every ``SPFFT_TPU_*`` environment knob.

The single allowed read path for the package's env-knob surface (enforced
by the ``knob-registry`` static-analysis checker, ``spfft_tpu.analysis``):
every knob is registered here once — name, type, default, bounds, doc — and
package code resolves values through the typed getters below instead of
ad-hoc ``os.environ`` parsing scattered per module. What that buys:

* **Loud configuration**: a malformed value raises typed
  :class:`~spfft_tpu.errors.InvalidParameterError` *naming the knob and the
  offending value* (the same rule ``faults.parse_spec`` and
  ``verify.resolve_mode`` already follow) — a typo'd knob can never be
  silently dropped or coerced to a default.
* **One source of truth for docs**: the knob table in ``docs/details.md``
  regenerates from this registry (``programs/gen_api_docs.py``), and the
  ``env-knob-docs`` checker holds the two in sync both ways — a knob cannot
  exist undocumented, and a doc row cannot outlive its knob.
* **Mechanical checkability**: registrations are pure literals, so the
  import-free analysis layer reads the whole surface via ``ast`` without
  pulling ``jax``.

Values are resolved at *call* time (no import-time caching): tests and the
tuning trial isolation scope (``tuning.env_overrides``) mutate
``os.environ`` between calls and must observe the change. Unset and
empty-string are both "use the default" (the usual shell idiom for clearing
a knob). Registered floors CLAMP (they encode "a lower value is
meaningless", e.g. at least one queue slot), while malformed *types* and
out-of-vocabulary choices RAISE — the distinction every migrated module
already drew.

``internal=True`` marks test/driver/measurement knobs exempt from the
user-facing docs table (the old ``programs/lint.py`` ``INTERNAL_KNOBS``
set, carried over as registry-level exemptions); they are documented where
they are read.
"""
from __future__ import annotations

import os

from .errors import InvalidParameterError

PREFIX = "SPFFT_TPU_"

_VALID_KINDS = ("int", "float", "bool", "str")

# the bool vocabulary the typed error message promises — exactly these;
# anything else (including yes/no) raises so a typo'd knob is never
# silently coerced
_TRUE_WORDS = ("1", "true", "on")
_FALSE_WORDS = ("0", "false", "off")


class Knob:
    """One registered environment knob (immutable record)."""

    __slots__ = (
        "name", "kind", "default", "doc", "floor", "choices", "internal",
        "doc_default",
    )

    def __init__(
        self, name, kind, default, doc, floor, choices, internal, doc_default
    ):
        self.name = name
        self.kind = kind
        self.default = default
        self.doc = doc
        self.floor = floor
        self.choices = choices
        self.internal = internal
        self.doc_default = doc_default

    def describe(self) -> dict:
        """JSON-plain registry row (docs generation / tests)."""
        return {
            "name": self.name,
            "kind": self.kind,
            "default": self.default,
            "doc": self.doc,
            "floor": self.floor,
            "choices": list(self.choices) if self.choices else None,
            "internal": self.internal,
            "doc_default": self.doc_default,
        }


REGISTRY: dict = {}


def register(
    name: str,
    kind: str,
    default,
    doc: str,
    *,
    floor=None,
    choices=None,
    internal: bool = False,
    doc_default: str | None = None,
) -> str:
    """Register one knob; returns ``name`` so modules can bind their
    ``*_ENV`` constants to the registration itself. ``doc_default``
    overrides how the docs table renders the default (e.g. ``"probe"``
    when unset means "probe the platform" rather than a plain unset)."""
    if not name.startswith(PREFIX):
        raise InvalidParameterError(
            f"knob {name!r} must start with {PREFIX!r}"
        )
    if kind not in _VALID_KINDS:
        raise InvalidParameterError(
            f"knob {name}: unknown kind {kind!r} (expected one of {_VALID_KINDS})"
        )
    if name in REGISTRY:
        raise InvalidParameterError(f"knob {name} registered twice")
    REGISTRY[name] = Knob(
        name, kind, default, doc, floor,
        tuple(choices) if choices else None, internal, doc_default,
    )
    return name


def _knob(name: str) -> Knob:
    knob = REGISTRY.get(name)
    if knob is None:
        raise InvalidParameterError(
            f"unregistered env knob {name!r}: every SPFFT_TPU_* knob must be "
            "registered in spfft_tpu.knobs"
        )
    return knob


def names(*, internal: bool | None = None) -> tuple:
    """Registered knob names, sorted; ``internal=`` filters by flag."""
    return tuple(
        sorted(
            k for k, v in REGISTRY.items()
            if internal is None or v.internal == internal
        )
    )


def describe() -> list:
    """JSON-plain dump of the whole registry (docs generation / tests)."""
    return [REGISTRY[k].describe() for k in names()]


def default(name: str):
    """The registered default of ``name`` (modules bind their ``DEFAULT_*``
    constants to this so the registry stays the single holder)."""
    return _knob(name).default


def raw(name: str):
    """The verbatim ambient value (``None`` when unset) of a REGISTERED
    knob — for signature capture (``tuning.wisdom.env_signature``) and the
    few resolvers with richer vocabularies than the typed getters
    (``ir.resolve_fuse`` tracks kwarg/env/default provenance)."""
    _knob(name)
    return os.environ.get(name)


def _ambient(name: str):
    value = os.environ.get(name)
    return None if value is None or value == "" else value


def get_int(name: str, override=None):
    """Typed integer resolve: ``override`` (an explicit caller argument)
    wins, else the env value, else the registered default; malformed values
    raise typed; a registered floor clamps."""
    knob = _knob(name)
    value = override if override is not None else _ambient(name)
    if value is None:
        value = knob.default
    if value is None:
        return None
    try:
        value = int(value)
    except (TypeError, ValueError):
        raise InvalidParameterError(
            f"invalid {name} value {value!r}: expected an integer"
        ) from None
    if knob.floor is not None:
        value = max(int(knob.floor), value)
    return value


def get_float(name: str, override=None):
    """Typed float resolve (same contract as :func:`get_int`)."""
    knob = _knob(name)
    value = override if override is not None else _ambient(name)
    if value is None:
        value = knob.default
    if value is None:
        return None
    try:
        value = float(value)
    except (TypeError, ValueError):
        raise InvalidParameterError(
            f"invalid {name} value {value!r}: expected a float"
        ) from None
    if knob.floor is not None:
        value = max(float(knob.floor), value)
    return value


def get_bool(name: str, override=None) -> bool:
    """Typed boolean resolve: ``1/true/on`` and ``0/false/off``
    (case-insensitive); anything else raises typed."""
    knob = _knob(name)
    if override is not None:
        return bool(override)
    value = _ambient(name)
    if value is None:
        return bool(knob.default)
    lowered = value.strip().lower()
    if lowered in _TRUE_WORDS:
        return True
    if lowered in _FALSE_WORDS:
        return False
    raise InvalidParameterError(
        f"invalid {name} value {value!r}: expected 0/1 (or true/false, on/off)"
    )


def get_str(name: str, override=None):
    """Typed string resolve; registered ``choices`` are enforced (an
    out-of-vocabulary value raises typed, naming the vocabulary)."""
    knob = _knob(name)
    value = override if override is not None else _ambient(name)
    if value is None:
        value = knob.default
    if value is None:
        return None
    value = str(value)
    if knob.choices and value not in knob.choices:
        raise InvalidParameterError(
            f"invalid {name} value {value!r}: expected one of "
            f"{'/'.join(knob.choices)}"
        )
    return value


_GETTERS = {
    "int": get_int,
    "float": get_float,
    "bool": get_bool,
    "str": get_str,
}


def get(name: str, override=None):
    """Kind-dispatched resolve (the generic entry point)."""
    return _GETTERS[_knob(name).kind](name, override)


# =============================================================================
# The registry. Grouped as in the docs/details.md table (which regenerates
# from these rows — edit the doc here, not there). Pure literal calls: the
# import-free analysis layer reads this surface via ``ast``.
# =============================================================================

# ---- engine / ops knobs (all measured A/B'd in BASELINE.md) -----------------
register(
    "SPFFT_TPU_GAUSS_MM", "bool", True,
    "Gauss 3-multiplication complex matmuls (`0` = textbook 4-matmul form)",
)
register(
    "SPFFT_TPU_PAIR_COPY", "bool", False,
    "`1` stacks the (re, im) copy-plan applies into one gather per pipe "
    "(measured slower on TPU)",
)
register(
    "SPFFT_TPU_SPARSE_Y", "str", "auto", choices=("auto", "0", "1"),
    doc="per-slot y-DFT contraction off the stick table; auto-engages below "
    "the measured Sy/Y < 0.6 crossover (`1`/`0` force on/off)",
)
register(
    "SPFFT_TPU_SPARSE_Y_BLOCKS", "str", "auto",
    "blocked sparse-y bucket count (the win region above the per-slot "
    "crossover); auto = 4 at dim ≤ 256, 8 above (measured sweep); `0` "
    "disables, a positive integer forces G",
)
register(
    "SPFFT_TPU_SPARSE_Y_BLOCKED_FRAC", "float", 0.8,
    "auto blocked-y engages when padded bucket rows < frac × dense extent",
)
register(
    "SPFFT_TPU_SPARSE_Y_MATRIX_MB", "int", 128,
    "bucket matrices above this ride as jit operands (local engine) or veto "
    "engagement (SPMD engines, which embed); embedded constants overflow the "
    "tunnel compile transport ≳300 MB",
)
register(
    "SPFFT_TPU_COPY_DENSE_FRAC", "float", 0.1,
    "copy-plan pipes covering at least this block fraction are padded to "
    "full coverage (direct write / dense add instead of the ~70 ns/row "
    "scatter-add)",
)
register(
    "SPFFT_TPU_XPAD", "int", 8, floor=1,
    doc="active-x extent padding quantum (sublane tile)",
)
register(
    "SPFFT_TPU_F64_STAGE_MB", "int", 256,
    "f64-emulation x-stage temp budget (chunking threshold)",
)
register(
    "SPFFT_TPU_PHASE_TABLE_MB", "int", 64,
    "above this, rotation phase tables are generated in-trace instead of "
    "embedded (512³-class plans)",
)
register(
    "SPFFT_TPU_PHASE_DEVICE_MB", "int", 2048,
    "budget for materializing phase tables as device-resident jit operands "
    "(the fast path at 512³); `0` disables operands",
)
register(
    "SPFFT_TPU_STAGE_CHUNK_MB", "int", 256,
    "host↔device staging chunk size for host-facing slabs (put/fetch); "
    "`0` = one-shot transfers",
)
register(
    "SPFFT_TPU_EXCH_ROUND_COST_KB", "int", 128,
    "per-collective-round latency (byte-equivalents) in the "
    "ExchangeType.DEFAULT cost model",
)
register(
    "SPFFT_TPU_OVERLAP_CHUNKS", "int", 1,
    "OVERLAPPED-discipline chunk count: padded exchanges split into C "
    "double-buffered chunk collectives pipelined against the neighbor "
    "chunks' FFTs (per-plan `overlap=` argument wins; under `policy=\"tuned\"` "
    "an unset knob is resolved by the autotuner — see \"Hiding the "
    "exchange\")",
)
register(
    "SPFFT_TPU_FUSE", "str", "1", choices=("0", "1"),
    doc="stage-graph fusion (`spfft_tpu.ir`): `1` compiles each direction's "
    "lowered stage graph into ONE jitted program (donated value buffers on "
    "the consuming backward, decompress/compress fused inside); `0` runs the "
    "staged per-node reference path with materialized intermediates "
    "(per-plan `fuse=` argument wins; under `policy=\"tuned\"` the "
    "fused/staged variants are trial candidates — see \"Fusing the "
    "pipeline\")",
)
register(
    "SPFFT_TPU_BATCH_FUSE", "str", "1", choices=("0", "1"),
    doc="batch fusion (`spfft_tpu.ir`): `1` lets a same-geometry batch of B "
    "transforms execute as ONE jitted program per direction (the composed "
    "stage graph vmapped over stacked per-request values/space, stacked "
    "buffers donated on the consuming backward); `0` keeps the per-request "
    "split-phase loop. Read at call time, so a serving A/B "
    "(`programs/loadgen.py --batch-fuse`) flips without rebuilding plans; "
    "batch size is tuner-owned under `policy=\"tuned\"` — see \"Batching "
    "through the IR\"",
)
register(
    "SPFFT_TPU_TWIDDLE_BF16", "bool", False,
    "`1` stores the MXU engines' DFT stage matrices in bfloat16 (mixed "
    "bf16×f32 contractions, half the twiddle HBM); f32 plans only — "
    "f64 plans ignore it; a `policy=\"tuned\"` candidate (`mxu/bf16-twiddle`), "
    "so the accuracy/speed trade is measured",
)
# ---- plan-decision / tuning knobs -------------------------------------------
register(
    "SPFFT_TPU_POLICY", "str", "default", choices=("default", "tuned"),
    doc="plan-decision policy: `tuned` resolves `ExchangeType.DEFAULT` / "
    "`engine=\"auto\"` empirically through `spfft_tpu.tuning` (per-plan "
    "`policy=` argument wins)",
)
register(
    "SPFFT_TPU_WISDOM", "str", None,
    "path of the persistent wisdom JSON the TUNED policy reads/writes; "
    "unset = process-memory store (see \"Autotuning & wisdom\")",
)
register(
    "SPFFT_TPU_TUNE_REPEATS", "int", 5, floor=1,
    doc="timed roundtrips per tuning trial candidate (best-of)",
)
register(
    "SPFFT_TPU_TUNE_WARMUP", "int", 1, floor=0,
    doc="untimed warmup roundtrips per trial candidate (compilation "
    "absorbed; `0` bills compile to the first timed repeat)",
)
register(
    "SPFFT_TPU_TUNE_CPU", "bool", False,
    "`1` lets tuning trials run on CPU-only hosts (CI/tests); default skips "
    "to the model policy so CPU timings never poison wisdom",
)
register(
    "SPFFT_TPU_ONESHOT_TRANSPORT", "str", None, choices=("ragged", "chain"),
    doc="`ragged`/`chain` overrides the ragged-all-to-all backend probe",
    doc_default="probe",
)
register(
    "SPFFT_TPU_NUM_CPU_DEVICES", "int", None,
    "virtual CPU mesh width for HOST-path distributed runs",
)
register(
    "SPFFT_TPU_ADVISORY_FENCE", "str", None, choices=("0", "1"),
    doc="`1` forces the scalar-probe synchronization fence on any platform, "
    "`0` disables it (runtimes whose `block_until_ready` genuinely waits); "
    "unset = probe the platform",
    doc_default="probe",
)
register(
    "SPFFT_TPU_ENSURE_PLATFORM", "str", None, choices=("default",),
    doc="`default` lets `ensure_virtual_devices` initialize the configured "
    "default platform (healthy pod slices); unset, it resolves virtual CPU "
    "devices without touching an uninitialized accelerator backend",
)
# ---- observability knobs ----------------------------------------------------
register(
    "SPFFT_TPU_METRICS", "bool", True,
    "`0` disables the `spfft_tpu.obs` run-metrics registry at import: "
    "instrument factories hand out shared no-ops (zero allocation on the hot "
    "path), `obs.enable()/disable()` override at runtime",
)
register(
    "SPFFT_TPU_TRACE", "bool", False,
    "`1` arms the flight recorder at import (`obs.trace.enable()` overrides "
    "at runtime); events land in a bounded ring buffer joined to plan cards "
    "by run ID",
)
register(
    "SPFFT_TPU_TRACE_CAP", "int", 4096, floor=1,
    doc="flight-recorder ring-buffer capacity (oldest events evicted; "
    "`dropped` counts them so snapshots are honest about truncation)",
)
register(
    "SPFFT_TPU_TRACE_DUMP", "str", None,
    "directory the recorder flushes to when a typed error is constructed "
    "(dump-on-error); unset = no dumps",
)
register(
    "SPFFT_TPU_PERF_FLOP_PER_BYTE", "float", 8.0,
    "machine-balance point (flop/byte) of the stage time model's "
    "compute-vs-memory roofline split",
)
# ---- fault-injection / guard knobs ------------------------------------------
register(
    "SPFFT_TPU_FAULTS", "str", None,
    "arms fault-injection sites: `\"site=kind[:rate],...\"` over the "
    "canonical `spfft_tpu.faults.SITES` vocabulary with kinds "
    "`raise`/`nan`/`corrupt`/`delay` (see \"Failure model & degradation "
    "ladder\"); unset = every site is a no-op check",
)
register(
    "SPFFT_TPU_FAULTS_SEED", "int", 0,
    "seed of the sub-1.0-rate fault draw stream — chaos runs with "
    "fractional rates replay deterministically (`faults.reseed`)",
)
register(
    "SPFFT_TPU_FAULTS_DELAY_S", "float", 0.005,
    "sleep injected by the `delay` fault kind",
)
register(
    "SPFFT_TPU_GUARD", "bool", False,
    "`1` turns on guard mode on every plan (per-plan `guard=` argument "
    "wins): NaN/Inf scans plus shape/dtype/device validation around "
    "host-facing transforms, raising typed `spfft_tpu.errors` exceptions "
    "with `guard_checks_total`/`guard_failures_total` metrics",
)
# ---- verification / breaker knobs -------------------------------------------
register(
    "SPFFT_TPU_VERIFY", "str", "0", choices=("0", "1", "on", "off", "strict"),
    doc="`1` arms ABFT self-verification on every plan (per-plan `verify=` "
    "argument wins): algebraic checks + the retry→demote→break "
    "recovery supervisor (see \"Silent-data-corruption detection & "
    "recovery\"); `strict` raises typed `VerificationError` on the first "
    "failed check with no recovery",
)
register(
    "SPFFT_TPU_VERIFY_RTOL", "float", None,
    "relative tolerance of the verification checks (default `1e-4` for f32 "
    "plans, `1e-9` for f64 — far above engine parity error, far below "
    "real corruption)",
    doc_default="per dtype",
)
register(
    "SPFFT_TPU_VERIFY_SEED", "int", 0,
    "seed of the deterministic probe-site stream — a failing `probe` "
    "check replays exactly",
)
register(
    "SPFFT_TPU_VERIFY_RETRIES", "int", 2, floor=0,
    doc="re-executions after a failed check or typed execution error, before "
    "demoting to the jnp.fft reference engine",
)
register(
    "SPFFT_TPU_VERIFY_BACKOFF_S", "float", 0.01, floor=0.0,
    doc="base of the exponential retry backoff (slept outside any lock, "
    "jittered ×[0.5, 1.5) so concurrent retriers of one failed engine "
    "spread out instead of thundering-herding it)",
)
register(
    "SPFFT_TPU_VERIFY_JITTER_SEED", "int", None,
    "seeds the retry-backoff jitter stream — a chaos run's sleep "
    "schedule replays exactly; unset, each supervisor draws from system "
    "entropy",
    doc_default="entropy",
)
register(
    "SPFFT_TPU_VERIFY_BREAKER_K", "int", 3, floor=1,
    doc="consecutive verified-failure episodes that trip an engine's "
    "process-global circuit breaker",
)
register(
    "SPFFT_TPU_VERIFY_BREAKER_COOLDOWN_S", "float", 30.0, floor=0.0,
    doc="open→half-open probe delay of the engine circuit breaker",
)
register(
    "SPFFT_TPU_FENCE_BUDGET_S", "float", 0.0,
    "wall-clock deadline for one completion fence: a wedged fence raises a "
    "typed execution error (counted in `execution_failures_total`) after "
    "the budget, with a `_platform.hang_watchdog` process backstop at "
    "2× the budget; unset = unbudgeted inline wait. Also extends over "
    "whole tuning trials (budget × (warmup + repeats + 1) per "
    "candidate): a hung candidate fails typed `TrialTimeout` into an "
    "`error` row instead of stalling `policy=\"tuned\"` planning",
)
register(
    "SPFFT_TPU_LOCKDEP", "bool", False,
    "`1` arms the runtime lockdep validator at import "
    "(`spfft_tpu.analysis.lockdep`): every `threading.Lock/RLock/Condition` "
    "the package creates is wrapped to record the REAL acquisition-order "
    "graph — cycles, and waits entered with another lock still held — and "
    "the observed graph cross-checks against the SA011 static model "
    "(`programs/analyze.py --lockdep-check`); see \"Static analysis & "
    "runtime lockdep\"",
)
register(
    "SPFFT_TPU_LOCKDEP_REPORT", "str", None,
    "path the armed lockdep validator writes its "
    "`spfft_tpu.analysis.lockdep/1` JSON report to at process exit; unset = "
    "in-process only (`lockdep.report()`)",
)
# ---- serving-layer knobs ----------------------------------------------------
register(
    "SPFFT_TPU_SERVE_QUEUE_CAP", "int", 256, floor=1,
    doc="bounded admission-queue capacity of a `serve.TransformService`: "
    "offered load beyond it is refused with typed `ServiceOverloadError` "
    "(see \"Serving under overload\")",
)
register(
    "SPFFT_TPU_SERVE_BATCH_MAX", "int", 8, floor=1,
    doc="max requests coalesced into one batched execution (and the "
    "plan-clone pool width per cached geometry)",
)
register(
    "SPFFT_TPU_SERVE_TENANT_QUOTA", "float", 0.5, floor=0.0,
    doc="fraction of the queue one tenant may hold (floor 1 slot): a "
    "runaway caller is refused at its quota even with the queue half-empty",
)
register(
    "SPFFT_TPU_SERVE_TIMEOUT_S", "float", 0.0, floor=0.0,
    doc="default per-request deadline (0 = none; per-request `timeout_s=` "
    "wins): enforced at admission AND pre-dispatch, including between retry "
    "attempts",
)
register(
    "SPFFT_TPU_SERVE_RETRIES", "int", 1, floor=0,
    doc="re-dispatches of a batch after a transient typed execution "
    "failure, with jittered exponential backoff",
)
register(
    "SPFFT_TPU_SERVE_BACKOFF_S", "float", 0.005, floor=0.0,
    doc="base of the serving retry backoff (jittered ×[0.5, 1.5), like "
    "the verify supervisor's)",
)
register(
    "SPFFT_TPU_SERVE_ON_BREAKER", "str", "demote", choices=("demote", "shed"),
    doc="what the service does with a batch whose engine's verify circuit "
    "breaker is open: `demote` reroutes through the plan's `jnp.fft` "
    "reference rung, `shed` fails the requests typed",
)
register(
    "SPFFT_TPU_SERVE_PLANS", "int", 16, floor=1,
    doc="plan-cache capacity (whole geometry entries, LRU-evicted; keyed "
    "like the wisdom store)",
)
register(
    "SPFFT_TPU_SERVE_SCHED", "bool", False,
    "`1` = the service dispatches through the task-graph scheduler: one "
    "cycle pops up to `SPFFT_TPU_SERVE_SCHED_BATCHES` coalesced batches "
    "— mixed geometries included — and runs them as one graph "
    "(see \"Scheduling transforms as a task graph\"; "
    "`programs/loadgen.py --sched` A/Bs it)",
)
register(
    "SPFFT_TPU_SERVE_SCHED_BATCHES", "int", 4, floor=1,
    doc="coalesced batches one graph-scheduled dispatch cycle may drain "
    "(the cross-geometry overlap window)",
)
register(
    "SPFFT_TPU_HOSTS_HEARTBEAT_S", "float", 0.25, floor=0.01,
    doc="heartbeat interval of the multi-host liveness monitor "
    "(`spfft_tpu.serve.cluster`): each sweep pings every live worker host, "
    "with the inter-sweep sleep jittered ×[0.5, 1.5) so fleet heartbeats "
    "never synchronize (see \"Multi-host serving & host loss\")",
)
register(
    "SPFFT_TPU_HOSTS_HEARTBEAT_MISSES", "int", 3, floor=1,
    doc="consecutive missed/failed heartbeat probes after which a worker "
    "host is declared lost (`hosts_lost_total`, the `host_lost` rung); a "
    "dead RPC transport on a live submission declares it immediately",
)
register(
    "SPFFT_TPU_HOSTS_RETRIES", "int", 2, floor=0,
    doc="times one in-flight task may be requeued onto a surviving host "
    "after its host was lost, before resolving typed `HostLostError` "
    "(the scheduler's `host_lost` outcome; `host_requeues_total` counts "
    "the moves)",
)
register(
    "SPFFT_TPU_HOSTS_BACKOFF_S", "float", 0.02, floor=0.0,
    doc="base of the jittered exponential backoff between host-loss "
    "requeue attempts (the same thundering-herd rule as every other retry "
    "loop)",
)
register(
    "SPFFT_TPU_HOSTS_WISDOM_BUNDLE", "str", None,
    "fleet wisdom bundle a worker host merges into its own store at boot "
    "(`spfft_tpu.hostmesh.warm_start`) so a fresh host serves pre-tuned; "
    "unset = cold store",
)
register(
    "SPFFT_TPU_RPC_TIMEOUT_S", "float", 30.0, floor=0.1,
    doc="per-call wall deadline of the length-prefixed-JSON RPC transport "
    "(`spfft_tpu.serve.rpc`): a connect/send/receive exceeding it raises "
    "typed `HostLostError` naming the host, feeding the requeue ladder",
)
register(
    "SPFFT_TPU_FLEET_SCRAPE_S", "float", 5.0, floor=0.1,
    doc="per-host wall deadline of one fleet metric scrape "
    "(`spfft_tpu.obs.fleet.fleet_snapshot` / the `metrics` RPC op): a "
    "host that cannot answer inside it is stamped `unreachable` in the "
    "fleet document instead of hanging the aggregation",
)
register(
    "SPFFT_TPU_SCHED_INFLIGHT", "int", 8, floor=1,
    doc="task-graph executor window: how many transform executions stay "
    "dispatched/device-resident at once before finalize must drain one "
    "(`sched.run_graph(max_inflight=)` wins)",
)
# ---- internal knobs (test / driver / measurement; documented at their read
# sites, exempt from the user-facing docs table) ------------------------------
register(
    "SPFFT_TPU_DRYRUN_BUDGET_S", "float", 300.0, internal=True,
    doc="hang-watchdog budget of the multichip dryrun driver "
    "(__graft_entry__.py)",
)
register(
    "SPFFT_TPU_MEASURE_INIT_BUDGET_S", "float", 900.0, internal=True,
    doc="hang-watchdog budget of the microbench drivers (programs/)",
)
register(
    "SPFFT_TPU_NATIVE_TEST_BUDGET_S", "float", 600.0, internal=True,
    doc="native API test budget (tests/test_native_api.py)",
)
register(
    "SPFFT_TPU_FUZZ_SEED", "int", 0, internal=True,
    doc="test-only: parity-fuzz seed offset "
    "(tests/test_engine_parity_fuzz.py)",
)
register(
    "SPFFT_TPU_BENCH_INIT_BUDGET_S", "float", 900.0, internal=True,
    doc="hang-watchdog budget of the headline bench driver (bench.py)",
)
register(
    "SPFFT_TPU_BENCH_RETRY_BUDGET_S", "float", 600.0, internal=True,
    doc="total retry budget of the headline bench driver (bench.py)",
)
