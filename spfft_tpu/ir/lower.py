"""Lowering: every engine's per-direction pipeline as a stage graph.

One builder per engine class turns the engine's extracted stage bodies
(``_st_*`` methods — the same code its monolithic impls call) into a
validated :class:`~spfft_tpu.ir.graph.StageGraph` per direction. The graphs
are *descriptions the engine executes through* (:mod:`spfft_tpu.ir.compile`
fuses each into one jitted program, or runs it node-per-dispatch), not
documentation: a stage missing here is a stage the plan does not run.

The OVERLAPPED exchange discipline is applied as a **graph rewrite** rather
than hand-threaded loop code: builders first lower the bulk-synchronous
pipeline (one exchange node), then — when the engine's tuned/requested
``overlap`` chunk count exceeds 1 — :func:`_split_slab_backward` /
:func:`_split_slab_forward` (slab engines) and the pencil tail splitters
remove the bulk z/pack/exchange segment and re-add C per-chunk node chains
pipelined against the neighbor chunks' FFT nodes, with the chunked
collectives carrying the canonical ``exchange* overlapped`` labels. The
rewritten graph reproduces the engines' PR-7 chunk loops exactly (parity
fuzz: ``tests/test_ir.py``).

Fault site ``ir.lower`` (armed by the chaos suite) models this layer
refusing to build; the engine then records ``ir_lower_failed`` and runs its
legacy monolithic jits (:func:`spfft_tpu.ir.compile.init_engine_ir`).
"""
from __future__ import annotations

import numpy as np

from ..errors import InvalidParameterError
from .graph import EdgeMeta, StageGraph


def lower_engine(engine) -> dict:
    """Lower ``engine`` to ``{"backward": graph, "forward": {scaling:
    graph}}`` — dispatched on the engine class (subclass walk so a derived
    engine inherits its family's lowering unless it registers its own)."""
    for klass in type(engine).__mro__:
        builder = _BUILDERS.get(klass.__name__)
        if builder is not None:
            return builder(engine)
    raise InvalidParameterError(
        f"ir: no lowering registered for engine {type(engine).__name__!r}"
    )


def _scalings():
    from ..types import ScalingType

    return (ScalingType.NONE, ScalingType.FULL)


# =============================================================================
# Local engines
# =============================================================================


def _lower_local_xla(e):
    p = e.params
    rt, ct = e.real_dtype, e.complex_dtype
    n = int(p.num_values)
    S, Z, Y, Xf, X = int(p.num_sticks), p.dim_z, p.dim_y, p.dim_x_freq, p.dim_x

    def backward():
        g = StageGraph("backward")
        g.add_input("values_re", dtype=rt, shape=(n,))
        g.add_input("values_im", dtype=rt, shape=(n,))
        g.batch_inputs = ("values_re", "values_im")
        g.add(
            "compression", e._st_decompress, ("values_re", "values_im"),
            ("sticks",), out_meta={"sticks": EdgeMeta(ct, (S, Z))},
        )
        g.expect_dtype("compression", "values_re", rt)
        g.expect_dtype("compression", "values_im", rt)
        cur = "sticks"
        if e.is_r2c:
            g.add(
                "stick symmetry", e._st_stick_symmetry, (cur,), ("sticks_h",),
                out_meta={"sticks_h": EdgeMeta(ct, (S, Z))},
            )
            cur = "sticks_h"
        g.add(
            "z transform", e._st_z_backward, (cur,), ("z_sticks",),
            out_meta={"z_sticks": EdgeMeta(ct, (S, Z))},
        )
        g.add(
            "expand", e._st_expand, ("z_sticks",), ("grid",),
            out_meta={"grid": EdgeMeta(ct, (Z, Y, Xf))},
        )
        cur = "grid"
        if e.is_r2c:
            g.add(
                "plane symmetry", e._st_plane_symmetry, (cur,), ("grid_h",),
                out_meta={"grid_h": EdgeMeta(ct, (Z, Y, Xf))},
            )
            cur = "grid_h"
        g.add(
            "y transform", e._st_y_backward, (cur,), ("grid_y",),
            out_meta={"grid_y": EdgeMeta(ct, (Z, Y, Xf))},
        )
        if e.is_r2c:
            g.add(
                "x transform", e._st_x_backward, ("grid_y",), ("space",),
                out_meta={"space": EdgeMeta(rt, (Z, Y, X))},
            )
            g.set_outputs(["space"])
        else:
            g.add(
                "x transform", e._st_x_backward, ("grid_y",),
                ("space_re", "space_im"),
                out_meta={
                    "space_re": EdgeMeta(rt, (Z, Y, X)),
                    "space_im": EdgeMeta(rt, (Z, Y, X)),
                },
            )
            g.set_outputs(["space_re", "space_im"])
        return g

    def forward(s):
        scale = e._scale_for(s)
        g = StageGraph("forward")
        g.add_input("space_re", dtype=rt, shape=(Z, Y, X))
        g.add_input("space_im", dtype=rt)  # (0,) placeholder for R2C
        g.batch_inputs = ("space_re", "space_im")
        g.add(
            "x transform", e._st_x_forward, ("space_re", "space_im"),
            ("grid",), out_meta={"grid": EdgeMeta(ct, (Z, Y, Xf))},
        )
        g.add(
            "y transform", e._st_y_forward, ("grid",), ("grid_y",),
            out_meta={"grid_y": EdgeMeta(ct, (Z, Y, Xf))},
        )
        g.add(
            "pack", e._st_pack, ("grid_y",), ("sticks",),
            out_meta={"sticks": EdgeMeta(ct, (S, Z))},
        )
        g.add(
            "z transform", e._st_z_forward, ("sticks",), ("z_sticks",),
            out_meta={"z_sticks": EdgeMeta(ct, (S, Z))},
        )
        g.add(
            "compression",
            lambda sticks: e._st_compress(sticks, scale),
            ("z_sticks",), ("out_re", "out_im"),
            out_meta={"out_re": EdgeMeta(rt, (n,)), "out_im": EdgeMeta(rt, (n,))},
        )
        g.set_outputs(["out_re", "out_im"])
        return g

    return {"backward": backward(), "forward": {s: forward(s) for s in _scalings()}}


def _lower_local_mxu(e):
    p = e.params
    rt = e.real_dtype
    n = int(p.num_values)
    Z = p.dim_z
    R = e._table_rows

    def backward():
        g = StageGraph("backward")
        g.add_input("values_re", dtype=rt, shape=(n,))
        g.add_input("values_im", dtype=rt, shape=(n,))
        g.add_input("phase")  # threaded plan operands (opaque varargs tuple)
        g.varargs = True
        g.batch_inputs = ("values_re", "values_im")
        g.add(
            "compression", e._st_decompress, ("values_re", "values_im"),
            ("sre", "sim"),
            out_meta={
                "sre": EdgeMeta(rt, (R, Z)), "sim": EdgeMeta(rt, (R, Z))
            },
        )
        cur = ("sre", "sim")
        if e.is_r2c and e._zero_stick_id is not None:
            g.add(
                "stick symmetry", e._st_stick_symmetry, cur, ("shre", "shim"),
                out_meta={
                    "shre": EdgeMeta(rt, (R, Z)), "shim": EdgeMeta(rt, (R, Z))
                },
            )
            cur = ("shre", "shim")
        g.add(
            "z transform", e._st_z_backward, (*cur, "phase"), ("zre", "zim"),
            out_meta={
                "zre": EdgeMeta(rt, (R, Z)), "zim": EdgeMeta(rt, (R, Z))
            },
        )
        if e._sparse_y:
            g.add(
                "y transform sparse", e._st_y_sparse_backward, ("zre", "zim"),
                ("gre", "gim"),
            )
        elif e._sparse_y_blocked is not None:
            g.add(
                "y transform blocked", e._st_y_blocked_backward,
                ("zre", "zim", "phase"), ("gre", "gim"),
            )
        else:
            g.add("expand", e._expand, ("zre", "zim"), ("ere", "eim"))
            cur = ("ere", "eim")
            if e.is_r2c and e._x0_slot is not None:
                g.add(
                    "plane symmetry", e._st_plane_symmetry, cur, ("pre", "pim")
                )
                cur = ("pre", "pim")
            g.add("y transform", e._st_y_dense_backward, cur, ("gre", "gim"))
        if e.is_r2c:
            g.add("x transform", e._st_x_backward, ("gre", "gim"), ("space",))
            g.set_outputs(["space"])
        else:
            g.add(
                "x transform", e._st_x_backward, ("gre", "gim"),
                ("space_re", "space_im"),
            )
            g.set_outputs(["space_re", "space_im"])
        return g

    def forward(s):
        g = StageGraph("forward")
        g.add_input("space_re", dtype=rt)
        g.add_input("space_im", dtype=rt)
        g.add_input("phase")
        g.varargs = True
        g.batch_inputs = ("space_re", "space_im")
        g.add(
            "x transform", e._st_x_forward, ("space_re", "space_im"),
            ("gre", "gim"),
        )
        if e._sparse_y:
            g.add(
                "y transform sparse", e._st_y_sparse_forward, ("gre", "gim"),
                ("sre", "sim"),
            )
        elif e._sparse_y_blocked is not None:
            g.add(
                "y transform blocked", e._st_y_blocked_forward,
                ("gre", "gim", "phase"), ("sre", "sim"),
            )
        else:
            g.add("y transform", e._st_y_dense_forward, ("gre", "gim"), ("yre", "yim"))
            g.add("pack", e._st_pack, ("yre", "yim"), ("sre", "sim"))
        g.add(
            "z transform",
            lambda sre, sim, phase: e._st_z_forward(sre, sim, phase, s),
            ("sre", "sim", "phase"), ("zre", "zim"),
        )
        g.add(
            "compression", e._compress, ("zre", "zim"), ("out_re", "out_im"),
            out_meta={"out_re": EdgeMeta(rt, (n,)), "out_im": EdgeMeta(rt, (n,))},
        )
        g.set_outputs(["out_re", "out_im"])
        return g

    return {"backward": backward(), "forward": {s: forward(s) for s in _scalings()}}


# =============================================================================
# 1-D slab mesh engines
# =============================================================================


def _split_slab_backward(g, e, sticks_edge):
    """OVERLAPPED rewrite (backward, slab engines): replace the bulk
    [z transform -> pack -> exchange] segment with C per-chunk chains whose
    collectives carry the ``exchange overlapped`` label, re-wiring the
    unpack node to consume every chunk's receive — the pipelined all-to-all
    of arxiv.org/pdf/1804.09536 as a graph transformation."""
    ct = e.complex_dtype
    p = e.params
    L = e._L
    pair = _is_pair_engine(e)
    phase = _phase_edges(e)
    for name in ("z transform", "pack", "exchange", "unpack"):
        g.remove(name)
    if pair and not phase:
        phase = _delta_phase_edges(g, e)
    recv_edges = []
    for k, (c0, c1) in enumerate(e._chunks):
        W = c1 - c0
        sfx = f"@{k}"
        if pair:
            zfn = (
                (
                    lambda sre, sim, pre, pim, c0=c0, c1=c1: e._st_z_backward(
                        sre, sim, pre, pim, zwin=(c0, c1)
                    )
                )
                if phase
                else (
                    lambda sre, sim, c0=c0, c1=c1: e._st_z_backward(
                        sre, sim, zwin=(c0, c1)
                    )
                )
            )
            g.add(
                "z transform",
                zfn,
                (*sticks_edge, *phase),
                (f"zre{sfx}", f"zim{sfx}"),
                name=f"z transform{sfx}",
                out_meta={
                    f"zre{sfx}": EdgeMeta(e.real_dtype, (W, p.dim_z)),
                    f"zim{sfx}": EdgeMeta(e.real_dtype, (W, p.dim_z)),
                },
            )
            g.add(
                "pack", e._st_pack, (f"zre{sfx}", f"zim{sfx}"),
                (f"bre{sfx}", f"bim{sfx}"), name=f"pack{sfx}",
                out_meta={
                    f"bre{sfx}": EdgeMeta(e.real_dtype, (p.num_shards, W, L)),
                    f"bim{sfx}": EdgeMeta(e.real_dtype, (p.num_shards, W, L)),
                },
            )
            g.add(
                "exchange overlapped", e._exchange,
                (f"bre{sfx}", f"bim{sfx}"), (f"rre{sfx}", f"rim{sfx}"),
                name=f"exchange overlapped{sfx}",
                out_meta={
                    f"rre{sfx}": EdgeMeta(e.real_dtype, (p.num_shards, W, L)),
                    f"rim{sfx}": EdgeMeta(e.real_dtype, (p.num_shards, W, L)),
                },
            )
            recv_edges.append((f"rre{sfx}", f"rim{sfx}"))
        else:
            g.add(
                "z transform",
                lambda sticks, c0=c0, c1=c1: e._st_z_backward(sticks[c0:c1]),
                sticks_edge, (f"z_sticks{sfx}",), name=f"z transform{sfx}",
                out_meta={f"z_sticks{sfx}": EdgeMeta(ct, (W, p.dim_z))},
            )
            g.add(
                "pack", e._st_pack, (f"z_sticks{sfx}",), (f"buf{sfx}",),
                name=f"pack{sfx}",
                out_meta={f"buf{sfx}": EdgeMeta(ct, (p.num_shards, L, W))},
            )
            g.add(
                "exchange overlapped", e._st_exchange, (f"buf{sfx}",),
                (f"recv{sfx}",), name=f"exchange overlapped{sfx}",
                out_meta={f"recv{sfx}": EdgeMeta(ct, (p.num_shards, L, W))},
            )
            recv_edges.append((f"recv{sfx}",))
    if pair:
        # _st_unpack's halves contract: first all re edges, then all im
        flat = tuple(pe[0] for pe in recv_edges) + tuple(
            pe[1] for pe in recv_edges
        )
    else:
        flat = tuple(edge for pair_edges in recv_edges for edge in pair_edges)
    out_meta = _slab_unpack_meta(e)
    g.add(
        "unpack", e._st_unpack, flat, tuple(out_meta), out_meta=out_meta
    )


def _is_pair_engine(e) -> bool:
    """MXU mesh engines carry (re, im) real pairs end to end; the XLA
    engines carry complex arrays. The graph edge layout follows."""
    return hasattr(e, "_decompress_branches")


def _delta_phase_edges(g, e):
    """Delta-rep hoist for the slab MXU chunk rewrites: PR-7 generated the
    in-trace (S, Z) alignment-phase tables once per direction and sliced per
    chunk; one producer node (stage ``z transform`` — where table generation
    has always been charged) restores that shape, its outputs threaded into
    every chunk's z node. Table-form reps already arrive hoisted as the
    staged ``phase_re``/``phase_im`` operand edges; plans without rotations
    have no tables to hoist (empty)."""
    rep = getattr(e, "_align_rep", None)
    if rep is None or rep[0] != "delta":
        return ()
    rt = e.real_dtype
    g.add(
        "z transform", e._st_phase_hoist, (), ("phre", "phim"),
        name="z transform phase",
        out_meta={
            "phre": EdgeMeta(rt, (e._S, e.params.dim_z)),
            "phim": EdgeMeta(rt, (e._S, e.params.dim_z)),
        },
    )
    return ("phre", "phim")


def _phase_edges(e):
    """The 1-D MXU engine's staged alignment-phase operand edges, when the
    plan rotates with table-form reps (empty otherwise)."""
    return (
        ("phase_re", "phase_im")
        if getattr(e, "_align_phase", None) is not None
        else ()
    )


def _slab_unpack_meta(e):
    """Output edges + metadata of the slab backward unpack stage (variant-
    dependent on the MXU engine: compact planes, sparse-y table, or blocked
    flats)."""
    p = e.params
    L, Y, Xf = e._L, p.dim_y, p.dim_x_freq
    if not _is_pair_engine(e):
        return {"slab": EdgeMeta(e.complex_dtype, (L, Y, Xf))}
    rt = e.real_dtype
    A = e._num_x_active
    if e._sparse_y:
        shape = (A, e._sy, L)
    elif e._sparse_y_blocked is not None:
        shape = (e._rb, L)
    else:
        shape = (L, Y, A)
    return {"gre": EdgeMeta(rt, shape), "gim": EdgeMeta(rt, shape)}


def _lower_slab_xla(e):
    p = e.params
    rt, ct = e.real_dtype, e.complex_dtype
    S, L, V = e._S, e._L, e._V
    Z, Y, Xf, X = p.dim_z, p.dim_y, p.dim_x_freq, p.dim_x
    P = p.num_shards

    def backward():
        g = StageGraph("backward")
        g.add_input("values_re", dtype=rt, shape=(V,))
        g.add_input("values_im", dtype=rt, shape=(V,))
        g.add_input("value_indices", dtype=np.int32, shape=(V,))
        g.batch_inputs = ("values_re", "values_im")
        g.add(
            "compression", e._st_decompress,
            ("values_re", "values_im", "value_indices"), ("sticks",),
            out_meta={"sticks": EdgeMeta(ct, (S, Z))},
        )
        g.expect_dtype("compression", "values_re", rt)
        cur = "sticks"
        if e.is_r2c and p.zero_stick_shard >= 0:
            g.add(
                "stick symmetry", e._st_stick_symmetry, (cur,), ("sticks_h",),
                out_meta={"sticks_h": EdgeMeta(ct, (S, Z))},
            )
            cur = "sticks_h"
        g.add(
            "z transform", e._st_z_backward, (cur,), ("z_sticks",),
            out_meta={"z_sticks": EdgeMeta(ct, (S, Z))},
        )
        if e._ragged is not None:
            g.add(
                "exchange", e._st_ragged_exchange_backward, ("z_sticks",),
                ("planes",), out_meta={"planes": EdgeMeta(ct, (Y * Xf, L))},
            )
            g.add(
                "unpack", e._st_ragged_unpack, ("planes",), ("slab",),
                out_meta={"slab": EdgeMeta(ct, (L, Y, Xf))},
            )
        else:
            g.add(
                "pack", e._st_pack, ("z_sticks",), ("buf",),
                out_meta={"buf": EdgeMeta(ct, (P, L, S))},
            )
            g.add(
                "exchange", e._st_exchange, ("buf",), ("recv",),
                out_meta={"recv": EdgeMeta(ct, (P, L, S))},
            )
            g.add(
                "unpack", e._st_unpack, ("recv",), ("slab",),
                out_meta={"slab": EdgeMeta(ct, (L, Y, Xf))},
            )
        cur = "slab"
        if e.is_r2c:
            g.add(
                "plane symmetry", e._st_plane_symmetry, (cur,), ("slab_h",),
                out_meta={"slab_h": EdgeMeta(ct, (L, Y, Xf))},
            )
            cur = "slab_h"
        g.add(
            "y transform", e._st_y_backward, (cur,), ("slab_y",),
            out_meta={"slab_y": EdgeMeta(ct, (L, Y, Xf))},
        )
        if e.is_r2c:
            g.add(
                "x transform", e._st_x_backward, ("slab_y",), ("space",),
                out_meta={"space": EdgeMeta(rt, (L, Y, X))},
            )
            g.set_outputs(["space"])
        else:
            g.add(
                "x transform", e._st_x_backward, ("slab_y",),
                ("space_re", "space_im"),
                out_meta={
                    "space_re": EdgeMeta(rt, (L, Y, X)),
                    "space_im": EdgeMeta(rt, (L, Y, X)),
                },
            )
            g.set_outputs(["space_re", "space_im"])
        if e._overlap > 1:
            sticks = (
                ("sticks_h",)
                if e.is_r2c and p.zero_stick_shard >= 0
                else ("sticks",)
            )
            _split_slab_backward(g, e, sticks)
        return g

    def forward(s):
        scale = None if s.name == "NONE" else 1.0 / p.total_size
        g = StageGraph("forward")
        if e.is_r2c:
            g.add_input("space_re", dtype=rt, shape=(L, Y, X))
            g.add_input("value_indices", dtype=np.int32, shape=(V,))
            g.batch_inputs = ("space_re",)
            g.add(
                "x transform", e._st_x_forward, ("space_re",), ("grid",),
                out_meta={"grid": EdgeMeta(ct, (L, Y, Xf))},
            )
        else:
            g.add_input("space_re", dtype=rt, shape=(L, Y, X))
            g.add_input("space_im", dtype=rt, shape=(L, Y, X))
            g.add_input("value_indices", dtype=np.int32, shape=(V,))
            g.batch_inputs = ("space_re", "space_im")
            g.add(
                "x transform", e._st_x_forward, ("space_re", "space_im"),
                ("grid",), out_meta={"grid": EdgeMeta(ct, (L, Y, Xf))},
            )
        g.add(
            "y transform", e._st_y_forward, ("grid",), ("grid_y",),
            out_meta={"grid_y": EdgeMeta(ct, (L, Y, Xf))},
        )
        if e._ragged is not None:
            g.add(
                "exchange", e._st_ragged_exchange_forward, ("grid_y",),
                ("sticks",), out_meta={"sticks": EdgeMeta(ct, (S, Z))},
            )
            g.add(
                "z transform", e._st_z_forward, ("sticks",), ("z_sticks",),
                out_meta={"z_sticks": EdgeMeta(ct, (S, Z))},
            )
        else:
            g.add(
                "pack", e._st_pack_fwd, ("grid_y",), ("buf",),
                out_meta={"buf": EdgeMeta(ct, (P, L, S))},
            )
            g.add(
                "exchange", e._st_exchange, ("buf",), ("recv",),
                out_meta={"recv": EdgeMeta(ct, (P, L, S))},
            )
            g.add(
                "unpack", e._st_unpack_fwd, ("recv",), ("sticks",),
                out_meta={"sticks": EdgeMeta(ct, (S, Z))},
            )
            g.add(
                "z transform", e._st_z_forward, ("sticks",), ("z_sticks",),
                out_meta={"z_sticks": EdgeMeta(ct, (S, Z))},
            )
        g.add(
            "compression",
            lambda sticks, vi: e._st_compress(sticks, vi, scale),
            ("z_sticks", "value_indices"), ("out_re", "out_im"),
            out_meta={
                "out_re": EdgeMeta(rt, (V,)), "out_im": EdgeMeta(rt, (V,))
            },
        )
        g.set_outputs(["out_re", "out_im"])
        if e._overlap > 1:
            _split_slab_forward_xla(g, e)
        return g

    return {"backward": backward(), "forward": {s: forward(s) for s in _scalings()}}


def _split_slab_forward_xla(g, e):
    """OVERLAPPED rewrite (forward, slab XLA engine): per-chunk
    [pack -> exchange overlapped -> unpack -> z transform] chains off the
    shared grid, concatenated back into the stick table."""
    p = e.params
    ct = e.complex_dtype
    L = e._L
    for name in ("pack", "exchange", "unpack", "z transform"):
        g.remove(name)
    part_edges = []
    for k, (c0, c1) in enumerate(e._chunks):
        W = c1 - c0
        sfx = f"@{k}"
        g.add(
            "pack",
            lambda grid, c0=c0, c1=c1: e._st_pack_fwd(grid, c0, c1),
            ("grid_y",), (f"buf{sfx}",), name=f"pack{sfx}",
            out_meta={f"buf{sfx}": EdgeMeta(ct, (p.num_shards, L, W))},
        )
        g.add(
            "exchange overlapped", e._st_exchange, (f"buf{sfx}",),
            (f"recv{sfx}",), name=f"exchange overlapped{sfx}",
            out_meta={f"recv{sfx}": EdgeMeta(ct, (p.num_shards, L, W))},
        )
        g.add(
            "unpack", e._st_unpack_fwd, (f"recv{sfx}",), (f"sz{sfx}",),
            name=f"unpack{sfx}",
            out_meta={f"sz{sfx}": EdgeMeta(ct, (W, p.dim_z))},
        )
        g.add(
            "z transform", e._st_z_forward, (f"sz{sfx}",), (f"zc{sfx}",),
            name=f"z transform{sfx}",
            out_meta={f"zc{sfx}": EdgeMeta(ct, (W, p.dim_z))},
        )
        part_edges.append(f"zc{sfx}")
    g.add(
        "z transform", e._st_concat_sticks, tuple(part_edges), ("z_sticks",),
        name="z transform concat",
        out_meta={"z_sticks": EdgeMeta(ct, (e._S, p.dim_z))},
    )


def _lower_slab_mxu(e):
    p = e.params
    rt = e.real_dtype
    S, L, V = e._S, e._L, e._V
    Z, Y, X = p.dim_z, p.dim_y, p.dim_x
    P = p.num_shards
    phase = _phase_edges(e)
    pmeta = {pe: EdgeMeta(rt, (S, Z)) for pe in phase}

    def backward():
        g = StageGraph("backward")
        g.add_input("values_re", dtype=rt, shape=(V,))
        g.add_input("values_im", dtype=rt, shape=(V,))
        for pe in phase:
            g.add_input(pe, dtype=rt, shape=(S, Z))
        g.batch_inputs = ("values_re", "values_im")
        g.add(
            "compression", e._st_decompress, ("values_re", "values_im"),
            ("sre", "sim"),
            out_meta={"sre": EdgeMeta(rt, (S, Z)), "sim": EdgeMeta(rt, (S, Z))},
        )
        cur = ("sre", "sim")
        if e.is_r2c and p.zero_stick_shard >= 0:
            g.add(
                "stick symmetry", e._st_stick_symmetry, cur, ("shre", "shim"),
                out_meta={
                    "shre": EdgeMeta(rt, (S, Z)), "shim": EdgeMeta(rt, (S, Z))
                },
            )
            cur = ("shre", "shim")
        unpack_meta = _slab_unpack_meta(e)
        g.add(
            "z transform",
            (lambda sre, sim, pre, pim: e._st_z_backward(sre, sim, pre, pim))
            if phase
            else (lambda sre, sim: e._st_z_backward(sre, sim)),
            (*cur, *phase), ("zre", "zim"),
            out_meta={
                "zre": EdgeMeta(rt, (S, Z)), "zim": EdgeMeta(rt, (S, Z))
            },
        )
        if e._ragged is not None:
            g.add(
                "exchange", e._st_ragged_exchange_backward, ("zre", "zim"),
                tuple(unpack_meta), out_meta=unpack_meta,
            )
        else:
            g.add(
                "pack", e._st_pack, ("zre", "zim"), ("bre", "bim"),
                out_meta={
                    "bre": EdgeMeta(rt, (P, S, L)),
                    "bim": EdgeMeta(rt, (P, S, L)),
                },
            )
            g.add(
                "exchange", e._exchange, ("bre", "bim"), ("rre", "rim"),
                out_meta={
                    "rre": EdgeMeta(rt, (P, S, L)),
                    "rim": EdgeMeta(rt, (P, S, L)),
                },
            )
            g.add(
                "unpack", e._st_unpack, ("rre", "rim"), tuple(unpack_meta),
                out_meta=unpack_meta,
            )
        cur = tuple(unpack_meta)
        if e._plane_symmetry_standalone():
            sym_meta = {
                "psre": unpack_meta[cur[0]], "psim": unpack_meta[cur[1]]
            }
            g.add(
                "plane symmetry", e._st_plane_symmetry, cur, ("psre", "psim"),
                out_meta=sym_meta,
            )
            cur = ("psre", "psim")
        ymeta = EdgeMeta(rt, (L, Y, e._num_x_active))
        g.add(
            e._y_stage_scope(), e._st_y_backward, cur, ("yre", "yim"),
            out_meta={"yre": ymeta, "yim": ymeta},
        )
        if e.is_r2c:
            g.add(
                "x transform", e._st_x_backward, ("yre", "yim"), ("space",),
                out_meta={"space": EdgeMeta(rt, (L, Y, X))},
            )
            g.set_outputs(["space"])
        else:
            g.add(
                "x transform", e._st_x_backward, ("yre", "yim"),
                ("space_re", "space_im"),
                out_meta={
                    "space_re": EdgeMeta(rt, (L, Y, X)),
                    "space_im": EdgeMeta(rt, (L, Y, X)),
                },
            )
            g.set_outputs(["space_re", "space_im"])
        if e._overlap > 1:
            sticks = (
                ("shre", "shim")
                if e.is_r2c and p.zero_stick_shard >= 0
                else ("sre", "sim")
            )
            _split_slab_backward(g, e, sticks)
        return g

    def forward(s):
        g = StageGraph("forward")
        g.add_input("space_re", dtype=rt, shape=(L, Y, X))
        if not e.is_r2c:
            g.add_input("space_im", dtype=rt, shape=(L, Y, X))
        for pe in phase:
            g.add_input(pe, dtype=rt, shape=(S, Z))
        g.batch_inputs = (
            ("space_re",) if e.is_r2c else ("space_re", "space_im")
        )
        A = e._num_x_active
        xmeta = EdgeMeta(rt, (L, Y, A))
        g.add(
            "x transform", e._st_x_forward,
            ("space_re",) if e.is_r2c else ("space_re", "space_im"),
            ("gre", "gim"), out_meta={"gre": xmeta, "gim": xmeta},
        )
        if e._sparse_y:
            yshape = (A, e._sy, L)
        elif e._sparse_y_blocked is not None:
            yshape = (e._rb, L)
        else:
            yshape = (L, Y, A)
        ymeta = EdgeMeta(rt, yshape)
        g.add(
            e._y_stage_scope(), e._st_y_forward, ("gre", "gim"),
            ("yre", "yim"), out_meta={"yre": ymeta, "yim": ymeta},
        )
        if e._ragged is not None:
            g.add(
                "exchange", e._st_ragged_exchange_forward, ("yre", "yim"),
                ("sre", "sim"),
                out_meta={
                    "sre": EdgeMeta(rt, (S, Z)), "sim": EdgeMeta(rt, (S, Z))
                },
            )
        else:
            fmeta = EdgeMeta(rt, (e._plane_slots + 1, L))
            g.add("pack", e._st_forward_flats, ("yre", "yim"),
                  ("fre", "fim"), name="pack flats",
                  out_meta={"fre": fmeta, "fim": fmeta})
            g.add(
                "pack", e._st_pack_fwd, ("fre", "fim"), ("bre", "bim"),
                out_meta={
                    "bre": EdgeMeta(rt, (P, S, L)),
                    "bim": EdgeMeta(rt, (P, S, L)),
                },
            )
            g.add(
                "exchange", e._exchange, ("bre", "bim"), ("rre", "rim"),
                out_meta={
                    "rre": EdgeMeta(rt, (P, S, L)),
                    "rim": EdgeMeta(rt, (P, S, L)),
                },
            )
            g.add(
                "unpack", e._st_unpack_fwd, ("rre", "rim"), ("sre", "sim"),
                out_meta={
                    "sre": EdgeMeta(rt, (S, Z)), "sim": EdgeMeta(rt, (S, Z))
                },
            )
        g.add(
            "z transform",
            (
                (lambda sre, sim, pre, pim: e._st_z_forward(sre, sim, s, pre, pim))
                if phase
                else (lambda sre, sim: e._st_z_forward(sre, sim, s))
            ),
            ("sre", "sim", *phase), ("zre", "zim"),
            out_meta={
                "zre": EdgeMeta(rt, (S, Z)), "zim": EdgeMeta(rt, (S, Z))
            },
        )
        g.add(
            "compression", e._st_compress, ("zre", "zim"),
            ("out_re", "out_im"),
            out_meta={
                "out_re": EdgeMeta(rt, (V,)), "out_im": EdgeMeta(rt, (V,))
            },
        )
        g.set_outputs(["out_re", "out_im"])
        if e._overlap > 1:
            _split_slab_forward_mxu(g, e, s)
        return g

    return {"backward": backward(), "forward": {s: forward(s) for s in _scalings()}}


def _split_slab_forward_mxu(g, e, scaling):
    """OVERLAPPED rewrite (forward, slab MXU engine): per-chunk
    [pack -> exchange overlapped -> unpack -> z transform] pair chains off
    the hoisted plane flats, concatenated back into the stick pair."""
    p = e.params
    rt = e.real_dtype
    S, L, Z = e._S, e._L, p.dim_z
    phase = _phase_edges(e)
    for name in ("pack", "exchange", "unpack", "z transform"):
        g.remove(name)
    if not phase:
        phase = _delta_phase_edges(g, e)
    parts = []
    for k, (c0, c1) in enumerate(e._chunks):
        W = c1 - c0
        sfx = f"@{k}"
        g.add(
            "pack",
            lambda fre, fim, c0=c0, c1=c1: e._st_pack_fwd(fre, fim, c0, c1),
            ("fre", "fim"), (f"bre{sfx}", f"bim{sfx}"), name=f"pack{sfx}",
            out_meta={
                f"bre{sfx}": EdgeMeta(rt, (p.num_shards, W, L)),
                f"bim{sfx}": EdgeMeta(rt, (p.num_shards, W, L)),
            },
        )
        g.add(
            "exchange overlapped", e._exchange,
            (f"bre{sfx}", f"bim{sfx}"), (f"rre{sfx}", f"rim{sfx}"),
            name=f"exchange overlapped{sfx}",
            out_meta={
                f"rre{sfx}": EdgeMeta(rt, (p.num_shards, W, L)),
                f"rim{sfx}": EdgeMeta(rt, (p.num_shards, W, L)),
            },
        )
        g.add(
            "unpack", e._st_unpack_fwd, (f"rre{sfx}", f"rim{sfx}"),
            (f"cre{sfx}", f"cim{sfx}"), name=f"unpack{sfx}",
            out_meta={
                f"cre{sfx}": EdgeMeta(rt, (W, Z)),
                f"cim{sfx}": EdgeMeta(rt, (W, Z)),
            },
        )
        g.add(
            "z transform",
            (
                (
                    lambda cre, cim, pre, pim, c0=c0, c1=c1: e._st_z_forward(
                        cre, cim, scaling, pre, pim, zwin=(c0, c1)
                    )
                )
                if phase
                else (
                    lambda cre, cim, c0=c0, c1=c1: e._st_z_forward(
                        cre, cim, scaling, zwin=(c0, c1)
                    )
                )
            ),
            (f"cre{sfx}", f"cim{sfx}", *phase),
            (f"zcre{sfx}", f"zcim{sfx}"), name=f"z transform{sfx}",
            out_meta={
                f"zcre{sfx}": EdgeMeta(rt, (W, Z)),
                f"zcim{sfx}": EdgeMeta(rt, (W, Z)),
            },
        )
        parts.append((f"zcre{sfx}", f"zcim{sfx}"))
    g.add(
        "z transform", e._st_concat_pair,
        tuple(pr[0] for pr in parts) + tuple(pr[1] for pr in parts),
        ("zre", "zim"), name="z transform concat",
        out_meta={"zre": EdgeMeta(rt, (S, Z)), "zim": EdgeMeta(rt, (S, Z))},
    )


# =============================================================================
# 2-D pencil mesh engines
# =============================================================================


def _pencil_backward_tail(g, e, chunks, overlapped):
    """Append the post-z pencil pipeline per z-window chunk; returns the
    added node names (the OVERLAPPED rewrite removes and re-adds them)."""
    p = e.params
    ct = e.complex_dtype
    Y, Xf, X = p.dim_y, p.dim_x_freq, p.dim_x
    P1, P2, Ax, Ly, SG = e.P1, e.P2, e._Ax, e._Ly, e._SG
    Pn = p.num_shards
    xa = "exchange A overlapped" if overlapped else "exchange A"
    xb = "exchange B overlapped" if overlapped else "exchange B"
    names = []

    def add(stage, fn, inputs, outputs, name=None, out_meta=None):
        g.add(stage, fn, inputs, outputs, name=name, out_meta=out_meta)
        names.append(name or stage)

    part_edges = []
    for k, (c0, c1) in enumerate(chunks):
        W = c1 - c0
        sfx = f"@{k}"
        add(
            "pack A",
            lambda sticks, c0=c0, c1=c1: e._st_pack_a(sticks, (c0, c1)),
            ("z_sticks",), (f"bufA{sfx}",), name=f"pack A{sfx}",
            out_meta={f"bufA{sfx}": EdgeMeta(ct, (Pn, SG, W))},
        )
        add(
            xa, e._st_exchange_a, (f"bufA{sfx}",), (f"recvA{sfx}",),
            name=f"{xa}{sfx}",
            out_meta={f"recvA{sfx}": EdgeMeta(ct, (Pn, SG, W))},
        )
        add(
            "unpack A", e._st_unpack_a, (f"recvA{sfx}",), (f"grid{sfx}",),
            name=f"unpack A{sfx}",
            out_meta={f"grid{sfx}": EdgeMeta(ct, (Y, Ax, W))},
        )
        cur = f"grid{sfx}"
        if e.is_r2c and e._have_x0:
            add(
                "plane symmetry", e._st_plane_symmetry, (cur,),
                (f"gridh{sfx}",), name=f"plane symmetry{sfx}",
                out_meta={f"gridh{sfx}": EdgeMeta(ct, (Y, Ax, W))},
            )
            cur = f"gridh{sfx}"
        add(
            "y transform", e._st_y_backward, (cur,), (f"gridy{sfx}",),
            name=f"y transform{sfx}",
            out_meta={f"gridy{sfx}": EdgeMeta(ct, (Y, Ax, W))},
        )
        add(
            "pack B", e._st_pack_b, (f"gridy{sfx}",), (f"bufB{sfx}",),
            name=f"pack B{sfx}",
            out_meta={f"bufB{sfx}": EdgeMeta(ct, (P1, Ly, Ax, W))},
        )
        add(
            xb, e._st_exchange_b, (f"bufB{sfx}",), (f"recvB{sfx}",),
            name=f"{xb}{sfx}",
            out_meta={f"recvB{sfx}": EdgeMeta(ct, (P1, Ly, Ax, W))},
        )
        add(
            "unpack B", e._st_unpack_b, (f"recvB{sfx}",), (f"slab{sfx}",),
            name=f"unpack B{sfx}",
            out_meta={f"slab{sfx}": EdgeMeta(ct, (Ly, Xf, W))},
        )
        add(
            "x transform", e._st_x_backward, (f"slab{sfx}",), (f"part{sfx}",),
            name=f"x transform{sfx}",
            out_meta={
                f"part{sfx}": EdgeMeta(
                    e.real_dtype if e.is_r2c else ct, (W, Ly, X)
                )
            },
        )
        part_edges.append(f"part{sfx}")
    if e.is_r2c:
        add(
            "x transform", e._st_space_out, tuple(part_edges), ("space",),
            name="x transform out",
            out_meta={"space": EdgeMeta(e.real_dtype, (e._Lz, Ly, X))},
        )
        g.set_outputs(["space"])
    else:
        add(
            "x transform", e._st_space_out, tuple(part_edges),
            ("space_re", "space_im"), name="x transform out",
            out_meta={
                "space_re": EdgeMeta(e.real_dtype, (e._Lz, Ly, X)),
                "space_im": EdgeMeta(e.real_dtype, (e._Lz, Ly, X)),
            },
        )
        g.set_outputs(["space_re", "space_im"])
    return names


def _pencil_forward_head(g, e, chunks, overlapped, pair):
    """Append the pre-unpack-A forward pencil pipeline per z-window chunk;
    returns (added node names, receive edges)."""
    p = e.params
    ct = e.complex_dtype
    rt = e.real_dtype
    Xf, X = p.dim_x_freq, p.dim_x
    P1, Ax, Ly, SG = e.P1, e._Ax, e._Ly, e._SG
    Pn = p.num_shards
    xa = "exchange A overlapped" if overlapped else "exchange A"
    xb = "exchange B overlapped" if overlapped else "exchange B"
    names = []
    recv_edges = []

    def add(stage, fn, inputs, outputs, name=None, out_meta=None):
        g.add(stage, fn, inputs, outputs, name=name, out_meta=out_meta)
        names.append(name or stage)

    space_in = ("space_re",) if e.is_r2c else ("space_re", "space_im")
    for k, (c0, c1) in enumerate(chunks):
        W = c1 - c0
        sfx = f"@{k}"
        if pair:
            add(
                "x transform",
                (
                    lambda sre, c0=c0, c1=c1: e._st_x_forward(
                        sre, zwin=(c0, c1)
                    )
                )
                if e.is_r2c
                else (
                    lambda sre, sim, c0=c0, c1=c1: e._st_x_forward(
                        sre, sim, zwin=(c0, c1)
                    )
                ),
                space_in, (f"hre{sfx}", f"him{sfx}"),
                name=f"x transform{sfx}",
                out_meta={
                    f"hre{sfx}": EdgeMeta(rt, (Ly, P1 * Ax, W)),
                    f"him{sfx}": EdgeMeta(rt, (Ly, P1 * Ax, W)),
                },
            )
            add(
                "pack B", e._st_pack_b_rev_pair, (f"hre{sfx}", f"him{sfx}"),
                (f"bBre{sfx}", f"bBim{sfx}"), name=f"pack B{sfx}",
                out_meta={
                    f"bBre{sfx}": EdgeMeta(rt, (P1, Ly, Ax, W)),
                    f"bBim{sfx}": EdgeMeta(rt, (P1, Ly, Ax, W)),
                },
            )
            add(
                xb,
                lambda bre, bim: e._st_exchange_b_pair(bre, bim, reverse=True),
                (f"bBre{sfx}", f"bBim{sfx}"), (f"rBre{sfx}", f"rBim{sfx}"),
                name=f"{xb}{sfx}",
                out_meta={
                    f"rBre{sfx}": EdgeMeta(rt, (P1, Ly, Ax, W)),
                    f"rBim{sfx}": EdgeMeta(rt, (P1, Ly, Ax, W)),
                },
            )
            add(
                "unpack B", e._st_unpack_b_rev_pair,
                (f"rBre{sfx}", f"rBim{sfx}"), (f"gre{sfx}", f"gim{sfx}"),
                name=f"unpack B{sfx}",
                out_meta={
                    f"gre{sfx}": EdgeMeta(rt, (p.dim_y, Ax, W)),
                    f"gim{sfx}": EdgeMeta(rt, (p.dim_y, Ax, W)),
                },
            )
            ymeta = EdgeMeta(rt, (p.dim_y, Ax, W))
            add(
                "y transform", e._st_y_forward, (f"gre{sfx}", f"gim{sfx}"),
                (f"yre{sfx}", f"yim{sfx}"), name=f"y transform{sfx}",
                out_meta={f"yre{sfx}": ymeta, f"yim{sfx}": ymeta},
            )
            add(
                "pack A",
                lambda gre, gim, c0=c0: e._st_pack_a_rev_pair(gre, gim, c0),
                (f"yre{sfx}", f"yim{sfx}"), (f"bAre{sfx}", f"bAim{sfx}"),
                name=f"pack A{sfx}",
                out_meta={
                    f"bAre{sfx}": EdgeMeta(rt, (Pn, SG, W)),
                    f"bAim{sfx}": EdgeMeta(rt, (Pn, SG, W)),
                },
            )
            add(
                xa,
                lambda bre, bim: e._st_exchange_a_pair(bre, bim, reverse=True),
                (f"bAre{sfx}", f"bAim{sfx}"), (f"rAre{sfx}", f"rAim{sfx}"),
                name=f"{xa}{sfx}",
                out_meta={
                    f"rAre{sfx}": EdgeMeta(rt, (Pn, SG, W)),
                    f"rAim{sfx}": EdgeMeta(rt, (Pn, SG, W)),
                },
            )
            recv_edges.append((f"rAre{sfx}", f"rAim{sfx}"))
        else:
            add(
                "x transform",
                (
                    lambda sre, c0=c0, c1=c1: e._st_x_forward(
                        sre, zwin=(c0, c1)
                    )
                )
                if e.is_r2c
                else (
                    lambda sre, sim, c0=c0, c1=c1: e._st_x_forward(
                        sre, sim, zwin=(c0, c1)
                    )
                ),
                space_in, (f"freq{sfx}",), name=f"x transform{sfx}",
                out_meta={f"freq{sfx}": EdgeMeta(ct, (W, Ly, Xf))},
            )
            add(
                "pack B", e._st_pack_b_rev, (f"freq{sfx}",), (f"bufB{sfx}",),
                name=f"pack B{sfx}",
                out_meta={f"bufB{sfx}": EdgeMeta(ct, (P1, Ly, Ax, W))},
            )
            add(
                xb, lambda b: e._st_exchange_b(b, reverse=True),
                (f"bufB{sfx}",), (f"recvB{sfx}",), name=f"{xb}{sfx}",
                out_meta={f"recvB{sfx}": EdgeMeta(ct, (P1, Ly, Ax, W))},
            )
            add(
                "unpack B", e._st_unpack_b_rev, (f"recvB{sfx}",),
                (f"grid{sfx}",), name=f"unpack B{sfx}",
                out_meta={f"grid{sfx}": EdgeMeta(ct, (p.dim_y, Ax, W))},
            )
            add(
                "y transform", e._st_y_forward, (f"grid{sfx}",),
                (f"gridy{sfx}",), name=f"y transform{sfx}",
                out_meta={f"gridy{sfx}": EdgeMeta(ct, (p.dim_y, Ax, W))},
            )
            add(
                "pack A",
                lambda grid, c0=c0: e._st_pack_a_rev(grid, c0),
                (f"gridy{sfx}",), (f"bufA{sfx}",), name=f"pack A{sfx}",
                out_meta={f"bufA{sfx}": EdgeMeta(ct, (Pn, SG, W))},
            )
            add(
                xa, lambda b: e._st_exchange_a(b, reverse=True),
                (f"bufA{sfx}",), (f"recvA{sfx}",), name=f"{xa}{sfx}",
                out_meta={f"recvA{sfx}": EdgeMeta(ct, (Pn, SG, W))},
            )
            recv_edges.append((f"recvA{sfx}",))
    return names, recv_edges


def _lower_pencil(e, pair: bool):
    p = e.params
    rt, ct = e.real_dtype, e.complex_dtype
    S, V = e._S, e._V
    Z = p.dim_z
    Lz, Ly = e._Lz, e._Ly
    X = p.dim_x

    def backward():
        g = StageGraph("backward")
        g.add_input("values_re", dtype=rt, shape=(V,))
        g.add_input("values_im", dtype=rt, shape=(V,))
        g.add_input("value_indices", dtype=np.int32, shape=(V,))
        g.batch_inputs = ("values_re", "values_im")
        if pair:
            g.add(
                "compression", e._st_decompress, ("values_re", "values_im"),
                ("sre", "sim"),
                out_meta={
                    "sre": EdgeMeta(rt, (S, Z)), "sim": EdgeMeta(rt, (S, Z))
                },
            )
            cur = ("sre", "sim")
            if e.is_r2c and p.zero_stick_shard >= 0:
                g.add(
                    "stick symmetry", e._st_stick_symmetry, cur,
                    ("shre", "shim"),
                    out_meta={
                        "shre": EdgeMeta(rt, (S, Z)),
                        "shim": EdgeMeta(rt, (S, Z)),
                    },
                )
                cur = ("shre", "shim")
            g.add(
                "z transform", e._st_z_backward, cur, ("zre", "zim"),
                out_meta={
                    "zre": EdgeMeta(rt, (S, Z)), "zim": EdgeMeta(rt, (S, Z))
                },
            )
            names = _pencil_backward_tail_pair(
                g, e, [(0, Lz)], overlapped=False
            )
            if e._overlap > 1:
                for nm in names:
                    g.remove(nm)
                _pencil_backward_tail_pair(g, e, e._chunks, overlapped=True)
        else:
            g.add(
                "compression", e._st_decompress,
                ("values_re", "values_im", "value_indices"), ("sticks",),
                out_meta={"sticks": EdgeMeta(ct, (S, Z))},
            )
            cur = "sticks"
            if e.is_r2c and p.zero_stick_shard >= 0:
                g.add(
                    "stick symmetry", e._st_stick_symmetry, (cur,),
                    ("sticks_h",),
                    out_meta={"sticks_h": EdgeMeta(ct, (S, Z))},
                )
                cur = "sticks_h"
            g.add(
                "z transform", e._st_z_backward, (cur,), ("z_sticks",),
                out_meta={"z_sticks": EdgeMeta(ct, (S, Z))},
            )
            names = _pencil_backward_tail(g, e, [(0, Lz)], overlapped=False)
            if e._overlap > 1:
                for nm in names:
                    g.remove(nm)
                _pencil_backward_tail(g, e, e._chunks, overlapped=True)
        return g

    def forward(s):
        scale = None if s.name == "NONE" else 1.0 / p.total_size
        g = StageGraph("forward")
        g.add_input("space_re", dtype=rt, shape=(Lz, Ly, X))
        if not e.is_r2c:
            g.add_input("space_im", dtype=rt, shape=(Lz, Ly, X))
        g.add_input("value_indices", dtype=np.int32, shape=(V,))
        g.batch_inputs = (
            ("space_re",) if e.is_r2c else ("space_re", "space_im")
        )
        names, recv_edges = _pencil_forward_head(
            g, e, [(0, Lz)], overlapped=False, pair=pair
        )
        if e._overlap > 1:
            for nm in names:
                g.remove(nm)
            _, recv_edges = _pencil_forward_head(
                g, e, e._chunks, overlapped=True, pair=pair
            )
        if pair:
            flat = tuple(r[0] for r in recv_edges) + tuple(
                r[1] for r in recv_edges
            )
            g.add(
                "unpack A", e._st_unpack_a_rev_pair, flat, ("sre", "sim"),
                out_meta={
                    "sre": EdgeMeta(rt, (S, Z)), "sim": EdgeMeta(rt, (S, Z))
                },
            )
            g.add(
                "z transform",
                lambda sre, sim: e._st_z_forward(sre, sim, s),
                ("sre", "sim"), ("zre", "zim"),
                out_meta={
                    "zre": EdgeMeta(rt, (S, Z)), "zim": EdgeMeta(rt, (S, Z))
                },
            )
            g.add(
                "compression", e._st_compress, ("zre", "zim"),
                ("out_re", "out_im"),
                out_meta={
                    "out_re": EdgeMeta(rt, (V,)), "out_im": EdgeMeta(rt, (V,))
                },
            )
        else:
            flat = tuple(r[0] for r in recv_edges)
            g.add(
                "unpack A", e._st_unpack_a_rev, flat, ("sticks",),
                out_meta={"sticks": EdgeMeta(ct, (S, Z))},
            )
            g.add(
                "z transform", e._st_z_forward, ("sticks",), ("z_sticks",),
                out_meta={"z_sticks": EdgeMeta(ct, (S, Z))},
            )
            g.add(
                "compression",
                lambda sticks, vi: e._st_compress(sticks, vi, scale),
                ("z_sticks", "value_indices"), ("out_re", "out_im"),
                out_meta={
                    "out_re": EdgeMeta(rt, (V,)), "out_im": EdgeMeta(rt, (V,))
                },
            )
        g.set_outputs(["out_re", "out_im"])
        return g

    return {"backward": backward(), "forward": {s: forward(s) for s in _scalings()}}


def _pencil_backward_tail_pair(g, e, chunks, overlapped):
    """Pair-array (MXU) variant of :func:`_pencil_backward_tail`."""
    p = e.params
    rt = e.real_dtype
    X = p.dim_x
    P1, Ax, Ly, SG = e.P1, e._Ax, e._Ly, e._SG
    Pn = p.num_shards
    xa = "exchange A overlapped" if overlapped else "exchange A"
    xb = "exchange B overlapped" if overlapped else "exchange B"
    names = []

    def add(stage, fn, inputs, outputs, name=None, out_meta=None):
        g.add(stage, fn, inputs, outputs, name=name, out_meta=out_meta)
        names.append(name or stage)

    part_edges = []
    for k, (c0, c1) in enumerate(chunks):
        W = c1 - c0
        sfx = f"@{k}"
        add(
            "pack A",
            lambda zre, zim, c0=c0, c1=c1: e._st_pack_a_pair(
                zre, zim, (c0, c1)
            ),
            ("zre", "zim"), (f"bAre{sfx}", f"bAim{sfx}"),
            name=f"pack A{sfx}",
            out_meta={
                f"bAre{sfx}": EdgeMeta(rt, (Pn, SG, W)),
                f"bAim{sfx}": EdgeMeta(rt, (Pn, SG, W)),
            },
        )
        add(
            xa, e._st_exchange_a_pair, (f"bAre{sfx}", f"bAim{sfx}"),
            (f"rAre{sfx}", f"rAim{sfx}"), name=f"{xa}{sfx}",
            out_meta={
                f"rAre{sfx}": EdgeMeta(rt, (Pn, SG, W)),
                f"rAim{sfx}": EdgeMeta(rt, (Pn, SG, W)),
            },
        )
        add(
            "unpack A", e._st_unpack_a_pair, (f"rAre{sfx}", f"rAim{sfx}"),
            (f"gre{sfx}", f"gim{sfx}"), name=f"unpack A{sfx}",
            out_meta={
                f"gre{sfx}": EdgeMeta(rt, (p.dim_y, Ax, W)),
                f"gim{sfx}": EdgeMeta(rt, (p.dim_y, Ax, W)),
            },
        )
        cur = (f"gre{sfx}", f"gim{sfx}")
        gmeta = EdgeMeta(rt, (p.dim_y, Ax, W))
        if e.is_r2c and e._have_x0:
            add(
                "plane symmetry", e._st_plane_symmetry, cur,
                (f"ghre{sfx}", f"ghim{sfx}"), name=f"plane symmetry{sfx}",
                out_meta={f"ghre{sfx}": gmeta, f"ghim{sfx}": gmeta},
            )
            cur = (f"ghre{sfx}", f"ghim{sfx}")
        add(
            "y transform", e._st_y_backward, cur, (f"yre{sfx}", f"yim{sfx}"),
            name=f"y transform{sfx}",
            out_meta={f"yre{sfx}": gmeta, f"yim{sfx}": gmeta},
        )
        add(
            "pack B", e._st_pack_b_pair, (f"yre{sfx}", f"yim{sfx}"),
            (f"bBre{sfx}", f"bBim{sfx}"), name=f"pack B{sfx}",
            out_meta={
                f"bBre{sfx}": EdgeMeta(rt, (P1, Ly, Ax, W)),
                f"bBim{sfx}": EdgeMeta(rt, (P1, Ly, Ax, W)),
            },
        )
        add(
            xb, e._st_exchange_b_pair, (f"bBre{sfx}", f"bBim{sfx}"),
            (f"rBre{sfx}", f"rBim{sfx}"), name=f"{xb}{sfx}",
            out_meta={
                f"rBre{sfx}": EdgeMeta(rt, (P1, Ly, Ax, W)),
                f"rBim{sfx}": EdgeMeta(rt, (P1, Ly, Ax, W)),
            },
        )
        if e.is_r2c:
            add(
                "x transform", e._st_x_backward,
                (f"rBre{sfx}", f"rBim{sfx}"), (f"part{sfx}",),
                name=f"x transform{sfx}",
                out_meta={f"part{sfx}": EdgeMeta(rt, (W, Ly, X))},
            )
            part_edges.append((f"part{sfx}",))
        else:
            add(
                "x transform", e._st_x_backward,
                (f"rBre{sfx}", f"rBim{sfx}"),
                (f"partre{sfx}", f"partim{sfx}"), name=f"x transform{sfx}",
                out_meta={
                    f"partre{sfx}": EdgeMeta(rt, (W, Ly, X)),
                    f"partim{sfx}": EdgeMeta(rt, (W, Ly, X)),
                },
            )
            part_edges.append((f"partre{sfx}", f"partim{sfx}"))
    if e.is_r2c:
        add(
            "x transform", e._st_space_out,
            tuple(pe[0] for pe in part_edges), ("space",),
            name="x transform out",
            out_meta={"space": EdgeMeta(rt, (e._Lz, Ly, X))},
        )
        g.set_outputs(["space"])
    else:
        flat = tuple(pe[0] for pe in part_edges) + tuple(
            pe[1] for pe in part_edges
        )
        add(
            "x transform", e._st_space_out, flat, ("space_re", "space_im"),
            name="x transform out",
            out_meta={
                "space_re": EdgeMeta(rt, (e._Lz, Ly, X)),
                "space_im": EdgeMeta(rt, (e._Lz, Ly, X)),
            },
        )
        g.set_outputs(["space_re", "space_im"])
    return names


def _lower_pencil_xla(e):
    return _lower_pencil(e, pair=False)


def _lower_pencil_mxu(e):
    return _lower_pencil(e, pair=True)


_BUILDERS = {
    "LocalExecution": _lower_local_xla,
    "MxuLocalExecution": _lower_local_mxu,
    "DistributedExecution": _lower_slab_xla,
    "MxuDistributedExecution": _lower_slab_mxu,
    "Pencil2Execution": _lower_pencil_xla,
    "MxuPencil2Execution": _lower_pencil_mxu,
}
